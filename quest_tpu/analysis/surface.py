"""Static API-surface parity auditor (QT9xx band, docs/parity.md).

The reference gives every public L5 function a Catch2 case against a
brute-force oracle (tests/ with the vendored Catch2 header); the analogue
here is a *zero-device static pass* over our own surface. A vendored
:data:`REFERENCE_MANIFEST` (name, parameter names, register kind,
category -- one row per QuEST.h L5 function, frozen from
``native/include/QuEST.h``) is audited against the live package with
``ast`` + ``inspect`` only -- nothing is executed on a device -- and every
function is classified into per-fact columns:

- ``exists``    -- exported from ``quest_tpu`` and callable,
- ``signature`` -- live parameter names match the vendored manifest row,
- ``validates`` -- reaches ``validation.py`` (transitive fixpoint over
  module-local helpers; rows with ``needs_validation=False`` take no
  user input worth guarding),
- ``documented``-- has a docstring AND appears on a ``docs/api`` page,
- ``tested``    -- has a literal call site somewhere under ``tests/``
  (AST scan, so meta-tests iterating names via ``getattr`` don't count),
- ``sharded``   -- called from a test module running the default 8-device
  mesh env (``createQuESTEnv()`` with no argument),
- ``df``        -- called from a test module exercising the f32/double-float
  route (``precision_code=1`` registers or ``QUEST_PALLAS_DF``),
- ``grad``      -- a parameter position is adjoint-liftable
  (:data:`quest_tpu.engine.params._LIFTABLE`, the QT006 audit's registry),
- ``tape``      -- composable onto a :class:`~quest_tpu.circuits.Circuit`
  tape (:func:`quest_tpu.circuits._resolve` accepts it),
- ``oracle``    -- the generated conformance harness
  (:mod:`.conformance`) carries a dense-oracle replay spec for it.

:func:`audit_surface` returns the classified rows plus QT901-QT906
findings; :func:`render_parity_md` / :func:`parity_json` serialize the
committed ``PARITY.md`` / ``parity.json`` manifests and
:func:`check_manifest_files` raises QT905 when they are stale vs. the
tree (the CI gate: ``tools/lint.py --surface``; regenerate with
``--surface --write``). Every scan input is injectable so the auditor
itself is testable with seeded manifest mutations (tests/test_surface.py).
"""

from __future__ import annotations

import ast
import importlib
import inspect
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Iterable, Mapping, Optional, Sequence

from .diagnostics import Finding, emit_findings, make_finding

__all__ = [
    "ManifestEntry", "SurfaceRow", "SurfaceAudit", "TestScan",
    "REFERENCE_MANIFEST", "FACT_COLUMNS", "PARITY_MD", "PARITY_JSON",
    "audit_surface", "check_surface", "check_manifest_files",
    "write_manifest_files", "render_parity_md", "parity_json",
    "scan_validated", "scan_tests", "scan_documented",
]

#: repo-relative names of the committed manifest artifacts
PARITY_MD = "PARITY.md"
PARITY_JSON = "parity.json"

#: fact columns, in manifest order
FACT_COLUMNS: tuple[str, ...] = (
    "exists", "signature", "validates", "documented", "tested",
    "sharded", "df", "grad", "tape", "oracle")

#: register-kind vocabulary for :attr:`ManifestEntry.kind`
KINDS: tuple[str, ...] = ("statevec", "density", "any", "none")


@dataclass(frozen=True)
class ManifestEntry:
    """One vendored reference-surface row: the contract a live export is
    audited against. ``params`` are the exact live parameter names
    (QT902 compares them verbatim); ``kind`` is the register kind the
    function consumes; ``category`` the implementing module;
    ``needs_validation=False`` marks functions whose inputs carry nothing
    to guard (destructors, reporters, fixed-state inits, env syncs)."""

    name: str
    params: tuple[str, ...]
    kind: str
    category: str
    needs_validation: bool = True


def _e(name: str, params: tuple[str, ...], kind: str, category: str,
       needs_validation: bool = True) -> ManifestEntry:
    return ManifestEntry(name, params, kind, category, needs_validation)


#: the vendored reference L5 surface (one row per QuEST.h function)
REFERENCE_MANIFEST: tuple[ManifestEntry, ...] = (
    _e('applyDiagonalOp', ('qureg', 'op'), 'any', 'operators'),
    _e('applyFullQFT', ('qureg',), 'any', 'operators'),
    _e('applyGateMatrixN', ('qureg', 'targets', 'u'), 'any', 'operators'),
    _e('applyGateSubDiagonalOp', ('qureg', 'targets', 'op'), 'any', 'operators'),
    _e('applyMatrix2', ('qureg', 'target', 'u'), 'any', 'operators'),
    _e('applyMatrix4', ('qureg', 't1', 't2', 'u'), 'any', 'operators'),
    _e('applyMatrixN', ('qureg', 'targets', 'u'), 'any', 'operators'),
    _e('applyMultiControlledGateMatrixN', ('qureg', 'controls', 'targets', 'u'), 'any', 'operators'),
    _e('applyMultiControlledMatrixN', ('qureg', 'controls', 'targets', 'u'), 'any', 'operators'),
    _e('applyMultiVarPhaseFunc', ('qureg', 'qubits_flat', 'num_qubits_per_reg', 'encoding', 'coeffs', 'exponents', 'num_terms_per_reg'), 'any', 'operators'),
    _e('applyMultiVarPhaseFuncOverrides', ('qureg', 'qubits_flat', 'num_qubits_per_reg', 'encoding', 'coeffs', 'exponents', 'num_terms_per_reg', 'override_inds', 'override_phases'), 'any', 'operators'),
    _e('applyNamedPhaseFunc', ('qureg', 'qubits_flat', 'num_qubits_per_reg', 'encoding', 'func_name'), 'any', 'operators'),
    _e('applyNamedPhaseFuncOverrides', ('qureg', 'qubits_flat', 'num_qubits_per_reg', 'encoding', 'func_name', 'override_inds', 'override_phases'), 'any', 'operators'),
    _e('applyParamNamedPhaseFunc', ('qureg', 'qubits_flat', 'num_qubits_per_reg', 'encoding', 'func_name', 'params'), 'any', 'operators'),
    _e('applyParamNamedPhaseFuncOverrides', ('qureg', 'qubits_flat', 'num_qubits_per_reg', 'encoding', 'func_name', 'params', 'override_inds', 'override_phases'), 'any', 'operators'),
    _e('applyPauliHamil', ('in_qureg', 'hamil', 'out_qureg'), 'any', 'operators'),
    _e('applyPauliSum', ('in_qureg', 'all_pauli_codes', 'term_coeffs', 'out_qureg'), 'any', 'operators'),
    _e('applyPhaseFunc', ('qureg', 'qubits', 'encoding', 'coeffs', 'exponents'), 'any', 'operators'),
    _e('applyPhaseFuncOverrides', ('qureg', 'qubits', 'encoding', 'coeffs', 'exponents', 'override_inds', 'override_phases'), 'any', 'operators'),
    _e('applyProjector', ('qureg', 'target', 'outcome'), 'any', 'operators'),
    _e('applyQFT', ('qureg', 'qubits'), 'any', 'operators'),
    _e('applySubDiagonalOp', ('qureg', 'targets', 'op'), 'any', 'operators'),
    _e('applyTrotterCircuit', ('qureg', 'hamil', 'time', 'order', 'reps'), 'any', 'operators'),
    _e('bindArraysToStackComplexMatrixN', ('num_qubits', 'real', 'imag', 're_storage', 'im_storage'), 'none', 'datatypes'),
    _e('calcDensityInnerProduct', ('rho1', 'rho2'), 'density', 'calculations'),
    _e('calcExpecDiagonalOp', ('qureg', 'op'), 'any', 'operators'),
    _e('calcExpecPauliHamil', ('qureg', 'hamil', 'workspace'), 'any', 'calculations'),
    _e('calcExpecPauliProd', ('qureg', 'targets', 'paulis', 'workspace'), 'any', 'calculations'),
    _e('calcExpecPauliSum', ('qureg', 'all_pauli_codes', 'term_coeffs', 'workspace'), 'any', 'calculations'),
    _e('calcFidelity', ('qureg', 'pure_state'), 'any', 'calculations'),
    _e('calcHilbertSchmidtDistance', ('a', 'b'), 'density', 'calculations'),
    _e('calcInnerProduct', ('bra', 'ket'), 'statevec', 'calculations'),
    _e('calcProbOfAllOutcomes', ('qureg', 'targets'), 'any', 'calculations'),
    _e('calcProbOfOutcome', ('qureg', 'target', 'outcome'), 'any', 'calculations'),
    _e('calcPurity', ('qureg',), 'density', 'calculations'),
    _e('calcTotalProb', ('qureg',), 'any', 'calculations', needs_validation=False),
    _e('clearRecordedQASM', ('qureg',), 'any', 'reporting', needs_validation=False),
    _e('cloneQureg', ('target', 'source'), 'any', 'state_init'),
    _e('collapseToOutcome', ('qureg', 'target', 'outcome'), 'any', 'gates'),
    _e('compactUnitary', ('qureg', 'target', 'alpha', 'beta'), 'any', 'gates'),
    _e('controlledCompactUnitary', ('qureg', 'control', 'target', 'alpha', 'beta'), 'any', 'gates'),
    _e('controlledMultiQubitUnitary', ('qureg', 'control', 'targets', 'u'), 'any', 'gates'),
    _e('controlledNot', ('qureg', 'control', 'target'), 'any', 'gates'),
    _e('controlledPauliY', ('qureg', 'control', 'target'), 'any', 'gates'),
    _e('controlledPhaseFlip', ('qureg', 'q1', 'q2'), 'any', 'gates'),
    _e('controlledPhaseShift', ('qureg', 'q1', 'q2', 'angle'), 'any', 'gates'),
    _e('controlledRotateAroundAxis', ('qureg', 'control', 'target', 'angle', 'axis'), 'any', 'gates'),
    _e('controlledRotateX', ('qureg', 'control', 'target', 'angle'), 'any', 'gates'),
    _e('controlledRotateY', ('qureg', 'control', 'target', 'angle'), 'any', 'gates'),
    _e('controlledRotateZ', ('qureg', 'control', 'target', 'angle'), 'any', 'gates'),
    _e('controlledTwoQubitUnitary', ('qureg', 'control', 't1', 't2', 'u'), 'any', 'gates'),
    _e('controlledUnitary', ('qureg', 'control', 'target', 'u'), 'any', 'gates'),
    _e('copyStateFromGPU', ('qureg',), 'any', 'registers'),
    _e('copyStateToGPU', ('qureg',), 'any', 'registers'),
    _e('copySubstateFromGPU', ('qureg', 'start_ind', 'num_amps'), 'any', 'registers'),
    _e('copySubstateToGPU', ('qureg', 'start_ind', 'num_amps'), 'any', 'registers'),
    _e('createCloneQureg', ('qureg', 'env'), 'any', 'registers', needs_validation=False),
    _e('createComplexMatrixN', ('num_qubits',), 'none', 'datatypes'),
    _e('createDensityQureg', ('num_qubits', 'env', 'precision_code'), 'none', 'registers'),
    _e('createDiagonalOp', ('num_qubits', 'env'), 'none', 'operators'),
    _e('createDiagonalOpFromPauliHamilFile', ('path', 'env'), 'none', 'operators'),
    _e('createPauliHamil', ('num_qubits', 'num_sum_terms'), 'none', 'datatypes'),
    _e('createPauliHamilFromFile', ('path',), 'none', 'datatypes'),
    _e('createQuESTEnv', ('devices', 'num_slices'), 'none', 'environment'),
    _e('createQureg', ('num_qubits', 'env', 'precision_code'), 'none', 'registers'),
    _e('createSubDiagonalOp', ('num_qubits',), 'none', 'datatypes'),
    _e('destroyComplexMatrixN', ('matrix',), 'none', 'datatypes', needs_validation=False),
    _e('destroyDiagonalOp', ('op', 'env'), 'none', 'operators', needs_validation=False),
    _e('destroyPauliHamil', ('hamil',), 'none', 'datatypes', needs_validation=False),
    _e('destroyQuESTEnv', ('env',), 'none', 'environment', needs_validation=False),
    _e('destroyQureg', ('qureg', 'env'), 'any', 'registers', needs_validation=False),
    _e('destroySubDiagonalOp', ('op',), 'none', 'datatypes', needs_validation=False),
    _e('diagonalUnitary', ('qureg', 'targets', 'op'), 'any', 'gates'),
    _e('getAmp', ('qureg', 'index'), 'statevec', 'calculations'),
    _e('getDensityAmp', ('qureg', 'row', 'col'), 'density', 'calculations'),
    _e('getEnvironmentString', ('env',), 'none', 'environment', needs_validation=False),
    _e('getImagAmp', ('qureg', 'index'), 'statevec', 'calculations'),
    _e('getNumAmps', ('qureg',), 'any', 'state_init'),
    _e('getNumQubits', ('qureg',), 'any', 'state_init', needs_validation=False),
    _e('getProbAmp', ('qureg', 'index'), 'statevec', 'calculations'),
    _e('getQuESTSeeds', ('env',), 'none', 'environment', needs_validation=False),
    _e('getRealAmp', ('qureg', 'index'), 'statevec', 'calculations'),
    _e('hadamard', ('qureg', 'target'), 'any', 'gates'),
    _e('initBlankState', ('qureg',), 'any', 'state_init', needs_validation=False),
    _e('initClassicalState', ('qureg', 'state_index'), 'any', 'state_init'),
    _e('initComplexMatrixN', ('matrix', 'real', 'imag'), 'none', 'datatypes'),
    _e('initDebugState', ('qureg',), 'any', 'state_init', needs_validation=False),
    _e('initDiagonalOp', ('op', 'reals', 'imags'), 'none', 'operators'),
    _e('initDiagonalOpFromPauliHamil', ('op', 'hamil'), 'none', 'operators'),
    _e('initPauliHamil', ('hamil', 'coeffs', 'codes'), 'none', 'datatypes'),
    _e('initPlusState', ('qureg',), 'any', 'state_init', needs_validation=False),
    _e('initPureState', ('qureg', 'pure'), 'any', 'state_init'),
    _e('initStateFromAmps', ('qureg', 'reals', 'imags'), 'any', 'state_init'),
    _e('initZeroState', ('qureg',), 'any', 'state_init', needs_validation=False),
    _e('invalidQuESTInputError', ('errMsg', 'errFunc'), 'none', 'validation'),
    _e('measure', ('qureg', 'target'), 'any', 'gates'),
    _e('measureWithStats', ('qureg', 'target'), 'any', 'gates'),
    _e('mixDamping', ('qureg', 'target', 'prob'), 'density', 'decoherence'),
    _e('mixDensityMatrix', ('combine', 'prob', 'other'), 'density', 'decoherence'),
    _e('mixDephasing', ('qureg', 'target', 'prob'), 'density', 'decoherence'),
    _e('mixDepolarising', ('qureg', 'target', 'prob'), 'density', 'decoherence'),
    _e('mixKrausMap', ('qureg', 'target', 'ops'), 'density', 'decoherence'),
    _e('mixMultiQubitKrausMap', ('qureg', 'targets', 'ops'), 'density', 'decoherence'),
    _e('mixNonTPKrausMap', ('qureg', 'target', 'ops'), 'density', 'decoherence'),
    _e('mixNonTPMultiQubitKrausMap', ('qureg', 'targets', 'ops'), 'density', 'decoherence'),
    _e('mixNonTPTwoQubitKrausMap', ('qureg', 'q1', 'q2', 'ops'), 'density', 'decoherence'),
    _e('mixPauli', ('qureg', 'target', 'px', 'py', 'pz'), 'density', 'decoherence'),
    _e('mixTwoQubitDephasing', ('qureg', 'q1', 'q2', 'prob'), 'density', 'decoherence'),
    _e('mixTwoQubitDepolarising', ('qureg', 'q1', 'q2', 'prob'), 'density', 'decoherence'),
    _e('mixTwoQubitKrausMap', ('qureg', 'q1', 'q2', 'ops'), 'density', 'decoherence'),
    _e('multiControlledMultiQubitNot', ('qureg', 'controls', 'targets'), 'any', 'gates'),
    _e('multiControlledMultiQubitUnitary', ('qureg', 'controls', 'targets', 'u'), 'any', 'gates'),
    _e('multiControlledMultiRotatePauli', ('qureg', 'controls', 'targets', 'paulis', 'angle'), 'any', 'gates'),
    _e('multiControlledMultiRotateZ', ('qureg', 'controls', 'targets', 'angle'), 'any', 'gates'),
    _e('multiControlledPhaseFlip', ('qureg', 'qubits'), 'any', 'gates'),
    _e('multiControlledPhaseShift', ('qureg', 'qubits', 'angle'), 'any', 'gates'),
    _e('multiControlledTwoQubitUnitary', ('qureg', 'controls', 't1', 't2', 'u'), 'any', 'gates'),
    _e('multiControlledUnitary', ('qureg', 'controls', 'target', 'u'), 'any', 'gates'),
    _e('multiQubitNot', ('qureg', 'targets'), 'any', 'gates'),
    _e('multiQubitUnitary', ('qureg', 'targets', 'u'), 'any', 'gates'),
    _e('multiRotatePauli', ('qureg', 'targets', 'paulis', 'angle'), 'any', 'gates'),
    _e('multiRotateZ', ('qureg', 'qubits', 'angle'), 'any', 'gates'),
    _e('multiStateControlledUnitary', ('qureg', 'controls', 'states', 'target', 'u'), 'any', 'gates'),
    _e('pauliX', ('qureg', 'target'), 'any', 'gates'),
    _e('pauliY', ('qureg', 'target'), 'any', 'gates'),
    _e('pauliZ', ('qureg', 'target'), 'any', 'gates'),
    _e('phaseShift', ('qureg', 'target', 'angle'), 'any', 'gates'),
    _e('printRecordedQASM', ('qureg',), 'any', 'reporting', needs_validation=False),
    _e('reportPauliHamil', ('hamil',), 'none', 'reporting', needs_validation=False),
    _e('reportQuESTEnv', ('env',), 'none', 'environment', needs_validation=False),
    _e('reportQuregParams', ('qureg',), 'any', 'reporting', needs_validation=False),
    _e('reportState', ('qureg',), 'any', 'reporting', needs_validation=False),
    _e('reportStateToScreen', ('qureg', 'env', 'report_rank'), 'any', 'reporting', needs_validation=False),
    _e('rotateAroundAxis', ('qureg', 'target', 'angle', 'axis'), 'any', 'gates'),
    _e('rotateX', ('qureg', 'target', 'angle'), 'any', 'gates'),
    _e('rotateY', ('qureg', 'target', 'angle'), 'any', 'gates'),
    _e('rotateZ', ('qureg', 'target', 'angle'), 'any', 'gates'),
    _e('sGate', ('qureg', 'target'), 'any', 'gates'),
    _e('seedQuEST', ('env', 'seeds'), 'none', 'environment'),
    _e('seedQuESTDefault', ('env',), 'none', 'environment', needs_validation=False),
    _e('setAmps', ('qureg', 'start_ind', 'reals', 'imags', 'num_amps'), 'statevec', 'state_init'),
    _e('setDensityAmps', ('qureg', 'start_row', 'start_col', 'reals', 'imags', 'num_amps'), 'density', 'state_init'),
    _e('setDiagonalOpElems', ('op', 'start_ind', 'reals', 'imags', 'num_elems'), 'none', 'operators'),
    _e('setQuregToPauliHamil', ('qureg', 'hamil'), 'any', 'operators'),
    _e('setWeightedQureg', ('fac1', 'qureg1', 'fac2', 'qureg2', 'fac_out', 'out'), 'any', 'state_init'),
    _e('sqrtSwapGate', ('qureg', 'qb1', 'qb2'), 'any', 'gates'),
    _e('startRecordingQASM', ('qureg',), 'any', 'reporting', needs_validation=False),
    _e('stopRecordingQASM', ('qureg',), 'any', 'reporting', needs_validation=False),
    _e('swapGate', ('qureg', 'qb1', 'qb2'), 'any', 'gates'),
    _e('syncDiagonalOp', ('op',), 'none', 'operators', needs_validation=False),
    _e('syncQuESTEnv', ('env',), 'none', 'environment', needs_validation=False),
    _e('syncQuESTSuccess', ('success_code',), 'none', 'environment', needs_validation=False),
    _e('tGate', ('qureg', 'target'), 'any', 'gates'),
    _e('twoQubitUnitary', ('qureg', 't1', 't2', 'u'), 'any', 'gates'),
    _e('unitary', ('qureg', 'target', 'u'), 'any', 'gates'),
    _e('writeRecordedQASMToFile', ('qureg', 'filename'), 'any', 'reporting'),
)


@dataclass(frozen=True)
class SurfaceRow:
    """One audited function: its manifest row plus the fact-column verdict."""

    name: str
    category: str
    kind: str
    facts: Mapping[str, bool]

    def fact(self, column: str) -> bool:
        return bool(self.facts[column])

    def as_dict(self) -> dict[str, Any]:
        return {"name": self.name, "category": self.category,
                "kind": self.kind,
                "facts": {c: bool(self.facts[c]) for c in FACT_COLUMNS}}


@dataclass(frozen=True)
class SurfaceAudit:
    """The audit result: one row per manifest entry plus the findings."""

    rows: tuple[SurfaceRow, ...]
    findings: tuple[Finding, ...]

    def row(self, name: str) -> SurfaceRow:
        for r in self.rows:
            if r.name == name:
                return r
        raise KeyError(name)

    def summary(self) -> dict[str, int]:
        return {c: sum(1 for r in self.rows if r.fact(c))
                for c in FACT_COLUMNS}


@dataclass(frozen=True)
class TestScan:
    """AST scan of ``tests/``: which functions have literal call sites,
    and which test files run the sharded / df routes."""

    calls: Mapping[str, frozenset[str]]
    sharded_files: frozenset[str]
    df_files: frozenset[str]

    def tested(self, name: str) -> bool:
        return bool(self.calls.get(name))

    def sharded(self, name: str) -> bool:
        return bool(self.calls.get(name, frozenset()) & self.sharded_files)

    def df(self, name: str) -> bool:
        return bool(self.calls.get(name, frozenset()) & self.df_files)


def _package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


# ---------------------------------------------------------------------------
# static scans (ast only -- no execution)
# ---------------------------------------------------------------------------

def scan_validated(package_root: Optional[Path] = None) -> frozenset[str]:
    """Function names (across the package's top-level L5 modules) that
    reach the validation layer: a direct ``V.validate_*`` /
    ``validate_*`` / ``invalid_quest_input_error`` call or a ``raise``,
    or -- to transitive fixpoint -- a call into any function that does
    (``mixKrausMap -> _mix_kraus``, ``multiRotatePauli ->
    _multi_rotate_pauli``, ``applyFullQFT -> _qft_on -> hadamard``)."""
    root = package_root if package_root is not None else _package_root()
    funcs: dict[tuple[str, str], set[str]] = {}
    validated: set[tuple[str, str]] = set()
    for path in sorted(root.glob("*.py")):
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue
        for node in tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            key = (path.stem, node.name)
            calls: set[str] = set()
            direct = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    fn = sub.func
                    if isinstance(fn, ast.Name):
                        calls.add(fn.id)
                        if (fn.id.startswith("validate")
                                or fn.id == "invalid_quest_input_error"):
                            direct = True
                    elif isinstance(fn, ast.Attribute):
                        calls.add(fn.attr)
                        if (isinstance(fn.value, ast.Name)
                                and fn.value.id in ("V", "validation")):
                            direct = True
                elif isinstance(sub, ast.Raise):
                    direct = True
            funcs[key] = calls
            if direct:
                validated.add(key)
    by_name: dict[str, list[tuple[str, str]]] = {}
    for mod, name in funcs:
        by_name.setdefault(name, []).append((mod, name))
    changed = True
    while changed:
        changed = False
        for key, calls in funcs.items():
            if key in validated:
                continue
            if any(cand in validated
                   for callee in calls
                   for cand in by_name.get(callee, [])):
                validated.add(key)
                changed = True
    return frozenset(name for _mod, name in validated)


def scan_tests(tests_root: Optional[Path] = None) -> TestScan:
    """AST-walk every ``tests/*.py`` for literal call sites (``foo(...)``
    and ``qt.foo(...)``) and flag each file's route coverage: sharded
    when it builds the default no-argument (8-device) env, df when it
    creates ``precision_code=1`` registers or drives the Pallas
    double-float route."""
    root = (tests_root if tests_root is not None
            else _repo_root() / "tests")
    calls: dict[str, set[str]] = {}
    sharded: set[str] = set()
    df: set[str] = set()
    for path in sorted(root.glob("*.py")):
        text = path.read_text()
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue
        if re.search(r"createQuESTEnv\(\s*\)", text):
            sharded.add(path.name)
        if re.search(r"precision_code\s*=\s*1\b|QUEST_PALLAS_DF", text):
            df.add(path.name)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Name):
                    calls.setdefault(fn.id, set()).add(path.name)
                elif isinstance(fn, ast.Attribute):
                    calls.setdefault(fn.attr, set()).add(path.name)
    return TestScan(
        calls={k: frozenset(v) for k, v in calls.items()},
        sharded_files=frozenset(sharded), df_files=frozenset(df))


def scan_documented(docs_root: Optional[Path] = None) -> frozenset[str]:
    """Function names with an entry (``def name(``) on any generated
    ``docs/api`` page."""
    root = (docs_root if docs_root is not None
            else _repo_root() / "docs" / "api")
    names: set[str] = set()
    if root.is_dir():
        for path in sorted(root.glob("*.md")):
            names.update(re.findall(r"`def (\w+)\(", path.read_text()))
    return frozenset(names)


def _grad_names() -> frozenset[str]:
    from ..engine import params
    return frozenset(params._LIFTABLE)


def _tape_names(names: Iterable[str]) -> frozenset[str]:
    from .. import circuits
    out = set()
    for name in names:
        try:
            circuits._resolve(name)
        except AttributeError:
            continue
        out.add(name)
    return frozenset(out)


def _oracle_names() -> frozenset[str]:
    from .conformance import ORACLE_SPECS
    return frozenset(ORACLE_SPECS)


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

def audit_surface(
    manifest: Sequence[ManifestEntry] = REFERENCE_MANIFEST,
    *,
    namespace: Optional[Mapping[str, Any]] = None,
    validated: Optional[frozenset[str]] = None,
    tests: Optional[TestScan] = None,
    documented: Optional[frozenset[str]] = None,
    grad_names: Optional[frozenset[str]] = None,
    tape_names: Optional[frozenset[str]] = None,
    oracle_names: Optional[frozenset[str]] = None,
) -> SurfaceAudit:
    """Classify every manifest row against the live package surface and
    return the rows plus QT901/QT902/QT903/QT904/QT906 findings. Every
    input is injectable; the defaults audit the real tree (``quest_tpu``
    exports, the :func:`scan_validated` fixpoint, the :func:`scan_tests`
    call-site scan, the ``docs/api`` pages, the engine lift registry,
    the Circuit tape resolver and the conformance spec registry)."""
    ns: Mapping[str, Any] = (namespace if namespace is not None
                             else vars(importlib.import_module("quest_tpu")))
    vset = validated if validated is not None else scan_validated()
    tscan = tests if tests is not None else scan_tests()
    dset = documented if documented is not None else scan_documented()
    gset = grad_names if grad_names is not None else _grad_names()
    tset = (tape_names if tape_names is not None
            else _tape_names([m.name for m in manifest]))
    oset = oracle_names if oracle_names is not None else _oracle_names()

    rows: list[SurfaceRow] = []
    findings: list[Finding] = []
    for entry in manifest:
        live = ns.get(entry.name)
        exists = callable(live)
        loc = f"quest_tpu.{entry.category}.{entry.name}"
        sig_ok = False
        doc_ok = False
        if exists:
            try:
                live_params = tuple(inspect.signature(live).parameters)
            except (TypeError, ValueError):
                live_params = ()
            sig_ok = live_params == entry.params
            if not sig_ok:
                findings.append(make_finding(
                    "QT902",
                    f"{entry.name} signature drifted: manifest "
                    f"({', '.join(entry.params)}) vs live "
                    f"({', '.join(live_params)})", loc))
            doc_ok = bool(inspect.getdoc(live)) and entry.name in dset
            if not doc_ok:
                findings.append(make_finding(
                    "QT906",
                    f"{entry.name} is undocumented "
                    f"(docstring: {bool(inspect.getdoc(live))}, docs/api "
                    f"page entry: {entry.name in dset})", loc))
        else:
            findings.append(make_finding(
                "QT901",
                f"reference L5 function {entry.name} "
                f"({entry.category}, {entry.kind}) is missing from the "
                f"quest_tpu public surface", loc))
        valid_ok = (not entry.needs_validation) or entry.name in vset
        if exists and not valid_ok:
            findings.append(make_finding(
                "QT903",
                f"{entry.name} takes user input but never reaches "
                f"validation.py (no direct or delegated validate_* call "
                f"found)", loc))
        tested = tscan.tested(entry.name)
        if exists and not tested:
            findings.append(make_finding(
                "QT904",
                f"{entry.name} has no literal call site under tests/",
                loc))
        facts = {
            "exists": exists,
            "signature": sig_ok,
            "validates": exists and valid_ok,
            "documented": doc_ok,
            "tested": tested,
            "sharded": tscan.sharded(entry.name),
            "df": tscan.df(entry.name),
            "grad": entry.name in gset,
            "tape": entry.name in tset,
            "oracle": entry.name in oset,
        }
        rows.append(SurfaceRow(entry.name, entry.category, entry.kind,
                               facts))
    return SurfaceAudit(rows=tuple(rows), findings=tuple(findings))


# ---------------------------------------------------------------------------
# manifest serialization + staleness gate
# ---------------------------------------------------------------------------

_MD_HEADER = """\
# L5 API-surface parity manifest

Generated by `python tools/lint.py --surface --write` from the vendored
reference manifest (`quest_tpu/analysis/surface.py`, frozen from
`native/include/QuEST.h`). **Do not edit by hand** -- CI fails (QT905)
when this file is stale vs. the audited tree. Column semantics:
docs/parity.md.

| column | meaning |
|---|---|
| exists | exported from `quest_tpu` and callable |
| sig | live parameter names match the vendored manifest |
| valid | reaches `validation.py` (or `needs_validation=False`) |
| doc | docstring + `docs/api` page entry |
| test | literal call site under `tests/` |
| shard | called from an 8-device-mesh test module |
| df | called from an f32/double-float-route test module |
| grad | adjoint-liftable parameter position (engine lift registry) |
| tape | composable onto a `Circuit` tape |
| oracle | dense-oracle replay spec in `analysis/conformance.py` |
"""


def _cell(v: bool) -> str:
    return "x" if v else "."


def render_parity_md(audit: SurfaceAudit) -> str:
    """The committed ``PARITY.md`` text: the legend, one table row per
    function (sorted by category then name), the per-column summary and
    the red-cell backlog. Deterministic -- no timestamps."""
    lines = [_MD_HEADER]
    lines.append("| function | category | kind | "
                 + " | ".join(("exists", "sig", "valid", "doc", "test",
                               "shard", "df", "grad", "tape", "oracle"))
                 + " |")
    lines.append("|---|---|---|" + "---|" * len(FACT_COLUMNS))
    for r in sorted(audit.rows, key=lambda r: (r.category, r.name)):
        cells = " | ".join(_cell(r.fact(c)) for c in FACT_COLUMNS)
        lines.append(f"| `{r.name}` | {r.category} | {r.kind} | {cells} |")
    total = len(audit.rows)
    s = audit.summary()
    lines.append("")
    lines.append("## Summary")
    lines.append("")
    lines.append("| column | green |")
    lines.append("|---|---|")
    for c in FACT_COLUMNS:
        lines.append(f"| {c} | {s[c]}/{total} |")
    red = sorted(r.name for r in audit.rows if not r.fact("oracle"))
    lines.append("")
    lines.append("## Red cells: no dense-oracle replay spec yet")
    lines.append("")
    lines.append("Each is a concrete next PR: add an `ORACLE_SPECS` row in "
                 "`quest_tpu/analysis/conformance.py` and the generated "
                 "harness picks it up (docs/parity.md).")
    lines.append("")
    lines.append(", ".join(f"`{n}`" for n in red) if red else "(none)")
    lines.append("")
    return "\n".join(lines)


def parity_json(audit: SurfaceAudit) -> str:
    """The committed ``parity.json`` text: the machine-readable manifest
    (``{"version", "columns", "functions", "summary"}``)."""
    payload = {
        "version": 1,
        "columns": list(FACT_COLUMNS),
        "functions": [r.as_dict()
                      for r in sorted(audit.rows,
                                      key=lambda r: (r.category, r.name))],
        "summary": audit.summary(),
        "total": len(audit.rows),
    }
    return json.dumps(payload, indent=1, sort_keys=True) + "\n"


def check_manifest_files(audit: SurfaceAudit,
                         repo_root: Optional[Path] = None) -> list[Finding]:
    """QT905 staleness gate: the committed ``PARITY.md`` /
    ``parity.json`` must byte-match what the audited tree regenerates."""
    root = repo_root if repo_root is not None else _repo_root()
    findings: list[Finding] = []
    for fname, render in ((PARITY_MD, render_parity_md),
                          (PARITY_JSON, parity_json)):
        path = root / fname
        want = render(audit)
        have = path.read_text() if path.is_file() else None
        if have != want:
            state = "missing" if have is None else "stale"
            findings.append(make_finding(
                "QT905",
                f"{fname} is {state} vs. the audited tree; regenerate "
                f"with `python tools/lint.py --surface --write`",
                str(path)))
    return findings


def write_manifest_files(audit: SurfaceAudit,
                         repo_root: Optional[Path] = None) -> list[Path]:
    """Regenerate the committed manifest artifacts; returns the paths."""
    root = repo_root if repo_root is not None else _repo_root()
    out = []
    for fname, render in ((PARITY_MD, render_parity_md),
                          (PARITY_JSON, parity_json)):
        path = root / fname
        path.write_text(render(audit))
        out.append(path)
    return out


def check_surface(*, write: bool = False,
                  repo_root: Optional[Path] = None,
                  emit: bool = True) -> tuple[SurfaceAudit, list[Finding]]:
    """The ``tools/lint.py --surface`` entry point: run the audit, gate
    the committed manifests (QT905; ``write=True`` regenerates them
    first), flight-record every finding on
    ``analysis_findings_total{code,severity}`` and return
    ``(audit, findings)``."""
    audit = audit_surface()
    findings = list(audit.findings)
    if write:
        write_manifest_files(audit, repo_root)
    findings += check_manifest_files(audit, repo_root)
    if emit:
        emit_findings(findings)
    return audit, findings
