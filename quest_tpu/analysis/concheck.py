"""Concurrency verifier for the serving fleet (QT6xx band).

The serving path -- engine batchers, quarantine drainers, replacement
spawners, the hedge loop, admission buckets -- is exactly the code a
test suite exercises least: its bugs live in interleavings the wall
clock rarely produces (the round-13 quarantined-``close`` deadlock was
found by hand). This module makes three of those bug classes mechanical,
over the instrumented primitives of :mod:`quest_tpu.resilience.sync`:

- :func:`check_lock_order` -- **QT601** deadlock-cycle analysis over the
  runtime held-while-acquiring graph ``sync.lock_order_edges()``
  records. A cycle (``pool.cv -> engine.cv -> pool.cv``) means two
  threads can take the same locks in opposing order; the finding names
  the cycle and carries the first-occurrence acquisition stack of every
  edge on it.
- :class:`InterleavingExplorer` -- a seeded, deterministic schedule
  explorer (loom/DPOR-lite): it installs itself as the sync layer's
  controller, parks every controlled thread at each sync operation
  (lock acquire/release, condition wait/notify, thread join, and
  :func:`await_future`), and replays the scenario under systematically
  varied schedules on two interleaved layers -- fresh-seed restarts
  whose per-schedule thread priorities each impose a different
  macro-ordering (the PCT idea: some seed starves each thread across a
  whole race window), alternating with branch flips over the recorded
  choice points of earlier runs (shallowest first) -- deduplicated by
  trace fingerprint, bounded by ``max_schedules`` and
  ``max_steps``. A schedule where no parked thread is runnable while a
  scenario thread is unfinished is a **deadlock breach**; a controlled
  thread crashing is a breach; every scenario's own invariant check
  (zero lost futures, no double resolution, bit-identical results)
  runs after each schedule. Three production scenarios ship here
  (:data:`SCENARIOS`): ``engine_close_race``, ``pool_failover_race``
  and ``hedge_race``.
- :func:`lint_concurrency` -- the AST pass behind
  ``tools/lint.py --concurrency``: **QT603** flags fields of a
  lock-owning class mutated both with and without the class lock held
  (an intra-class call-graph fixpoint absorbs the ``callers hold
  self._cv`` helper idiom), **QT604** flags raw
  ``threading.Lock/RLock/Condition`` construction in serving code that
  should be on the instrumented layer (``# concheck: allow-raw-lock``
  opts a deliberate line out; ``sync.py`` and this module are
  allowlisted -- the instrumenter cannot instrument itself).

The explorer's own latches are deliberately raw: they must never route
through the layer they schedule.
"""

from __future__ import annotations

import ast
import os
import re
import threading
import time
from concurrent.futures import CancelledError, Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Iterable, List, Optional, Tuple

from ..resilience import sync as _sync
from .diagnostics import Finding, emit_findings, make_finding

__all__ = [
    "check_lock_order",
    "InterleavingExplorer", "ExplorationResult", "await_future",
    "CountingFuture", "SCENARIOS", "run_scenario",
    "lint_concurrency", "check_raw_locks", "check_atomicity",
]


# ---------------------------------------------------------------------------
# QT601: lock-order deadlock-cycle analysis
# ---------------------------------------------------------------------------

def check_lock_order(graph: Optional[dict] = None, *,
                     location: str = "concheck.lock_order",
                     emit: bool = True) -> List[Finding]:
    """Detect cycles in the held-while-acquiring graph (QT601).

    ``graph`` defaults to everything :func:`sync.lock_order_edges`
    recorded so far in this process (``QUEST_CONCHECK=1`` runs, explorer
    schedules). Each distinct cycle yields one error finding naming the
    cycle and quoting the first-occurrence acquisition stack of every
    edge on it -- the two (or more) call paths that can deadlock."""
    if graph is None:
        graph = _sync.lock_order_edges()
    adj: dict = {}
    nodes = set()
    for (a, b) in graph:
        adj.setdefault(a, set()).add(b)
        nodes.add(a)
        nodes.add(b)
    findings: List[Finding] = []
    seen: set = set()
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in nodes}
    path: List[str] = []

    def visit(n: str) -> None:
        color[n] = GREY
        path.append(n)
        for m in sorted(adj.get(n, ())):
            if color[m] == GREY:
                cyc = tuple(path[path.index(m):])
                k = cyc.index(min(cyc))
                canon = cyc[k:] + cyc[:k]
                if canon in seen:
                    continue
                seen.add(canon)
                ring = list(canon) + [canon[0]]
                stacks = []
                for a, b in zip(ring, ring[1:]):
                    e = graph.get((a, b), {})
                    if e.get("stack"):
                        stacks.append(f"--- {a} held while acquiring {b} "
                                      f"(seen {e.get('count', '?')}x):\n"
                                      f"{e['stack']}")
                findings.append(make_finding(
                    "QT601",
                    "lock-order cycle " + " -> ".join(ring) + ": threads "
                    "taking these locks in opposing order can deadlock"
                    + ("\n" + "".join(stacks) if stacks else ""),
                    location))
            elif color[m] == WHITE:
                visit(m)
        path.pop()
        color[n] = BLACK

    for n in sorted(nodes):
        if color[n] == WHITE:
            visit(n)
    if emit and findings:
        emit_findings(findings)
    return findings


# ---------------------------------------------------------------------------
# deterministic interleaving explorer
# ---------------------------------------------------------------------------

#: adopted thread-name prefixes: the serving fleet's worker threads
_ADOPT_PREFIXES = ("quest-engine", "quest-pool")


def _norm(name: str) -> str:
    """Thread-name fingerprint: replica/thread ordinals collapse so the
    same logical schedule hashes identically across runs."""
    return re.sub(r"\d+", "N", name)


class _WaitToken:
    __slots__ = ("notified",)

    def __init__(self) -> None:
        self.notified = False


class _TState:
    """Controller-side view of one controlled thread."""

    __slots__ = ("thread", "name", "norm", "ordinal", "gate", "parked",
                 "eligible", "finished", "holds", "scenario")

    def __init__(self, thread: threading.Thread, ordinal: int,
                 scenario: bool) -> None:
        self.thread = thread
        self.name = thread.name
        self.norm = _norm(thread.name)
        self.ordinal = ordinal
        # the explorer's gates are raw on purpose: the scheduler must
        # never route through the layer it is scheduling
        self.gate = threading.Event()
        self.parked: Optional[tuple] = None
        self.eligible: Optional[Callable[[], bool]] = None
        self.finished = False
        self.holds: list = []         # lock objects, one entry per acquire
        self.scenario = scenario      # scenario-owned (vs adopted) thread


def _prio(seed: int, ordinal: int) -> int:
    """Deterministic per-(schedule, thread) priority: an integer hash
    mix, so each seed induces a near-uniform random ordering over the
    registered threads. No RNG state -- replays are exact."""
    h = (ordinal * 2654435761 + seed * 0x9E3779B9 + 0x7F4A7C15) \
        & 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 0x45D9F3B) & 0xFFFFFFFF
    return h ^ (h >> 16)


class _Run:
    """Per-schedule state: registered threads, cooperative waiters, the
    decision trail, and the breaches this schedule produced."""

    def __init__(self, prefix: Tuple[int, ...], seed: int = 0) -> None:
        self.prefix = prefix
        self.seed = seed                 # per-schedule priority seed
        self.reglock = threading.Lock()  # concheck: allow-raw-lock
        self.states: dict = {}           # Thread -> _TState
        self.owners: dict = {}           # lock object -> [state, depth]
        self.waiters: dict = {}          # Condition -> [_WaitToken]
        self.sched_evt = threading.Event()
        self.detached = False
        self.steps = 0
        self.alts: List[int] = []        # eligible count per choice point
        self.taken: List[int] = []       # index chosen per choice point
        self.trace: List[tuple] = []     # (thread norm, parked op)
        self.breaches: List[str] = []
        self.truncated = False
        self.diverged = False
        self._ordinal = 0

    def snapshot(self) -> list:
        with self.reglock:
            return list(self.states.values())

    def next_ordinal(self) -> int:
        with self.reglock:
            self._ordinal += 1
            return self._ordinal


def _always() -> bool:
    return True


class ExplorationResult:
    """What :meth:`InterleavingExplorer.explore` found: schedule counts,
    the distinct-interleaving count, invariant breaches (strings, each
    prefixed with the schedule that produced it) and the QT602 findings
    the schedules flight-recorded."""

    def __init__(self) -> None:
        self.schedules = 0
        self.interleavings = 0
        self.truncated = 0
        self.breaches: List[str] = []
        self.qt602: List[Finding] = []

    @property
    def ok(self) -> bool:
        return not self.breaches and not self.qt602

    def __repr__(self) -> str:
        return (f"<ExplorationResult schedules={self.schedules} "
                f"interleavings={self.interleavings} "
                f"breaches={len(self.breaches)} qt602={len(self.qt602)}>")


def await_future(fut: Future, timeout: Optional[float] = None):
    """Yield-aware ``fut.result()``: under the interleaving explorer the
    wait is a scheduling point (eligible once the future resolves, or
    always when timed -- the modeled spurious timeout); otherwise it is a
    plain ``result()`` behind the QT602 blocking-boundary guard."""
    ctrl = _sync.get_controller()
    if ctrl is not None and ctrl.controls_current():
        return ctrl.op_future(fut, timeout)
    _sync.guard_blocking("await_future")
    return fut.result(timeout)


class CountingFuture(Future):
    """A Future that counts resolution attempts -- the probe the
    double-resolution invariant checks read (``resolves`` must end at
    exactly 1 on a settled request)."""

    def __init__(self) -> None:
        super().__init__()
        self.resolves = 0

    def set_result(self, result) -> None:
        self.resolves += 1
        super().set_result(result)

    def set_exception(self, exc) -> None:
        self.resolves += 1
        super().set_exception(exc)


class InterleavingExplorer:
    """Deterministic schedule controller over the instrumented sync
    layer (module docstring). One instance explores one scenario at a
    time::

        result = InterleavingExplorer().explore(scenario)
        assert result.ok and result.interleavings > 1

    A *scenario* is any object with ``setup() -> ctx``,
    ``threads(ctx) -> [(name, fn), ...]``, ``check(ctx) -> [breach
    strings]`` and ``teardown(ctx)``; an optional ``warm()`` runs once
    before exploration, outside the controller, to pre-compile
    executables so every schedule replays cheaply."""

    def __init__(self, *, max_schedules: int = 64, max_steps: int = 400,
                 stall_s: float = 120.0) -> None:
        self.max_schedules = int(max_schedules)
        self.max_steps = int(max_steps)
        self.stall_s = float(stall_s)
        self._run: Optional[_Run] = None

    # -- controller protocol (called by quest_tpu.resilience.sync) ----------

    def controls_current(self) -> bool:
        run = self._run
        if run is None or run.detached:
            return False
        with run.reglock:
            return threading.current_thread() in run.states

    def op_acquire(self, lock, blocking: bool = True,
                   timeout: float = -1) -> bool:
        run, st = self._current()
        while True:
            if not self._park(run, st, ("acquire", lock.name),
                              self._acquire_elig(run, st, lock)):
                return lock.acquire(blocking, timeout)  # detached
            # the grant can race an UNCONTROLLED holder of the real lock
            # (a free-running thread from outside the schedule); wait it
            # out briefly instead of re-parking, so a loaded machine's
            # longer hold windows don't burn the schedule's step budget
            # on retries. Controlled threads are all parked at this
            # point, so the short block cannot reorder the schedule.
            if _sync._acquire_checked(lock, True, 0.05):
                st.holds.append(lock)
                own = run.owners.setdefault(lock, [st, 0])
                own[1] += 1
                return True
            # still held past the grace window: yield again

    def op_release(self, lock) -> None:
        run, st = self._current()
        if not self._park(run, st, ("release", lock.name), _always):
            lock.release()
            return
        _sync._release_checked(lock)
        self._drop_hold(run, st, lock)

    def op_wait(self, cond, timeout: Optional[float] = None) -> bool:
        run, st = self._current()
        lock = cond._lock
        held = _sync._held_stack()
        ent = None
        for h in held:
            if h.lock is lock:
                ent = h
                break
        if ent is None:
            raise RuntimeError(
                f"cannot wait on un-acquired instrumented lock "
                f"{cond.name!r}"
                + (" (dropped by chaos_drop_lock)"
                   if cond.name in _sync._dropped else ""))
        others = tuple(h.lock.name for h in held if h.lock is not lock)
        if others:
            _sync._qt602(f"cond:{cond.name}.wait", others,
                         "condition wait on a different lock")
        token = _WaitToken()
        run.waiters.setdefault(cond, []).append(token)
        # cooperative wait: really release the lock (mirroring the
        # instrumented wait), park until notified -- or immediately
        # grantable when timed, which models the spurious/timeout wakeup
        _sync._release_checked(lock)
        self._drop_hold(run, st, lock)
        elig = _always if timeout is not None else (lambda: token.notified)
        granted = self._park(run, st, ("wait", cond.name), elig)
        toks = run.waiters.get(cond, [])
        if token in toks:
            toks.remove(token)
        if not granted:  # detached mid-wait: reacquire for real and go on
            lock.acquire()
            return token.notified
        while True:
            if not self._park(run, st, ("wakeup", cond.name),
                              self._acquire_elig(run, st, lock)):
                lock.acquire()
                return token.notified
            # same uncontrolled-holder grace window as op_acquire
            if _sync._acquire_checked(lock, True, 0.05):
                st.holds.append(lock)
                own = run.owners.setdefault(lock, [st, 0])
                own[1] += 1
                return token.notified

    def op_notify(self, cond, n: Optional[int] = None) -> None:
        run, st = self._current()
        if not self._park(run, st, ("notify", cond.name), _always):
            try:
                cond._real.notify_all() if n is None else cond._real.notify(n)
            except RuntimeError:
                pass
            return
        toks = run.waiters.get(cond, [])
        for tok in toks if n is None else toks[:n]:
            tok.notified = True
        try:
            # wake real waiters too (threads that began waiting before
            # the controller attached); needs the real lock, which a
            # chaos-dropped acquire never took -- hence the except
            cond._real.notify_all() if n is None else cond._real.notify(n)
        except RuntimeError:
            pass

    def op_join(self, thread: threading.Thread,
                timeout: Optional[float] = None) -> None:
        run, st = self._current()
        with run.reglock:
            target = run.states.get(thread)

        def elig() -> bool:
            if timeout is not None:
                return True
            if target is not None:
                return target.finished
            return not thread.is_alive()

        if not self._park(run, st, ("join", _norm(thread.name)), elig):
            thread.join(timeout)
            return
        if target is not None and not target.finished:
            thread.join(0)  # modeled timeout expiry
        else:
            thread.join(timeout)

    def op_future(self, fut: Future, timeout: Optional[float] = None):
        run, st = self._current()
        elig = _always if timeout is not None else fut.done
        if not self._park(run, st, ("future", "result"), elig):
            # detached (post-run, all threads free-running): never hang a
            # leaked schedule -- an unresolvable future here is already a
            # recorded breach, so a short bound is enough
            return fut.result(timeout if timeout is not None else 2.0)
        if not fut.done():
            raise FutureTimeoutError(
                "modeled timeout: future unresolved at this scheduling "
                "point")
        return fut.result(0)

    # -- internals -----------------------------------------------------------

    def _current(self) -> Tuple[_Run, _TState]:
        run = self._run
        assert run is not None
        with run.reglock:
            return run, run.states[threading.current_thread()]

    @staticmethod
    def _acquire_elig(run: _Run, st: _TState, lock) -> Callable[[], bool]:
        def elig() -> bool:
            if lock.name in _sync._dropped:
                return True
            own = run.owners.get(lock)
            if own is not None and own[1] > 0:
                # held by a controlled thread: grantable only to the
                # owner of a reentrant lock (a non-reentrant self-acquire
                # stays ineligible forever == a detected self-deadlock)
                return own[0] is st and lock.reentrant
            return not (not lock.reentrant and lock._real.locked())
        return elig

    @staticmethod
    def _drop_hold(run: _Run, st: _TState, lock) -> None:
        if lock in st.holds:
            st.holds.remove(lock)
        own = run.owners.get(lock)
        if own is not None and own[0] is st:
            own[1] -= 1
            if own[1] <= 0:
                del run.owners[lock]

    def _park(self, run: _Run, st: _TState, op: tuple,
              elig: Callable[[], bool]) -> bool:
        if run.detached:
            return False
        st.eligible = elig
        st.parked = op
        run.sched_evt.set()
        st.gate.wait()
        # clear parked BEFORE the gate: while the gate is set the
        # scheduler counts this thread as busy (grant pending), and once
        # the gate clears parked is already None -- there is no window
        # where a consumed park still looks grantable, so a slow wakeup
        # (loaded box, 1 CPU) cannot be re-granted and burn steps
        st.parked = None
        st.eligible = None
        st.gate.clear()
        return not run.detached

    def _register(self, run: _Run, t: threading.Thread,
                  scenario_thread: bool) -> _TState:
        st = _TState(t, run.next_ordinal(), scenario_thread)
        orig_run = t.run

        def wrapped_run() -> None:
            try:
                orig_run()
            finally:
                st.finished = True
                run.sched_evt.set()

        t.run = wrapped_run  # type: ignore[method-assign]
        with run.reglock:
            run.states[t] = st
        return st

    def _quiesce(self, run: _Run) -> bool:
        deadline = time.monotonic() + self.stall_s
        while True:
            run.sched_evt.clear()
            # a set gate means a grant is pending consumption: the thread
            # was woken but has not run yet -- it is busy, not parked
            # (re-granting it would be a free no-op step, and a scheduler
            # hot loop here can burn the whole step budget before the
            # woken thread ever gets CPU time on a saturated machine)
            busy = [s for s in run.snapshot()
                    if not s.finished
                    and (s.parked is None or s.gate.is_set())]
            if not busy:
                return True
            if time.monotonic() > deadline:
                run.breaches.append(
                    "scheduler stall: controlled thread(s) did not yield: "
                    + ", ".join(s.name for s in busy))
                return False
            run.sched_evt.wait(0.05)

    def _schedule(self, run: _Run) -> None:
        while True:
            if not self._quiesce(run):
                return
            live = [s for s in run.snapshot() if not s.finished]
            if not any(s.scenario for s in live):
                return  # every scenario thread completed
            eligible = [s for s in live if s.parked is not None
                        and s.eligible is not None and s.eligible()]
            eligible.sort(key=lambda s: (s.norm, s.ordinal))
            if not eligible:
                run.breaches.append(
                    "deadlock: no runnable thread; parked: " + ", ".join(
                        f"{s.name}@{s.parked}" for s in live
                        if s.parked is not None))
                return
            if run.steps >= self.max_steps:
                run.truncated = True
                return
            if len(eligible) > 1:
                d = len(run.taken)
                if d < len(run.prefix):
                    want = run.prefix[d]
                    if want >= len(eligible):
                        want = 0
                        run.diverged = True
                else:
                    # beyond the replayed prefix, the default choice is
                    # the thread with the highest seeded priority -- NOT
                    # a fixed sort position. A fixed default makes the
                    # alphabetically-first thread (an engine batcher) win
                    # every branch, so the default schedule drains queues
                    # instantly and any race that needs the consumer
                    # starved across a window (quarantine landing on a
                    # queued request) hides behind a long all-non-default
                    # prefix the DFS budget never builds. Per-schedule
                    # priorities (the PCT insight) starve each thread for
                    # whole windows in SOME schedule while every choice
                    # stays a pure function of (seed, ordinal): replays
                    # and recorded prefixes are unaffected.
                    want = max(range(len(eligible)),
                               key=lambda i: _prio(run.seed,
                                                   eligible[i].ordinal))
                run.alts.append(len(eligible))
                run.taken.append(want)
                chosen = eligible[want]
            else:
                chosen = eligible[0]
            run.steps += 1
            # the ordinal keeps same-named threads (two "quest-engine"
            # batchers, a scenario's t0-/t1- pair) distinct in the
            # fingerprint; it is registration order, deterministic under
            # a replayed prefix
            run.trace.append((chosen.norm, chosen.ordinal, chosen.parked))
            chosen.gate.set()

    def _detach(self, run: _Run) -> None:
        run.detached = True
        for st in run.snapshot():
            st.gate.set()

    def _run_schedule(self, scenario, prefix: Tuple[int, ...],
                      seed: int = 0) -> Tuple[_Run, list]:
        run = _Run(prefix, seed)
        qt602_mark = len(_sync.blocking_findings())
        ctx = None
        owned: List[threading.Thread] = []
        self._run = run
        try:
            _sync.set_controller(self)
            try:
                ctx = scenario.setup()
                for name, fn in scenario.threads(ctx):
                    t = threading.Thread(
                        target=self._scenario_body(run, name, fn),
                        name=name, daemon=True)
                    self._register(run, t, scenario_thread=True)
                    owned.append(t)
                    t.start()
                self._schedule(run)
            finally:
                self._detach(run)
                for t in owned:
                    t.join(15.0)
                    if t.is_alive():
                        run.breaches.append(
                            f"scenario thread {t.name!r} leaked past "
                            f"detach")
            if ctx is not None:
                try:
                    run.breaches.extend(scenario.check(ctx))
                except Exception as e:
                    run.breaches.append(
                        f"invariant check raised {type(e).__name__}: {e}")
        finally:
            if ctx is not None:
                try:
                    scenario.teardown(ctx)
                except Exception:
                    pass
            self._run = None
            _sync.set_controller(None)
        return run, _sync.blocking_findings()[qt602_mark:]

    @staticmethod
    def _scenario_body(run: _Run, name: str,
                       fn: Callable[[], None]) -> Callable[[], None]:
        def body() -> None:
            try:
                fn()
            except BaseException as e:
                run.breaches.append(
                    f"scenario thread {name!r} raised "
                    f"{type(e).__name__}: {e}")
        return body

    def explore(self, scenario) -> ExplorationResult:
        """Run ``scenario`` under systematically varied schedules
        (class docstring). Returns the aggregate
        :class:`ExplorationResult`."""
        result = ExplorationResult()
        explorer = self
        saved_sync = (_sync._env_read, _sync._active)
        _sync.configure(True)
        orig_start = threading.Thread.start
        orig_hook = threading.excepthook

        def patched_start(t: threading.Thread) -> None:
            run = explorer._run
            if (run is not None and not run.detached
                    and t.name.startswith(_ADOPT_PREFIXES)):
                with run.reglock:
                    known = t in run.states
                if not known:
                    explorer._register(run, t, scenario_thread=False)
            orig_start(t)

        def hook(args) -> None:
            run = explorer._run
            if run is not None:
                with run.reglock:
                    known = args.thread in run.states
                if known:
                    run.breaches.append(
                        f"thread {args.thread.name!r} crashed: "
                        f"{args.exc_type.__name__}: {args.exc_value}")
                    run.sched_evt.set()
                    return
            orig_hook(args)

        threading.Thread.start = patched_start  # type: ignore[method-assign]
        threading.excepthook = hook
        try:
            warm = getattr(scenario, "warm", None)
            if warm is not None:
                warm()
            frontier: List[Tuple[int, ...]] = []
            visited = {()}
            traces: set = set()
            while result.schedules < self.max_schedules:
                k = result.schedules
                # two interleaved exploration layers: even schedules
                # restart from an EMPTY prefix under a fresh priority
                # seed (each seed is a whole different macro-ordering --
                # some starve the consumer through the race window, some
                # run the killer first, some the client); odd schedules
                # refine recorded runs by flipping one branch. Seeds
                # alone miss fine interleavings, branch flips alone pin
                # ever-longer prefixes that freeze the macro-ordering.
                prefix = frontier.pop() if (k % 2 == 1 and frontier) \
                    else ()
                run, qt602 = self._run_schedule(scenario, prefix, k)
                result.schedules += 1
                result.qt602.extend(qt602)
                result.breaches.extend(
                    f"[schedule {result.schedules}, prefix {prefix}] {b}"
                    for b in run.breaches)
                if run.truncated:
                    result.truncated += 1
                traces.add(tuple(run.trace))
                if not run.diverged:
                    # deepest alternatives first, so the LIFO frontier
                    # pops the SHALLOWEST flip next: early choices set
                    # the macro-ordering (who wins the race window), and
                    # pinning a near-complete prefix would freeze every
                    # schedule into the same trace with only tail noise
                    # -- the per-seed priorities would never get to act
                    for d in reversed(range(len(prefix), len(run.alts))):
                        for j in range(1, run.alts[d]):
                            p = tuple(run.taken[:d]) + (j,)
                            if p not in visited:
                                visited.add(p)
                                frontier.append(p)
            result.interleavings = len(traces)
        finally:
            threading.Thread.start = orig_start  # type: ignore[method-assign]
            threading.excepthook = orig_hook
            _sync.set_controller(None)
            self._run = None
            _sync._env_read, _sync._active = saved_sync
        return result


# ---------------------------------------------------------------------------
# the three production scenarios
# ---------------------------------------------------------------------------

def _demo_circuit():
    from ..circuits import Circuit
    from ..engine.params import Param

    c = Circuit(2)
    c.hadamard(0)
    c.rotateX(0, Param("a"))
    c.rotateZ(1, Param("b"))
    c.controlledNot(0, 1)
    return c


_PARAMS_A = {"a": 0.37, "b": -1.1}
_PARAMS_B = {"a": 1.9, "b": 0.61}


class _ScenarioBase:
    """Shared plumbing: one demo param circuit, reference results
    computed once in ``warm()`` (which also pre-compiles the vmap
    executable into the process-global LRU so every schedule replays it
    warm)."""

    #: engine knobs shared by warm() and every schedule's engines -- the
    #: vmap executable key includes max_batch, so these must agree
    engine_kw = dict(max_batch=2, max_delay_ms=0.0)

    def __init__(self) -> None:
        self.circ = None
        self.expected: dict = {}

    def warm(self) -> None:
        import numpy as np

        from ..engine.engine import Engine

        if self.circ is None:
            self.circ = _demo_circuit()
        eng = Engine(self.circ, **self.engine_kw)
        try:
            eng.warmup()
            for key, params in (("a", _PARAMS_A), ("b", _PARAMS_B)):
                self.expected[key] = np.asarray(eng.run(params))
        finally:
            eng.close()

    def _bitcheck(self, label: str, got, key: str) -> List[str]:
        import numpy as np

        if not np.array_equal(np.asarray(got), self.expected[key]):
            return [f"{label}: result is not bit-identical to the "
                    f"reference"]
        return []


class EngineCloseRaceScenario(_ScenarioBase):
    """``submit`` racing ``close(drain=False)`` on one engine: the
    accepted-or-rejected contract. Every schedule must end with the
    submission either rejected typed (engine already closed), cancelled
    typed (queued, then dropped by close), or served bit-identically --
    never hung, never an untyped error."""

    name = "engine_close_race"

    def setup(self) -> dict:
        from ..engine.engine import Engine

        return {"eng": Engine(self.circ, **self.engine_kw), "out": {}}

    def threads(self, ctx: dict) -> list:
        from ..resilience.errors import QuESTCancelledError

        eng, out = ctx["eng"], ctx["out"]

        def submit() -> None:
            try:
                fut = eng.submit(_PARAMS_A)
            except RuntimeError as e:
                out["submit"] = ("rejected", str(e))
                return
            try:
                out["submit"] = ("served", await_future(fut))
            except QuESTCancelledError:
                out["submit"] = ("cancelled", None)

        def close() -> None:
            eng.close(drain=False)

        return [("t0-submit", submit), ("t1-close", close)]

    def check(self, ctx: dict) -> List[str]:
        out = ctx["out"].get("submit")
        if out is None:
            return ["submit thread recorded no outcome"]
        kind, val = out
        if kind == "served":
            return self._bitcheck("submit", val, "a")
        if kind not in ("cancelled", "rejected"):
            return [f"unexpected submit outcome {kind!r}"]
        return []

    def teardown(self, ctx: dict) -> None:
        ctx["eng"].close(drain=False)


class PoolFailoverRaceScenario(_ScenarioBase):
    """Quarantine-drain/failover racing live submissions on a 2-replica
    pool: a killer quarantines replica 0 while a client submits two
    requests and awaits both. Invariants: zero lost futures (every
    accepted future resolves -- a drain hands its cancelled work to the
    failover path), no double resolution (crash-free run), and the
    recovered results are bit-identical to the reference."""

    name = "pool_failover_race"

    def setup(self) -> dict:
        from ..engine.pool import EnginePool

        pool = EnginePool(replicas=2, spawn_replacements=False,
                          hedge_ms=0, **self.engine_kw)
        fp = self.circ.fingerprint()
        for rep in pool._replicas:
            pool._engine_for(rep, fp, self.circ)
        return {"pool": pool, "results": {}, "errors": {}}

    def threads(self, ctx: dict) -> list:
        pool = ctx["pool"]

        def client() -> None:
            futs = pool.submit_many(self.circ, [_PARAMS_A, _PARAMS_B])
            for i, f in enumerate(futs):
                try:
                    ctx["results"][i] = await_future(f)
                except Exception as e:  # lost futures surface in check()
                    ctx["errors"][i] = e

        def killer() -> None:
            pool._quarantine(pool._replicas[0], reason="test")

        return [("t0-client", client), ("t1-killer", killer)]

    def check(self, ctx: dict) -> List[str]:
        breaches: List[str] = []
        for i, key in enumerate(("a", "b")):
            if i in ctx["errors"]:
                e = ctx["errors"][i]
                breaches.append(f"request {i} lost: "
                                f"{type(e).__name__}: {e}")
            elif i not in ctx["results"]:
                breaches.append(f"request {i} never resolved")
            else:
                breaches += self._bitcheck(f"request {i} (post-failover)",
                                           ctx["results"][i], key)
        return breaches

    def teardown(self, ctx: dict) -> None:
        ctx["pool"].close(drain=False)


class HedgeRaceScenario(_ScenarioBase):
    """Hedged dispatch racing primary completion: a request in flight on
    a degraded replica is hedged to a healthy peer (the pool's
    ``_issue_hedge``, driven from a scenario thread so the race itself is
    the schedule, not the hedge loop's timer). First completion wins;
    the caller's future must resolve exactly once, bit-identically, in
    every schedule."""

    name = "hedge_race"

    def setup(self) -> dict:
        from ..engine import pool as _pool_mod
        from ..engine.pool import EnginePool

        pool = EnginePool(replicas=2, spawn_replacements=False,
                          hedge_ms=0, **self.engine_kw)
        fp = self.circ.fingerprint()
        rep0, rep1 = pool._replicas
        eng0 = pool._engine_for(rep0, fp, self.circ)
        pool._engine_for(rep1, fp, self.circ)
        eng0._note_breach(hang=False)  # degraded: the hedge precondition
        with pool._cv:
            pool._manifest.setdefault(fp, self.circ)
        req = _pool_mod._PoolRequest(self.circ, fp, _PARAMS_A, "default",
                                     "normal", None)
        req.fut = CountingFuture()
        return {"pool": pool, "req": req, "rep0": rep0, "rep1": rep1,
                "out": {}}

    def threads(self, ctx: dict) -> list:
        pool, req = ctx["pool"], ctx["req"]

        def primary() -> None:
            pool._dispatch_attempt(req, ctx["rep0"])
            with pool._cv:
                inner = [f for (_r, f, h, _sp) in req.inner if not h]
            try:
                if inner:
                    await_future(inner[0])
            except (CancelledError, Exception):
                pass  # a cancelled hedge loser is a legal outcome
            try:
                ctx["out"]["result"] = await_future(req.fut)
            except Exception as e:
                ctx["out"]["error"] = e

        def hedger() -> None:
            with pool._cv:
                req.hedged = True
            pool._issue_hedge(req, ctx["rep1"])
            with pool._cv:
                inner = [f for (_r, f, h, _sp) in req.inner if h]
            try:
                if inner:
                    await_future(inner[0])
            except (CancelledError, Exception):
                pass

        return [("t0-primary", primary), ("t1-hedger", hedger)]

    def check(self, ctx: dict) -> List[str]:
        req, out = ctx["req"], ctx["out"]
        breaches: List[str] = []
        if "error" in out:
            e = out["error"]
            breaches.append(f"caller future failed: "
                            f"{type(e).__name__}: {e}")
        elif "result" not in out:
            breaches.append("caller future never resolved")
        else:
            breaches += self._bitcheck("hedged request", out["result"], "a")
        if req.fut.resolves > 1:
            breaches.append(
                f"double resolution: caller future resolved "
                f"{req.fut.resolves}x")
        if not req.settled:
            breaches.append("request completed without settling")
        return breaches

    def teardown(self, ctx: dict) -> None:
        ctx["pool"].close(drain=False)


class AsyncDispatchDrainScenario(_ScenarioBase):
    """Async dispatch racing ``close(drain=True)`` on a completion-ring
    engine (round 18): two clients submit while a closer drains. Ring
    admission separates ISSUE from RESOLUTION, so the close path must
    retire every admitted entry before the batcher exits -- an exit
    condition that forgets the ring strands resolved-on-device work in
    unresolved futures. Invariants: each submission ends served
    bit-identically, cancelled typed, or rejected typed -- never hung,
    never untyped -- and the ring is empty once close returns."""

    name = "async_dispatch_drain"

    def setup(self) -> dict:
        from ..engine.engine import Engine

        return {"eng": Engine(self.circ, async_depth=2, **self.engine_kw),
                "out": {}}

    def threads(self, ctx: dict) -> list:
        from ..resilience.errors import QuESTCancelledError

        eng, out = ctx["eng"], ctx["out"]

        def submitter(slot: str, params: dict):
            def submit() -> None:
                try:
                    fut = eng.submit(params)
                except RuntimeError as e:
                    out[slot] = ("rejected", str(e))
                    return
                try:
                    out[slot] = ("served", await_future(fut))
                except QuESTCancelledError:
                    out[slot] = ("cancelled", None)
            return submit

        def close() -> None:
            eng.close(drain=True)
            out["ring_after_close"] = len(eng._ring)

        return [("t0-submitA", submitter("a", _PARAMS_A)),
                ("t1-submitB", submitter("b", _PARAMS_B)),
                ("t2-close", close)]

    def check(self, ctx: dict) -> List[str]:
        out = ctx["out"]
        breaches: List[str] = []
        for slot in ("a", "b"):
            rec = out.get(slot)
            if rec is None:
                breaches.append(f"submit {slot!r} recorded no outcome")
                continue
            kind, val = rec
            if kind == "served":
                breaches += self._bitcheck(f"submit {slot!r}", val, slot)
            elif kind not in ("cancelled", "rejected"):
                breaches.append(f"unexpected submit outcome {kind!r}")
        ring = out.get("ring_after_close")
        if ring is None:
            breaches.append("close thread recorded no outcome")
        elif ring:
            breaches.append(
                f"{ring} completion-ring entr{'y' if ring == 1 else 'ies'} "
                "survived close(drain=True)")
        return breaches

    def teardown(self, ctx: dict) -> None:
        ctx["eng"].close(drain=False)


#: name -> scenario class, the explorer's production scenario registry
SCENARIOS = {
    EngineCloseRaceScenario.name: EngineCloseRaceScenario,
    PoolFailoverRaceScenario.name: PoolFailoverRaceScenario,
    HedgeRaceScenario.name: HedgeRaceScenario,
    AsyncDispatchDrainScenario.name: AsyncDispatchDrainScenario,
}


def run_scenario(name: str, *, max_schedules: int = 64,
                 max_steps: int = 400) -> ExplorationResult:
    """Explore one registered scenario by name (:data:`SCENARIOS`)."""
    cls = SCENARIOS.get(name)
    if cls is None:
        raise KeyError(f"unknown scenario {name!r}; "
                       f"pick from {sorted(SCENARIOS)}")
    return InterleavingExplorer(max_schedules=max_schedules,
                                max_steps=max_steps).explore(cls())


# ---------------------------------------------------------------------------
# QT603/QT604: the AST atomicity + raw-lock lints
# ---------------------------------------------------------------------------

_RAW_PRAGMA = "concheck: allow-raw-lock"
_LOCK_CTORS = ("Lock", "RLock", "Condition")
#: files allowed to construct raw primitives: the instrumented layer
#: itself and the explorer that schedules it
_RAW_ALLOWLIST = (os.path.join("resilience", "sync.py"),
                  os.path.join("analysis", "concheck.py"))


def _is_lock_ctor(node: ast.expr) -> bool:
    """True for ``<anything>.Lock/RLock/Condition(...)`` -- matches both
    ``threading.Lock()`` and ``_sync.Lock(...)`` shapes."""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _LOCK_CTORS)


def check_raw_locks(path: str, tree: ast.Module, lines: List[str], *,
                    location: Optional[str] = None) -> List[Finding]:
    """QT604: raw ``threading.Lock/RLock/Condition`` construction in
    code that should build on the instrumented sync layer. A line
    carrying ``# concheck: allow-raw-lock`` is a deliberate opt-out."""
    rel = path.replace(os.sep, "/")
    if any(rel.endswith(a.replace(os.sep, "/")) for a in _RAW_ALLOWLIST):
        return []
    findings: List[Finding] = []
    threading_aliases = {"threading"}
    from_imported: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "threading":
                    threading_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "threading":
            for a in node.names:
                if a.name in _LOCK_CTORS:
                    from_imported.add(a.asname or a.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        raw = False
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _LOCK_CTORS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in threading_aliases):
            raw = True
        elif (isinstance(node.func, ast.Name)
              and node.func.id in from_imported):
            raw = True
        if not raw:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if _RAW_PRAGMA in line:
            continue
        findings.append(make_finding(
            "QT604",
            f"raw threading.{getattr(node.func, 'attr', None) or node.func.id}() "  # type: ignore[union-attr]
            f"constructed; serving code must use the instrumented "
            f"quest_tpu.resilience.sync wrappers",
            location or f"{os.path.basename(path)}:{node.lineno}"))
    return findings


class _MethodScan(ast.NodeVisitor):
    """One method's lock-relative facts: ``self.F`` mutations and
    ``self.m()`` call sites, each tagged with whether a ``with
    self.<lock>:`` block encloses the site."""

    def __init__(self, lock_attrs: set) -> None:
        self.lock_attrs = lock_attrs
        self.depth = 0
        self.mutations: List[Tuple[str, bool, int]] = []  # (field, locked, line)
        self.calls: List[Tuple[str, bool]] = []           # (method, locked)

    def _is_lock_item(self, expr: ast.expr) -> bool:
        return (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in self.lock_attrs)

    def visit_With(self, node: ast.With) -> None:
        locked = any(self._is_lock_item(item.context_expr)
                     for item in node.items)
        if locked:
            self.depth += 1
        self.generic_visit(node)
        if locked:
            self.depth -= 1

    def _note_target(self, target: ast.expr, lineno: int) -> None:
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
                and target.attr not in self.lock_attrs):
            self.mutations.append((target.attr, self.depth > 0, lineno))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._note_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._note_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._note_target(t, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self"):
            self.calls.append((f.attr, self.depth > 0))
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested defs (callbacks) run on foreign threads; skip

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass


def _class_atomicity(cls: ast.ClassDef, path: str) -> List[Finding]:
    methods = [n for n in cls.body if isinstance(n, ast.FunctionDef)]
    lock_attrs = set()
    for m in methods:
        for node in ast.walk(m):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        lock_attrs.add(t.attr)
    if not lock_attrs:
        return []
    scans = {}
    for m in methods:
        scan = _MethodScan(lock_attrs)
        for stmt in m.body:
            scan.visit(stmt)
        scans[m.name] = scan
    # intra-class call-graph fixpoint: a method every caller invokes
    # under the lock is itself a locked context ("callers hold self._cv"
    # helpers); __init__'s call sites are pre-publication and ignored
    sites: dict = {}
    for caller, scan in scans.items():
        if caller == "__init__":
            continue
        for callee, locked in scan.calls:
            if callee in scans:
                sites.setdefault(callee, []).append((caller, locked))
    locked_methods: set = set()
    changed = True
    while changed:
        changed = False
        for m, callers in sites.items():
            if m in locked_methods or m == "__init__":
                continue
            if all(locked or c in locked_methods for c, locked in callers):
                locked_methods.add(m)
                changed = True
    findings: List[Finding] = []
    fields: dict = {}
    for mname, scan in scans.items():
        if mname == "__init__":
            continue
        method_locked = mname in locked_methods
        for field, locked, lineno in scan.mutations:
            fields.setdefault(field, {"locked": [], "bare": []})[
                "locked" if (locked or method_locked) else "bare"
            ].append((mname, lineno))
    for field in sorted(fields):
        info = fields[field]
        if info["locked"] and info["bare"]:
            lm, ll = info["locked"][0]
            bm, bl = info["bare"][0]
            findings.append(make_finding(
                "QT603",
                f"{cls.name}.{field} is mutated under the class lock in "
                f"{lm} (line {ll}) but WITHOUT it in {bm} (line {bl}); "
                f"one of the two is lying about the locking contract",
                f"{os.path.basename(path)}:{bl}"))
    return findings


def check_atomicity(path: str, tree: ast.Module) -> List[Finding]:
    """QT603 over one parsed module: for every lock-owning class, fields
    mutated both with and without the class lock held (module
    docstring). Scope: direct ``self.F`` assignments outside
    ``__init__``; container-method mutations and cross-object writes are
    out of reach of a syntactic pass and stay the suite's job."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            findings += _class_atomicity(node, path)
    return findings


def lint_concurrency(paths: Optional[Iterable[str]] = None, *,
                     emit: bool = True) -> List[Finding]:
    """The ``tools/lint.py --concurrency`` entry point: run the QT603
    atomicity lint and the QT604 raw-lock lint over ``paths`` (files or
    directories; default: the whole ``quest_tpu`` package)."""
    if paths is None:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files += [os.path.join(root, n) for n in names
                          if n.endswith(".py")]
        else:
            files.append(p)
    findings: List[Finding] = []
    for path in sorted(files):
        with open(path, "r") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            findings.append(make_finding(
                "QT600", f"unparseable module: {e}",
                os.path.basename(path)))
            continue
        lines = source.splitlines()
        findings += check_raw_locks(path, tree, lines)
        findings += check_atomicity(path, tree)
    if emit and findings:
        emit_findings(findings)
    return findings
