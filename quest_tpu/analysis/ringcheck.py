"""DMA-ring schedule checker: prove the manual ring pipeline hazard-free.

:func:`quest_tpu.ops.pallas_gates._make_dma_kernel` owns a whole fused
pass as ONE Pallas program looping over the ``2^grid`` chunks through an
N-slot in-flight ring: the prologue fills ``ring - 1`` load slots, the
steady-state loop prefetches chunk ``c + ring - 1`` while computing chunk
``c``, and a store only blocks when its slot comes around again ``ring``
chunks later (the store-wait at ``c - ring``). That event order is a
static schedule over ``(slot, chunk)`` pairs -- so its safety invariants
are provable without running the kernel:

- **load-slot hazards** (QT201): every load is started before it is
  waited, waited before its chunk is computed, and its slot is not
  refilled until the compute consumed it (no WAR/RAW on ``ins``);
- **store-slot hazards** (QT202): a slot's output buffer is not
  rewritten while its previous store is still draining, stores start
  only after the slot was written, and every started copy is waited
  exactly once by program end (copy/wait pairing);
- **VMEM budget** (QT203/QT204): the in+out ring buffers
  (``2 * ring * slot_bytes``) fit ``_RING_VMEM_BUDGET`` after the
  caller's clamp/derate (:func:`..ops.pallas_gates.effective_ring_depth`
  -- the ONE clamp both the kernel caller and this checker use).

:func:`ring_events` generates the exact event sequence of the kernel's
pipeline and exposes fault-injection knobs (``store_wait_offset``,
``prologue_fill``, ``skip_final_waits``) so the mutation tests can seed
the classic off-by-one bugs and prove :func:`check_events` catches them.
"""

from __future__ import annotations

from typing import Optional

from .diagnostics import Finding, make_finding

__all__ = ["ring_events", "check_events", "check_ring",
           "sweep_reachable", "REACHABLE_GEOMETRIES"]

#: one simulated event: (kind, slot, chunk) with kind in
#: load_start | load_wait | compute | store_write | store_start | store_wait
Event = tuple

#: tile geometries reachable from plan knobs, as (label, planes, sublane
#: rows, itemsize): the planar f32 pair at the default S=4096 tile, the
#: native-f64 interpreter geometry (same tile, 8-byte elements), and the
#: double-float 4-plane f32 layout at its tuned smaller tile
#: (ops.pallas_df.DF_SUBLANES).
REACHABLE_GEOMETRIES: tuple[tuple[str, int, int, int], ...] = (
    ("f32", 2, 4096, 4),
    ("f64", 2, 4096, 8),
    ("df", 4, 1024, 4),
)


def ring_events(nchunks: int, ring: int, *,
                store_wait_offset: int = 0,
                prologue_fill: Optional[int] = None,
                skip_final_waits: bool = False) -> list[Event]:
    """The event sequence of ``_make_dma_kernel``'s pipeline for
    ``nchunks`` chunks at ring depth ``ring`` (callers pass the already
    clamped depth). The keyword knobs inject schedule defects for
    mutation testing -- the defaults reproduce the kernel exactly:

    - ``store_wait_offset=1`` delays the store-wait guard by one chunk
      (the classic off-by-one: ``c >= ring + 1`` instead of
      ``c >= ring``), so a slot's output buffer is rewritten while its
      store is still draining;
    - ``prologue_fill`` overrides the ``ring - 1`` prologue load count;
    - ``skip_final_waits`` drops the epilogue store-waits (unpaired
      copies at program end).
    """
    ring = int(ring)
    nchunks = int(nchunks)
    events: list[Event] = []
    fill = ring - 1 if prologue_fill is None else int(prologue_fill)
    # prologue: fill all but one ring slot
    for j in range(min(fill, nchunks)):
        events.append(("load_start", j, j))
    for c in range(nchunks):
        slot = c % ring
        ahead = c + ring - 1
        if ahead < nchunks:
            # refill the slot chunk c-1's compute freed, ring-1 ahead
            events.append(("load_start", ahead % ring, ahead))
        events.append(("load_wait", slot, c))
        events.append(("compute", slot, c))
        if c >= ring + store_wait_offset:
            # the store that used this slot ring chunks ago must drain
            # before the slot's output buffer is overwritten
            events.append(("store_wait", slot, c - ring))
        events.append(("store_write", slot, c))
        events.append(("store_start", slot, c))
    if not skip_final_waits:
        for c in range(max(0, nchunks - ring), nchunks):
            events.append(("store_wait", c % ring, c))
    return events


def check_events(events: list[Event], nchunks: int, ring: int, *,
                 location: str = "ring") -> list[Finding]:
    """Simulate ``events`` over per-slot load/store state machines and
    report every hazard (see module docstring for the invariant set).
    An empty return is the hazard-freedom proof for that schedule."""
    findings: list[Finding] = []
    # slot -> (state, chunk); load states: inflight -> ready -> consumed
    loads: dict[int, tuple[str, int]] = {}
    # store states: written -> inflight -> drained
    stores: dict[int, tuple[str, int]] = {}
    computed: list[int] = []

    def bad(code: str, msg: str) -> None:
        findings.append(make_finding(code, msg, location))

    for kind, slot, c in events:
        if kind == "load_start":
            st = loads.get(slot)
            if st is not None and st[0] == "inflight":
                bad("QT201", f"load of chunk {c} starts into slot {slot} "
                             f"while chunk {st[1]}'s load is in flight")
            elif st is not None and st[0] == "ready":
                bad("QT201", f"load of chunk {c} overwrites slot {slot} "
                             f"before chunk {st[1]} was computed (WAR)")
            loads[slot] = ("inflight", c)
        elif kind == "load_wait":
            st = loads.get(slot)
            if st is None or st[0] != "inflight" or st[1] != c:
                bad("QT201", f"load-wait on (slot {slot}, chunk {c}) with "
                             f"no matching in-flight load (state {st})")
            else:
                loads[slot] = ("ready", c)
        elif kind == "compute":
            st = loads.get(slot)
            if st is None or st[0] != "ready" or st[1] != c:
                bad("QT201", f"compute of chunk {c} reads slot {slot} "
                             f"without a completed load (state {st}, RAW)")
            else:
                loads[slot] = ("consumed", c)
            computed.append(c)
        elif kind == "store_write":
            st = stores.get(slot)
            if st is not None and st[0] == "inflight":
                bad("QT202", f"chunk {c} rewrites out-slot {slot} while "
                             f"chunk {st[1]}'s store is draining (WAR)")
            stores[slot] = ("written", c)
        elif kind == "store_start":
            st = stores.get(slot)
            if st is None or st[0] != "written" or st[1] != c:
                bad("QT202", f"store of chunk {c} starts from slot {slot} "
                             f"that was not written for it (state {st})")
            else:
                stores[slot] = ("inflight", c)
        elif kind == "store_wait":
            st = stores.get(slot)
            if st is None or st[0] != "inflight" or st[1] != c:
                bad("QT202", f"store-wait on (slot {slot}, chunk {c}) "
                             f"with no matching in-flight store "
                             f"(state {st})")
            else:
                stores[slot] = ("drained", c)
        else:  # pragma: no cover - generator emits only the kinds above
            bad("QT201", f"unknown ring event kind {kind!r}")

    for slot, st in sorted(loads.items()):
        if st[0] == "inflight":
            bad("QT201", f"load of chunk {st[1]} (slot {slot}) never "
                         f"waited (unpaired copy at program end)")
    for slot, st in sorted(stores.items()):
        if st[0] in ("written", "inflight"):
            bad("QT202", f"store of chunk {st[1]} (slot {slot}) never "
                         f"drained (unpaired copy at program end)")
    if computed != list(range(nchunks)):
        bad("QT201", f"chunks computed out of order or missing: "
                     f"{computed[:8]}... expected 0..{nchunks - 1}")
    return findings


def check_ring(nchunks: int, ring_depth: int, slot_bytes: int, *,
               budget: Optional[int] = None,
               location: str = "ring",
               max_sim_chunks: int = 256) -> list[Finding]:
    """Full check of one ring operating point: resolve the effective
    depth through the caller's clamp/derate
    (:func:`..ops.pallas_gates.effective_ring_depth`), prove VMEM-budget
    compliance, and simulate the pipeline's event schedule for hazards.

    Long sweeps are simulated at a capped chunk count
    (``max_sim_chunks``, >= several ring periods): the pipeline is
    periodic in ``ring``, so a steady-state prefix plus the epilogue
    covers every distinct (slot, chunk-phase) interaction."""
    from ..ops.pallas_gates import _RING_VMEM_BUDGET, effective_ring_depth

    budget_b = _RING_VMEM_BUDGET if budget is None else int(budget)
    findings: list[Finding] = []
    ring = effective_ring_depth(ring_depth, nchunks, slot_bytes,
                                budget=budget_b)
    requested = int(ring_depth)
    if ring != max(2, requested):
        findings.append(make_finding(
            "QT204",
            f"requested ring depth {requested} runs at {ring} "
            f"(chunks={nchunks}, slot_bytes={slot_bytes}, "
            f"budget={budget_b})",
            location))
    if 2 * ring * slot_bytes > budget_b:
        findings.append(make_finding(
            "QT203",
            f"ring buffers need {2 * ring * slot_bytes} bytes at the "
            f"minimum depth {ring}, over the {budget_b}-byte budget",
            location))
    sim_chunks = min(int(nchunks), max(int(max_sim_chunks), 4 * ring + 4))
    sim_ring = max(2, min(ring, sim_chunks))
    findings.extend(check_events(ring_events(sim_chunks, sim_ring),
                                 sim_chunks, sim_ring,
                                 location=f"{location}"
                                          f"(chunks={nchunks},"
                                          f"ring={ring})"))
    return findings


def sweep_reachable(*, rings: tuple = (2, 3, 4, 5),
                    chunk_counts: tuple = (2, 3, 4, 5, 8, 16, 64, 128),
                    geometries: Optional[tuple] = None) -> list[Finding]:
    """The cross-product proof the tentpole asks for: every ring depth
    {2..5} x chunk count x reachable tile geometry (incl. the df 4-plane
    layout) is clamp-resolved, budget-checked and hazard-simulated.
    Returns the concatenated findings (errors empty = proof holds)."""
    from ..ops.pallas_gates import _LANES

    geos = REACHABLE_GEOMETRIES if geometries is None else geometries
    findings: list[Finding] = []
    for label, planes, s, itemsize in geos:
        slot_bytes = planes * s * _LANES * itemsize
        for ring in rings:
            for nchunks in chunk_counts:
                findings.extend(check_ring(
                    nchunks, ring, slot_bytes,
                    location=f"sweep[{label},s={s},ring={ring},"
                             f"chunks={nchunks}]"))
    return findings
