"""Trace integrity checks for the request-tracing subsystem (QT70x).

Two invariants guard the round-17 span trees
(:mod:`quest_tpu.telemetry`):

- **QT702 -- span opened but never closed**: a finished trace whose span
  list still carries an open entry (``dur_ms is None``). Every
  :meth:`~quest_tpu.telemetry.TraceContext.child` must be ``end()``-ed
  before the layer that minted the root finishes it; an open span at
  export time means an instrumentation site leaked a handle (typically
  an early return between ``child()`` and ``end()``), and the Perfetto
  waterfall for that request renders a span of unknown extent.
- **QT703 -- trace context leaked across pooled-thread reuse**: a
  batcher/callback thread still bound (via
  :func:`~quest_tpu.telemetry.set_current_trace`) to contexts whose
  traces have ALL finished. The next request dispatched on that thread
  would be adopted into a dead trace -- cross-request attribution, the
  tracing analogue of the QT603 torn-state lint. Dispatch loops must
  pair every bind with :func:`~quest_tpu.telemetry.clear_current_trace`.

Reachable three ways, like every checker in this package: the
``tools/lint.py --trace FILE`` CLI (over an
:func:`~quest_tpu.telemetry.export_traces` file), the pytest suite, and
the dryrun trace-smoke (``__graft_entry__`` runs
:func:`check_live_traces` before exporting). See docs/observability.md.
"""

from __future__ import annotations

import json

from .diagnostics import Finding, make_finding

__all__ = ["check_traces", "check_live_traces", "check_trace_file"]


def check_traces(trs, location: str = "traces") -> list:
    """QT702 over finished trace dicts (:func:`quest_tpu.telemetry.traces`
    or the ``traces`` list of an ``export_traces`` file): one finding per
    trace that retains at least one open span, naming the spans."""
    findings: list[Finding] = []
    for tr in trs:
        open_spans = [sp for sp in tr.get("spans", ())
                      if sp.get("dur_ms") is None]
        if open_spans:
            names = ", ".join(
                f"{sp.get('id')}:{sp.get('name')}" for sp in open_spans[:5])
            more = len(open_spans) - 5
            findings.append(make_finding(
                "QT702",
                f"trace {tr.get('trace_id')} finished with "
                f"{len(open_spans)} open span(s): {names}"
                + (f" (+{more} more)" if more > 0 else ""),
                f"{location}.{tr.get('trace_id')}"))
    return findings


def check_live_traces(location: str = "telemetry") -> list:
    """QT702 + QT703 over the LIVE registry: retained finished traces plus
    the thread-binding table (:func:`~quest_tpu.telemetry
    .trace_thread_leaks`). The dryrun trace-smoke and the pool/engine
    teardown tests call this after the fleet quiesces."""
    from .. import telemetry
    findings = check_traces(telemetry.traces(), location=location)
    for tname, trace_id in telemetry.trace_thread_leaks():
        findings.append(make_finding(
            "QT703",
            f"thread {tname!r} is still bound to finished trace "
            f"{trace_id}: the next request dispatched there would be "
            f"adopted into a dead trace (missing clear_current_trace)",
            f"{location}.{tname}"))
    return findings


def check_trace_file(path: str, location: str | None = None) -> list:
    """QT702 over an :func:`~quest_tpu.telemetry.export_traces` JSON file
    (``{"traces": [...]}``; a bare list is accepted too) -- the
    ``tools/lint.py --trace`` entry point."""
    with open(path) as f:
        doc = json.load(f)
    trs = doc.get("traces", []) if isinstance(doc, dict) else doc
    return check_traces(trs, location=location or path)
