"""Trace integrity checks for the request-tracing subsystem (QT70x).

Two invariants guard the round-17 span trees
(:mod:`quest_tpu.telemetry`):

- **QT702 -- span opened but never closed**: a finished trace whose span
  list still carries an open entry (``dur_ms is None``). Every
  :meth:`~quest_tpu.telemetry.TraceContext.child` must be ``end()``-ed
  before the layer that minted the root finishes it; an open span at
  export time means an instrumentation site leaked a handle (typically
  an early return between ``child()`` and ``end()``), and the Perfetto
  waterfall for that request renders a span of unknown extent.
- **QT703 -- trace context leaked across pooled-thread reuse**: a
  batcher/callback thread still bound (via
  :func:`~quest_tpu.telemetry.set_current_trace`) to contexts whose
  traces have ALL finished. The next request dispatched on that thread
  would be adopted into a dead trace -- cross-request attribution, the
  tracing analogue of the QT603 torn-state lint. Dispatch loops must
  pair every bind with :func:`~quest_tpu.telemetry.clear_current_trace`.
- **QT704 -- phase vector does not tile the request (overlap-aware,
  round 18)**: a request carrying the full canonical phase vector whose
  phase COVERAGE falls outside [90%, 110%] of its end-to-end latency.
  Coverage is the UNION of the trace's phase span windows
  (:func:`phase_coverage`), NOT their sum: under the async dispatch
  pipeline the ``dispatch`` and ``device`` phases legitimately overlap
  across the launch-call window (the host is still inside the issuing
  call while the device already executes), so a plain
  ``sum(phases_ms)/dur_ms`` over-counts the shared interval and would
  false-positive on exactly the requests the pipeline is helping.
  Counting every overlapped instant once restores the tiling invariant:
  less than 90% coverage means an instrumentation site dropped a phase
  attribution, more than 110% means one double-counted outside a
  legitimate overlap.

Reachable three ways, like every checker in this package: the
``tools/lint.py --trace FILE`` CLI (over an
:func:`~quest_tpu.telemetry.export_traces` file), the pytest suite, and
the dryrun trace-smoke (``__graft_entry__`` runs
:func:`check_live_traces` before exporting). See docs/observability.md.
"""

from __future__ import annotations

import json

from .diagnostics import Finding, make_finding

__all__ = ["PHASES", "phase_coverage", "check_phase_tiling",
           "check_traces", "check_live_traces", "check_trace_file"]

#: the canonical request phase vector (round 17; docs/serving.md) --
#: traces carrying ALL of these are subject to the QT704 tiling check
PHASES = ("queue_wait", "coalesce", "cache_lookup", "compile", "dispatch",
          "device", "resolve")


def phase_coverage(tr) -> float | None:
    """Fraction of a finished trace's end-to-end latency covered by the
    UNION of its canonical phase windows (overlap counted once -- the
    async dispatch/device overlap rule, QT704). Reads the per-span
    ``cat="phase"`` entries for window positions; a trace whose spans are
    absent (older export, or a hand-built dict) falls back to the plain
    ``sum(phases_ms)/dur_ms`` ratio -- correct whenever phases don't
    overlap, i.e. everywhere the span-less form predates the async
    pipeline. Returns None when the trace has no duration or no phase
    data at all."""
    dur = tr.get("dur_ms")
    if not dur:
        return None
    spans = [sp for sp in tr.get("spans", ())
             if sp.get("cat") == "phase" and sp.get("name") in PHASES
             and sp.get("t0_ms") is not None
             and sp.get("dur_ms") is not None]
    if not spans:
        phases = tr.get("phases_ms")
        if not phases:
            return None
        return sum(phases.values()) / dur
    ivals = sorted((sp["t0_ms"], sp["t0_ms"] + sp["dur_ms"])
                   for sp in spans)
    covered = 0.0
    cur_a, cur_b = ivals[0]
    for a, b in ivals[1:]:
        if a <= cur_b:
            cur_b = max(cur_b, b)
        else:
            covered += cur_b - cur_a
            cur_a, cur_b = a, b
    covered += cur_b - cur_a
    return covered / dur


def check_phase_tiling(trs, location: str = "traces") -> list:
    """QT704 over finished trace dicts: one finding per trace that
    carries the FULL canonical phase vector (partial vectors -- error
    paths, non-request traces -- are out of scope; a missing phase there
    is expected, not a tiling breach) whose :func:`phase_coverage` falls
    outside [0.9, 1.1]."""
    findings: list[Finding] = []
    for tr in trs:
        phases = tr.get("phases_ms") or {}
        if not all(p in phases for p in PHASES):
            continue
        frac = phase_coverage(tr)
        if frac is None or 0.9 <= frac <= 1.1:
            continue
        findings.append(make_finding(
            "QT704",
            f"trace {tr.get('trace_id')} phase union covers "
            f"{frac * 100.0:.1f}% of its {tr['dur_ms']:.3f}ms end-to-end "
            f"latency (expected 90-110%)",
            f"{location}.{tr.get('trace_id')}"))
    return findings


def check_traces(trs, location: str = "traces") -> list:
    """QT702 over finished trace dicts (:func:`quest_tpu.telemetry.traces`
    or the ``traces`` list of an ``export_traces`` file): one finding per
    trace that retains at least one open span, naming the spans."""
    findings: list[Finding] = []
    for tr in trs:
        open_spans = [sp for sp in tr.get("spans", ())
                      if sp.get("dur_ms") is None]
        if open_spans:
            names = ", ".join(
                f"{sp.get('id')}:{sp.get('name')}" for sp in open_spans[:5])
            more = len(open_spans) - 5
            findings.append(make_finding(
                "QT702",
                f"trace {tr.get('trace_id')} finished with "
                f"{len(open_spans)} open span(s): {names}"
                + (f" (+{more} more)" if more > 0 else ""),
                f"{location}.{tr.get('trace_id')}"))
    return findings


def check_live_traces(location: str = "telemetry") -> list:
    """QT702 + QT703 over the LIVE registry: retained finished traces plus
    the thread-binding table (:func:`~quest_tpu.telemetry
    .trace_thread_leaks`). The dryrun trace-smoke and the pool/engine
    teardown tests call this after the fleet quiesces."""
    from .. import telemetry
    findings = check_traces(telemetry.traces(), location=location)
    findings += check_phase_tiling(telemetry.traces(), location=location)
    for tname, trace_id in telemetry.trace_thread_leaks():
        findings.append(make_finding(
            "QT703",
            f"thread {tname!r} is still bound to finished trace "
            f"{trace_id}: the next request dispatched there would be "
            f"adopted into a dead trace (missing clear_current_trace)",
            f"{location}.{tname}"))
    return findings


def check_trace_file(path: str, location: str | None = None) -> list:
    """QT702 + QT704 over an :func:`~quest_tpu.telemetry.export_traces`
    JSON file (``{"traces": [...]}``; a bare list is accepted too) -- the
    ``tools/lint.py --trace`` entry point."""
    with open(path) as f:
        doc = json.load(f)
    trs = doc.get("traces", []) if isinstance(doc, dict) else doc
    return (check_traces(trs, location=location or path)
            + check_phase_tiling(trs, location=location or path))
