"""Generated conformance harness: dense-oracle replay specs for the L5
surface (docs/parity.md).

The reference drives every L5 function through Catch2 generators
(``sublists`` / ``bitsets`` / ``pauliseqs``, tests/utilities.hpp) against
brute-force linear-algebra oracles. This module is the *registry* side of
that discipline for quest_tpu: :data:`ORACLE_SPECS` carries, per function,
how to build call arguments plus the dense target-subspace matrix the
call must equal, and :func:`conformance_cases` walks the registry emitting
deterministic :class:`ConformanceCase` descriptors. The pytest side
(tests/test_conformance.py) replays each case against the dense numpy
oracles in ``tests/oracle.py`` (``full_operator`` semantics: ``targets[0]``
is the least-significant bit of the matrix index, controls gate on
``control_states`` defaulting to all-1) -- on statevec and density
registers, and for :data:`ROUTE_MATRIX_NAMES` across the
unsharded/8-device-mesh x f64/f32 route matrix.

Coverage scales with the registry instead of hand-written tests: adding
one ``ORACLE_SPECS`` row flips that function's ``oracle`` cell in
``PARITY.md`` green (the surface auditor reads this registry) and the
generated harness picks it up with no new test code.

The shared enumeration generators (``sublists``, ``subsets``,
``ctrl_targ_splits``, ``pauliseqs``) live here too -- one implementation
behind both this harness and tests/test_exhaustive.py, mirroring the
reference's single ``utilities.hpp``.

Everything here is plain numpy: importable with no device, usable at
pytest collection time.
"""

from __future__ import annotations

import itertools
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

__all__ = [
    "ConformanceCase", "GateSpec", "ORACLE_SPECS", "ROUTE_MATRIX_NAMES",
    "conformance_cases", "route_cases", "case_rng",
    "sublists", "subsets", "ctrl_targ_splits", "pauliseqs",
]


# ---------------------------------------------------------------------------
# the reference's enumeration generators (tests/utilities.hpp:1124-1252)
# ---------------------------------------------------------------------------

def sublists(items: Sequence[int], min_len: int = 1,
             max_len: Optional[int] = None) -> Iterator[tuple[int, ...]]:
    """Every ordered k-sublist (permutation of every combination), as the
    reference's `sublists` generator (tests/utilities.hpp:1124)."""
    max_len = len(items) if max_len is None else max_len
    for k in range(min_len, max_len + 1):
        yield from itertools.permutations(items, k)


def subsets(items: Sequence[int], min_len: int = 1
            ) -> Iterator[tuple[int, ...]]:
    for k in range(min_len, len(items) + 1):
        yield from itertools.combinations(items, k)


def ctrl_targ_splits(items: Iterable[int], max_targs: Optional[int] = None
                     ) -> Iterator[tuple[tuple[int, ...], tuple[int, ...]]]:
    """Every (controls, targets) partition with both non-empty and disjoint,
    as the reference's paired sublist enumeration."""
    pool = set(items)
    for targs in sublists(sorted(pool), 1, max_targs):
        rest = sorted(pool - set(targs))
        for nc in range(1, len(rest) + 1):
            for ctrls in itertools.combinations(rest, nc):
                yield ctrls, targs


def pauliseqs(targets: Sequence[int]) -> Iterator[tuple[int, ...]]:
    """Every non-identity Pauli code sequence on ``targets``, as the
    reference's `pauliseqs` (identity-only sequences excluded)."""
    for codes in itertools.product((1, 2, 3), repeat=len(targets)):
        yield codes


# ---------------------------------------------------------------------------
# dense single/multi-qubit matrices (targets[0] = least-significant bit)
# ---------------------------------------------------------------------------

_H = np.array([[1, 1], [1, -1]], dtype=np.complex128) / np.sqrt(2)
_X = np.array([[0, 1], [1, 0]], dtype=np.complex128)
_Y = np.array([[0, -1j], [1j, 0]], dtype=np.complex128)
_Z = np.array([[1, 0], [0, -1]], dtype=np.complex128)
_S = np.diag([1, 1j]).astype(np.complex128)
_T = np.diag([1, np.exp(1j * np.pi / 4)]).astype(np.complex128)
_SWAP = np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                  [0, 1, 0, 0], [0, 0, 0, 1]], dtype=np.complex128)
_SQRT_SWAP = np.array(
    [[1, 0, 0, 0],
     [0, (1 + 1j) / 2, (1 - 1j) / 2, 0],
     [0, (1 - 1j) / 2, (1 + 1j) / 2, 0],
     [0, 0, 0, 1]], dtype=np.complex128)
_PAULIS = (np.eye(2, dtype=np.complex128), _X, _Y, _Z)


def _rot(angle: float, axis: tuple[float, float, float]) -> np.ndarray:
    """exp(-i angle/2 n.sigma) for the (normalised) axis."""
    n = np.asarray(axis, dtype=np.float64)
    n = n / np.linalg.norm(n)
    gen = n[0] * _X + n[1] * _Y + n[2] * _Z
    return (np.cos(angle / 2) * np.eye(2)
            - 1j * np.sin(angle / 2) * gen).astype(np.complex128)


def _phase(angle: float) -> np.ndarray:
    return np.diag([1.0, np.exp(1j * angle)]).astype(np.complex128)


def _kron_seq(mats: Sequence[np.ndarray]) -> np.ndarray:
    """Tensor product with ``mats[0]`` acting on the least-significant bit
    (the ``full_operator`` target convention)."""
    out = np.eye(1, dtype=np.complex128)
    for m in mats:
        out = np.kron(m, out)
    return out


def _all_ones_phase(k: int, phase: complex) -> np.ndarray:
    d = np.ones(1 << k, dtype=np.complex128)
    d[-1] = phase
    return np.diag(d)


def _parity_z_diag(k: int, angle: float) -> np.ndarray:
    """exp(-i angle/2 Z^(x)k): diagonal by bit parity."""
    idx = np.arange(1 << k)
    parity = np.zeros(1 << k, dtype=np.int64)
    for b in range(k):
        parity ^= (idx >> b) & 1
    sign = 1 - 2 * parity
    return np.diag(np.exp(-0.5j * angle * sign)).astype(np.complex128)


def _pauli_rot(codes: Sequence[int], angle: float) -> np.ndarray:
    """exp(-i angle/2 P) for a non-identity Pauli product P (P^2 = I)."""
    P = _kron_seq([_PAULIS[c] for c in codes])
    k = len(codes)
    return (np.cos(angle / 2) * np.eye(1 << k)
            - 1j * np.sin(angle / 2) * P).astype(np.complex128)


def _random_unitary(k: int, rng: np.random.RandomState) -> np.ndarray:
    dim = 1 << k
    m = rng.randn(dim, dim) + 1j * rng.randn(dim, dim)
    q, r = np.linalg.qr(m)
    return (q * (np.diag(r) / np.abs(np.diag(r)))).astype(np.complex128)


def case_rng(case_id: str) -> np.random.RandomState:
    """Deterministic per-case RNG: seeded by a CRC of the case id (stable
    across processes, unlike ``hash``)."""
    return np.random.RandomState(zlib.crc32(case_id.encode()) & 0x7FFFFFFF)


# ---------------------------------------------------------------------------
# the spec registry
# ---------------------------------------------------------------------------

#: build(rng, targets, controls) ->
#:   (args after qureg, matrix on targets, control_states or None)
BuildFn = Callable[
    [np.random.RandomState, tuple[int, ...], tuple[int, ...]],
    tuple[tuple[Any, ...], np.ndarray, Optional[tuple[int, ...]]],
]


@dataclass(frozen=True)
class GateSpec:
    """One conformance registry row: how to call the function and the
    dense matrix (on the target subspace) the call must apply. ``nt`` is
    the target count (variable-arity functions enumerate 2 and 3), ``nc``
    the control count the call signature takes."""

    name: str
    nt: int
    nc: int
    build: BuildFn


def _angle(rng: np.random.RandomState) -> float:
    return float(rng.uniform(-np.pi, np.pi))


def _compact_pair(rng: np.random.RandomState) -> tuple[complex, complex]:
    v = rng.randn(2) + 1j * rng.randn(2)
    v = v / np.linalg.norm(v)
    return complex(v[0]), complex(v[1])


def _specs() -> dict[str, GateSpec]:
    S: dict[str, GateSpec] = {}

    def add(name: str, nt: int, nc: int, build: BuildFn) -> None:
        S[name] = GateSpec(name, nt, nc, build)

    def fixed(m: np.ndarray) -> BuildFn:
        def b(rng, t, c):
            return tuple(c) + tuple(t), m, None
        return b

    # 1-target, no parameter
    add("hadamard", 1, 0, fixed(_H))
    add("pauliX", 1, 0, fixed(_X))
    add("pauliY", 1, 0, fixed(_Y))
    add("pauliZ", 1, 0, fixed(_Z))
    add("sGate", 1, 0, fixed(_S))
    add("tGate", 1, 0, fixed(_T))
    add("controlledNot", 1, 1, fixed(_X))
    add("controlledPauliY", 1, 1, fixed(_Y))
    add("controlledPhaseFlip", 1, 1, fixed(_Z))
    add("swapGate", 2, 0, fixed(_SWAP))
    add("sqrtSwapGate", 2, 0, fixed(_SQRT_SWAP))

    # angle families
    def angled(mat: Callable[[float], np.ndarray]) -> BuildFn:
        def b(rng, t, c):
            a = _angle(rng)
            return tuple(c) + tuple(t) + (a,), mat(a), None
        return b

    add("phaseShift", 1, 0, angled(_phase))
    add("controlledPhaseShift", 1, 1, angled(_phase))
    add("rotateX", 1, 0, angled(lambda a: _rot(a, (1, 0, 0))))
    add("rotateY", 1, 0, angled(lambda a: _rot(a, (0, 1, 0))))
    add("rotateZ", 1, 0, angled(lambda a: _rot(a, (0, 0, 1))))
    add("controlledRotateX", 1, 1, angled(lambda a: _rot(a, (1, 0, 0))))
    add("controlledRotateY", 1, 1, angled(lambda a: _rot(a, (0, 1, 0))))
    add("controlledRotateZ", 1, 1, angled(lambda a: _rot(a, (0, 0, 1))))

    def axis_rot(rng, t, c):
        from ..datatypes import Vector
        a = _angle(rng)
        ax = tuple(rng.uniform(-1, 1, 3))
        args = tuple(c) + tuple(t) + (a, Vector(*ax))
        return args, _rot(a, ax), None

    add("rotateAroundAxis", 1, 0, axis_rot)
    add("controlledRotateAroundAxis", 1, 1, axis_rot)

    def compact(rng, t, c):
        al, be = _compact_pair(rng)
        m = np.array([[al, -np.conj(be)], [be, np.conj(al)]],
                     dtype=np.complex128)
        return tuple(c) + tuple(t) + (al, be), m, None

    add("compactUnitary", 1, 0, compact)
    add("controlledCompactUnitary", 1, 1, compact)

    # matrix families: (controls..., targets..., u) argument layouts
    def mat_scalar_targs(rng, t, c):
        u = _random_unitary(len(t), rng)
        return tuple(c) + tuple(t) + (u,), u, None

    add("unitary", 1, 0, mat_scalar_targs)
    add("controlledUnitary", 1, 1, mat_scalar_targs)
    add("twoQubitUnitary", 2, 0, mat_scalar_targs)
    add("controlledTwoQubitUnitary", 2, 1, mat_scalar_targs)
    add("applyMatrix2", 1, 0, mat_scalar_targs)
    add("applyMatrix4", 2, 0, mat_scalar_targs)

    def mat_list_ctrls(rng, t, c):
        u = _random_unitary(len(t), rng)
        return (list(c),) + tuple(t) + (u,), u, None

    add("multiControlledUnitary", 1, 2, mat_list_ctrls)
    add("multiControlledTwoQubitUnitary", 2, 2, mat_list_ctrls)

    def mat_states(rng, t, c):
        u = _random_unitary(len(t), rng)
        states = tuple(int(s) for s in rng.randint(0, 2, len(c)))
        return (list(c), list(states)) + tuple(t) + (u,), u, states

    add("multiStateControlledUnitary", 1, 2, mat_states)

    def mat_list_targs(rng, t, c):
        u = _random_unitary(len(t), rng)
        if c:
            head = (list(c),) if len(c) > 1 else (c[0],)
        else:
            head = ()
        return head + (list(t), u), u, None

    add("multiQubitUnitary", 3, 0, mat_list_targs)
    add("controlledMultiQubitUnitary", 3, 1, mat_list_targs)
    add("multiControlledMultiQubitUnitary", 3, 2, mat_list_targs)
    add("applyMatrixN", 3, 0, mat_list_targs)
    add("applyGateMatrixN", 2, 0, mat_list_targs)

    def mat_ctrl_list_targ_list(rng, t, c):
        u = _random_unitary(len(t), rng)
        return (list(c), list(t), u), u, None

    add("applyMultiControlledMatrixN", 2, 2, mat_ctrl_list_targ_list)
    add("applyMultiControlledGateMatrixN", 2, 2, mat_ctrl_list_targ_list)

    def not_list_targs(rng, t, c):
        m = _kron_seq([_X] * len(t))
        if c:
            return (list(c), list(t)), m, None
        return (list(t),), m, None

    add("multiQubitNot", 2, 0, not_list_targs)
    add("multiControlledMultiQubitNot", 2, 2, not_list_targs)

    # symmetric phase families: every listed qubit is a "target"
    def all_ones_flip(rng, t, c):
        return (list(t),), _all_ones_phase(len(t), -1.0), None

    add("multiControlledPhaseFlip", 3, 0, all_ones_flip)

    def all_ones_shift(rng, t, c):
        a = _angle(rng)
        return (list(t), a), _all_ones_phase(len(t), np.exp(1j * a)), None

    add("multiControlledPhaseShift", 3, 0, all_ones_shift)

    def multi_rz(rng, t, c):
        a = _angle(rng)
        if c:
            return (list(c), list(t), a), _parity_z_diag(len(t), a), None
        return (list(t), a), _parity_z_diag(len(t), a), None

    add("multiRotateZ", 2, 0, multi_rz)
    add("multiControlledMultiRotateZ", 2, 2, multi_rz)

    def multi_rp(rng, t, c):
        a = _angle(rng)
        codes = tuple(int(x) for x in rng.randint(1, 4, len(t)))
        m = _pauli_rot(codes, a)
        if c:
            return (list(c), list(t), list(codes), a), m, None
        return (list(t), list(codes), a), m, None

    add("multiRotatePauli", 2, 0, multi_rp)
    add("multiControlledMultiRotatePauli", 2, 2, multi_rp)

    return S


#: function name -> replay spec; the surface auditor's ``oracle`` column
#: is exactly this registry's key set
ORACLE_SPECS: dict[str, GateSpec] = _specs()

#: operator-apply functions that LEFT-multiply a density register
#: (m rho, not m rho m^dagger) -- the reference's applyMatrix* contract;
#: the density replay compares against F @ rho for these
LEFT_MULT_ON_DENSITY: frozenset[str] = frozenset((
    "applyMatrix2", "applyMatrix4", "applyMatrixN",
    "applyMultiControlledMatrixN"))

#: the tier-1 route-matrix smoke set: each of these replays on
#: unsharded and 8-device-mesh registers at f64 and f32
ROUTE_MATRIX_NAMES: tuple[str, ...] = (
    "hadamard", "rotateX", "controlledNot", "controlledPhaseShift",
    "swapGate", "multiRotateZ", "unitary", "twoQubitUnitary",
    "multiQubitNot", "compactUnitary")


@dataclass(frozen=True)
class ConformanceCase:
    """One generated replay: call ``name(qureg, *args)`` and assert the
    register equals the dense oracle ``full_operator(n, targets, matrix,
    controls, control_states)`` applied to the input state."""

    id: str
    name: str
    targets: tuple[int, ...]
    controls: tuple[int, ...]
    control_states: Optional[tuple[int, ...]]
    args: tuple[Any, ...]
    matrix: np.ndarray


def _layouts(nt: int, nc: int, n: int
             ) -> Iterator[tuple[tuple[int, ...], tuple[int, ...]]]:
    """Two disjoint target/control layouts per spec: the low qubits with
    controls on top, then the reversed high qubits with controls below --
    deterministic, and distinct enough to catch index-order bugs."""
    qs = list(range(n))
    yield tuple(qs[:nt]), tuple(qs[n - nc:])
    yield tuple(reversed(qs[n - nt:])), tuple(qs[:nc])


def conformance_cases(num_qubits: int = 5,
                      names: Optional[Sequence[str]] = None
                      ) -> list[ConformanceCase]:
    """Walk the registry and emit every generated replay case for an
    ``num_qubits``-qubit register, deterministically (stable ids, CRC-
    seeded payloads -- the same list every process)."""
    wanted = sorted(ORACLE_SPECS if names is None else names)
    cases: list[ConformanceCase] = []
    for name in wanted:
        spec = ORACLE_SPECS[name]
        for i, (targets, controls) in enumerate(
                _layouts(spec.nt, spec.nc, num_qubits)):
            cid = f"{name}-{i}"
            rng = case_rng(cid)
            args, matrix, states = spec.build(rng, targets, controls)
            cases.append(ConformanceCase(
                id=cid, name=name, targets=targets, controls=controls,
                control_states=states, args=args, matrix=matrix))
    return cases


def route_cases(num_qubits: int = 5) -> list[ConformanceCase]:
    """The route-matrix smoke set: one case per ROUTE_MATRIX_NAMES entry
    (the first generated layout)."""
    return [c for c in conformance_cases(num_qubits,
                                         names=ROUTE_MATRIX_NAMES)
            if c.id.endswith("-0")]
