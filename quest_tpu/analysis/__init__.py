"""Static-analysis subsystem: prove schedule invariants before execution.

The checkers share one diagnostics framework (:mod:`.diagnostics`;
codes ``QT0xx`` lint / ``QT1xx`` plan / ``QT2xx`` kernel / ``QT6xx``
concurrency / ``QT7xx`` tracing / ``QT9xx`` surface parity):

- :mod:`.plancheck` -- symbolic FusePlan frame replay and scheduler
  journal re-pricing (the model-vs-plan gate),
- :mod:`.ringcheck` -- abstract DMA-ring pipeline hazard/VMEM proofs,
- :mod:`.commcheck` -- abstract comm-pipeline (pipelined collective)
  transfer/compute hazard proofs,
- :mod:`.tapelint` -- GateEvent tape lints (cancellations, mergeable
  rotations, param-lift candidates, apply-time traps),
- :mod:`.concheck` -- the concurrency verifier for the serving fleet:
  QT601 lock-order deadlock-cycle analysis over the runtime
  held-while-acquiring graph, the deterministic
  :class:`~.concheck.InterleavingExplorer` (schedule-complete racing of
  submit/close, quarantine-failover, and hedged dispatch), and the
  QT603/QT604 atomicity + raw-lock AST lints
  (``tools/lint.py --concurrency``),
- :mod:`.tracecheck` -- request-trace integrity (QT702 open spans in
  finished traces, QT703 trace contexts leaked across pooled-thread
  reuse; ``tools/lint.py --trace FILE``),
- :mod:`.surface` -- the QT9xx API-surface parity auditor: the vendored
  reference L5 manifest audited (AST + inspect, zero-device) against
  the live exports into the committed ``PARITY.md`` / ``parity.json``
  fact table (``tools/lint.py --surface``, docs/parity.md), with
  :mod:`.conformance` carrying the generated dense-oracle replay specs
  the harness in tests/test_conformance.py walks.

Reachable three ways: the ``tools/lint.py`` CLI, the pytest suites, and
``QUEST_VERIFY=1`` runtime gating -- :func:`verify_plan` runs at
``Circuit.fused()`` compile time, flight-records findings
(``analysis_findings_total{code,severity}``) and raises
:class:`AnalysisError` on error-severity findings. See docs/analysis.md.
"""

from __future__ import annotations

import os

from .. import telemetry
from .diagnostics import (CATALOG, SEVERITIES, AnalysisError, Finding,
                          emit_findings, error_findings, make_finding,
                          render_json, render_text, summarize)
from .commcheck import (check_comm_pipeline, check_pipeline_events,
                        pipeline_events, sweep_comm_pipeline)
from .concheck import (SCENARIOS, CountingFuture, ExplorationResult,
                       InterleavingExplorer, await_future, check_atomicity,
                       check_lock_order, check_raw_locks, lint_concurrency,
                       run_scenario)
from .plancheck import (check_circuit_comm, check_plan, check_schedule,
                        check_tape)
from .ringcheck import check_events, check_ring, ring_events, sweep_reachable
from .tapelint import lint_circuit, lint_events, lint_tape
from .tracecheck import check_live_traces, check_trace_file, check_traces
from .surface import (FACT_COLUMNS, REFERENCE_MANIFEST, ManifestEntry,
                      SurfaceAudit, SurfaceRow, audit_surface,
                      check_manifest_files, check_surface, parity_json,
                      render_parity_md, write_manifest_files)
from .conformance import (ORACLE_SPECS, ROUTE_MATRIX_NAMES, ConformanceCase,
                          conformance_cases, route_cases)

__all__ = [
    "Finding", "AnalysisError", "CATALOG", "SEVERITIES",
    "make_finding", "emit_findings", "error_findings",
    "render_text", "render_json", "summarize",
    "check_plan", "check_tape", "check_schedule", "check_circuit_comm",
    "ring_events", "check_events", "check_ring", "sweep_reachable",
    "pipeline_events", "check_pipeline_events", "check_comm_pipeline",
    "sweep_comm_pipeline",
    "lint_events", "lint_tape", "lint_circuit",
    "check_lock_order", "InterleavingExplorer", "ExplorationResult",
    "await_future", "CountingFuture", "SCENARIOS", "run_scenario",
    "lint_concurrency", "check_raw_locks", "check_atomicity",
    "check_traces", "check_live_traces", "check_trace_file",
    "verify_enabled", "verify_plan", "check_smoke_spec",
    "ManifestEntry", "SurfaceRow", "SurfaceAudit", "REFERENCE_MANIFEST",
    "FACT_COLUMNS", "audit_surface", "check_surface",
    "check_manifest_files", "write_manifest_files", "render_parity_md",
    "parity_json",
    "ConformanceCase", "ORACLE_SPECS", "ROUTE_MATRIX_NAMES",
    "conformance_cases", "route_cases",
]

_VERIFY_ENV = "QUEST_VERIFY"


def verify_enabled() -> bool:
    """True when ``QUEST_VERIFY`` requests compile-time plan
    verification (any value but empty/0/false/off)."""
    return os.environ.get(_VERIFY_ENV, "").strip().lower() not in (
        "", "0", "false", "off")


def verify_plan(plan, *, nsv: int, dtype=None, shard_qubits=None,
                location: str = "plan",
                raise_on_error: bool = True, emit: bool = True):
    """The ``QUEST_VERIFY=1`` gate: run :func:`check_plan`, flight-record
    the findings, and raise :class:`AnalysisError` when any carry error
    severity. Returns the findings for callers that want them."""
    findings = check_plan(plan, nsv, dtype=dtype,
                          shard_qubits=shard_qubits, location=location)
    if emit:
        emit_findings(findings)
        telemetry.inc("analysis_plans_verified_total")
    if raise_on_error and error_findings(findings):
        raise AnalysisError(findings)
    return findings


def check_smoke_spec(spec: dict) -> list:
    """Run every applicable checker over one bench smoke-plan spec (a
    ``bench.smoke_plan_specs()`` row): tape lint always; the frame/ring
    plan check when the spec carries ``fused`` kwargs; the comm-schedule
    re-pricing when it names a ``mesh_shape`` (on the fused circuit when
    one was built, matching what the bench config itself plans).
    Returns the concatenated findings -- the one implementation behind
    ``tools/lint.py --bench-plans`` and the tier-1 analysis gate."""
    from .._compat import abstract_mesh
    from ..environment import AMP_AXIS

    name = spec["name"]
    circ = spec["build"]()
    findings = lint_tape(list(circ._tape), circ.num_qubits,
                         is_density=circ.is_density_matrix,
                         location=f"{name}.tape")
    fz = None
    if spec.get("fused"):
        kw = dict(spec["fused"])
        fz = circ.fused(**kw)
        # frame grid blocks may reach sharded qubits (collective
        # transposes), so the plan is verified over the FULL space; the
        # DMA-ring grid, though, is what one shard's kernel sweeps
        nsv = (2 if circ.is_density_matrix else 1) * circ.num_qubits
        d = int(kw.get("shard_devices") or 1)
        shard_q = nsv - (d.bit_length() - 1) if d > 1 else None
        findings += check_tape(fz._tape, nsv, dtype=kw.get("dtype"),
                               shard_qubits=shard_q,
                               location=f"{name}.plan")
    if spec.get("mesh_shape"):
        mesh = abstract_mesh(tuple(spec["mesh_shape"]), (AMP_AXIS,))
        target = fz if fz is not None else circ
        sched_findings, _stats, _journal = check_circuit_comm(
            target, mesh, dtype=spec.get("dtype"),
            comm_pipeline=spec.get("comm_pipeline"),
            num_slices=int(spec.get("num_slices", 1)),
            hierarchical=bool(spec.get("hierarchical", False)),
            comm_pipeline_dcn=spec.get("comm_pipeline_dcn"),
            location=f"{name}.schedule")
        findings += sched_findings
    return findings
