"""Standard gate matrices and rotation decompositions.

Mirrors the reference's hardware-agnostic algebra (QuEST_common.c:120-139,
310-324): axis rotations reduce to a "compact unitary" (alpha, beta) pair,
i.e. the 2x2 matrix [[alpha, -conj(beta)], [beta, conj(alpha)]].
All host-side numpy; cast to the register dtype at apply time.
"""

from __future__ import annotations

import math

import numpy as np

SQRT2_INV = 1.0 / math.sqrt(2.0)

HADAMARD = np.array([[SQRT2_INV, SQRT2_INV], [SQRT2_INV, -SQRT2_INV]], dtype=np.complex128)
PAULI_X_M = np.array([[0, 1], [1, 0]], dtype=np.complex128)
PAULI_Y_M = np.array([[0, -1j], [1j, 0]], dtype=np.complex128)
PAULI_Z_M = np.array([[1, 0], [0, -1]], dtype=np.complex128)
S_GATE = np.array([[1, 0], [0, 1j]], dtype=np.complex128)
T_GATE = np.array([[1, 0], [0, np.exp(1j * math.pi / 4)]], dtype=np.complex128)

SQRT_SWAP = np.array(
    [[1, 0, 0, 0],
     [0, 0.5 + 0.5j, 0.5 - 0.5j, 0],
     [0, 0.5 - 0.5j, 0.5 + 0.5j, 0],
     [0, 0, 0, 1]], dtype=np.complex128)


def compact_unitary_matrix(alpha: complex, beta: complex) -> np.ndarray:
    """[[alpha, -conj(beta)], [beta, conj(alpha)]] (compactUnitary, QuEST.h:2562)."""
    return np.array([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]], dtype=np.complex128)


def rotation_around_axis_pair(angle: float, axis) -> tuple[complex, complex]:
    """(alpha, beta) for exp(-i angle/2 (n . sigma)) about unit axis n
    (getComplexPairFromRotation, QuEST_common.c:120-127)."""
    x, y, z = axis[0], axis[1], axis[2]
    mag = math.sqrt(x * x + y * y + z * z)
    x, y, z = x / mag, y / mag, z / mag
    c, s = math.cos(angle / 2), math.sin(angle / 2)
    alpha = complex(c, -s * z)
    beta = complex(s * y, -s * x)
    return alpha, beta


def rotation_matrix(angle: float, axis) -> np.ndarray:
    a, b = rotation_around_axis_pair(angle, axis)
    return compact_unitary_matrix(a, b)


def rx_matrix(theta: float) -> np.ndarray:
    return rotation_matrix(theta, (1.0, 0.0, 0.0))


def ry_matrix(theta: float) -> np.ndarray:
    return rotation_matrix(theta, (0.0, 1.0, 0.0))


def rz_diag(theta: float) -> np.ndarray:
    """Diagonal of Rz(theta) = exp(-i theta/2 Z)."""
    return np.array([np.exp(-0.5j * theta), np.exp(0.5j * theta)], dtype=np.complex128)


def phase_shift_diag(theta: float) -> np.ndarray:
    """diag(1, e^{i theta}) (phaseShift, QuEST.h:1916)."""
    return np.array([1.0, np.exp(1j * theta)], dtype=np.complex128)


#: basis-change matrices sending Pauli P to Z: P = U^dagger Z U
#: X = H Z H; Y = (H S^dagger)^dagger Z (H S^dagger)
BASIS_TO_Z = {
    1: HADAMARD,
    2: HADAMARD @ np.conj(S_GATE).T,
}
