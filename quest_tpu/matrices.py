"""Standard gate matrices and rotation decompositions.

Mirrors the reference's hardware-agnostic algebra (QuEST_common.c:120-139,
310-324): axis rotations reduce to a "compact unitary" (alpha, beta) pair,
i.e. the 2x2 matrix [[alpha, -conj(beta)], [beta, conj(alpha)]].

Host-side numpy by default; cast to the register dtype at apply time. The
parameterized-replay path (quest_tpu.engine.params) instead feeds TRACED
scalars, and every angle-taking builder carries a traced branch assembling
the same matrix with jax.numpy *inside* the jit trace -- entrywise from
real cos/sin components (never a complex transcendental), which keeps the
assembly TPU-portable (no complex dtypes on device) and bit-identical to
the numpy path after the planar cast: libm's ``cexp(iy)`` is exactly
``(cos y, sin y)``, and XLA:CPU lowers ``cos``/``sin`` to the same libm.
"""

from __future__ import annotations

import math

import numpy as np


def is_traced(*xs) -> bool:
    """True when any argument is a jax array/tracer -- matrix assembly must
    then happen inside the trace (runtime gate parameters)."""
    import jax

    return any(isinstance(x, jax.Array) for x in xs)

SQRT2_INV = 1.0 / math.sqrt(2.0)

HADAMARD = np.array([[SQRT2_INV, SQRT2_INV], [SQRT2_INV, -SQRT2_INV]], dtype=np.complex128)
PAULI_X_M = np.array([[0, 1], [1, 0]], dtype=np.complex128)
PAULI_Y_M = np.array([[0, -1j], [1j, 0]], dtype=np.complex128)
PAULI_Z_M = np.array([[1, 0], [0, -1]], dtype=np.complex128)
S_GATE = np.array([[1, 0], [0, 1j]], dtype=np.complex128)
T_GATE = np.array([[1, 0], [0, np.exp(1j * math.pi / 4)]], dtype=np.complex128)

SQRT_SWAP = np.array(
    [[1, 0, 0, 0],
     [0, 0.5 + 0.5j, 0.5 - 0.5j, 0],
     [0, 0.5 - 0.5j, 0.5 + 0.5j, 0],
     [0, 0, 0, 1]], dtype=np.complex128)


def compact_unitary_matrix(alpha: complex, beta: complex) -> np.ndarray:
    """[[alpha, -conj(beta)], [beta, conj(alpha)]] (compactUnitary, QuEST.h:2562)."""
    if is_traced(alpha, beta):
        import jax
        import jax.numpy as jnp

        a, b = jnp.asarray(alpha), jnp.asarray(beta)
        ar, ai = jnp.real(a), jnp.imag(a)
        br, bi = jnp.real(b), jnp.imag(b)
        re = jnp.stack([jnp.stack([ar, -br]), jnp.stack([br, ar])])
        im = jnp.stack([jnp.stack([ai, bi]), jnp.stack([bi, -ai])])
        return jax.lax.complex(re, im)
    return np.array([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]], dtype=np.complex128)


def rotation_around_axis_pair(angle: float, axis) -> tuple[complex, complex]:
    """(alpha, beta) for exp(-i angle/2 (n . sigma)) about unit axis n
    (getComplexPairFromRotation, QuEST_common.c:120-127)."""
    x, y, z = axis[0], axis[1], axis[2]
    mag = math.sqrt(x * x + y * y + z * z)
    x, y, z = x / mag, y / mag, z / mag
    if is_traced(angle):
        import jax
        import jax.numpy as jnp

        c, s = jnp.cos(angle / 2), jnp.sin(angle / 2)
        alpha = jax.lax.complex(c, -s * z)
        beta = jax.lax.complex(s * y, -s * x)
        return alpha, beta
    c, s = math.cos(angle / 2), math.sin(angle / 2)
    alpha = complex(c, -s * z)
    beta = complex(s * y, -s * x)
    return alpha, beta


def rotation_matrix(angle: float, axis) -> np.ndarray:
    a, b = rotation_around_axis_pair(angle, axis)
    return compact_unitary_matrix(a, b)


def rx_matrix(theta: float) -> np.ndarray:
    return rotation_matrix(theta, (1.0, 0.0, 0.0))


def ry_matrix(theta: float) -> np.ndarray:
    return rotation_matrix(theta, (0.0, 1.0, 0.0))


def rz_diag(theta: float) -> np.ndarray:
    """Diagonal of Rz(theta) = exp(-i theta/2 Z)."""
    if is_traced(theta):
        import jax
        import jax.numpy as jnp

        c, s = jnp.cos(theta / 2), jnp.sin(theta / 2)
        return jax.lax.complex(jnp.stack([c, c]), jnp.stack([-s, s]))
    return np.array([np.exp(-0.5j * theta), np.exp(0.5j * theta)], dtype=np.complex128)


def phase_shift_diag(theta: float) -> np.ndarray:
    """diag(1, e^{i theta}) (phaseShift, QuEST.h:1916)."""
    if is_traced(theta):
        import jax
        import jax.numpy as jnp

        c, s = jnp.cos(theta), jnp.sin(theta)
        one, zero = jnp.ones_like(c), jnp.zeros_like(c)
        return jax.lax.complex(jnp.stack([one, c]), jnp.stack([zero, s]))
    return np.array([1.0, np.exp(1j * theta)], dtype=np.complex128)


#: basis-change matrices sending Pauli P to Z: P = U^dagger Z U
#: X = H Z H; Y = (H S^dagger)^dagger Z (H S^dagger)
BASIS_TO_Z = {
    1: HADAMARD,
    2: HADAMARD @ np.conj(S_GATE).T,
}
