"""quest_tpu: a TPU-native full-state quantum circuit simulator.

A ground-up JAX/XLA re-design with the full capability surface of QuEST
(the Quantum Exact Simulation Toolkit): state-vectors and density matrices,
~140 API functions (unitaries, decoherence channels, calculations, operators,
QASM logging), distribution via ``jax.sharding`` over TPU meshes instead of
MPI, and kernels expressed as XLA-fusable tensor programs instead of
hand-written loops.

Public names match the reference C API (``hadamard``, ``controlledNot``,
``calcFidelity``, ...) so a QuEST program ports by swapping includes for
imports; see README for the idiomatic-JAX functional layer underneath.

Architecture map (reference -> here):
  QuEST.h / QuEST.c (L5 API)      -> this package's top-level modules
  QuEST_validation.c (L4a)        -> validation.py
  QuEST_qasm.c (L4b)              -> qasm.py
  mt19937ar.c (L4c RNG)           -> numpy MT19937 in environment.py
  QuEST_common.c (L3 algorithms)  -> matrices.py + per-module logic
  QuEST_internal.h (L2 contract)  -> ops/ (pure jitted kernels)
  QuEST_cpu*.c / QuEST_gpu*.cu    -> ops/* via XLA (one backend, all targets)
  MPI exchange (L1 distributed)   -> parallel/ + XLA SPMD collectives
"""

from .datatypes import (  # noqa: F401
    PAULI_I, PAULI_X, PAULI_Y, PAULI_Z,
    DiagonalOp, PauliHamil, SubDiagonalOp, Vector,
    bindArraysToStackComplexMatrixN, bitEncoding,
    createComplexMatrixN, createPauliHamil, createPauliHamilFromFile,
    createSubDiagonalOp, destroyComplexMatrixN, destroyPauliHamil,
    destroySubDiagonalOp, getStaticComplexMatrixN, initComplexMatrixN,
    initPauliHamil, pauliOpType, phaseFunc,
)
from .environment import (  # noqa: F401
    QuESTEnv, createQuESTEnv, destroyQuESTEnv, getEnvironmentString,
    getQuESTSeeds, reportQuESTEnv, seedQuEST, seedQuESTDefault, syncQuESTEnv,
    syncQuESTSuccess,
)
from .registers import (  # noqa: F401
    Qureg, copyStateFromGPU, copyStateToGPU, copySubstateFromGPU,
    copySubstateToGPU, createCloneQureg, createDensityQureg, createQureg,
    destroyQureg, get_np,
)
from .validation import (  # noqa: F401
    QuESTError, invalidQuESTInputError, invalid_quest_input_error,
    set_input_error_handler,
)
from .circuits import Circuit  # noqa: F401
from .parallel.scheduler import explicit_mesh, plan_circuit  # noqa: F401
from .state_init import *  # noqa: F401,F403
from .gates import *  # noqa: F401,F403
from .calculations import *  # noqa: F401,F403
from .decoherence import *  # noqa: F401,F403
from .operators import *  # noqa: F401,F403
from .reporting import *  # noqa: F401,F403
from .checkpoint import (  # noqa: F401
    loadQureg, saveQureg, verify_snapshot, writeStateToCSV,
)
from . import profiling  # noqa: F401
from . import telemetry  # noqa: F401
from . import engine  # noqa: F401
from .engine import Engine, EnginePool, P, Param  # noqa: F401
from . import resilience  # noqa: F401
from .resilience import (  # noqa: F401
    QuESTBackpressureError, QuESTCancelledError, QuESTChecksumError,
    QuESTHangError, QuESTIntegrityError, QuESTPreemptionError,
    QuESTRetryError, QuESTTimeoutError, resume_segmented,
)
from . import channels  # noqa: F401
from . import trajectories  # noqa: F401
from .trajectories import (  # noqa: F401
    applyTrajectoryKraus, ensemble_density, run_ensemble, unravel,
)
from . import sampling  # noqa: F401
from .sampling import (  # noqa: F401
    applyMidCollapse, applyMidMeasurement, sampleQureg, sample_request,
)
from . import gradients  # noqa: F401
from .gradients import gradient_executable, parameter_shift  # noqa: F401

__version__ = "0.1.0"
