"""Explicit distribution layer: the TPU-native analogue of the reference's
MPI backend (QuEST/src/CPU/QuEST_cpu_distributed.c).

Two ways to run sharded:

1. **GSPMD (default)** — the amplitude array carries a ``NamedSharding``;
   every kernel in :mod:`quest_tpu.ops` is sharding-agnostic and XLA inserts
   the collectives. Zero code, good baseline.
2. **Explicit (this package)** — ``shard_map`` kernels that spell out the
   reference's communication protocol in XLA collectives: the pairwise chunk
   exchange (`exchangeStateVectors` -> ``lax.ppermute``), rank-conditional
   half-updates (`getRotAngle`), the odd-parity swap relocation
   (`statevec_swapQubitAmps`, applied out and back around each non-local
   multi-target gate, as the reference does), and comm-free rank-masked
   phases. Sharded *controls* additionally never travel (device-index
   predicates) -- an improvement over shipping them through the exchange.
   A lazy logical->physical qubit permutation that amortises the swap-backs
   is the next planned optimisation, not yet implemented.
"""

from .mesh import shard_info, local_qubit_count  # noqa: F401
from .exchange import (  # noqa: F401
    dist_apply_matrix1, dist_apply_x, dist_apply_diag_phase,
    dist_apply_parity_phase, dist_apply_local_matrix, dist_swap,
)
from .scheduler import (  # noqa: F401
    DistributedScheduler, active, explicit_mesh, plan_circuit,
)
