"""Mesh/shard bookkeeping.

The state (2, 2^n) is block-sharded over the 1-D ``amps`` mesh axis into
D = 2^d chunks, exactly the reference's rank partition
(``numAmpsPerChunk = 2^n / numRanks``, QuEST_cpu.c:1296-1319): device r holds
flat indices [r*C, (r+1)*C), C = 2^(n-d). Hence qubit q is **local** iff
q < n - d (its amplitude pairs lie within one chunk -- the reference's
``halfMatrixBlockFitsInChunk`` predicate, QuEST_cpu_distributed.c:372-377),
and a **sharded** qubit q >= n - d is bit (q - (n-d)) of the device index.
"""

from __future__ import annotations

from jax.sharding import Mesh

from ..environment import AMP_AXIS

def local_qubit_count(n: int, mesh: Mesh | None) -> int:
    """Number of low qubits entirely local to each shard."""
    if mesh is None or mesh.size == 1:
        return n
    d = (mesh.size - 1).bit_length()
    return n - d


def shard_info(n: int, mesh: Mesh | None):
    """(num_local_qubits, num_shard_qubits, axis_name)."""
    nl = local_qubit_count(n, mesh)
    return nl, n - nl, AMP_AXIS


def slice_chip_bits(mesh: Mesh | None, num_slices: int) -> int:
    """Number of intra-slice (ICI) shard bits of a slice-major pod
    topology: the device index's low bits address chips within a slice,
    the top log2(num_slices) bits cross slices (DCN). Rejects a slice
    count that does not evenly power-of-two-partition the mesh -- the
    slice-major device order is only meaningful when every slice holds
    the same power-of-two chip count."""
    ns = max(int(num_slices), 1)
    if ns & (ns - 1):
        raise ValueError(
            f"num_slices must be a power of two (got {ns}): slice-major "
            f"device order splits the shard bits at a bit boundary")
    size = 1 if mesh is None else mesh.size
    if ns > size or size % ns:
        raise ValueError(
            f"num_slices={ns} does not partition the {size}-device mesh "
            f"into equal power-of-two slices")
    return ((size // ns) - 1).bit_length()


def shard_bit_link(n: int, mesh: Mesh | None, num_slices: int,
                   qubit: int) -> str | None:
    """Which interconnect a comm op on sharded ``qubit`` rides: 'ici'
    (intra-slice chip axis, the low shard bits) or 'dcn' (inter-slice,
    the top log2(num_slices) shard bits); None for local qubits."""
    nl = local_qubit_count(n, mesh)
    if qubit < nl:
        return None
    return "ici" if (qubit - nl) < slice_chip_bits(mesh, num_slices) \
        else "dcn"
