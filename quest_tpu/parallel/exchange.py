"""shard_map kernels spelling out the reference's distributed protocol in
XLA collectives.

Reference protocol (QuEST_cpu_distributed.c):
  - non-local 1q dense gate: pairwise full-chunk swap over MPI_Isend/Irecv
    (``exchangeStateVectors``, :495-533) then a rank-conditional half-update
    (``getRotAngle``, :260-308; ``statevec_compactUnitaryDistributed``).
  - non-local X class: pure chunk exchange (:1109-1152).
  - diagonal/phase ops: never communicate (phase depends only on index bits).
  - qubit relocation: odd-parity half-chunk exchange
    (``statevec_swapQubitAmps``, :1424-1459).
  - scalar reductions: MPI_Allreduce -> here ``jnp.sum`` on the sharded
    array (XLA emits the psum) or an explicit ``lax.psum`` inside shard_map.

Here each becomes a ``shard_map`` over the 1-D ``amps`` mesh axis with
``lax.ppermute`` as the exchange primitive, riding ICI instead of MPI.
All kernels are pure (amps -> amps), composable under an outer ``jax.jit``,
and handle controls split into *local* controls (index-mask inside the
chunk) and *sharded* controls (device-index predicate -- zero communication,
an improvement over shipping them into the exchange).

Layout (see .mesh): device r of D=2^d holds flat indices [r*C, (r+1)*C);
qubit q local iff q < nl = n-d; sharded qubit q is bit (q-nl) of r.

Plane contract (round 7, the sharded double-float path): the DATA-MOVEMENT
collectives (``dist_permute_bits``, ``dist_swap``'s sharded regimes, the
``dist_apply_x`` chunk permute) are plane-agnostic -- they carry the planar
(2, 2^n) pair or the PRECISION=2 double-float (4, 2^n) f32 layout natively,
which is how per-shard df kernel runs are joined by the same grouped
collectives as f32 plans. The ARITHMETIC kernels (pair exchange's blended
update, diag/parity phases) stay planar: a df state REJOINS to (2, 2^n)
f64 via the exact ``pallas_df.df_join`` before any of them runs -- the
documented hi/lo plane-pair relabeling (both conversions are exact, so the
round trip costs bandwidth, never precision).

Pipelined collectives (round 8): every launch site here accepts a
``pipeline`` depth. At depth ``P > 1`` the per-device chunk is split into
``P`` contiguous power-of-two sub-chunks and the collective is issued as
``P`` independent sub-collectives interleaved with the per-sub-chunk
blend/mask/scatter compute -- the prologue issues slice 0's transfer, the
steady state issues slice k+1 while consuming slice k, and the epilogue
drains (``_pipeline_schedule``). XLA's latency-hiding scheduler can then
run slice k's compute while slice k+1's ``ppermute``/``all_to_all`` is in
flight -- the comm-side twin of the Pallas N-slot DMA ring. Slicing is
always along the amplitude axis with purely elementwise / slice-local
compute per sub-chunk, so the pipelined result is BIT-IDENTICAL to the
monolithic ``P=1`` launch by construction, and the chunk-unit cost model
(:func:`permute_collective_stats`, scheduler journal pricing) is
deliberately blind to the depth: pipelining re-times the same traffic, it
never adds any. Depth resolution: explicit ``pipeline=`` argument, else
the ``QUEST_COMM_PIPELINE`` env default (:func:`comm_pipeline_default`),
then one clamp to the site's slice limit (:func:`effective_comm_pipeline`,
shared with analysis.commcheck exactly like effective_ring_depth is
shared with ringcheck).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import telemetry
from .._compat import shard_map

from ..environment import AMP_AXIS
from ..ops import apply as K
from ..ops.layout import grouped_axes
from .mesh import local_qubit_count

__all__ = ["dist_apply_matrix1", "dist_apply_x", "dist_apply_diag_phase",
           "dist_apply_parity_phase", "dist_apply_local_matrix", "dist_swap",
           "dist_permute_bits", "permute_collective_stats",
           "comm_pipeline_default", "comm_pipeline_dcn_default",
           "resolve_pipeline", "resolve_pipeline_dcn",
           "effective_comm_pipeline"]


def _specs(mesh):
    return dict(mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P(None, AMP_AXIS))


#: env knob for the default comm-pipeline depth (1 = monolithic launch);
#: overridden per-plan by Circuit.fused(comm_pipeline=) / per-context by
#: explicit_mesh(comm_pipeline=). Deliberately distinct from the
#: scheduler's num_slices ICI/DCN split: num_slices partitions the MESH,
#: the pipeline depth partitions each device's CHUNK.
_PIPE_ENV = "QUEST_COMM_PIPELINE"

#: monolithic until the on-chip kernelprobe sweep picks a better default
#: (BASELINE.md documents the sweep recipe); the emulated-CPU tier-1 mesh
#: cannot measure overlap, so the committed default keeps the exchange
#: lowering byte-identical to round 7.
_DEF_COMM_PIPELINE = 1

_PIPE_ENV_WARNED: set = set()


def comm_pipeline_default() -> int:
    """The env-resolved comm-pipeline depth (warn-once QT206 on a
    malformed ``QUEST_COMM_PIPELINE``, mirroring the ring's QT205)."""
    from ..analysis.diagnostics import parse_env_int
    return parse_env_int(_PIPE_ENV, _DEF_COMM_PIPELINE, minimum=1,
                         code="QT206", noun="pipeline depth",
                         below="is below the monolithic minimum",
                         warned=_PIPE_ENV_WARNED)


def resolve_pipeline(pipeline) -> int:
    """Explicit ``pipeline=`` argument if given, else the env default."""
    return int(pipeline) if pipeline is not None else comm_pipeline_default()


#: per-link-class override (round 15): collectives whose shard bits ride
#: the slow cross-slice DCN link pipeline at this depth instead of the
#: base QUEST_COMM_PIPELINE -- the latency gap between DCN and ICI means
#: the overlap window a DCN sub-collective must fill is deeper. Unset
#: inherits the base depth (the flat, single-tier behaviour).
_PIPE_DCN_ENV = "QUEST_COMM_PIPELINE_DCN"

_PIPE_DCN_ENV_WARNED: set = set()


def comm_pipeline_dcn_default():
    """The env-resolved DCN comm-pipeline depth, or None when
    ``QUEST_COMM_PIPELINE_DCN`` is unset (inherit the base depth).
    Malformed values warn once via QT210, mirroring the base knob's
    QT206."""
    import os
    if not os.environ.get(_PIPE_DCN_ENV, "").strip():
        return None
    from ..analysis.diagnostics import parse_env_int
    return parse_env_int(_PIPE_DCN_ENV, 1, minimum=1,
                         code="QT210", noun="DCN pipeline depth",
                         below="is below the monolithic minimum",
                         warned=_PIPE_DCN_ENV_WARNED)


def resolve_pipeline_dcn(pipeline_dcn, pipeline=None) -> int:
    """Depth for a DCN-riding collective: the explicit ``pipeline_dcn``
    argument, else the ``QUEST_COMM_PIPELINE_DCN`` env, else fall all the
    way back to the base (ICI) resolution of ``pipeline``."""
    if pipeline_dcn is not None:
        return int(pipeline_dcn)
    env = comm_pipeline_dcn_default()
    if env is not None:
        return env
    return resolve_pipeline(pipeline)


def effective_comm_pipeline(depth: int, limit: int, *,
                            site: str = "exchange") -> int:
    """The ONE clamp from a requested depth to what a launch site can
    slice: the largest power of two that is neither above the request nor
    above ``limit`` (the site's slice count ceiling -- per-device columns
    for the elementwise kernels, the grouped-view minor axis for
    all_to_all / odd-parity sends). Pure -- no diagnostics are emitted
    here; analysis.commcheck re-runs this clamp and reports QT209 when it
    bites, exactly as ringcheck shares pallas_gates.effective_ring_depth.
    ``site`` only labels commcheck findings."""
    depth = max(1, int(depth))
    depth = 1 << (depth.bit_length() - 1)      # round down to power of two
    limit = max(1, int(limit))
    limit = 1 << (limit.bit_length() - 1)
    return min(depth, limit)


def _pipeline_schedule(nslices, transfer, compute, src=None):
    """Emit the software-pipelined transfer/compute interleaving for
    ``nslices`` sub-chunks and return the per-slice outputs in order.

    ``transfer(j)`` issues sub-chunk j's collective; ``compute(k, landed)``
    consumes the landed transfer that output slice k needs, which is
    transfer ``src(k)`` (identity when the collective does not permute the
    slice index; dist_apply_x's local hi-bit flips make it an XOR). The
    emission order is the classic three phases -- prologue issues slice 0's
    transfer; the steady state issues transfer k+1 BEFORE computing slice k
    so XLA's latency-hiding scheduler always has the next collective in
    flight behind the current blend; the epilogue drains the last transfer
    into the last compute. Every transfer is issued exactly once and
    consumed exactly once (analysis.commcheck proves the QT207/QT208
    hazard-freedom of this exact schedule)."""
    if src is None:
        src = lambda k: k
    inflight = {}

    def ensure(j):
        if j not in inflight:
            inflight[j] = transfer(j)

    ensure(src(0))                       # prologue: slice 0's transfer
    outs = []
    for k in range(nslices):             # steady state + epilogue
        if k + 1 < nslices:
            ensure(src(k + 1))           # next transfer in flight ...
        outs.append(compute(k, inflight.pop(src(k))))  # ... behind compute k
    assert not inflight                  # epilogue drained
    return outs


def _launch(kernel, mesh, amps, *, kind="collective", pipeline=1):
    """The one launch point for every collective kernel here, threaded
    through the resilience guard (site ``exchange.collective``): a direct
    call when no fault plan is installed; injected transient comm faults
    retry under the backoff policy and exhaustion fails closed with a
    typed QuESTRetryError (quest_tpu.resilience.guard.collective). With
    ``QUEST_WATCHDOG_MS`` armed the launch is deadline-bounded -- a hung
    collective raises a typed QuESTHangError instead of blocking forever
    -- EXCEPT under jit tracing: jax trace state is thread-local, so a
    traced launch must stay on the tracing thread (the compiled
    execution is covered by the engine-dispatch watchdog instead).

    Retry-vs-pipeline contract (round 8): the guard wraps the WHOLE
    shard_map closure, so at pipeline depth > 1 a transient fault replays
    the ENTIRE multi-slice launch from the untouched input -- never a
    resume mid-slice. The kernels are pure (amps -> amps, no donation at
    this boundary), which is what makes the whole-launch replay
    bit-identical.

    ``kind``/``pipeline`` label telemetry: the effective depth lands in
    the ``comm_pipeline_depth`` gauge, and eager (non-traced) launches are
    wall-timed into the ``comm_collective_ms{kind,pipeline}`` histogram
    (traced launches fuse into an enclosing jit, so there is no
    per-collective wall time to observe)."""
    import time

    import jax

    from ..resilience import guard
    telemetry.set_gauge("comm_pipeline_depth", int(pipeline))
    run = lambda: shard_map(kernel, **_specs(mesh))(amps)
    traced = isinstance(amps, jax.core.Tracer)
    if traced or not telemetry.enabled():
        return guard.collective(run, watched=not traced)
    t0 = time.perf_counter()
    out = guard.collective(run, watched=True)
    jax.block_until_ready(out)
    telemetry.observe("comm_collective_ms",
                      (time.perf_counter() - t0) * 1e3,
                      kind=kind, pipeline=int(pipeline))
    return out


def _rank_bit(r, q, nl):
    return (r >> (q - nl)) & 1


def _ctrl_pred(r, shard_controls, shard_states, nl):
    """Device-index predicate for sharded controls (comm-free)."""
    pred = jnp.bool_(True)
    for c, s in zip(shard_controls, shard_states):
        pred = jnp.logical_and(pred, _rank_bit(r, c, nl) == s)
    return pred


def _apply_local_ctrl_mask(own, new, nl, local_controls, local_states,
                           offset=0):
    """new where all local controls match, else own (flat-iota bit mask).

    ``offset`` is the in-chunk column index of ``own[:, 0]`` -- 0 for a
    whole-chunk call, ``k * slice_width`` when a pipelined launch masks
    sub-chunk k (the control bits are tested on the GLOBAL in-chunk index,
    so a sliced mask composes bit-identically with the monolithic one).

    This was a grouped-view ``told.at[idx].set(new[idx])`` until round 6:
    that scatter form MISCOMPILES when two shard_map kernels compose under
    one jit on this container's jax (batched-relocation layouts surfaced
    it: eager and single-kernel jit agree with the numpy oracle, two
    chained kernels under jit corrupt exactly the control-masked half).
    The elementwise select lowers to a fused where with identical traffic
    and is immune to the scatter fusion."""
    if not local_controls:
        return new
    j = lax.iota(jnp.int32, own.shape[1]) + offset
    ok = jnp.ones(own.shape[1], bool)
    for c, s in zip(local_controls, local_states):
        ok = jnp.logical_and(ok, ((j >> c) & 1) == s)
    return jnp.where(ok[None, :], new, own)


def _split_controls(controls, states, nl):
    states = tuple(states) if states else (1,) * len(controls)
    lc = [(c, s) for c, s in zip(controls, states) if c < nl]
    sc = [(c, s) for c, s in zip(controls, states) if c >= nl]
    return ([c for c, _ in lc], [s for _, s in lc],
            [c for c, _ in sc], [s for _, s in sc])


# ---------------------------------------------------------------------------
# 1-qubit dense gate (compactUnitary / unitary class)
# ---------------------------------------------------------------------------

def dist_apply_matrix1(amps, matrix, *, n: int, target: int,
                       controls: tuple[int, ...] = (),
                       control_states: tuple[int, ...] = (),
                       conj: bool = False, mesh: Mesh, pipeline=None):
    """U (planar (2,2,2)) on ``target``; the explicit-exchange analogue of
    ops.apply.apply_matrix for one target qubit.

    Sharded target: ``ppermute`` pair exchange + blended update --
    identical traffic to the reference's exchangeStateVectors scheme. At
    ``pipeline`` depth P > 1 the chunk is split into P column slices and
    each slice's exchange is issued ahead of the previous slice's blend
    (the blend, control mask and rank predicate are all elementwise, so
    the sliced launch is bit-identical to the monolithic one). Local
    target with (possibly) sharded controls: no communication.
    """
    nl = local_qubit_count(n, mesh)
    eff, kind = 1, "local_matrix"
    if target >= nl:
        telemetry.inc("exchange_calls_total", kind="pair_exchange")
        eff = effective_comm_pipeline(resolve_pipeline(pipeline), 1 << nl,
                                      site="pair_exchange")
        kind = "pair_exchange"
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)
    mr, mi = matrix[0], matrix[1]
    if conj:
        mi = -mi

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)
        if target < nl:
            new = K.apply_matrix(own, matrix, n=nl, targets=(target,),
                                 controls=tuple(lc), control_states=tuple(ls),
                                 conj=conj)
        else:
            bitpos = target - nl
            size = mesh.shape[AMP_AXIS]
            perm = [(i, i ^ (1 << bitpos)) for i in range(size)]
            b = _rank_bit(r, target, nl)
            # new_amp(bit=b) = m[b,b] * own + m[b,1-b] * pair
            m_bb_r, m_bb_i = mr[b, b], mi[b, b]
            m_bo_r, m_bo_i = mr[b, 1 - b], mi[b, 1 - b]

            def blend(own_s, pair_s, off):
                re = (m_bb_r * own_s[0] - m_bb_i * own_s[1]
                      + m_bo_r * pair_s[0] - m_bo_i * pair_s[1])
                im = (m_bb_r * own_s[1] + m_bb_i * own_s[0]
                      + m_bo_r * pair_s[1] + m_bo_i * pair_s[0])
                return _apply_local_ctrl_mask(own_s, jnp.stack([re, im]),
                                              nl, lc, ls, offset=off)

            if eff == 1:
                pair = lax.ppermute(own, AMP_AXIS, perm)
                new = blend(own, pair, 0)
            else:
                s = own.shape[1] // eff

                def sl(k):
                    return lax.slice_in_dim(own, k * s, (k + 1) * s, axis=1)

                new = jnp.concatenate(_pipeline_schedule(
                    eff,
                    lambda j: lax.ppermute(sl(j), AMP_AXIS, perm),
                    lambda k, pair_s: blend(sl(k), pair_s, k * s)), axis=1)
        if sc:
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps, kind=kind, pipeline=eff)


def dist_apply_local_matrix(amps, matrix, *, n: int, targets: tuple[int, ...],
                            controls: tuple[int, ...] = (),
                            control_states: tuple[int, ...] = (),
                            conj: bool = False, mesh: Mesh, pipeline=None):
    """Dense gate whose targets are ALL local: embarrassingly parallel
    shard_map around the single-chunk kernel (the reference's *Local fast
    path, QuEST_cpu_distributed.c:372-377) -- sharded controls become a
    comm-free device-index predicate instead of participating in the kernel.

    ``pipeline`` is accepted for launch-site uniformity but the kernel is
    comm-free and its GEMM gathers across the whole chunk, so the launch
    is always monolithic (there is no transfer to overlap).
    """
    nl = local_qubit_count(n, mesh)
    assert all(t < nl for t in targets)
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)

    def kernel(chunk):
        own = chunk
        new = K.apply_matrix(own, matrix, n=nl, targets=tuple(targets),
                             controls=tuple(lc), control_states=tuple(ls),
                             conj=conj)
        if sc:
            r = lax.axis_index(AMP_AXIS)
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps, kind="local_matrix", pipeline=1)


# ---------------------------------------------------------------------------
# X class (amplitude permutation)
# ---------------------------------------------------------------------------

def dist_apply_x(amps, *, n: int, targets: tuple[int, ...],
                 controls: tuple[int, ...] = (),
                 control_states: tuple[int, ...] = (),
                 mesh: Mesh, pipeline=None):
    """Multi-controlled multi-target NOT: sharded target bits become one
    ``ppermute`` (rank-index XOR), local target bits an in-chunk flip
    (reference: ctrl-skip exchange, QuEST_cpu_distributed.c:1109-1152).

    Pipelined form (depth P > 1, sharded targets present): the chunk is
    split into P column slices and each slice is exchanged independently.
    The local target bits split at the slice width -- bits at or above
    log2(slice) select WHICH transferred slice feeds output slice k (an
    XOR of the slice index, the ``src`` hook of ``_pipeline_schedule``)
    while bits below it flip within the slice -- so the permutation the
    monolithic kernel applies in one piece is reproduced slice-exactly.
    """
    nl = local_qubit_count(n, mesh)
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)
    local_t = tuple(t for t in targets if t < nl)
    shard_t = tuple(t for t in targets if t >= nl)
    eff = 1
    if shard_t:
        telemetry.inc("exchange_calls_total", kind="x_permute")
        eff = effective_comm_pipeline(resolve_pipeline(pipeline), 1 << nl,
                                      site="x_permute")

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)
        if eff == 1 or not shard_t:
            new = own
            if shard_t:
                mask = 0
                for t in shard_t:
                    mask |= 1 << (t - nl)
                size = mesh.shape[AMP_AXIS]
                perm = [(i, i ^ mask) for i in range(size)]
                new = lax.ppermute(new, AMP_AXIS, perm)
            if local_t:
                new = K.apply_x_class(new, n=nl, targets=local_t)
            new = _apply_local_ctrl_mask(own, new, nl, lc, ls)
        else:
            mask = 0
            for t in shard_t:
                mask |= 1 << (t - nl)
            size = mesh.shape[AMP_AXIS]
            perm = [(i, i ^ mask) for i in range(size)]
            s = own.shape[1] // eff
            s_bits = s.bit_length() - 1
            lo_t = tuple(t for t in local_t if t < s_bits)
            hi_mask = 0
            for t in local_t:
                if t >= s_bits:
                    hi_mask |= 1 << (t - s_bits)

            def transfer(j):
                return lax.ppermute(
                    lax.slice_in_dim(own, j * s, (j + 1) * s, axis=1),
                    AMP_AXIS, perm)

            def compute(k, recv):
                new_s = (K.apply_x_class(recv, n=s_bits, targets=lo_t)
                         if lo_t else recv)
                own_s = lax.slice_in_dim(own, k * s, (k + 1) * s, axis=1)
                return _apply_local_ctrl_mask(own_s, new_s, nl, lc, ls,
                                              offset=k * s)

            new = jnp.concatenate(
                _pipeline_schedule(eff, transfer, compute,
                                   src=lambda k: k ^ hi_mask), axis=1)
        if sc:
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps,
                   kind="x_permute" if shard_t else "local_x", pipeline=eff)


# ---------------------------------------------------------------------------
# whole-layout bit permutation (one-collective reconciliation)
# ---------------------------------------------------------------------------

def _permute_decompose(n: int, source, nl: int):
    """Split the bit permutation ``new_bit[q] = old_bit[source[q]]`` into
    the three machine moves: a device-index relabel (shard->shard bits), a
    grouped all-to-all (shard<->local crossings), and a free local
    transpose. Returns (rho_src, Q_c, L_in, L_out, dest) where ``rho_src``
    maps shard position -> old shard position it takes its bit from (None
    when no relabel is needed), ``Q_c`` lists the shard positions fed from
    local bits, ``L_in[k]``/``L_out[k]`` the outgoing/incoming local bit of
    crossing ``k``, and ``dest`` the inverse permutation."""
    source = tuple(source)
    assert sorted(source) == list(range(n)), source
    dest = [0] * n
    for q, p in enumerate(source):
        dest[p] = q
    shard = range(nl, n)
    Q_c = [q for q in shard if source[q] < nl]
    P_out = [p for p in shard if dest[p] < nl]
    rho_src = None
    holds = {q: q for q in shard}  # device position -> original bit it holds
    if any(source[q] >= nl and source[q] != q for q in shard):
        # shard->shard bits displaced: one ppermute relabel puts each at its
        # home device-bit position; the outgoing (P_out) bits park at the
        # Q_c positions so the residual crossing is position-aligned
        rho_src = {q: source[q] for q in shard if source[q] >= nl}
        for q, p in zip(sorted(Q_c), sorted(P_out)):
            rho_src[q] = p
        holds = dict(rho_src)
    L_in = [source[q] for q in sorted(Q_c)]
    L_out = [dest[holds[q]] for q in sorted(Q_c)]
    return rho_src, sorted(Q_c), L_in, L_out, dest


def permute_collective_stats(n: int, source, mesh: Mesh,
                             unit_scale: float = 1.0) -> dict:
    """Trace-free cost model of :func:`dist_permute_bits`: number of
    collectives and chunk-units ((send+recv)/half-chunk pairs) it will pay.
    A relabel ppermute re-routes the full chunk (2 units, like a rank
    permute); the grouped all-to-all over m crossing bits moves
    (2^m - 1)/2^m of the chunk each way (2*(1 - 2^-m) units: m=1 is exactly
    the odd-parity half-exchange's 1 unit).

    ``unit_scale`` restates the units for wider state layouts: 1 is the
    planar f32 pair; the double-precision layouts -- planar f64, or the
    double-float 4-plane f32 state the sharded PRECISION=2 fast path
    permutes between per-shard kernel runs -- move twice the bytes per
    chunk and price at ``unit_scale=2`` (the df 2x chunk-unit accounting,
    scheduler.DistributedScheduler.apply_frame_permute)."""
    nl = local_qubit_count(n, mesh)
    rho_src, Q_c, _, _, _ = _permute_decompose(n, source, nl)
    m = len(Q_c)
    units = (2.0 if rho_src is not None else 0.0)
    units += 2.0 * (1.0 - 0.5 ** m) if m else 0.0
    return {"relabel_ppermute": rho_src is not None, "crossing_bits": m,
            "chunk_units": units * unit_scale,
            "collectives": int(rho_src is not None) + int(m > 0)}


def dist_permute_bits(amps, *, n: int, source, mesh: Mesh, pipeline=None):
    """Apply an arbitrary bit permutation of the physical index in at most
    two collectives: ``new_bit[q] = old_bit[source[q]]``.

    This is the deferred scheduler's reconciliation primitive (round 5):
    instead of restoring the identity layout one odd-parity pair swap per
    displaced qubit (the reference's swapQubitAmps unit,
    QuEST_cpu_distributed.c:1443-1459), the whole permutation runs as

    - one ``ppermute`` device relabel IF any shard bit moves to another
      shard position (pure re-route, no local data motion), then
    - one grouped ``lax.all_to_all`` carrying ALL shard<->local crossings
      at once (each device sends (2^m-1)/2^m of its chunk for m crossing
      bits -- vs m full half-exchanges for m sequential swaps), then
    - one free in-chunk transpose for the local->local remainder.

    Plane-agnostic (round 7): ``amps`` may carry any leading plane count --
    the planar (2, 2^n) pair or the double-float (4, 2^n) layout the
    sharded PRECISION=2 fast path permutes between per-shard kernel runs.
    The permutation is pure data movement on the amplitude axis, so all
    P planes ride the same relabel/all-to-all/transpose natively.

    Pipelined form (depth > 1, crossing bits present): the grouped view's
    residual minor axis (the 2^(nl-m) columns every crossing piece keeps
    in place) is split into ``pipeline`` slices and each slice ships as
    its own grouped ``all_to_all`` -- the all-to-all routing depends only
    on the major (piece) axis, so per-slice collectives concatenate back
    bit-exactly, and the df 4-plane layout rides the sliced collective as
    natively as the monolithic one (the planes axis is untouched). The
    device-relabel ppermute (a pure re-route) stays monolithic.
    """
    nl = local_qubit_count(n, mesh)
    source = tuple(source)
    if all(source[q] == q for q in range(n)):
        return amps
    telemetry.inc("exchange_calls_total", kind="grouped_permute")
    rho_src, Q_c, L_in, L_out, dest = _permute_decompose(n, source, nl)
    m = len(Q_c)
    P = amps.shape[0]
    size = mesh.shape[AMP_AXIS] if mesh is not None and mesh.size > 1 else 1
    eff = (effective_comm_pipeline(resolve_pipeline(pipeline),
                                   1 << (nl - m), site="grouped_permute")
           if m else 1)

    if rho_src is not None:
        def relabel(r: int) -> int:
            out = 0
            for q, p in rho_src.items():
                out |= ((r >> (p - nl)) & 1) << (q - nl)
            return out

        perm = [(r, relabel(r)) for r in range(size)]

        def relabel_kernel(chunk):
            return lax.ppermute(chunk, AMP_AXIS, perm)

        amps = shard_map(relabel_kernel, **_specs(mesh))(amps)

    groups = None
    if m:
        qbits = [q - nl for q in Q_c]
        gmask = sum(1 << b for b in qbits)
        by_base: dict[int, list[int]] = {}
        for r in range(size):
            by_base.setdefault(r & ~gmask, []).append(r)
        groups = [sorted(v) for _, v in sorted(by_base.items())]

    def kernel(chunk):
        # grouped view: axis 0 = the P planes (re/im, or the df 4-plane
        # stack), then bits nl-1 .. 0 (bit b at axis 1 + (nl-1-b))
        t = chunk.reshape((P,) + (2,) * nl)

        def ax(b):
            return 1 + (nl - 1 - b)

        if m:
            front = [ax(b) for b in reversed(L_in)]
            fset = set(front)
            rest = [a for a in range(1, nl + 1) if a not in fset]
            t = t.transpose(front + [0] + rest)
            t = t.reshape((1 << m, P) + (2,) * len(rest))
            # piece j (chunk bits at L_in spell j) -> group member whose
            # device bits at Q_c spell j; received concat index j' = the
            # sender's Q_c device bits = the incoming values for L_out
            if eff == 1:
                t = lax.all_to_all(t, AMP_AXIS, 0, 0,
                                   axis_index_groups=groups)
            else:
                # routing depends only on the piece (major) axis: slicing
                # the residual minor axis into eff independent grouped
                # all_to_alls ships the same bytes to the same peers,
                # just in overlap-schedulable sub-collectives
                R = 1 << (nl - m)
                sR = R // eff
                t2 = t.reshape((1 << m, P, R))
                t2 = jnp.concatenate(_pipeline_schedule(
                    eff,
                    lambda j: lax.all_to_all(
                        lax.slice_in_dim(t2, j * sR, (j + 1) * sR, axis=2),
                        AMP_AXIS, 0, 0, axis_index_groups=groups),
                    lambda k, got: got), axis=2)
                t = t2.reshape((1 << m, P) + (2,) * len(rest))
            t = t.reshape((2,) * m + (P,) + (2,) * len(rest))
            src_axis = {}
            for k in range(m):
                src_axis[L_out[k]] = m - 1 - k
            rest_bits = [nl - 1 - (a - 1) for a in rest]
            for i, b in enumerate(rest_bits):
                src_axis[dest[b]] = m + 1 + i
            perm_axes = [m] + [src_axis[u] for u in range(nl - 1, -1, -1)]
            t = t.transpose(perm_axes)
        else:
            # no crossings: only the local->local remainder moves
            src_axis = {dest[b]: ax(b) for b in range(nl)}
            t = t.transpose([0] + [src_axis[u] for u in range(nl - 1, -1, -1)])
        return t.reshape(P, -1)

    if mesh is None or mesh.size == 1:
        assert m == 0 and rho_src is None
        return kernel(amps)
    return _launch(kernel, mesh, amps, kind="grouped_permute", pipeline=eff)

def dist_apply_diag_phase(amps, diag, *, n: int, targets: tuple[int, ...],
                          controls: tuple[int, ...] = (),
                          control_states: tuple[int, ...] = (),
                          conj: bool = False, mesh: Mesh, pipeline=None):
    """diag (planar (2, 2^t)) applied to ``targets``; entry index bit k is
    targets[k]'s bit. Phases depend only on index bits, so sharded qubits
    contribute a per-device scalar offset into the diagonal -- no traffic at
    all (the reference's phase kernels are likewise exchange-free,
    QuEST_cpu.c:3235-3285). At ``pipeline`` depth P > 1 the (comm-free,
    purely elementwise) phase is emitted in P column slices so XLA can
    interleave it with any in-flight neighbouring collective."""
    nl = local_qubit_count(n, mesh)
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)
    eff = effective_comm_pipeline(resolve_pipeline(pipeline), 1 << nl,
                                  site="diag_phase")
    dr, di = diag[0], diag[1]
    if conj:
        di = -di

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)

        def phase(own_s, off):
            j = lax.iota(jnp.int32, own_s.shape[1]) + off
            idx = jnp.zeros((), jnp.int32)
            for k, t in enumerate(targets):
                if t < nl:
                    bit = (j >> t) & 1
                else:
                    bit = _rank_bit(r, t, nl).astype(jnp.int32)
                idx = idx + (bit << k)
            fr, fi = dr[idx], di[idx]
            re = fr * own_s[0] - fi * own_s[1]
            im = fr * own_s[1] + fi * own_s[0]
            return _apply_local_ctrl_mask(own_s, jnp.stack([re, im]),
                                          nl, lc, ls, offset=off)

        if eff == 1:
            new = phase(own, 0)
        else:
            s = own.shape[1] // eff
            new = jnp.concatenate(
                [phase(lax.slice_in_dim(own, k * s, (k + 1) * s, axis=1),
                       k * s) for k in range(eff)], axis=1)
        if sc:
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps, kind="diag_phase", pipeline=eff)


def dist_apply_parity_phase(amps, theta, *, n: int, qubits: tuple[int, ...],
                            controls: tuple[int, ...] = (),
                            control_states: tuple[int, ...] = (),
                            conj: bool = False, mesh: Mesh, pipeline=None):
    """exp(-i theta/2 Z x...x Z): comm-free; sharded qubits fold their bit
    into the device-index parity (reference mask-parity kernel
    QuEST_cpu.c:3235-3285 -- likewise exchange-free). At ``pipeline``
    depth P > 1 the elementwise sign flip is emitted in P column slices,
    as :func:`dist_apply_diag_phase`."""
    nl = local_qubit_count(n, mesh)
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)
    eff = effective_comm_pipeline(resolve_pipeline(pipeline), 1 << nl,
                                  site="parity_phase")
    local_q = [q for q in qubits if q < nl]
    shard_q = [q for q in qubits if q >= nl]

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)

        def phase(own_s, off):
            j = lax.iota(jnp.int32, own_s.shape[1]) + off
            par = jnp.zeros((), jnp.int32)
            for q in local_q:
                par = par ^ ((j >> q) & 1)
            for q in shard_q:
                par = par ^ _rank_bit(r, q, nl).astype(jnp.int32)
            sign = (1 - 2 * par).astype(own_s.dtype)
            th = jnp.asarray(-theta if conj else theta, dtype=own_s.dtype)
            fr, fi = jnp.cos(th / 2), -jnp.sin(th / 2) * sign
            re = fr * own_s[0] - fi * own_s[1]
            im = fr * own_s[1] + fi * own_s[0]
            return _apply_local_ctrl_mask(own_s, jnp.stack([re, im]),
                                          nl, lc, ls, offset=off)

        if eff == 1:
            new = phase(own, 0)
        else:
            s = own.shape[1] // eff
            new = jnp.concatenate(
                [phase(lax.slice_in_dim(own, k * s, (k + 1) * s, axis=1),
                       k * s) for k in range(eff)], axis=1)
        if sc:
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps, kind="parity_phase", pipeline=eff)


# ---------------------------------------------------------------------------
# qubit-amplitude swap (the relocation primitive)
# ---------------------------------------------------------------------------

def dist_swap(amps, *, n: int, qb1: int, qb2: int, mesh: Mesh,
              pipeline=None):
    """SWAP(qb1, qb2). Three regimes, as the reference (:1424-1459):

    - both local: in-chunk axis transposition;
    - both sharded: pure device-index bit swap (one ppermute);
    - mixed: odd-parity half-chunk exchange -- each device sends the half of
      its chunk whose local bit differs from its device bit, halving traffic
      vs a full exchange.

    The sharded regimes are pure data movement and carry any leading plane
    count (planar pair or the df 4-plane layout); the both-local regime
    routes through the planar apply_swap kernel and takes (2, N) only.

    Pipelined form (depth P > 1): the both-sharded ppermute slices the
    chunk columns; the odd-parity exchange slices the grouped view's
    MAJOR axis (the 2^(nl-1-lo) blocks above the swapped local bit), so
    each slice's send/recv/reassemble is independent and the per-slice
    stacks concatenate back bit-exactly.
    """
    nl = local_qubit_count(n, mesh)
    lo, hi = min(qb1, qb2), max(qb1, qb2)
    eff, kind = 1, "swap_local"
    if hi >= nl:
        kind = "swap_rank_permute" if lo >= nl else "swap_odd_parity"
        telemetry.inc("exchange_calls_total", kind=kind)
        limit = (1 << nl) if lo >= nl else (1 << (nl - 1 - lo))
        eff = effective_comm_pipeline(resolve_pipeline(pipeline), limit,
                                      site=kind)

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)
        size = mesh.shape[AMP_AXIS]
        if hi < nl:  # both local
            return K.apply_swap(own, n=nl, qb1=lo, qb2=hi)
        if lo >= nl:  # both sharded: permute device indices
            b1, b2 = lo - nl, hi - nl

            def swap_bits(i):
                x, y = (i >> b1) & 1, (i >> b2) & 1
                return i ^ (((x ^ y) << b1) | ((x ^ y) << b2))

            perm = [(i, swap_bits(i)) for i in range(size)]
            if eff == 1:
                return lax.ppermute(own, AMP_AXIS, perm)
            s = own.shape[1] // eff
            return jnp.concatenate(_pipeline_schedule(
                eff,
                lambda j: lax.ppermute(
                    lax.slice_in_dim(own, j * s, (j + 1) * s, axis=1),
                    AMP_AXIS, perm),
                lambda k, recv: recv), axis=1)

        # mixed: lo local, hi sharded
        bitpos = hi - nl
        perm = [(i, i ^ (1 << bitpos)) for i in range(size)]
        b = _rank_bit(r, hi, nl)  # device's bit of qb2
        # grouped view over the local qubit: (P, A, 2, B), axis 2 = lo's bit
        shape, axis_of = grouped_axes(nl, (lo,))
        gshape = (own.shape[0],) + shape
        ax = axis_of[lo] + 1
        t = own.reshape(gshape)
        sub0 = lax.index_in_dim(t, 0, axis=ax, keepdims=False)
        sub1 = lax.index_in_dim(t, 1, axis=ax, keepdims=False)
        send = jnp.where(b == 0, sub1, sub0)       # local bit != device bit
        keep = jnp.where(b == 0, sub0, sub1)

        def reassemble(send_s, keep_s):
            recv = lax.ppermute(send_s, AMP_AXIS, perm)  # partner's half
            # slot (local bit == b) keeps own, other slot gets recv
            new0 = jnp.where(b == 0, keep_s, recv)
            new1 = jnp.where(b == 0, recv, keep_s)
            return jnp.stack([new0, new1], axis=ax)

        if eff == 1:
            new = reassemble(send, keep)
        else:
            # slice the A (major-block) axis of the (P, A, B) halves; each
            # sub-block's exchange + reassembly is independent
            sA = send.shape[1] // eff

            def sl(x, k):
                return lax.slice_in_dim(x, k * sA, (k + 1) * sA, axis=1)

            new = jnp.concatenate(_pipeline_schedule(
                eff,
                lambda j: lax.ppermute(sl(send, j), AMP_AXIS, perm),
                lambda k, recv: jnp.stack(
                    [jnp.where(b == 0, sl(keep, k), recv),
                     jnp.where(b == 0, recv, sl(keep, k))], axis=ax)),
                axis=1)
        return new.reshape(own.shape)

    return _launch(kernel, mesh, amps, kind=kind, pipeline=eff)
