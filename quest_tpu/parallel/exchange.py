"""shard_map kernels spelling out the reference's distributed protocol in
XLA collectives.

Reference protocol (QuEST_cpu_distributed.c):
  - non-local 1q dense gate: pairwise full-chunk swap over MPI_Isend/Irecv
    (``exchangeStateVectors``, :495-533) then a rank-conditional half-update
    (``getRotAngle``, :260-308; ``statevec_compactUnitaryDistributed``).
  - non-local X class: pure chunk exchange (:1109-1152).
  - diagonal/phase ops: never communicate (phase depends only on index bits).
  - qubit relocation: odd-parity half-chunk exchange
    (``statevec_swapQubitAmps``, :1424-1459).
  - scalar reductions: MPI_Allreduce -> here ``jnp.sum`` on the sharded
    array (XLA emits the psum) or an explicit ``lax.psum`` inside shard_map.

Here each becomes a ``shard_map`` over the 1-D ``amps`` mesh axis with
``lax.ppermute`` as the exchange primitive, riding ICI instead of MPI.
All kernels are pure (amps -> amps), composable under an outer ``jax.jit``,
and handle controls split into *local* controls (index-mask inside the
chunk) and *sharded* controls (device-index predicate -- zero communication,
an improvement over shipping them into the exchange).

Layout (see .mesh): device r of D=2^d holds flat indices [r*C, (r+1)*C);
qubit q local iff q < nl = n-d; sharded qubit q is bit (q-nl) of r.

Plane contract (round 7, the sharded double-float path): the DATA-MOVEMENT
collectives (``dist_permute_bits``, ``dist_swap``'s sharded regimes, the
``dist_apply_x`` chunk permute) are plane-agnostic -- they carry the planar
(2, 2^n) pair or the PRECISION=2 double-float (4, 2^n) f32 layout natively,
which is how per-shard df kernel runs are joined by the same grouped
collectives as f32 plans. The ARITHMETIC kernels (pair exchange's blended
update, diag/parity phases) stay planar: a df state REJOINS to (2, 2^n)
f64 via the exact ``pallas_df.df_join`` before any of them runs -- the
documented hi/lo plane-pair relabeling (both conversions are exact, so the
round trip costs bandwidth, never precision).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import telemetry
from .._compat import shard_map

from ..environment import AMP_AXIS
from ..ops import apply as K
from ..ops.layout import grouped_axes
from .mesh import local_qubit_count

__all__ = ["dist_apply_matrix1", "dist_apply_x", "dist_apply_diag_phase",
           "dist_apply_parity_phase", "dist_apply_local_matrix", "dist_swap",
           "dist_permute_bits", "permute_collective_stats"]


def _specs(mesh):
    return dict(mesh=mesh, in_specs=P(None, AMP_AXIS), out_specs=P(None, AMP_AXIS))


def _launch(kernel, mesh, amps):
    """The one launch point for every collective kernel here, threaded
    through the resilience guard (site ``exchange.collective``): a direct
    call when no fault plan is installed; injected transient comm faults
    retry under the backoff policy and exhaustion fails closed with a
    typed QuESTRetryError (quest_tpu.resilience.guard.collective). With
    ``QUEST_WATCHDOG_MS`` armed the launch is deadline-bounded -- a hung
    collective raises a typed QuESTHangError instead of blocking forever
    -- EXCEPT under jit tracing: jax trace state is thread-local, so a
    traced launch must stay on the tracing thread (the compiled
    execution is covered by the engine-dispatch watchdog instead)."""
    import jax

    from ..resilience import guard
    return guard.collective(lambda: shard_map(kernel, **_specs(mesh))(amps),
                            watched=not isinstance(amps, jax.core.Tracer))


def _rank_bit(r, q, nl):
    return (r >> (q - nl)) & 1


def _ctrl_pred(r, shard_controls, shard_states, nl):
    """Device-index predicate for sharded controls (comm-free)."""
    pred = jnp.bool_(True)
    for c, s in zip(shard_controls, shard_states):
        pred = jnp.logical_and(pred, _rank_bit(r, c, nl) == s)
    return pred


def _apply_local_ctrl_mask(own, new, nl, local_controls, local_states):
    """new where all local controls match, else own (flat-iota bit mask).

    This was a grouped-view ``told.at[idx].set(new[idx])`` until round 6:
    that scatter form MISCOMPILES when two shard_map kernels compose under
    one jit on this container's jax (batched-relocation layouts surfaced
    it: eager and single-kernel jit agree with the numpy oracle, two
    chained kernels under jit corrupt exactly the control-masked half).
    The elementwise select lowers to a fused where with identical traffic
    and is immune to the scatter fusion."""
    if not local_controls:
        return new
    j = lax.iota(jnp.int32, own.shape[1])
    ok = jnp.ones(own.shape[1], bool)
    for c, s in zip(local_controls, local_states):
        ok = jnp.logical_and(ok, ((j >> c) & 1) == s)
    return jnp.where(ok[None, :], new, own)


def _split_controls(controls, states, nl):
    states = tuple(states) if states else (1,) * len(controls)
    lc = [(c, s) for c, s in zip(controls, states) if c < nl]
    sc = [(c, s) for c, s in zip(controls, states) if c >= nl]
    return ([c for c, _ in lc], [s for _, s in lc],
            [c for c, _ in sc], [s for _, s in sc])


# ---------------------------------------------------------------------------
# 1-qubit dense gate (compactUnitary / unitary class)
# ---------------------------------------------------------------------------

def dist_apply_matrix1(amps, matrix, *, n: int, target: int,
                       controls: tuple[int, ...] = (),
                       control_states: tuple[int, ...] = (),
                       conj: bool = False, mesh: Mesh):
    """U (planar (2,2,2)) on ``target``; the explicit-exchange analogue of
    ops.apply.apply_matrix for one target qubit.

    Sharded target: one ``ppermute`` full-chunk pair exchange + blended
    update -- identical traffic to the reference's exchangeStateVectors
    scheme. Local target with (possibly) sharded controls: no communication.
    """
    nl = local_qubit_count(n, mesh)
    if target >= nl:
        telemetry.inc("exchange_calls_total", kind="pair_exchange")
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)
    mr, mi = matrix[0], matrix[1]
    if conj:
        mi = -mi

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)
        if target < nl:
            new = K.apply_matrix(own, matrix, n=nl, targets=(target,),
                                 controls=tuple(lc), control_states=tuple(ls),
                                 conj=conj)
        else:
            bitpos = target - nl
            size = mesh.shape[AMP_AXIS]
            perm = [(i, i ^ (1 << bitpos)) for i in range(size)]
            pair = lax.ppermute(own, AMP_AXIS, perm)
            b = _rank_bit(r, target, nl)
            # new_amp(bit=b) = m[b,b] * own + m[b,1-b] * pair
            m_bb_r, m_bb_i = mr[b, b], mi[b, b]
            m_bo_r, m_bo_i = mr[b, 1 - b], mi[b, 1 - b]
            re = (m_bb_r * own[0] - m_bb_i * own[1]
                  + m_bo_r * pair[0] - m_bo_i * pair[1])
            im = (m_bb_r * own[1] + m_bb_i * own[0]
                  + m_bo_r * pair[1] + m_bo_i * pair[0])
            new = jnp.stack([re, im])
            new = _apply_local_ctrl_mask(own, new, nl, lc, ls)
        if sc:
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps)


def dist_apply_local_matrix(amps, matrix, *, n: int, targets: tuple[int, ...],
                            controls: tuple[int, ...] = (),
                            control_states: tuple[int, ...] = (),
                            conj: bool = False, mesh: Mesh):
    """Dense gate whose targets are ALL local: embarrassingly parallel
    shard_map around the single-chunk kernel (the reference's *Local fast
    path, QuEST_cpu_distributed.c:372-377) -- sharded controls become a
    comm-free device-index predicate instead of participating in the kernel.
    """
    nl = local_qubit_count(n, mesh)
    assert all(t < nl for t in targets)
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)

    def kernel(chunk):
        own = chunk
        new = K.apply_matrix(own, matrix, n=nl, targets=tuple(targets),
                             controls=tuple(lc), control_states=tuple(ls),
                             conj=conj)
        if sc:
            r = lax.axis_index(AMP_AXIS)
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps)


# ---------------------------------------------------------------------------
# X class (amplitude permutation)
# ---------------------------------------------------------------------------

def dist_apply_x(amps, *, n: int, targets: tuple[int, ...],
                 controls: tuple[int, ...] = (),
                 control_states: tuple[int, ...] = (),
                 mesh: Mesh):
    """Multi-controlled multi-target NOT: sharded target bits become one
    ``ppermute`` (rank-index XOR), local target bits an in-chunk flip
    (reference: ctrl-skip exchange, QuEST_cpu_distributed.c:1109-1152)."""
    nl = local_qubit_count(n, mesh)
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)
    local_t = tuple(t for t in targets if t < nl)
    shard_t = tuple(t for t in targets if t >= nl)
    if shard_t:
        telemetry.inc("exchange_calls_total", kind="x_permute")

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)
        new = own
        if shard_t:
            mask = 0
            for t in shard_t:
                mask |= 1 << (t - nl)
            size = mesh.shape[AMP_AXIS]
            perm = [(i, i ^ mask) for i in range(size)]
            new = lax.ppermute(new, AMP_AXIS, perm)
        if local_t:
            new = K.apply_x_class(new, n=nl, targets=local_t)
        new = _apply_local_ctrl_mask(own, new, nl, lc, ls)
        if sc:
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps)


# ---------------------------------------------------------------------------
# whole-layout bit permutation (one-collective reconciliation)
# ---------------------------------------------------------------------------

def _permute_decompose(n: int, source, nl: int):
    """Split the bit permutation ``new_bit[q] = old_bit[source[q]]`` into
    the three machine moves: a device-index relabel (shard->shard bits), a
    grouped all-to-all (shard<->local crossings), and a free local
    transpose. Returns (rho_src, Q_c, L_in, L_out, dest) where ``rho_src``
    maps shard position -> old shard position it takes its bit from (None
    when no relabel is needed), ``Q_c`` lists the shard positions fed from
    local bits, ``L_in[k]``/``L_out[k]`` the outgoing/incoming local bit of
    crossing ``k``, and ``dest`` the inverse permutation."""
    source = tuple(source)
    assert sorted(source) == list(range(n)), source
    dest = [0] * n
    for q, p in enumerate(source):
        dest[p] = q
    shard = range(nl, n)
    Q_c = [q for q in shard if source[q] < nl]
    P_out = [p for p in shard if dest[p] < nl]
    rho_src = None
    holds = {q: q for q in shard}  # device position -> original bit it holds
    if any(source[q] >= nl and source[q] != q for q in shard):
        # shard->shard bits displaced: one ppermute relabel puts each at its
        # home device-bit position; the outgoing (P_out) bits park at the
        # Q_c positions so the residual crossing is position-aligned
        rho_src = {q: source[q] for q in shard if source[q] >= nl}
        for q, p in zip(sorted(Q_c), sorted(P_out)):
            rho_src[q] = p
        holds = dict(rho_src)
    L_in = [source[q] for q in sorted(Q_c)]
    L_out = [dest[holds[q]] for q in sorted(Q_c)]
    return rho_src, sorted(Q_c), L_in, L_out, dest


def permute_collective_stats(n: int, source, mesh: Mesh,
                             unit_scale: float = 1.0) -> dict:
    """Trace-free cost model of :func:`dist_permute_bits`: number of
    collectives and chunk-units ((send+recv)/half-chunk pairs) it will pay.
    A relabel ppermute re-routes the full chunk (2 units, like a rank
    permute); the grouped all-to-all over m crossing bits moves
    (2^m - 1)/2^m of the chunk each way (2*(1 - 2^-m) units: m=1 is exactly
    the odd-parity half-exchange's 1 unit).

    ``unit_scale`` restates the units for wider state layouts: 1 is the
    planar f32 pair; the double-precision layouts -- planar f64, or the
    double-float 4-plane f32 state the sharded PRECISION=2 fast path
    permutes between per-shard kernel runs -- move twice the bytes per
    chunk and price at ``unit_scale=2`` (the df 2x chunk-unit accounting,
    scheduler.DistributedScheduler.apply_frame_permute)."""
    nl = local_qubit_count(n, mesh)
    rho_src, Q_c, _, _, _ = _permute_decompose(n, source, nl)
    m = len(Q_c)
    units = (2.0 if rho_src is not None else 0.0)
    units += 2.0 * (1.0 - 0.5 ** m) if m else 0.0
    return {"relabel_ppermute": rho_src is not None, "crossing_bits": m,
            "chunk_units": units * unit_scale,
            "collectives": int(rho_src is not None) + int(m > 0)}


def dist_permute_bits(amps, *, n: int, source, mesh: Mesh):
    """Apply an arbitrary bit permutation of the physical index in at most
    two collectives: ``new_bit[q] = old_bit[source[q]]``.

    This is the deferred scheduler's reconciliation primitive (round 5):
    instead of restoring the identity layout one odd-parity pair swap per
    displaced qubit (the reference's swapQubitAmps unit,
    QuEST_cpu_distributed.c:1443-1459), the whole permutation runs as

    - one ``ppermute`` device relabel IF any shard bit moves to another
      shard position (pure re-route, no local data motion), then
    - one grouped ``lax.all_to_all`` carrying ALL shard<->local crossings
      at once (each device sends (2^m-1)/2^m of its chunk for m crossing
      bits -- vs m full half-exchanges for m sequential swaps), then
    - one free in-chunk transpose for the local->local remainder.

    Plane-agnostic (round 7): ``amps`` may carry any leading plane count --
    the planar (2, 2^n) pair or the double-float (4, 2^n) layout the
    sharded PRECISION=2 fast path permutes between per-shard kernel runs.
    The permutation is pure data movement on the amplitude axis, so all
    P planes ride the same relabel/all-to-all/transpose natively.
    """
    nl = local_qubit_count(n, mesh)
    source = tuple(source)
    if all(source[q] == q for q in range(n)):
        return amps
    telemetry.inc("exchange_calls_total", kind="grouped_permute")
    rho_src, Q_c, L_in, L_out, dest = _permute_decompose(n, source, nl)
    m = len(Q_c)
    P = amps.shape[0]
    size = mesh.shape[AMP_AXIS] if mesh is not None and mesh.size > 1 else 1

    if rho_src is not None:
        def relabel(r: int) -> int:
            out = 0
            for q, p in rho_src.items():
                out |= ((r >> (p - nl)) & 1) << (q - nl)
            return out

        perm = [(r, relabel(r)) for r in range(size)]

        def relabel_kernel(chunk):
            return lax.ppermute(chunk, AMP_AXIS, perm)

        amps = shard_map(relabel_kernel, **_specs(mesh))(amps)

    groups = None
    if m:
        qbits = [q - nl for q in Q_c]
        gmask = sum(1 << b for b in qbits)
        by_base: dict[int, list[int]] = {}
        for r in range(size):
            by_base.setdefault(r & ~gmask, []).append(r)
        groups = [sorted(v) for _, v in sorted(by_base.items())]

    def kernel(chunk):
        # grouped view: axis 0 = the P planes (re/im, or the df 4-plane
        # stack), then bits nl-1 .. 0 (bit b at axis 1 + (nl-1-b))
        t = chunk.reshape((P,) + (2,) * nl)

        def ax(b):
            return 1 + (nl - 1 - b)

        if m:
            front = [ax(b) for b in reversed(L_in)]
            fset = set(front)
            rest = [a for a in range(1, nl + 1) if a not in fset]
            t = t.transpose(front + [0] + rest)
            t = t.reshape((1 << m, P) + (2,) * len(rest))
            # piece j (chunk bits at L_in spell j) -> group member whose
            # device bits at Q_c spell j; received concat index j' = the
            # sender's Q_c device bits = the incoming values for L_out
            t = lax.all_to_all(t, AMP_AXIS, 0, 0, axis_index_groups=groups)
            t = t.reshape((2,) * m + (P,) + (2,) * len(rest))
            src_axis = {}
            for k in range(m):
                src_axis[L_out[k]] = m - 1 - k
            rest_bits = [nl - 1 - (a - 1) for a in rest]
            for i, b in enumerate(rest_bits):
                src_axis[dest[b]] = m + 1 + i
            perm_axes = [m] + [src_axis[u] for u in range(nl - 1, -1, -1)]
            t = t.transpose(perm_axes)
        else:
            # no crossings: only the local->local remainder moves
            src_axis = {dest[b]: ax(b) for b in range(nl)}
            t = t.transpose([0] + [src_axis[u] for u in range(nl - 1, -1, -1)])
        return t.reshape(P, -1)

    if mesh is None or mesh.size == 1:
        assert m == 0 and rho_src is None
        return kernel(amps)
    return _launch(kernel, mesh, amps)

def dist_apply_diag_phase(amps, diag, *, n: int, targets: tuple[int, ...],
                          controls: tuple[int, ...] = (),
                          control_states: tuple[int, ...] = (),
                          conj: bool = False, mesh: Mesh):
    """diag (planar (2, 2^t)) applied to ``targets``; entry index bit k is
    targets[k]'s bit. Phases depend only on index bits, so sharded qubits
    contribute a per-device scalar offset into the diagonal -- no traffic at
    all (the reference's phase kernels are likewise exchange-free,
    QuEST_cpu.c:3235-3285)."""
    nl = local_qubit_count(n, mesh)
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)
    dr, di = diag[0], diag[1]
    if conj:
        di = -di

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)
        C = own.shape[1]
        j = lax.iota(jnp.int32, C)
        idx = jnp.zeros((), jnp.int32)
        for k, t in enumerate(targets):
            if t < nl:
                bit = (j >> t) & 1
            else:
                bit = _rank_bit(r, t, nl).astype(jnp.int32)
            idx = idx + (bit << k)
        fr, fi = dr[idx], di[idx]
        re = fr * own[0] - fi * own[1]
        im = fr * own[1] + fi * own[0]
        new = jnp.stack([re, im])
        new = _apply_local_ctrl_mask(own, new, nl, lc, ls)
        if sc:
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps)


def dist_apply_parity_phase(amps, theta, *, n: int, qubits: tuple[int, ...],
                            controls: tuple[int, ...] = (),
                            control_states: tuple[int, ...] = (),
                            conj: bool = False, mesh: Mesh):
    """exp(-i theta/2 Z x...x Z): comm-free; sharded qubits fold their bit
    into the device-index parity (reference mask-parity kernel
    QuEST_cpu.c:3235-3285 -- likewise exchange-free)."""
    nl = local_qubit_count(n, mesh)
    lc, ls, sc, ss = _split_controls(controls, control_states, nl)
    local_q = [q for q in qubits if q < nl]
    shard_q = [q for q in qubits if q >= nl]

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)
        C = own.shape[1]
        j = lax.iota(jnp.int32, C)
        par = jnp.zeros((), jnp.int32)
        for q in local_q:
            par = par ^ ((j >> q) & 1)
        for q in shard_q:
            par = par ^ _rank_bit(r, q, nl).astype(jnp.int32)
        sign = (1 - 2 * par).astype(own.dtype)
        th = jnp.asarray(-theta if conj else theta, dtype=own.dtype)
        fr, fi = jnp.cos(th / 2), -jnp.sin(th / 2) * sign
        re = fr * own[0] - fi * own[1]
        im = fr * own[1] + fi * own[0]
        new = jnp.stack([re, im])
        new = _apply_local_ctrl_mask(own, new, nl, lc, ls)
        if sc:
            new = jnp.where(_ctrl_pred(r, sc, ss, nl), new, own)
        return new

    return _launch(kernel, mesh, amps)


# ---------------------------------------------------------------------------
# qubit-amplitude swap (the relocation primitive)
# ---------------------------------------------------------------------------

def dist_swap(amps, *, n: int, qb1: int, qb2: int, mesh: Mesh):
    """SWAP(qb1, qb2). Three regimes, as the reference (:1424-1459):

    - both local: in-chunk axis transposition;
    - both sharded: pure device-index bit swap (one ppermute);
    - mixed: odd-parity half-chunk exchange -- each device sends the half of
      its chunk whose local bit differs from its device bit, halving traffic
      vs a full exchange.

    The sharded regimes are pure data movement and carry any leading plane
    count (planar pair or the df 4-plane layout); the both-local regime
    routes through the planar apply_swap kernel and takes (2, N) only.
    """
    nl = local_qubit_count(n, mesh)
    lo, hi = min(qb1, qb2), max(qb1, qb2)
    if hi >= nl:
        telemetry.inc("exchange_calls_total",
                      kind=("swap_rank_permute" if lo >= nl
                            else "swap_odd_parity"))

    def kernel(chunk):
        own = chunk
        r = lax.axis_index(AMP_AXIS)
        size = mesh.shape[AMP_AXIS]
        if hi < nl:  # both local
            return K.apply_swap(own, n=nl, qb1=lo, qb2=hi)
        if lo >= nl:  # both sharded: permute device indices
            b1, b2 = lo - nl, hi - nl

            def swap_bits(i):
                x, y = (i >> b1) & 1, (i >> b2) & 1
                return i ^ (((x ^ y) << b1) | ((x ^ y) << b2))

            perm = [(i, swap_bits(i)) for i in range(size)]
            return lax.ppermute(own, AMP_AXIS, perm)

        # mixed: lo local, hi sharded
        bitpos = hi - nl
        perm = [(i, i ^ (1 << bitpos)) for i in range(size)]
        b = _rank_bit(r, hi, nl)  # device's bit of qb2
        # grouped view over the local qubit: (P, A, 2, B), axis 2 = lo's bit
        shape, axis_of = grouped_axes(nl, (lo,))
        gshape = (own.shape[0],) + shape
        ax = axis_of[lo] + 1
        t = own.reshape(gshape)
        sub0 = lax.index_in_dim(t, 0, axis=ax, keepdims=False)
        sub1 = lax.index_in_dim(t, 1, axis=ax, keepdims=False)
        send = jnp.where(b == 0, sub1, sub0)       # local bit != device bit
        recv = lax.ppermute(send, AMP_AXIS, perm)  # partner's odd-parity half
        keep = jnp.where(b == 0, sub0, sub1)
        # reassemble: slot (local bit == b) keeps own, other slot gets recv
        new0 = jnp.where(b == 0, keep, recv)
        new1 = jnp.where(b == 0, recv, keep)
        new = jnp.stack([new0, new1], axis=ax)
        return new.reshape(own.shape)

    return _launch(kernel, mesh, amps)
