"""Gate-dispatch scheduler for explicit distributed execution.

Reference dispatch policy (QuEST_cpu_distributed.c):
  - 1q dense gate, target non-local -> full-chunk pair exchange (:870-905);
  - n-target dense gate with non-local targets -> relocate each to a free
    local qubit via swapQubitAmps, apply locally, swap back (:1526-1568);
  - X class -> chunk exchange with ctrl-skip (:1109-1152);
  - diagonal/phase -> never communicate.

The scheduler reproduces that policy over the :mod:`.exchange` shard_map
kernels and improves on it where TPU semantics allow:
  - sharded *controls* never travel: they become device-index predicates
    (the reference ships control bits through the exchange);
  - everything composes under one ``jax.jit``, so XLA overlaps the
    ``ppermute`` traffic of one gate with the local compute of the next --
    the reference synchronises on MPI_Waitall per gate.

Usage: ``with explicit_mesh(mesh): <apply gates / run circuits>`` -- the L5
API helpers in gates.py route through :func:`active` while the context is
live. Works eagerly and on Circuit tapes (enter the context before
``Circuit.run`` / inside the traced step).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

from jax.sharding import Mesh

from .. import telemetry
from . import exchange as X
from .mesh import local_qubit_count

_STATE = threading.local()


def _cycle_swaps(occ, pos, n: int) -> list:
    """The (a, b) position-swap sequence that restores the identity layout
    (at most one swap per displaced qubit, cycle restoration). The single
    source of the swap-chain order -- shared by the A/B cost simulation
    and the fallback execution path."""
    occ, pos = list(occ), list(pos)
    out = []
    for a in range(n):
        while occ[a] != a:
            b = pos[a]
            out.append((a, b))
            la, lb = occ[a], occ[b]
            occ[a], occ[b] = lb, la
            pos[la], pos[lb] = b, a
    return out


def _cycle_swaps_hier(occ, weight, n: int) -> list:
    """Topology-aware variant of :func:`_cycle_swaps`: each permutation
    cycle is decomposed as a PATH closed at its heaviest-link position d
    (ties: highest position), so d rides exactly ONE swap. The pivot
    chain above fixes the lowest position first, which funnels every
    remaining cycle element through later swaps -- a high (DCN) position
    inside a k-cycle can be touched up to k-1 times. Here the walk
    e0 = occ[d], e_{i+1} = occ[e_i] ends at e_{k-1} = d and the swaps
    (e_i, e_{i+1}) are emitted for i = k-2 .. 0: interiors ride two
    swaps, the endpoints (d and its successor) one -- the "each
    DCN-crossing bit moves at most once per reconcile" invariant
    check_schedule's QT108 verifies."""
    occ = list(occ)
    out = []
    seen = [False] * n
    for start in range(n):
        if seen[start] or occ[start] == start:
            seen[start] = True
            continue
        cyc = []
        p = start
        while not seen[p]:
            seen[p] = True
            cyc.append(p)
            p = occ[p]
        d = max(cyc, key=lambda q: (weight(q), q))
        e = [occ[d]]
        while e[-1] != d:
            e.append(occ[e[-1]])
        for i in range(len(e) - 2, -1, -1):
            out.append((e[i], e[i + 1]))
    return out


def plane_unit_scale(amps) -> float:
    """Chunk-unit scale of a state layout relative to the planar f32 pair
    (8 bytes/amplitude): 1.0 for planar f32, 2.0 for BOTH double-precision
    layouts -- planar f64 and the double-float 4-plane f32 state the
    sharded PRECISION=2 fast path ships between per-shard kernel runs.
    Only the pallas frame-transpose accounting uses this (the df 2x
    chunk-unit rule); the gate-dispatch stats keep their historical
    register-chunk units, with dtype width entering via
    ``comm_volume(bytes_per_amp=...)`` as before."""
    import numpy as np

    return (amps.shape[0] * np.dtype(amps.dtype).itemsize) / 8.0


def _swap_price(a: int, b: int, nl: int) -> float:
    """Chunk-units of one dist_swap, same prices as apply_swap: free when
    both positions are local, 1 (odd-parity half-exchange) when mixed,
    2 (full-chunk rank permute) when both are sharded."""
    if max(a, b) < nl:
        return 0.0
    return 2.0 if min(a, b) >= nl else 1.0


#: default relative price of a DCN chunk-unit against an ICI one for the
#: HIERARCHICAL scheduling decisions (hierarchical=True). The published
#: inter-slice figures put DCN an order of magnitude below ICI per link;
#: 4x is the conservative planning ratio -- any weight > 2 already flips
#: every decision this PR adds (relay staging beats a direct DCN rank
#: permute when 2 + w < 2w). The weight NEVER enters the chunk-unit
#: accounting itself: stats/telemetry stay in flat chunk-units per link,
#: so flat and hierarchical plans are compared in one currency.
DCN_COST_WEIGHT = 4.0


@dataclass
class DistributedScheduler:
    """Gate dispatcher bound to a mesh; collects plan stats (number of pair
    exchanges / relocations / comm-free ops) at trace time.

    Two dispatch modes:

    - **immediate** (default, the reference's policy): every relocation is
      undone right after its gate (QuEST_cpu_distributed.c:1526-1568), so
      the register is always in the identity layout.
    - **deferred** (``begin_defer``, active during ``Circuit`` replays):
      relocation swap-backs are elided. The scheduler keeps a
      logical->physical qubit permutation; gate coordinates are remapped
      through it, a sharded qubit relocated once stays local for every
      subsequent gate (evicting the least-recently-used local qubit), and
      uncontrolled SWAP gates become pure permutation updates -- zero
      communication AND zero compute. The layout is reconciled back to
      identity only at barriers (non-gate tape entries) and at replay end.
      This is where the build stops mirroring the reference's
      one-relocation-per-gate scheme and beats it (SURVEY.md section 5:
      "gate scheduling / qubit-index remapping to keep hot qubits local").
    """

    mesh: Mesh
    #: pod-slice count for ICI-vs-DCN traffic classification (1 = all ICI)
    num_slices: int = 1
    #: True makes the PLANNING topology-hierarchical (round 15): reconcile
    #: swap chains path-decompose with DCN positions as endpoints (each
    #: DCN bit rides at most one swap per reconciliation), a both-sharded
    #: ICI<->DCN swap stages through an intra-slice local relay when the
    #: two-tier model prices it cheaper, relocation batching orders DCN
    #: sources onto the idlest eviction slots, and every chain-vs-
    #: collective decision weighs DCN chunk-units by ``dcn_cost_weight``.
    #: False (the default) is the flat single-tier scheduler, bit-
    #: identical to the pre-round-15 behaviour -- the A/B baseline.
    hierarchical: bool = False
    #: relative DCN-vs-ICI chunk-unit price for hierarchical decisions
    #: (never enters the accounting; see DCN_COST_WEIGHT)
    dcn_cost_weight: float = DCN_COST_WEIGHT
    #: False forces the reference's immediate policy (begin_defer no-ops)
    allow_defer: bool = True
    #: False reverts reconciliation to the round-3/4 per-cycle swap chain
    #: (for A/B plan stats; the collective path is the production one)
    collective_reconcile: bool = True
    #: False reverts deferred-mode relocations to the round-5 one-swap-at-
    #: a-time policy (for A/B plan stats; the batched grouped permute is
    #: the production path -- see :meth:`_relocate`)
    batch_relocations: bool = True
    #: comm-pipeline depth every collective launch runs at (None = the
    #: QUEST_COMM_PIPELINE env default; 1 = monolithic). Pipelining only
    #: re-times traffic -- the chunk-unit pricing above is identical at
    #: every depth (check_schedule proves it from the journal stamp) --
    #: so this knob never changes any scheduling decision, only how each
    #: launched collective is sliced. Deliberately distinct from
    #: ``num_slices``: that splits the MESH into ICI/DCN slices, this
    #: splits each device's CHUNK into overlappable sub-transfers.
    comm_pipeline: int | None = None
    #: per-link-class pipeline override (round 15): collectives that touch
    #: a DCN shard bit launch at this depth instead of ``comm_pipeline``
    #: (None = the QUEST_COMM_PIPELINE_DCN env, else inherit the base
    #: depth). Like the base knob it only re-times traffic -- pricing and
    #: every scheduling decision are depth-invariant -- and it is inert at
    #: num_slices=1 (no DCN bits exist to classify).
    comm_pipeline_dcn: int | None = None
    stats: dict = field(default_factory=lambda: {
        "pair_exchanges": 0, "relocation_swaps": 0, "rank_permutes": 0,
        "comm_free": 0, "local": 0, "channel_superops": 0,
        "virtual_swaps": 0, "reconcile_swaps": 0,
        "reconcile_collectives": 0, "reconcile_chunks": 0.0,
        "reconcile_swap_equiv_chunks": 0.0,
        "relocation_batches": 0, "relocation_batch_qubits": 0,
        "relocation_prefetched": 0, "relocation_batch_chunks": 0.0,
        "relocation_batch_swap_equiv_chunks": 0.0,
        "frame_transpose_collectives": 0,
        "frame_transpose_chunks": 0.0,
        "frame_transpose_planar_chunks": 0.0,
        "staged_relays": 0,
        "ici_chunks": 0.0, "dcn_chunks": 0.0,
        # two-tier model detail: chunk-units per "kind/link" pair, the
        # exact per-cell figures the telemetry series
        # comm_chunk_units_total{kind,link} must sum to (and
        # check_schedule re-derives from the journal)
        "chunks_by_kind_link": {}})
    #: optional decision journal for the static plan verifier
    #: (analysis.plancheck.check_schedule): when set to a list, every
    #: communication decision appends one record --
    #:   ("pair_exchange", n, target) | ("rank_permute", n, qubit)
    #:   | ("dist_swap", n, a, b, layout_tracked)
    #:   | ("virtual_swap", p1, p2) | ("reconcile_swap", n, a, b)
    #:   | ("permute", n, source, unit_scale, kind)
    #:   | ("reconcile_done", n)
    #:   | ("segment", lo)   -- zero-cost marker: a sliced segment-program
    #:     replay opened a defer span at tape cursor ``lo`` (round 13)
    #:   | ("staged_relay", n, a, b, r)  -- zero-cost marker: the three
    #:     reconcile_swap records that follow relay the a<->b exchange
    #:     through local position r (round 15, hierarchical only)
    #: plus one leading ("comm_pipeline", depth) stamp recording the
    #: resolved pipeline depth the plan's collectives launch at (priced at
    #: ZERO chunk-units by check_schedule: the proof that pipelining
    #: leaves the model unchanged). A multi-slice plan (num_slices > 1)
    #: stamps ("comm_pipeline", depth, dcn_depth) instead -- the per-link-
    #: class depths; single-slice journals keep the historical 2-tuple.
    #: -- enough to re-price the whole plan and replay the layout
    #: independently. None (the default) records nothing.
    journal: list | None = None

    def _note(self, *rec) -> None:
        if self.journal is not None:
            if not self.journal:
                # stamped lazily at the first record: plan_circuit attaches
                # the journal list after construction
                base = X.resolve_pipeline(self.comm_pipeline)
                if self.num_slices > 1:
                    self.journal.append(
                        ("comm_pipeline", base,
                         X.resolve_pipeline_dcn(self.comm_pipeline_dcn,
                                                self.comm_pipeline)))
                else:
                    self.journal.append(("comm_pipeline", base))
            self.journal.append(rec)

    def _count_comm(self, n: int, qubit: int, chunks: float,
                    kind: str = "other") -> None:
        """Attribute ``chunks`` of traffic to the interconnect the comm op
        on sharded physical ``qubit`` rides (slice-major device order: low
        shard bits = ICI chip axis, top log2(num_slices) bits = DCN), and
        flight-record the same units per collective ``kind`` -- the
        telemetry series ``comm_chunk_units_total{kind,link}`` sums to
        exactly :func:`comm_chunks` of this plan's stats (asserted by
        tests/test_telemetry.py against the plan_circuit model)."""
        from .mesh import shard_bit_link

        link = shard_bit_link(n, self.mesh, self.num_slices, qubit)
        if link is not None:
            self.stats[f"{link}_chunks"] += chunks
        cell = f"{kind}/{link or 'local'}"
        by = self.stats["chunks_by_kind_link"]
        by[cell] = by.get(cell, 0.0) + chunks
        telemetry.inc("comm_chunk_units_total", chunks, kind=kind,
                      link=link or "local")

    def _link_weight(self, n: int, qubit: int) -> float:
        """Decision weight of one chunk-unit attributed to physical
        ``qubit``: ``dcn_cost_weight`` on the DCN bits, 1 on ICI, 0 local.
        Only the hierarchical planner consults it."""
        from .mesh import shard_bit_link

        link = shard_bit_link(n, self.mesh, self.num_slices, qubit)
        if link is None:
            return 0.0
        return self.dcn_cost_weight if link == "dcn" else 1.0

    def _is_dcn(self, n: int, qubit: int) -> bool:
        from .mesh import shard_bit_link

        return shard_bit_link(n, self.mesh, self.num_slices,
                              qubit) == "dcn"

    def _pipeline_for(self, n: int, positions, pipeline=None,
                      pipeline_dcn=None):
        """Launch depth for a collective touching the sharded physical
        ``positions``: the per-link-class resolution (round 15) hands a
        DCN-riding collective the DCN depth (explicit argument, then the
        scheduler's ``comm_pipeline_dcn``, then QUEST_COMM_PIPELINE_DCN,
        then fall back to the base); everything else -- and every launch
        on a single-slice mesh -- keeps the base depth unchanged."""
        base = pipeline if pipeline is not None else self.comm_pipeline
        if self.num_slices <= 1:
            return base
        if not any(self._is_dcn(n, p) for p in positions):
            return base
        return X.resolve_pipeline_dcn(
            pipeline_dcn if pipeline_dcn is not None
            else self.comm_pipeline_dcn, base)

    def _weighted_permute_units(self, n: int, nl: int, source,
                                cstats) -> float:
        """The grouped-permute collective's cost under the two-tier model:
        the same even-split attribution as the accounting, each bit's
        share scaled by its link weight."""
        total = 0.0
        cross = [q for q in range(nl, n) if source[q] < nl]
        if cross:
            share = 2.0 * (1.0 - 0.5 ** len(cross)) / len(cross)
            total += sum(share * self._link_weight(n, q) for q in cross)
        if cstats["relabel_ppermute"]:
            moved = [q for q in range(nl, n)
                     if source[q] >= nl and source[q] != q]
            total += sum(2.0 * self._link_weight(n, q) / len(moved)
                         for q in moved)
        return total

    def _chain_plan(self, swaps, n: int, nl: int):
        """Execution plan for a hierarchical reconcile swap chain:
        ('swap', a, b) steps, with a both-sharded ICI<->DCN swap replaced
        by a ('relay', d, o, r) staging triple -- d ALWAYS the DCN
        position, o the ICI one (apply_swap's immediate-mode convention;
        _cycle_swaps_hier emits the DCN endpoint in either tuple slot) --
        executed as swap(o,r); swap(d,r); swap(o,r) through local r,
        which composes to swap(d,o), leaves r untouched, and rides the
        DCN link ONCE at 1 unit instead of the direct rank permute's 2
        -- whenever the two-tier model prices 2 + w below 2w. Returns
        (plan, flat_units, weighted_units)."""
        plan, units, weighted = [], 0.0, 0.0
        for a, b in swaps:
            price = _swap_price(a, b, nl)
            wmax = self._link_weight(n, max(a, b))
            if (price == 2.0 and nl > 0
                    and self._is_dcn(n, max(a, b))
                    and not self._is_dcn(n, min(a, b))
                    and 2.0 + self.dcn_cost_weight < 2.0 * wmax):
                plan.append(("relay", max(a, b), min(a, b), 0))
                units += 3.0
                weighted += 2.0 + self.dcn_cost_weight
            else:
                plan.append(("swap", a, b))
                units += price
                weighted += price * wmax
        return plan, units, weighted

    def __post_init__(self):
        self.deferring = False
        self._pos = None        # logical qubit -> physical position
        self._occ = None        # physical position -> logical qubit
        self._last_use = None   # logical qubit -> last-touch counter
        self._clock = 0
        self._future = None     # per-tape-entry access sets (Belady)
        self._future_dense = None  # aligned relocation-forcing subsets
        self._cursor = 0

    def comm_volume(self, n: int, bytes_per_amp: int = 8) -> dict:
        """Trace-time communication-volume estimate for the collected plan,
        per device, mirroring the reference's comm cost model (BASELINE.md:
        a non-local 1q gate exchanges a full chunk send+recv per rank,
        QuEST_cpu_distributed.c:495-533; a relocation/odd-parity swap moves
        half a chunk each way, :1443-1459; an X-class rank permute
        re-routes the full chunk; a virtual swap costs nothing;
        reconciliation contributes its measured ``reconcile_chunks`` --
        per-swap prices for the swap chain, 2*(1-2^-m) for the grouped
        all-to-all over m crossing bits plus 2 for a relabel ppermute).
        ``bytes_per_amp`` = 8 for planar f32 (re+im), 16 for f64."""
        chunk = (1 << n) // self.mesh.size
        amps_moved = chunk * comm_chunks(self.stats)
        return {
            "amps_per_device": amps_moved,
            "bytes_per_device": amps_moved * bytes_per_amp,
            "chunk_amps": chunk,
        }

    # -- deferred-permutation machinery --------------------------------------

    def begin_defer(self, segment: int | None = None) -> bool:
        """Enter deferred mode; returns False if already deferring or
        deferral is disabled (the caller then must not end it).

        ``segment`` labels this defer span with its tape-slice origin
        (round 13: sliced segment-program replays pass their ``lo``
        cursor) -- journaled as a zero-cost ``("segment", lo)`` marker so
        check_schedule re-prices a segmented plan per span. None (whole-
        tape replays, plan_circuit) records nothing, keeping pre-round-13
        journals byte-identical."""
        if self.deferring or not self.allow_defer:
            return False
        self.deferring = True
        if segment is not None:
            self._note("segment", int(segment))
        return True

    def end_defer(self, amps, n: int):
        """Reconcile the layout to identity and leave deferred mode."""
        amps = self.reconcile(amps, n)
        self.deferring = False
        return amps

    def abort_defer(self) -> None:
        """Drop deferred state WITHOUT reconciling -- for exception paths
        where the amps are being discarded anyway. Leaving a stale layout
        active would silently corrupt the next replay."""
        self.deferring = False
        self._pos = self._occ = self._last_use = None
        self._future = None
        self._future_dense = None
        self._cursor = 0

    def set_lookahead(self, accesses, dense=None) -> None:
        """Future qubit-access sequence for Belady eviction: one entry per
        tape item -- a frozenset of the logical qubits it touches, or None
        for a barrier (layout reconciles there, so nothing beyond a barrier
        matters for eviction). Circuit.as_fn installs this.

        ``dense`` (aligned with ``accesses``) lists per entry the subset of
        qubits used in a RELOCATION-FORCING role -- non-diagonal matrix /
        X-class targets, channel rows+columns -- or None at barriers.
        Only the relocation batcher reads it (:meth:`_pending_shard_uses`):
        controls, parity members and diagonal targets are comm-free on
        sharded qubits, so prefetching them would relocate (and evict) for
        nothing. Without ``dense`` the batcher never prefetches."""
        self._future = list(accesses) if accesses is not None else None
        self._future_dense = list(dense) if dense is not None else None
        self._cursor = 0

    def advance(self, index: int) -> None:
        self._cursor = index

    def _next_use(self, lq: int) -> int:
        """Tape index of the next access to logical qubit ``lq`` (cursor
        inclusive -- the current entry's own qubits must never be evicted);
        a large sentinel if unused before the next barrier."""
        for j in range(self._cursor, len(self._future)):
            s = self._future[j]
            if s is None:
                break  # reconciliation point: later uses are irrelevant
            if lq in s:
                return j
        return 1 << 30

    def _ensure_perm(self, n: int) -> None:
        if self._pos is None or len(self._pos) != n:
            self._pos = list(range(n))
            self._occ = list(range(n))
            self._last_use = [0] * n

    def _map(self, n, qs) -> tuple:
        """Logical -> physical coordinates under the current layout."""
        if self._pos is None:
            return tuple(qs)
        self._ensure_perm(n)
        return tuple(self._pos[q] for q in qs)

    def _touch(self, qs) -> None:
        self._clock += 1
        if self._last_use is not None:
            for q in qs:
                self._last_use[q] = self._clock

    def _swap_positions(self, a: int, b: int) -> None:
        """Record a PHYSICAL swap of positions a and b in the layout."""
        la, lb = self._occ[a], self._occ[b]
        self._occ[a], self._occ[b] = lb, la
        self._pos[la], self._pos[lb] = b, a

    def reconcile(self, amps, n: int):
        """Physically restore the identity layout (logical q at position q).

        Production path (round 5): the whole displacement runs as ONE
        grouped all-to-all (plus a ppermute relabel only when shard bits
        moved among themselves) -- :func:`..exchange.dist_permute_bits`.
        The 34q bench plan's reconciliation drops from 7 sequential
        odd-parity swaps (7 chunk-units; the reference's swapQubitAmps
        unit, QuEST_cpu_distributed.c:1443-1459) to one collective at
        <=2 chunk-units. The cheaper policy is chosen per reconciliation
        (the collective wins on wide displacements: m crossings cost
        2*(1-2^-m) < m; a shard->shard relabel pays a full 2-unit
        re-route, so relabel-dominated small displacements keep the swap
        chain). ``collective_reconcile=False`` forces the swap chain for
        A/B plan stats. Both paths account their traffic in
        ``reconcile_chunks`` with the same per-swap prices as
        :meth:`apply_swap` (1 unit mixed, 2 units both-sharded).

        Hierarchical mode (round 15): the chain comes from
        :func:`_cycle_swaps_hier` (every DCN position an endpoint of its
        cycle's path decomposition -- at most one DCN swap per bit per
        reconciliation), a both-sharded ICI<->DCN swap stages through a
        local relay when 2 + w < 2w under the ``dcn_cost_weight`` w, and
        the chain-vs-collective choice compares the TWO-TIER weighted
        prices instead of the flat units. The accounting itself stays in
        flat chunk-units either way."""
        if self._pos is None:
            return amps
        self._ensure_perm(n)
        nl = local_qubit_count(n, self.mesh)
        swaps = _cycle_swaps(self._occ, self._pos, n)
        if not swaps:
            return amps
        # A/B bookkeeping: what the swap chain would pay, recorded under
        # both policies
        swap_units = sum(_swap_price(a, b, nl) for a, b in swaps)
        local_swaps = sum(1 for a, b in swaps if max(a, b) < nl)
        self.stats["reconcile_swap_equiv_chunks"] += swap_units
        source = tuple(self._pos)  # new bit q <- old bit pos[q]
        cstats = X.permute_collective_stats(n, source, self.mesh)
        if self.hierarchical:
            plan, _chain_units, chain_w = self._chain_plan(
                _cycle_swaps_hier(self._occ,
                                  lambda q: self._link_weight(n, q), n),
                n, nl)
            use_chain = not self.collective_reconcile or \
                chain_w < self._weighted_permute_units(n, nl, source,
                                                       cstats)
        else:
            plan = [("swap", a, b) for a, b in swaps]
            use_chain = not self.collective_reconcile or \
                swap_units < cstats["chunk_units"]
        if use_chain:
            for step in plan:
                if step[0] == "relay":
                    _, a, b, r = step
                    # the DCN position must ride ONLY the middle swap --
                    # the outer pair touches the relay twice, so putting
                    # the DCN bit there pays the slow link twice and
                    # breaks the QT108 once-per-reconcile invariant
                    h = a if self._is_dcn(n, a) else b
                    o = b if h == a else a
                    self.stats["staged_relays"] += 1
                    self._note("staged_relay", n, a, b, r)
                    chain = ((o, r), (h, r), (o, r))
                else:
                    chain = (step[1:],)
                for x, y in chain:
                    price = _swap_price(x, y, nl)
                    if price:
                        self.stats["reconcile_swaps"] += 1
                        self.stats["reconcile_chunks"] += price
                        self._count_comm(n, max(x, y), price,
                                         kind="reconciliation")
                    else:
                        self.stats["local"] += 1
                    self._note("reconcile_swap", n, x, y)
                    amps = X.dist_swap(
                        amps, n=n, qb1=x, qb2=y, mesh=self.mesh,
                        pipeline=self._pipeline_for(n, (x, y)))
                    self._swap_positions(x, y)
            self._note("reconcile_done", n)
            return amps
        self.stats["reconcile_collectives"] += cstats["collectives"]
        self.stats["reconcile_chunks"] += cstats["chunk_units"]
        # the local->local remainder rides the collective's final in-chunk
        # transpose; keep the op count comparable with the swap chain's
        self.stats["local"] += local_swaps
        # link attribution: split the collective's volume evenly over the
        # participating shard bits (crossing bits for the all-to-all; the
        # relabeled bits for the ppermute)
        cross = [q for q in range(nl, n) if source[q] < nl]
        if cross:
            share = 2.0 * (1.0 - 0.5 ** len(cross)) / len(cross)
            for q in cross:
                self._count_comm(n, q, share, kind="reconciliation")
        if cstats["relabel_ppermute"]:
            moved = [q for q in range(nl, n)
                     if source[q] >= nl and source[q] != q]
            for q in moved:
                self._count_comm(n, q, 2.0 / len(moved),
                                 kind="reconciliation")
        self._note("permute", n, source, 1.0, "reconciliation")
        touched = [q for q in range(nl, n) if source[q] != q]
        amps = X.dist_permute_bits(amps, n=n, source=source, mesh=self.mesh,
                                   pipeline=self._pipeline_for(n, touched))
        self._pos = list(range(n))
        self._occ = list(range(n))
        self._note("reconcile_done", n)
        return amps

    def apply_frame_permute(self, amps, *, n, lo1, lo2, k, pipeline=None,
                            pipeline_dcn=None):
        """One pallas frame transpose -- the bit-block swap
        [lo1, lo1+k) <-> [lo2, lo2+k) -- executed as the COUNTED grouped
        permute collective (exchange.dist_permute_bits) instead of an
        uncounted GSPMD transpose. This is how per-shard PallasRuns are
        joined under the explicit scheduler (round 7, sharded df): the
        state may be the planar pair or the double-float 4-plane layout,
        and the chunk-unit accounting prices it by plane_unit_scale --
        planar f32 = 1x, planar f64 / df 4-plane = 2x (the df chunk-unit
        2x rule; `frame_transpose_planar_chunks` keeps the unscaled A/B
        figure). Telemetry series kind="frame_transpose" sums exactly to
        the model, as every other counted collective (tested)."""
        source = list(range(n))
        for j in range(k):
            source[lo1 + j], source[lo2 + j] = source[lo2 + j], source[lo1 + j]
        source = tuple(source)
        scale = plane_unit_scale(amps)
        cstats = X.permute_collective_stats(n, source, self.mesh)
        nl = local_qubit_count(n, self.mesh)
        self.stats["frame_transpose_collectives"] += cstats["collectives"]
        self.stats["frame_transpose_chunks"] += cstats["chunk_units"] * scale
        self.stats["frame_transpose_planar_chunks"] += cstats["chunk_units"]
        # link attribution mirrors reconcile(): the all-to-all's volume is
        # split evenly over the crossing shard bits, the relabel ppermute's
        # over the relabeled bits
        cross = [q for q in range(nl, n) if source[q] < nl]
        if cross:
            share = 2.0 * (1.0 - 0.5 ** len(cross)) * scale / len(cross)
            for q in cross:
                self._count_comm(n, q, share, kind="frame_transpose")
        if cstats["relabel_ppermute"]:
            moved = [q for q in range(nl, n)
                     if source[q] >= nl and source[q] != q]
            for q in moved:
                self._count_comm(n, q, 2.0 * scale / len(moved),
                                 kind="frame_transpose")
        self._note("permute", n, source, scale, "frame_transpose")
        touched = [q for q in range(nl, n) if source[q] != q]
        return X.dist_permute_bits(
            amps, n=n, source=source, mesh=self.mesh,
            pipeline=self._pipeline_for(n, touched, pipeline,
                                        pipeline_dcn))

    def _pending_shard_uses(self, n, nl, exclude, capacity) -> list:
        """Sharded physical positions that tape entries between the cursor
        and the next reconciliation barrier will use in a RELOCATION-
        FORCING role (dense targets -- see set_lookahead's ``dense``), in
        first-use order (at most ``capacity``, skipping ``exclude``).
        These are exactly the relocation swaps that would otherwise run
        serially between two PallasRuns -- the batch candidates for
        :meth:`_relocate`. Prefetching from the full access sets instead
        measurably LOSES (34q plan probe): diagonal/control uses are
        comm-free on sharded qubits, and relocating them evicts local
        qubits into fresh relocations of their own."""
        dense = getattr(self, "_future_dense", None)
        if capacity <= 0 or dense is None or \
                getattr(self, "_future", None) is None:
            return []
        self._ensure_perm(n)
        out = []
        seen = set(exclude)
        for j in range(self._cursor, min(len(self._future), len(dense))):
            if self._future[j] is None:
                break  # reconciliation point: later uses are irrelevant
            s = dense[j]
            if not s:
                continue
            for lq in sorted(s):
                p = self._pos[lq]
                if p >= nl and p not in seen:
                    seen.add(p)
                    out.append((p, j))
                    if len(out) >= capacity:
                        return out
        return out

    def _next_dense_use(self, lq: int) -> int:
        """Tape index of the next RELOCATION-FORCING access to logical
        ``lq`` (a large sentinel if none before the next barrier) -- the
        Belady counterpart of :meth:`_next_use` over the dense sets."""
        dense = getattr(self, "_future_dense", None)
        if dense is None:
            return 1 << 30
        for j in range(self._cursor, min(len(self._future), len(dense))):
            if self._future[j] is None:
                break
            s = dense[j]
            if s and lq in s:
                return j
        return 1 << 30

    def _relocate(self, amps, n, nl, phys_ts, support_phys,
                  on_fail: str = "raise"):
        """Swap each sharded physical position in ``phys_ts`` with a free
        local slot (deferred mode: LRU-occupant slot, no swap-back --
        callers read the new positions from the layout afterwards).
        Returns (amps, {old_phys: new_phys}).

        Production path (round 6): in deferred mode the pending relocations
        are BATCHED -- the positions this gate forces plus every sharded
        position the lookahead sees used before the next barrier -- and,
        when the batch beats the per-swap price, the whole batch runs as
        ONE :func:`..exchange.dist_permute_bits` grouped all-to-all
        (m crossings cost 2*(1-2^-m) < m; the reference pays one odd-parity
        exchange per swap, QuEST_cpu_distributed.c:1443-1459). Singleton
        batches keep the cheap pair-swap path (the costs tie at m=1), and
        ``batch_relocations=False`` forces it for A/B plan stats."""
        shard = [p for p in phys_ts if p >= nl]
        if not shard:
            return amps, {}
        free = [p for p in range(nl) if p not in support_phys]
        if len(free) < len(shard):
            if on_fail == "none":
                # the caller has a relocation-free route (pair exchange /
                # rank permute); never error where immediate mode wouldn't
                return amps, None
            # surface through the overridable error hook, as the reference's
            # matrix-fits-in-node check (validateMultiQubitMatrixFitsInNode,
            # QuEST_validation.c:522-524, E_CANNOT_FIT_MULTI_QUBIT_MATRIX)
            from .. import validation as V
            V.validate_matrix_fits_in_node(len(free), len(shard),
                                           "applyMatrix")
        if self.deferring:
            self._ensure_perm(n)
            if getattr(self, "_future", None) is not None:
                # Belady: evict the occupant whose next use is farthest
                # (or never, before the next reconciliation barrier)
                free.sort(key=lambda p: -self._next_use(self._occ[p]))
            else:
                # no lookahead (eager deferral): least-recently-used,
                # preferring high slots on ties (low qubits run hot)
                free.sort(key=lambda p: (self._last_use[self._occ[p]], -p))
            if self.hierarchical:
                # two-tier slot assignment (round 15): DCN sources first,
                # and each one claims the free slot whose occupant has the
                # FARTHEST next dense use over the whole lookahead -- the
                # qubit parked on the DCN bit is the one that keeps it
                # quiet longest (the flat sort ranks by any-next-use,
                # which diagonal-only traffic inflates for nothing)
                shard = sorted(shard,
                               key=lambda p: -self._link_weight(n, p))
                dcn_src = [p for p in shard if self._is_dcn(n, p)]
                if dcn_src:
                    idle, pool = [], list(free)
                    for s in dcn_src:
                        # on a next-dense tie (typically the 1<<30 "never"
                        # sentinel) send the bit HOME: parking logical s at
                        # physical s means the closing reconcile finds the
                        # DCN bit already in place and never crosses DCN
                        best = max(pool, key=lambda p: (
                            self._next_dense_use(self._occ[p]),
                            self._occ[p] == s))
                        pool.remove(best)
                        idle.append(best)
                    free = idle + pool
        batch = list(shard)
        slots = free[:len(shard)]
        if self.deferring and self.batch_relocations:
            # widen the batch with the relocations pending before the next
            # barrier: the marginal crossing costs 2^-m of a chunk, far
            # below the 1 unit each would pay as its own dist_swap later.
            # Prefetch slots re-sort by the occupant's next DENSE use
            # (farthest first: evicting a never-dense-used occupant is
            # free), and admission is Belady-sound -- a candidate joins
            # only if its first dense use comes BEFORE the next dense use
            # of the occupant it evicts; past that point the prefetch
            # trades one pending relocation for a fresh one (measured to
            # LOSE on the 34q plan when admission was unconditional).
            # Candidates arrive soonest-use-first and slots most-idle-
            # first, so the first failed admission ends the matching.
            tail = free[len(shard):]
            tail.sort(key=lambda p: -self._next_dense_use(self._occ[p]))
            cands = self._pending_shard_uses(
                n, nl, set(batch) | set(support_phys), len(tail))
            if self.hierarchical:
                # DCN-avoiding admission (round 15): a DCN position is
                # NEVER prefetched -- an early pull shortens its
                # occupant's residency and adds a whole extra DCN epoch
                # over the defer window (each relocation of the DCN bit
                # parks a fresh dense-usable qubit there; moving it as
                # late as possible minimises how many cycle through).
                # When its dense use finally arrives the relocation is
                # FORCED and rides that gate's batch at the grouped
                # all-to-all's even-split share. The surviving (ICI)
                # candidates keep the weighted order, and a candidate
                # that loses its Belady test no longer ends the matching
                # (the reorder breaks the soonest-first monotonicity
                # that made the early exit sound).
                cands = [pf for pf in cands if not self._is_dcn(n, pf[0])]
                cands.sort(key=lambda pf: (-self._link_weight(n, pf[0]),
                                           pf[1]))
            dcn_batch = self.hierarchical and \
                any(self._is_dcn(n, p) for p in shard)
            for p, first_use in cands:
                si = len(batch) - len(shard)
                if si >= len(tail):
                    break
                if first_use >= self._next_dense_use(self._occ[tail[si]]):
                    if self.hierarchical:
                        if dcn_batch:
                            # fatten the DCN-bearing all-to-all: each
                            # extra crossing costs 2^-m marginally but
                            # shrinks the DCN bit's even-split share from
                            # u_m/m to u_{m+1}/(m+1) -- under the w-fold
                            # DCN weight that dominates the churn risk of
                            # an unsound (early-next-use) eviction, which
                            # lands on an ICI position either way
                            batch.append(p)
                        continue
                    break
                batch.append(p)
            slots = slots + tail[:len(batch) - len(shard)]
        if self.deferring and self.batch_relocations and len(batch) >= 2:
            pairs = list(zip(batch, slots))
            swap_units = float(sum(_swap_price(f, s, nl)
                                   for s, f in pairs))
            source = list(range(n))
            for s, f in pairs:
                source[s], source[f] = source[f], source[s]
            cstats = X.permute_collective_stats(n, tuple(source), self.mesh)
            if self.hierarchical:
                # two-tier comparison: the batched all-to-all crosses the
                # DCN bit once at its even-split share, each singleton
                # swap at a full unit -- weigh both sides per link
                win = self._weighted_permute_units(
                    n, nl, source, cstats) < sum(
                        _swap_price(f, s, nl) * self._link_weight(
                            n, max(f, s)) for s, f in pairs)
            else:
                win = cstats["chunk_units"] < swap_units
            if win:
                self.stats["relocation_batches"] += 1
                self.stats["relocation_batch_qubits"] += len(pairs)
                self.stats["relocation_prefetched"] += len(batch) - len(shard)
                self.stats["relocation_batch_chunks"] += \
                    cstats["chunk_units"]
                self.stats["relocation_batch_swap_equiv_chunks"] += \
                    swap_units
                # link attribution: the grouped all-to-all's volume split
                # evenly over the crossing shard bits (as reconcile())
                share = cstats["chunk_units"] / len(pairs)
                for s, _ in pairs:
                    self._count_comm(n, s, share, kind="relocation_batch")
                self._note("permute", n, tuple(source), 1.0,
                           "relocation_batch")
                amps = X.dist_permute_bits(
                    amps, n=n, source=tuple(source), mesh=self.mesh,
                    pipeline=self._pipeline_for(
                        n, [s for s, _ in pairs]))
                for s, f in pairs:
                    self._swap_positions(f, s)
                return amps, {s: f for s, f in pairs if s in set(shard)}
        relocation = {}
        for s, f in zip(shard, free):
            self.stats["relocation_swaps"] += 1
            self._count_comm(n, s, 1.0, kind="dist_swap")
            self._note("dist_swap", n, f, s, self.deferring)
            amps = X.dist_swap(amps, n=n, qb1=f, qb2=s, mesh=self.mesh,
                               pipeline=self._pipeline_for(n, (s,)))
            if self.deferring:
                self._swap_positions(f, s)
            relocation[s] = f
        return amps, relocation

    # -- dense matrices -----------------------------------------------------

    def apply_matrix(self, amps, matrix, *, n, targets, controls=(),
                     control_states=(), conj=False):
        nl = local_qubit_count(n, self.mesh)
        self._touch(targets)
        p_targets = self._map(n, targets)
        p_controls = self._map(n, controls)
        shard_ts = [t for t in p_targets if t >= nl]
        if not shard_ts:
            self.stats["local"] += 1
            return X.dist_apply_local_matrix(
                amps, matrix, n=n, targets=p_targets,
                controls=p_controls, control_states=tuple(control_states),
                conj=conj, mesh=self.mesh, pipeline=self.comm_pipeline)
        support = set(p_targets) | set(p_controls)
        if len(targets) == 1:
            # the reference's policy: full-chunk pair exchange per gate
            # (QuEST_cpu_distributed.c:870-905). Deferred mode relocates
            # instead (half the traffic now, zero for later gates on the
            # same qubit) and falls back to the pair exchange when no
            # local slot is free.
            relocation = None
            if self.deferring:
                amps, relocation = self._relocate(amps, n, nl, p_targets,
                                                  support, on_fail="none")
            if relocation is None:
                self.stats["pair_exchanges"] += 1
                self._count_comm(n, p_targets[0], 2.0,
                                 kind="pair_exchange")
                self._note("pair_exchange", n, p_targets[0])
                return X.dist_apply_matrix1(
                    amps, matrix, n=n, target=p_targets[0],
                    controls=p_controls,
                    control_states=tuple(control_states), conj=conj,
                    mesh=self.mesh,
                    pipeline=self._pipeline_for(n, (p_targets[0],)))
            self.stats["local"] += 1
            return X.dist_apply_local_matrix(
                amps, matrix, n=n,
                targets=tuple(relocation.get(t, t) for t in p_targets),
                controls=tuple(relocation.get(c, c) for c in p_controls),
                control_states=tuple(control_states), conj=conj,
                mesh=self.mesh, pipeline=self.comm_pipeline)
        # relocate sharded targets to free local slots, apply locally;
        # immediate mode swaps back (reference :1526-1568), deferred mode
        # leaves the new layout in place
        amps, relocation = self._relocate(amps, n, nl, p_targets, support)
        new_targets = tuple(relocation.get(t, t) for t in p_targets)
        new_controls = tuple(relocation.get(c, c) for c in p_controls)
        self.stats["local"] += 1
        amps = X.dist_apply_local_matrix(
            amps, matrix, n=n, targets=new_targets, controls=new_controls,
            control_states=tuple(control_states), conj=conj, mesh=self.mesh,
            pipeline=self.comm_pipeline)
        if not self.deferring:
            for s, f in relocation.items():
                self.stats["relocation_swaps"] += 1
                self._count_comm(n, s, 1.0, kind="dist_swap")
                self._note("dist_swap", n, f, s, False)
                amps = X.dist_swap(amps, n=n, qb1=f, qb2=s, mesh=self.mesh,
                                   pipeline=self._pipeline_for(n, (s,)))
        return amps

    # -- permutation class --------------------------------------------------

    def apply_x(self, amps, *, n, targets, controls=(), control_states=()):
        nl = local_qubit_count(n, self.mesh)
        self._touch(tuple(targets) + tuple(controls))
        p_targets = self._map(n, targets)
        p_controls = self._map(n, controls)
        if not any(t >= nl for t in p_targets):
            self.stats["local"] += 1
            return X.dist_apply_x(amps, n=n, targets=p_targets,
                                  controls=p_controls,
                                  control_states=tuple(control_states),
                                  mesh=self.mesh,
                                  pipeline=self.comm_pipeline)
        relocation = None
        if self.deferring:
            # relocate sharded X targets too: a rank permute re-routes the
            # full chunk (2 units) where a relocation moves half each way
            # (1 unit) and leaves the qubit resident for later gates;
            # fall back to the rank permute when no local slot is free
            support = set(p_targets) | set(p_controls)
            amps, relocation = self._relocate(amps, n, nl, p_targets,
                                              support, on_fail="none")
        if relocation is not None:
            p_targets = tuple(relocation.get(t, t) for t in p_targets)
            p_controls = tuple(relocation.get(c, c) for c in p_controls)
            self.stats["local"] += 1
        else:
            self.stats["rank_permutes"] += 1
            self._count_comm(n, max(t for t in p_targets if t >= nl), 2.0,
                             kind="grouped_permute")
            self._note("rank_permute", n,
                       max(t for t in p_targets if t >= nl))
        return X.dist_apply_x(amps, n=n, targets=p_targets,
                              controls=p_controls,
                              control_states=tuple(control_states),
                              mesh=self.mesh,
                              pipeline=self._pipeline_for(
                                  n, [t for t in p_targets if t >= nl]))

    def apply_swap(self, amps, *, n, qb1, qb2):
        self._touch((qb1, qb2))
        if self.deferring:
            # an uncontrolled SWAP gate is a pure relabeling: update the
            # layout, move no data at all (the reference's swapQubitAmps
            # always pays an odd-parity exchange, :1443-1459)
            self._ensure_perm(n)
            p1, p2 = self._pos[qb1], self._pos[qb2]
            self._swap_positions(p1, p2)
            self.stats["virtual_swaps"] += 1
            self._note("virtual_swap", p1, p2)
            telemetry.inc("comm_ops_total", kind="virtual_swap")
            return amps
        p1, p2 = self._map(n, (qb1, qb2))
        nl = local_qubit_count(n, self.mesh)
        both_local = max(p1, p2) < nl
        if both_local:
            self.stats["local"] += 1
        elif min(p1, p2) >= nl:
            a, b = max(p1, p2), min(p1, p2)
            if (self.hierarchical and nl > 0 and self._is_dcn(n, a)
                    and not self._is_dcn(n, b)
                    and 2.0 + self.dcn_cost_weight
                        < 2.0 * self.dcn_cost_weight):
                # stage the cross-slice exchange through a local relay:
                # three odd-parity half-exchanges (1 unit each, one on
                # DCN) instead of a full-chunk rank permute (2 units, all
                # on DCN) -- the immediate-mode twin of the reconcile
                # chain's ('relay', a, b, r) step
                r = 0
                self.stats["staged_relays"] += 1
                self._note("staged_relay", n, a, b, r)
                for x, y in ((b, r), (a, r), (b, r)):
                    self.stats["relocation_swaps"] += 1
                    self._count_comm(n, max(x, y), 1.0, kind="dist_swap")
                    self._note("dist_swap", n, y, x, False)
                    amps = X.dist_swap(
                        amps, n=n, qb1=x, qb2=y, mesh=self.mesh,
                        pipeline=self._pipeline_for(n, (x, y)))
                return amps
            self.stats["rank_permutes"] += 1
            self._count_comm(n, max(p1, p2), 2.0, kind="grouped_permute")
            self._note("rank_permute", n, max(p1, p2))
        else:
            self.stats["relocation_swaps"] += 1
            self._count_comm(n, max(p1, p2), 1.0, kind="dist_swap")
            self._note("dist_swap", n, p1, p2, False)
        return X.dist_swap(amps, n=n, qb1=p1, qb2=p2, mesh=self.mesh,
                           pipeline=self._pipeline_for(n, (p1, p2)))

    # -- diagonal family (always comm-free) ---------------------------------

    def map_diagonal_qubits(self, n: int, qubits) -> tuple:
        """Physical coordinates for a purely-diagonal access (phase
        functions, projectors, sub-diagonal ops): index-algebra ops work
        under ANY layout comm-free, so the caller just needs the current
        physical positions. Counted as a comm-free plan entry. This is what
        lets operator tape entries run while a deferred layout is live
        instead of forcing reconciliation (round-4; VERDICT r3 weak #5)."""
        self.stats["comm_free"] += 1
        self._touch(qubits)
        return self._map(n, qubits)

    def apply_diagonal(self, amps, diag, *, n, targets, controls=(),
                       control_states=(), conj=False):
        self.stats["comm_free"] += 1
        self._touch(targets)
        return X.dist_apply_diag_phase(
            amps, diag, n=n, targets=self._map(n, targets),
            controls=self._map(n, controls),
            control_states=tuple(control_states), conj=conj, mesh=self.mesh,
            pipeline=self.comm_pipeline)

    def apply_parity_phase(self, amps, theta, *, n, qubits, controls=(),
                           control_states=(), conj=False):
        self.stats["comm_free"] += 1
        self._touch(qubits)
        return X.dist_apply_parity_phase(
            amps, theta, n=n, qubits=self._map(n, qubits),
            controls=self._map(n, controls),
            control_states=tuple(control_states), conj=conj, mesh=self.mesh,
            pipeline=self.comm_pipeline)


@contextmanager
def explicit_mesh(mesh: Mesh, num_slices: int = 1, defer: bool = True,
                  collective_reconcile: bool = True,
                  batch_relocations: bool = True,
                  comm_pipeline: int | None = None,
                  hierarchical: bool = False,
                  comm_pipeline_dcn: int | None = None):
    """Route L5 gate application through the explicit shard_map kernels.
    ``num_slices`` > 1 splits the plan's comm stats into ICI vs DCN chunks
    (slice-major device order; parallel.mesh.shard_bit_link).
    ``batch_relocations=False`` forces the per-swap relocation policy
    (A/B against the round-6 grouped-permute batching).
    ``comm_pipeline`` sets the collective pipeline depth every exchange
    launch in the context runs at (None = the QUEST_COMM_PIPELINE env
    default, 1 = monolithic; bit-identical at every depth);
    ``comm_pipeline_dcn`` overrides it for DCN-riding collectives (None =
    the QUEST_COMM_PIPELINE_DCN env, else inherit). ``hierarchical=True``
    turns on the two-tier DCN-aware planning decisions (round 15;
    False keeps the flat scheduler, the A/B baseline)."""
    from ..environment import AMP_AXIS
    if mesh is not None and mesh.size > 1 and AMP_AXIS not in mesh.shape:
        raise ValueError(
            f"explicit_mesh requires a mesh whose amplitude axis is named "
            f"'{AMP_AXIS}' (got axes {tuple(mesh.shape)}); build it with "
            f"createQuESTEnv or Mesh(devices, ('{AMP_AXIS}',))")
    sched = (DistributedScheduler(mesh, num_slices=num_slices,
                                  allow_defer=defer,
                                  collective_reconcile=collective_reconcile,
                                  batch_relocations=batch_relocations,
                                  comm_pipeline=comm_pipeline,
                                  hierarchical=hierarchical,
                                  comm_pipeline_dcn=comm_pipeline_dcn)
             if mesh is not None and mesh.size > 1 else None)
    prev = getattr(_STATE, "sched", None)
    _STATE.sched = sched
    try:
        yield sched
    finally:
        _STATE.sched = prev


def active() -> DistributedScheduler | None:
    """The scheduler of the innermost explicit_mesh context, if any."""
    return getattr(_STATE, "sched", None)


def comm_chunks(stats: dict) -> float:
    """Total comm traffic of a plan in chunk units, the single source of
    the cost-model weights (2 per pair exchange / rank permute, 1 per
    relocation swap, 0 for virtual swaps, plus ``reconcile_chunks``,
    ``relocation_batch_chunks`` and ``frame_transpose_chunks`` -- the
    measured units of whichever reconciliation / relocation policy ran,
    per-swap or collective, and of the pallas frame transposes the
    scheduler executed, df layouts priced at 2x) -- comm_volume() and
    every report derive from this."""
    return (2.0 * stats["pair_exchanges"] + 1.0 * stats["relocation_swaps"]
            + 2.0 * stats["rank_permutes"]
            + stats.get("reconcile_chunks", 0.0)
            + stats.get("relocation_batch_chunks", 0.0)
            + stats.get("frame_transpose_chunks", 0.0))


def plan_circuit(circuit, mesh: Mesh, num_slices: int = 1,
                 defer: bool = True, collective_reconcile: bool = True,
                 batch_relocations: bool = True, dtype=None,
                 journal: list | None = None,
                 comm_pipeline: int | None = None,
                 hierarchical: bool = False,
                 comm_pipeline_dcn: int | None = None):
    """Trace ``circuit`` abstractly under the explicit scheduler and return
    its communication plan stats (no device execution -- jax.eval_shape).
    ``dtype`` sets the abstract register's amplitude dtype (default: the
    process precision) -- an f64 plan whose fused tape takes the sharded
    double-float route prices its frame transposes at the df 2x chunk-unit
    scale, exactly as the executed replay counts them. ``journal`` (a
    caller-owned list) additionally records every communication decision
    for the static verifier (see DistributedScheduler.journal);
    ``comm_pipeline`` stamps the resolved collective pipeline depth into
    that journal (pricing is depth-invariant -- check_schedule proves
    it); at num_slices > 1 the stamp widens to (base, dcn) per-link-class
    depths, ``comm_pipeline_dcn`` overriding the DCN one.
    ``hierarchical=True`` plans with the two-tier DCN-aware decisions
    (see explicit_mesh)."""
    import jax
    import numpy as np

    from ..precision import precision_for_dtype, real_dtype

    if dtype is not None:
        # an f64 plan needs jax x64 or eval_shape canonicalises the
        # abstract register down to f32 (and the df route never engages)
        real_dtype(precision_for_dtype(dtype))
    dt = np.dtype(dtype) if dtype is not None else real_dtype(None)
    nsv = (2 if circuit.is_density_matrix else 1) * circuit.num_qubits
    num_amps = 1 << nsv
    with explicit_mesh(mesh, num_slices=num_slices, defer=defer,
                       collective_reconcile=collective_reconcile,
                       batch_relocations=batch_relocations,
                       comm_pipeline=comm_pipeline,
                       hierarchical=hierarchical,
                       comm_pipeline_dcn=comm_pipeline_dcn) as sched:
        if sched is not None and journal is not None:
            sched.journal = journal
        fn = circuit.as_fn()
        jax.eval_shape(fn, jax.ShapeDtypeStruct((2, num_amps), dt))
    if sched is None:
        return {}
    out = dict(sched.stats)
    out["comm_volume"] = sched.comm_volume(
        nsv, bytes_per_amp=2 * np.dtype(dt).itemsize)
    return out
