"""Gate-dispatch scheduler for explicit distributed execution.

Reference dispatch policy (QuEST_cpu_distributed.c):
  - 1q dense gate, target non-local -> full-chunk pair exchange (:870-905);
  - n-target dense gate with non-local targets -> relocate each to a free
    local qubit via swapQubitAmps, apply locally, swap back (:1526-1568);
  - X class -> chunk exchange with ctrl-skip (:1109-1152);
  - diagonal/phase -> never communicate.

The scheduler reproduces that policy over the :mod:`.exchange` shard_map
kernels and improves on it where TPU semantics allow:
  - sharded *controls* never travel: they become device-index predicates
    (the reference ships control bits through the exchange);
  - everything composes under one ``jax.jit``, so XLA overlaps the
    ``ppermute`` traffic of one gate with the local compute of the next --
    the reference synchronises on MPI_Waitall per gate.

Usage: ``with explicit_mesh(mesh): <apply gates / run circuits>`` -- the L5
API helpers in gates.py route through :func:`active` while the context is
live. Works eagerly and on Circuit tapes (enter the context before
``Circuit.run`` / inside the traced step).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

from jax.sharding import Mesh

from . import exchange as X
from .mesh import local_qubit_count

_STATE = threading.local()


@dataclass
class DistributedScheduler:
    """Stateless-per-gate dispatcher bound to a mesh; collects plan stats
    (number of pair exchanges / relocations / comm-free ops) at trace time."""

    mesh: Mesh
    stats: dict = field(default_factory=lambda: {
        "pair_exchanges": 0, "relocation_swaps": 0, "rank_permutes": 0,
        "comm_free": 0, "local": 0, "channel_superops": 0})

    def comm_volume(self, n: int, bytes_per_amp: int = 8) -> dict:
        """Trace-time communication-volume estimate for the collected plan,
        per device, mirroring the reference's comm cost model (BASELINE.md:
        a non-local 1q gate exchanges a full chunk send+recv per rank,
        QuEST_cpu_distributed.c:495-533; a relocation/odd-parity swap moves
        half a chunk each way, :1443-1459; an X-class rank permute
        re-routes the full chunk). ``bytes_per_amp`` = 8 for planar f32
        (re+im), 16 for f64."""
        chunk = (1 << n) // self.mesh.size
        s = self.stats
        amps_moved = chunk * (2.0 * s["pair_exchanges"]
                              + 1.0 * s["relocation_swaps"]
                              + 2.0 * s["rank_permutes"])
        return {
            "amps_per_device": amps_moved,
            "bytes_per_device": amps_moved * bytes_per_amp,
            "chunk_amps": chunk,
        }

    # -- dense matrices -----------------------------------------------------

    def apply_matrix(self, amps, matrix, *, n, targets, controls=(),
                     control_states=(), conj=False):
        nl = local_qubit_count(n, self.mesh)
        shard_ts = [t for t in targets if t >= nl]
        if not shard_ts:
            self.stats["local"] += 1
            return X.dist_apply_local_matrix(
                amps, matrix, n=n, targets=tuple(targets),
                controls=tuple(controls), control_states=tuple(control_states),
                conj=conj, mesh=self.mesh)
        if len(targets) == 1:
            self.stats["pair_exchanges"] += 1
            return X.dist_apply_matrix1(
                amps, matrix, n=n, target=targets[0], controls=tuple(controls),
                control_states=tuple(control_states), conj=conj, mesh=self.mesh)
        # n-target: relocate sharded targets to free local qubits, apply,
        # swap back (reference :1526-1568). Local slots are chosen low-first
        # among qubits outside the gate's support.
        support = set(targets) | set(controls)
        free = [q for q in range(nl) if q not in support]
        if len(free) < len(shard_ts):
            # surface through the overridable error hook, as the reference's
            # matrix-fits-in-node check (validateMultiQubitMatrixFitsInNode,
            # QuEST_validation.c:522-524, E_CANNOT_FIT_MULTI_QUBIT_MATRIX)
            from .. import validation as V
            V.validate_matrix_fits_in_node(len(free), len(shard_ts),
                                           "applyMatrix")
        relocation = dict(zip(shard_ts, free))
        for s, f in relocation.items():
            amps = self.apply_swap(amps, n=n, qb1=f, qb2=s)
        new_targets = tuple(relocation.get(t, t) for t in targets)
        new_controls = tuple(relocation.get(c, c) for c in controls)
        self.stats["local"] += 1
        amps = X.dist_apply_local_matrix(
            amps, matrix, n=n, targets=new_targets, controls=new_controls,
            control_states=tuple(control_states), conj=conj, mesh=self.mesh)
        for s, f in relocation.items():
            amps = self.apply_swap(amps, n=n, qb1=f, qb2=s)
        return amps

    # -- permutation class --------------------------------------------------

    def apply_x(self, amps, *, n, targets, controls=(), control_states=()):
        nl = local_qubit_count(n, self.mesh)
        if any(t >= nl for t in targets):
            self.stats["rank_permutes"] += 1
        else:
            self.stats["local"] += 1
        return X.dist_apply_x(amps, n=n, targets=tuple(targets),
                              controls=tuple(controls),
                              control_states=tuple(control_states),
                              mesh=self.mesh)

    def apply_swap(self, amps, *, n, qb1, qb2):
        nl = local_qubit_count(n, self.mesh)
        both_local = max(qb1, qb2) < nl
        if both_local:
            self.stats["local"] += 1
        elif min(qb1, qb2) >= nl:
            self.stats["rank_permutes"] += 1
        else:
            self.stats["relocation_swaps"] += 1
        return X.dist_swap(amps, n=n, qb1=qb1, qb2=qb2, mesh=self.mesh)

    # -- diagonal family (always comm-free) ---------------------------------

    def apply_diagonal(self, amps, diag, *, n, targets, controls=(),
                       control_states=(), conj=False):
        self.stats["comm_free"] += 1
        return X.dist_apply_diag_phase(
            amps, diag, n=n, targets=tuple(targets), controls=tuple(controls),
            control_states=tuple(control_states), conj=conj, mesh=self.mesh)

    def apply_parity_phase(self, amps, theta, *, n, qubits, controls=(),
                           control_states=(), conj=False):
        self.stats["comm_free"] += 1
        return X.dist_apply_parity_phase(
            amps, theta, n=n, qubits=tuple(qubits), controls=tuple(controls),
            control_states=tuple(control_states), conj=conj, mesh=self.mesh)


@contextmanager
def explicit_mesh(mesh: Mesh):
    """Route L5 gate application through the explicit shard_map kernels."""
    from ..environment import AMP_AXIS
    if mesh is not None and mesh.size > 1 and AMP_AXIS not in mesh.shape:
        raise ValueError(
            f"explicit_mesh requires a mesh whose amplitude axis is named "
            f"'{AMP_AXIS}' (got axes {tuple(mesh.shape)}); build it with "
            f"createQuESTEnv or Mesh(devices, ('{AMP_AXIS}',))")
    sched = DistributedScheduler(mesh) if mesh is not None and mesh.size > 1 else None
    prev = getattr(_STATE, "sched", None)
    _STATE.sched = sched
    try:
        yield sched
    finally:
        _STATE.sched = prev


def active() -> DistributedScheduler | None:
    """The scheduler of the innermost explicit_mesh context, if any."""
    return getattr(_STATE, "sched", None)


def plan_circuit(circuit, mesh: Mesh):
    """Trace ``circuit`` abstractly under the explicit scheduler and return
    its communication plan stats (no device execution -- jax.eval_shape)."""
    import jax
    import numpy as np

    from ..precision import real_dtype

    nsv = (2 if circuit.is_density_matrix else 1) * circuit.num_qubits
    num_amps = 1 << nsv
    with explicit_mesh(mesh) as sched:
        fn = circuit.as_fn()
        jax.eval_shape(fn, jax.ShapeDtypeStruct((2, num_amps), real_dtype(None)))
    if sched is None:
        return {}
    out = dict(sched.stats)
    out["comm_volume"] = sched.comm_volume(
        nsv, bytes_per_amp=2 * np.dtype(real_dtype(None)).itemsize)
    return out
