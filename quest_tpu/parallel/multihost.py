"""Multi-host (multi-slice / DCN) environment setup.

The reference reaches multi-node scale through mpirun + MPI_Init
(QuEST_cpu_distributed.c:131-164); the JAX equivalent is
``jax.distributed.initialize`` on every host followed by building one
global mesh over ``jax.devices()``. This module packages that, so a pod
user writes:

    import quest_tpu as qt
    from quest_tpu.parallel import multihost

    multihost.init()                       # no-op on single host
    env = qt.createQuESTEnv()              # mesh over ALL hosts' devices
    qureg = qt.createQureg(36, env)        # sharded across the pod

Design note (SURVEY.md section 2.5): amplitude sharding is this
framework's one parallel axis, so the mesh is 1-D over every global
device; XLA routes the resulting collectives over ICI within a slice and
DCN across slices. Host-local process coordination (the reference's rank
broadcast of seeds, QuEST_cpu_distributed.c:1400-1418) is unnecessary:
JAX's single-controller-per-host SPMD model ships identical host code,
and seeding is deterministic given the same user-provided seeds.
"""

from __future__ import annotations

import inspect
import os

import jax

from ..validation import QuESTError

__all__ = ["init", "is_multihost", "process_info"]

_DEF_TIMEOUT_S = 300.0


def _is_initialized() -> bool:
    """Whether the jax distributed runtime is already up. jax >= 0.5 has
    ``jax.distributed.is_initialized()``; older releases (this container's
    0.4.x) expose only the global client state -- probing it avoids the
    AttributeError that silently broke ``init`` on 0.4.37."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _d
        return _d.global_state.client is not None
    except Exception:
        return False


def _resolve_timeout(initialization_timeout: float | None) -> float:
    if initialization_timeout is not None:
        return float(initialization_timeout)
    raw = os.environ.get("QUEST_INIT_TIMEOUT_S", "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            from ..analysis.diagnostics import emit_findings, make_finding
            emit_findings([make_finding(
                "QT303", f"QUEST_INIT_TIMEOUT_S={raw!r} is not numeric; "
                "using the default", "parallel.multihost")])
    return _DEF_TIMEOUT_S


def _probe_coordinator(coordinator_address: str, timeout_s: float) -> None:
    """Bounded TCP reachability check of the coordinator, retried until
    ``timeout_s``. jax 0.4.x's distributed client turns a RegisterTask
    deadline into an absl FATAL that *aborts the process* (client.h:80) --
    no Python exception ever surfaces -- so a missing/unreachable
    coordinator must be caught HERE, before handing off, to fail typed."""
    import socket
    import time

    host, _, port_s = coordinator_address.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        raise QuESTError(
            f"coordinator address {coordinator_address!r} is not host:port",
            "multihost.init") from None
    deadline = time.monotonic() + timeout_s
    last: Exception | None = None
    while True:
        try:
            with socket.create_connection(
                    (host or "127.0.0.1", port),
                    timeout=max(0.1, min(2.0, timeout_s))):
                return
        except OSError as e:
            last = e
        if time.monotonic() >= deadline:
            break
        time.sleep(min(0.2, timeout_s / 10))
    from ..analysis.diagnostics import emit_findings, make_finding
    emit_findings([make_finding(
        "QT301", f"coordinator {coordinator_address!r} unreachable within "
        f"{timeout_s:g}s: {last}", "parallel.multihost.init")])
    raise QuESTError(
        f"multi-host initialization failed against coordinator "
        f"{coordinator_address!r} within the {timeout_s:g}s "
        f"initialization_timeout: {last} [QT301]", "multihost.init")


def init(coordinator_address: str | None = None,
         num_processes: int | None = None,
         process_id: int | None = None,
         initialization_timeout: float | None = None) -> None:
    """Initialise cross-host communication (idempotent; no-op when the
    JAX runtime already knows its topology, e.g. TPU pod metadata).

    On Cloud TPU pods all three arguments auto-detect; elsewhere pass them
    explicitly or via JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID, exactly like mpirun's rank/size but resolved by the
    JAX distributed runtime instead of an MPI launcher.

    ``initialization_timeout`` (seconds; default ``QUEST_INIT_TIMEOUT_S``
    or 300) bounds the wait for the coordinator: a missing or unreachable
    coordinator raises a QuESTError naming the timeout (flight-recorded
    QT301) instead of hanging the process indefinitely -- the ISSUE 7
    resilience contract for cluster bring-up.

    Must run before anything touches the XLA backend (jax.distributed's
    own contract) -- so the already-initialised check goes through
    :func:`_is_initialized`, NOT jax.process_count(), which would itself
    initialise the backend (found by the round-4 2-process smoke test,
    tests/test_multihost.py)."""
    if _is_initialized():
        return
    timeout_s = _resolve_timeout(initialization_timeout)
    kwargs = {}
    if "initialization_timeout" in inspect.signature(
            jax.distributed.initialize).parameters:
        # jax wants whole seconds; never round a positive timeout to zero
        kwargs["initialization_timeout"] = max(1, int(timeout_s))
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None and num_processes is None:
        # single host, or TPU-pod autodetection at first backend use
        try:
            jax.distributed.initialize(**kwargs)
        except Exception:
            pass  # single-process environments: nothing to do
        return
    if process_id not in (None, 0):
        # process 0 hosts the coordination service itself (nothing to probe
        # before it binds); every other process must reach it over TCP
        _probe_coordinator(coordinator_address, timeout_s)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id, **kwargs)
    except Exception as e:
        from ..analysis.diagnostics import emit_findings, make_finding

        emit_findings([make_finding(
            "QT301", f"multi-host initialization failed against "
            f"coordinator {coordinator_address!r} within {timeout_s:g}s: "
            f"{e}", "parallel.multihost.init")])
        raise QuESTError(
            f"multi-host initialization failed against coordinator "
            f"{coordinator_address!r} within the {timeout_s:g}s "
            f"initialization_timeout: {e} [QT301]", "multihost.init") from e


def is_multihost() -> bool:
    return jax.process_count() > 1


def process_info() -> dict:
    """Rank-style identity, the analogue of the reference env's
    (rank, numRanks) pair (QuEST.h:405-415)."""
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
