"""Multi-host (multi-slice / DCN) environment setup.

The reference reaches multi-node scale through mpirun + MPI_Init
(QuEST_cpu_distributed.c:131-164); the JAX equivalent is
``jax.distributed.initialize`` on every host followed by building one
global mesh over ``jax.devices()``. This module packages that, so a pod
user writes:

    import quest_tpu as qt
    from quest_tpu.parallel import multihost

    multihost.init()                       # no-op on single host
    env = qt.createQuESTEnv()              # mesh over ALL hosts' devices
    qureg = qt.createQureg(36, env)        # sharded across the pod

Design note (SURVEY.md section 2.5): amplitude sharding is this
framework's one parallel axis, so the mesh is 1-D over every global
device; XLA routes the resulting collectives over ICI within a slice and
DCN across slices. Host-local process coordination (the reference's rank
broadcast of seeds, QuEST_cpu_distributed.c:1400-1418) is unnecessary:
JAX's single-controller-per-host SPMD model ships identical host code,
and seeding is deterministic given the same user-provided seeds.
"""

from __future__ import annotations

import os

import jax

__all__ = ["init", "is_multihost", "process_info"]


def init(coordinator_address: str | None = None,
         num_processes: int | None = None,
         process_id: int | None = None) -> None:
    """Initialise cross-host communication (idempotent; no-op when the
    JAX runtime already knows its topology, e.g. TPU pod metadata).

    On Cloud TPU pods all three arguments auto-detect; elsewhere pass them
    explicitly or via JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID, exactly like mpirun's rank/size but resolved by the
    JAX distributed runtime instead of an MPI launcher.

    Must run before anything touches the XLA backend (jax.distributed's
    own contract) -- so the already-initialised check goes through
    jax.distributed.is_initialized(), NOT jax.process_count(), which
    would itself initialise the backend (found by the round-4 2-process
    smoke test, tests/test_multihost.py)."""
    if jax.distributed.is_initialized():
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None and num_processes is None:
        # single host, or TPU-pod autodetection at first backend use
        try:
            jax.distributed.initialize()
        except Exception:
            pass  # single-process environments: nothing to do
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)


def is_multihost() -> bool:
    return jax.process_count() > 1


def process_info() -> dict:
    """Rank-style identity, the analogue of the reference env's
    (rank, numRanks) pair (QuEST.h:405-415)."""
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
