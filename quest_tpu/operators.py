"""Operators: possibly non-unitary applications and structured operators
(reference QuEST.h:5688-7421 + DiagonalOp family QuEST.h:1033-1513).

Includes: applyMatrix2/4/N (+Gate/MultiControlled variants), applyPauliSum /
applyPauliHamil, applyTrotterCircuit, applyFullQFT / applyQFT, the phase
function family, DiagonalOp / SubDiagonalOp application, applyProjector.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from . import validation as V
from .datatypes import (DiagonalOp, PauliHamil, SubDiagonalOp,
                        pauli_term_matrix, phaseFunc)
from .ops import apply as K, cplx, diagonal as D, measure as M
from .ops import phasefunc as PF, reduce as R
from .parallel import scheduler as _dist
from .registers import Qureg, createCloneQureg

__all__ = [
    "applyMatrix2", "applyMatrix4", "applyMatrixN", "applyGateMatrixN",
    "applyMultiControlledMatrixN", "applyMultiControlledGateMatrixN",
    "applyPauliSum", "applyPauliHamil", "applyTrotterCircuit",
    "applyFullQFT", "applyQFT", "applyProjector",
    "applyPhaseFunc", "applyPhaseFuncOverrides",
    "applyMultiVarPhaseFunc", "applyMultiVarPhaseFuncOverrides",
    "applyNamedPhaseFunc", "applyNamedPhaseFuncOverrides",
    "applyParamNamedPhaseFunc", "applyParamNamedPhaseFuncOverrides",
    "createDiagonalOp", "destroyDiagonalOp", "syncDiagonalOp",
    "initDiagonalOp", "setDiagonalOpElems", "initDiagonalOpFromPauliHamil",
    "createDiagonalOpFromPauliHamilFile", "applyDiagonalOp",
    "calcExpecDiagonalOp", "applySubDiagonalOp", "applyGateSubDiagonalOp",
    "setQuregToPauliHamil",
]


def _record(qureg, text):
    if qureg.qasm_log is not None:
        qureg.qasm_log.record_comment(text)


# ---------------------------------------------------------------------------
# direct (non-unitary) matrix application: left-multiplies a density matrix
# (no conj-shadow), unlike the Gate variants (QuEST.h:5892-6147)
# ---------------------------------------------------------------------------

def _apply_matrix_left(qureg: Qureg, matrix, targets, controls=()):
    """M|psi> or M.rho (left multiplication only). Routes through the
    explicit scheduler when one is active, so the entry both shows in plan
    stats and remaps its coordinates under a deferred layout (round-4:
    operator entries no longer force deferral reconciliation)."""
    nsv = qureg.num_qubits_in_state_vec
    m = cplx.from_complex(matrix, qureg.dtype)
    sched = _dist.active()
    apply_m = sched.apply_matrix if sched is not None else K.apply_matrix
    qureg.put(apply_m(qureg.amps, m, n=nsv, targets=tuple(targets),
                      controls=tuple(controls)))


def _apply_matrix_gate(qureg: Qureg, matrix, targets, controls=()):
    """M|psi> or M.rho.M^dagger (the Gate variants)."""
    n = qureg.num_qubits_represented
    nsv = qureg.num_qubits_in_state_vec
    m = cplx.from_complex(matrix, qureg.dtype)
    sched = _dist.active()
    apply_m = sched.apply_matrix if sched is not None else K.apply_matrix
    amps = apply_m(qureg.amps, m, n=nsv, targets=tuple(targets),
                   controls=tuple(controls))
    if qureg.is_density_matrix:
        amps = apply_m(amps, m, n=nsv,
                       targets=tuple(q + n for q in targets),
                       controls=tuple(c + n for c in controls), conj=True)
    qureg.put(amps)


def applyMatrix2(qureg: Qureg, target: int, u) -> None:
    """(QuEST.h:5892)."""
    func = "applyMatrix2"
    V.validate_target(qureg, target, func)
    V.validate_matrix_size(u, 1, func)
    _apply_matrix_left(qureg, u, (target,))
    _record(qureg, "applyMatrix2")


def applyMatrix4(qureg: Qureg, t1: int, t2: int, u) -> None:
    """Left-multiply a general 4x4 matrix, not necessarily unitary (QuEST.h:298)."""
    func = "applyMatrix4"
    V.validate_multi_targets(qureg, (t1, t2), func)
    V.validate_matrix_size(u, 2, func)
    _apply_matrix_left(qureg, u, (t1, t2))
    _record(qureg, "applyMatrix4")


def applyMatrixN(qureg: Qureg, targets, u) -> None:
    """Left-multiply a general 2^N x 2^N matrix, not necessarily unitary (QuEST.h:299)."""
    func = "applyMatrixN"
    V.validate_multi_targets(qureg, targets, func)
    V.validate_matrix_init(u, func)
    V.validate_matrix_size(u, len(targets), func)
    _apply_matrix_left(qureg, u, tuple(targets))
    _record(qureg, "applyMatrixN")


def applyGateMatrixN(qureg: Qureg, targets, u) -> None:
    """Applies M (and M^dagger on the bra side of a density matrix) without
    requiring unitarity (QuEST.h:6043)."""
    func = "applyGateMatrixN"
    V.validate_multi_targets(qureg, targets, func)
    V.validate_matrix_init(u, func)
    V.validate_matrix_size(u, len(targets), func)
    _apply_matrix_gate(qureg, u, tuple(targets))
    _record(qureg, "applyGateMatrixN")


def applyMultiControlledMatrixN(qureg: Qureg, controls, targets, u) -> None:
    """Left-multiply a controlled general matrix, not necessarily unitary (QuEST.h:301)."""
    func = "applyMultiControlledMatrixN"
    V.validate_multi_controls_multi_targets(qureg, controls, targets, func)
    V.validate_matrix_init(u, func)
    V.validate_matrix_size(u, len(targets), func)
    _apply_matrix_left(qureg, u, tuple(targets), tuple(controls))
    _record(qureg, "applyMultiControlledMatrixN")


def applyMultiControlledGateMatrixN(qureg: Qureg, controls, targets, u) -> None:
    """(QuEST.h:6094)."""
    func = "applyMultiControlledGateMatrixN"
    V.validate_multi_controls_multi_targets(qureg, controls, targets, func)
    V.validate_matrix_init(u, func)
    V.validate_matrix_size(u, len(targets), func)
    _apply_matrix_gate(qureg, u, tuple(targets), tuple(controls))
    _record(qureg, "applyMultiControlledGateMatrixN")


# ---------------------------------------------------------------------------
# Pauli sums and Hamiltonians (statevec_applyPauliSum, QuEST_common.c:534-555)
# ---------------------------------------------------------------------------

def applyPauliSum(in_qureg: Qureg, all_pauli_codes, term_coeffs, out_qureg: Qureg) -> None:
    """out = sum_t c_t P_t |in> (QuEST.h:5747). Matches the reference's
    apply-undo loop semantics (in_qureg is restored)."""
    func = "applyPauliSum"
    codes = np.asarray(all_pauli_codes, dtype=np.int32).reshape(len(term_coeffs), -1)
    V._assert(codes.size == len(term_coeffs) * in_qureg.num_qubits_represented,
              "Invalid number of Pauli codes. The number of codes must equal numQubits * numSumTerms.",
              func)
    V.validate_pauli_codes(codes.ravel(), func)
    V.validate_matching_qureg_types(in_qureg, out_qureg, func)
    V.validate_matching_qureg_dims(in_qureg, out_qureg, func)
    _apply_pauli_sum(in_qureg, codes, term_coeffs, out_qureg)
    _record(out_qureg, "applyPauliSum")


def applyPauliHamil(in_qureg: Qureg, hamil: PauliHamil, out_qureg: Qureg) -> None:
    """(QuEST.h:5791)."""
    func = "applyPauliHamil"
    V.validate_pauli_hamil(hamil, func)
    V.validate_hamil_matches_qureg(in_qureg, hamil, func)
    V.validate_matching_qureg_types(in_qureg, out_qureg, func)
    V.validate_matching_qureg_dims(in_qureg, out_qureg, func)
    _apply_pauli_sum(in_qureg, hamil.pauli_codes, hamil.term_coeffs, out_qureg)
    _record(out_qureg, "applyPauliHamil")


def _apply_pauli_sum(in_qureg, codes, coeffs, out_qureg):
    from .calculations import _apply_pauli_prod
    n = in_qureg.num_qubits_represented
    targets = list(range(n))
    out_amps = jnp.zeros_like(in_qureg.amps)
    work = createCloneQureg(in_qureg, in_qureg.env)
    for t in range(codes.shape[0]):
        work.put(in_qureg.amps + 0)
        _apply_pauli_prod(work, targets, codes[t])
        c = float(coeffs[t])
        out_amps = out_amps + c * work.amps
    out_qureg.put(out_amps)


def applyTrotterCircuit(qureg: Qureg, hamil: PauliHamil, time: float,
                        order: int, reps: int) -> None:
    """Symmetrised Trotter-Suzuki evolution e^{-iHt}
    (agnostic_applyTrotterCircuit, QuEST_common.c:762-844)."""
    func = "applyTrotterCircuit"
    V.validate_pauli_hamil(hamil, func)
    V.validate_hamil_matches_qureg(qureg, hamil, func)
    V.validate_trotter_params(order, reps, func)
    was_recording = qureg.qasm_log.recording if qureg.qasm_log else False
    if qureg.qasm_log:
        qureg.qasm_log.recording = False
    for _ in range(reps):
        _trotter_cycle(qureg, hamil, time / reps, order)
    if qureg.qasm_log:
        qureg.qasm_log.recording = was_recording
    _record(qureg, f"applyTrotterCircuit(t={time:g}, order={order}, reps={reps})")


def _first_order_trotter(qureg, hamil, time, reverse):
    from .gates import multiRotatePauli
    terms = range(hamil.num_sum_terms)
    if reverse:
        terms = reversed(list(terms))
    targets = list(range(hamil.num_qubits))
    for t in terms:
        angle = 2 * float(hamil.term_coeffs[t]) * time
        multiRotatePauli(qureg, targets, hamil.pauli_codes[t], angle)


def _trotter_cycle(qureg, hamil, time, order):
    # recursion of agnostic_applyTrotterCircuit (QuEST_common.c:800-844)
    if order == 1:
        _first_order_trotter(qureg, hamil, time, False)
    elif order == 2:
        _first_order_trotter(qureg, hamil, time / 2, False)
        _first_order_trotter(qureg, hamil, time / 2, True)
    else:
        p = 1.0 / (4 - 4 ** (1.0 / (order - 1)))
        _trotter_cycle(qureg, hamil, p * time, order - 2)
        _trotter_cycle(qureg, hamil, p * time, order - 2)
        _trotter_cycle(qureg, hamil, (1 - 4 * p) * time, order - 2)
        _trotter_cycle(qureg, hamil, p * time, order - 2)
        _trotter_cycle(qureg, hamil, p * time, order - 2)


def setQuregToPauliHamil(qureg: Qureg, hamil: PauliHamil) -> None:
    """rho = H as a dense operator (QuEST.h:1854; densmatr_setQuregToPauliHamil).

    Built on device by a progressive Kronecker expansion of each term."""
    func = "setQuregToPauliHamil"
    V.validate_density_matr(qureg, func)
    V.validate_pauli_hamil(hamil, func)
    V.validate_hamil_matches_qureg(qureg, hamil, func)
    n = qureg.num_qubits_represented
    acc = np.zeros((2 ** n, 2 ** n), dtype=np.complex128)
    for t in range(hamil.num_sum_terms):
        acc += hamil.term_coeffs[t] * pauli_term_matrix(hamil.pauli_codes[t])
    # element rho[r, c] at flat index c*2^n + r -> [col, row] = acc.T
    from .state_init import _put_shaped
    _put_shaped(qureg, cplx.from_complex(acc.T.reshape(-1), qureg.dtype))


# ---------------------------------------------------------------------------
# QFT (agnostic_applyQFT, QuEST_common.c:846-908)
# ---------------------------------------------------------------------------

def _qft_on(qureg: Qureg, qubits) -> None:
    from .gates import controlledPhaseShift, hadamard, swapGate
    m = len(qubits)
    # textbook QFT: H + controlled phases, then qubit-order reversal
    for j in reversed(range(m)):
        hadamard(qureg, qubits[j])
        for k in range(j):
            angle = math.pi / (1 << (j - k))
            controlledPhaseShift(qureg, qubits[k], qubits[j], angle)
    for j in range(m // 2):
        swapGate(qureg, qubits[j], qubits[m - 1 - j])


def applyFullQFT(qureg: Qureg) -> None:
    """QFT on every qubit (QuEST.h:7277)."""
    was = qureg.qasm_log.recording if qureg.qasm_log else False
    if qureg.qasm_log:
        qureg.qasm_log.recording = False
    _qft_on(qureg, list(range(qureg.num_qubits_represented)))
    if qureg.qasm_log:
        qureg.qasm_log.recording = was
    _record(qureg, "applyFullQFT")


def applyQFT(qureg: Qureg, qubits) -> None:
    """QFT on a qubit subset (QuEST.h:7397)."""
    func = "applyQFT"
    V.validate_multi_targets(qureg, qubits, func)
    was = qureg.qasm_log.recording if qureg.qasm_log else False
    if qureg.qasm_log:
        qureg.qasm_log.recording = False
    _qft_on(qureg, list(qubits))
    if qureg.qasm_log:
        qureg.qasm_log.recording = was
    _record(qureg, f"applyQFT on {list(qubits)}")


def applyProjector(qureg: Qureg, target: int, outcome: int) -> None:
    """Unnormalised projection |outcome><outcome| on target (QuEST.h:7421)."""
    func = "applyProjector"
    V.validate_target(qureg, target, func)
    V.validate_outcome(outcome, func)
    n = qureg.num_qubits_represented
    nsv = qureg.num_qubits_in_state_vec
    sched = _dist.active()
    t_row, t_col = target, target + n
    if sched is not None:  # projection is diagonal: remap, never reconcile
        (t_row,) = sched.map_diagonal_qubits(nsv, (t_row,))
    amps = M.project_statevec(qureg.amps, n=nsv, target=t_row, outcome=outcome)
    if qureg.is_density_matrix:
        if sched is not None:
            (t_col,) = sched.map_diagonal_qubits(nsv, (t_col,))
        amps = M.project_statevec(amps, n=nsv, target=t_col, outcome=outcome)
    qureg.put(amps)
    _record(qureg, f"applyProjector({outcome}) on q[{target}]")


# ---------------------------------------------------------------------------
# phase functions (QuEST.h:6407-7179; kernels in ops.phasefunc)
# ---------------------------------------------------------------------------

def _phase_func_apply(qureg, qubits_flat, reg_sizes, encoding, coeffs, exponents,
                      terms_per_reg, override_inds, override_phases, func,
                      multi_var=False):
    V.validate_num_subregisters(len(reg_sizes), func)
    V.validate_multi_reg_bit_encoding(reg_sizes, encoding, func)
    for m, off in zip(reg_sizes, np.cumsum([0] + list(reg_sizes))[:-1]):
        V.validate_multi_targets(qureg, qubits_flat[off:off + m], func)
    n_ovr = len(override_phases)
    V.validate_num_phase_func_overrides(
        sum(reg_sizes), n_ovr, single_var=len(reg_sizes) == 1, func=func)
    V.validate_phase_func_overrides(reg_sizes, encoding, override_inds, n_ovr, func)
    nsv = qureg.num_qubits_in_state_vec
    n = qureg.num_qubits_represented
    dt = qureg.dtype
    args = dict(
        reg_sizes=tuple(int(m) for m in reg_sizes),
        encoding=int(encoding),
        exponents=tuple(float(e) for e in exponents),
        num_terms_per_reg=tuple(int(t) for t in terms_per_reg),
        num_overrides=n_ovr,
    )
    coeffs_d = jnp.asarray(np.asarray(coeffs, dtype=np.float64), dtype=dt)
    ovr_i = jnp.asarray(np.asarray(override_inds, dtype=np.float64), dtype=dt)
    ovr_p = jnp.asarray(np.asarray(override_phases, dtype=np.float64), dtype=dt)
    # phase functions are pure index algebra over their qubits: under the
    # explicit scheduler they remap to physical coordinates (comm-free in
    # any deferred layout) instead of forcing reconciliation
    sched = _dist.active()
    row = tuple(int(q) for q in qubits_flat)
    if sched is not None:
        row = sched.map_diagonal_qubits(nsv, row)
    amps = PF.apply_poly_phase(qureg.amps, coeffs_d, ovr_i, ovr_p,
                               n=nsv, qubits=row, conj=False, **args)
    if qureg.is_density_matrix:
        shifted = tuple(int(q) + n for q in qubits_flat)
        if sched is not None:
            shifted = sched.map_diagonal_qubits(nsv, shifted)
        amps = PF.apply_poly_phase(amps, coeffs_d, ovr_i, ovr_p,
                                   n=nsv, qubits=shifted, conj=True, **args)
    qureg.put(amps)
    if qureg.qasm_log is not None:
        if not multi_var:
            qureg.qasm_log.record_phase_func(
                list(qubits_flat), encoding, list(coeffs), list(exponents),
                list(override_inds), list(override_phases))
        else:
            qureg.qasm_log.record_multi_var_phase_func(
                list(qubits_flat), list(reg_sizes), encoding, list(coeffs),
                list(exponents), list(terms_per_reg), list(override_inds),
                list(override_phases))


def applyPhaseFunc(qureg: Qureg, qubits, encoding, coeffs, exponents) -> None:
    """phase(r) = sum_t coeffs[t] r^exponents[t] on the sub-register value r
    (QuEST.h:6407)."""
    applyPhaseFuncOverrides(qureg, qubits, encoding, coeffs, exponents, [], [])


def applyPhaseFuncOverrides(qureg: Qureg, qubits, encoding, coeffs, exponents,
                            override_inds, override_phases) -> None:
    """(QuEST.h:6518)."""
    func = "applyPhaseFuncOverrides"
    V.validate_phase_func_terms(len(qubits), encoding, coeffs, exponents,
                                list(override_inds), len(override_phases), func)
    _phase_func_apply(qureg, list(qubits), [len(qubits)], encoding, coeffs,
                      exponents, [len(coeffs)], override_inds, override_phases, func)


def applyMultiVarPhaseFunc(qureg: Qureg, qubits_flat, num_qubits_per_reg, encoding,
                           coeffs, exponents, num_terms_per_reg) -> None:
    """(QuEST.h:6679)."""
    applyMultiVarPhaseFuncOverrides(qureg, qubits_flat, num_qubits_per_reg, encoding,
                                    coeffs, exponents, num_terms_per_reg, [], [])


def applyMultiVarPhaseFuncOverrides(qureg: Qureg, qubits_flat, num_qubits_per_reg,
                                    encoding, coeffs, exponents, num_terms_per_reg,
                                    override_inds, override_phases) -> None:
    """(QuEST.h:6761)."""
    func = "applyMultiVarPhaseFuncOverrides"
    V.validate_num_subregisters(len(num_qubits_per_reg), func)
    V._assert(sum(num_terms_per_reg) == len(coeffs) == len(exponents)
              and all(t > 0 for t in num_terms_per_reg),
              "Invalid number of terms in the phase function specified. Must be >0.",
              func)
    V.validate_multi_var_phase_func_terms(encoding, exponents, func)
    _phase_func_apply(qureg, list(qubits_flat), list(num_qubits_per_reg), encoding,
                      coeffs, exponents, list(num_terms_per_reg),
                      override_inds, override_phases, func, multi_var=True)


def applyNamedPhaseFunc(qureg: Qureg, qubits_flat, num_qubits_per_reg, encoding,
                        func_name) -> None:
    """(QuEST.h:6901)."""
    applyParamNamedPhaseFuncOverrides(qureg, qubits_flat, num_qubits_per_reg,
                                      encoding, func_name, [], [], [])


def applyNamedPhaseFuncOverrides(qureg: Qureg, qubits_flat, num_qubits_per_reg,
                                 encoding, func_name, override_inds,
                                 override_phases) -> None:
    """(QuEST.h:6974)."""
    applyParamNamedPhaseFuncOverrides(qureg, qubits_flat, num_qubits_per_reg,
                                      encoding, func_name, [],
                                      override_inds, override_phases)


def applyParamNamedPhaseFunc(qureg: Qureg, qubits_flat, num_qubits_per_reg,
                             encoding, func_name, params) -> None:
    """(QuEST.h:7104)."""
    applyParamNamedPhaseFuncOverrides(qureg, qubits_flat, num_qubits_per_reg,
                                      encoding, func_name, params, [], [])


def applyParamNamedPhaseFuncOverrides(qureg: Qureg, qubits_flat, num_qubits_per_reg,
                                      encoding, func_name, params,
                                      override_inds, override_phases) -> None:
    """(QuEST.h:7179)."""
    func = "applyParamNamedPhaseFuncOverrides"
    reg_sizes = [int(m) for m in num_qubits_per_reg]
    V.validate_num_subregisters(len(reg_sizes), func)
    V.validate_phase_func_name(int(func_name), func)
    fn = phaseFunc(int(func_name))
    V.validate_num_regs_distance_phase_func(int(func_name), len(reg_sizes), func)
    V.validate_multi_reg_bit_encoding(reg_sizes, encoding, func)
    V.validate_num_named_phase_func_params(int(func_name), len(reg_sizes),
                                           len(params or []), func)
    n_ovr = len(override_phases)
    V.validate_num_phase_func_overrides(
        sum(reg_sizes), n_ovr, single_var=len(reg_sizes) == 1, func=func)
    V.validate_phase_func_overrides(reg_sizes, encoding, override_inds, n_ovr, func)
    for m, off in zip(reg_sizes, np.cumsum([0] + reg_sizes)[:-1]):
        V.validate_multi_targets(qureg, list(qubits_flat)[off:off + m], func)

    nsv = qureg.num_qubits_in_state_vec
    n = qureg.num_qubits_represented
    dt = qureg.dtype
    # pad params so indexed accesses (params[2+r] etc.) are always in range
    padded = list(map(float, params)) + [0.0] * (2 + 2 * len(reg_sizes))
    params_d = jnp.asarray(padded, dtype=dt)
    ovr_i = jnp.asarray(np.asarray(override_inds, dtype=np.float64), dtype=dt)
    ovr_p = jnp.asarray(np.asarray(override_phases, dtype=np.float64), dtype=dt)
    args = dict(reg_sizes=tuple(reg_sizes), encoding=int(encoding),
                func_name=int(func_name), num_params=len(params),
                num_overrides=n_ovr)
    sched = _dist.active()
    row = tuple(int(q) for q in qubits_flat)
    if sched is not None:
        row = sched.map_diagonal_qubits(nsv, row)
    amps = PF.apply_named_phase(qureg.amps, params_d, ovr_i, ovr_p,
                                n=nsv, qubits=row, conj=False, **args)
    if qureg.is_density_matrix:
        shifted = tuple(int(q) + n for q in qubits_flat)
        if sched is not None:
            shifted = sched.map_diagonal_qubits(nsv, shifted)
        amps = PF.apply_named_phase(amps, params_d, ovr_i, ovr_p,
                                    n=nsv, qubits=shifted, conj=True, **args)
    qureg.put(amps)
    if qureg.qasm_log is not None:
        qureg.qasm_log.record_named_phase_func(
            list(qubits_flat), reg_sizes, encoding, int(func_name),
            list(params) if params else [], list(override_inds),
            list(override_phases))


# ---------------------------------------------------------------------------
# DiagonalOp (QuEST.h:1033-1314) -- full 2^N diagonal, sharded like a Qureg
# ---------------------------------------------------------------------------

def createDiagonalOp(num_qubits: int, env) -> DiagonalOp:
    """Allocate an all-zero 2^N diagonal operator in the env (QuEST.h:175)."""
    func = "createDiagonalOp"
    V.validate_num_qubits(num_qubits, func)
    V.validate_num_amps_fit_type(num_qubits, False, func)
    if getattr(env, "requires_sharding", False):
        V.validate_diag_op_fits_devices(num_qubits, env.mesh.size, func)
    from . import precision
    dt = precision.real_dtype(None)

    def alloc():
        elems = jnp.zeros((2, 1 << num_qubits), dtype=dt)
        sharding = env.sharding(1 << num_qubits)
        if sharding is not None:
            import jax
            elems = jax.device_put(elems, sharding)
        return elems

    return DiagonalOp(num_qubits, V.validate_diag_op_allocation(alloc, func))


def destroyDiagonalOp(op: DiagonalOp, env=None) -> None:
    """Release a DiagonalOp's device buffers (QuEST.h:176)."""
    try:
        op.elems.delete()
    except Exception:
        pass
    op.elems = None


def syncDiagonalOp(op: DiagonalOp) -> None:
    """No-op: elems already live on device (reference copies host->GPU,
    QuEST_gpu_common.cu:508-640)."""


def initDiagonalOp(op: DiagonalOp, reals, imags) -> None:
    """Overwrite a DiagonalOp's elements from real/imag arrays (QuEST.h:178)."""
    func = "initDiagonalOp"
    V.validate_diag_op_init(op, func)
    reals = np.asarray(reals).reshape(-1)
    imags = np.asarray(imags).reshape(-1)
    V._assert(reals.size == (1 << op.num_qubits) and imags.size == (1 << op.num_qubits),
              "Invalid number of elements.", func)
    new = jnp.asarray(np.stack([reals, imags]), dtype=op.elems.dtype)
    # preserve the mesh sharding createDiagonalOp established
    import jax
    if hasattr(op.elems, "sharding") and op.elems.sharding is not None:
        new = jax.device_put(new, op.elems.sharding)
    op.elems = new


def setDiagonalOpElems(op: DiagonalOp, start_ind: int, reals, imags, num_elems: int) -> None:
    """Overwrite a slice of a DiagonalOp's elements (QuEST.h:181)."""
    func = "setDiagonalOpElems"
    V.validate_diag_op_init(op, func)
    V.validate_num_elems(op, start_ind, num_elems, func)
    vals = np.stack([np.asarray(reals).reshape(-1)[:num_elems],
                     np.asarray(imags).reshape(-1)[:num_elems]])
    op.elems = op.elems.at[:, start_ind:start_ind + num_elems].set(
        jnp.asarray(vals, dtype=op.elems.dtype))


def initDiagonalOpFromPauliHamil(op: DiagonalOp, hamil: PauliHamil) -> None:
    """Hamil of only I/Z terms -> diagonal elements (QuEST.h:1158)."""
    func = "initDiagonalOpFromPauliHamil"
    V.validate_pauli_hamil(hamil, func)
    V.validate_diag_op_init(op, func)
    V.validate_hamil_matches_diag_op(hamil, op, func)
    V.validate_diag_pauli_hamil(hamil, func)
    n = op.num_qubits
    idx = np.arange(1 << n, dtype=np.int64)
    diag = np.zeros(1 << n, dtype=np.float64)
    for t in range(hamil.num_sum_terms):
        sign = np.ones(1 << n, dtype=np.float64)
        for q in range(n):
            if hamil.pauli_codes[t, q] == 3:
                sign *= 1.0 - 2.0 * ((idx >> q) & 1)
        diag += hamil.term_coeffs[t] * sign
    initDiagonalOp(op, diag, np.zeros_like(diag))


def createDiagonalOpFromPauliHamilFile(path: str, env) -> DiagonalOp:
    """(QuEST.h:1201)."""
    from .datatypes import createPauliHamilFromFile
    hamil = createPauliHamilFromFile(path)
    op = createDiagonalOp(hamil.num_qubits, env)
    initDiagonalOpFromPauliHamil(op, hamil)
    return op


def applyDiagonalOp(qureg: Qureg, op: DiagonalOp) -> None:
    """|psi> -> D|psi>; rho -> D rho (QuEST.h:1282)."""
    func = "applyDiagonalOp"
    V.validate_diag_op_init(op, func)
    V.validate_diag_op_matches_qureg(qureg, op, func)
    elems = op.elems.astype(qureg.dtype)
    if qureg.is_density_matrix:
        qureg.put(D.apply_full_diagonal_to_density(
            qureg.amps, elems, n=qureg.num_qubits_represented))
    else:
        qureg.put(D.apply_full_diagonal(qureg.amps, elems))
    _record(qureg, "applyDiagonalOp")


def calcExpecDiagonalOp(qureg: Qureg, op: DiagonalOp) -> complex:
    """(QuEST.h:1314)."""
    func = "calcExpecDiagonalOp"
    V.validate_diag_op_init(op, func)
    V.validate_diag_op_matches_qureg(qureg, op, func)
    elems = op.elems.astype(qureg.dtype)
    if qureg.is_density_matrix:
        re, im = R.expec_diag_op_density(qureg.amps, elems,
                                         n=qureg.num_qubits_represented)
    else:
        re, im = R.expec_diag_op_statevec(qureg.amps, elems)
    return complex(float(re), float(im))


def applySubDiagonalOp(qureg: Qureg, targets, op: SubDiagonalOp) -> None:
    """D on a qubit subset, without unitarity checks and without the bra-side
    shadow (QuEST.h:1513)."""
    func = "applySubDiagonalOp"
    V.validate_multi_targets(qureg, targets, func)
    V._assert(op.num_qubits == len(targets),
              "The diagonal operator must act upon the same number of qubits as specified.", func)
    d = cplx.from_complex(np.asarray(op.elems), qureg.dtype)
    sched = _dist.active()
    apply_d = sched.apply_diagonal if sched is not None else D.apply_diagonal
    qureg.put(apply_d(qureg.amps, d, n=qureg.num_qubits_in_state_vec,
                      targets=tuple(targets)))
    _record(qureg, "applySubDiagonalOp")


def applyGateSubDiagonalOp(qureg: Qureg, targets, op: SubDiagonalOp) -> None:
    """D with the conjugated bra-side shadow on density matrices (QuEST.h:1473)."""
    func = "applyGateSubDiagonalOp"
    V.validate_multi_targets(qureg, targets, func)
    V._assert(op.num_qubits == len(targets),
              "The diagonal operator must act upon the same number of qubits as specified.", func)
    n = qureg.num_qubits_represented
    nsv = qureg.num_qubits_in_state_vec
    d = cplx.from_complex(np.asarray(op.elems), qureg.dtype)
    sched = _dist.active()
    apply_d = sched.apply_diagonal if sched is not None else D.apply_diagonal
    amps = apply_d(qureg.amps, d, n=nsv, targets=tuple(targets))
    if qureg.is_density_matrix:
        amps = apply_d(amps, d, n=nsv,
                       targets=tuple(q + n for q in targets), conj=True)
    qureg.put(amps)
    _record(qureg, "applyGateSubDiagonalOp")
