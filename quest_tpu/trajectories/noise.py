"""Tapeable trajectory-noise entry: the channel site of an unraveled tape.

``applyTrajectoryKraus`` is the single recordable primitive every unraveled
channel lowers to (trajectories.unravel maps the built-in mix* table onto
it). Its Kraus stack, targets and site index are baked tape *structure*;
the ``seed`` argument is a runtime value slot of kind ``'seed'``
(engine/params._LIFTABLE) -- a plain int or a :class:`~quest_tpu.engine.P`
placeholder both lift, so plan structure and the executable-cache
fingerprint never depend on the seed.

On the fused path these entries are unconditional barriers
(fusion.capture returns None for them -- the drawn operator only exists at
apply time), exactly like PR 4's param barriers; on the deferred scheduler
they reconcile first (the module is not in circuits._DEFER_SAFE_MODULES).
"""

from __future__ import annotations

import numpy as np

from typing import TYPE_CHECKING, Iterable, Sequence

from .. import validation as V
from ..validation import QuESTError
from .sample import apply_traj_kraus

if TYPE_CHECKING:
    from ..registers import Qureg

__all__ = ["applyTrajectoryKraus"]


def applyTrajectoryKraus(qureg: Qureg, targets: Iterable[int],
                         ops: Sequence[np.ndarray], seed: object,
                         site: int = 0) -> None:
    """Sample one Kraus operator of ``ops`` on ``targets`` with the
    trajectory's PRNG stream and apply it renormalised to the state-vector
    ``qureg`` (density registers take the exact channel via mix* instead).

    ``ops``: the channel's CPTP Kraus set (host matrices, baked structure).
    ``seed``: the per-trajectory uint32 seed -- recordable as ``P("seed")``
    so the engine batches T trajectories into one vmap dispatch.
    ``site``: static per-site counter (``fold_in`` stream split); distinct
    channel sites of one tape must carry distinct sites.
    """
    func = "applyTrajectoryKraus"
    if qureg.is_density_matrix:
        raise QuESTError(
            f"{func} unravels noise over pure states; density registers "
            "apply the exact channel via the mix* family instead")
    targets = tuple(int(t) for t in targets)
    V.validate_multi_targets(qureg, targets, func)
    ops = [np.asarray(op) for op in ops]
    V.validate_kraus_ops(ops, len(targets), qureg.eps, func, check_cptp=True)
    amps = apply_traj_kraus(qureg.amps, ops,
                            n=qureg.num_qubits_in_state_vec,
                            targets=targets, seed=seed, site=int(site))
    qureg.put(amps)
    if qureg.qasm_log is not None:
        qureg.qasm_log.record_comment(
            f"trajectoryKraus site {int(site)} on qubits {list(targets)} "
            f"({len(ops)} ops)")


# the drawn operator is assembled at apply time from the runtime seed --
# there is never a spy-capturable static event, even for a constant seed
applyTrajectoryKraus._fusion_barrier = True
