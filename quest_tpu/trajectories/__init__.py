"""Quantum-trajectory noise engine: noisy circuits at state-vector cost.

Unravels the decoherence channels of a density-matrix tape into stochastic
pure-state trajectories (the qsim Monte-Carlo-wavefunction technique,
arXiv:2111.02396) and runs the ensemble as ONE fixed-shape batched program
through the serving engine's vmap-over-params batcher: channel sites carry
a runtime uint32 seed slot (engine/params kind ``'seed'``), so T
trajectories compile once and replay with T independent counter-based PRNG
streams -- branch-free selection keeps plan structure value-independent,
the same invariant PR 4 proved for param barriers.

Surface:

- :func:`unravel` -- density tape -> trajectory tape (shared seed Param)
- :func:`noise.applyTrajectoryKraus` -- the recordable channel site
- :func:`run_ensemble` -- T seeds through one Engine, ``TrajectoryResult``
- :func:`ensemble_density` -- small-n oracle-comparison helper
- the canonical channel table both noise routes share lives in
  :mod:`quest_tpu.channels`

docs/trajectories.md carries the math, the seeding contract and the
when-to-prefer table; the QT501/QT502 diagnostics band covers the env knob
and non-CPTP hazards.
"""

from .ensemble import (DEFAULT_TRAJECTORIES, SEED_PARAM, TrajectoryResult,
                       ensemble_density, run_ensemble,
                       trajectory_count_default, unravel)
from .noise import applyTrajectoryKraus
from .sample import apply_traj_kraus

__all__ = [
    "unravel", "run_ensemble", "ensemble_density", "TrajectoryResult",
    "trajectory_count_default", "applyTrajectoryKraus", "apply_traj_kraus",
    "DEFAULT_TRAJECTORIES", "SEED_PARAM",
]
