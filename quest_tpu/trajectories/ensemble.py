"""Unravel a noisy circuit and run its trajectory ensemble through the Engine.

``unravel`` rewrites a density-matrix tape (gates + mix* channels) into a
state-vector tape whose channel sites are :func:`noise.applyTrajectoryKraus`
entries sharing ONE named seed Param; ``run_ensemble`` then executes T
trajectories as T parameter bindings of that single structure through
:class:`~quest_tpu.engine.Engine` -- the engine's vmap-over-params batcher
stacks the seed lanes, so the whole ensemble is one fixed-shape compiled
program (cuQuantum-style batched ensemble apply, arXiv:2308.01999), riding
the plan/executable cache and the sharded route unchanged.

Cost: a trajectory is a state vector, so a T-trajectory ensemble at n
qubits costs T * 2^n amplitudes against the density route's 4^n -- at 20q
with T=256 that is 64x fewer amplitudes than one density register, and it
opens sizes (20q+) where no density matrix fits at all. The price is
statistical: observables converge at 1/sqrt(T) (docs/trajectories.md has
the when-to-prefer table).
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass
from typing import TYPE_CHECKING, Iterable

import numpy as np

from .. import channels as _channels
from .. import telemetry
from ..circuits import Circuit
from ..engine.params import _SEED, Param
from ..validation import QuESTError
from . import noise

if TYPE_CHECKING:
    from ..environment import QuESTEnv

__all__ = ["unravel", "run_ensemble", "ensemble_density",
           "trajectory_count_default", "TrajectoryResult",
           "DEFAULT_TRAJECTORIES", "SEED_PARAM"]

#: ensemble size when neither an argument nor QUEST_TRAJECTORIES says
#: otherwise -- 64 keeps the 1/sqrt(T) error near 0.125 at interactive cost.
DEFAULT_TRAJECTORIES = 64

#: the Param name `unravel` records its seed slot under.
SEED_PARAM = "traj_seed"

#: general Kraus mix* entries that unravel directly (their operator lists
#: are already explicit on the tape).
_KRAUS_MIX = {"mixKrausMap", "mixTwoQubitKrausMap", "mixMultiQubitKrausMap"}

#: entries no unraveling exists for: non-trace-preserving maps have no
#: probability interpretation (the sampler's p_k would be biased -- the
#: same hazard tapelint flags as QT502), and mixDensityMatrix needs a
#: second register.
_UNRAVELABLE = {"mixNonTPKrausMap", "mixNonTPTwoQubitKrausMap",
                "mixNonTPMultiQubitKrausMap", "mixDensityMatrix"}

_ENV_WARNED: set = set()


def trajectory_count_default() -> int:
    """Ensemble size from ``QUEST_TRAJECTORIES`` (malformed or sub-1 values
    warn once as QT501 and fall back to ``DEFAULT_TRAJECTORIES``)."""
    from ..analysis.diagnostics import parse_env_int
    return parse_env_int("QUEST_TRAJECTORIES", DEFAULT_TRAJECTORIES,
                         minimum=1, code="QT501", warned=_ENV_WARNED,
                         noun="trajectory count")


def _bound_args(fn, args, kwargs):
    """The entry's arguments by parameter name (qureg bound to None)."""
    sig = inspect.signature(fn)
    ba = sig.bind(None, *args, **kwargs)
    ba.apply_defaults()
    return ba.arguments


def _channel_site(name, fn, args, kwargs):
    """(table_key, targets, kraus_ops) of one recorded channel entry."""
    got = _bound_args(fn, args, kwargs)
    if name in _channels.MIX_CHANNELS:
        key = _channels.MIX_CHANNELS[name]
        spec = _channels.CHANNELS[key]
        if spec.num_targets == 1:
            targets = (int(got["target"]),)
        else:
            targets = (int(got["q1"]), int(got["q2"]))
        if key == "pauli":
            probs = (float(got["px"]), float(got["py"]), float(got["pz"]))
        else:
            probs = (float(got["prob"]),)
        return key, targets, tuple(_channels.kraus_ops(key, *probs))
    # explicit Kraus entries
    if name == "mixKrausMap":
        targets = (int(got["target"]),)
    elif name == "mixTwoQubitKrausMap":
        targets = (int(got["q1"]), int(got["q2"]))
    else:
        targets = tuple(int(t) for t in got["targets"])
    ops = tuple(np.asarray(op, dtype=np.complex128) for op in got["ops"])
    return "kraus", targets, ops


def unravel(circuit: Circuit,
            seed: Param | int | None = None) -> Circuit:
    """Rewrite a noisy (typically density-matrix) circuit into its
    trajectory form: every built-in mix* channel and explicit CPTP Kraus
    entry becomes an :func:`noise.applyTrajectoryKraus` site over a pure
    state; every other entry passes through unchanged (the gate functions
    branch on the register kind themselves).

    All sites share one seed value slot (``seed``, default
    ``P("traj_seed")``) and carry consecutive static ``site`` indices, so
    one uint32 per trajectory drives an independent counter-based stream at
    every site. Non-trace-preserving maps (mixNonTP*) and
    ``mixDensityMatrix`` have no unraveling and raise."""
    if seed is None:
        seed = Param(SEED_PARAM)
    out = Circuit(circuit.num_qubits, is_density_matrix=False)
    site = 0
    for fn, args, kwargs in circuit._tape:
        name = getattr(fn, "__name__", "")
        if name in _UNRAVELABLE:
            raise QuESTError(
                f"cannot unravel '{name}': non-trace-preserving maps have "
                "no trajectory probability interpretation (QT502)" if
                name != "mixDensityMatrix" else
                "cannot unravel 'mixDensityMatrix': it mixes in a second "
                "register, not a Kraus channel")
        if name in _channels.MIX_CHANNELS or name in _KRAUS_MIX:
            key, targets, ops = _channel_site(name, fn, args, kwargs)
            out.append(noise.applyTrajectoryKraus, targets, ops, seed,
                       site=site)
            telemetry.inc("trajectory_channels_total", channel=key)
            site += 1
        else:
            out.append(fn, *args, **kwargs)
    return out


def ensemble_density(states: np.ndarray) -> np.ndarray:
    """The ensemble-mean density matrix (2^n, 2^n complex) of a stack of
    planar trajectory states (T, 2, 2^n) -- the small-n oracle-comparison
    helper; rho[i, j] = mean_t psi_t[i] conj(psi_t[j])."""
    arr = np.asarray(states, dtype=np.float64)
    psi = arr[:, 0, :] + 1j * arr[:, 1, :]
    return psi.T @ psi.conj() / psi.shape[0]


@dataclass(frozen=True)
class TrajectoryResult:
    """One executed ensemble: ``states`` is the (T, 2, 2^n) planar stack in
    seed order, ``seeds`` the per-trajectory uint32 seeds, ``seed_name``
    the bound Param. ``density()`` gives the ensemble-mean density matrix
    (small n only: it materialises 4^n complex entries).

    When the ensemble sampled on device (``run_ensemble(..., shots=S)``),
    ``shot_tables`` is the (T, S) int32 outcome stack and ``states`` is
    None -- the 2^n trajectory states never left the device."""
    states: np.ndarray | None
    seeds: tuple
    seed_name: str
    shot_tables: np.ndarray | None = None

    @property
    def num_trajectories(self) -> int:
        return len(self.seeds)

    def density(self) -> np.ndarray:
        if self.states is None:
            raise QuESTError(
                "TrajectoryResult.density() needs the trajectory states; "
                "this ensemble sampled on device (shots=...) and only the "
                "shot tables crossed to the host")
        return ensemble_density(self.states)


#: the static sampling ``site`` of an ensemble's terminal shot stage --
#: far above any tape's channel-site indices, so the shot stream never
#: collides with a trajectory Kraus stream sharing the same uint32 seed.
_SHOT_SITE = 1 << 16


def _shot_finalize(*, n: int, targets: tuple, shots: int, shot_seed: int):
    """A cached ``finalize(amps)`` drawing the per-trajectory shot table on
    device (the Engine finalize hook). The draw uniforms are SHARED across
    the vmap lanes of a batch (one static ``shot_seed``): common random
    numbers -- each trajectory's table is still an unbiased sample of its
    own outcome distribution, and cross-trajectory variance shrinks."""
    from ..engine import cache as _ec
    from ..sampling import sampler as _sampler
    key = ("ensemble_shot_finalize", n, targets, int(shots),
           int(shot_seed))

    def build():
        def finalize(amps):
            return _sampler.sample_statevec(
                amps, n=n, targets=targets, shots=int(shots),
                seed=int(shot_seed), site=_SHOT_SITE)

        return finalize

    return _ec.executables().get_or_create(key, build)


def run_ensemble(circuit: Circuit, num_trajectories: int | None = None, *,
                 env: QuESTEnv | None = None,
                 seeds: Iterable[int] | None = None, base_seed: int = 0,
                 params: dict | None = None,
                 max_batch: int | None = None,
                 precision_code: int | None = None,
                 initial: object = "zero",
                 timeout: float | None = None,
                 shots: int | None = None,
                 shot_targets=None,
                 shot_seed: int = 0) -> TrajectoryResult:
    """Execute a trajectory ensemble of ``circuit`` through the serving
    engine: one Engine per call, T = ``num_trajectories`` (default: the
    QUEST_TRAJECTORIES count) seed bindings submitted atomically so the
    vmap batcher coalesces them into ceil(T / max_batch) fixed-shape
    dispatches of ONE compiled program.

    ``circuit`` may be the density form (it is unraveled here) or an
    already-unraveled tape carrying exactly one named seed Param. ``seeds``
    overrides the default ``base_seed + t`` stream ids; ``params`` supplies
    any additional named Params the tape carries. Replaying with the same
    seeds is bit-identical -- sharded or not, f32 or f64/df.

    ``shots`` (round 19): sample S outcomes per trajectory ON DEVICE
    (over ``shot_targets``, default all qubits, seeded by ``shot_seed``)
    instead of returning the states -- the sampler composes into the
    batched program via the Engine ``finalize`` hook, so a T-trajectory
    S-shot ensemble moves T*S int32 words to the host, never T*2^n
    amplitudes. The result's ``shot_tables`` is the (T, S) stack and
    ``states`` is None."""
    from ..engine import Engine

    if circuit.is_density_matrix:
        circuit = unravel(circuit)
    lifted = circuit.lifted()
    seed_names = sorted({s.name for s in lifted.slots
                         if s.kind == _SEED and s.name is not None})
    if len(seed_names) != 1:
        raise QuESTError(
            f"run_ensemble needs exactly one named seed Param on the tape, "
            f"found {seed_names or 'none'}; record channels via unravel() "
            f"(its sites share P({SEED_PARAM!r}))")
    seed_name = seed_names[0]
    if seeds is None:
        t_count = (int(num_trajectories) if num_trajectories is not None
                   else trajectory_count_default())
        if t_count < 1:
            raise QuESTError(
                f"num_trajectories must be >= 1, got {t_count}")
        seeds = [int(base_seed) + t for t in range(t_count)]
    else:
        seeds = [int(s) for s in seeds]
        if not seeds:
            raise QuESTError("seeds must be non-empty")
    sites = sum(1 for fn, _, _ in circuit._tape
                if getattr(fn, "__name__", "") == "applyTrajectoryKraus")
    finalize = None
    if shots is not None:
        if int(shots) < 1:
            raise QuESTError(f"shots must be >= 1, got {shots}")
        if shot_targets is None:
            shot_targets = tuple(range(circuit.num_qubits))
        shot_targets = tuple(int(t) for t in shot_targets)
        finalize = _shot_finalize(n=circuit.num_qubits,
                                  targets=shot_targets, shots=int(shots),
                                  shot_seed=int(shot_seed))
    mb = min(len(seeds), max_batch) if max_batch else len(seeds)
    eng = Engine(circuit, env, max_batch=mb, max_delay_ms=0.0,
                 precision_code=precision_code, initial=initial,
                 finalize=finalize)
    try:
        reqs = [dict(params or {}, **{seed_name: s}) for s in seeds]
        futs = eng.submit_many(reqs, timeout=timeout)
        results = np.stack([np.asarray(f.result()) for f in futs])
    finally:
        eng.close()
    telemetry.inc("trajectory_runs_total", len(seeds))
    telemetry.inc("trajectory_sites_total", sites * len(seeds))
    telemetry.inc("trajectory_ensembles_total")
    if finalize is not None:
        telemetry.inc("sample_shots_total", int(shots) * len(seeds))
        telemetry.set_gauge("sample_host_transfer_bytes",
                            int(results.nbytes))
    telemetry.event("trajectories.ensemble", trajectories=len(seeds),
                    sites=sites, max_batch=mb, sharded=eng.sharded,
                    shots=0 if shots is None else int(shots))
    if finalize is not None:
        return TrajectoryResult(states=None, seeds=tuple(seeds),
                                seed_name=seed_name, shot_tables=results)
    return TrajectoryResult(states=results, seeds=tuple(seeds),
                            seed_name=seed_name)
