"""Branch-free stochastic Kraus selection over a pure state.

The quantum-trajectory unraveling (qsim's approximate-noise technique,
arXiv:2111.02396): at a channel site with Kraus operators {K_k}, a
trajectory draws index k with probability p_k = <psi| K_k^dagger K_k |psi>
and continues in the renormalised state K_k|psi> / sqrt(p_k). The ensemble
mean of |psi><psi| over trajectories converges to the density-matrix
evolution at 1/sqrt(T).

Everything here must be *traceable with a value-independent structure*: the
selection runs inside the engine's one compiled vmap-over-params program, so
there is no branching on the drawn index. Instead:

- the selection probabilities come from ONE reduced-density-matrix pass over
  the target qubits (p_k = Tr(M_k rho_red) with M_k = K_k^dagger K_k baked
  host-side), not from applying each operator;
- the drawn index is the branch-free inverse-CDF count
  ``sum(u * norm >= cumsum(p))``;
- the selected operator is assembled by a one-hot contraction over the baked
  Kraus stack, with the 1/sqrt(p_k) renormalisation folded into the matrix
  itself -- one ordinary (non-unitary) ``ops.apply.apply_matrix`` pass
  applies it, riding the same sharded/grouped-transpose machinery as every
  gate.

The PRNG is counter-based (threefry): ``fold_in(PRNGKey(seed), site)``
gives every channel site its own stream from one per-trajectory uint32
seed, deterministic across shardings, devices and replays -- the
bit-identical-replay contract of docs/trajectories.md.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import apply as _apply

__all__ = ["kraus_probabilities", "traj_kraus_matrix", "apply_traj_kraus"]

#: probability floor for the folded renormalisation: a trajectory can only
#: reach a p_k this small through numerical cancellation (the CPTP check
#: bounds real channels away from it), so the clamp never biases sampling.
_P_FLOOR = 1e-30


def _targets_front(plane, n, targets):
    """One planar component (2^n,) reshaped/permuted to (d, rest) with the
    collapsed target index s = sum_j bit(targets[j]) << j -- targets[0] is
    the least-significant matrix bit, the apply_matrix convention."""
    t = len(targets)
    x = plane.reshape((2,) * n)
    # row-major reshape puts qubit q at axis (n-1-q)
    axes = [n - 1 - q for q in reversed(targets)]
    rest = [a for a in range(n) if a not in axes]
    x = jnp.transpose(x, axes + rest)
    return x.reshape(2 ** t, -1)


def kraus_probabilities(amps, mre, mim, *, n, targets):
    """p_k = Tr(M_k rho_red) for the whole Kraus stack in one reduction
    pass: ``amps`` is the planar (2, 2^n) state, ``mre``/``mim`` the baked
    real/imag parts of M_k = K_k^dagger K_k, shape (m, d, d). Returns the
    (m,) probability vector in the state's real dtype (sums to the current
    squared norm for a CPTP set)."""
    a = _targets_front(amps[0], n, targets)
    b = _targets_front(amps[1], n, targets)
    # rho_red[s,t] = R[s,t] + i I[s,t] over the d-dim target subspace
    r = a @ a.T + b @ b.T
    im = b @ a.T - a @ b.T
    mre = jnp.asarray(mre, dtype=amps.dtype)
    mim = jnp.asarray(mim, dtype=amps.dtype)
    # Re Tr(M rho) = sum_{s,t} Mre[t,s] R[s,t] - Mim[t,s] I[s,t]
    p = jnp.einsum("kts,st->k", mre, r) - jnp.einsum("kts,st->k", mim, im)
    return jnp.maximum(p, 0.0)


def traj_kraus_matrix(p, u, kre, kim, dtype):
    """The selected-and-renormalised Kraus operator as a planar (2, d, d)
    matrix, branch-free: ``p`` the (m,) probability vector, ``u`` a uniform
    [0,1) draw, ``kre``/``kim`` the baked (m, d, d) Kraus stack. Selection
    is norm-proportional (``u`` scaled by sum(p), so slight norm drift
    cannot push the draw off the table) and the 1/sqrt(p_k) renormalisation
    is folded into the returned matrix."""
    m = p.shape[0]
    cdf = jnp.cumsum(p)
    draw = u.astype(p.dtype) * cdf[-1]
    idx = jnp.minimum(jnp.sum((draw >= cdf).astype(jnp.int32)), m - 1)
    w = (jnp.arange(m) == idx).astype(dtype)
    p_sel = jnp.sum(w * p.astype(dtype))
    scale = jax.lax.rsqrt(jnp.maximum(p_sel, jnp.asarray(_P_FLOOR, dtype)))
    kre = jnp.asarray(kre, dtype=dtype)
    kim = jnp.asarray(kim, dtype=dtype)
    sel_re = jnp.einsum("k,kij->ij", w, kre) * scale
    sel_im = jnp.einsum("k,kij->ij", w, kim) * scale
    return jnp.stack([sel_re, sel_im])


def apply_traj_kraus(amps, kraus, *, n, targets, seed, site):
    """One trajectory step: sample a Kraus operator of ``kraus`` (a host
    list/stack of complex operators) on ``targets`` and apply it
    renormalised. ``seed`` is the per-trajectory uint32 (python int or
    traced device scalar -- the lifted seed slot); ``site`` is the static
    per-site counter that decorrelates channel sites within a trajectory.

    Structure (shapes, plan, branch layout) is independent of both the seed
    value and the drawn index -- the invariant that lets T trajectories
    share one compiled vmap program."""
    k = np.asarray([np.asarray(op, dtype=np.complex128) for op in kraus])
    m_ops = np.einsum("kli,klj->kij", k.conj(), k)  # K^dagger K, baked
    p = kraus_probabilities(amps, m_ops.real, m_ops.imag,
                            n=n, targets=tuple(targets))
    key = jax.random.fold_in(jax.random.PRNGKey(seed), site)
    # float32 draw regardless of route: f32/f64/df trajectories of one seed
    # walk the same Kraus path
    u = jax.random.uniform(key, dtype=jnp.float32)
    km = traj_kraus_matrix(p, u, k.real, k.imag, amps.dtype)
    from ..parallel import scheduler as _dist
    sched = _dist.active()
    apply_fn = sched.apply_matrix if sched else _apply.apply_matrix
    return apply_fn(amps, km, n=n, targets=tuple(targets))
