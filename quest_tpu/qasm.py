"""OPENQASM 2.0 circuit recording (reference: ``QuEST/src/QuEST_qasm.c``).

Pure host-side string accumulation, one logger per Qureg. The reference keeps
a growable char buffer (1 KiB, x2 growth, QuEST_qasm.c:35-107); Python lists
make that machinery unnecessary, but the recorded text format follows the
reference: the OPENQASM header (``:69-77``), the gate-name table (``:40-54``),
one-control gates as ``c<name>``, and explanatory comments for operations that
QASM 2.0 cannot express (multi-controlled gates, decoherence, init etc. --
the reference does the same, e.g. QuEST.c:670-674).
"""

from __future__ import annotations


#: gate-name table, mirroring qasmGateLabels (QuEST_qasm.c:40-54)
GATE_QASM_LABELS = {
    "sigmaX": "x", "sigmaY": "y", "sigmaZ": "z",
    "tGate": "t", "sGate": "s", "hadamard": "h",
    "rotateX": "Rx", "rotateY": "Ry", "rotateZ": "Rz",
    "unitary": "U", "phaseShift": "Rz", "swap": "swap", "sqrtSwap": "srswap",
}


class QASMLogger:
    def __init__(self, num_qubits: int):
        self.num_qubits = num_qubits
        self.recording = False
        self._lines: list[str] = []
        self._write_header()

    def _write_header(self):
        self._lines = [
            "OPENQASM 2.0;",
            f"qreg q[{self.num_qubits}];",
            f"creg c[{self.num_qubits}];",
        ]

    # -- control (startRecordingQASM etc., QuEST.h:3906-3965) ---------------

    def start(self):
        self.recording = True

    def stop(self):
        self.recording = False

    def clear(self):
        self._write_header()

    def printed(self) -> str:
        return "\n".join(self._lines) + "\n"

    def write_to_file(self, filename: str):
        with open(filename, "w") as f:
            f.write(self.printed())

    # -- recording ----------------------------------------------------------

    def _fmt_params(self, params) -> str:
        if not params:
            return ""
        return "(" + ",".join(f"{float(p):g}" for p in params) + ")"

    def record_gate(self, gate: str, targets, controls=(), params=()):
        """Record one gate application. Gates with 0 or 1 controls map to QASM
        (``h q[0];`` / ``ch q[1],q[0];``); others become comments, as the
        reference's qasm_recordMultiControlledGate fallback."""
        if not self.recording:
            return
        label = GATE_QASM_LABELS.get(gate, gate)
        p = self._fmt_params(params)
        qubits = list(controls) + list(targets)
        args = ",".join(f"q[{q}]" for q in qubits)
        if len(controls) == 0:
            self._lines.append(f"{label}{p} {args};")
        elif len(controls) == 1:
            self._lines.append(f"c{label}{p} {args};")
        else:
            self._lines.append(
                f"// {len(controls)}-controlled {label}{p} on {args} "
                "(not expressible in QASM 2.0)")

    def record_measurement(self, target: int):
        if self.recording:
            self._lines.append(f"measure q[{target}] -> c[{target}];")

    def record_init_zero(self):
        if self.recording:
            self._lines.append("// Initialised zero state")

    def record_comment(self, comment: str):
        """qasm_recordComment (QuEST_qasm.c): used for every op QASM cannot
        express -- init, decoherence, phase functions, QFT internals etc."""
        if self.recording:
            self._lines.append(f"// {comment}")
