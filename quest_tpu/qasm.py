"""OPENQASM 2.0 circuit recording (reference: ``QuEST/src/QuEST_qasm.c``).

Pure host-side string accumulation, one logger per Qureg. The reference keeps
a growable char buffer (1 KiB, x2 growth, QuEST_qasm.c:35-107); Python lists
make that machinery unnecessary, but the recorded *text* mirrors the
reference byte-for-byte:

- the OPENQASM header (QuEST_qasm.c:69-77) and gate-name table (:40-54);
- one ``c`` prefix per control qubit (CTRL_LABEL_PREF, addGateToQASM
  :133-173) -- so multi-controlled gates print ``ccU(...) q[a],q[b],q[t];``;
- ``unitary``/``compactUnitary``/``rotateAroundAxis`` and their controlled
  variants are decomposed to ZYZ angles and logged as ``U(rz2,ry,rz1)``
  (qasm_recordCompactUnitary / qasm_recordUnitary / qasm_recordAxisRotation,
  QuEST_qasm.c:191-310; angle math getZYZRotAnglesFromComplexPair and
  getComplexPairAndPhaseFromUnitary, QuEST_common.c:130-153);
- the global phase discarded by QASM's U(a,b,c) for *controlled* unitaries
  and controlled phase shifts is restored by a trailing ``Rz`` on the target
  plus an explanatory comment (QuEST_qasm.c:244-259,276-294,336-356);
- controls-on-0 are wrapped in NOTs (qasm_recordMultiStateControlledUnitary,
  QuEST_qasm.c:358-376);
- numbers are printed with REAL_QASM_FORMAT: %.8g single / %.14g double
  precision (QuEST_precision.h:47,62);
- operations QASM 2.0 cannot express become comments with the reference's
  exact wording (e.g. QuEST.c:670-674).
"""

from __future__ import annotations

import math

import numpy as np

from . import precision

#: gate-name table, mirroring qasmGateLabels (QuEST_qasm.c:40-54)
GATE_QASM_LABELS = {
    "sigmaX": "x", "sigmaY": "y", "sigmaZ": "z",
    "tGate": "t", "sGate": "s", "hadamard": "h",
    "rotateX": "Rx", "rotateY": "Ry", "rotateZ": "Rz",
    "unitary": "U", "phaseShift": "Rz", "swap": "swap", "sqrtSwap": "sqrtswap",
}


# ---------------------------------------------------------------------------
# decomposition helpers (QuEST_common.c:120-153)
# ---------------------------------------------------------------------------

def zyz_angles_from_complex_pair(alpha: complex, beta: complex):
    """U(alpha, beta) = Rz(rz2) Ry(ry) Rz(rz1), as
    getZYZRotAnglesFromComplexPair (QuEST_common.c:130-139)."""
    alpha, beta = complex(alpha), complex(beta)
    alpha_mag = abs(alpha)
    ry = 2.0 * math.acos(min(alpha_mag, 1.0))
    alpha_phase = math.atan2(alpha.imag, alpha.real)
    beta_phase = math.atan2(beta.imag, beta.real)
    rz2 = -alpha_phase + beta_phase
    rz1 = -alpha_phase - beta_phase
    return rz2, ry, rz1


def complex_pair_and_phase_from_unitary(u):
    """u = exp(i globalPhase) [[alpha, -conj(beta)], [beta, conj(alpha)]], as
    getComplexPairAndPhaseFromUnitary (QuEST_common.c:142-153)."""
    u = np.asarray(u, dtype=complex)
    r0c0_phase = math.atan2(u[0, 0].imag, u[0, 0].real)
    r1c1_phase = math.atan2(u[1, 1].imag, u[1, 1].real)
    global_phase = (r0c0_phase + r1c1_phase) / 2.0
    rot = complex(math.cos(global_phase), -math.sin(global_phase))
    alpha = u[0, 0] * rot
    beta = u[1, 0] * rot
    return alpha, beta, global_phase


def complex_pair_from_rotation(angle, axis):
    """Axis rotation -> (alpha, beta), as getComplexPairFromRotation
    (QuEST_common.c:120-127); delegates to the one implementation in
    :mod:`.matrices` so the QASM log always matches the applied gate."""
    from .matrices import rotation_around_axis_pair

    return rotation_around_axis_pair(angle, axis)


class QASMLogger:
    def __init__(self, num_qubits: int, dtype=None):
        self.num_qubits = num_qubits
        self.recording = False
        # REAL_QASM_FORMAT: %.8g single / %.14g double (QuEST_precision.h)
        prec = (precision.precision_for_dtype(dtype) if dtype is not None
                else precision.default_precision())
        self._fmt = "%.8g" if prec == 1 else "%.14g"
        self._lines: list[str] = []
        self._write_header()

    def _write_header(self):
        self._lines = [
            "OPENQASM 2.0;",
            f"qreg q[{self.num_qubits}];",
            f"creg c[{self.num_qubits}];",
        ]

    # -- control (startRecordingQASM etc., QuEST.h:3906-3965) ---------------

    def start(self):
        self.recording = True

    def stop(self):
        self.recording = False

    def clear(self):
        self._write_header()

    def printed(self) -> str:
        return "\n".join(self._lines) + "\n"

    def write_to_file(self, filename: str):
        with open(filename, "w") as f:
            f.write(self.printed())

    # -- low-level line assembly (addGateToQASM, QuEST_qasm.c:133-173) ------

    def _num(self, p) -> str:
        return self._fmt % float(p)

    def _add_gate(self, gate: str, controls, target, params=()):
        label = GATE_QASM_LABELS.get(gate, gate)
        line = "c" * len(controls) + label
        if params:
            line += "(" + ",".join(self._num(p) for p in params) + ")"
        line += " " + "".join(f"q[{c}]," for c in controls) + f"q[{int(target)}];"
        self._lines.append(line)

    # -- gate records (qasm_record*, QuEST_qasm.c:175-426) ------------------

    def record_gate(self, gate: str, target: int):
        if self.recording:
            self._add_gate(gate, (), target)

    def record_param_gate(self, gate: str, target: int, param: float):
        if self.recording:
            self._add_gate(gate, (), target, (param,))

    def record_compact_unitary(self, alpha, beta, target: int):
        if not self.recording:
            return
        rz2, ry, rz1 = zyz_angles_from_complex_pair(alpha, beta)
        self._add_gate("unitary", (), target, (rz2, ry, rz1))

    def record_unitary(self, u, target: int):
        if not self.recording:
            return
        alpha, beta, _ = complex_pair_and_phase_from_unitary(u)
        rz2, ry, rz1 = zyz_angles_from_complex_pair(alpha, beta)
        self._add_gate("unitary", (), target, (rz2, ry, rz1))

    def record_axis_rotation(self, angle, axis, target: int):
        if not self.recording:
            return
        alpha, beta = complex_pair_from_rotation(angle, axis)
        rz2, ry, rz1 = zyz_angles_from_complex_pair(alpha, beta)
        self._add_gate("unitary", (), target, (rz2, ry, rz1))

    def record_controlled_gate(self, gate: str, control: int, target: int):
        if self.recording:
            self._add_gate(gate, (control,), target)

    def record_controlled_param_gate(self, gate: str, control: int,
                                     target: int, param: float):
        if not self.recording:
            return
        self._add_gate(gate, (control,), target, (param,))
        # correct the global phase of controlled phase shifts
        # (qasm_recordControlledParamGate, QuEST_qasm.c:244-259)
        if gate == "phaseShift":
            self.record_comment("Restoring the discarded global phase of the "
                                "previous controlled phase gate")
            self._add_gate("rotateZ", (), target, (param / 2.0,))

    def record_controlled_compact_unitary(self, alpha, beta,
                                          control: int, target: int):
        if not self.recording:
            return
        rz2, ry, rz1 = zyz_angles_from_complex_pair(alpha, beta)
        self._add_gate("unitary", (control,), target, (rz2, ry, rz1))

    def record_controlled_unitary(self, u, control: int, target: int):
        """Additionally performs Rz on target to restore the global phase lost
        from u in QASM U(a,b,c) (qasm_recordControlledUnitary)."""
        if not self.recording:
            return
        self.record_multi_controlled_unitary(u, (control,), target,
                                             _kind="controlled")

    def record_controlled_axis_rotation(self, angle, axis,
                                        control: int, target: int):
        if not self.recording:
            return
        alpha, beta = complex_pair_from_rotation(angle, axis)
        rz2, ry, rz1 = zyz_angles_from_complex_pair(alpha, beta)
        self._add_gate("unitary", (control,), target, (rz2, ry, rz1))

    def record_multi_controlled_gate(self, gate: str, controls, target: int):
        if self.recording:
            self._add_gate(gate, tuple(controls), target)

    def record_multi_controlled_param_gate(self, gate: str, controls,
                                           target: int, param: float):
        if not self.recording:
            return
        self._add_gate(gate, tuple(controls), target, (param,))
        if gate == "phaseShift":
            self.record_comment("Restoring the discarded global phase of the "
                                "previous multicontrolled phase gate")
            self._add_gate("rotateZ", (), target, (param / 2.0,))

    def record_multi_controlled_unitary(self, u, controls, target: int,
                                        _kind: str = "multicontrolled"):
        if not self.recording:
            return
        alpha, beta, global_phase = complex_pair_and_phase_from_unitary(u)
        rz2, ry, rz1 = zyz_angles_from_complex_pair(alpha, beta)
        self._add_gate("unitary", tuple(controls), target, (rz2, ry, rz1))
        self.record_comment("Restoring the discarded global phase of the "
                            f"previous {_kind} unitary")
        self._add_gate("rotateZ", (), target, (global_phase,))

    def record_multi_state_controlled_unitary(self, u, controls, states,
                                              target: int):
        """Controls-on-0 wrapped in NOTs
        (qasm_recordMultiStateControlledUnitary, QuEST_qasm.c:358-376)."""
        if not self.recording:
            return
        self.record_comment(
            "NOTing some gates so that the subsequent unitary is controlled-on-0")
        for c, s in zip(controls, states):
            if s == 0:
                self._add_gate("sigmaX", (), c)
        self.record_multi_controlled_unitary(u, controls, target)
        self.record_comment(
            "Undoing the NOTing of the controlled-on-0 qubits of the previous unitary")
        for c, s in zip(controls, states):
            if s == 0:
                self._add_gate("sigmaX", (), c)

    def record_multi_controlled_multi_qubit_not(self, controls, targets):
        """(qasm_recordMultiControlledMultiQubitNot, QuEST_qasm.c:378-388)."""
        if not self.recording:
            return
        name = ("multiControlledMultiQubitNot" if controls
                else "multiQubitNot")
        self.record_comment(
            f"The following {len(targets)} gates resulted from a single "
            f"{name}() call")
        for t in targets:
            self._add_gate("sigmaX", tuple(controls), t)

    def record_measurement(self, target: int):
        if self.recording:
            self._lines.append(f"measure q[{target}] -> c[{target}];")

    # -- init records (QuEST_qasm.c:438-480) --------------------------------

    def record_init_zero(self):
        """INIT_ZERO_CMD: ``reset q;`` (QuEST_qasm.c:33,470-480)."""
        if self.recording:
            self._lines.append("reset q;")

    def record_init_plus(self):
        if self.recording:
            self.record_comment("Initialising state |+>")
            self.record_init_zero()
            self._lines.append("h q;")

    def record_init_classical(self, state_index: int):
        if not self.recording:
            return
        self.record_comment(f"Initialising state |{int(state_index)}>")
        self.record_init_zero()
        for q in range(self.num_qubits):
            if (int(state_index) >> q) & 1:
                self._add_gate("sigmaX", (), q)

    def record_comment(self, comment: str):
        """qasm_recordComment (QuEST_qasm.c): used for every op QASM cannot
        express -- init, decoherence, phase functions, QFT internals etc."""
        if self.recording:
            self._lines.append(f"// {comment}")

    # -- phase-function records (QuEST_qasm.c:485-868) ----------------------
    #
    # Phase functions aren't expressible in OPENQASM 2.0; the reference
    # renders them as structured comments -- the applied scalar in closed
    # form, the sub-register qubit lists, and any overrides -- and these
    # mirror that text.

    @staticmethod
    def _symbol(num_regs: int, ind: int) -> str:
        """getPhaseFuncSymbol (QuEST_qasm.c:553-566)."""
        if num_regs <= 7:
            return "xyztrvu"[ind]
        if num_regs <= 24:
            return "abcdefghjklmnpqrstuvwxyz"[ind]  # no i or o
        return f"x{ind}"

    def _term_text(self, coeff, exponent, symbol, first):
        mag = coeff if first else abs(coeff)
        if exponent > 0:
            return f"{self._num(mag)} {symbol}^{self._num(exponent)}"
        return f"{self._num(mag)} {symbol}^({self._num(exponent)})"

    def _add_regs_comment(self, qubits_flat, reg_sizes, encoding):
        """addMultiVarRegsToQASM (QuEST_qasm.c:568-596)."""
        enc = "an unsigned" if int(encoding) == 0 else "a two's complement"
        self.record_comment("  upon substates informed by qubits (under "
                            f"{enc} binary encoding)")
        off = 0
        for r, m in enumerate(reg_sizes):
            sym = f"|{self._symbol(len(reg_sizes), r)}>"
            qs = ", ".join(str(int(q)) for q in qubits_flat[off:off + m])
            self._lines.append(f"//     {sym} = {{{qs}}}")
            off += m

    def _add_overrides_comment(self, num_regs, override_inds, override_phases):
        """addMultiVarOverridesToQASM (QuEST_qasm.c:598-636)."""
        self.record_comment("  though with overrides")
        vi = 0
        for v in range(len(override_phases)):
            parts = []
            for r in range(num_regs):
                sym = self._symbol(num_regs, r)
                parts.append(f"{sym}={int(override_inds[vi])}")
                vi += 1
            p = float(override_phases[v])
            phase = (f"exp(i {self._num(p)})" if p >= 0
                     else f"exp(i ({self._num(p)}))")
            self._lines.append("//     |" + ", ".join(parts) + f"> -> {phase}")

    def record_phase_func(self, qubits, encoding, coeffs, exponents,
                          override_inds, override_phases):
        """qasm_recordPhaseFunc (QuEST_qasm.c:485-550)."""
        if not self.recording:
            return
        self.record_comment(
            "Here, applyPhaseFunc() multiplied a complex scalar of the form")
        terms = []
        for t, (c, e) in enumerate(zip(coeffs, exponents)):
            if t > 0:
                terms.append(" + " if float(coeffs[t]) > 0 else " - ")
            terms.append(self._term_text(float(c), float(e), "x", t == 0))
        self._lines.append("//     exp(i (" + "".join(terms) + "))")
        enc = "an unsigned" if int(encoding) == 0 else "a two's complement"
        self.record_comment("  upon every substate |x>, informed by qubits "
                            f"(under {enc} binary encoding)")
        self._lines.append(
            "//     {" + ", ".join(str(int(q)) for q in qubits) + "}")
        if override_phases:
            self.record_comment("  though with overrides")
            for i, p in zip(override_inds, override_phases):
                p = float(p)
                phase = (f"exp(i {self._num(p)})" if p >= 0
                         else f"exp(i ({self._num(p)}))")
                self.record_comment(f"    |{int(i)}> -> {phase}")

    def record_multi_var_phase_func(self, qubits_flat, reg_sizes, encoding,
                                    coeffs, exponents, terms_per_reg,
                                    override_inds, override_phases):
        """qasm_recordMultiVarPhaseFunc (QuEST_qasm.c:661-719)."""
        if not self.recording:
            return
        self.record_comment("Here, applyMultiVarPhaseFunc() multiplied a "
                            "complex scalar of the form")
        self.record_comment("    exp(i (")
        num_regs = len(reg_sizes)
        ti = 0
        for r in range(num_regs):
            sym = self._symbol(num_regs, r)
            line = " + " if float(coeffs[ti]) > 0 else " - "
            parts = [line]
            for t in range(terms_per_reg[r]):
                parts.append(self._term_text(
                    abs(float(coeffs[ti])), float(exponents[ti]), sym, False))
                if t < terms_per_reg[r] - 1:
                    parts.append(" + " if float(coeffs[ti + 1]) > 0 else " - ")
                ti += 1
            tail = " ))" if r == num_regs - 1 else ""
            self._lines.append("//         " + "".join(parts) + tail)
        self._add_regs_comment(qubits_flat, reg_sizes, encoding)
        if override_phases:
            self._add_overrides_comment(num_regs, override_inds,
                                        override_phases)

    def record_named_phase_func(self, qubits_flat, reg_sizes, encoding,
                                func_code, params, override_inds,
                                override_phases):
        """qasm_recordNamedPhaseFunc (QuEST_qasm.c:721-857)."""
        if not self.recording:
            return
        self.record_comment(
            "Here, applyNamedPhaseFunc() multiplied a complex scalar of form")
        f = int(func_code)
        num_regs = len(reg_sizes)
        syms = [self._symbol(num_regs, r) for r in range(num_regs)]

        def coeff_text():
            p0 = float(params[0])
            return (f"{self._num(p0)} " if p0 > 0
                    else f"({self._num(p0)}) ")

        body = "exp(i "
        if f in (0, 1, 2, 3, 4):        # NORM family
            if f in (1, 3, 4):
                body += coeff_text()
            body += {0: "sqrt(", 1: "sqrt(", 2: "1 / sqrt("}.get(f, "/ sqrt(")
            parts = []
            for r in range(num_regs):
                if f == 4:  # SCALED_INVERSE_SHIFTED_NORM
                    # the kernel applies sum (x_r - d_r)^2; the reference's
                    # <=24-register comment misprints this as (x^2 - d) --
                    # its own >24 branch and kernel use (x-d)^2, so record
                    # the form that matches the applied scalar
                    d = float(params[2 + r])
                    sign = "+" if d < 0 else "-"
                    parts.append(f"({syms[r]}{sign}{self._num(abs(d))})^2")
                else:
                    parts.append(f"{syms[r]}^2")
            body += " + ".join(parts) + "))"
        elif f in (5, 6, 7, 8):         # PRODUCT family
            if f in (6, 8):
                body += coeff_text()
            if f == 7:
                body += "1 / ("
            elif f == 8:
                body += "/ ("
            body += " ".join(syms[:-1]) + (" " if len(syms) > 1 else "")
            body += f"{syms[-1]})"
            if f in (7, 8):
                body += ")"
        elif f in (9, 10, 11, 12, 13, 14):  # DISTANCE family
            if f in (10, 12, 13, 14):
                body += coeff_text()
            body += {9: "sqrt(", 10: "sqrt(", 11: "1 / sqrt("}.get(f, "/ sqrt(")
            parts = []
            for r in range(0, num_regs, 2):
                if f == 13:  # SCALED_INVERSE_SHIFTED_DISTANCE
                    d = float(params[2 + r // 2])
                    sign = "+" if d < 0 else "-"
                    parts.append(f"({syms[r]}-{syms[r + 1]}{sign}"
                                 f"{self._num(abs(d))})^2")
                elif f == 14:  # SCALED_INVERSE_SHIFTED_WEIGHTED_DISTANCE:
                    # kernel: sum_r w_r (x_r - y_r - d_r)^2 with per-pair
                    # (factor, offset) params (ops/phasefunc.py:199-201);
                    # the reference renders no formula for this code at all
                    w = float(params[2 + r])
                    d = float(params[2 + r + 1])
                    sign = "+" if d < 0 else "-"
                    parts.append(f"{self._num(w)} ({syms[r]}-{syms[r + 1]}"
                                 f"{sign}{self._num(abs(d))})^2")
                else:
                    parts.append(f"({syms[r]}-{syms[r + 1]})^2")
            body += " + ".join(parts) + "))"
        self._lines.append("//     " + body)
        self._add_regs_comment(qubits_flat, reg_sizes, encoding)
        if override_phases:
            self._add_overrides_comment(num_regs, override_inds,
                                        override_phases)

    def fmt_real(self, value: float) -> str:
        """REAL_QASM_FORMAT rendering for comment text interpolation."""
        return self._num(value)
