"""Site guards: where fault injection, retry, watchdog, and degradation
meet.

Each hot path wraps its failable operation in one of these guards. When no
fault plan is installed (the production default) every guard is a direct
call -- one boolean read of :func:`faultinject.enabled` (plus one cached
watchdog-deadline read on the collective path) -- so the suite's
zero-new-fallbacks acceptance criterion holds by construction.

With a plan installed the guard visits its site (which may raise a typed
:class:`~quest_tpu.resilience.errors.InjectedFault`), retries transients
under the :mod:`.retry` policy, and on exhaustion takes the site's
documented exit:

- ``pallas.dispatch``    -- degrade along the EXISTING fallback lattice
  (the caller's engine-replay path), counted
  ``engine_fallback_total{reason=fault_degraded}``;
- ``exchange.collective`` -- fail closed with
  :class:`~quest_tpu.resilience.errors.QuESTRetryError` (a collective
  that stays down has no single-device rewrite at this layer); injected
  ``hang`` faults stall the launch past the watchdog deadline so the
  typed :class:`~quest_tpu.resilience.errors.QuESTHangError` path is
  provable;
- ``checkpoint.write``   -- retried ``io`` faults, torn/corrupt payload
  mutations applied post-write so verification (CRC) catches them;
- ``state.corrupt``      -- deterministic single-bit amplitude flips
  (:func:`corrupt_amps`) for the integrity sentinels to catch;
- sentinel breaches      -- :func:`sentinel_replay` drives the
  self-healing escalation lattice: retry the same route from the last
  verified state, then degrade (eager fallback replay), then fail closed
  with :class:`~quest_tpu.resilience.errors.QuESTIntegrityError`.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, TypeVar

from .. import telemetry
from . import faultinject, retry, watchdog
from .errors import (KernelCompileFault, QuESTIntegrityError,
                     QuESTRetryError, TransientFault)

if TYPE_CHECKING:
    import jax

__all__ = ["DEGRADED", "pallas_dispatch", "collective", "device_sync",
           "checkpoint_write", "segment_boundary", "corrupt_amps",
           "sentinel_replay"]

T = TypeVar("T")

#: sentinel returned by :func:`pallas_dispatch` when the degrade path ran
DEGRADED = object()


def pallas_dispatch(attempt: Callable[[], T],
                    degrade: Callable[[], object] | None = None,
                    *, site: str = "pallas.dispatch") -> T | object:
    """Run a kernel-route ``attempt``: retry injected transients; on a
    compile fault or retry exhaustion run ``degrade`` (the caller's
    engine-replay closure) and return :data:`DEGRADED`, counting the
    degradation on the existing fallback series."""
    if not faultinject.enabled():
        return attempt()

    def guarded() -> T:
        faultinject.check(site)
        return attempt()

    try:
        return retry.call_with_retry(guarded, site=site)
    except (KernelCompileFault, TransientFault) as e:
        if degrade is None:
            raise
        telemetry.inc("engine_fallback_total", reason="fault_degraded")
        telemetry.event("resilience.degrade", site=site,
                        kind=getattr(e, "kind", type(e).__name__))
        if telemetry.trace_on():
            telemetry.trace_event_current(
                "degrade", site=site,
                kind=getattr(e, "kind", type(e).__name__))
        degrade()
        return DEGRADED


def collective(fn: Callable[[], T], *, site: str = "exchange.collective",
               watched: bool = True) -> T:
    """Run a collective launch: retry injected transients (failing closed
    with a typed :class:`QuESTRetryError` when the budget is spent), and
    -- when ``QUEST_WATCHDOG_MS`` is armed and ``watched`` -- bound the
    launch by the watchdog deadline. Callers pass ``watched=False`` under
    ``jit`` tracing (jax trace state is thread-local, so a traced launch
    must not move to the watchdog's worker thread); an injected ``hang``
    then degenerates to the bounded :data:`watchdog.HANG_SLEEP_S` stall."""
    deadline = watchdog.deadline_s() if watched else None
    if not faultinject.enabled():
        if deadline is None:
            return fn()
        return watchdog.watched(fn, site=site, deadline=deadline)

    def guarded() -> T:
        kind = faultinject.fire(site)
        if kind == "transient":
            raise TransientFault(site, kind)
        return watchdog.watched(fn, site=site, deadline=deadline,
                                hang=(kind == "hang"))

    try:
        # QuESTHangError is NOT retryable: a deadline breach escalates to
        # the caller (engine quarantine / fail closed), never a silent
        # second eternal wait
        return retry.call_with_retry(guarded, site=site)
    except TransientFault as e:
        raise QuESTRetryError(
            f"collective at {site!r} still failing after retry budget "
            f"({e})", site) from e


def device_sync(fn: Callable[[], T], *, site: str = "engine.retire") -> T:
    """Run a completion-side device sync (the async dispatch pipeline's
    ring retire: ``jax.block_until_ready`` on an in-flight batch). With
    ``QUEST_WATCHDOG_MS`` armed the sync is deadline-bounded -- a wedged
    device surfaces as a typed :class:`QuESTHangError` attributed to the
    RING ENTRY being retired, never to the batch the host happens to be
    issuing. No retry: a device error at retire is the caller's bisection
    ladder's problem (the result buffers are gone either way). Injected
    ``hang`` faults at ``site`` stall past the watchdog deadline so the
    retire-time hang path is provable."""
    if not faultinject.enabled():
        return watchdog.watched(fn, site=site)
    kind = faultinject.fire(site)
    return watchdog.watched(fn, site=site, hang=(kind == "hang"))


def checkpoint_write(write: Callable[[], str],
                     *, site: str = "checkpoint.write") -> str:
    """Run a shard ``write`` (returning the final path): retry transient
    ``io`` faults, then apply any torn/corrupt payload fault to the
    written file -- the verified-load machinery must catch it."""
    if not faultinject.enabled():
        return write()

    def guarded() -> str:
        kind = faultinject.fire(site)
        if kind == "io":
            raise TransientFault(site, kind)
        path = write()
        if kind == "torn":
            size = max(1, _size(path) // 2)
            with open(path, "r+b") as f:
                f.truncate(size)
        elif kind == "corrupt":
            _flip_payload(path)
        return path

    return retry.call_with_retry(guarded, site=site)


def _size(path: str) -> int:
    import os
    return os.path.getsize(path)


def _flip_payload(path: str) -> None:
    """Flip one byte of the shard's AMPLITUDE payload and rewrite the file
    as a structurally valid npz. A raw byte flip at some file offset could
    land in zip framing or the start/stop members and verify clean; this
    manufactures exactly the failure the index CRC exists to catch -- a
    readable shard whose payload silently differs from what was indexed."""
    import numpy as np
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    amps = np.ascontiguousarray(data["amps"])
    raw = bytearray(amps.tobytes())
    raw[len(raw) // 2] ^= 0xFF
    data["amps"] = np.frombuffer(bytes(raw), dtype=amps.dtype).reshape(
        amps.shape)
    with open(path, "wb") as f:
        np.savez_compressed(f, **data)


def segment_boundary(cursor: int, checkpoint_dir: str) -> None:
    """Visit the inter-segment preemption site; raises
    :class:`~quest_tpu.resilience.errors.QuESTPreemptionError` carrying
    the resume cursor when the plan preempts here."""
    if not faultinject.enabled():
        return
    kind = faultinject.fire("segment.boundary")
    if kind == "preempt":
        from .errors import QuESTPreemptionError
        raise QuESTPreemptionError(
            f"injected preemption after checkpoint at tape cursor {cursor}"
            f" (resume from {checkpoint_dir!r})", "run_segmented",
            cursor=cursor, checkpoint_dir=checkpoint_dir)


def corrupt_amps(amps: jax.Array, *,
                 site: str = "state.corrupt") -> jax.Array:
    """Visit the SDC injection site over a planar ``(2, N)`` amplitude
    array: on a ``bitflip[<shard>]`` fire, flip the top exponent bit of
    one real-plane amplitude in the middle of the named shard's chunk
    (deterministic -- visit-counted and position-fixed, so the recovery
    replay is provably bit-identical) and return the corrupted array with
    the ORIGINAL sharding; otherwise return ``amps`` untouched. Flipping
    the exponent MSB turns even an exactly-zero amplitude into 2.0, so
    the norm leaves every tolerance band -- the sentinels cannot miss a
    flip that actually landed."""
    if not faultinject.enabled():
        return amps
    kind = faultinject.fire(site)
    if kind is None or not kind.startswith("bitflip"):
        return amps
    import numpy as np
    shard = int(kind[len("bitflip"):] or 0)
    host = np.array(amps)  # host copy; never mutate the live buffer
    mesh = getattr(getattr(amps, "sharding", None), "mesh", None)
    nshards = max(1, getattr(mesh, "size", 1) or 1)
    chunk = host.shape[-1] // nshards
    idx = (shard % nshards) * chunk + chunk // 2
    real = host[0].reshape(-1)
    if real.dtype == np.float64:
        view, bit = real.view(np.uint64), 62
    else:
        view, bit = real.view(np.uint32), 30
    view[idx] ^= np.asarray(1 << bit, dtype=view.dtype)
    telemetry.event("resilience.sdc_injected", site=site,
                    shard=shard % nshards, index=int(idx),
                    dtype=str(host.dtype))
    sharding = getattr(amps, "sharding", None)
    if sharding is None:
        return host
    import jax
    return jax.device_put(host, sharding)


def sentinel_replay(replay: Callable[[], T],
                    degrade: Callable[[], T] | None = None,
                    *, site: str = "segment.sentinel") -> T:
    """Drive the self-healing escalation lattice after an integrity
    breach. ``replay`` rolls the register back to the last verified state,
    re-runs the breached span on the SAME route, re-checks the sentinels
    and raises :class:`QuESTIntegrityError` if they breach again; it is
    retried under the :mod:`.retry` policy (transient SDC -- a one-off
    flip -- heals on the first replay). On exhaustion, ``degrade`` (an
    eager fallback-route replay from the same verified state) runs once;
    if even that breaches, the :class:`QuESTIntegrityError` propagates --
    fail closed, never serve a corrupt state. Outcomes count
    ``segmented_rollbacks_total{outcome=replayed|degraded|failed}``."""
    try:
        out = retry.call_with_retry(replay, site=site,
                                    retryable=(QuESTIntegrityError,))
        telemetry.inc("segmented_rollbacks_total", outcome="replayed")
        return out
    except QuESTIntegrityError as e:
        if degrade is None:
            telemetry.inc("segmented_rollbacks_total", outcome="failed")
            raise
        telemetry.inc("engine_fallback_total", reason="sentinel_degraded")
        telemetry.event("resilience.sentinel_degrade", site=site,
                        findings=len(getattr(e, "findings", ())))
        if telemetry.trace_on():
            telemetry.trace_event_current(
                "degrade", site=site, kind="sentinel",
                findings=len(getattr(e, "findings", ())))
        try:
            out = degrade()
        except QuESTIntegrityError:
            telemetry.inc("segmented_rollbacks_total", outcome="failed")
            raise
        telemetry.inc("segmented_rollbacks_total", outcome="degraded")
        return out
