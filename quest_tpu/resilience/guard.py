"""Site guards: where fault injection, retry, and degradation meet.

Each hot path wraps its failable operation in one of these guards. When no
fault plan is installed (the production default) every guard is a direct
call -- one boolean read of :func:`faultinject.enabled` -- so the suite's
zero-new-fallbacks acceptance criterion holds by construction.

With a plan installed the guard visits its site (which may raise a typed
:class:`~quest_tpu.resilience.errors.InjectedFault`), retries transients
under the :mod:`.retry` policy, and on exhaustion takes the site's
documented exit:

- ``pallas.dispatch``    -- degrade along the EXISTING fallback lattice
  (the caller's engine-replay path), counted
  ``engine_fallback_total{reason=fault_degraded}``;
- ``exchange.collective`` -- fail closed with
  :class:`~quest_tpu.resilience.errors.QuESTRetryError` (a collective
  that stays down has no single-device rewrite at this layer);
- ``checkpoint.write``   -- retried ``io`` faults, torn/corrupt payload
  mutations applied post-write so verification (CRC) catches them.
"""

from __future__ import annotations

from typing import Callable, TypeVar

from .. import telemetry
from . import faultinject, retry
from .errors import (KernelCompileFault, QuESTRetryError, TransientFault)

__all__ = ["DEGRADED", "pallas_dispatch", "collective", "checkpoint_write",
           "segment_boundary"]

T = TypeVar("T")

#: sentinel returned by :func:`pallas_dispatch` when the degrade path ran
DEGRADED = object()


def pallas_dispatch(attempt: Callable[[], T],
                    degrade: Callable[[], object] | None = None,
                    *, site: str = "pallas.dispatch"):
    """Run a kernel-route ``attempt``: retry injected transients; on a
    compile fault or retry exhaustion run ``degrade`` (the caller's
    engine-replay closure) and return :data:`DEGRADED`, counting the
    degradation on the existing fallback series."""
    if not faultinject.enabled():
        return attempt()

    def guarded() -> T:
        faultinject.check(site)
        return attempt()

    try:
        return retry.call_with_retry(guarded, site=site)
    except (KernelCompileFault, TransientFault) as e:
        if degrade is None:
            raise
        telemetry.inc("engine_fallback_total", reason="fault_degraded")
        telemetry.event("resilience.degrade", site=site,
                        kind=getattr(e, "kind", type(e).__name__))
        degrade()
        return DEGRADED


def collective(fn: Callable[[], T], *,
               site: str = "exchange.collective") -> T:
    """Run a collective launch: retry injected transients, fail closed
    with a typed :class:`QuESTRetryError` when the budget is spent."""
    if not faultinject.enabled():
        return fn()

    def guarded() -> T:
        faultinject.check(site)
        return fn()

    try:
        return retry.call_with_retry(guarded, site=site)
    except TransientFault as e:
        raise QuESTRetryError(
            f"collective at {site!r} still failing after retry budget "
            f"({e})", site) from e


def checkpoint_write(write: Callable[[], str],
                     *, site: str = "checkpoint.write") -> str:
    """Run a shard ``write`` (returning the final path): retry transient
    ``io`` faults, then apply any torn/corrupt payload fault to the
    written file -- the verified-load machinery must catch it."""
    if not faultinject.enabled():
        return write()

    def guarded() -> str:
        kind = faultinject.fire(site)
        if kind == "io":
            raise TransientFault(site, kind)
        path = write()
        if kind == "torn":
            size = max(1, _size(path) // 2)
            with open(path, "r+b") as f:
                f.truncate(size)
        elif kind == "corrupt":
            _flip_payload(path)
        return path

    return retry.call_with_retry(guarded, site=site)


def _size(path: str) -> int:
    import os
    return os.path.getsize(path)


def _flip_payload(path: str) -> None:
    """Flip one byte of the shard's AMPLITUDE payload and rewrite the file
    as a structurally valid npz. A raw byte flip at some file offset could
    land in zip framing or the start/stop members and verify clean; this
    manufactures exactly the failure the index CRC exists to catch -- a
    readable shard whose payload silently differs from what was indexed."""
    import numpy as np
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    amps = np.ascontiguousarray(data["amps"])
    raw = bytearray(amps.tobytes())
    raw[len(raw) // 2] ^= 0xFF
    data["amps"] = np.frombuffer(bytes(raw), dtype=amps.dtype).reshape(
        amps.shape)
    with open(path, "wb") as f:
        np.savez_compressed(f, **data)


def segment_boundary(cursor: int, checkpoint_dir: str) -> None:
    """Visit the inter-segment preemption site; raises
    :class:`~quest_tpu.resilience.errors.QuESTPreemptionError` carrying
    the resume cursor when the plan preempts here."""
    if not faultinject.enabled():
        return
    kind = faultinject.fire("segment.boundary")
    if kind == "preempt":
        from .errors import QuESTPreemptionError
        raise QuESTPreemptionError(
            f"injected preemption after checkpoint at tape cursor {cursor}"
            f" (resume from {checkpoint_dir!r})", "run_segmented",
            cursor=cursor, checkpoint_dir=checkpoint_dir)
