"""Preemption-safe segmented execution with verified checkpoints.

A fused :class:`~quest_tpu.fusion.FusePlan` tape is not interruptible at
arbitrary points: between a PallasRun's folded load swap and its store
swap the amplitudes live in a PERMUTED frame, and a snapshot taken there
is not a state the public API can name. The points where the frame
returns to identity -- exactly what ``analysis/plancheck`` (QT102/QT103)
proves exist before every non-plan item and at plan end -- are the legal
segment boundaries. :func:`segment_plan` recomputes them here by symbolic
frame replay of the tape's swap blocks (the same bit-block composition
plancheck walks).

:func:`run_segmented` executes the tape segment by segment; at each
selected boundary it writes one checkpoint GENERATION: a full
:func:`~quest_tpu.checkpoint.saveQureg` snapshot (amplitudes + env seeds
+ MT19937 RNG cursor, per-shard CRC32 in the index) plus a
``segment.json`` manifest recording the tape cursor and the circuit
fingerprint. Generations are retained ``keep`` deep; the preemption
fault-injection site (``segment.boundary:preempt``) fires BETWEEN
segments, after the checkpoint is durable.

:func:`resume_segmented` walks generations newest-first, picks the last
one that passes :func:`~quest_tpu.checkpoint.verify_snapshot` (rejected
generations are flight-recorded QT305 and skipped -- a CRC-divergent
shard counts ``outcome=skipped_corrupt`` with the expected/actual CRC32
in the finding, every other failure ``outcome=rejected_gen`` -- so a
torn or bit-flipped shard falls back to the previous generation instead
of failing the resume), reloads the register and RNG, and replays the
remaining segments. Segment executables are deterministic functions of
the tape slice, and snapshot round-trips are exact, so an interrupted +
resumed run is bit-identical to an uninterrupted segmented run -- the
property tests/test_resilience.py proves on the 8-device mesh for both
the f32 and the double-float route.

Self-healing (ISSUE 8): with a sentinel policy armed
(:mod:`~quest_tpu.resilience.sentinel`, ``QUEST_SENTINEL``), every
segment boundary is also an integrity probe. A breach (norm drift,
per-shard checksum divergence, trace/hermiticity loss) triggers
rollback-and-replay BEFORE the corrupt state can be checkpointed: the
register rolls back to the last verified state -- the CRC-verified
generation at the segment's start cursor, or an in-memory baseline for
the first segment of a fresh run (writing a gen-0 snapshot just to have
a rollback target would charge every clean run the cost of one extra
checkpoint) -- and the segment replays on the same route under the
:func:`guard.sentinel_replay` escalation lattice: retry -> eager
fallback-route replay -> fail closed with
:class:`~quest_tpu.resilience.errors.QuESTIntegrityError`. Because
fault-injection visits are counted, an injected single-bit flip
(``state.corrupt:bitflip<shard>:nth``) does NOT re-fire on the replay,
so recovery is provably bit-identical to the uncorrupted run.
"""

from __future__ import annotations

import json
import os
import shutil

from typing import TYPE_CHECKING

from .. import telemetry
from ..validation import QuESTError
from . import faultinject, guard, sentinel
from .errors import QuESTChecksumError, QuESTIntegrityError

if TYPE_CHECKING:
    from ..analysis.diagnostics import Finding
    from ..circuits import Circuit
    from ..environment import QuESTEnv
    from ..registers import Qureg
    from .sentinel import SentinelPolicy

__all__ = ["segment_plan", "run_segmented", "resume_segmented"]

_MANIFEST = "segment.json"
_GEN_PREFIX = "gen_"


def _qt304(message: str) -> QuESTError:
    from ..analysis.diagnostics import emit_findings, make_finding
    emit_findings([make_finding("QT304", message, "resilience.segmented")])
    return QuESTError(f"{message} [QT304]", "run_segmented")


def _qt305(gen_dir: str, why: str) -> None:
    from ..analysis.diagnostics import emit_findings, make_finding
    emit_findings([make_finding(
        "QT305", f"checkpoint generation {os.path.basename(gen_dir)!r} "
        f"failed verification ({why}); falling back to an older generation",
        "resilience.segmented")])


def _qt305_crc(gen_dir: str, e: QuESTChecksumError) -> None:
    from ..analysis.diagnostics import emit_findings, make_finding
    expected = e.expected_crc if e.expected_crc is not None else 0
    actual = e.actual_crc if e.actual_crc is not None else 0
    emit_findings([make_finding(
        "QT305", f"checkpoint generation {os.path.basename(gen_dir)!r} "
        f"shard {e.shard!r} is corrupt: payload CRC32 {actual:#010x} != "
        f"indexed {expected:#010x}; skipping this generation",
        "resilience.segmented")])


# the symbolic frame replay lives in quest_tpu.segments since round 13
# (the segment-dispatch emitter shares the boundary computation); the
# re-export keeps this module's historical surface
from ..segments import _swap_blocks  # noqa: F401  (compat re-export)


def segment_plan(tape: list, nsv: int, every_n_items: int = 1) -> list:
    """The selected checkpoint cuts for ``tape``: a sorted list of tape
    indices starting at 0 and ending at ``len(tape)``, each a
    frame-identity boundary, spaced at least ``every_n_items`` tape
    entries apart (the next identity boundary when the exact spacing
    lands mid-permutation). Boundaries come from
    :func:`quest_tpu.segments.identity_boundaries` -- the same seams the
    round-13 segment programs dispatch over, so a checkpoint cadence and
    a segment-program chain always agree on where the frame is identity.
    (The pre-round-13 replay here unpacked FrameSwap args as an exact
    3-tuple and broke on comm_pipeline-stamped tapes; the shared
    decoder's slice unpack is codec-tolerant.)"""
    from ..segments import identity_boundaries
    if every_n_items < 1:
        raise _qt304(f"every_n_items must be >= 1, got {every_n_items}")
    boundaries = identity_boundaries(tape, nsv)
    if boundaries[-1] != len(tape):
        raise _qt304(
            "tape does not return to the identity frame at its end "
            "(plancheck QT103 would reject this plan)")
    cuts = [0]
    for b in boundaries[1:]:
        if b - cuts[-1] >= every_n_items:
            cuts.append(b)
    if cuts[-1] != len(tape):
        cuts.append(len(tape))
    return cuts


def _as_qureg(circuit, target):
    from ..environment import QuESTEnv
    from ..registers import Qureg, createDensityQureg, createQureg

    if isinstance(target, Qureg):
        return target
    if isinstance(target, QuESTEnv):
        make = (createDensityQureg if circuit.is_density_matrix
                else createQureg)
        return make(circuit.num_qubits, target)
    raise QuESTError(
        f"run_segmented needs a QuESTEnv or Qureg, got {type(target)!r}",
        "run_segmented")


def _gen_dirs(checkpoint_dir: str) -> list:
    """Existing generation dirs sorted ascending by tape cursor."""
    out = []
    if not os.path.isdir(checkpoint_dir):
        return out
    for name in os.listdir(checkpoint_dir):
        if name.startswith(_GEN_PREFIX):
            try:
                cursor = int(name[len(_GEN_PREFIX):])
            except ValueError:
                continue
            out.append((cursor, os.path.join(checkpoint_dir, name)))
    return [p for _, p in sorted(out)]


def _checkpoint(circuit: Circuit, qureg: Qureg, checkpoint_dir: str,
                cursor: int, every_n_items: int, keep: int) -> str:
    from ..checkpoint import saveQureg

    gen = os.path.join(checkpoint_dir, f"{_GEN_PREFIX}{cursor:08d}")
    saveQureg(qureg, gen)
    manifest = {"cursor": cursor, "total_items": len(circuit._tape),
                "fingerprint": circuit.fingerprint(),
                "every_n_items": every_n_items}
    tmp = os.path.join(gen, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(gen, _MANIFEST))
    telemetry.inc("segmented_checkpoints_total")
    gens = _gen_dirs(checkpoint_dir)
    for stale in gens[:-keep] if keep > 0 else []:
        shutil.rmtree(stale, ignore_errors=True)
    return gen


def _run_segment(circuit: Circuit, qureg: Qureg, lo: int,
                 hi: int) -> None:
    # round 13: the segment rides quest_tpu.segments.run_slice -- ONE
    # segment-program dispatch, cached on the PARENT circuit's stable
    # token (the pre-round-13 path built a throwaway Circuit per segment
    # whose fresh cache token forced a full recompile of every segment
    # on every run AND every healing replay). QUEST_SEGMENT_DISPATCH=0
    # falls back to the per-item interpreter inside run_slice.
    from .. import segments

    with telemetry.span("segmented.segment", lo=lo, hi=hi):
        segments.run_slice(circuit, qureg, lo, hi)
    telemetry.inc("segmented_segments_total")
    if faultinject.enabled():
        # the SDC injection point: one visit of state.corrupt per segment
        # execution (replays re-visit it, so an nth-scoped bit-flip stays
        # out of the healing replay by construction)
        corrupted = guard.corrupt_amps(qureg.amps)
        if corrupted is not qureg.amps:
            qureg.put(corrupted)


def _capture_baseline(qureg):
    """In-memory rollback target for the first segment of a fresh run
    (no disk generation exists yet): host amplitudes + the env RNG
    stream, the same pair a generation snapshot round-trips."""
    import numpy as np
    env = qureg.env
    rng = env.rng.get_state() if env is not None and env.rng is not None \
        else None
    return np.array(qureg.amps), rng


def _rollback(qureg: Qureg, lo: int, checkpoint_dir: str,
              baseline: tuple | None) -> None:
    telemetry.event("segmented.rollback", cursor=lo,
                    source="baseline" if baseline is not None else "gen")
    if baseline is not None:
        host, rng = baseline
        import jax
        sharding = getattr(qureg.amps, "sharding", None)
        qureg.put(jax.device_put(host) if sharding is None
                  else jax.device_put(host, sharding))
        if rng is not None and qureg.env is not None \
                and qureg.env.rng is not None:
            qureg.env.rng.set_state(rng)
        return
    from ..checkpoint import loadQureg

    gen = os.path.join(checkpoint_dir, f"{_GEN_PREFIX}{lo:08d}")
    # CRC-verified, fail-closed: a corrupt rollback target raises rather
    # than feeding the replay a second bad state
    restored = loadQureg(gen, qureg.env)
    qureg.put(restored.amps)


def _heal(circuit: Circuit, qureg: Qureg, lo: int, hi: int,
          checkpoint_dir: str, baseline: tuple | None,
          policy: SentinelPolicy | None,
          findings: list[Finding]) -> None:
    """Drive rollback-and-replay for a breached segment ``[lo, hi)``."""
    where = f"segment[{lo}:{hi}]"
    telemetry.event("segmented.heal", lo=lo, hi=hi,
                    codes=",".join(f.code for f in findings))

    def _recheck(stage: str) -> None:
        # tick=0 is divisible by every cadence: a healing re-check always
        # runs ALL armed sentinel kinds, whatever the boundary schedule
        again = sentinel.check_qureg(qureg, policy=policy, tick=0,
                                     where=f"{where}:{stage}")
        if again:
            raise QuESTIntegrityError(
                f"sentinel breach persists after {stage} of {where}: "
                + "; ".join(f.code for f in again),
                "run_segmented", findings=again)

    def replay():
        _rollback(qureg, lo, checkpoint_dir, baseline)
        _run_segment(circuit, qureg, lo, hi)
        _recheck("replay")
        return True

    def degrade():
        # eager per-item replay with the Pallas route forced onto the
        # engine fallback lattice: a compiled segment would cache-hit the
        # suspect executable, so degradation must bypass the cache
        _rollback(qureg, lo, checkpoint_dir, baseline)
        from .. import fusion
        from ..circuits import _register_mesh

        with fusion.pallas_mesh(_register_mesh(qureg)):
            with faultinject.fault_plan("pallas.dispatch:compile:1+"):
                for f, a, kw in circuit._tape[lo:hi]:
                    telemetry.inc("device_dispatch_total", route="item")
                    f(qureg, *a, **kw)
        _recheck("degraded replay")
        return True

    guard.sentinel_replay(replay, degrade, site="segment.sentinel")


def _execute(circuit: Circuit, qureg: Qureg, cuts: list, start: int,
             checkpoint_dir: str, every_n_items: int,
             keep: int) -> Qureg:
    armed = sentinel.enabled()
    policy = sentinel.active_policy() if armed else None
    tick = 0
    for lo, hi in zip(cuts, cuts[1:]):
        if hi <= start:
            continue
        tick += 1
        baseline = None
        if armed and not os.path.isdir(
                os.path.join(checkpoint_dir, f"{_GEN_PREFIX}{lo:08d}")):
            # first segment of a fresh run: no generation to roll back to
            baseline = _capture_baseline(qureg)
        _run_segment(circuit, qureg, lo, hi)
        if armed:
            findings = sentinel.check_qureg(
                qureg, policy=policy, tick=tick,
                where=f"segment[{lo}:{hi}]")
            if findings:
                _heal(circuit, qureg, lo, hi, checkpoint_dir, baseline,
                      policy, findings)
        _checkpoint(circuit, qureg, checkpoint_dir, hi, every_n_items, keep)
        if hi < cuts[-1]:
            # the injectable preemption point: the checkpoint above is
            # durable, so a preemption here resumes from cursor == hi
            guard.segment_boundary(hi, checkpoint_dir)
    return qureg


def run_segmented(circuit: Circuit, target: QuESTEnv | Qureg, *,
                  checkpoint_dir: str, every_n_items: int = 1,
                  keep: int = 2) -> Qureg:
    """Execute ``circuit`` segment by segment (see module docstring).

    ``target`` is a :class:`~quest_tpu.environment.QuESTEnv` (a fresh
    |0...0> register is created over it) or an existing
    :class:`~quest_tpu.registers.Qureg`. Returns the final register; the
    last generation under ``checkpoint_dir`` holds the completed state
    (cursor == len(tape))."""
    if keep < 1:
        raise _qt304(f"keep must be >= 1, got {keep}")
    qureg = _as_qureg(circuit, target)
    nsv = (2 if circuit.is_density_matrix else 1) * circuit.num_qubits
    cuts = segment_plan(circuit._tape, nsv, every_n_items)
    os.makedirs(checkpoint_dir, exist_ok=True)
    telemetry.event("segmented.run", segments=len(cuts) - 1,
                    items=len(circuit._tape))
    return _execute(circuit, qureg, cuts, 0, checkpoint_dir,
                    every_n_items, keep)


def resume_segmented(circuit: Circuit, checkpoint_dir: str,
                     env: QuESTEnv, *,
                     every_n_items: int | None = None,
                     keep: int = 2) -> Qureg:
    """Restart a :func:`run_segmented` execution from the last VERIFIED
    generation under ``checkpoint_dir`` (see module docstring), replaying
    the remaining segments; returns the final register. ``every_n_items``
    defaults to the value recorded in the manifest, so resumed
    checkpointing continues on the original cadence."""
    gens = _gen_dirs(checkpoint_dir)
    if not gens:
        raise QuESTError(
            f"no checkpoint generations under {checkpoint_dir!r}",
            "resume_segmented")
    from ..checkpoint import loadQureg, verify_snapshot

    chosen = manifest = None
    for gen in reversed(gens):
        mpath = os.path.join(gen, _MANIFEST)
        try:
            with open(mpath) as f:
                m = json.load(f)
            verify_snapshot(gen)
        except QuESTChecksumError as e:
            # silent payload corruption, specifically: name both CRCs and
            # count it apart from structural rejections
            _qt305_crc(gen, e)
            telemetry.inc("segmented_resume_total",
                          outcome="skipped_corrupt")
            continue
        except (OSError, ValueError, QuESTError) as e:
            _qt305(gen, str(e))
            telemetry.inc("segmented_resume_total", outcome="rejected_gen")
            continue
        if m.get("fingerprint") != circuit.fingerprint():
            raise QuESTError(
                f"checkpoint generation {os.path.basename(gen)!r} belongs "
                f"to a different circuit (fingerprint mismatch)",
                "resume_segmented")
        chosen, manifest = gen, m
        break
    if chosen is None:
        telemetry.inc("segmented_resume_total", outcome="no_verified_gen")
        raise QuESTError(
            f"no generation under {checkpoint_dir!r} passed verification",
            "resume_segmented")

    qureg = loadQureg(chosen, env)
    cursor = int(manifest["cursor"])
    n_items = (int(manifest.get("every_n_items", 1))
               if every_n_items is None else every_n_items)
    telemetry.inc("segmented_resume_total", outcome="verified")
    telemetry.event("segmented.resume", cursor=cursor,
                    generation=os.path.basename(chosen))
    if cursor >= len(circuit._tape):
        return qureg
    nsv = (2 if circuit.is_density_matrix else 1) * circuit.num_qubits
    cuts = segment_plan(circuit._tape, nsv, n_items)
    if cursor not in cuts:
        raise QuESTError(
            f"manifest cursor {cursor} is not a segment boundary of this "
            f"circuit at every_n_items={n_items}", "resume_segmented")
    return _execute(circuit, qureg, cuts, cursor, checkpoint_dir,
                    n_items, keep)
