"""Typed failure vocabulary for the resilience layer.

The reference fails whole: ``exitWithError`` (QuEST_validation.c:154)
prints and aborts the process, so every failure is terminal and untyped.
Serving production traffic needs the opposite contract -- each failure
mode carries its own type so callers (and the engine's batcher) can route
it: retry :class:`TransientFault`, degrade on :class:`KernelCompileFault`,
isolate :class:`PoisonedRequestFault` to its request, resume after
:class:`QuESTPreemptionError`, and surface deadline/queue pressure as
:class:`QuESTTimeoutError` / :class:`QuESTBackpressureError`.

Injected faults (raised by :mod:`.faultinject` at named sites) derive from
:class:`InjectedFault`; user-facing terminal errors derive from
:class:`~quest_tpu.validation.QuESTError` so existing ``except QuESTError``
handlers keep working.
"""

from __future__ import annotations

from typing import Iterable

from ..validation import QuESTError

__all__ = [
    "QuESTTimeoutError", "QuESTBackpressureError", "QuESTCancelledError",
    "QuESTPreemptionError", "QuESTRetryError", "QuESTIntegrityError",
    "QuESTHangError", "QuESTChecksumError",
    "InjectedFault", "TransientFault", "KernelCompileFault",
    "PoisonedRequestFault",
]


class QuESTTimeoutError(QuESTError):
    """A request's deadline expired before the engine dispatched it."""


class QuESTBackpressureError(QuESTError):
    """The submit was rejected rather than growing a queue unboundedly:
    the engine queue is at ``QUEST_ENGINE_QUEUE_MAX``, the engine is
    quarantined, or a tenant's admission quota is spent.

    ``reason`` mirrors the ``engine_backpressure_total{reason}`` label:
    ``"queue_full"`` | ``"quarantined"`` | ``"quota"`` |
    ``"pool_capacity"`` (None on legacy raisers)."""

    def __init__(self, message: str, func: str = "",
                 reason: str | None = None) -> None:
        super().__init__(message, func)
        self.reason = reason


class QuESTCancelledError(QuESTError):
    """The request was dropped by ``Engine.close(drain=False)`` before
    dispatch; the future resolves with this instead of dangling."""


class QuESTPreemptionError(QuESTError):
    """Execution was preempted between segments of a segmented run.

    Carries ``cursor`` (the tape index of the last verified checkpoint)
    and ``checkpoint_dir`` so the caller can hand both straight to
    :func:`~quest_tpu.resilience.segmented.resume_segmented`."""

    def __init__(self, message: str, func: str = "",
                 cursor: int | None = None,
                 checkpoint_dir: str | None = None) -> None:
        super().__init__(message, func)
        self.cursor = cursor
        self.checkpoint_dir = checkpoint_dir


class QuESTRetryError(QuESTError):
    """A retryable site stayed faulty past the retry policy's attempt or
    deadline budget and has no degradation path (fail closed)."""


class QuESTIntegrityError(QuESTError):
    """An online integrity sentinel (:mod:`.sentinel`) found silent data
    corruption -- norm/trace drift beyond the precision band or a
    divergent per-shard checksum -- and the self-healing lattice
    (rollback + replay + degrade) could not produce a clean state.

    Carries the sentinel ``findings`` (QT4xx
    :class:`~quest_tpu.analysis.diagnostics.Finding` records) so callers
    can name the breached invariant and the divergent shard."""

    def __init__(self, message: str, func: str = "",
                 findings: Iterable[object] = ()) -> None:
        super().__init__(message, func)
        self.findings = list(findings)


class QuESTHangError(QuESTError):
    """A watchdog deadline (``QUEST_WATCHDOG_MS``) expired around a
    collective launch or an engine dispatch: the caller gets this typed
    error instead of blocking forever on a hung mesh. Carries ``site``
    and the ``deadline_ms`` that was enforced."""

    def __init__(self, message: str, func: str = "",
                 site: str | None = None,
                 deadline_ms: float | None = None) -> None:
        super().__init__(message, func)
        self.site = site
        self.deadline_ms = deadline_ms


class QuESTChecksumError(QuESTError):
    """A stored payload failed CRC32 verification: the bytes on disk are
    not the bytes that were indexed at write time. Carries the ``shard``
    file name plus ``expected_crc`` (index) and ``actual_crc`` (payload)
    so skip-and-fall-back paths (segmented resume, QT305) can report the
    divergence precisely."""

    def __init__(self, message: str, func: str = "",
                 shard: str | None = None,
                 expected_crc: int | None = None,
                 actual_crc: int | None = None) -> None:
        super().__init__(message, func)
        self.shard = shard
        self.expected_crc = expected_crc
        self.actual_crc = actual_crc


class InjectedFault(RuntimeError):
    """Base for faults raised by :mod:`~quest_tpu.resilience.faultinject`
    at a named site (never raised when ``QUEST_FAULTS`` is unset)."""

    def __init__(self, site: str, kind: str) -> None:
        super().__init__(f"injected {kind} fault at site {site!r}")
        self.site = site
        self.kind = kind


class TransientFault(InjectedFault):
    """A fault that a retry is expected to clear (device hiccup, dropped
    collective) -- the retryable class for :mod:`.retry`."""


class KernelCompileFault(InjectedFault):
    """A permanent kernel-route failure (compile error): retrying cannot
    help, the guard degrades along the engine fallback lattice."""


class PoisonedRequestFault(InjectedFault):
    """A single poisoned request inside an engine batch: the batcher must
    isolate it to its own future, not fail its neighbors."""
