"""Online integrity sentinels: cheap invariants that catch silent data
corruption (SDC) while a computation is still running.

The reference's own defense against silent corruption is
``calcTotalProb`` -- "check it stays 1" (statevec_calcTotalProb, Kahan
summation, QuEST_cpu_distributed.c:62-119) -- applied manually by the
user between circuit runs. At fleet scale a flipped amplitude bit on one
device produces no exception, just a wrong answer, so this module makes
the invariant ONLINE: the segmented runner and the serving engine probe
the live state at a configurable cadence, and a breach feeds the
self-healing loop (rollback-and-replay in
:mod:`~quest_tpu.resilience.segmented`, health quarantine in
:mod:`~quest_tpu.engine.engine`).

Three sentinel kinds (:data:`KINDS`):

- ``norm``     -- total probability must stay 1 within a precision-aware
  band (:func:`tolerance`): f32 registers get the wide band the pairwise
  f32 cascade needs, f64 / double-float registers (the PRECISION=2 route
  accumulates within ~2^-47) get the tight one. On a density register
  this is Re tr(rho) -- QT401 (QT404 for density) on breach.
- ``checksum`` -- per-shard partial-norm checksums folded via ONE
  ``lax.psum``: every shard returns its local partial plus the folded
  total, so all shards provably agree on the total or the QT402 finding
  NAMES the divergent shard (non-finite or out-of-range partial, or a
  shard whose psum result disagrees). This is the shard-attribution
  channel the norm check lacks.
- ``trace``    -- density registers only: Re tr(rho) plus hermiticity
  (max |rho - rho^H| within the band) -- QT404 on breach; counted
  ``outcome=skipped`` on state-vectors.

Configuration (``QUEST_SENTINEL`` env, read once, or an explicit
:class:`SentinelPolicy`):

    QUEST_SENTINEL=norm:every_2,checksum:segment
    QUEST_SENTINEL=default          # norm + checksum, every segment

Each entry is ``kind[:cadence]`` where cadence is ``segment`` (every
check opportunity, the default), ``every_N``, or a bare integer ``N``
(every Nth opportunity). Malformed entries are skipped with a QT403
diagnostic (``strict=True`` raises) -- same hygiene as ``QUEST_FAULTS``.

Every executed check counts ``sentinel_checks_total{kind,outcome}``
(``ok`` | ``breach`` | ``skipped``). With no policy armed every probe
point is one module-level boolean read -- the zero-cost discipline of
:mod:`.faultinject`, asserted by the sentinels-off test.
"""

from __future__ import annotations

import contextlib
import os
from typing import TYPE_CHECKING, Iterable, Iterator, NamedTuple

import numpy as np

from .. import telemetry
from ..validation import QuESTError
from . import sync as _sync

if TYPE_CHECKING:
    import jax

    from ..analysis.diagnostics import Finding
    from ..registers import Qureg

__all__ = ["KINDS", "ENV_VAR", "DEFAULT_SPEC", "SentinelSpec",
           "SentinelPolicy", "enabled", "active_policy", "install",
           "clear", "sentinel_policy", "tolerance", "check_amps",
           "check_qureg"]

ENV_VAR = "QUEST_SENTINEL"

#: sentinel kinds a policy may arm
KINDS: tuple[str, ...] = ("norm", "checksum", "trace")

#: what ``QUEST_SENTINEL=default`` (or ``1``/``on``) arms
DEFAULT_SPEC = "norm:segment,checksum:segment"

#: precision-aware tolerance bands for the norm/trace/checksum invariants
#: (|total - 1| must stay inside): f32 needs the wide band (pairwise f32
#: cascade error ~1e-7/amp over 2^20+ terms plus per-gate rounding), f64
#: and the double-float route (~2^-47 accumulation) get the tight one
_TOL = {np.dtype(np.float32): 1e-4, np.dtype(np.float64): 1e-9}


def tolerance(dtype: np.dtype | type | str) -> float:
    """The drift band for a register of real ``dtype`` (see module
    docstring); unknown dtypes get the conservative f32 band."""
    return _TOL.get(np.dtype(dtype), 1e-4)


def _qt403(entry: str, why: str) -> None:
    from ..analysis.diagnostics import emit_findings, make_finding
    emit_findings([make_finding(
        "QT403", f"{ENV_VAR} entry {entry!r} ignored: {why}",
        "resilience.sentinel")])


class SentinelSpec(NamedTuple):
    """One armed sentinel: its kind and cadence (in check opportunities
    -- segment boundaries for the segmented runner, dispatches for the
    engine)."""
    kind: str
    cadence: int = 1

    def due(self, tick: int) -> bool:
        """True when 1-based opportunity ``tick`` should run this check."""
        return tick % self.cadence == 0


class SentinelPolicy:
    """A parsed sentinel policy: which kinds run, at what cadence."""

    def __init__(self,
                 specs: Iterable[SentinelSpec] | tuple = ()) -> None:
        self.specs: tuple[SentinelSpec, ...] = tuple(specs)

    @classmethod
    def parse(cls, text: str, strict: bool = False) -> "SentinelPolicy":
        """Parse ``kind[:cadence][,...]`` (see module docstring);
        malformed entries are skipped with a QT403 diagnostic, or raise
        when ``strict``. ``default``/``on``/``1`` arm
        :data:`DEFAULT_SPEC`; ``off``/``0`` arm nothing."""
        low = text.strip().lower()
        if low in ("", "off", "0", "none"):
            return cls(())
        if low in ("default", "on", "1"):
            text = DEFAULT_SPEC
        specs = []
        for entry in filter(None, (e.strip() for e in text.split(","))):
            parts = entry.split(":")
            kind, cad_s = parts[0], (parts[1] if len(parts) == 2 else
                                     "segment")
            why = None
            cadence = 1
            if len(parts) > 2:
                why = "expected kind[:cadence]"
            elif kind not in KINDS:
                why = f"unknown kind (one of {KINDS})"
            else:
                c = cad_s[len("every_"):] if cad_s.startswith("every_") \
                    else cad_s
                if c == "segment":
                    cadence = 1
                elif c.isdigit() and int(c) >= 1:
                    cadence = int(c)
                else:
                    why = ("cadence must be 'segment', 'every_N' or a "
                           "positive integer")
            if why is not None:
                if strict:
                    raise QuESTError(
                        f"bad {ENV_VAR} entry {entry!r}: {why} [QT403]",
                        "SentinelPolicy.parse")
                _qt403(entry, why)
                continue
            specs.append(SentinelSpec(kind, cadence))
        return cls(specs)

    def due_kinds(self, tick: int) -> tuple[str, ...]:
        """The kinds due at 1-based opportunity ``tick``, in spec order,
        deduplicated."""
        seen: list[str] = []
        for s in self.specs:
            if s.due(tick) and s.kind not in seen:
                seen.append(s.kind)
        return tuple(seen)


# -- module-level policy management (the zero-cost disabled path) -----------

_active: SentinelPolicy | None = None
_env_read = False
_state_lock = _sync.Lock("sentinel.state")


def _load_env() -> None:
    global _active, _env_read
    with _state_lock:
        if _env_read:
            return
        _env_read = True
        text = os.environ.get(ENV_VAR, "").strip()
        if text:
            pol = SentinelPolicy.parse(text)
            if pol.specs:
                _active = pol


def enabled() -> bool:
    """True when a sentinel policy is armed (env or explicit). The first
    call reads ``QUEST_SENTINEL`` once; afterwards this is one boolean."""
    if not _env_read:
        _load_env()
    return _active is not None


def active_policy() -> SentinelPolicy | None:
    """The armed policy, or None."""
    if not _env_read:
        _load_env()
    return _active


def install(policy: SentinelPolicy | str | None) -> None:
    """Arm ``policy`` (a :class:`SentinelPolicy`, a spec string, or None
    to disarm), replacing whatever was active."""
    global _active, _env_read
    with _state_lock:
        _env_read = True
        if isinstance(policy, str):
            policy = SentinelPolicy.parse(policy, strict=True)
        _active = policy if (policy is None or policy.specs) else None


def clear() -> None:
    """Disarm all sentinels (probe points become no-ops again)."""
    install(None)


@contextlib.contextmanager
def sentinel_policy(
        policy: SentinelPolicy | str) -> Iterator[SentinelPolicy | None]:
    """Context manager arming ``policy`` for the block (tests/bench);
    restores the previous policy on exit."""
    global _active, _env_read
    prev, prev_read = _active, _env_read
    install(policy)
    try:
        yield active_policy()
    finally:
        with _state_lock:
            _active, _env_read = prev, prev_read


# -- the checks -------------------------------------------------------------

def _finding(code: str, message: str, where: str) -> Finding:
    from ..analysis.diagnostics import emit_findings, make_finding
    f = make_finding(code, message, where or "resilience.sentinel")
    emit_findings([f])
    return f


def _shard_partials(amps, mesh):
    """(per-shard partial |amp|^2 sums, psum-folded totals) as host
    arrays of length D. On the mesh each shard computes its local
    partial and ONE ``lax.psum`` folds the total, returned per shard --
    so either every shard holds the same total or the disagreement
    itself localizes the fault. Unsharded registers degenerate to one
    "shard"."""
    import jax.numpy as jnp

    from ..ops.reduce import _csum

    if mesh is None or mesh.size <= 1:
        # sum|amps|^2 via the JITTED cascade (total_prob_statevec is
        # exactly _csum(a0^2 + a1^2), which is the norm on a statevector
        # and the purity on a density register): the eager _csum tree
        # would cost ~100x more per probe than the compiled program
        from ..ops.reduce import total_prob_statevec
        p = float(total_prob_statevec(amps))
        return np.array([p]), np.array([p])

    from jax import lax
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..environment import AMP_AXIS

    def kernel(a):
        p = _csum(a[0] * a[0] + a[1] * a[1])
        t = lax.psum(p, AMP_AXIS)
        return jnp.stack([p, t]).reshape(2, 1)

    out = np.asarray(shard_map(
        kernel, mesh=mesh, in_specs=P(None, AMP_AXIS),
        out_specs=P(None, AMP_AXIS))(amps))
    return out[0], out[1]


def _check_norm(amps: jax.Array, density: bool, n: int, tol: float,
                where: str) -> Finding | None:
    from ..ops import reduce as R

    if density:
        total = float(R.total_prob_density(amps, n=n))
        code, what = "QT404", "Re tr(rho)"
    else:
        total = float(R.total_prob_statevec(amps))
        code, what = "QT401", "total probability"
    drift = abs(total - 1.0)
    if np.isfinite(total) and drift <= tol:
        return None
    return _finding(
        code, f"{what} {total!r} drifted |delta|={drift:.3e} beyond the "
        f"{tol:.1e} band for dtype {np.dtype(amps.dtype).name}", where)


def _check_checksum(amps: jax.Array, density: bool, tol: float,
                    where: str,
                    mesh: jax.sharding.Mesh | None) -> Finding | None:
    partials, totals = _shard_partials(amps, mesh)
    # sum|amps|^2 is the norm (statevec) or purity (density): both must
    # land in [0, 1] within the band, and every shard's folded total
    # must agree -- a violation names the shard
    bad = [i for i, p in enumerate(partials)
           if not np.isfinite(p) or p < -tol or p > 1.0 + tol]
    if not bad and totals.size > 1 and not np.all(totals == totals[0]):
        bad = [int(np.argmax(totals != totals[0]))]
    total = totals[0] if np.isfinite(totals[0]) else float("nan")
    global_bad = not np.isfinite(total) or total > 1.0 + tol or total < -tol
    if not bad and not global_bad:
        return None
    shard = bad[0] if bad else int(np.argmax(
        ~np.isfinite(partials) | (partials > 1.0 + tol)))
    return _finding(
        "QT402", f"per-shard checksum divergence: shard {shard} partial "
        f"|amps|^2 = {partials[shard]!r} (psum-folded total {total!r}, "
        f"band {tol:.1e}, {len(partials)} shard(s))", where)


def _check_trace(amps: jax.Array, density: bool, n: int, tol: float,
                 where: str) -> Finding | str | None:
    if not density:
        return "skipped"
    from ..ops import reduce as R

    total = float(R.total_prob_density(amps, n=n))
    host = np.asarray(amps)
    dim = 1 << n
    re = host[0].reshape(dim, dim)
    im = host[1].reshape(dim, dim)
    asym = max(float(np.max(np.abs(re - re.T))),
               float(np.max(np.abs(im + im.T))))
    drift = abs(total - 1.0)
    if np.isfinite(total) and drift <= tol and np.isfinite(asym) \
            and asym <= tol:
        return None
    return _finding(
        "QT404", f"density register breached trace/hermiticity: "
        f"Re tr(rho) = {total!r} (|delta|={drift:.3e}), "
        f"max |rho - rho^H| = {asym:.3e}, band {tol:.1e}", where)


def check_amps(amps: jax.Array, *, density: bool = False,
               n: int | None = None,
               mesh: jax.sharding.Mesh | None = None,
               policy: SentinelPolicy | None = None,
               tick: int = 1, where: str = "") -> list:
    """Run every armed sentinel due at opportunity ``tick`` over a
    planar ``(2, 2**nsv)`` amplitude array; returns the breach findings
    (empty = clean). ``n`` is the represented qubit count (density
    registers need it for the trace); ``mesh`` enables the per-shard
    checksum fold. Each executed check counts
    ``sentinel_checks_total{kind,outcome}``; findings are already
    flight-recorded when returned."""
    pol = policy if policy is not None else active_policy()
    if pol is None or not pol.specs:
        return []
    if n is None:
        n = int(np.log2(amps.shape[-1])) // (2 if density else 1)
    tol = tolerance(amps.dtype)
    findings = []
    for kind in pol.due_kinds(tick):
        if kind == "norm":
            out = _check_norm(amps, density, n, tol, where)
        elif kind == "checksum":
            out = _check_checksum(amps, density, tol, where, mesh)
        else:
            out = _check_trace(amps, density, n, tol, where)
        outcome = ("skipped" if out == "skipped"
                   else "ok" if out is None else "breach")
        telemetry.inc("sentinel_checks_total", kind=kind, outcome=outcome)
        if outcome == "breach":
            telemetry.event("resilience.sentinel_breach", kind=kind,
                            code=out.code, where=where)
            findings.append(out)
    return findings


def check_qureg(qureg: Qureg, *, policy: SentinelPolicy | None = None,
                tick: int = 1, where: str = "") -> list:
    """:func:`check_amps` over a live register (mesh inferred from its
    sharding)."""
    from ..circuits import _register_mesh

    return check_amps(qureg.amps, density=qureg.is_density_matrix,
                      n=qureg.num_qubits_represented,
                      mesh=_register_mesh(qureg), policy=policy,
                      tick=tick, where=where)
