"""Seeded, deterministic fault injection at named execution sites.

The hot paths carry named sites (table below). Each site calls
:func:`fire` (or :func:`check` / :func:`corrupt_file`) exactly once per
visit; with no plan installed the call is a no-op returning ``None`` --
the disabled path is one module-level boolean read, so production traffic
pays nothing. A plan (``QUEST_FAULTS`` env or an explicit
:class:`FaultPlan`) names *which visit* of *which site* fails *how*:

    QUEST_FAULTS=site:kind:nth[,site:kind:nth...]

``nth`` is the 1-based visit count at which the fault fires (``3`` = the
third visit only; ``3+`` = every visit from the third on -- the form
exhaustion tests use). Because visits are counted, not sampled, a fault
plan replays identically run over run: the determinism the bit-identity
recovery proofs in tests/test_resilience.py rely on.

Sites and their kinds (the failure-mode table in docs/resilience.md):

==================== ======================= ===========================
site                 kinds                   raised / effect
==================== ======================= ===========================
``pallas.dispatch``  ``transient, compile``  TransientFault (retried) /
                                             KernelCompileFault (degrade)
``exchange.collective`` ``transient, hang``  TransientFault (retried;
                                             exhaustion fails closed) /
                                             simulated hang the watchdog
                                             (QUEST_WATCHDOG_MS) converts
                                             to a typed QuESTHangError
``engine.request``   ``poison``              PoisonedRequestFault pinned
                                             to one request at submit
``engine.dispatch``  ``hang``                simulated hang inside one
                                             engine dispatch; the
                                             watchdog quarantines the
                                             engine (QuESTHangError)
``pool.replica``     ``kill, hang``          abrupt replica death / hang
                                             at the pool's routing visit:
                                             the EnginePool quarantines
                                             the replica and fails its
                                             queued + in-flight-unacked
                                             requests over to healthy
                                             peers (engine/pool.py)
``checkpoint.write`` ``torn, corrupt, io``   truncate / bit-flip the
                                             just-written shard; ``io``
                                             raises TransientFault
``segment.boundary`` ``preempt``             QuESTPreemptionError between
                                             segments (after checkpoint)
``state.corrupt``    ``bitflip[<shard>]``    deterministic single-bit
                                             amplitude flip on the named
                                             shard (default 0), applied
                                             by guard.corrupt_amps for
                                             the sentinels to catch
==================== ======================= ===========================

The ``state.corrupt`` kind is parameterized: ``bitflip`` flips one bit on
shard 0, ``bitflip3`` on shard 3 -- the shard-naming form the QT402
checksum-divergence proofs use. Visits stay counted per SITE, so the
corruption replays bit-identically (the rollback-and-replay recovery
proofs depend on the nth visit replaying clean).

Every fired fault counts ``fault_injected_total{site,kind}``. Malformed
or unknown ``QUEST_FAULTS`` entries are skipped with a QT302 diagnostic
(flight-recorded, warning severity) -- a typo'd plan must not take down a
production process that merely inherited the env var.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, NamedTuple

from .. import telemetry
from ..validation import QuESTError
from . import sync as _sync
from .errors import (InjectedFault, KernelCompileFault, PoisonedRequestFault,
                     QuESTPreemptionError, TransientFault)

__all__ = ["SITES", "FaultSpec", "FaultPlan", "enabled", "active_plan",
           "install", "clear", "fault_plan", "fire", "check",
           "corrupt_file"]

ENV_VAR = "QUEST_FAULTS"

#: site name -> kinds a plan may inject there (``state.corrupt`` also
#: accepts the shard-parameterized ``bitflip<N>`` form -- see _kind_ok)
SITES: dict[str, tuple[str, ...]] = {
    "pallas.dispatch": ("transient", "compile"),
    "exchange.collective": ("transient", "hang"),
    "engine.request": ("poison",),
    "engine.dispatch": ("hang", "transient"),
    "engine.retire": ("hang",),
    "pool.replica": ("kill", "hang"),
    "checkpoint.write": ("torn", "corrupt", "io"),
    "segment.boundary": ("preempt",),
    "state.corrupt": ("bitflip",),
}


def _kind_ok(site: str, kind: str) -> bool:
    """Exact catalog membership, plus the parameterized ``bitflip<N>``
    (N = target shard index) form on ``state.corrupt``."""
    if kind in SITES[site]:
        return True
    return (site == "state.corrupt" and kind.startswith("bitflip")
            and kind[len("bitflip"):].isdigit())

_EXC: dict[str, type[InjectedFault]] = {
    "transient": TransientFault,
    "io": TransientFault,
    "compile": KernelCompileFault,
    "poison": PoisonedRequestFault,
}


class FaultSpec(NamedTuple):
    """One ``site:kind:nth`` entry; ``from_nth_on`` marks the ``nth+``
    every-visit-from-then-on form."""
    site: str
    kind: str
    nth: int
    from_nth_on: bool = False

    def matches(self, visit: int) -> bool:
        return visit >= self.nth if self.from_nth_on else visit == self.nth


def _qt302(entry: str, why: str) -> None:
    from ..analysis.diagnostics import emit_findings, make_finding
    emit_findings([make_finding(
        "QT302", f"QUEST_FAULTS entry {entry!r} ignored: {why}",
        "resilience.faultinject")])


class FaultPlan:
    """A parsed fault plan: specs plus per-site visit counters (the
    counters live on the plan, so installing a fresh plan restarts the
    deterministic visit numbering)."""

    def __init__(self,
                 specs: Iterator[FaultSpec] | tuple = ()) -> None:
        self.specs: tuple[FaultSpec, ...] = tuple(specs)
        self._visits: dict[str, int] = {}
        self._lock = _sync.Lock("faultinject.plan")

    @classmethod
    def parse(cls, text: str, strict: bool = False) -> "FaultPlan":
        """Parse ``site:kind:nth[,...]``; unknown/malformed entries are
        skipped with a QT302 diagnostic, or raise when ``strict``."""
        specs = []
        for entry in filter(None, (e.strip() for e in text.split(","))):
            parts = entry.split(":")
            why = None
            if len(parts) != 3:
                why = "expected site:kind:nth"
            else:
                site, kind, nth_s = parts
                from_on = nth_s.endswith("+")
                if site not in SITES:
                    why = f"unknown site (one of {sorted(SITES)})"
                elif not _kind_ok(site, kind):
                    why = f"kind not valid for site (one of {SITES[site]})"
                elif not nth_s.rstrip("+").isdigit() \
                        or int(nth_s.rstrip("+")) < 1:
                    why = "nth must be a positive integer (optionally 'N+')"
            if why is not None:
                if strict:
                    raise QuESTError(
                        f"bad QUEST_FAULTS entry {entry!r}: {why} [QT302]",
                        "FaultPlan.parse")
                _qt302(entry, why)
                continue
            specs.append(FaultSpec(site, kind, int(nth_s.rstrip("+")),
                                   from_on))
        return cls(specs)

    def visits(self, site: str) -> int:
        """How many times ``site`` has fired so far (test introspection)."""
        with self._lock:
            return self._visits.get(site, 0)

    def fire(self, site: str) -> str | None:
        """Record one visit of ``site``; return the fault kind to inject
        on this visit, or None."""
        with self._lock:
            visit = self._visits.get(site, 0) + 1
            self._visits[site] = visit
        for spec in self.specs:
            if spec.site == site and spec.matches(visit):
                telemetry.inc("fault_injected_total", site=site,
                              kind=spec.kind)
                telemetry.event("resilience.fault", site=site,
                                kind=spec.kind, visit=visit)
                return spec.kind
        return None


# -- module-level plan management (the zero-cost disabled path) -------------

_active: FaultPlan | None = None
_env_read = False
_state_lock = _sync.Lock("faultinject.state")


def _load_env() -> None:
    global _active, _env_read
    with _state_lock:
        if _env_read:
            return
        _env_read = True
        text = os.environ.get(ENV_VAR, "").strip()
        if text:
            plan = FaultPlan.parse(text)
            if plan.specs:
                _active = plan


def enabled() -> bool:
    """True when a fault plan is installed (env or explicit). The first
    call reads ``QUEST_FAULTS`` once; afterwards this is one boolean."""
    if not _env_read:
        _load_env()
    return _active is not None


def active_plan() -> FaultPlan | None:
    """The installed plan, or None."""
    if not _env_read:
        _load_env()
    return _active


def install(plan: FaultPlan | str | None) -> None:
    """Install ``plan`` (a :class:`FaultPlan`, a spec string, or None to
    disable), replacing whatever was active."""
    global _active, _env_read
    with _state_lock:
        _env_read = True
        _active = (FaultPlan.parse(plan, strict=True)
                   if isinstance(plan, str) else plan)


def clear() -> None:
    """Remove any installed plan (injection sites become no-ops again)."""
    install(None)


@contextlib.contextmanager
def fault_plan(plan: FaultPlan | str) -> Iterator[FaultPlan | None]:
    """Context manager installing ``plan`` for the block (tests/chaos);
    restores the previous plan -- and its visit counters -- on exit."""
    global _active, _env_read
    prev, prev_read = _active, _env_read
    install(plan)
    try:
        yield active_plan()
    finally:
        with _state_lock:
            _active, _env_read = prev, prev_read


def fire(site: str) -> str | None:
    """The injection-site primitive: no-op (None) when disabled, else
    delegate to the plan's visit-counted matcher."""
    if _active is None and _env_read:
        return None
    if not enabled():
        return None
    plan = _active
    return plan.fire(site) if plan is not None else None


def check(site: str) -> None:
    """Visit ``site`` and raise the mapped typed fault if the plan says
    this visit fails; no-op when disabled."""
    kind = fire(site)
    if kind is None:
        return
    exc = _EXC.get(kind)
    if exc is not None:
        raise exc(site, kind)
    if kind == "preempt":
        raise QuESTPreemptionError(
            f"injected preemption at site {site!r}", site)
    # torn/corrupt/bitflip/hang only make sense via their dedicated
    # handlers (corrupt_file, guard.corrupt_amps, the watchdog); reaching
    # here means a site miswired the helper -- surface loudly, don't pass
    raise QuESTError(f"fault kind {kind!r} at {site!r} needs its dedicated "
                     "handler (corrupt_file / guard.corrupt_amps / "
                     "watchdog.watched)", "faultinject.check")


def corrupt_file(site: str, path: str) -> str | None:
    """Visit ``site``; apply a file-level fault to ``path`` (``torn``
    truncates the tail half, ``corrupt`` flips one payload byte) or raise
    for raisable kinds. Returns the kind applied, or None."""
    kind = fire(site)
    if kind is None:
        return None
    if kind == "torn":
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        return kind
    if kind == "corrupt":
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(max(0, size // 2))
            b = f.read(1)
            f.seek(max(0, size // 2))
            f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
        return kind
    exc = _EXC.get(kind)
    if exc is not None:
        raise exc(site, kind)
    return kind
