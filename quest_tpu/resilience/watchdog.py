"""Hung-collective / hung-dispatch watchdog (deadline enforcement).

A flipped bit produces a wrong answer; a wedged device produces NO
answer -- the launch blocks forever and takes the whole serving process
with it. This module bounds the two places a hang can capture the
process: every collective launch (``parallel.exchange._launch``) and
every engine dispatch (``engine.Engine._dispatch``). With
``QUEST_WATCHDOG_MS`` set, the guarded call runs on a worker thread and
the caller waits at most the deadline; expiry raises a typed
:class:`~quest_tpu.resilience.errors.QuESTHangError` (flight-recorded
QT405, counted ``watchdog_timeouts_total{site}``) instead of the eternal
block. The abandoned worker thread is daemonic: a genuinely hung XLA
call cannot be cancelled in-band, so the watchdog's contract is to free
the CALLER (who can quarantine, shed load, or re-plan), not to unwedge
the device.

Unset/zero ``QUEST_WATCHDOG_MS`` disables enforcement: the guarded call
runs inline on the caller's thread with zero new machinery -- the same
one-boolean discipline as :mod:`.faultinject`. Malformed values fall
back to disabled with a QT303 diagnostic.

Hangs are injectable (``exchange.collective:hang:nth`` /
``engine.dispatch:hang:nth``): the worker sleeps past the deadline
before calling through, so the watchdog proof fires deterministically.
With the watchdog DISABLED an injected hang degenerates to a bounded
stall (:data:`HANG_SLEEP_S`) -- tests must be able to observe the
no-watchdog behavior without actually blocking forever.

Deadline enforcement only applies to calls on concrete values: a
collective visited during ``jit`` tracing must stay on the tracing
thread (jax trace state is thread-local), so guards pass
``watched=False`` under trace and the deadline covers the compiled
execution path via the engine dispatch watchdog instead.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, Iterator, TypeVar

from .. import telemetry
from . import sync as _sync
from .errors import QuESTHangError

__all__ = ["ENV_MS", "HANG_SLEEP_S", "deadline_s", "configure",
           "watchdog_deadline", "watched"]

T = TypeVar("T")

ENV_MS = "QUEST_WATCHDOG_MS"

#: bounded stand-in for an "eternal" injected hang when no watchdog is
#: armed (a test can prove the un-watched behavior without blocking)
HANG_SLEEP_S = 0.1

_UNSET = object()
_override: object = _UNSET          # configure()/watchdog_deadline value
_env_cache: object = _UNSET         # parsed QUEST_WATCHDOG_MS (None = off)
_lock = _sync.Lock("watchdog.env")


def _qt303(raw: str) -> None:
    from ..analysis.diagnostics import emit_findings, make_finding
    emit_findings([make_finding(
        "QT303", f"{ENV_MS}={raw!r} is not numeric; watchdog disabled",
        "resilience.watchdog")])


def _qt405(site: str, deadline: float) -> None:
    from ..analysis.diagnostics import emit_findings, make_finding
    emit_findings([make_finding(
        "QT405", f"guarded call at site {site!r} exceeded the "
        f"{deadline * 1e3:.0f}ms watchdog deadline",
        f"resilience.watchdog[{site}]")])


def deadline_s() -> float | None:
    """The enforced deadline in seconds, or None when the watchdog is
    disabled. Reads ``QUEST_WATCHDOG_MS`` once (cached); an explicit
    :func:`configure` value wins over the env."""
    global _env_cache
    if _override is not _UNSET:
        return _override  # type: ignore[return-value]
    if _env_cache is _UNSET:
        with _lock:
            if _env_cache is _UNSET:
                raw = os.environ.get(ENV_MS, "").strip()
                if not raw:
                    _env_cache = None
                else:
                    try:
                        ms = float(raw)
                        _env_cache = ms / 1e3 if ms > 0 else None
                    except ValueError:
                        _qt303(raw)
                        _env_cache = None
    return _env_cache  # type: ignore[return-value]


def configure(ms: float | None) -> None:
    """Override the deadline (milliseconds; None/0 disables). Replaces
    whatever ``QUEST_WATCHDOG_MS`` said; ``configure(None)`` does NOT
    fall back to the env -- use :func:`reset` for that."""
    global _override
    _override = None if not ms else ms / 1e3


def reset() -> None:
    """Drop any :func:`configure` override and the cached env read."""
    global _override, _env_cache
    _override = _UNSET
    _env_cache = _UNSET


@contextlib.contextmanager
def watchdog_deadline(ms: float | None) -> Iterator[None]:
    """Context manager arming the watchdog at ``ms`` for the block
    (tests/chaos); restores the previous setting on exit."""
    global _override
    prev = _override
    configure(ms)
    try:
        yield
    finally:
        _override = prev


def watched(fn: Callable[[], T], *, site: str,
            deadline: float | None = None, hang: bool = False) -> T:
    """Run ``fn`` under the watchdog. ``deadline`` (seconds) defaults to
    :func:`deadline_s`; None runs inline. ``hang=True`` marks an
    injected hang (the caller's fault-plan fire already named this
    visit): the worker sleeps past the deadline first, so the watchdog
    proof is deterministic. Raises
    :class:`~quest_tpu.resilience.errors.QuESTHangError` on expiry."""
    dl = deadline if deadline is not None else deadline_s()
    if dl is None:
        if hang:
            # no watchdog armed: the injected "eternal" hang degenerates
            # to a bounded stall so the un-watched path stays testable
            time.sleep(HANG_SLEEP_S)
        return fn()

    box: dict = {}
    done = threading.Event()

    def worker() -> None:
        try:
            if hang:
                time.sleep(max(4 * dl, HANG_SLEEP_S))
            box["out"] = fn()
        except BaseException as e:  # noqa: BLE001 -- relayed to caller
            box["err"] = e
        finally:
            done.set()

    t = threading.Thread(target=worker, daemon=True,
                         name=f"quest-watchdog[{site}]")
    t.start()
    if not done.wait(dl):
        telemetry.inc("watchdog_timeouts_total", site=site)
        telemetry.event("resilience.watchdog_timeout", site=site,
                        deadline_ms=dl * 1e3)
        _qt405(site, dl)
        raise QuESTHangError(
            f"call at site {site!r} exceeded the {dl * 1e3:.0f}ms "
            f"watchdog deadline [QT405]", "watchdog.watched",
            site=site, deadline_ms=dl * 1e3)
    if "err" in box:
        raise box["err"]
    return box["out"]
