"""Deadline-aware exponential backoff with deterministic jitter.

One policy object wraps every injectable site (:mod:`.guard`). Backoff is
the standard capped-exponential-with-full-jitter shape, but the jitter
stream is seeded (``random.Random(seed)``), so a retry schedule -- like
the fault plan it answers -- replays identically run over run.

Every attempt counts ``retry_attempts_total{site,outcome}``:

- ``ok``        -- the attempt succeeded after at least one failure
                   (first-try successes are NOT counted, so the series
                   stays silent on healthy traffic),
- ``retried``   -- the attempt failed and another follows,
- ``exhausted`` -- the attempt failed and the budget (attempts or
                   deadline) is spent; the last error propagates.

Env knobs (read once per :func:`from_env`, malformed values fall back to
the default with a QT303 diagnostic): ``QUEST_RETRY_MAX`` (attempts,
default 3), ``QUEST_RETRY_BASE_MS`` (first backoff, default 5),
``QUEST_RETRY_DEADLINE_MS`` (total budget, default unset = attempts-only).
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from typing import Callable, TypeVar

from .. import telemetry
from .errors import TransientFault

__all__ = ["RetryPolicy", "call_with_retry", "default_policy"]

T = TypeVar("T")

_DEF_ATTEMPTS = 3
_DEF_BASE_MS = 5.0
_DEF_MULTIPLIER = 2.0
_DEF_MAX_DELAY_MS = 100.0


def _qt303(name: str, raw: str) -> None:
    from ..analysis.diagnostics import emit_findings, make_finding
    emit_findings([make_finding(
        "QT303", f"{name}={raw!r} is not numeric; using the default",
        "resilience.retry")])


def _env_float(name: str, default: float | None) -> float | None:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        _qt303(name, raw)
        return default


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with seeded full jitter and an optional
    wall-clock deadline over the whole retry span."""

    max_attempts: int = _DEF_ATTEMPTS
    base_delay_s: float = _DEF_BASE_MS / 1e3
    multiplier: float = _DEF_MULTIPLIER
    max_delay_s: float = _DEF_MAX_DELAY_MS / 1e3
    deadline_s: float | None = None
    seed: int = 0

    def delays(self):
        """The deterministic backoff schedule: one delay per retry, drawn
        uniformly in ``[base * mult^i / 2, base * mult^i]`` (capped)."""
        rng = random.Random(self.seed)
        d = self.base_delay_s
        for _ in range(max(0, self.max_attempts - 1)):
            cap = min(d, self.max_delay_s)
            yield rng.uniform(cap / 2, cap)
            d *= self.multiplier


def default_policy(seed: int = 0) -> RetryPolicy:
    """The env-configured policy (see module docstring for the knobs)."""
    attempts = _env_float("QUEST_RETRY_MAX", float(_DEF_ATTEMPTS))
    base_ms = _env_float("QUEST_RETRY_BASE_MS", _DEF_BASE_MS)
    deadline_ms = _env_float("QUEST_RETRY_DEADLINE_MS", None)
    if attempts is None or attempts < 1:
        attempts = float(_DEF_ATTEMPTS)
    return RetryPolicy(
        max_attempts=int(attempts),
        base_delay_s=float(base_ms or _DEF_BASE_MS) / 1e3,
        deadline_s=None if deadline_ms is None else deadline_ms / 1e3,
        seed=seed)


def call_with_retry(fn: Callable[[], T], *, site: str,
                    policy: RetryPolicy | None = None,
                    retryable: tuple = (TransientFault,),
                    sleep: Callable[[float], None] = time.sleep) -> T:
    """Run ``fn`` under ``policy``; retry on ``retryable`` with backoff,
    re-raise the last error once attempts or the deadline are spent.
    Non-retryable exceptions propagate immediately (attempt 1 included)."""
    pol = policy if policy is not None else default_policy()
    t0 = time.monotonic()
    failed = False
    delays = pol.delays()
    for attempt in range(1, pol.max_attempts + 1):
        try:
            out = fn()
        except retryable as e:
            over_deadline = (pol.deadline_s is not None
                             and time.monotonic() - t0 >= pol.deadline_s)
            if attempt >= pol.max_attempts or over_deadline:
                telemetry.inc("retry_attempts_total", site=site,
                              outcome="exhausted")
                telemetry.event("resilience.retry_exhausted", site=site,
                                attempts=attempt,
                                deadline=bool(over_deadline),
                                error=type(e).__name__)
                if telemetry.trace_on():
                    telemetry.trace_event_current(
                        "retry.exhausted", site=site, attempt=attempt,
                        error=type(e).__name__)
                raise
            failed = True
            telemetry.inc("retry_attempts_total", site=site,
                          outcome="retried")
            if telemetry.trace_on():
                # each failed attempt shows as an instant on every trace
                # the calling thread is working for (the retry-attempts
                # causal links the waterfall renders)
                telemetry.trace_event_current(
                    "retry.attempt", site=site, attempt=attempt,
                    error=type(e).__name__)
            delay = next(delays, pol.base_delay_s)
            if pol.deadline_s is not None:
                delay = min(delay, max(
                    0.0, pol.deadline_s - (time.monotonic() - t0)))
            sleep(delay)
        else:
            if failed:
                telemetry.inc("retry_attempts_total", site=site,
                              outcome="ok")
            return out
    raise AssertionError("unreachable")  # pragma: no cover
