"""Resilience layer: fault injection, retry/backoff, preemption-safe
segmented execution (ISSUE 7), and the integrity/self-healing machinery
(ISSUE 8).

The reference fails closed and fails whole -- QuEST validates inputs and
then assumes every MPI exchange, kernel launch, and file write succeeds.
Serving production traffic (ROADMAP north star) needs every failure mode
to be *injectable*, *observed*, and either retried to a bit-identical
result or failed closed with a typed error. Six pieces:

- :mod:`.faultinject` -- seeded deterministic fault plans
  (``QUEST_FAULTS=site:kind:nth[,...]``) fired at named sites in the hot
  paths; no-ops (one boolean read) when disabled, counted
  ``fault_injected_total{site,kind}`` when they fire.
- :mod:`.retry` -- deadline-aware exponential backoff with deterministic
  jitter, counted ``retry_attempts_total{site,outcome}``.
- :mod:`.guard` -- per-site wrappers tying the two together: Pallas
  dispatch retries transients then degrades along the existing fallback
  lattice (``engine_fallback_total{reason=fault_degraded}``); collectives
  retry then fail closed; checkpoint writes absorb injected torn/corrupt
  payloads for the verified loader to catch.
- :mod:`.segmented` -- ``Circuit.run_segmented`` / :func:`resume_segmented`:
  checkpointed execution at frame-identity boundaries with CRC-verified
  generation fallback, plus sentinel-driven rollback-and-replay when a
  policy is armed.
- :mod:`.sentinel` -- online integrity sentinels (``QUEST_SENTINEL``):
  precision-banded total-probability drift, psum-folded per-shard
  checksums (the QT402 finding names the divergent shard), density
  trace/hermiticity -- counted ``sentinel_checks_total{kind,outcome}``.
- :mod:`.watchdog` -- deadline enforcement (``QUEST_WATCHDOG_MS``)
  around collective launches and engine dispatches: a hung call raises a
  typed ``QuESTHangError`` (QT405) instead of blocking forever.
- :mod:`.sync` -- named, instrumented lock/condition primitives for the
  whole serving fleet (``QUEST_CONCHECK=1``): per-lock acquisition/hold
  telemetry, the held-while-acquiring order graph behind the QT601
  deadlock analysis, QT602 blocking-boundary guards, the
  ``resolve_future`` once-resolution helper, ``chaos_drop_lock``
  mutation hook, and the controller seam the
  :class:`~quest_tpu.analysis.concheck.InterleavingExplorer` schedules
  through. One boolean of overhead when off (the default).

Typed errors (:mod:`.errors`) subclass
:class:`~quest_tpu.validation.QuESTError`:
``QuESTTimeoutError`` (engine deadline), ``QuESTBackpressureError``
(bounded queue full, or a quarantined engine), ``QuESTCancelledError``
(dropped by ``close(drain=False)``), ``QuESTPreemptionError`` (carries
the resume cursor), ``QuESTRetryError`` (retry budget spent, no
degradation path), ``QuESTIntegrityError`` (sentinel breach the healing
lattice could not clear; carries the QT4xx findings), ``QuESTHangError``
(watchdog deadline; carries site and deadline_ms), ``QuESTChecksumError``
(stored payload CRC divergence; carries shard + expected/actual CRC32).

See docs/resilience.md for the failure-mode table and tools/chaos.py for
the one-fault-per-site CI drill.
"""

from .errors import (  # noqa: F401
    InjectedFault, KernelCompileFault, PoisonedRequestFault,
    QuESTBackpressureError, QuESTCancelledError, QuESTChecksumError,
    QuESTHangError, QuESTIntegrityError, QuESTPreemptionError,
    QuESTRetryError, QuESTTimeoutError, TransientFault,
)
from .faultinject import (  # noqa: F401
    SITES, FaultPlan, FaultSpec, active_plan, clear, enabled, fault_plan,
    fire, install,
)
from .retry import RetryPolicy, call_with_retry, default_policy  # noqa: F401
from .segmented import (  # noqa: F401
    resume_segmented, run_segmented, segment_plan,
)
from . import sentinel  # noqa: F401
from . import sync  # noqa: F401
from . import watchdog  # noqa: F401
from .sync import (  # noqa: F401
    chaos_drop_lock, checking, guard_blocking, held_locks, join_thread,
    lock_order_edges, resolve_future,
)
from .sentinel import SentinelPolicy, SentinelSpec, sentinel_policy  # noqa: F401
from .watchdog import watchdog_deadline  # noqa: F401

__all__ = [
    "QuESTTimeoutError", "QuESTBackpressureError", "QuESTCancelledError",
    "QuESTPreemptionError", "QuESTRetryError", "QuESTIntegrityError",
    "QuESTHangError", "QuESTChecksumError",
    "InjectedFault", "TransientFault", "KernelCompileFault",
    "PoisonedRequestFault",
    "SITES", "FaultPlan", "FaultSpec", "enabled", "active_plan", "install",
    "clear", "fault_plan", "fire",
    "RetryPolicy", "default_policy", "call_with_retry",
    "segment_plan", "run_segmented", "resume_segmented",
    "sentinel", "SentinelPolicy", "SentinelSpec", "sentinel_policy",
    "watchdog", "watchdog_deadline",
    "sync", "checking", "held_locks", "lock_order_edges", "guard_blocking",
    "resolve_future", "join_thread", "chaos_drop_lock",
]
