"""Instrumented synchronization primitives for the serving fleet.

The serving path is a web of threads -- the engine batcher, the pool's
quarantine drainers / replacement spawners / hedge loop, admission
buckets, the watchdog -- and until round 15 its safety was proven only
anecdotally (the round-13 quarantined-``close`` fix was found by hand).
This module is the substrate the concurrency verifier
(:mod:`quest_tpu.analysis.concheck`) analyses: named, thin wrappers over
``threading.Lock`` / ``RLock`` / ``Condition`` that every lock in the
serving stack constructs instead of the raw primitives.

With ``QUEST_CONCHECK`` unset/0 (the default) each operation is a
pass-through costing one module-boolean read -- the same zero-overhead
discipline as :mod:`.faultinject` and :mod:`.watchdog`. With
``QUEST_CONCHECK=1`` (or :func:`configure`), every acquire/release:

- maintains a per-thread held-lock stack (:func:`held_locks`),
- records the **held-while-acquiring** edge into the process-global
  lock-order graph (:func:`lock_order_edges`; the acquisition stack is
  captured once, on the first occurrence of each edge) -- the input to
  concheck's QT601 deadlock-cycle analysis,
- counts ``lock_acquisitions_total{lock}`` and observes
  ``lock_hold_ms{lock}`` on the telemetry registry (lock *names* are
  role strings -- ``engine.cv``, ``pool.cv`` -- so metric cardinality is
  bounded by the number of lock roles, not lock instances),
- checks the QT602 family at declared blocking boundaries:
  :func:`guard_blocking` (device dispatch), :func:`resolve_future`
  (future resolution while holding any instrumented lock -- the exact
  round-13 bug class), condition wait while holding a *different*
  instrumented lock, and :func:`join_thread`.

Malformed ``QUEST_CONCHECK`` values warn once with QT605 via
:func:`~quest_tpu.analysis.diagnostics.parse_env_int`.

Two test-only hooks complete the verifier loop:

- :func:`chaos_drop_lock` -- make one named lock a no-op for a block
  (the "deleted lock" mutation): the un-acquired condition wait is then
  detected deterministically instead of surfacing as a data race.
- :func:`set_controller` -- installs the deterministic interleaving
  explorer (:class:`quest_tpu.analysis.concheck.InterleavingExplorer`);
  every primitive routes controlled threads through it so schedules are
  serialized at these yield points.

Import discipline: this module imports ONLY the stdlib at module scope
(telemetry and diagnostics are imported lazily at call time), so
:mod:`quest_tpu.telemetry` -- whose registry lock this module supplies --
can exist below it without a cycle.
"""

from __future__ import annotations

import contextlib
import threading
import time
import traceback
from typing import TYPE_CHECKING, Any, Callable, Iterator, Optional

if TYPE_CHECKING:
    from ..analysis.diagnostics import Finding

__all__ = [
    "ENV", "Lock", "RLock", "Condition",
    "checking", "configure", "reset",
    "held_locks", "lock_order_edges", "reset_graph",
    "guard_blocking", "resolve_future", "join_thread",
    "blocking_findings", "reset_findings",
    "chaos_drop_lock", "set_controller", "get_controller",
]

ENV = "QUEST_CONCHECK"

#: cap on retained QT602 findings (telemetry still counts every one)
_MAX_FINDINGS = 256

#: frames kept per first-occurrence edge stack
_STACK_LIMIT = 16

_env_read = False
_active = False
_warned: set = set()

#: the installed interleaving explorer (analysis.concheck), or None
_controller: Any = None

#: lock names currently no-op'ed by :func:`chaos_drop_lock`
_dropped: set = set()

_tls = threading.local()

#: (held_name, acquiring_name) -> {"count": int, "stack": str}
_graph: dict = {}
# the recorder's own latch -- deliberately raw (instrumenting the
# instrumenter would recurse); sync.py is allowlisted by the QT604 lint
_graph_guard = threading.Lock()

_qt602_list: list = []


# ---------------------------------------------------------------------------
# enablement (QUEST_CONCHECK, lazy like watchdog.deadline_s)
# ---------------------------------------------------------------------------

def _load_env() -> None:
    global _env_read, _active
    if _env_read:
        return
    # set the latch FIRST: a malformed value's QT605 emission routes
    # through telemetry -> the registry lock -> back into this module
    _env_read = True
    from ..analysis.diagnostics import parse_env_int
    val = parse_env_int(ENV, 0, minimum=0, code="QT605", warned=_warned,
                        noun="concheck mode")
    _active = val >= 1


def checking() -> bool:
    """True when the instrumented paths are recording (``QUEST_CONCHECK``
    >= 1 or an in-process :func:`configure` override)."""
    if not _env_read:
        _load_env()
    return _active


def configure(on: bool) -> None:
    """Enable/disable checking in-process, overriding ``QUEST_CONCHECK``.
    Toggle only at quiescent points: a lock acquired while checking was
    off is invisible to the held stack, so flipping mid-hold can misread
    guards (the suite toggles between requests, never inside one)."""
    global _env_read, _active
    _env_read = True
    _active = bool(on)


def reset() -> None:
    """Drop the :func:`configure` override and the cached env read."""
    global _env_read, _active
    _env_read = False
    _active = False


# ---------------------------------------------------------------------------
# per-thread held stack + lock-order graph
# ---------------------------------------------------------------------------

class _Held:
    __slots__ = ("lock", "t0", "depth")

    def __init__(self, lock: "Lock", t0: float) -> None:
        self.lock = lock
        self.t0 = t0
        self.depth = 1


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def held_locks() -> tuple:
    """Names of the instrumented locks the CURRENT thread holds,
    outermost first (empty when checking is off)."""
    return tuple(h.lock.name for h in _held_stack())


def _record_edge(held_name: str, acquiring_name: str) -> None:
    if held_name == acquiring_name:
        return
    key = (held_name, acquiring_name)
    with _graph_guard:
        e = _graph.get(key)
        if e is None:
            # the stack is captured ONLY on an edge's first occurrence:
            # steady-state acquisitions pay one dict hit + one int add
            _graph[key] = {
                "count": 1,
                "stack": "".join(traceback.format_stack(limit=_STACK_LIMIT)
                                 [:-2]),
            }
        else:
            e["count"] += 1


def lock_order_edges() -> dict:
    """A copy of the held-while-acquiring graph recorded so far:
    ``{(held, acquiring): {"count", "stack"}}`` -- concheck's QT601
    input."""
    with _graph_guard:
        return {k: dict(v) for k, v in _graph.items()}


def reset_graph() -> None:
    """Drop every recorded lock-order edge (tests isolate runs)."""
    with _graph_guard:
        _graph.clear()


# ---------------------------------------------------------------------------
# QT602: blocking boundaries and future resolution under a lock
# ---------------------------------------------------------------------------

def _qt602(site: str, held: tuple, what: str) -> "Finding":
    from ..analysis.diagnostics import emit_findings, make_finding
    f = make_finding(
        "QT602", f"{what} at {site!r} while holding instrumented lock(s) "
                 f"{', '.join(held)}", f"sync.guard[{site}]")
    if len(_qt602_list) < _MAX_FINDINGS:
        _qt602_list.append(f)
    emit_findings([f])
    return f


def guard_blocking(site: str) -> None:
    """Declare a blocking boundary (device dispatch, thread join, a
    ``Future.result()`` wait): flight-records QT602 when the current
    thread holds ANY instrumented lock here. One boolean when checking
    is off."""
    if not _env_read:
        _load_env()
    if not _active:
        return
    held = held_locks()
    if held:
        _qt602(site, held, "blocking boundary crossed")


def resolve_future(fut: Any, *, result: Any = None,
                   exception: BaseException | None = None,
                   site: str = "") -> bool:
    """The ONE future-resolution helper for engine/pool code: resolves
    ``fut`` (exception wins when given) behind the usual ``done()``
    guard, and flight-records QT602 when the resolving thread still
    holds an instrumented lock -- resolution runs arbitrary done
    callbacks (the pool's failover re-dispatch), so doing it under a
    lock is the round-13 deadlock class. Returns True when this call
    resolved the future."""
    if not _env_read:
        _load_env()
    if _active:
        held = held_locks()
        if held:
            _qt602(site, held, "future resolved")
    if fut.done():
        return False
    if exception is not None:
        fut.set_exception(exception)
    else:
        fut.set_result(result)
    return True


def blocking_findings() -> list:
    """The QT602 findings recorded since the last :func:`reset_findings`
    (capped at 256; telemetry counts every occurrence)."""
    return list(_qt602_list)


def reset_findings() -> None:
    """Drop the retained QT602 findings."""
    del _qt602_list[:]


def join_thread(t: threading.Thread, timeout: Optional[float] = None) -> None:
    """Controller-aware ``t.join()``: under the interleaving explorer the
    join becomes a yield point (eligible once ``t`` finishes); otherwise
    it is a plain join behind a QT602 blocking-boundary guard."""
    ctrl = _controller
    if ctrl is not None and ctrl.controls_current():
        ctrl.op_join(t, timeout)
        return
    guard_blocking(f"join:{t.name}")
    t.join(timeout)


# ---------------------------------------------------------------------------
# mutation + explorer hooks
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def chaos_drop_lock(name: str) -> Iterator[None]:
    """Mutation hook: make every lock named ``name`` a no-op for the
    block (acquire succeeds without locking, release does nothing).
    This is the "deleted lock" seeded mutation the concurrency verifier
    must catch: a condition wait on the dropped lock then raises
    deterministically (the un-acquired wait), and the interleaving
    explorer sees the invariant breach the lost mutual exclusion causes.
    Checking is forced ON inside the block so the instrumented paths
    (where the drop takes effect) are active."""
    global _env_read, _active
    prev = (_env_read, _active)
    _env_read = True
    _active = True
    _dropped.add(name)
    try:
        yield
    finally:
        _dropped.discard(name)
        _env_read, _active = prev


def set_controller(ctrl: Any) -> None:
    """Install (or clear, with None) the deterministic interleaving
    explorer. While installed, every primitive asks it to intercept the
    calling thread; uncontrolled threads use the normal paths."""
    global _controller
    _controller = ctrl


def get_controller():
    """The installed interleaving explorer, or None."""
    return _controller


# ---------------------------------------------------------------------------
# checked operation bodies (shared by Lock and RLock)
# ---------------------------------------------------------------------------

def _acquire_checked(lock: "Lock", blocking: bool, timeout: float) -> bool:
    if lock.name in _dropped:
        return True
    held = _held_stack()
    if lock.reentrant:
        for h in held:
            if h.lock is lock:
                h.depth += 1
                return lock._real.acquire(blocking, timeout)
    for h in held:
        _record_edge(h.lock.name, lock.name)
    ok = lock._real.acquire(blocking, timeout)
    if ok:
        held.append(_Held(lock, time.perf_counter()))
        if lock.record:
            from .. import telemetry
            telemetry.inc("lock_acquisitions_total", lock=lock.name)
    return ok


def _release_checked(lock: "Lock") -> None:
    if lock.name in _dropped:
        return
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        h = held[i]
        if h.lock is lock:
            if h.depth > 1:
                h.depth -= 1
                lock._real.release()
                return
            del held[i]
            lock._real.release()
            if lock.record:
                from .. import telemetry
                telemetry.observe(
                    "lock_hold_ms",
                    (time.perf_counter() - h.t0) * 1e3, lock=lock.name)
            return
    # acquired before checking was enabled: release untracked
    lock._real.release()


class Lock:
    """Named wrapper over ``threading.Lock`` (module docstring).
    ``record=False`` keeps a lock on the instrumented layer (held stack,
    order graph, guards) without telemetry metrics -- the telemetry
    registry's own lock uses it to avoid recording recursion."""

    __slots__ = ("name", "record", "_real")

    reentrant = False

    def __init__(self, name: str = "lock", *,
                 record: bool = True) -> None:
        self.name = name
        self.record = record
        self._real = self._make_real()

    @staticmethod
    def _make_real():
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ctrl = _controller
        if ctrl is not None and ctrl.controls_current():
            if self.record:
                return ctrl.op_acquire(self, blocking, timeout)
            # record=False locks (the telemetry registry) guard leaf
            # bookkeeping: no code parks while holding one, and no
            # scenario invariant depends on their interleaving. Skipping
            # the scheduling point keeps schedule depth proportional to
            # the locks that matter, not to metric traffic -- the checked
            # acquire still feeds the held stack and the order graph.
            return _acquire_checked(self, blocking, timeout)
        if not _env_read:
            _load_env()
        if not _active:
            return self._real.acquire(blocking, timeout)
        return _acquire_checked(self, blocking, timeout)

    def release(self) -> None:
        ctrl = _controller
        if ctrl is not None and ctrl.controls_current():
            if self.record:
                ctrl.op_release(self)
                return
            _release_checked(self)
            return
        if not _active:
            self._real.release()
            return
        _release_checked(self)

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<sync.{type(self).__name__} {self.name!r}>"


class RLock(Lock):
    """Named wrapper over ``threading.RLock``: re-entrant acquisitions
    deepen the existing held entry instead of re-recording edges (a
    self-edge is never an ordering fact)."""

    __slots__ = ()

    reentrant = True

    @staticmethod
    def _make_real():
        return threading.RLock()


class Condition:
    """Named wrapper over ``threading.Condition`` sharing its lock with
    the instrumented :class:`Lock` wrapper (pass ``lock=`` to build a
    condition over an existing instrumented lock). ``wait`` mirrors the
    real release/reacquire in the held stack -- hold-time metrics
    exclude the wait, and an un-acquired wait (the dropped-lock
    mutation) raises deterministically. Waiting while holding a
    DIFFERENT instrumented lock flight-records QT602."""

    __slots__ = ("name", "_lock", "_real")

    def __init__(self, name: str = "cond", *,
                 lock: Optional[Lock] = None,
                 record: bool = True) -> None:
        if lock is None:
            lock = Lock(name, record=record)
        self._lock = lock
        self.name = lock.name
        self._real = threading.Condition(lock._real)

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._lock.acquire(blocking, timeout)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "Condition":
        self._lock.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._lock.release()
        return False

    # -- condition protocol --------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> bool:
        ctrl = _controller
        if ctrl is not None and ctrl.controls_current():
            return ctrl.op_wait(self, timeout)
        if not _env_read:
            _load_env()
        if not _active:
            return self._real.wait(timeout)
        held = _held_stack()
        ent = None
        for h in held:
            if h.lock is self._lock:
                ent = h
                break
        if ent is None:
            raise RuntimeError(
                f"cannot wait on un-acquired instrumented lock "
                f"{self.name!r}"
                + (" (dropped by chaos_drop_lock)"
                   if self.name in _dropped else ""))
        others = tuple(h.lock.name for h in held if h.lock is not self._lock)
        if others:
            _qt602(f"cond:{self.name}.wait", others,
                   "condition wait on a different lock")
        # the real wait releases the real lock: mirror it in the held
        # stack so guards and hold-time see the truth during the wait
        held.remove(ent)
        try:
            return self._real.wait(timeout)
        finally:
            ent.t0 = time.perf_counter()
            ent.depth = 1
            held.append(ent)

    def wait_for(self, predicate: Callable[[], Any],
                 timeout: Optional[float] = None) -> Any:
        # threading.Condition.wait_for, re-expressed over self.wait so
        # the explorer's cooperative wait is reused
        endtime = None
        waittime = timeout
        result = predicate()
        while not result:
            if waittime is not None:
                if endtime is None:
                    endtime = time.monotonic() + waittime
                else:
                    waittime = endtime - time.monotonic()
                    if waittime <= 0:
                        break
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        ctrl = _controller
        if ctrl is not None and ctrl.controls_current():
            ctrl.op_notify(self, n)
            return
        if self.name in _dropped:
            return  # a dropped lock never took the real lock: the
            # mutation under test is lost mutual exclusion, and it is
            # detected at wait sites -- a notify crash would only mask it
        self._real.notify(n)

    def notify_all(self) -> None:
        ctrl = _controller
        if ctrl is not None and ctrl.controls_current():
            ctrl.op_notify(self, None)
            return
        if self.name in _dropped:
            return
        self._real.notify_all()

    def __repr__(self) -> str:
        return f"<sync.Condition {self.name!r}>"


# ---------------------------------------------------------------------------
# adopt the telemetry registry's lock: telemetry cannot import this
# module (it sits below everything), so the swap happens here, exactly
# once, the first time the serving stack pulls the instrumented layer in
# ---------------------------------------------------------------------------

def _adopt_registry_lock() -> None:
    from .. import telemetry
    reg = getattr(telemetry, "REGISTRY", None)
    if reg is not None and not isinstance(reg._lock, Lock):
        reg._lock = Lock("telemetry.registry", record=False)


_adopt_registry_lock()
