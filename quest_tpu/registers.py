"""Qureg: the qubit register (reference struct at QuEST.h:360-396).

The reference Qureg carries planar host arrays, an MPI receive buffer
(``pairStateVec``), and GPU mirrors + reduction buffers. The TPU-native Qureg
is a thin mutable handle around one device ``jax.Array`` of shape
(2, 2^numQubitsInStateVec) -- planar (real, imag) float amplitudes, the same
SoA layout as the reference's ComplexArray (QuEST.h:94-98), chosen because
the TPU has no native complex dtype. It is sharded over the env's mesh (XLA
owns all scratch/comm buffers, so pairStateVec and the reduction buffers have
no equivalent).

Mutation model: the C API mutates Quregs in place; JAX arrays are immutable.
API functions therefore rebind ``qureg.amps`` to the new functional value --
the handle is stable, the array is fresh (XLA donation keeps this
allocation-neutral inside jit).

Density matrices are state-vectors of 2N qubits (QuEST.c:8-10): element
rho[row, col] lives at flat index col * 2^N + row (row bits low). Gates apply
to row-qubit q and, conjugated, to col-qubit q+N -- the "shadow" op.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import precision, validation
from .environment import QuESTEnv
from .ops import init as ops_init
from .qasm import QASMLogger


@dataclass
class Qureg:
    num_qubits_represented: int
    is_density_matrix: bool
    amps: jax.Array
    env: QuESTEnv
    qasm_log: Optional[QASMLogger] = None
    #: lazily-created host planar mirror for copyState{To,From}GPU
    host_amps: Optional[np.ndarray] = None

    @property
    def state_vec(self) -> np.ndarray:
        """Host planar mirror (the reference's ``qureg.stateVec``); sync with
        copyStateFromGPU/copyStateToGPU."""
        return _host_mirror(self)

    @property
    def num_qubits_in_state_vec(self) -> int:
        return (2 if self.is_density_matrix else 1) * self.num_qubits_represented

    @property
    def num_amps_total(self) -> int:
        return 1 << self.num_qubits_in_state_vec

    # parity aliases matching the reference field names
    @property
    def numQubitsRepresented(self) -> int:
        return self.num_qubits_represented

    @property
    def numAmpsTotal(self) -> int:
        return self.num_amps_total

    @property
    def dtype(self):
        """Real dtype of the planar amplitude planes (float32/float64)."""
        return self.amps.dtype

    @property
    def eps(self) -> float:
        return precision.eps_for_dtype(self.amps.dtype)

    def put(self, new_amps) -> None:
        """Rebind the amplitude array, preserving the register's sharding."""
        self.amps = new_amps

    def __repr__(self):
        kind = "density-matrix" if self.is_density_matrix else "state-vector"
        return (f"Qureg({kind}, qubits={self.num_qubits_represented}, "
                f"amps=2^{self.num_qubits_in_state_vec}, dtype={self.amps.dtype})")


def _alloc(env: QuESTEnv, num_qubits_sv: int, dtype, index: int = 0,
           func: str = "createQureg") -> jax.Array:
    num_amps = 1 << num_qubits_sv

    def alloc():
        amps = ops_init.init_classical(num_amps, jnp.dtype(dtype), index)
        sharding = env.sharding(num_amps)
        if sharding is not None:
            amps = jax.device_put(amps, sharding)
        return amps

    # allocator failures surface through the validation hook, attributed to
    # the calling API function like validateQuregAllocation (QuEST_cpu.c:1318)
    return validation.validate_qureg_allocation(alloc, func)


def createQureg(num_qubits: int, env: QuESTEnv, precision_code: int | None = None) -> Qureg:
    """State-vector register in |0...0> (createQureg, QuEST.h:579)."""
    func = "createQureg"
    validation._assert(num_qubits > 0, "Invalid number of qubits. Must create >0.", func)
    validation.validate_num_amps_fit_type(num_qubits, False, func)
    if env.requires_sharding:
        validation.validate_qureg_fits_devices(num_qubits, env.mesh.size,
                                               False, func)
    dtype = precision.real_dtype(precision_code)
    q = Qureg(num_qubits, False, _alloc(env, num_qubits, dtype, func=func), env)
    q.qasm_log = QASMLogger(num_qubits, dtype)
    return q


def createDensityQureg(num_qubits: int, env: QuESTEnv, precision_code: int | None = None) -> Qureg:
    """Density-matrix register in |0><0| (createDensityQureg, QuEST.h:673)."""
    func = "createDensityQureg"
    validation._assert(num_qubits > 0, "Invalid number of qubits. Must create >0.", func)
    validation.validate_num_amps_fit_type(num_qubits, True, func)
    if env.requires_sharding:
        validation.validate_qureg_fits_devices(num_qubits, env.mesh.size,
                                               True, func)
    dtype = precision.real_dtype(precision_code)
    q = Qureg(num_qubits, True, _alloc(env, 2 * num_qubits, dtype,
                                       func=func), env)
    q.qasm_log = QASMLogger(num_qubits, dtype)
    return q


def createCloneQureg(qureg: Qureg, env: QuESTEnv) -> Qureg:
    """Deep copy (createCloneQureg, QuEST.h:694)."""
    q = Qureg(qureg.num_qubits_represented, qureg.is_density_matrix,
              qureg.amps + 0, env)
    q.qasm_log = QASMLogger(qureg.num_qubits_represented, qureg.dtype)
    return q


def destroyQureg(qureg: Qureg, env: QuESTEnv | None = None) -> None:
    """Release the device buffer eagerly (destroyQureg, QuEST.h:716)."""
    try:
        qureg.amps.delete()
    except Exception:
        pass
    qureg.amps = None


def get_np(qureg: Qureg) -> np.ndarray:
    """Gather the full amplitude array to host as numpy complex
    (tests / reporting)."""
    from .ops import cplx
    return cplx.to_complex(qureg.amps)


# --------------------------------------------------------------------------
# Host-mirror synchronisation (copyStateToGPU/FromGPU, QuEST.h:2286-2383).
#
# The reference keeps a host planar array (qureg.stateVec) beside the device
# copy and lets users edit it directly, syncing explicitly. Here the device
# jax.Array is the state of record; ``qureg.state_vec`` is a lazily-created
# planar numpy mirror (shape (2, numAmps): real plane, imag plane) that these
# four functions sync in either direction. On CPU backends they still work --
# they are then just host<->host copies, matching the reference's no-op CPU
# definitions (QuEST_cpu_local.c) while keeping the mirror coherent.
# --------------------------------------------------------------------------

def _host_mirror(qureg: Qureg) -> np.ndarray:
    if getattr(qureg, "host_amps", None) is None:
        qureg.host_amps = np.zeros((2, qureg.num_amps_total),
                                   dtype=qureg.amps.dtype)
    return qureg.host_amps


def _validate_live(qureg: Qureg, func: str) -> None:
    validation._assert(
        qureg.amps is not None,
        "Invalid Qureg. The register has been destroyed.", func)


def copyStateFromGPU(qureg: Qureg) -> np.ndarray:
    """Pull the device state into the host mirror (copyStateFromGPU, QuEST.h:2321)."""
    _validate_live(qureg, "copyStateFromGPU")
    mirror = _host_mirror(qureg)
    mirror[...] = np.asarray(qureg.amps)
    return mirror


def copyStateToGPU(qureg: Qureg) -> None:
    """Push the host mirror to the device (copyStateToGPU, QuEST.h:2301)."""
    _validate_live(qureg, "copyStateToGPU")
    mirror = _host_mirror(qureg)
    new = jax.device_put(jnp.asarray(mirror), qureg.amps.sharding)
    qureg.put(new)


def copySubstateFromGPU(qureg: Qureg, start_ind: int, num_amps: int) -> np.ndarray:
    """Pull amplitudes [start, start+num) into the host mirror
    (copySubstateFromGPU, QuEST.h:2383)."""
    func = "copySubstateFromGPU"
    _validate_live(qureg, func)
    validation.validate_num_amps(qureg, start_ind, num_amps, func)
    mirror = _host_mirror(qureg)
    chunk = jax.lax.dynamic_slice_in_dim(qureg.amps, start_ind, num_amps, axis=1)
    mirror[:, start_ind:start_ind + num_amps] = np.asarray(chunk)
    return mirror


def copySubstateToGPU(qureg: Qureg, start_ind: int, num_amps: int) -> None:
    """Push host-mirror amplitudes [start, start+num) to the device
    (copySubstateToGPU, QuEST.h:2352)."""
    func = "copySubstateToGPU"
    _validate_live(qureg, func)
    validation.validate_num_amps(qureg, start_ind, num_amps, func)
    mirror = _host_mirror(qureg)
    patch = jnp.asarray(mirror[:, start_ind:start_ind + num_amps])
    # static-index .at[].set, not dynamic_update_slice: the indices are
    # host ints, and on a sharded operand some jaxlib releases lower the
    # dynamic form with mixed s64/s32 index clamps (hlo verifier error)
    new = qureg.amps.at[:, start_ind:start_ind + num_amps].set(patch)
    new = jax.device_put(new, qureg.amps.sharding)
    qureg.put(new)
