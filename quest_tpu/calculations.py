"""Calculations: probabilities, inner products, expectation values
(reference QuEST.h:2404-2516, 3544-3799, 4247-4917; kernels in ops.reduce).
"""

from __future__ import annotations

from functools import partial

import numpy as np

from . import validation as V
from .datatypes import PauliHamil, pauliOpType
from .ops import measure as M, reduce as R
from .registers import Qureg, createCloneQureg, get_np

__all__ = [
    "calcTotalProb", "calcProbOfOutcome", "calcProbOfAllOutcomes",
    "calcInnerProduct", "calcDensityInnerProduct", "calcPurity", "calcFidelity",
    "calcHilbertSchmidtDistance", "calcExpecPauliProd", "calcExpecPauliSum",
    "calcExpecPauliHamil", "calcGradExpecPauliSum", "getAmp", "getRealAmp",
    "getImagAmp", "getProbAmp", "getDensityAmp",
]


def calcTotalProb(qureg: Qureg) -> float:
    """sum |amp|^2 (state-vector) or Re tr(rho) (density) (QuEST.h:2516)."""
    if qureg.is_density_matrix:
        return float(R.total_prob_density(qureg.amps, n=qureg.num_qubits_represented))
    return float(R.total_prob_statevec(qureg.amps))


def calcProbOfOutcome(qureg: Qureg, target: int, outcome: int) -> float:
    """Probability of measuring ``outcome`` on ``measureQubit`` (QuEST.h:276)."""
    func = "calcProbOfOutcome"
    V.validate_target(qureg, target, func)
    V.validate_outcome(outcome, func)
    if qureg.is_density_matrix:
        return float(M.density_prob_of_outcome(
            qureg.amps, n=qureg.num_qubits_represented, target=target, outcome=outcome))
    return float(M.prob_of_outcome(
        qureg.amps, n=qureg.num_qubits_in_state_vec, target=target, outcome=outcome))


def calcProbOfAllOutcomes(qureg: Qureg, targets) -> np.ndarray:
    """2^t outcome distribution; targets[0] is the outcome's least-significant
    bit (QuEST.h:3633)."""
    func = "calcProbOfAllOutcomes"
    V.validate_multi_targets(qureg, targets, func)
    if qureg.is_density_matrix:
        p = M.density_prob_of_all_outcomes(
            qureg.amps, n=qureg.num_qubits_represented, targets=tuple(targets))
    else:
        p = M.prob_of_all_outcomes(
            qureg.amps, n=qureg.num_qubits_in_state_vec, targets=tuple(targets))
    return np.asarray(p)


def calcInnerProduct(bra: Qureg, ket: Qureg) -> complex:
    """<bra|ket> (QuEST.h:3746)."""
    func = "calcInnerProduct"
    V.validate_state_vec(bra, func)
    V.validate_state_vec(ket, func)
    V.validate_matching_qureg_dims(bra, ket, func)
    re, im = R.inner_product(bra.amps, ket.amps)
    return complex(float(re), float(im))


def calcDensityInnerProduct(rho1: Qureg, rho2: Qureg) -> float:
    """Re Tr(rho1^dag rho2) (QuEST.h:3799)."""
    func = "calcDensityInnerProduct"
    V.validate_density_matr(rho1, func)
    V.validate_density_matr(rho2, func)
    V.validate_matching_qureg_dims(rho1, rho2, func)
    return float(R.density_inner_product(rho1.amps, rho2.amps))


def calcPurity(qureg: Qureg) -> float:
    """Tr(rho^2) (QuEST.h:4247)."""
    V.validate_density_matr(qureg, "calcPurity")
    return float(R.purity_density(qureg.amps))


def calcFidelity(qureg: Qureg, pure_state: Qureg) -> float:
    """|<psi|phi>|^2 or <psi|rho|psi> (QuEST.h:4283)."""
    func = "calcFidelity"
    V.validate_second_qureg_state_vec(pure_state, func)
    V.validate_matching_qureg_dims(qureg, pure_state, func)
    if qureg.is_density_matrix:
        return float(R.density_fidelity(qureg.amps, pure_state.amps,
                                        n=qureg.num_qubits_represented))
    re, im = R.inner_product(qureg.amps, pure_state.amps)
    return float(re) ** 2 + float(im) ** 2


def calcHilbertSchmidtDistance(a: Qureg, b: Qureg) -> float:
    """sqrt(sum |a-b|^2) (QuEST.h:5663)."""
    func = "calcHilbertSchmidtDistance"
    V.validate_density_matr(a, func)
    V.validate_density_matr(b, func)
    V.validate_matching_qureg_dims(a, b, func)
    return float(R.hilbert_schmidt_distance(a.amps, b.amps))


# ---------------------------------------------------------------------------
# Pauli expectation values (logic: QuEST_common.c:491-555)
# ---------------------------------------------------------------------------

def _apply_pauli_prod(workspace: Qureg, targets, codes) -> None:
    """Apply a product of Paulis gate-wise to the workspace (the clone-based
    scheme of statevec_calcExpecPauliProd, QuEST_common.c:505-518). Note the
    workspace is treated as a plain 2N-amplitude vector even for density
    matrices (no shadow op), matching the reference."""
    from . import matrices
    from .ops import apply as K, cplx, diagonal as D
    nsv = workspace.num_qubits_in_state_vec
    dt = workspace.dtype
    amps = workspace.amps
    for t, c in zip(targets, codes):
        c = int(c)
        if c == 0:
            continue
        if c == 1:
            amps = K.apply_x_class(amps, n=nsv, targets=(int(t),))
        elif c == 2:
            amps = K.apply_matrix(amps, cplx.from_complex(matrices.PAULI_Y_M, dt),
                                  n=nsv, targets=(int(t),))
        else:
            amps = D.apply_diagonal(amps, cplx.from_complex(np.array([1.0, -1.0]), dt),
                                    n=nsv, targets=(int(t),))
    workspace.put(amps)


def calcExpecPauliProd(qureg: Qureg, targets, paulis, workspace: Qureg) -> float:
    """<qureg| P |qureg> (QuEST.h:4777). The workspace is clobbered with
    P|qureg>, matching the reference's contract."""
    func = "calcExpecPauliProd"
    V.validate_multi_targets(qureg, targets, func)
    V.validate_num_pauli_codes(paulis, len(targets), func)
    V.validate_matching_qureg_types(qureg, workspace, func)
    V.validate_matching_qureg_dims(qureg, workspace, func)
    workspace.put(qureg.amps + 0)
    _apply_pauli_prod(workspace, targets, paulis)
    if qureg.is_density_matrix:
        # Tr(P rho): the reference takes densmatr_calcTotalProb of P.rho
        return float(R.total_prob_density(workspace.amps, n=qureg.num_qubits_represented))
    return float(R.inner_product(qureg.amps, workspace.amps)[0])


def _pauli_prod_amps(amps, term, nsv, dt):
    """P|amps> for one static code tuple (inlined under jit)."""
    from . import matrices
    from .ops import apply as K, cplx, diagonal as D
    for t, c in enumerate(term):
        if c == 0:
            continue
        if c == 1:
            amps = K.apply_x_class(amps, n=nsv, targets=(t,))
        elif c == 2:
            amps = K.apply_matrix(amps, cplx.from_complex(matrices.PAULI_Y_M, dt),
                                  n=nsv, targets=(t,))
        else:
            amps = D.apply_diagonal(amps, cplx.from_complex(np.array([1.0, -1.0]), dt),
                                    n=nsv, targets=(t,))
    return amps


#: terms per compiled block in _expec_pauli_sum_fused: each term unrolls an
#: O(n)-op Pauli pipeline into the program, so program size (and compile
#: time) grows linearly with terms -- the same compile-limit failure mode
#: Circuit.blocks() bounds. 64 terms x ~n ops stays well under XLA limits.
_EXPEC_TERM_BLOCK = 64


def _expec_pauli_sum_fused(amps, coeffs, *, codes, n, density):
    """sum_t c_t <P_t>, fused into one XLA program per 64-term block.

    The reference pays a full state clone, O(n) kernel launches, and an
    Allreduce per term (QuEST_common.c:505-532); here the term loop unrolls
    at trace time so XLA schedules every term's Pauli pipeline and reduction
    inside a single dispatch (SURVEY.md section 3.5's noted fusion win).
    Hamiltonians beyond _EXPEC_TERM_BLOCK terms chain a few block-sized
    executables instead of growing one unbounded program."""
    total = 0.0
    for i in range(0, len(codes), _EXPEC_TERM_BLOCK):
        block = codes[i:i + _EXPEC_TERM_BLOCK]
        total = total + _expec_pauli_sum_run(
            amps, coeffs[i:i + _EXPEC_TERM_BLOCK], codes=block, n=n,
            density=density)
    return total


def expec_pauli_sum_amps(amps, coeffs, *, codes, n, density):
    """sum_t c_t <P_t> as a TRACEABLE function of the planar amps: the
    body of the fused expectation, exposed (round 19) so the sampling
    request path can lower calcExpecPauliSum into a request executable's
    terminal ``reduce(amps)`` stage -- circuit + shots + expectation as
    one dispatched program. ``codes`` is a static tuple of code tuples;
    term unrolling happens at trace time exactly as under the jitted
    eager entry."""
    nsv = (2 if density else 1) * n
    total = 0.0
    for t, term in enumerate(codes):
        work = _pauli_prod_amps(amps, term, nsv, amps.dtype)
        if density:
            val = R.total_prob_density(work, n=n)
        else:
            val = R.inner_product(amps, work)[0]
        total = total + coeffs[t] * val
    return total


def _make_expec_pauli_sum_run():
    import jax

    @partial(jax.jit, static_argnames=("codes", "n", "density"))
    def run(amps, coeffs, *, codes, n, density):
        return expec_pauli_sum_amps(amps, coeffs, codes=codes, n=n,
                                    density=density)

    return run


_expec_pauli_sum_run = _make_expec_pauli_sum_run()


def calcExpecPauliSum(qureg: Qureg, all_pauli_codes, term_coeffs, workspace: Qureg) -> float:
    """sum_t c_t <P_t> (QuEST.h:4832). Reference semantics (the workspace is
    scratch with unspecified final state), but fused: one compiled program
    for the whole sum instead of the reference's clone + launches + reduce
    per term (QuEST_common.c:520-532)."""
    func = "calcExpecPauliSum"
    codes = np.asarray(all_pauli_codes, dtype=np.int32).reshape(len(term_coeffs), -1)
    V._assert(codes.size == len(term_coeffs) * qureg.num_qubits_represented,
              "Invalid number of Pauli codes. The number of codes must equal numQubits * numSumTerms.",
              func)
    V.validate_pauli_codes(codes.ravel(), func)
    V.validate_matching_qureg_types(qureg, workspace, func)
    V.validate_matching_qureg_dims(qureg, workspace, func)
    import jax.numpy as jnp
    coeffs = jnp.asarray(np.asarray(term_coeffs, dtype=np.float64), dtype=qureg.dtype)
    total = _expec_pauli_sum_fused(
        qureg.amps, coeffs,
        codes=tuple(tuple(int(c) for c in row) for row in codes),
        n=qureg.num_qubits_represented, density=qureg.is_density_matrix)
    return float(total)


def calcExpecPauliHamil(qureg: Qureg, hamil: PauliHamil, workspace: Qureg) -> float:
    """(QuEST.h:4873)."""
    func = "calcExpecPauliHamil"
    V.validate_pauli_hamil(hamil, func)
    V.validate_hamil_matches_qureg(qureg, hamil, func)
    return calcExpecPauliSum(qureg, hamil.pauli_codes, hamil.term_coeffs, workspace)


def calcGradExpecPauliSum(qureg: Qureg, circuit, all_pauli_codes,
                          term_coeffs, params=None):
    """Value and parameter gradients of ``sum_t c_t <P_t>`` after applying
    ``circuit`` to ``qureg``'s current state, by the adjoint-state method
    (quest_tpu/gradients, docs/gradients.md): one forward sweep, one
    Hamiltonian application, one backward sweep -- versus 2P full replays
    for parameter-shift. ``qureg`` is read, never written. Returns
    ``(value, grads)`` with ``grads`` a name -> float dict over the
    circuit's named :class:`~quest_tpu.engine.P` parameters. This is the
    one-shot convenience; the serving route is :meth:`Engine.submit_grad`
    / :meth:`EnginePool.submit_grad` over the same executable."""
    from .gradients import gradient_executable

    func = "calcGradExpecPauliSum"
    V._assert(not qureg.is_density_matrix,
              "calcGradExpecPauliSum needs a state-vector register (the "
              "adjoint sweep differentiates pure states).", func)
    out = gradient_executable(circuit, (all_pauli_codes, term_coeffs),
                              donate=False)(qureg.amps, params)
    return float(out["value"]), {k: float(v) for k, v in
                                 out["grads"].items()}


# ---------------------------------------------------------------------------
# amplitude getters (QuEST.h:2404-2489)
# ---------------------------------------------------------------------------

def getAmp(qureg: Qureg, index: int) -> complex:
    """One statevector amplitude as a complex (QuEST.h:286)."""
    func = "getAmp"
    V.validate_state_vec(qureg, func)
    V.validate_amp_index(qureg, index, func)
    return complex(float(qureg.amps[0, index]), float(qureg.amps[1, index]))


def getRealAmp(qureg: Qureg, index: int) -> float:
    """Real part of one statevector amplitude (QuEST.h:287)."""
    return getAmp(qureg, index).real


def getImagAmp(qureg: Qureg, index: int) -> float:
    """Imaginary part of one statevector amplitude (QuEST.h:288)."""
    return getAmp(qureg, index).imag


def getProbAmp(qureg: Qureg, index: int) -> float:
    """|amp|^2 of one statevector amplitude (QuEST.h:289)."""
    a = getAmp(qureg, index)
    return a.real * a.real + a.imag * a.imag


def getDensityAmp(qureg: Qureg, row: int, col: int) -> complex:
    """rho[row, col] (QuEST.h:2489); flat index col*2^N + row."""
    func = "getDensityAmp"
    V.validate_density_matr(qureg, func)
    dim = 1 << qureg.num_qubits_represented
    V._assert(0 <= row < dim and 0 <= col < dim,
              "Invalid amplitude index. Note amplitudes are zero indexed.", func)
    i = col * dim + row
    return complex(float(qureg.amps[0, i]), float(qureg.amps[1, i]))
