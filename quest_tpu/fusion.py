"""Tape-level gate fusion: contract runs of gates into k-qubit unitaries.

The reference executes one kernel (and one MPI exchange, when distributed)
per gate -- its cost model is per-gate (QuEST_cpu_distributed.c:870-905).
On TPU the optimal execution unit is much coarser: a block of consecutive
gates whose combined support fits in k qubits multiplies into a single
2^k x 2^k unitary **on the host** (numpy, trace-time), and the whole block
hits the state as one dense matmul that XLA tiles onto the MXU. A deep
circuit collapses from hundreds of elementwise passes into a handful of
GEMMs: fewer HBM round-trips, drastically smaller XLA programs (compile
time scales with op count), and MXU utilisation instead of VPU.

This is the standard dense-fusion technique of state-vector simulators
(qsim's gate fusion, cuQuantum's custatevecApplyMatrix batching); the
reference itself has no analogue -- it is pure TPU-side gain.

Mechanics: each recorded tape entry is *replayed once against a spy
register* with the gate-application primitives patched to record
(kind, operands, qubits) instead of touching any device array. Entries
that don't route through the four gate primitives (decoherence, phase
functions, state inits, ...) simply fail capture and act as fusion
barriers, passing through to the device path unchanged -- so ``fused()``
is semantics-preserving for arbitrary tapes.

Blocks that remain diagonal are emitted through the broadcast-multiply
diagonal kernel (no matmul, one VPU pass) instead of a dense GEMM.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from . import precision
from . import telemetry


# ---------------------------------------------------------------------------
# captured gate events
# ---------------------------------------------------------------------------

@dataclass
class GateEvent:
    """One primitive application captured from a tape entry.

    kind: 'matrix' | 'diag' | 'x' | 'parity' | 'swap' | 'channel'

    ``extended=True`` marks events that take no conj-shadow twin during
    density planning. For 'diag' events captured from the dephasing
    appliers the targets are already FLATTENED-state coordinates (column
    qubits at q + n explicit); 'channel' events instead carry ROW targets
    only -- their lowering (_lower_channel) and access sets
    (circuits._tape_accesses) add the + n column coordinates themselves.
    """
    kind: str
    targets: tuple
    controls: tuple = ()
    states: tuple = ()
    matrix: Optional[np.ndarray] = None   # 'matrix': (2^t, 2^t) complex
    diag: Optional[np.ndarray] = None     # 'diag':   (2^t,) complex
    theta: float = 0.0                    # 'parity'
    superop: Optional[np.ndarray] = None  # 'channel': (4^t, 4^t) complex
    extended: bool = False                # targets already in 2n coords

    @property
    def support(self) -> frozenset:
        return frozenset(self.targets) | frozenset(self.controls)


class _SpyAmps:
    """Stands in for ``qureg.amps`` during capture: carries a dtype for
    validation tolerances, raises on any real use."""

    def __init__(self, dtype):
        self.dtype = dtype


class _SpyQureg:
    """Minimal stand-in satisfying validation + the patched primitives."""

    def __init__(self, num_qubits: int, is_density: bool, dtype):
        self.num_qubits_represented = int(num_qubits)
        self.is_density_matrix = bool(is_density)
        self.amps = _SpyAmps(dtype)
        self.qasm_log = None
        self.env = None

    @property
    def num_qubits_in_state_vec(self):
        return (2 if self.is_density_matrix else 1) * self.num_qubits_represented

    @property
    def dtype(self):
        return self.amps.dtype

    @property
    def eps(self):
        return precision.eps_for_dtype(self.amps.dtype)

    def put(self, amps):  # swapGate's inline path calls this with the token
        self.amps = amps


@contextlib.contextmanager
def _channel_capture_ctx(events: list):
    """Patch the density-channel appliers in :mod:`.ops.density` to record
    events: Kraus channels (via apply_channel) and dephasing diagonals (via
    _diag_dispatch) -- both in flattened 2n coordinates."""
    from .ops import density as DN

    def cap_channel(amps, superop, *, n, targets):
        events.append(GateEvent(
            "channel", tuple(targets),
            superop=np.asarray(superop, dtype=complex), extended=True))
        return amps

    def cap_dens_diag(amps, d, *, n, targets):
        dc = np.asarray(d[0]) + 1j * np.asarray(d[1])
        events.append(GateEvent("diag", tuple(targets), diag=dc,
                                extended=True))
        return amps

    saved = (DN.apply_channel, DN._diag_dispatch)
    DN.apply_channel = cap_channel
    DN._diag_dispatch = cap_dens_diag
    try:
        yield
    finally:
        DN.apply_channel, DN._diag_dispatch = saved


@contextlib.contextmanager
def _aux_capture_ctx(events: list):
    """Patch the operator-level kernel appliers (phase functions, direct
    diagonals, projections, raw matrix applications) to record ACCESS-ONLY
    events (kind 'aux': support coordinates, no operator data). Only the
    deferred scheduler's lookahead (circuits._tape_accesses) uses these --
    the fuser never captures with them, so operator entries keep acting as
    fusion barriers while still exposing their qubit sets to Belady
    eviction."""
    from .ops import apply as KA
    from .ops import diagonal as DG
    from .ops import measure as MS
    from .ops import phasefunc as PFK

    def cap_phase(amps, *a, **kw):
        events.append(GateEvent("aux", tuple(kw["qubits"])))
        return amps

    def cap_diag(amps, d, *, targets, **kw):
        events.append(GateEvent("aux", tuple(targets)))
        return amps

    def cap_project(amps, *, target, **kw):
        events.append(GateEvent("aux", (target,)))
        return amps

    def cap_matrix(amps, m, *, targets, controls=(), **kw):
        events.append(GateEvent("aux", tuple(targets), tuple(controls)))
        return amps

    saved = (PFK.apply_poly_phase, PFK.apply_named_phase, DG.apply_diagonal,
             MS.project_statevec, KA.apply_matrix)
    PFK.apply_poly_phase = cap_phase
    PFK.apply_named_phase = cap_phase
    DG.apply_diagonal = cap_diag
    MS.project_statevec = cap_project
    KA.apply_matrix = cap_matrix
    try:
        yield
    finally:
        (PFK.apply_poly_phase, PFK.apply_named_phase, DG.apply_diagonal,
         MS.project_statevec, KA.apply_matrix) = saved


@contextlib.contextmanager
def _capture_ctx(events: list):
    """Patch the gate primitives in :mod:`.gates` to record events."""
    from . import gates as G
    from .ops import apply as K

    def cap_matrix(qureg, matrix, targets, controls=(), states=()):
        events.append(GateEvent(
            "matrix", tuple(targets), tuple(controls), tuple(states),
            matrix=np.asarray(matrix, dtype=complex)))

    def cap_diag(qureg, diag, targets, controls=()):
        events.append(GateEvent(
            "diag", tuple(targets), tuple(controls),
            diag=np.asarray(diag, dtype=complex).reshape(-1)))

    def cap_x(qureg, targets, controls=(), states=()):
        events.append(GateEvent("x", tuple(targets), tuple(controls), tuple(states)))

    def cap_parity(qureg, theta, qubits, controls=()):
        events.append(GateEvent(
            "parity", tuple(qubits), tuple(controls), theta=float(theta)))

    def cap_swap(amps, *, n, qb1, qb2, controls=()):
        events.append(GateEvent("swap", (qb1, qb2), tuple(controls)))
        return amps

    saved = (G._apply_gate_matrix, G._apply_gate_diag, G._apply_gate_x,
             G._apply_gate_parity_phase, K.apply_swap)
    G._apply_gate_matrix = cap_matrix
    G._apply_gate_diag = cap_diag
    G._apply_gate_x = cap_x
    G._apply_gate_parity_phase = cap_parity
    K.apply_swap = cap_swap
    try:
        yield
    finally:
        (G._apply_gate_matrix, G._apply_gate_diag, G._apply_gate_x,
         G._apply_gate_parity_phase, K.apply_swap) = saved


def _entry_has_params(args, kwargs) -> bool:
    """True when a tape entry carries engine.params.Param placeholders: the
    planner never spy-captures it (there is no concrete matrix to fuse at
    plan time) -- the entry passes through as a barrier whose matrix is
    assembled from the traced runtime scalars at apply time, so the plan's
    STRUCTURE stays value-independent and one compiled replay serves every
    parameter vector."""
    from .engine.params import has_params

    return has_params(args, kwargs)


def capture(fn, args, kwargs, num_qubits: int, dtype,
            is_density: bool = False, aux: bool = False) -> Optional[list]:
    """Replay one tape entry against a spy register; return its GateEvents,
    or None if the entry doesn't route through the capturable primitives
    (it then acts as a fusion barrier and runs on the device path
    unchanged).

    The first attempt always uses a STATE-VECTOR spy: gate functions with
    inline density branches (swapGate) would otherwise record their shadow
    op too, and shadows are derived at planning/emission instead. Entries
    that fail that attempt on a density tape (decoherence channels, whose
    validation demands a density register) get a second attempt against a
    density spy with the channel appliers patched -- their events carry
    flattened-state coordinates and ``extended=True``.

    ``aux=True`` additionally patches the operator-level appliers
    (_aux_capture_ctx) so phase-function/projector/matrixN entries yield
    access-only 'aux' events -- used by the deferred scheduler's lookahead,
    never by the fuser (aux events carry no operator data)."""
    from .parallel import scheduler as _dist

    # trajectory-noise sites (and anything else tagged _fusion_barrier)
    # assemble their operator at apply time from runtime PRNG draws: there
    # is no static event to capture, even with a constant seed. The
    # mid-circuit measurement/collapse entries of sampling.measure carry
    # the same tag: their one-hot collapse mask is a function of the
    # runtime draw (or of the state's own marginal), so a measurement
    # site is always a fusion barrier -- gate runs fuse up to it and
    # resume after it, mirroring the segment seam it also forces.
    if getattr(fn, "_fusion_barrier", False):
        return None

    aux_ctx = _aux_capture_ctx if aux else _null_ctx
    events: list = []
    shell = _SpyQureg(num_qubits, False, dtype)
    try:
        # suspend any active distributed scheduler: the spy replay must not
        # route through (or mutate) it -- swapGate's inline dispatch would
        # otherwise record phantom virtual swaps in its layout/stats
        with _dist.explicit_mesh(None), _capture_ctx(events), \
                aux_ctx(events):
            fn(shell, *args, **kwargs)
        return events if events else None
    except Exception:
        pass
    if not is_density:
        return None
    events = []
    shell = _SpyQureg(num_qubits, True, dtype)
    try:
        with _dist.explicit_mesh(None), _capture_ctx(events), \
                _channel_capture_ctx(events), aux_ctx(events):
            fn(shell, *args, **kwargs)
    except Exception:
        return None
    return events if events else None


@contextlib.contextmanager
def _null_ctx(events):
    yield


def event_dagger(ev: GateEvent) -> GateEvent:
    """The exact inverse of a captured unitary event, as a new event.

    Unitary kinds only: 'matrix' conjugate-transposes its block, 'diag'
    conjugates its diagonal, 'parity' negates its angle, 'x' and 'swap'
    are self-inverse. 'channel'/'aux' events (and ``extended`` density
    shadows) are not unitary -- no inverse exists; raising here is what
    lets the adjoint gradient planner (quest_tpu/gradients/adjoint.py)
    turn "cannot invert" into a typed lift-time error naming the site.
    """
    if ev.kind == "matrix" and ev.matrix is not None and not ev.extended:
        return GateEvent("matrix", ev.targets, ev.controls, ev.states,
                         matrix=np.conj(np.asarray(ev.matrix)).T)
    if ev.kind == "diag" and ev.diag is not None and not ev.extended:
        return GateEvent("diag", ev.targets, ev.controls, ev.states,
                         diag=np.conj(np.asarray(ev.diag)))
    if ev.kind == "parity":
        return GateEvent("parity", ev.targets, ev.controls, ev.states,
                         theta=-ev.theta)
    if ev.kind in ("x", "swap"):
        return ev
    raise ValueError(f"'{ev.kind}' event has no unitary inverse")


# ---------------------------------------------------------------------------
# dense embedding of one event into a block's qubit space
# ---------------------------------------------------------------------------

def event_matrix(ev: GateEvent, block_qubits: Sequence[int]) -> np.ndarray:
    """The event's full operator on ``block_qubits`` (ascending order; qubit
    block_qubits[j] is bit j of the matrix index). Controls are folded in
    (identity on control-unsatisfied states). Matrix index convention matches
    apply_matrix: for the event's own matrix, targets[k] is bit k
    (reference multiQubitUnitary doc, QuEST.h:5193)."""
    pos = {q: j for j, q in enumerate(block_qubits)}
    k = len(block_qubits)
    N = 1 << k
    out = np.zeros((N, N), dtype=complex)

    cbits = [pos[c] for c in ev.controls]
    states = ev.states if ev.states else (1,) * len(ev.controls)
    tbits = [pos[q] for q in ev.targets]
    t = len(ev.targets)

    if ev.kind == "matrix":
        M = ev.matrix
    elif ev.kind == "diag":
        M = np.diag(ev.diag)
    elif ev.kind == "x":
        M = None  # pure bit-flip, handled per column below
    elif ev.kind == "swap":
        M = np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                      [0, 1, 0, 0], [0, 0, 0, 1]], dtype=complex)
    elif ev.kind == "parity":
        # exp(-i theta/2 Z x...x Z): diagonal, phase sign by parity of bits
        d = np.empty(1 << t, dtype=complex)
        for s in range(1 << t):
            par = bin(s).count("1") & 1
            d[s] = np.exp(-1j * ev.theta / 2 * (1 - 2 * par))
        M = np.diag(d)
    else:  # pragma: no cover
        raise ValueError(f"unknown event kind {ev.kind!r}")

    for s in range(N):
        if any(((s >> c) & 1) != st for c, st in zip(cbits, states)):
            out[s, s] = 1.0
            continue
        if ev.kind == "x":
            s2 = s
            for b in tbits:
                s2 ^= 1 << b
            out[s2, s] = 1.0
            continue
        col = 0
        for j, b in enumerate(tbits):
            col |= ((s >> b) & 1) << j
        base = s
        for b in tbits:
            base &= ~(1 << b)
        for row in range(1 << t):
            s2 = base
            for j, b in enumerate(tbits):
                s2 |= ((row >> j) & 1) << b
            out[s2, s] = M[row, col]
    return out


def _embed_block(U: np.ndarray, old_qubits: Sequence[int],
                 new_qubits: Sequence[int]) -> np.ndarray:
    """Re-embed a block unitary when its qubit set grows (kron with identity
    on the added qubits, bits interleaved by qubit order)."""
    if tuple(old_qubits) == tuple(new_qubits):
        return U
    ev = GateEvent("matrix", tuple(old_qubits), matrix=U)
    return event_matrix(ev, new_qubits)


# ---------------------------------------------------------------------------
# the fuser
# ---------------------------------------------------------------------------

_DIAG_KINDS = ("diag", "parity")


def _event_is_diag(ev: GateEvent) -> bool:
    return ev.kind in _DIAG_KINDS


def _event_diag(ev: GateEvent, qubits: Sequence[int]) -> np.ndarray:
    """The event's diagonal over ``qubits`` (ascending; qubits[j] is bit j).
    Only valid for diagonal-kind events; controls folded in."""
    pos = {q: j for j, q in enumerate(qubits)}
    k = len(qubits)
    cbits = [pos[c] for c in ev.controls]
    states = ev.states if ev.states else (1,) * len(ev.controls)
    tbits = [pos[q] for q in ev.targets]
    out = np.ones(1 << k, dtype=complex)
    for s in range(1 << k):
        if any(((s >> c) & 1) != st for c, st in zip(cbits, states)):
            continue
        if ev.kind == "parity":
            par = bin(sum(((s >> b) & 1) << j for j, b in enumerate(tbits))).count("1") & 1
            out[s] = np.exp(-1j * ev.theta / 2 * (1 - 2 * par))
        else:
            idx = sum(((s >> b) & 1) << j for j, b in enumerate(tbits))
            out[s] = ev.diag[idx]
    return out


@dataclass
class FusedBlock:
    """A dense unitary over a *contiguous* qubit window [qubits[0], qubits[-1]].

    Contiguity is load-bearing: a contiguous window applies with zero
    transposes as one MXU GEMM (ops.apply._apply_matrix_window), whereas
    scattered targets take the grouped-transpose path whose high-rank
    intermediates tile-pad catastrophically at large n."""
    qubits: tuple            # ascending contiguous run; qubits[j] is bit j
    matrix: np.ndarray       # (2^k, 2^k) complex


@dataclass
class DiagBlock:
    """An accumulated diagonal over (possibly scattered) support qubits --
    diagonals broadcast against the grouped view without any transpose, so
    they need no window constraint."""
    qubits: tuple            # ascending; qubits[j] is bit j of the diag index
    diag: np.ndarray         # (2^k,) complex


@dataclass
class FusePlan:
    #: sequence of FusedBlock | DiagBlock | (fn, args, kwargs) passthroughs
    items: list = field(default_factory=list)
    num_fused_gates: int = 0
    num_barriers: int = 0


@dataclass
class PallasRun:
    """A run of tile-local 1-qubit matrices / parity phases executed in ONE
    Pallas HBM pass (ops.pallas_gates.fused_local_run). Gate targets must be
    below ``tile_bits``; controls and parity members may be any qubit.
    Ops are in PHYSICAL coordinates (after any active frame swap).

    ``load_swap_k`` / ``store_swap_k`` fold the frame-switch transpose into
    this run's input gather / output scatter (zero extra HBM passes; see
    ops.pallas_gates._swap_spec): nonzero k means the amps arrive in (or
    must be left in) another frame and the kernel's block specs perform
    the relabeling during DMA. ``load_swap_hi``/``store_swap_hi`` give the
    grid-bit offset of the swapped block (None = tile_bits, the classic
    two-frame case; round 4 generalises to ANY grid block so registers
    wider than 2*tile_bits - LANE_BITS qubits -- e.g. a sharded 34q state
    -- are fully covered by multiple frames). When the executing register
    cannot take the folded path (sharded, mismatched tile geometry), the
    swap runs as an explicit swap_bit_blocks pass instead -- same
    semantics; on a sharded register GSPMD lowers it to ONE collective
    (all-to-all) transpose, the analogue of the reference's swap-to-local
    exchanges (QuEST_cpu_distributed.c:1526-1568)."""
    ops: tuple
    tile_bits: int
    load_swap_k: int = 0
    store_swap_k: int = 0
    load_swap_hi: int | None = None
    store_swap_hi: int | None = None
    #: manual-DMA ring depth override for this run (None = the process
    #: default: QUEST_PALLAS_RING env, else pallas_gates._DEF_RING_DEPTH)
    ring_depth: int | None = None
    #: comm-pipeline depth for the collective frame relabelings this run
    #: triggers under the explicit scheduler (None = the scheduler's /
    #: QUEST_COMM_PIPELINE default; bit-identical at every depth --
    #: exchange.dist_permute_bits)
    comm_pipeline: int | None = None
    #: frame-identity segment index this run belongs to (round 13:
    #: quest_tpu.segments.stamp_plan; plancheck QT107 re-derives and
    #: checks it). Plan-time annotation only -- ignored at apply time;
    #: None on pre-round-13 tapes and unplanned items.
    seg: int | None = None
    #: per-link-class pipeline depth (round 15): sub-collectives of this
    #: run's frame relabelings that cross a DCN shard bit pipeline at
    #: this depth instead of ``comm_pipeline`` (None = inherit --
    #: QUEST_COMM_PIPELINE_DCN env, else the base depth). Encoded LAST
    #: in the tape entry; pre-round-15 tapes decode to None.
    comm_pipeline_dcn: int | None = None


@dataclass
class FrameSwap:
    """Exchange the k-bit grid block [hi, hi+k) (hi = None means
    tile_bits) with the sublane block [tile_bits-k, tile_bits): one
    bandwidth-cost transpose (ops.pallas_gates.swap_bit_blocks) that
    relabels high qubits tile-local so the next PallasRun can target them.
    Self-inverse; the planner always returns the register to the identity
    frame before any non-Pallas item. On sharded registers the transpose
    is a collective when [hi, hi+k) includes sharded qubits, and
    shard-local otherwise."""
    tile_bits: int
    k: int
    hi: int | None = None
    #: comm-pipeline depth when the transpose rides the scheduler's
    #: grouped permute collective (None = default; see PallasRun)
    comm_pipeline: int | None = None
    #: frame-identity segment index (see PallasRun.seg)
    seg: int | None = None
    #: DCN-crossing pipeline depth (round 15; see PallasRun)
    comm_pipeline_dcn: int | None = None


def _window(qubits) -> tuple:
    return tuple(range(min(qubits), max(qubits) + 1))


# ---------------------------------------------------------------------------
# two-frame Pallas planning
#
# The fused Pallas kernel can target any qubit below tile_bits (in-tile) and
# can use any qubit diagonally (controls, parity members, diagonal targets
# -- grid bits enter as per-program scalars). The only thing it cannot do is
# a dense target on a grid bit. The planner therefore runs the circuit in
# two alternating qubit labelings ("frames"):
#
#   frame A: identity; in-tile logical qubits = [0, tile_bits)
#   frame B: grid block [tile_bits, tile_bits+k) swapped with sublane block
#            [tile_bits-k, tile_bits); in-tile = [0, tile_bits-k) and
#            [tile_bits, tile_bits+k)
#
# with k = min(num grid bits, num sublane bits). Switching frames is ONE
# bandwidth-cost transpose (swap_bit_blocks, ~ the elementwise floor), so a
# deep circuit executes as [run_A][swap][run_B][swap][run_A]... -- every
# gate rides a fused single-HBM-pass kernel and the whole layer costs ~2
# kernel passes + ~2 transposes instead of one einsum block per high-qubit
# window (the round-1 scheme: 60 blocks for a 26q depth-8 circuit; this
# scheme: ~32 passes). This generalises the reference's swap-to-local trick
# (QuEST_cpu_distributed.c:1526-1568) from one qubit per exchange to the
# whole high block per transpose.
# ---------------------------------------------------------------------------

@dataclass
class _POp:
    """A primitive op in LOGICAL coordinates plus its diagonality roles."""
    kind: str            # 'matrix' | 'swap' | 'diagw' | 'parity'
    targets: tuple
    controls: tuple
    states: tuple
    data: object         # matrix ndarray | diag ndarray | theta
    diag_targets: bool   # True if the op acts diagonally on its targets

    @property
    def support(self):
        return frozenset(self.targets) | frozenset(self.controls)

    def diag_on(self, q: int) -> bool:
        return q in self.controls or self.diag_targets


def _lower_event(ev: GateEvent):
    """GateEvent -> list of _POp, or None if not expressible as kernel ops
    (dense multi-qubit matrices, wide diagonals)."""
    states = tuple(ev.states) if ev.states else (1,) * len(ev.controls)
    ctrls = tuple(ev.controls)
    if ev.kind == "parity":
        return [_POp("parity", tuple(ev.targets), ctrls, (), float(ev.theta), True)]
    if ev.kind == "swap":
        return [_POp("swap", tuple(ev.targets), ctrls, states, None, False)]
    if ev.kind == "x":
        # C[X (x) X ...] = product of single-target CXs (identical controls)
        X = np.array([[0, 1], [1, 0]], dtype=complex)
        return [_POp("matrix", (t,), ctrls, states, X, False)
                for t in ev.targets]
    if ev.kind == "diag":
        if len(ev.targets) == 1:
            return [_POp("matrix", tuple(ev.targets), ctrls, states,
                         np.diag(ev.diag), True)]
        if len(ev.targets) <= 5:
            if any(s == 0 for s in states):
                # the kernel diagw op has no control-state slot; an
                # anti-controlled wide diagonal must not silently drop its
                # states -- run the entry through the ordinary engine
                return None
            return [_POp("diagw", tuple(ev.targets), ctrls, (),
                         np.asarray(ev.diag).reshape(-1), True)]
        return None
    if ev.kind == "matrix":
        if len(ev.targets) != 1:
            return None
        m = np.asarray(ev.matrix)
        is_diag = m[0, 1] == 0 and m[1, 0] == 0
        return [_POp("matrix", tuple(ev.targets), ctrls, states, m, is_diag)]
    return None  # pragma: no cover


#: max kernel primitive ops per emitted PallasRun (pre-fold); splitting a
#: longer run costs one extra HBM pass (the bench circuit's 8-pass
#: structural floor is worth more than compile time: capping at 48 split
#: it to 10 passes and cost ~4% of throughput), but the cap must exist:
#: Mosaic compile time is strongly superlinear in op count (round-4
#: matrix at 2^26: 24 ops 16 s, 48 ops 112 s, 96 ops 737 s) and a 20q
#: mono-kernel at 316 ops ran past 20 minutes. 96 covers the bench's
#: largest natural run; the persistent compilation cache amortises the
#: one-time cost.
_RUN_OP_CAP = 96


class _FramePlanner:
    """Greedy multi-frame scheduler over an ordered list of pending runs
    (see the Scheduling paragraph below; the eager two-slot variant lives
    in _FramePlannerTwoSlot).

    A *frame* is a qubit relabeling: ``None`` is the identity; ``(hi, kf)``
    means the grid-bit block [hi, hi+kf) is swapped with the sublane block
    [tb-kf, tb). The candidate frames tile the grid bits in k-sized blocks
    from tb upward, so EVERY qubit of an arbitrarily wide (e.g. sharded)
    register is in-tile in some frame -- the round-4 generalisation that
    lets a sharded 34q register execute fused PallasRuns per shard with
    each frame switch one (collective) transpose (VERDICT r3 missing #1).

    Scheduling (round-4b): an ordered list of PENDING runs, each pinned
    to a frame. A new op joins the EARLIEST run whose frame localises it
    and whose every LATER pending op commutes past it (runs execute in
    list order; an op placed in run i runs before everything in runs
    j > i, so it must commute with what is already there -- and later
    arrivals into runs j < i check against it symmetrically). Ops that
    fit nowhere open a new run. Holding every run open until flush lets
    late ops join early runs, which cuts frame alternations well below
    the two-slot (open + one lookahead) round-4a scheme on >=3-frame
    plans (34q sharded, density tapes)."""

    def __init__(self, out: FusePlan, tile_bits: int, k: int, nsv: int,
                 boundary: int | None = None):
        self.out = out
        self.tb = tile_bits
        self.k = k
        self.nsv = nsv
        self.boundary = boundary  # shard-local qubit count (or None)
        #: candidate frames: identity + one per k-wide grid block. Block
        #: edges align to ``boundary`` (the shard-local qubit count) so
        #: frames stay entirely below it where possible -- their
        #: transposes are then shard-LOCAL (no collective); only frames
        #: reaching into the sharded bits pay an all-to-all
        self.frames = [None]
        edges = [tile_bits, nsv]
        if boundary is not None and tile_bits < boundary < nsv:
            edges.insert(1, boundary)
        for lo, hi_edge in zip(edges, edges[1:]):
            hi = lo
            while k > 0 and hi < hi_edge:
                self.frames.append((hi, min(k, hi_edge - hi)))
                hi += k
        self.cur_frame = None        # physical frame of the amps stream
        self.runs = []               # ordered pending [frame, [_POp]]

    # -- frame geometry -----------------------------------------------------

    def phys(self, q: int, frame) -> int:
        if frame is None:
            return q
        hi, kf = frame
        if self.tb - kf <= q < self.tb:
            return q - (self.tb - kf) + hi
        if hi <= q < hi + kf:
            return q - hi + (self.tb - kf)
        return q

    def feasible(self, op: _POp, frame) -> bool:
        if op.kind in ("parity", "diagw") or (op.kind == "matrix" and op.diag_targets):
            return True
        return all(self.phys(t, frame) < self.tb for t in op.targets)

    def _frame_for(self, op: _POp, exclude):
        for f in self.frames:
            if f != exclude and self.feasible(op, f):
                return f
        f = self._synth_frame(op)
        if f is not None and f != exclude:
            self.frames.append(f)
            return f
        return Ellipsis

    def _synth_frame(self, op: _POp):
        """Invent a frame when the static k-block tiling localises none
        (round 5): the fixed tiling displaces the sublane block
        [tb-k, tb), so an op pairing a HIGH qubit with a row target
        inside that block -- e.g. a 17q density channel's (row 16,
        column 33) kraus pair over a 19-bit shard tile -- fits no
        candidate. A bespoke block [hi0, hi0+kf) anchored at the op's
        high targets, with kf kept small enough that the displaced
        sublane region avoids the op's low targets, restores coverage.
        The synthesized frame joins ``self.frames`` so later ops (and
        the run scheduler) reuse it.

        When a shard boundary is set and the minimal span block straddles
        it, boundary-CLIPPED anchors are tried first (round 6, closing the
        last round-5 ADVICE finding): a clipped block keeps its transposes
        shard-local (or confines the collective to the genuinely sharded
        bits), so a straddling frame -- whose reuse by later ops would pay
        collective transposes they don't need -- is accepted only when no
        clipped anchor localises the op."""
        targs = tuple(op.targets)
        high = sorted(t for t in targs if t >= self.tb)
        if not high or self.k <= 0:
            return None
        lo_t = [t for t in targs if t < self.tb]
        max_lo = max(lo_t, default=-1)
        hi0 = high[0]
        kf = high[-1] + 1 - hi0
        b = self.boundary
        cands = []
        if b is not None and hi0 < b < hi0 + kf:
            # span block straddles the boundary: clipped anchors first
            cands.append((hi0, b - hi0))
            cands.append((b, high[-1] + 1 - b))
        cands.append((hi0, kf))
        for a0, w in cands:
            # the displaced region [tb-w, tb) must stay above every low
            # target, and the block must fit the frame width and register
            if w <= 0 or w > self.k or w >= self.tb - max_lo \
                    or a0 + w > self.nsv:
                continue
            f = (a0, w)
            if self.feasible(op, f):
                return f
        return None

    def feasible_somewhere(self, op: _POp) -> bool:
        return (any(self.feasible(op, f) for f in self.frames)
                or self._synth_frame(op) is not None)

    # -- emission -----------------------------------------------------------

    def _leave_cur_frame(self):
        """Fold the undo of the current frame into the last run's output
        scatter, or emit an explicit FrameSwap."""
        if self.cur_frame is None:
            return
        hi, kf = self.cur_frame
        last = self.out.items[-1] if self.out.items else None
        if isinstance(last, PallasRun) and last.store_swap_k == 0:
            last.store_swap_k = kf
            last.store_swap_hi = hi
        else:  # pragma: no cover - a run always precedes a non-identity frame
            self.out.items.append(FrameSwap(self.tb, kf, hi))
        self.cur_frame = None

    def _emit_run(self, frame, ops: list):
        if not ops:
            return
        load_k, load_hi = 0, None
        if self.cur_frame != frame:
            # leaving one non-identity frame for another: the undo folds
            # into the PREVIOUS run's store DMA, the new frame's swap into
            # THIS run's load DMA -- still zero extra HBM passes
            self._leave_cur_frame()
            if frame is not None:
                load_hi, load_k = frame
            self.cur_frame = frame
        # cap ops per kernel: Mosaic compile time explodes past a few
        # hundred ops in one program (20q mono-kernel probe: >20 min at
        # 316 ops), so over-long runs split into consecutive passes; only
        # the first carries the folded frame-entry swap
        phys = [self._phys_op(op, frame) for op in ops]
        for i in range(0, len(phys), _RUN_OP_CAP):
            self.out.items.append(PallasRun(
                tuple(phys[i:i + _RUN_OP_CAP]), self.tb,
                load_swap_k=load_k if i == 0 else 0,
                load_swap_hi=load_hi if i == 0 else None))

    def _phys_op(self, op: _POp, frame):
        from .ops.pallas_gates import HashableMatrix

        t = tuple(self.phys(q, frame) for q in op.targets)
        c = tuple(self.phys(q, frame) for q in op.controls)
        if op.kind == "matrix":
            return ("matrix", t[0], c, op.states, HashableMatrix(op.data))
        if op.kind == "swap":
            return ("swap", t[0], t[1], c, op.states)
        if op.kind == "kraus1":
            return ("kraus1", t[0], t[1], op.data)
        if op.kind == "kraus2":
            return ("kraus2", t[0], t[1], t[2], t[3], op.data)
        if op.kind == "krausn":
            h = len(t) // 2
            return ("krausn", t[:h], t[h:], op.data)
        if op.kind == "diagw":
            return ("diagw", t, c, HashableMatrix(op.data))
        return ("parity", t, c, op.data)

    def flush(self):
        """Emit every pending run in order and return to the identity."""
        for frame, ops in self.runs:
            self._emit_run(frame, ops)
        self._leave_cur_frame()
        self.runs = []

    # -- scheduling ---------------------------------------------------------

    def add(self, op: _POp):
        # earliest run that localises the op AND whose every later op
        # commutes past it (see class docstring for the ordering argument)
        for i, (frame, ops) in enumerate(self.runs):
            if not self.feasible(op, frame):
                continue
            if all(self._commutes(op, other)
                   for _, later in self.runs[i + 1:] for other in later):
                ops.append(op)
                return
        f = self._frame_for(op, exclude=Ellipsis)
        if f is Ellipsis:  # pragma: no cover - callers pre-check
            raise AssertionError("op feasible in no frame reached the scheduler")
        self.runs.append([f, [op]])

    @staticmethod
    def _commutes(a: _POp, b: _POp) -> bool:
        return all(a.diag_on(q) and b.diag_on(q)
                   for q in a.support & b.support)


class _FramePlannerTwoSlot(_FramePlanner):
    """The round-4a two-slot variant: one OPEN run plus one lookahead run,
    rotated eagerly when an op fits neither. Kept alongside the ordered-
    list scheduler because neither dominates: eager rotation balances
    two-frame tapes better (26q bench: 8 raw runs vs the list's 9, whose
    first run absorbs 153 ops and then pays an op-cap split), while the
    list wins on >=3-frame plans (34q sharded: 14 passes vs 42).
    _plan_pallas schedules with both and keeps the cheaper plan."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.open = [None, []]       # [frame, [_POp]]
        self.next = [Ellipsis, []]   # Ellipsis = frame not yet chosen

    def rotate(self):
        frame, ops = self.open
        self._emit_run(frame, ops)
        self.open = self.next
        if self.open[0] is Ellipsis:
            self.open[0] = None
        self.next = [Ellipsis, []]

    def flush(self):
        self._emit_run(*self.open)
        if self.next[0] is not Ellipsis:
            self._emit_run(*self.next)
        self._leave_cur_frame()
        self.open = [None, []]
        self.next = [Ellipsis, []]

    def add(self, op: _POp):
        for _ in range(3):
            of, oops = self.open
            nf, nops = self.next
            if self.feasible(op, of) and all(
                    self._commutes(op, other) for other in nops):
                oops.append(op)
                return
            if nf is Ellipsis:
                nf = self._frame_for(op, exclude=of)
                if nf is not Ellipsis:
                    self.next[0] = nf
                    nops.append(op)
                    return
            elif self.feasible(op, nf):
                nops.append(op)
                return
            self.rotate()
        raise AssertionError(  # pragma: no cover
            "op feasible in no frame reached the scheduler")


def _record_plan_telemetry(p: FusePlan, mode: str, nsv: int,
                           tile_bits: int | None,
                           shard_qubits: int | None = None) -> None:
    """Flight-record a finished plan's shape: item mix, frame-transpose
    counts, tile geometry. One counter per plan plus a structured event
    (the per-plan detail bench.py ships in BENCH_DETAIL.json)."""
    if not telemetry.enabled():
        return
    runs = [i for i in p.items if isinstance(i, PallasRun)]
    folded = sum((1 if r.load_swap_k else 0) + (1 if r.store_swap_k else 0)
                 for r in runs)
    explicit = sum(isinstance(i, FrameSwap) for i in p.items)
    telemetry.inc("fusion_plans_total", mode=mode)
    telemetry.inc("fusion_fused_gates_total", p.num_fused_gates, mode=mode)
    telemetry.inc("fusion_barriers_total", p.num_barriers, mode=mode)
    telemetry.inc("fusion_pallas_runs_total", len(runs), mode=mode)
    telemetry.inc("fusion_frame_transposes_total", folded + explicit,
                  mode=mode)
    telemetry.event(
        "fusion.plan", mode=mode, nsv=nsv, tile_bits=tile_bits,
        items=len(p.items), pallas_runs=len(runs),
        dense_blocks=sum(isinstance(i, FusedBlock) for i in p.items),
        diag_blocks=sum(isinstance(i, DiagBlock) for i in p.items),
        frame_transposes=folded + explicit,
        ops_per_run=[len(r.ops) for r in runs],
        fused_gates=p.num_fused_gates, barriers=p.num_barriers,
        **(transpose_stats(p, shard_qubits)
           if shard_qubits is not None else {}))


def plan(tape, num_qubits: int, dtype, max_qubits: int = 5,
         max_diag_qubits: int = 12, pallas_tile_bits: int | None = None,
         is_density: bool = False,
         shard_boundary: int | None = None) -> FusePlan:
    """Greedy left-to-right fusion of a Circuit tape.

    Without ``pallas_tile_bits``: dense events merge while the combined
    contiguous window spans at most ``max_qubits``; diagonal events (phase
    gates, Z-rotations, parity phases) merge by support up to
    ``max_diag_qubits`` regardless of span. A tape entry that fails capture,
    or containing an event too wide for either rule, flushes the current
    block and passes through unchanged.

    With ``pallas_tile_bits``: two-frame Pallas planning (see the
    _FramePlanner block comment) -- every expressible gate joins a fused
    single-HBM-pass kernel run, with frame swaps localising high qubits;
    only dense multi-qubit matrices fall out as window blocks.
    ``is_density`` extends this to density tapes: the captured row ops gain
    explicit conj-shadow twins on (targets + n) and the planner schedules
    both over the flattened 2n-qubit state -- the column qubits are just
    more high qubits for the frame machinery to relabel (the round-2 build
    excluded density tapes entirely; VERDICT r2 missing #1).
    """
    nsv = (2 if is_density else 1) * num_qubits
    if pallas_tile_bits is not None:
        with telemetry.span("fusion.plan", mode="pallas"):
            p = _plan_pallas(tape, num_qubits, dtype, max_qubits,
                             pallas_tile_bits, is_density=is_density,
                             shard_boundary=shard_boundary)
        _record_plan_telemetry(p, "pallas", nsv, pallas_tile_bits)
        return p
    import time as _time
    _t0 = _time.perf_counter()
    out = FusePlan()
    cur = None  # None | FusedBlock | DiagBlock (mutable accumulators)

    def flush():
        nonlocal cur
        if cur is not None:
            out.items.append(cur)
        cur = None

    def window_ok(joint):
        return len(joint) <= max_qubits

    def add_dense(ev):
        nonlocal cur
        win = _window(ev.support)
        if isinstance(cur, DiagBlock):
            joint = _window(set(cur.qubits) | ev.support)
            if window_ok(joint):
                cur = FusedBlock(joint, np.diag(
                    _event_diag(GateEvent("diag", cur.qubits, diag=cur.diag),
                                joint)))
            else:
                flush()
        if isinstance(cur, FusedBlock):
            joint = _window(set(cur.qubits) | ev.support)
            if window_ok(joint):
                U = _embed_block(cur.matrix, cur.qubits, joint)
                cur = FusedBlock(joint, event_matrix(ev, joint) @ U)
                return
            flush()
        cur = FusedBlock(win, event_matrix(ev, win))

    def add_diag(ev):
        nonlocal cur
        if isinstance(cur, FusedBlock):
            joint = _window(set(cur.qubits) | ev.support)
            if window_ok(joint):
                cur = FusedBlock(joint,
                                 np.diag(_event_diag(ev, joint)) @
                                 _embed_block(cur.matrix, cur.qubits, joint))
                return
            flush()
        if isinstance(cur, DiagBlock):
            joint = tuple(sorted(set(cur.qubits) | ev.support))
            if len(joint) <= max_diag_qubits:
                d = _event_diag(GateEvent("diag", cur.qubits, diag=cur.diag), joint)
                cur = DiagBlock(joint, d * _event_diag(ev, joint))
                return
            flush()
        qs = tuple(sorted(ev.support))
        cur = DiagBlock(qs, _event_diag(ev, qs))

    for fn, args, kwargs in tape:
        if _entry_has_params(args, kwargs):
            flush()
            out.items.append((fn, args, kwargs))
            out.num_barriers += 1
            telemetry.inc("fusion_param_barriers_total", mode="dense")
            continue
        events = capture(fn, args, kwargs, num_qubits, dtype)
        fusible = events is not None and all(
            (len(ev.support) <= max_diag_qubits) if _event_is_diag(ev)
            else (len(_window(ev.support)) <= max_qubits)
            for ev in events)
        if not fusible:
            flush()
            out.items.append((fn, args, kwargs))
            out.num_barriers += 1
            continue
        for ev in events:
            if _event_is_diag(ev):
                add_diag(ev)
            else:
                add_dense(ev)
            out.num_fused_gates += 1
    flush()
    telemetry.observe("fusion.plan_seconds", _time.perf_counter() - _t0,
                      mode="dense")
    _record_plan_telemetry(out, "dense", nsv, None)
    return out


#: widest channel the krausn kernel op takes: each extra target doubles the
#: matn delta count (4^t coefficient selects per term), so t=3 (a 512-delta
#: pair of matn sweeps per Kraus term) is the practical in-register ceiling
_KRAUSN_MAX_TARGETS = 3


def _lower_channel(ev: GateEvent, n: int):
    """'channel' event -> [_POp('kraus1'|'kraus2'|'krausn', extended
    targets, ...)] for <= _KRAUSN_MAX_TARGETS-target Kraus maps, or None
    (wider channels stay barriers and run the engine path). The op's data
    is the hashable Kraus-term tuple ((sign, K), ...) from the
    superoperator's Choi decomposition -- ALL arities ride the one-pass
    kernel, mirroring the reference's single superoperator mechanism for
    every channel width (QuEST_common.c:581-638)."""
    from .ops.density import choi_kraus
    from .ops.pallas_gates import HashableMatrix

    if not 1 <= len(ev.targets) <= _KRAUSN_MAX_TARGETS:
        return None
    terms = tuple((float(s), HashableMatrix(k))
                  for s, k in choi_kraus(ev.superop))
    if len(ev.targets) == 1:
        t = ev.targets[0]
        return [_POp("kraus1", (t, t + n), (), (), terms, False)]
    if len(ev.targets) == 2:
        t1, t2 = ev.targets
        return [_POp("kraus2", (t1, t2, t1 + n, t2 + n), (), (), terms,
                     False)]
    rows = tuple(ev.targets)
    return [_POp("krausn", rows + tuple(q + n for q in rows), (), (),
                 terms, False)]


def _shadow_pop(op: _POp, n: int) -> _POp:
    """The density conj-shadow twin of a lowered row op: same op on the
    column qubits (q + n) with conjugated data (QuEST.c:184-193). Parity
    phases conjugate by negating theta; swaps are real."""
    targets = tuple(q + n for q in op.targets)
    controls = tuple(q + n for q in op.controls)
    if op.kind == "parity":
        data = -float(op.data)
    elif op.kind == "swap":
        data = op.data
    else:  # 'matrix' | 'diagw'
        data = np.conj(np.asarray(op.data))
    return _POp(op.kind, targets, controls, op.states, data, op.diag_targets)


def transpose_stats(p: FusePlan, shard_qubits: int | None,
                    nsv: int | None = None, num_slices: int = 1) -> dict:
    """(collective, local) frame-transpose counts of a pallas plan: a
    relabeling is a cross-device collective exactly when its grid block
    reaches a sharded qubit (>= ``shard_qubits``); None counts all as
    local (single device).

    With ``nsv`` and ``num_slices`` > 1, collective transposes further
    split by the interconnect they ride on a slice-major pod topology
    (parallel.mesh.shard_bit_link): a transpose whose grid block reaches
    one of the top log2(num_slices) shard bits crosses slices (DCN);
    the rest stay on the intra-slice ICI axis."""
    coll = loc = dcn = 0
    slice_bits = (num_slices - 1).bit_length() if num_slices > 1 else 0
    for i in p.items:
        swaps = []
        if isinstance(i, PallasRun):
            for k, hi in ((i.load_swap_k, i.load_swap_hi),
                          (i.store_swap_k, i.store_swap_hi)):
                if k:
                    swaps.append((k, i.tile_bits if hi is None else hi))
        elif isinstance(i, FrameSwap):
            swaps.append((i.k, i.tile_bits if i.hi is None else i.hi))
        for k, hi in swaps:
            if shard_qubits is not None and hi + k > shard_qubits:
                coll += 1
                if nsv is not None and slice_bits and \
                        hi + k > nsv - slice_bits:
                    dcn += 1
            else:
                loc += 1
    out = {"collective_transposes": coll, "local_transposes": loc}
    if nsv is not None and slice_bits:
        out["dcn_transposes"] = dcn
        out["ici_transposes"] = coll - dcn
    return out


def plan_from_tape(tape) -> FusePlan:
    """Decode an ``as_tape`` tape back into a :class:`FusePlan` -- the
    ONE decoder of the `_apply_pallas_run` / `_apply_frame_swap` /
    `_apply_dense_block` / `_apply_gate_diag` tape-entry layouts
    (:func:`as_tape` is the encoder). Entries that aren't plan items pass
    through verbatim as ``(fn, args, kwargs)`` tuples, so
    ``plan_from_tape(as_tape(p))`` round-trips. Used by the bench
    artifacts, the driver dryrun and the static plan verifier
    (analysis.plancheck), which see executed circuits, not plans."""
    p = FusePlan()
    for entry in tape:
        f, a, _kw = entry
        name = getattr(f, "__name__", "")
        if name == "_apply_pallas_run":
            ops, tb, lk, sk, lh, sh = a[:6]
            rd = a[6] if len(a) > 6 else None
            cp = a[7] if len(a) > 7 else None
            sg = a[8] if len(a) > 8 else None
            cpd = a[9] if len(a) > 9 else None
            p.items.append(PallasRun(tuple(ops), tb, load_swap_k=lk,
                                     store_swap_k=sk, load_swap_hi=lh,
                                     store_swap_hi=sh, ring_depth=rd,
                                     comm_pipeline=cp, seg=sg,
                                     comm_pipeline_dcn=cpd))
        elif name == "_apply_frame_swap":
            tb, k, hi = a[:3]
            p.items.append(FrameSwap(tb, k, hi,
                                     comm_pipeline=(a[3] if len(a) > 3
                                                    else None),
                                     seg=(a[4] if len(a) > 4 else None),
                                     comm_pipeline_dcn=(a[5] if len(a) > 5
                                                        else None)))
        elif name == "_apply_dense_block":
            p.items.append(FusedBlock(tuple(a[1]), a[0]))
        elif name == "_apply_gate_diag":
            p.items.append(DiagBlock(tuple(a[1]), a[0]))
        else:
            p.items.append(entry)
    return p


def tape_transpose_stats(tape, shard_qubits: int | None,
                         nsv: int | None = None,
                         num_slices: int = 1) -> dict:
    """:func:`transpose_stats` over an ``as_tape`` tape instead of a
    FusePlan (used by the bench artifacts and the driver dryrun, which
    see executed circuits, not plans)."""
    return transpose_stats(plan_from_tape(tape), shard_qubits, nsv=nsv,
                           num_slices=num_slices)


def plan_pallas_sharded(tape, num_qubits: int, dtype, max_qubits: int,
                        tile_bits: int, n_local: int,
                        is_density: bool = False) -> FusePlan:
    """Plan a sharded register's pallas schedule twice -- frame blocks
    tiled plainly from tile_bits, and aligned to the shard boundary (so
    sub-boundary frames relabel shard-locally) -- and keep whichever plan
    pays fewer collective transposes (ties: fewer total passes). Which
    wins depends on the tape: boundary alignment removes collectives for
    tapes concentrated below the boundary but splits frames (more passes)
    for tapes with dense layers across every qubit."""
    nsv = (2 if is_density else 1) * num_qubits
    boundaries = [None]
    if tile_bits < n_local < nsv:
        # otherwise the aligned tiling is identical and the second full
        # spy-replay of the tape (the dominant trace-time cost) is waste
        boundaries.append(n_local)
    with telemetry.span("fusion.plan", mode="pallas_sharded"):
        cands = [
            _plan_pallas(tape, num_qubits, dtype, max_qubits, tile_bits,
                         is_density=is_density, shard_boundary=b,
                         score_shard_qubits=n_local)
            for b in boundaries
        ]
        best = min(cands, key=lambda p: (
            transpose_stats(p, n_local)["collective_transposes"],
            len(p.items)))
    _record_plan_telemetry(best, "pallas_sharded", nsv, tile_bits,
                           shard_qubits=n_local)
    return best


def _plan_pallas(tape, num_qubits: int, dtype, max_qubits: int,
                 tile_bits: int, is_density: bool = False,
                 shard_boundary: int | None = None,
                 score_shard_qubits: int | None = None) -> FusePlan:
    """Multi-frame Pallas plan: lower every event to kernel primitive ops
    (ONE spy-capture pass over the tape -- the dominant trace-time cost),
    then schedule the lowered stream with BOTH frame schedulers (the
    ordered-list _FramePlanner and the two-slot variant) and keep the
    cheaper plan: fewer passes single-chip, fewer collective transposes
    first when ``score_shard_qubits`` is set. Density tapes
    (``is_density``) plan over the flattened 2n-qubit state: every
    lowered row op is paired with its conj-shadow twin and both are
    scheduled; the emitted PallasRuns then carry EXPLICIT shadow ops, and
    every execution path applies them raw (no shadow re-derivation)."""
    from .ops.pallas_gates import LANE_BITS

    nsv = (2 if is_density else 1) * num_qubits
    k = min(max(nsv - tile_bits, 0), tile_bits - LANE_BITS)

    def make_planner(cls):
        return cls(FusePlan(), tile_bits, k, nsv, boundary=shard_boundary)

    probe = make_planner(_FramePlanner)  # frame geometry only

    # -- pass 1: resolve every tape entry (capture + lower + routability) --
    resolved = []  # ('barrier', entry) | ('events', [(ev, pops|None)])
    for fn, args, kwargs in tape:
        if _entry_has_params(args, kwargs):
            # runtime-parameter entry: apply-time-assembled barrier between
            # the static kernel runs (see _entry_has_params)
            telemetry.inc("fusion_param_barriers_total", mode="pallas")
            resolved.append(("barrier", (fn, args, kwargs)))
            continue
        events = capture(fn, args, kwargs, num_qubits, dtype,
                         is_density=is_density)
        lowered = None
        if events is not None:
            lowered = []
            for ev in events:
                if ev.kind == "channel":
                    pops = _lower_channel(ev, num_qubits)
                else:
                    pops = _lower_event(ev)
                    if pops is not None and is_density and not ev.extended:
                        pops = [q for p in pops
                                for q in (p, _shadow_pop(p, num_qubits))]
                if pops is not None and not all(
                        probe.feasible_somewhere(p) for p in pops):
                    pops = None  # a target no frame localises
                lowered.append(pops)

            def routable(ev, pops):
                if pops is not None:
                    return True
                # dense window fallback -- unitary events only (a channel
                # has no dense 2^w x 2^w unitary to fall back to)
                return (ev.kind != "channel"
                        and len(_window(ev.support)) <= max_qubits)

            if not all(routable(ev, pops)
                       for ev, pops in zip(events, lowered)):
                events = None  # no route for some event: run the entry as-is
        if events is None:
            resolved.append(("barrier", (fn, args, kwargs)))
        else:
            resolved.append(("events", list(zip(events, lowered))))

    # -- pass 2: schedule with each planner, keep the cheaper plan --------
    def schedule(cls):
        sched = make_planner(cls)
        out = sched.out
        for kind, payload in resolved:
            if kind == "barrier":
                sched.flush()
                out.items.append(payload)
                out.num_barriers += 1
                continue
            for ev, pops in payload:
                if pops is not None:
                    for p in pops:
                        sched.add(p)
                else:
                    # dense multi-qubit matrix (or a target no frame
                    # localises): standalone window block through the
                    # engine, identity frame (FusedBlock stays in ROW
                    # coordinates; _apply_dense_block re-derives the
                    # density shadow itself)
                    sched.flush()
                    win = _window(ev.support)
                    out.items.append(FusedBlock(win, event_matrix(ev, win)))
                out.num_fused_gates += 1
        sched.flush()
        return out

    def score(p):
        st = transpose_stats(p, score_shard_qubits)
        if score_shard_qubits is not None:
            return (st["collective_transposes"], len(p.items))
        return (len(p.items), st["local_transposes"])

    return min((schedule(cls)
                for cls in (_FramePlanner, _FramePlannerTwoSlot)), key=score)


import threading

_PALLAS_MESH = threading.local()


@contextlib.contextmanager
def pallas_mesh(mesh):
    """Ambient execution mesh for PallasRuns inside jit traces, where the
    amps tracer hides its sharding. Circuit.run derives it from the actual
    register and activates it around the traced replay, so a fused plan is
    never bound to one device set; set it manually only when calling a
    compiled replay directly on a sharded register (see
    examples/distributed_34q.py)."""
    prev = getattr(_PALLAS_MESH, "mesh", None)
    _PALLAS_MESH.mesh = mesh
    try:
        yield
    finally:
        _PALLAS_MESH.mesh = prev


def active_pallas_mesh():
    return getattr(_PALLAS_MESH, "mesh", None)


def _df_route(dtype) -> bool:
    """True when an f64 register's PallasRuns take the double-float
    (4-plane f32) kernel route: always on the TPU backend (Mosaic has no
    f64 lowering, so df IS the f64 fast path there), opt-in elsewhere via
    ``QUEST_PALLAS_DF=1`` (pallas_df.df_wanted) -- the switch the CPU-mesh
    parity suite and the driver dryrun flip so CI executes the same route
    as the chip. Off: non-TPU f64 keeps the native-f64 interpreter/engine
    policy unchanged."""
    import numpy as np

    from .ops.pallas_df import df_wanted

    return np.dtype(dtype) == np.dtype("float64") and df_wanted()


def _apply_pallas_run(qureg, ops: tuple, tile_bits: int,
                      load_swap_k: int = 0, store_swap_k: int = 0,
                      load_swap_hi: int | None = None,
                      store_swap_hi: int | None = None,
                      ring_depth: int | None = None,
                      comm_pipeline: int | None = None,
                      seg: int | None = None,
                      comm_pipeline_dcn: int | None = None) -> None:
    """Tape-entry wrapper for a PallasRun. Ops are RAW kernel ops over the
    full flattened state: density plans carry explicit conj-shadow twins
    (fusion._shadow_pop), so no path here re-derives shadows.

    Multi-device registers run the kernel PER SHARD under shard_map when
    every op is shard-executable (non-diagonal targets within the shard's
    tile; roles on sharded qubits resolve against the shard index inside
    the kernel -- see fused_local_run's shard_index). PRECISION=2
    registers on the df route (fusion._df_route) run the double-float
    4-plane kernels per shard, chunked at DF_MAX_OPS; under the explicit
    distributed scheduler the per-shard df runs are joined by the
    scheduler's COUNTED grouped permute collectives
    (_sched_df_pallas_run). Otherwise (f32 under the explicit scheduler,
    non-canonical sharding, or a target the shard can't pair) ops replay
    through the sharding-aware engine gate-by-gate, with the reason
    counted in engine_fallback_total.

    Frame swaps annotated on the run (load/store_swap_k) execute folded
    into the kernel's DMA when the executing register's tile geometry
    matches the plan -- single-device, or per-shard when the swapped
    block is SHARD-LOCAL (round 7); every other case (collective
    relabelings reaching sharded bits, geometry mismatches -- the latter
    counted as swap_not_foldable) gets an explicit swap_bit_blocks pass
    before/after -- identical semantics.
    """
    from .ops import pallas_gates as PG
    from .ops.pallas_gates import fused_local_run, swap_bit_blocks
    from .parallel import scheduler as _dist
    from .resilience import guard as _guard

    import jax

    nsv = qureg.num_qubits_in_state_vec

    def pre_swap():
        if load_swap_k:
            telemetry.inc("pallas_pass_total", kind="frame_swap")
            qureg.put(swap_bit_blocks(
                qureg.amps, n=nsv, lo1=tile_bits - load_swap_k,
                lo2=tile_bits if load_swap_hi is None else load_swap_hi,
                k=load_swap_k))

    def post_swap():
        if store_swap_k:
            telemetry.inc("pallas_pass_total", kind="frame_swap")
            qureg.put(swap_bit_blocks(
                qureg.amps, n=nsv, lo1=tile_bits - store_swap_k,
                lo2=tile_bits if store_swap_hi is None else store_swap_hi,
                k=store_swap_k))

    amps = qureg.amps
    sched = _dist.active()

    # --- explicit distributed scheduler x double-float register: the
    # per-shard df fast path, frame relabelings riding the scheduler's
    # counted grouped collectives (ISSUE 3 tentpole) ---
    if (sched is not None and sched.mesh is not None
            and sched.mesh.size > 1 and _df_route(qureg.dtype)):
        # the whole sched-df route is idempotent until its final put
        # (planes re-split from qureg.amps per invocation), so the guard
        # may retry it wholesale; injected compile faults degrade to the
        # engine replay below (reason=fault_degraded)
        res = _guard.pallas_dispatch(
            lambda: _sched_df_pallas_run(
                qureg, ops, sched, tile_bits, load_swap_k, store_swap_k,
                load_swap_hi, store_swap_hi, ring_depth, comm_pipeline,
                comm_pipeline_dcn),
            degrade=lambda: None)
        if res is not _guard.DEGRADED and res:
            return
        # not shard-executable at the df tile geometry (reason counted
        # inside) or fault-degraded: sharding-aware engine replay,
        # explicit swap passes
        pre_swap()
        _apply_ops_via_engine(qureg, ops)
        post_swap()
        return

    mesh = active_pallas_mesh()
    if (mesh is not None and mesh.size > 1 and sched is None
            and isinstance(amps, jax.core.Tracer)):
        # inside a jit trace the tracer hides its sharding; use the ambient
        # mesh, which Circuit.run derived from the register actually being
        # replayed (so it always matches the traced input's sharding)
        if _dispatch_pallas_sharded(qureg, ops, mesh, tile_bits,
                                    load_swap_k, store_swap_k,
                                    load_swap_hi, store_swap_hi,
                                    ring_depth, pre_swap, post_swap):
            return
        if load_swap_k:  # swap already applied; replay ops via the engine
            _apply_ops_via_engine(qureg, ops)
            post_swap()
            return
    sharding = getattr(qureg.amps, "sharding", None)
    if sharding is not None and len(sharding.device_set) > 1:
        if sched is None:
            mesh2 = _canonical_amps_mesh(qureg)
            if mesh2 is not None:
                if _dispatch_pallas_sharded(qureg, ops, mesh2, tile_bits,
                                            load_swap_k, store_swap_k,
                                            load_swap_hi, store_swap_hi,
                                            ring_depth, pre_swap, post_swap):
                    return
            else:
                telemetry.inc("engine_fallback_total",
                              reason="shard_map_unsupported")
                pre_swap()
        else:
            telemetry.inc("engine_fallback_total",
                          reason="explicit_scheduler")
            pre_swap()
        _apply_ops_via_engine(qureg, ops)
        post_swap()
        return
    if _df_route(qureg.dtype) or not _mosaic_supports(qureg.dtype):
        if ((mesh is None or mesh.size == 1)
                and np.dtype(qureg.dtype) == np.dtype("float64")
                and (1 << nsv) >= 2 * PG._LANES):
            # f64 on the TPU backend, single device: the double-float
            # fast path (round 5; VERDICT r4 missing #2). The f64 state
            # splits exactly into paired-f32 (hi, lo) planes and the run
            # executes as error-free-transform VPU arithmetic inside the
            # SAME fused single-pass kernel -- the PRECISION=2 analogue
            # of the f32 path's bf16x3 zone dots (ops/pallas_df).
            from .ops.pallas_df import (DF_MAX_OPS, DF_SUBLANES, df_join,
                                        df_split)

            lq_df = PG.local_qubits(nsv, DF_SUBLANES)
            if any(q >= lq_df for op in ops
                   for q in PG.op_dense_targets(op)):
                # a plan built with non-DF tile geometry (e.g.
                # Circuit.fused(dtype=np.float32) replayed on an f64
                # register) can carry dense targets in [lq_df, plan
                # tile_bits); the engine fallback -- not a runtime
                # ValueError from fused_local_run -- is the contract for
                # f64 registers (ADVICE round 5)
                telemetry.inc("engine_fallback_total",
                              reason="df_tile_mismatch")
                pre_swap()
                _apply_ops_via_engine(qureg, ops)
                post_swap()
                return
            k_max = max(load_swap_k, store_swap_k)
            foldable = (k_max > 0
                        and tile_bits == PG.local_qubits(nsv, DF_SUBLANES)
                        and tile_bits - PG.LANE_BITS - k_max >= 3)
            if k_max and not foldable:
                telemetry.inc("engine_fallback_total",
                              reason="swap_not_foldable")
                pre_swap()
            # Mosaic compile time is superlinear in op count and df ops
            # carry ~15x the arithmetic, so long runs split into short
            # kernels chained on the (4, N) planes -- extra HBM passes
            # are cheap next to the compile blowup (a 27-op df kernel
            # exceeded 9 minutes; 8-op kernels compile in seconds)
            chunks = ([ops[i:i + DF_MAX_OPS]
                       for i in range(0, len(ops), DF_MAX_OPS)] or [ops])
            if len(chunks) > 1:
                # each extra chunk is one extra HBM pass the plan did not
                # price in -- visible, not silent (ISSUE 1 tentpole)
                telemetry.inc("engine_fallback_total", len(chunks) - 1,
                              reason="df_max_ops_split")
            last = len(chunks) - 1

            def df_attempt():
                planes = df_split(qureg.amps)
                for ci, chunk in enumerate(chunks):
                    planes = fused_local_run(
                        planes, n=nsv, ops=chunk, sublanes=DF_SUBLANES,
                        load_swap_k=load_swap_k if (foldable and ci == 0)
                        else 0,
                        store_swap_k=store_swap_k
                        if (foldable and ci == last) else 0,
                        load_swap_hi=load_swap_hi if (foldable and ci == 0)
                        else None,
                        store_swap_hi=store_swap_hi
                        if (foldable and ci == last) else None,
                        ring_depth=ring_depth)
                return df_join(planes)

            def df_degrade():
                if foldable:
                    pre_swap()
                _apply_ops_via_engine(qureg, ops)
                if foldable:
                    post_swap()

            out = _guard.pallas_dispatch(df_attempt, df_degrade)
            if out is not _guard.DEGRADED:
                qureg.put(out)
            if k_max and not foldable:
                post_swap()
            return
        # the genuinely unsupported f64 residue -- sub-tile registers, or
        # sharded dispatch that already failed above -- keeps the counted
        # engine fallback (sharded-df-CAPABLE runs no longer land here:
        # they ride _dispatch_pallas_sharded / _sched_df_pallas_run)
        telemetry.inc("engine_fallback_total", reason="f64_engine")
        pre_swap()
        _apply_ops_via_engine(qureg, ops)
        post_swap()
        return
    # single device: fold the swaps into the kernel DMA when this register's
    # tile geometry matches the plan's (s_low >= one sublane tile keeps the
    # gathered chunks layout-free); otherwise run them as explicit passes
    k_max = max(load_swap_k, store_swap_k)
    foldable = (k_max > 0
                and tile_bits == PG.local_qubits(nsv)
                and tile_bits - PG.LANE_BITS - k_max >= 3)
    if k_max and not foldable:
        telemetry.inc("engine_fallback_total", reason="swap_not_foldable")
        pre_swap()

    def local_attempt():
        return fused_local_run(
            qureg.amps, n=nsv, ops=ops,
            load_swap_k=load_swap_k if foldable else 0,
            store_swap_k=store_swap_k if foldable else 0,
            load_swap_hi=load_swap_hi if foldable else None,
            store_swap_hi=store_swap_hi if foldable else None,
            ring_depth=ring_depth)

    def local_degrade():
        if foldable:
            pre_swap()
        _apply_ops_via_engine(qureg, ops)
        if foldable:
            post_swap()

    out = _guard.pallas_dispatch(local_attempt, local_degrade)
    if out is not _guard.DEGRADED:
        qureg.put(out)
    if k_max and not foldable:
        post_swap()


def _canonical_amps_mesh(qureg):
    """The 1-D amps mesh of the register's concrete canonical sharding
    (NamedSharding over P(None, AMP_AXIS)), or None."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .environment import AMP_AXIS

    sharding = getattr(qureg.amps, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return None
    if sharding.spec != P(None, AMP_AXIS):
        return None
    return sharding.mesh


def _sharded_run_plan(qureg, ops: tuple, mesh):
    """Per-shard executability check: ((df, n_local, sublanes), None) when
    every op of the run is executable against the shard-local tile, else
    (None, fallback_reason).

    Legality: amplitude sharding splits off the TOP qubits, so each shard
    is a contiguous (2, 2^n_local) sub-state on which in-tile targets pair
    locally, while sharded-qubit controls/diagonals/parity members depend
    only on the shard index (jax.lax.axis_index -> the kernel's SMEM
    scalar). One HBM pass per device, zero communication -- the fusion
    analogue of the reference running its local kernel per rank between
    exchanges (QuEST_cpu_distributed.c:870-905). PRECISION=2 registers on
    the df route check against the DF tile geometry (DF_SUBLANES), and a
    plan built with non-DF geometry is the SHARDED df_tile_mismatch case
    -- counted by the caller, never a runtime ValueError (the round-7
    generalisation of the single-device guard)."""
    from .environment import AMP_AXIS
    from .ops import pallas_gates as PG

    df = _df_route(qureg.dtype)
    if tuple(mesh.shape.keys()) != (AMP_AXIS,):
        return None, ("f64_engine" if df else "shard_map_unsupported")
    ndev = mesh.shape[AMP_AXIS]
    if ndev & (ndev - 1):
        return None, ("f64_engine" if df else "shard_map_unsupported")
    nsv = qureg.num_qubits_in_state_vec
    n_local = nsv - (ndev.bit_length() - 1)
    if df:
        # one lane tile per shard suffices for the gridless df kernel
        if (1 << n_local) < PG._LANES:
            return None, "f64_engine"
        from .ops.pallas_df import DF_SUBLANES
        sublanes = DF_SUBLANES
    else:
        if not _mosaic_supports(qureg.dtype):
            return None, "f64_engine"
        if (1 << n_local) < 2 * PG._LANES:
            return None, "shard_map_unsupported"
        sublanes = PG._DEF_SUBLANES
    lq = PG.local_qubits(n_local, sublanes)
    for op in ops:
        if any(q >= lq for q in PG.op_dense_targets(op)):
            return None, ("df_tile_mismatch" if df
                          else "shard_map_unsupported")
    return (df, n_local, sublanes), None


def _df_shard_chunks(ops: tuple, n_local: int, sublanes: int,
                     lk: int = 0, sk: int = 0, lh=None, sh=None,
                     ring_depth=None):
    """Per-shard double-float executor factory: returns
    ``run(planes, shard_idx) -> planes`` applying the op run to one
    shard's (4, C) df planes, chunked at DF_MAX_OPS (Mosaic compile time
    is superlinear in op count and df ops carry ~15x the arithmetic);
    folded frame swaps ride the first/last chunk's DMA."""
    from .ops import pallas_gates as PG
    from .ops.pallas_df import DF_MAX_OPS

    chunks = ([ops[i:i + DF_MAX_OPS]
               for i in range(0, len(ops), DF_MAX_OPS)] or [tuple(ops)])
    if len(chunks) > 1:
        # each extra chunk is one extra HBM pass the plan did not price
        # in -- visible, not silent (ISSUE 1 tentpole)
        telemetry.inc("engine_fallback_total", len(chunks) - 1,
                      reason="df_max_ops_split")
    last = len(chunks) - 1

    def run(planes, shard_idx):
        for ci, chunk in enumerate(chunks):
            planes = PG.fused_local_run(
                planes, n=n_local, ops=chunk, sublanes=sublanes,
                shard_index=shard_idx,
                load_swap_k=lk if ci == 0 else 0,
                load_swap_hi=lh if ci == 0 else None,
                store_swap_k=sk if ci == last else 0,
                store_swap_hi=sh if ci == last else None,
                ring_depth=ring_depth)
        return planes

    return run


def _exec_pallas_sharded(amps, mesh, ops: tuple, df: bool, n_local: int,
                         sublanes: int, lk: int = 0, sk: int = 0,
                         lh=None, sh=None, ring_depth=None):
    """shard_map the fused kernel over ``mesh`` (caller has established
    legality via _sharded_run_plan). f64-df shards split to the 4-plane
    layout, run the df kernels (DF_MAX_OPS-chunked), and join back --
    split/join are exact and shard-local. Folded frame swaps (lk/sk,
    SHARD-LOCAL blocks only) ride the kernel DMA."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map
    from .environment import AMP_AXIS
    from .ops import pallas_gates as PG

    if df:
        from .ops.pallas_df import df_join, df_split

        run = _df_shard_chunks(ops, n_local, sublanes, lk, sk, lh, sh,
                               ring_depth)

        def body(x):
            return df_join(run(df_split(x), jax.lax.axis_index(AMP_AXIS)))
    else:
        def body(x):
            hi = jax.lax.axis_index(AMP_AXIS)
            return PG.fused_local_run(
                x, n=n_local, ops=ops, sublanes=sublanes, shard_index=hi,
                load_swap_k=lk, load_swap_hi=lh, store_swap_k=sk,
                store_swap_hi=sh, ring_depth=ring_depth)

    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, which the checker (on by default) rejects
    fn = shard_map(body, mesh=mesh, in_specs=P(None, AMP_AXIS),
                   out_specs=P(None, AMP_AXIS), check_vma=False)
    return fn(amps)


def _dispatch_pallas_sharded(qureg, ops: tuple, mesh, tile_bits: int,
                             lk: int, sk: int, lh, sh, ring_depth,
                             pre_swap, post_swap) -> bool:
    """Route one PallasRun per shard over ``mesh`` (f32 native; f64 via
    the double-float planes when the df route is on), folding SHARD-LOCAL
    frame swaps into the per-shard kernel DMA and running the rest --
    collective relabelings reaching sharded bits (the designed all-to-all
    path), or shard-local swaps whose tile geometry mismatches the plan
    (counted swap_not_foldable) -- as explicit transpose passes.

    Returns True when handled end to end. Returns False with the fallback
    reason counted and the load swap already applied explicitly (a no-op
    when lk == 0), so the caller can replay the ops via the engine."""
    from .ops import pallas_gates as PG

    plan, reason = _sharded_run_plan(qureg, ops, mesh)
    if plan is None:
        telemetry.inc("engine_fallback_total", reason=reason)
        pre_swap()
        return False
    df, n_local, sublanes = plan

    def foldable(k, hi):
        if not k:
            return False
        hi_eff = tile_bits if hi is None else hi
        if hi_eff + k > n_local:
            return False  # reaches sharded bits: the collective transpose
        ok = (tile_bits == PG.local_qubits(n_local, sublanes)
              and tile_bits - PG.LANE_BITS - k >= 3)
        if not ok:
            telemetry.inc("engine_fallback_total",
                          reason="swap_not_foldable")
        return ok

    fold_l = foldable(lk, lh)
    fold_s = foldable(sk, sh)
    if lk and not fold_l:
        pre_swap()

    from .resilience import guard as _guard

    def attempt():
        return _exec_pallas_sharded(
            qureg.amps, mesh, ops, df, n_local, sublanes,
            lk=lk if fold_l else 0, lh=lh if fold_l else None,
            sk=sk if fold_s else 0, sh=sh if fold_s else None,
            ring_depth=ring_depth)

    def degrade():
        # the kernel route stays down (injected compile fault / exhausted
        # transients): sharding-aware engine replay; swaps that would have
        # folded into the kernel DMA run as explicit passes instead
        if fold_l:
            pre_swap()
        _apply_ops_via_engine(qureg, ops)
        if fold_s:
            post_swap()

    new = _guard.pallas_dispatch(attempt, degrade)
    if new is not _guard.DEGRADED:
        qureg.put(new)
    if sk and not fold_s:
        post_swap()
    return True


def _sched_df_pallas_run(qureg, ops: tuple, sched, tile_bits: int,
                         lk: int, sk: int, lh, sh, ring_depth,
                         comm_pipeline=None,
                         comm_pipeline_dcn=None) -> bool:
    """Explicit-scheduler route for a PallasRun on a sharded PRECISION=2
    register (the ISSUE 3 tentpole): df-split ONCE, run the fused df
    kernels per shard over the scheduler's mesh, and execute the run's
    frame relabelings through the scheduler's COUNTED grouped permute
    collective ON the 4-plane state (exchange.dist_permute_bits carries
    all four planes natively; chunk-units price at the df 2x scale --
    scheduler.DistributedScheduler.apply_frame_permute). Returns False
    with the fallback reason counted when the run is not shard-executable
    at the df tile geometry."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ._compat import shard_map
    from .environment import AMP_AXIS
    from .ops.pallas_df import df_join, df_split

    plan, reason = _sharded_run_plan(qureg, ops, sched.mesh)
    if plan is None:
        telemetry.inc("engine_fallback_total", reason=reason)
        return False
    df, n_local, sublanes = plan
    nsv = qureg.num_qubits_in_state_vec
    planes = df_split(qureg.amps)
    if lk:
        telemetry.inc("pallas_pass_total", kind="frame_swap")
        planes = sched.apply_frame_permute(
            planes, n=nsv, lo1=tile_bits - lk,
            lo2=tile_bits if lh is None else lh, k=lk,
            pipeline=comm_pipeline, pipeline_dcn=comm_pipeline_dcn)
    run = _df_shard_chunks(ops, n_local, sublanes, ring_depth=ring_depth)

    def body(x):
        return run(x, jax.lax.axis_index(AMP_AXIS))

    planes = shard_map(body, mesh=sched.mesh, in_specs=P(None, AMP_AXIS),
                       out_specs=P(None, AMP_AXIS), check_vma=False)(planes)
    if sk:
        telemetry.inc("pallas_pass_total", kind="frame_swap")
        planes = sched.apply_frame_permute(
            planes, n=nsv, lo1=tile_bits - sk,
            lo2=tile_bits if sh is None else sh, k=sk,
            pipeline=comm_pipeline, pipeline_dcn=comm_pipeline_dcn)
    qureg.put(df_join(planes))
    return True


def _shard_map_pallas_run(qureg, ops: tuple):
    """Eager-path entry: run a PallasRun per-shard over the mesh of the
    register's own (concrete) sharding, or None if the layout or the run
    isn't shard-executable."""
    mesh = _canonical_amps_mesh(qureg)
    if mesh is None:
        return None
    return _run_pallas_sharded(qureg, ops, mesh)


def _run_pallas_sharded(qureg, ops: tuple, mesh):
    """shard_map the fused kernel over ``mesh`` if every op is executable
    against the shard-local tile; None otherwise (see _sharded_run_plan
    for the legality rules and _exec_pallas_sharded for execution)."""
    plan, _reason = _sharded_run_plan(qureg, ops, mesh)
    if plan is None:
        return None
    df, n_local, sublanes = plan
    return _exec_pallas_sharded(qureg.amps, mesh, ops, df, n_local, sublanes)


def _apply_ops_via_engine(qureg, ops: tuple) -> None:
    """Replay pallas-format ops through the standard kernels (sharding-aware
    via GSPMD or the explicit scheduler). Ops are in physical coordinates
    over the FULL flattened state and already include any density shadow
    twins, so they apply raw -- routing through the gates.py wrappers would
    re-derive shadows and double-apply them on density registers."""
    from .ops import apply as K
    from .ops import cplx
    from .ops import diagonal as D
    from .parallel import scheduler as _dist

    nsv = qureg.num_qubits_in_state_vec
    telemetry.inc("engine_replayed_ops_total", len(ops))
    sched = _dist.active()
    apply_m = sched.apply_matrix if sched else K.apply_matrix
    apply_d = sched.apply_diagonal if sched else D.apply_diagonal
    apply_p = sched.apply_parity_phase if sched else D.apply_parity_phase
    for op in ops:
        if op[0] == "matrix":
            _, q, controls, states, m = op
            mm = cplx.from_complex(np.asarray(m.arr), qureg.dtype)
            qureg.put(apply_m(qureg.amps, mm, n=nsv, targets=(q,),
                              controls=controls, control_states=states))
        elif op[0] == "parity":
            _, qubits, controls, theta = op
            qureg.put(apply_p(qureg.amps, theta, n=nsv, qubits=qubits,
                              controls=controls))
        elif op[0] == "diagw":
            _, targets, controls, d = op
            dd = cplx.from_complex(np.asarray(d.arr), qureg.dtype)
            qureg.put(apply_d(qureg.amps, dd, n=nsv, targets=targets,
                              controls=controls))
        elif op[0] == "swap":
            _, q1, q2, controls, states = op
            if states and any(s == 0 for s in states):  # pragma: no cover
                raise ValueError("swap with 0-controls has no engine route")
            qureg.put(K.apply_swap(qureg.amps, n=nsv, qb1=q1, qb2=q2,
                                   controls=controls))
        elif op[0] in ("kraus1", "kraus2", "krausn"):
            from .ops.density import _acc_kraus_term

            if op[0] == "kraus1":
                _, t, c, terms = op
                rows, cols = (t,), (c,)
            elif op[0] == "kraus2":
                _, t1, t2, c1, c2, terms = op
                rows, cols = (t1, t2), (c1, c2)
            else:
                _, rows, cols, terms = op
            amps0 = qureg.amps
            out = None
            for sign, kk in terms:
                km = cplx.from_complex(np.asarray(kk.arr), qureg.dtype)
                y = apply_m(amps0 + 0, km, n=nsv, targets=rows)
                y = apply_m(y, km, n=nsv, targets=cols, conj=True)
                out = _acc_kraus_term(out, sign, y)
            qureg.put(out)
        else:  # pragma: no cover
            raise ValueError(f"unknown pallas op {op[0]!r}")


def _mosaic_supports(dtype) -> bool:
    """Mosaic (TPU Pallas) has no f64 lowering for the kernel's MXU dots;
    f64 registers on TPU take the XLA engine paths instead (XLA emulates
    f64 on TPU -- slow but correct, the documented QUEST_PRECISION=2
    policy; see precision.py)."""
    import jax
    import numpy as np

    if jax.default_backend() != "tpu":
        return True  # CPU interpreter handles f64
    return np.dtype(dtype) != np.dtype("float64")


def _pallas_usable(qureg) -> bool:
    import jax

    sharding = getattr(qureg.amps, "sharding", None)
    if sharding is not None and len(sharding.device_set) > 1:
        return False
    return jax.default_backend() == "tpu" and _mosaic_supports(qureg.dtype)


def _apply_dense_block(qureg, U: np.ndarray, qubits: tuple) -> None:
    """Dense window block dispatch: Pallas MXU dot paths when the register
    is single-device on TPU (window_dot for lo >= 7, a folded lane_u pass
    for hi < 7), the ordinary engine otherwise (CPU, sharded, windows the
    dot kernels can't take).

    Measured per-block at 2^26 amps f32, loop-inside-jit (tools/microbench):
    elementwise floor 3.0 ms, lane_u pallas 4.0 ms, window_dot (5q, hi
    qubits) 4.5 ms, XLA einsum same window 32 ms standalone -- yet routing
    the hi-window blocks through window_dot made the *full* bench slightly
    slower (694 vs 739 gates/s): inside one program XLA fuses the einsum
    with neighbouring diagonal/elementwise work, while a pallas_call is an
    opaque barrier. The einsum engine therefore keeps the hi windows; the
    real win is eliminating standalone blocks entirely (two-frame Pallas
    scheduling, see plan())."""
    from . import gates as G
    from .ops import pallas_gates as PG

    lo, hi = qubits[0], qubits[-1]
    nsv = qureg.num_qubits_in_state_vec
    if (_pallas_usable(qureg) and hi < PG.LANE_BITS
            and (1 << nsv) >= 2 * PG._LANES
            and not qureg.is_density_matrix):
        ev = GateEvent("matrix", tuple(qubits), matrix=U)
        lane_U = event_matrix(ev, tuple(range(PG.LANE_BITS)))
        ur, ui = lane_U.real, lane_U.imag
        # Karatsuba operand stack, matching the kernel's lane_u format
        W = np.stack([ur.T, ui.T, ur.T + ui.T])
        amps = PG.fused_local_run(
            qureg.amps, n=nsv, ops=(("lane_u", PG.HashableMatrix(W)),))
        qureg.put(amps)
        return
    G._apply_gate_matrix(qureg, U, qubits)


def _apply_frame_swap(qureg, tile_bits: int, k: int,
                      hi: int | None = None,
                      comm_pipeline: int | None = None,
                      seg: int | None = None,
                      comm_pipeline_dcn: int | None = None) -> None:
    """Tape-entry wrapper for FrameSwap: one relabeling transpose. Works on
    every backend (plain XLA); on a sharded register GSPMD lowers it to the
    all-to-all the relabeling implies (shard-local when [hi, hi+k) avoids
    the sharded qubits). Under an active explicit scheduler the transpose
    rides the scheduler's COUNTED grouped permute instead
    (apply_frame_permute), so the plan_circuit comm model and the
    frame_transpose telemetry series stay exact."""
    from .ops.pallas_gates import swap_bit_blocks
    from .parallel import scheduler as _dist

    telemetry.inc("pallas_pass_total", kind="frame_swap")
    nsv = qureg.num_qubits_in_state_vec
    sched = _dist.active()
    if sched is not None and sched.mesh is not None and sched.mesh.size > 1:
        qureg.put(sched.apply_frame_permute(
            qureg.amps, n=nsv, lo1=tile_bits - k,
            lo2=tile_bits if hi is None else hi, k=k,
            pipeline=comm_pipeline, pipeline_dcn=comm_pipeline_dcn))
        return
    qureg.put(swap_bit_blocks(qureg.amps, n=nsv, lo1=tile_bits - k,
                              lo2=tile_bits if hi is None else hi, k=k))


def as_tape(p: FusePlan) -> list:
    """Lower a FusePlan back to Circuit tape entries (fn, args, kwargs)."""
    from . import gates as G

    entries = []
    for item in p.items:
        if isinstance(item, DiagBlock):
            entries.append((G._apply_gate_diag, (item.diag, item.qubits), {}))
        elif isinstance(item, FusedBlock):
            entries.append((_apply_dense_block, (item.matrix, item.qubits), {}))
        elif isinstance(item, PallasRun):
            entries.append((_apply_pallas_run,
                            (item.ops, item.tile_bits, item.load_swap_k,
                             item.store_swap_k, item.load_swap_hi,
                             item.store_swap_hi, item.ring_depth,
                             item.comm_pipeline, item.seg,
                             item.comm_pipeline_dcn), {}))
        elif isinstance(item, FrameSwap):
            entries.append((_apply_frame_swap,
                            (item.tile_bits, item.k, item.hi,
                             item.comm_pipeline, item.seg,
                             item.comm_pipeline_dcn), {}))
        else:
            entries.append(item)
    return entries
