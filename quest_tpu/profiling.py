"""Profiling / tracing: the observability layer the reference lacks.

The reference ships no timers, counters, or trace hooks (SURVEY.md section 5
-- its only introspection is reportQuregParams and the QASM log). On TPU the
right tool is the XLA profiler; this module packages it plus lightweight
host-side op accounting so users can see where a circuit spends its time
without leaving the QuEST-style API.

Since round 6 this module is a thin veneer over :mod:`quest_tpu.telemetry`
(the engine flight recorder): every instrumented call lands in the
process-global registry under ``api_call_total{op=...}`` /
``api_call_seconds{op=...}`` in addition to the local :class:`OpStats`, so
one :func:`quest_tpu.telemetry.snapshot` carries the L5 accounting next to
the engine-internal metrics (fusion plans, comm chunk-units, Pallas passes).

- :func:`trace` -- context manager around ``jax.profiler`` producing a
  Perfetto/TensorBoard trace directory (wrapped in a telemetry span).
- :class:`OpStats` / :func:`instrument` -- count and wall-time every L5 API
  call on a register (eager path) or every block of a Circuit run.
- :func:`device_memory_report` -- live HBM usage per buffer, the analogue of
  the reference's createQureg memory documentation (QuEST.h:423-430); also
  exports the figures as telemetry gauges.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from dataclasses import dataclass, field

import jax

from . import telemetry

__all__ = ["trace", "OpStats", "instrument", "device_memory_report"]


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA device trace (view with TensorBoard or Perfetto):

        with quest_tpu.profiling.trace("/tmp/qtrace"):
            circuit.run(qureg)
    """
    with telemetry.span("profiling.trace", log_dir=log_dir):
        jax.profiler.start_trace(log_dir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()


@dataclass
class OpStats:
    """Host-side per-op accounting collected by :func:`instrument`.

    A local mirror of the registry series the same instrumentation writes
    (``api_call_total`` / ``api_call_seconds``): the dataclass scopes the
    numbers to ONE instrument() block, while the registry accumulates
    process-wide for snapshot/export."""
    counts: dict = field(default_factory=lambda: defaultdict(int))
    seconds: dict = field(default_factory=lambda: defaultdict(float))

    def report(self) -> str:
        lines = ["op                              calls      host-seconds"]
        for name in sorted(self.seconds, key=self.seconds.get, reverse=True):
            lines.append(f"{name:30s} {self.counts[name]:6d} {self.seconds[name]:16.4f}")
        return "\n".join(lines)


@contextlib.contextmanager
def instrument(stats: OpStats | None = None):
    """Wrap every public gate/operator call with count + wall-time recording.

    Host-side wall time includes dispatch but not necessarily device drain
    (JAX is async); use :func:`trace` for true device timelines. Yields the
    OpStats, restoring the un-instrumented functions on exit. Every call is
    also recorded into the telemetry registry (``api_call_total{op=}``,
    ``api_call_seconds{op=}``)."""
    import quest_tpu as qt

    stats = stats or OpStats()
    wrapped = {}

    def make(name, fn):
        def timed(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                dt = time.perf_counter() - t0
                stats.counts[name] += 1
                stats.seconds[name] += dt
                telemetry.inc("api_call_total", op=name)
                telemetry.observe("api_call_seconds", dt, op=name)
        timed.__name__ = name
        return timed

    from . import gates, operators, decoherence, state_init, calculations
    modules = [gates, operators, decoherence, state_init, calculations]
    try:
        for mod in modules:
            for name in getattr(mod, "__all__", []):
                fn = getattr(mod, name, None)
                if callable(fn):
                    wrapped[(mod, name)] = fn
                    timed = make(name, fn)
                    setattr(mod, name, timed)
                    if getattr(qt, name, None) is fn:
                        setattr(qt, name, timed)
        yield stats
    finally:
        for (mod, name), fn in wrapped.items():
            setattr(mod, name, fn)
            if hasattr(qt, name):
                setattr(qt, name, fn)


def device_memory_report(device=None) -> str:
    """Per-buffer live HBM usage on ``device`` (default: first device);
    the figures also land as ``hbm_bytes{...}`` telemetry gauges."""
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if not stats:
        return f"{device.device_kind}: memory stats unavailable"
    used = stats.get("bytes_in_use", 0)
    limit = stats.get("bytes_limit", 0)
    peak = stats.get("peak_bytes_in_use", 0)
    kind = device.device_kind
    telemetry.set_gauge("hbm_bytes", used, state="in_use", device=kind)
    telemetry.set_gauge("hbm_bytes", peak, state="peak", device=kind)
    telemetry.set_gauge("hbm_bytes", limit, state="limit", device=kind)
    return (f"{device.device_kind}: {used/2**20:.1f} MiB in use, "
            f"peak {peak/2**20:.1f} MiB, limit {limit/2**20:.1f} MiB")
