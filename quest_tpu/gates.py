"""Unitaries, measurement and collapse: the reference's L5 gate API
(``QuEST/src/QuEST.c``; declarations QuEST.h:1916-4760).

Every function follows the reference's invariant structure (QuEST.c:5-6):
validate -> state-vector op -> (density) conjugated shadow op on the shifted
qubits (QuEST.c:184-193) -> QASM record. API functions never call each other.

Function names match the reference exactly (hadamard, controlledNot,
multiControlledMultiQubitUnitary, ...) so a QuEST user can port by changing
imports only.
"""

from __future__ import annotations

import math

import numpy as np

from . import matrices, validation as V
from .datatypes import SubDiagonalOp, Vector
from .ops import apply as K, cplx, diagonal as D, measure as M
from .parallel import scheduler as _dist
from .registers import Qureg

__all__ = [
    "phaseShift", "controlledPhaseShift", "multiControlledPhaseShift",
    "controlledPhaseFlip", "multiControlledPhaseFlip", "sGate", "tGate",
    "compactUnitary", "unitary", "rotateX", "rotateY", "rotateZ",
    "rotateAroundAxis", "controlledRotateX", "controlledRotateY",
    "controlledRotateZ", "controlledRotateAroundAxis",
    "controlledCompactUnitary", "controlledUnitary", "multiControlledUnitary",
    "multiStateControlledUnitary", "pauliX", "pauliY", "pauliZ", "hadamard",
    "controlledNot", "multiQubitNot", "multiControlledMultiQubitNot",
    "controlledPauliY", "swapGate", "sqrtSwapGate", "multiRotateZ",
    "multiRotatePauli", "multiControlledMultiRotateZ",
    "multiControlledMultiRotatePauli", "twoQubitUnitary",
    "controlledTwoQubitUnitary", "multiControlledTwoQubitUnitary",
    "multiQubitUnitary", "controlledMultiQubitUnitary",
    "multiControlledMultiQubitUnitary", "diagonalUnitary",
    "measure", "measureWithStats", "collapseToOutcome",
]


# ---------------------------------------------------------------------------
# helpers: statevec + density-shadow application
# ---------------------------------------------------------------------------

def _shift(qs, n):
    return tuple(q + n for q in qs)


def _apply_gate_matrix(qureg: Qureg, matrix, targets, controls=(), states=()):
    """Gate semantics: U on a state-vector; U . U^dagger on a density matrix
    via the conj-shadow (QuEST.c:184-193). Routed through the explicit
    distributed scheduler when an ``explicit_mesh`` context is active."""
    n = qureg.num_qubits_represented
    nsv = qureg.num_qubits_in_state_vec
    targets, controls, states = tuple(targets), tuple(controls), tuple(states)
    m = cplx.from_complex(matrix, qureg.dtype)
    sched = _dist.active()
    apply = sched.apply_matrix if sched else K.apply_matrix
    amps = apply(qureg.amps, m, n=nsv, targets=targets,
                 controls=controls, control_states=states)
    if qureg.is_density_matrix:
        amps = apply(amps, m, n=nsv, targets=_shift(targets, n),
                     controls=_shift(controls, n), control_states=states,
                     conj=True)
    qureg.put(amps)


def _apply_gate_diag(qureg: Qureg, diag, targets, controls=()):
    n = qureg.num_qubits_represented
    nsv = qureg.num_qubits_in_state_vec
    targets, controls = tuple(targets), tuple(controls)
    d = cplx.from_complex(diag, qureg.dtype)
    sched = _dist.active()
    apply = sched.apply_diagonal if sched else D.apply_diagonal
    amps = apply(qureg.amps, d, n=nsv, targets=targets, controls=controls)
    if qureg.is_density_matrix:
        amps = apply(amps, d, n=nsv, targets=_shift(targets, n),
                     controls=_shift(controls, n), conj=True)
    qureg.put(amps)


def _apply_gate_x(qureg: Qureg, targets, controls=(), states=()):
    n = qureg.num_qubits_represented
    nsv = qureg.num_qubits_in_state_vec
    targets, controls, states = tuple(targets), tuple(controls), tuple(states)
    sched = _dist.active()
    apply = sched.apply_x if sched else K.apply_x_class
    amps = apply(qureg.amps, n=nsv, targets=targets,
                 controls=controls, control_states=states)
    if qureg.is_density_matrix:
        amps = apply(amps, n=nsv, targets=_shift(targets, n),
                     controls=_shift(controls, n), control_states=states)
    qureg.put(amps)


def _apply_gate_parity_phase(qureg: Qureg, theta, qubits, controls=()):
    n = qureg.num_qubits_represented
    nsv = qureg.num_qubits_in_state_vec
    qubits, controls = tuple(qubits), tuple(controls)
    sched = _dist.active()
    apply = sched.apply_parity_phase if sched else D.apply_parity_phase
    amps = apply(qureg.amps, theta, n=nsv, qubits=qubits, controls=controls)
    if qureg.is_density_matrix:
        amps = apply(amps, theta, n=nsv, qubits=_shift(qubits, n),
                     controls=_shift(controls, n), conj=True)
    qureg.put(amps)


def _log(qureg):
    """The register's QASM logger, or None (spy registers carry none)."""
    return qureg.qasm_log


# ---------------------------------------------------------------------------
# phase gates (diagonal family)
# ---------------------------------------------------------------------------

def phaseShift(qureg: Qureg, target: int, angle: float) -> None:
    """diag(1, e^{i angle}) on target (QuEST.h:1916)."""
    V.validate_target(qureg, target, "phaseShift")
    _apply_gate_diag(qureg, matrices.phase_shift_diag(angle), (target,))
    if _log(qureg): _log(qureg).record_param_gate("phaseShift", target, angle)


def controlledPhaseShift(qureg: Qureg, q1: int, q2: int, angle: float) -> None:
    """Symmetric two-qubit phase (QuEST.h:1965)."""
    V.validate_control_target(qureg, q1, q2, "controlledPhaseShift")
    _apply_gate_diag(qureg, matrices.phase_shift_diag(angle), (q2,), (q1,))
    if _log(qureg): _log(qureg).record_controlled_param_gate("phaseShift", q1, q2, angle)


def multiControlledPhaseShift(qureg: Qureg, qubits, angle: float) -> None:
    """Phase on the all-ones subspace of ``qubits`` (QuEST.h:2012)."""
    V.validate_multi_targets(qureg, qubits, "multiControlledPhaseShift")
    _apply_gate_diag(qureg, matrices.phase_shift_diag(angle), (qubits[0],), tuple(qubits[1:]))
    if _log(qureg):
        _log(qureg).record_multi_controlled_param_gate(
            "phaseShift", tuple(qubits[:-1]), qubits[-1], angle)


def controlledPhaseFlip(qureg: Qureg, q1: int, q2: int) -> None:
    """Controlled-Z: phase -1 on the |11> subspace (QuEST.h:211)."""
    V.validate_control_target(qureg, q1, q2, "controlledPhaseFlip")
    _apply_gate_diag(qureg, np.array([1.0, -1.0]), (q2,), (q1,))
    if _log(qureg): _log(qureg).record_controlled_gate("sigmaZ", q1, q2)


def multiControlledPhaseFlip(qureg: Qureg, qubits) -> None:
    """Phase -1 on the all-ones subspace of ``controls`` (QuEST.h:212)."""
    V.validate_multi_targets(qureg, qubits, "multiControlledPhaseFlip")
    _apply_gate_diag(qureg, np.array([1.0, -1.0]), (qubits[0],), tuple(qubits[1:]))
    if _log(qureg):
        _log(qureg).record_multi_controlled_gate("sigmaZ", tuple(qubits[:-1]), qubits[-1])


def sGate(qureg: Qureg, target: int) -> None:
    """Phase gate diag(1, i) (QuEST.h:213)."""
    V.validate_target(qureg, target, "sGate")
    _apply_gate_diag(qureg, np.array([1.0, 1.0j]), (target,))
    if _log(qureg): _log(qureg).record_gate("sGate", target)


def tGate(qureg: Qureg, target: int) -> None:
    """T gate diag(1, exp(i pi/4)) (QuEST.h:214)."""
    V.validate_target(qureg, target, "tGate")
    _apply_gate_diag(qureg, np.array([1.0, np.exp(0.25j * math.pi)]), (target,))
    if _log(qureg): _log(qureg).record_gate("tGate", target)


def pauliZ(qureg: Qureg, target: int) -> None:
    """sigma-Z (QuEST.h:231)."""
    V.validate_target(qureg, target, "pauliZ")
    _apply_gate_diag(qureg, np.array([1.0, -1.0]), (target,))
    if _log(qureg): _log(qureg).record_gate("sigmaZ", target)


def rotateZ(qureg: Qureg, target: int, angle: float) -> None:
    """exp(-i angle/2 Z) (QuEST.h:219)."""
    V.validate_target(qureg, target, "rotateZ")
    _apply_gate_diag(qureg, matrices.rz_diag(angle), (target,))
    if _log(qureg): _log(qureg).record_param_gate("rotateZ", target, angle)


def controlledRotateZ(qureg: Qureg, control: int, target: int, angle: float) -> None:
    """Controlled exp(-i angle/2 Z) (QuEST.h:223)."""
    V.validate_control_target(qureg, control, target, "controlledRotateZ")
    _apply_gate_diag(qureg, matrices.rz_diag(angle), (target,), (control,))
    if _log(qureg): _log(qureg).record_controlled_param_gate("rotateZ", control, target, angle)


def multiRotateZ(qureg: Qureg, qubits, angle: float) -> None:
    """exp(-i angle/2 Z x...x Z) (QuEST.h:4483)."""
    V.validate_multi_targets(qureg, qubits, "multiRotateZ")
    _apply_gate_parity_phase(qureg, angle, tuple(qubits))
    if _log(qureg):
        _log(qureg).record_comment(
            f"Here a {len(qubits)}-qubit multiRotateZ of angle "
            f"{_log(qureg).fmt_real(angle)} was performed (QASM not yet implemented)")


def multiControlledMultiRotateZ(qureg: Qureg, controls, targets, angle: float) -> None:
    """(QuEST.h:4616)."""
    V.validate_multi_controls_multi_targets(qureg, controls, targets, "multiControlledMultiRotateZ")
    _apply_gate_parity_phase(qureg, angle, tuple(targets), tuple(controls))
    if _log(qureg):
        _log(qureg).record_comment(
            f"Here a {len(controls)}-control {len(targets)}-target "
            f"multiControlledMultiRotateZ of angle {_log(qureg).fmt_real(angle)} "
            "was performed (QASM not yet implemented)")


def diagonalUnitary(qureg: Qureg, targets, op: SubDiagonalOp) -> None:
    """Apply a SubDiagonalOp as a unitary (diagonalUnitary, QuEST.h:1444)."""
    func = "diagonalUnitary"
    V.validate_multi_targets(qureg, targets, func)
    V.validate_sub_diag_op_targets(op, len(targets), func)
    V.validate_unitary_sub_diag_op(op, qureg.eps, func)
    elems = np.asarray(op.elems)
    _apply_gate_diag(qureg, elems, tuple(targets))
    if _log(qureg):
        _log(qureg).record_comment(
            "Here, the register was modified by an undisclosed diagonal unitary (via diagonalUnitary).")


# ---------------------------------------------------------------------------
# X-class (amplitude permutation) gates
# ---------------------------------------------------------------------------

def pauliX(qureg: Qureg, target: int) -> None:
    """sigma-X (QuEST.h:229)."""
    V.validate_target(qureg, target, "pauliX")
    _apply_gate_x(qureg, (target,))
    if _log(qureg): _log(qureg).record_gate("sigmaX", target)


def controlledNot(qureg: Qureg, control: int, target: int) -> None:
    """CNOT (QuEST.h:233)."""
    V.validate_control_target(qureg, control, target, "controlledNot")
    _apply_gate_x(qureg, (target,), (control,))
    if _log(qureg): _log(qureg).record_controlled_gate("sigmaX", control, target)


def multiQubitNot(qureg: Qureg, targets) -> None:
    """(QuEST.h:3464)."""
    V.validate_multi_targets(qureg, targets, "multiQubitNot")
    _apply_gate_x(qureg, tuple(targets))
    if _log(qureg):
        _log(qureg).record_multi_controlled_multi_qubit_not((), tuple(targets))


def multiControlledMultiQubitNot(qureg: Qureg, controls, targets) -> None:
    """(QuEST.h:3403)."""
    V.validate_multi_controls_multi_targets(qureg, controls, targets,
                                            "multiControlledMultiQubitNot")
    _apply_gate_x(qureg, tuple(targets), tuple(controls))
    if _log(qureg):
        _log(qureg).record_multi_controlled_multi_qubit_not(tuple(controls), tuple(targets))


# ---------------------------------------------------------------------------
# dense 1-qubit gates
# ---------------------------------------------------------------------------

def hadamard(qureg: Qureg, target: int) -> None:
    """Hadamard gate (QuEST.h:232)."""
    V.validate_target(qureg, target, "hadamard")
    _apply_gate_matrix(qureg, matrices.HADAMARD, (target,))
    if _log(qureg): _log(qureg).record_gate("hadamard", target)


def pauliY(qureg: Qureg, target: int) -> None:
    """sigma-Y (QuEST.h:230)."""
    V.validate_target(qureg, target, "pauliY")
    _apply_gate_matrix(qureg, matrices.PAULI_Y_M, (target,))
    if _log(qureg): _log(qureg).record_gate("sigmaY", target)


def controlledPauliY(qureg: Qureg, control: int, target: int) -> None:
    """Controlled sigma-Y (QuEST.h:236)."""
    V.validate_control_target(qureg, control, target, "controlledPauliY")
    _apply_gate_matrix(qureg, matrices.PAULI_Y_M, (target,), (control,))
    if _log(qureg): _log(qureg).record_controlled_gate("sigmaY", control, target)


def compactUnitary(qureg: Qureg, target: int, alpha: complex, beta: complex) -> None:
    """[[alpha, -conj(beta)], [beta, conj(alpha)]] (QuEST.h:2562)."""
    func = "compactUnitary"
    V.validate_target(qureg, target, func)
    V.validate_unitary_complex_pair(alpha, beta, qureg.eps, func)
    _apply_gate_matrix(qureg, matrices.compact_unitary_matrix(alpha, beta), (target,))
    if _log(qureg): _log(qureg).record_compact_unitary(alpha, beta, target)


def controlledCompactUnitary(qureg: Qureg, control: int, target: int,
                             alpha: complex, beta: complex) -> None:
    """Controlled [[alpha, -conj(beta)], [beta, conj(alpha)]] (QuEST.h:225)."""
    func = "controlledCompactUnitary"
    V.validate_control_target(qureg, control, target, func)
    V.validate_unitary_complex_pair(alpha, beta, qureg.eps, func)
    _apply_gate_matrix(qureg, matrices.compact_unitary_matrix(alpha, beta),
                       (target,), (control,))
    if _log(qureg): _log(qureg).record_controlled_compact_unitary(alpha, beta, control, target)


def unitary(qureg: Qureg, target: int, u) -> None:
    """General single-qubit unitary, unitarity-validated (QuEST.h:216)."""
    func = "unitary"
    V.validate_target(qureg, target, func)
    V.validate_unitary_matrix(u, 1, qureg.eps, func)
    _apply_gate_matrix(qureg, u, (target,))
    if _log(qureg): _log(qureg).record_unitary(np.asarray(u), target)


def controlledUnitary(qureg: Qureg, control: int, target: int, u) -> None:
    """Controlled general single-qubit unitary (QuEST.h:226)."""
    func = "controlledUnitary"
    V.validate_control_target(qureg, control, target, func)
    V.validate_unitary_matrix(u, 1, qureg.eps, func)
    _apply_gate_matrix(qureg, u, (target,), (control,))
    if _log(qureg): _log(qureg).record_controlled_unitary(np.asarray(u), control, target)


def multiControlledUnitary(qureg: Qureg, controls, target: int, u) -> None:
    """Multi-control general single-qubit unitary (QuEST.h:227)."""
    func = "multiControlledUnitary"
    V.validate_multi_controls_multi_targets(qureg, controls, (target,), func)
    V.validate_unitary_matrix(u, 1, qureg.eps, func)
    _apply_gate_matrix(qureg, u, (target,), tuple(controls))
    if _log(qureg): _log(qureg).record_multi_controlled_unitary(np.asarray(u), tuple(controls), target)


def multiStateControlledUnitary(qureg: Qureg, controls, states, target: int, u) -> None:
    """Controls conditioned on given bit values (QuEST.h:4448)."""
    func = "multiStateControlledUnitary"
    V.validate_multi_controls_multi_targets(qureg, controls, (target,), func)
    V.validate_control_state(states, len(controls), func)
    V.validate_unitary_matrix(u, 1, qureg.eps, func)
    _apply_gate_matrix(qureg, u, (target,), tuple(controls), tuple(int(s) for s in states))
    if _log(qureg):
        _log(qureg).record_multi_state_controlled_unitary(
            np.asarray(u), tuple(controls), tuple(int(s) for s in states), target)


# ---------------------------------------------------------------------------
# rotations
# ---------------------------------------------------------------------------

def rotateX(qureg: Qureg, target: int, angle: float) -> None:
    """exp(-i angle/2 X) (QuEST.h:217)."""
    V.validate_target(qureg, target, "rotateX")
    _apply_gate_matrix(qureg, matrices.rx_matrix(angle), (target,))
    if _log(qureg): _log(qureg).record_param_gate("rotateX", target, angle)


def rotateY(qureg: Qureg, target: int, angle: float) -> None:
    """exp(-i angle/2 Y) (QuEST.h:218)."""
    V.validate_target(qureg, target, "rotateY")
    _apply_gate_matrix(qureg, matrices.ry_matrix(angle), (target,))
    if _log(qureg): _log(qureg).record_param_gate("rotateY", target, angle)


def rotateAroundAxis(qureg: Qureg, target: int, angle: float, axis: Vector) -> None:
    """exp(-i angle/2 n.sigma) about a Bloch-sphere axis (QuEST.h:220)."""
    func = "rotateAroundAxis"
    V.validate_target(qureg, target, func)
    V.validate_vector(axis, func)
    _apply_gate_matrix(qureg, matrices.rotation_matrix(angle, axis), (target,))
    if _log(qureg): _log(qureg).record_axis_rotation(angle, axis, target)


def controlledRotateX(qureg: Qureg, control: int, target: int, angle: float) -> None:
    """Controlled exp(-i angle/2 X) (QuEST.h:221)."""
    V.validate_control_target(qureg, control, target, "controlledRotateX")
    _apply_gate_matrix(qureg, matrices.rx_matrix(angle), (target,), (control,))
    if _log(qureg): _log(qureg).record_controlled_param_gate("rotateX", control, target, angle)


def controlledRotateY(qureg: Qureg, control: int, target: int, angle: float) -> None:
    """Controlled exp(-i angle/2 Y) (QuEST.h:222)."""
    V.validate_control_target(qureg, control, target, "controlledRotateY")
    _apply_gate_matrix(qureg, matrices.ry_matrix(angle), (target,), (control,))
    if _log(qureg): _log(qureg).record_controlled_param_gate("rotateY", control, target, angle)


def controlledRotateAroundAxis(qureg: Qureg, control: int, target: int,
                               angle: float, axis: Vector) -> None:
    """Controlled rotation about an arbitrary Bloch axis (QuEST.h:224)."""
    func = "controlledRotateAroundAxis"
    V.validate_control_target(qureg, control, target, func)
    V.validate_vector(axis, func)
    _apply_gate_matrix(qureg, matrices.rotation_matrix(angle, axis), (target,), (control,))
    if _log(qureg): _log(qureg).record_controlled_axis_rotation(angle, axis, control, target)


def multiRotatePauli(qureg: Qureg, targets, paulis, angle: float) -> None:
    """exp(-i angle/2 P1 x P2 x ...) via basis rotation to Z then multiRotateZ
    (statevec_multiRotatePauli, QuEST_common.c:410-488)."""
    func = "multiRotatePauli"
    _multi_rotate_pauli(qureg, (), targets, paulis, angle, func)


def multiControlledMultiRotatePauli(qureg: Qureg, controls, targets, paulis,
                                    angle: float) -> None:
    """(QuEST.h:4726)."""
    func = "multiControlledMultiRotatePauli"
    _multi_rotate_pauli(qureg, tuple(controls), targets, paulis, angle, func)


def _multi_rotate_pauli(qureg, controls, targets, paulis, angle, func):
    V.validate_multi_controls_multi_targets(qureg, controls, targets, func)
    V.validate_num_pauli_codes(paulis, len(targets), func)
    codes = [int(p) for p in paulis]
    # identity Paulis are dropped from the Z-product (reference behaviour)
    active = [(t, c) for t, c in zip(targets, codes) if c != 0]
    if not active:
        # global phase exp(-i angle/2) on the controlled subspace
        if matrices.is_traced(angle):
            # runtime-parameter angle: assemble the phase inside the trace
            import jax
            import jax.numpy as jnp

            ph = jax.lax.complex(jnp.cos(angle / 2), -jnp.sin(angle / 2))
            if controls:
                _apply_gate_diag(qureg, jnp.stack([jnp.ones_like(ph), ph]),
                                 (controls[0],), tuple(controls[1:]))
            else:
                _apply_gate_diag(qureg, jnp.stack([ph, ph]), (targets[0],))
            return
        if controls:
            _apply_gate_diag(qureg, np.array([1.0, np.exp(-0.5j * angle)]),
                             (controls[0],), tuple(controls[1:]))
        else:
            _apply_gate_diag(qureg, np.full(2, np.exp(-0.5j * angle)), (targets[0],))
        return
    for t, c in active:
        if c in matrices.BASIS_TO_Z:
            _apply_gate_matrix(qureg, matrices.BASIS_TO_Z[c], (t,))
    _apply_gate_parity_phase(qureg, angle, tuple(t for t, _ in active), tuple(controls))
    for t, c in active:
        if c in matrices.BASIS_TO_Z:
            _apply_gate_matrix(qureg, np.conj(matrices.BASIS_TO_Z[c]).T, (t,))
    if _log(qureg):
        if controls:
            _log(qureg).record_comment(
                f"Here a {len(controls)}-control {len(targets)}-target "
                f"multiControlledMultiRotatePauli of angle {_log(qureg).fmt_real(angle)} "
                "was performed (QASM not yet implemented)")
        else:
            _log(qureg).record_comment(
                f"Here a {len(targets)}-qubit multiRotatePauli of angle "
                f"{_log(qureg).fmt_real(angle)} was performed (QASM not yet implemented)")


# ---------------------------------------------------------------------------
# swaps and multi-qubit unitaries
# ---------------------------------------------------------------------------

def swapGate(qureg: Qureg, qb1: int, qb2: int) -> None:
    """(QuEST.h:4331); axis transposition, see ops.apply.apply_swap."""
    V.validate_unique_targets(qureg, qb1, qb2, "swapGate")
    n = qureg.num_qubits_represented
    nsv = qureg.num_qubits_in_state_vec
    sched = _dist.active()
    apply = sched.apply_swap if sched else K.apply_swap
    amps = apply(qureg.amps, n=nsv, qb1=qb1, qb2=qb2)
    if qureg.is_density_matrix:
        amps = apply(amps, n=nsv, qb1=qb1 + n, qb2=qb2 + n)
    qureg.put(amps)
    if _log(qureg): _log(qureg).record_controlled_gate("swap", qb1, qb2)


def sqrtSwapGate(qureg: Qureg, qb1: int, qb2: int) -> None:
    """Square root of SWAP (QuEST.h:238)."""
    V.validate_unique_targets(qureg, qb1, qb2, "sqrtSwapGate")
    _apply_gate_matrix(qureg, matrices.SQRT_SWAP, (qb1, qb2))
    if _log(qureg): _log(qureg).record_controlled_gate("sqrtSwap", qb1, qb2)


def twoQubitUnitary(qureg: Qureg, t1: int, t2: int, u) -> None:
    """(QuEST.h:4945). Matrix rows ordered with t1 as the least-significant bit."""
    func = "twoQubitUnitary"
    V.validate_multi_targets(qureg, (t1, t2), func)
    V.validate_unitary_matrix(u, 2, qureg.eps, func)
    _apply_gate_matrix(qureg, u, (t1, t2))
    if _log(qureg):
        _log(qureg).record_comment("Here, an undisclosed 2-qubit unitary was applied.")


def controlledTwoQubitUnitary(qureg: Qureg, control: int, t1: int, t2: int, u) -> None:
    """Single-control dense two-target unitary (QuEST.h:244)."""
    func = "controlledTwoQubitUnitary"
    V.validate_multi_controls_multi_targets(qureg, (control,), (t1, t2), func)
    V.validate_unitary_matrix(u, 2, qureg.eps, func)
    _apply_gate_matrix(qureg, u, (t1, t2), (control,))
    if _log(qureg):
        _log(qureg).record_comment("Here, an undisclosed controlled 2-qubit unitary was applied.")


def multiControlledTwoQubitUnitary(qureg: Qureg, controls, t1: int, t2: int, u) -> None:
    """Multi-control dense two-target unitary (QuEST.h:245)."""
    func = "multiControlledTwoQubitUnitary"
    V.validate_multi_controls_multi_targets(qureg, controls, (t1, t2), func)
    V.validate_unitary_matrix(u, 2, qureg.eps, func)
    _apply_gate_matrix(qureg, u, (t1, t2), tuple(controls))
    if _log(qureg):
        _log(qureg).record_comment("Here, an undisclosed multi-controlled 2-qubit unitary was applied.")


def multiQubitUnitary(qureg: Qureg, targets, u) -> None:
    """General dense unitary (QuEST.h:5193); the kernel every gate reduces to."""
    func = "multiQubitUnitary"
    V.validate_multi_targets(qureg, targets, func)
    V.validate_matrix_init(u, func)
    V.validate_unitary_matrix(u, len(targets), qureg.eps, func)
    _apply_gate_matrix(qureg, u, tuple(targets))
    if _log(qureg):
        _log(qureg).record_comment("Here, an undisclosed multi-qubit unitary was applied.")


def controlledMultiQubitUnitary(qureg: Qureg, control: int, targets, u) -> None:
    """Single-control dense multi-target unitary (QuEST.h:247)."""
    func = "controlledMultiQubitUnitary"
    V.validate_multi_controls_multi_targets(qureg, (control,), targets, func)
    V.validate_matrix_init(u, func)
    V.validate_unitary_matrix(u, len(targets), qureg.eps, func)
    _apply_gate_matrix(qureg, u, tuple(targets), (control,))
    if _log(qureg):
        _log(qureg).record_comment("Here, an undisclosed controlled multi-qubit unitary was applied.")


def multiControlledMultiQubitUnitary(qureg: Qureg, controls, targets, u) -> None:
    """(QuEST.h:5366; reference dispatch QuEST_cpu_distributed.c:1526-1568)."""
    func = "multiControlledMultiQubitUnitary"
    V.validate_multi_controls_multi_targets(qureg, controls, targets, func)
    V.validate_matrix_init(u, func)
    V.validate_unitary_matrix(u, len(targets), qureg.eps, func)
    _apply_gate_matrix(qureg, u, tuple(targets), tuple(controls))
    if _log(qureg):
        _log(qureg).record_comment("Here, an undisclosed multi-controlled multi-qubit unitary was applied.")


# ---------------------------------------------------------------------------
# measurement (QuEST.h:3544-3719; logic QuEST_common.c:360-366)
# ---------------------------------------------------------------------------

def _prob_of_outcome(qureg: Qureg, target: int, outcome: int) -> float:
    nsv = qureg.num_qubits_in_state_vec
    if qureg.is_density_matrix:
        p = M.density_prob_of_outcome(qureg.amps, n=qureg.num_qubits_represented,
                                      target=target, outcome=outcome)
    else:
        p = M.prob_of_outcome(qureg.amps, n=nsv, target=target, outcome=outcome)
    # the float() below is THE per-shot host round-trip the on-device
    # sampler (quest_tpu.sampling) exists to avoid -- count it so the two
    # readout routes are comparable in telemetry
    from . import telemetry
    telemetry.inc("measure_host_syncs_total")
    return float(p)


def _collapse(qureg: Qureg, target: int, outcome: int, prob: float) -> None:
    nsv = qureg.num_qubits_in_state_vec
    if qureg.is_density_matrix:
        amps = M.density_collapse(qureg.amps, prob, n=qureg.num_qubits_represented,
                                  target=target, outcome=outcome)
    else:
        amps = M.collapse_statevec(qureg.amps, prob, n=nsv, target=target, outcome=outcome)
    qureg.put(amps)


def collapseToOutcome(qureg: Qureg, target: int, outcome: int) -> float:
    """Force a measurement outcome; returns its probability (QuEST.h:3668)."""
    func = "collapseToOutcome"
    V.validate_target(qureg, target, func)
    V.validate_outcome(outcome, func)
    prob = _prob_of_outcome(qureg, target, outcome)
    V.validate_measurement_prob(prob, qureg.eps, func)
    _collapse(qureg, target, outcome, prob)
    if qureg.qasm_log is not None:
        qureg.qasm_log.record_comment(
            f"Here, qubit {target} was un-physically projected into outcome {outcome}")
    return prob


def measureWithStats(qureg: Qureg, target: int):
    """Random measurement; returns (outcome, its probability) (QuEST.h:3719).

    The random draw uses the env's host Mersenne Twister so results are
    reproducible under seedQuEST, as generateMeasurementOutcome
    (QuEST_common.c:168-183).
    """
    V.validate_target(qureg, target, "measureWithStats")
    zero_prob = _prob_of_outcome(qureg, target, 0)
    # generateMeasurementOutcome (QuEST_common.c:168-183): REAL_EPS-scaled
    # cutoffs (precision-dependent, not absolute -- in f32 a zero-probability
    # branch sits well above 1e-16 of noise), and the RNG is consumed only
    # when the outcome is genuinely random, keeping the stream aligned with
    # the reference's across deterministic measurements.
    eps = qureg.eps
    if zero_prob < eps:
        outcome = 1
    elif 1 - zero_prob < eps:
        outcome = 0
    else:
        draw = (qureg.env.rng.random_sample() if qureg.env.rng is not None
                else np.random.random())
        outcome = int(draw > zero_prob)
    prob = zero_prob if outcome == 0 else 1 - zero_prob
    _collapse(qureg, target, outcome, prob)
    if qureg.qasm_log is not None:
        qureg.qasm_log.record_measurement(target)
    return outcome, prob


def measure(qureg: Qureg, target: int) -> int:
    """(QuEST.h:3693)."""
    V.validate_target(qureg, target, "measure")
    outcome, _ = measureWithStats(qureg, target)
    return outcome
