"""Whole-segment single-dispatch execution (ISSUE 12, round 13).

Historically every PallasRun / FrameSwap / collective on a tape was its
own device dispatch with the host interpreting the tape between them --
BASELINE.md's round-5 methodology measured that fixed host dispatch+sync
cost at ~25-100 ms per round (``dispatch_fixed_ms``), dominating serve
latency at small sizes. This module lowers a whole FusePlan *segment* --
a maximal tape slice whose two-frame permutation starts AND ends at
identity (the same boundaries ``run_segmented`` checkpoints at, proved
by plancheck QT102) -- into ONE jitted program dispatched once: the
command-buffer/graph-launch idea from the cuQuantum lineage (PAPERS.md)
re-targeted at XLA's one-traced-program-per-structure executable model.

Three execution surfaces ride it:

- :func:`run_slice` -- execute ``tape[lo:hi]`` on a register as one
  segment program (or item-by-item when segment dispatch is off);
  ``resilience.segmented`` uses it between checkpoints, with a stable
  cache key so resumed/healed segments never retrace.
- :func:`chain_executable` (behind ``Circuit.compiled_segments``) -- the
  tape as a chain of frame-identity-aligned segment programs, each at
  most ``max_items`` tape entries: the compile-boundedness of
  ``compiled_blocks`` with checkpointable seams and a dispatch count
  equal to the SEGMENT count, not the gate count.
- the per-item interpreter (:func:`run_slice` with the knob off) -- the
  fallback lattice rung: one device dispatch per tape entry, the
  pre-round-13 behavior, kept verbatim for triage and degraded modes.

Numeric contract (tests/test_segments.py pins all of it): a fixed
segmentation is run-to-run deterministic (bit-identical) on every leg;
the whole-tape segment program is bit-identical to ``compiled()``; and
on a single device the native-dtype per-item chain
(``compiled_segments(max_items=1)``) reproduces item-by-item
interpretation bit-for-bit. ACROSS program granularities XLA-CPU
duplicates producer expressions and contracts fma differently per
compiled program (the documented tests/test_sharded_df.py caveat), so
item-route vs multi-item-program comparisons -- and anything on the df
route or a CPU mesh, where even single items embed differently -- agree
to ~1 ulp, not bit-exactly. On TPU the Mosaic kernel is opaque to XLA,
so recontraction cannot reach inside it and the routes coincide.

Every device program launch counts ``device_dispatch_total{route}``
host-side (telemetry counters inside jit would count traces, not
executions): ``route="segment"`` per segment program, ``route="item"``
per eagerly interpreted tape entry, ``route="circuit"`` per whole-tape
``Circuit.run`` dispatch, ``route="request"`` per whole-request program
(:func:`request_executable` -- round 18: every segment plus the final
reduction composed into ONE dispatched program, the
``dispatches_per_circuit == 1`` floor), ``route="engine_vmap"`` /
``"engine_param"`` at the serving engine's two dispatch sites. docs/observability.md has
the full table; ``bench.py --config dispatch`` measures the A/B.

``QUEST_SEGMENT_DISPATCH`` (default 1 = on; 0 restores item-by-item
interpretation) gates the lowering, parsed warn-once via
``analysis.diagnostics.parse_env_int`` (QT306). :func:`force_route`
overrides it per-thread for A/B harnesses.
"""

from __future__ import annotations

import contextlib
import threading

from . import telemetry

__all__ = [
    "identity_boundaries", "segment_cuts", "stamp_plan",
    "segment_dispatch_default", "segment_dispatch_enabled", "force_route",
    "slice_executable", "run_slice", "chain_executable",
    "request_executable",
]

_SEG_ENV = "QUEST_SEGMENT_DISPATCH"
_DEF_SEGMENT_DISPATCH = 1
#: raw env strings already warned about (diagnostics.parse_env_int
#: warn-once contract; tests monkeypatch a fresh set)
_SEG_ENV_WARNED: set = set()

_ROUTE = threading.local()


def segment_dispatch_default() -> int:
    """The ``QUEST_SEGMENT_DISPATCH`` env value (default 1 = segment
    programs on, 0 = per-item interpretation), parsed warn-once: a
    malformed or negative value emits QT306 and falls back to the
    default."""
    from .analysis.diagnostics import parse_env_int
    return parse_env_int(_SEG_ENV, _DEF_SEGMENT_DISPATCH, minimum=0,
                         code="QT306", warned=_SEG_ENV_WARNED,
                         noun="segment-dispatch mode")


def segment_dispatch_enabled() -> bool:
    """Whether tape slices lower to single-dispatch segment programs:
    a :func:`force_route` override if one is active on this thread,
    else the ``QUEST_SEGMENT_DISPATCH`` env default."""
    forced = getattr(_ROUTE, "route", None)
    if forced is not None:
        return forced == "segment"
    return segment_dispatch_default() != 0


@contextlib.contextmanager
def force_route(route: str | None):
    """Pin the execution route for this thread: ``"segment"`` (one
    program per slice), ``"item"`` (per-entry interpretation), or None
    (defer to the env knob). The A/B harnesses (bench dispatch_20q,
    kernelprobe dispatch_sweep) use this to run both legs in one
    process regardless of the ambient ``QUEST_SEGMENT_DISPATCH``."""
    if route not in (None, "segment", "item"):
        raise ValueError(f"unknown dispatch route {route!r}")
    prev = getattr(_ROUTE, "route", None)
    _ROUTE.route = route
    try:
        yield
    finally:
        _ROUTE.route = prev


# -- frame-identity boundaries -----------------------------------------------

def _swap_blocks(perm: list, tile_bits: int, k: int, hi) -> None:
    """Apply one frame relabeling to the symbolic qubit permutation:
    blocks ``[tile_bits-k, tile_bits)`` and ``[hi, hi+k)`` (``hi`` =
    tile_bits when None) exchange, exactly mirroring what
    ``swap_bit_blocks`` / the scheduler's frame transpose do to the
    physical layout."""
    lo = tile_bits - k
    hi = tile_bits if hi is None else hi
    for i in range(k):
        perm[lo + i], perm[hi + i] = perm[hi + i], perm[lo + i]


def identity_boundaries(tape, nsv: int) -> list:
    """Indices ``i`` where the two-frame permutation is identity after
    ``tape[:i]`` -- the legal segment seams. Always includes 0; includes
    ``len(tape)`` iff the tape ends at identity (every fused plan does,
    by the QT102 contract). Replays the frame symbolically from the
    PallasRun load/store swaps and standalone FrameSwaps; all other
    entries leave the frame untouched.

    This is the ONE boundary computation -- ``resilience.segmented``
    delegates here (its pre-round-13 replay unpacked FrameSwap args as
    an exact 3-tuple and broke on the 4-arg comm_pipeline-stamped
    entries of PR 8; the codec-tolerant slice unpack below is the
    regression-tested fix)."""
    perm = list(range(nsv))
    ident = list(range(nsv))
    bounds = [0]
    for i, (f, a, _kw) in enumerate(tape):
        name = getattr(f, "__name__", "")
        if name == "_apply_pallas_run":
            _ops, tb, lk, sk, lh, sh = a[:6]
            if lk:
                _swap_blocks(perm, tb, lk, lh)
            if sk:
                _swap_blocks(perm, tb, sk, sh)
        elif name == "_apply_frame_swap":
            tb, k, hi = a[:3]
            _swap_blocks(perm, tb, k, hi)
        if perm == ident:
            bounds.append(i + 1)
    return bounds


def measurement_seams(tape) -> set:
    """Tape indices that MUST be segment cuts because a measurement site
    (round 19, ``quest_tpu.sampling.measure`` -- entries tagged
    ``_measurement_site``) sits between them: the seam before and after
    each site. Measurement sites are where recorded outcomes become
    definite, so checkpoint/resume boundaries align with them exactly
    like they align with frame identity."""
    seams: set = set()
    for i, (f, _a, _kw) in enumerate(tape):
        if getattr(f, "_measurement_site", False):
            seams.add(i)
            seams.add(i + 1)
    return seams


def segment_cuts(tape, nsv: int, max_items: int | None = None) -> list:
    """Greedy coarsest identity-aligned cut list ``[0, ..., len(tape)]``:
    each segment is the LARGEST boundary-to-boundary span of at most
    ``max_items`` tape entries (None = unbounded, typically the whole
    tape as one program -- in the two-frame scheme most items restore
    identity individually, so boundaries are plentiful and the cap, not
    the boundary supply, sets the segment size). A single
    boundary-to-boundary gap longer than ``max_items`` becomes its own
    segment (frames cannot be cut mid-flight). A tape that does not end
    at identity gets a final non-checkpointable segment to ``len(tape)``
    -- execution stays correct; only fused plans guarantee the QT102
    tail.

    Measurement sites (:func:`measurement_seams`) force additional cuts:
    a segment never spans across a mid-circuit measurement, so every
    site starts (and ends) its own segment -- the seam where a recorded
    outcome becomes definite. A seam that is not at frame identity is
    skipped (the frame cannot be cut mid-flight; tapelint QT005 flags
    that tape)."""
    if max_items is not None and max_items < 1:
        raise ValueError("max_items must be >= 1")
    bounds = identity_boundaries(tape, nsv)
    if bounds[-1] != len(tape):
        bounds.append(len(tape))
    # forced measurement seams, restricted to legal (identity) boundaries
    forced = sorted(measurement_seams(tape) & set(bounds))
    cuts = [0]
    while cuts[-1] < len(tape):
        start = cuts[-1]
        fence = next((b for b in forced if b > start), None)
        nxt = [b for b in bounds if b > start
               and (fence is None or b <= fence)]
        if max_items is not None:
            capped = [b for b in nxt if b - start <= max_items]
            cuts.append(capped[-1] if capped else nxt[0])
        else:
            cuts.append(nxt[-1])
    return cuts


def stamp_plan(plan, nsv: int) -> int:
    """Stamp every frame-carrying plan item (PallasRun / FrameSwap) with
    the index of the frame-identity segment it belongs to (``item.seg``,
    round-13 tape codec slot) and return the segment count. Segment
    indices advance exactly at identity returns, so plancheck's QT107
    check can re-derive them independently and prove each emitted
    segment starts and ends at frame identity in FusePlan order."""
    from . import fusion
    perm = list(range(nsv))
    ident = list(range(nsv))
    seg = 0
    for item in plan.items:
        if isinstance(item, fusion.PallasRun):
            item.seg = seg
            if item.load_swap_k:
                _swap_blocks(perm, item.tile_bits, item.load_swap_k,
                             item.load_swap_hi)
            if item.store_swap_k:
                _swap_blocks(perm, item.tile_bits, item.store_swap_k,
                             item.store_swap_hi)
        elif isinstance(item, fusion.FrameSwap):
            item.seg = seg
            _swap_blocks(perm, item.tile_bits, item.k, item.hi)
        if perm == ident:
            seg += 1
    return seg


# -- segment programs --------------------------------------------------------

def slice_executable(circuit, lo: int, hi: int, donate: bool = True):
    """``tape[lo:hi]`` as ONE jitted executable -- the segment program.

    Cached in the process-global bounded LRU (engine.cache.executables)
    keyed on the circuit's stable ``_cache_token`` plus the slice and
    execution-mode meshes, so repeated segment executions -- checkpoint
    cadences, rollback-and-replay healing, bench chains -- dispatch
    warm without retracing (the pre-round-13 ``run_segmented`` built a
    fresh Circuit per segment and paid a full recompile every run).
    Mesh pinning mirrors ``Circuit.compiled``: jit traces on first
    call, which may happen under a different scheduler/pallas-mesh
    context than the one this executable is keyed on."""
    import jax

    from . import fusion
    from .engine import cache as _ec
    from .parallel import scheduler as _dist
    sched = _dist.active()
    mesh = sched.mesh if sched else None
    pmesh = fusion.active_pallas_mesh()
    key = ("segment", circuit._cache_token, lo, hi, donate, mesh, pmesh)

    def build():
        inner = jax.jit(circuit._replay_fn(None, lo=lo, hi=hi),
                        donate_argnums=(0,) if donate else ())

        def fn(amps, _inner=inner, _mesh=mesh, _pmesh=pmesh):
            from .circuits import _amps_mesh
            pm = _pmesh if _pmesh is not None else _amps_mesh(amps)
            with _dist.explicit_mesh(_mesh), fusion.pallas_mesh(pm):
                return _inner(amps)

        return fn

    return _ec.executables().get_or_create(key, build)


def run_slice(circuit, qureg, lo: int = 0, hi: int | None = None, *,
              donate: bool = True):
    """Execute ``tape[lo:hi]`` on ``qureg`` (mutates its amps).

    With segment dispatch on (:func:`segment_dispatch_enabled`), the
    slice runs as ONE segment program --
    ``device_dispatch_total{route="segment"}`` counts exactly one
    launch. Otherwise the host interprets item-by-item, the fallback
    lattice rung: each entry is applied eagerly (its own device
    program(s), the pre-round-13 behavior) and counts
    ``route="item"``. Both routes satisfy the numeric contract in the
    module docstring: deterministic per route, bit-identical where the
    compiled programs match, ~1 ulp across program granularities on
    XLA-CPU (granularity-invariant on TPU, where Mosaic kernels are
    opaque to fma recontraction)."""
    from . import fusion
    from .circuits import _register_mesh
    hi = len(circuit._tape) if hi is None else hi
    if hi <= lo:
        return qureg
    ctx = telemetry.current_trace() if telemetry.trace_on() else None
    with fusion.pallas_mesh(_register_mesh(qureg)):
        if segment_dispatch_enabled():
            fn = slice_executable(circuit, lo, hi, donate=donate)
            telemetry.inc("device_dispatch_total", route="segment")
            if ctx is not None:
                # the segment launch splits into its dispatch/device
                # phases: an explicit sync separates the host-side
                # launch from the device drain (armed path only -- the
                # untraced path never blocks)
                import time as _time

                import jax as _jax
                t0 = _time.perf_counter()
                out = fn(qureg.amps)
                t1 = _time.perf_counter()
                _jax.block_until_ready(out)
                t2 = _time.perf_counter()
                ctx.phase("dispatch", t0, t1 - t0)
                ctx.phase("device", t1, t2 - t1)
                qureg.put(out)
            else:
                qureg.put(fn(qureg.amps))
        else:
            for f, a, kw in circuit._tape[lo:hi]:
                telemetry.inc("device_dispatch_total", route="item")
                f(qureg, *a, **kw)
    return qureg


def chain_executable(circuit, max_items: int | None = None,
                     donate: bool = True):
    """The whole tape as a chain of segment programs (one per
    :func:`segment_cuts` span), behind ``Circuit.compiled_segments``.
    Each link is a cached :func:`slice_executable`; the chain itself is
    cached too. Calling the chain counts one
    ``device_dispatch_total{route="segment"}`` per link -- the dispatch
    tax is the segment count, amortizing the per-item tax by the mean
    items-per-segment (the dispatch_20q bench row asserts the
    collapse)."""
    from . import fusion
    from .engine import cache as _ec
    from .parallel import scheduler as _dist
    sched = _dist.active()
    key = ("segment_chain", circuit._cache_token, max_items, donate,
           sched.mesh if sched else None, fusion.active_pallas_mesh())

    def build():
        nsv = (2 if circuit.is_density_matrix else 1) * circuit.num_qubits
        cuts = segment_cuts(circuit._tape, nsv, max_items)
        fns = tuple(slice_executable(circuit, a, b, donate=donate)
                    for a, b in zip(cuts, cuts[1:]))

        def chained(amps, _fns=fns):
            for f in _fns:
                telemetry.inc("device_dispatch_total", route="segment")
                amps = f(amps)
            return amps

        chained.num_segments = len(fns)
        return chained

    return _ec.executables().get_or_create(key, build)


def request_executable(circuit, donate: bool = True, reduce=None):
    """The WHOLE request as ONE dispatched program (round 18): every
    frame-identity segment of the tape, plus an optional final traceable
    ``reduce(amps)`` (a probability readout, an expectation contraction),
    composed inside a single ``jax.jit`` with the state buffer donated
    end-to-end -- intermediate segment states live and die inside the
    one XLA program, never round-tripping through the host. ``reduce``
    may declare extra RUNTIME positional arguments after ``amps`` (the
    round-19 shot sampler's PRNG seed); the returned executable passes
    them through -- ``fn(amps, *extra)`` -- so value changes never touch
    the cache key or the compiled structure. A request
    then touches the host exactly twice (submit, result) and
    ``device_dispatch_total{route="request"}`` counts exactly ONE launch
    per call: ``dispatches_per_circuit`` hits its floor of 1, where
    :func:`chain_executable` pays one launch per segment.

    The segment seams (every :func:`identity_boundaries` return to frame
    identity) are preserved as replay-slice boundaries, so the program
    is the composition of the SAME per-segment replays the chained and
    checkpointed routes run -- slice replays compose into the identical
    primitive sequence as the whole-tape replay, making the request
    program bit-identical to ``compiled()`` run-to-run (the chained-vs-
    item cross-granularity caveat in the module docstring still applies
    on XLA-CPU). Cached in the process-global LRU under
    ``("request_chain", ...)``; ``fn.num_segments`` reports how many
    segments were composed, ``fn.num_dispatches = 1`` the launch
    count."""
    import jax

    from . import fusion
    from .engine import cache as _ec
    from .parallel import scheduler as _dist
    if getattr(reduce, "wants_values", False):
        from .validation import QuESTError
        raise QuESTError(
            "request_executable replays a concrete tape and has no "
            "parameter-values vector to hand a wants_values reduce (the "
            "gradient engine's grad_reduce); use Circuit.gradient / "
            "Engine.submit_grad for the one-dispatch grad_request route",
            "request_executable")
    sched = _dist.active()
    mesh = sched.mesh if sched else None
    pmesh = fusion.active_pallas_mesh()
    key = ("request_chain", circuit._cache_token, donate, reduce, mesh,
           pmesh)

    def build():
        nsv = (2 if circuit.is_density_matrix else 1) * circuit.num_qubits
        bounds = identity_boundaries(circuit._tape, nsv)
        if bounds[-1] != len(circuit._tape):
            bounds.append(len(circuit._tape))
        replays = tuple(circuit._replay_fn(None, lo=a, hi=b)
                        for a, b in zip(bounds, bounds[1:]))

        def whole(amps, *extra, _replays=replays, _reduce=reduce):
            for f in _replays:
                amps = f(amps)
            return amps if _reduce is None else _reduce(amps, *extra)

        inner = jax.jit(whole, donate_argnums=(0,) if donate else ())

        def fn(amps, *extra, _inner=inner, _mesh=mesh, _pmesh=pmesh):
            from .circuits import _amps_mesh
            pm = _pmesh if _pmesh is not None else _amps_mesh(amps)
            # ONE launch for the whole request -- the counter delta the
            # bench's dispatches_per_circuit row and native.yml gate read
            telemetry.inc("device_dispatch_total", route="request")
            with _dist.explicit_mesh(_mesh), fusion.pallas_mesh(pm):
                return _inner(amps, *extra)

        fn.num_segments = len(replays)
        fn.num_dispatches = 1
        return fn

    return _ec.executables().get_or_create(key, build)
