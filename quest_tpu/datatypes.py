"""User-facing data structures (reference: ``QuEST/include/QuEST.h``).

The reference's planar (SoA) ``ComplexArray`` layout (QuEST.h:94-98) is an
implementation detail of its C kernels; here gate matrices are plain
numpy/jax arrays and the state itself is a complex jax.Array (XLA stores
complex as a (re, im) pair internally, which is the same planar layout).

Structures:
  - pauliOpType enum            (QuEST.h:262-270)
  - phaseFunc / bitEncoding     (QuEST.h enums for the phase-function family)
  - ComplexMatrix2/4/N helpers  (QuEST.h:154-208; create/destroy are no-ops
                                 in Python -- any (2^n, 2^n) array-like works)
  - Vector                      (QuEST.h:215-218)
  - PauliHamil                  (QuEST.h:296-307, createPauliHamilFromFile QuEST.h:914)
  - DiagonalOp                  (QuEST.h:316-332) -- full 2^N diagonal, device-resident
  - SubDiagonalOp               (QuEST.h:340-351) -- small diagonal on <=N targets
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from . import precision, validation


class pauliOpType(enum.IntEnum):
    """Pauli operator codes, as the reference enum (QuEST.h:262-270)."""

    PAULI_I = 0
    PAULI_X = 1
    PAULI_Y = 2
    PAULI_Z = 3


PAULI_I = pauliOpType.PAULI_I
PAULI_X = pauliOpType.PAULI_X
PAULI_Y = pauliOpType.PAULI_Y
PAULI_Z = pauliOpType.PAULI_Z

#: dense 2x2 matrices for each Pauli code (row-major, numpy)
PAULI_MATRICES = {
    0: np.eye(2, dtype=np.complex128),
    1: np.array([[0, 1], [1, 0]], dtype=np.complex128),
    2: np.array([[0, -1j], [1j, 0]], dtype=np.complex128),
    3: np.array([[1, 0], [0, -1]], dtype=np.complex128),
}


class bitEncoding(enum.IntEnum):
    """Sub-register value encodings for phase functions (QuEST.h enum bitEncoding)."""

    UNSIGNED = 0
    TWOS_COMPLEMENT = 1


class phaseFunc(enum.IntEnum):
    """Named phase functions (QuEST.h enum phaseFunc)."""

    NORM = 0
    SCALED_NORM = 1
    INVERSE_NORM = 2
    SCALED_INVERSE_NORM = 3
    SCALED_INVERSE_SHIFTED_NORM = 4
    PRODUCT = 5
    SCALED_PRODUCT = 6
    INVERSE_PRODUCT = 7
    SCALED_INVERSE_PRODUCT = 8
    DISTANCE = 9
    SCALED_DISTANCE = 10
    INVERSE_DISTANCE = 11
    SCALED_INVERSE_DISTANCE = 12
    SCALED_INVERSE_SHIFTED_DISTANCE = 13
    SCALED_INVERSE_SHIFTED_WEIGHTED_DISTANCE = 14


@dataclass
class Vector:
    """A 3-vector, used for Bloch-axis rotations (QuEST.h:215-218)."""

    x: float
    y: float
    z: float

    def __getitem__(self, i):
        return (self.x, self.y, self.z)[i]


# ---------------------------------------------------------------------------
# gate matrices
# ---------------------------------------------------------------------------

def createComplexMatrixN(num_qubits: int) -> np.ndarray:
    """Zeroed 2^n x 2^n gate matrix (reference: createComplexMatrixN, QuEST.c:775-819).

    In Python any array-like of that shape is accepted by the apply functions;
    this exists for API parity and convenience.
    """
    validation.validate_num_qubits(num_qubits, "createComplexMatrixN")
    dim = 2 ** num_qubits
    return np.zeros((dim, dim), dtype=np.complex128)


def destroyComplexMatrixN(matrix) -> None:
    """No-op (garbage collected); kept for API parity."""


def initComplexMatrixN(matrix: np.ndarray, real, imag) -> None:
    """Overwrite a matrix from real/imag nested lists (initComplexMatrixN, QuEST.c)."""
    func = "initComplexMatrixN"
    validation.validate_matrix_init(matrix, func)
    validation.validate_matrix_init_dims(matrix, real, imag, func)
    matrix[...] = np.asarray(real) + 1j * np.asarray(imag)


class BoundComplexMatrixN:
    """A ComplexMatrixN aliasing caller-owned real/imag storage
    (bindArraysToStackComplexMatrixN, QuEST.h:6232, QuEST_common.c:649-677).

    The reference points a stack matrix at user row arrays without copying,
    so later edits to the storage are seen by subsequent gate applications.
    Here the bound numpy planes are kept by reference and the complex matrix
    is assembled lazily on each use (every consumer funnels through
    ``np.asarray``, which calls ``__array__``).
    """

    def __init__(self, real: np.ndarray, imag: np.ndarray):
        self.real = real
        self.imag = imag
        self.shape = real.shape
        self.ndim = 2

    def __array__(self, dtype=None, copy=None):
        m = self.real + 1j * self.imag
        return m.astype(dtype) if dtype is not None else m

    def __getitem__(self, idx):
        return (self.real + 1j * self.imag)[idx]

    def __repr__(self):
        return f"BoundComplexMatrixN({self.real + 1j * self.imag!r})"


def bindArraysToStackComplexMatrixN(num_qubits: int, real, imag,
                                    re_storage=None, im_storage=None) -> BoundComplexMatrixN:
    """Bind a 2^n x 2^n matrix over caller-provided planar arrays without
    copying; see :class:`BoundComplexMatrixN`. The ``re_storage``/
    ``im_storage`` pointer-plumbing arguments are accepted for signature
    parity and ignored (numpy arrays own their storage).
    """
    func = "bindArraysToStackComplexMatrixN"
    dim = 1 << num_qubits
    real = np.asarray(real, dtype=float)
    imag = np.asarray(imag, dtype=float)
    validation._assert(real.shape == (dim, dim) and imag.shape == (dim, dim),
                       "Invalid matrix dimensions. The real and imaginary components must each be 2^numQubits x 2^numQubits.",
                       func)
    return BoundComplexMatrixN(real, imag)


def getStaticComplexMatrixN(real, imag=None, _imag=None) -> np.ndarray:
    """Build a matrix from nested lists (reference macro getStaticComplexMatrixN,
    QuEST.h:6232). Accepts both the 2-arg (re, im) and the reference's 3-arg
    (numQubits, re, im) call shapes."""
    func = "getStaticComplexMatrixN"
    if np.ndim(real) == 0:  # 3-arg reference shape: (numQubits, re, im)
        num_qubits, real, imag = int(real), imag, _imag
        validation._assert(imag is not None,
                           "Both real and imaginary matrix components must be given.", func)
        m = np.asarray(real) + 1j * np.asarray(imag)
        validation._assert(m.shape == (1 << num_qubits, 1 << num_qubits),
                           "Invalid matrix dimensions for the given number of qubits.", func)
        return m
    validation._assert(_imag is None and imag is not None,
                       "Both real and imaginary matrix components must be given.", func)
    return np.asarray(real) + 1j * np.asarray(imag)


# ---------------------------------------------------------------------------
# PauliHamil
# ---------------------------------------------------------------------------

@dataclass
class PauliHamil:
    """Real-weighted sum of Pauli products (QuEST.h:296-307).

    ``pauli_codes`` has shape (num_sum_terms, num_qubits): codes[t, q] is the
    Pauli acting on qubit q in term t (the reference flattens this to a single
    array of length numSumTerms*numQubits with the same ordering).
    """

    num_qubits: int
    num_sum_terms: int
    pauli_codes: np.ndarray = field(default=None)
    term_coeffs: np.ndarray = field(default=None)

    def __post_init__(self):
        if self.pauli_codes is None:
            self.pauli_codes = np.zeros((self.num_sum_terms, self.num_qubits), dtype=np.int32)
        else:
            self.pauli_codes = np.asarray(self.pauli_codes, dtype=np.int32).reshape(
                self.num_sum_terms, self.num_qubits)
        if self.term_coeffs is None:
            self.term_coeffs = np.zeros((self.num_sum_terms,), dtype=np.float64)
        else:
            self.term_coeffs = np.asarray(self.term_coeffs, dtype=np.float64).reshape(
                self.num_sum_terms)


def createPauliHamil(num_qubits: int, num_sum_terms: int) -> PauliHamil:
    """Blank Hamiltonian (createPauliHamil, QuEST.h:858)."""
    func = "createPauliHamil"
    validation.validate_num_qubits(num_qubits, func)
    validation._assert(num_sum_terms > 0, "Invalid number of terms in the PauliHamil. The number of terms must be strictly positive.", func)
    return PauliHamil(num_qubits, num_sum_terms)


def destroyPauliHamil(hamil: PauliHamil) -> None:
    """No-op; kept for API parity."""


def initPauliHamil(hamil: PauliHamil, coeffs, codes) -> None:
    """Overwrite a Hamiltonian in-place (initPauliHamil, QuEST.h:953)."""
    func = "initPauliHamil"
    codes = np.asarray(codes, dtype=np.int32).reshape(hamil.num_sum_terms, hamil.num_qubits)
    validation.validate_pauli_codes(codes.ravel(), func)
    hamil.term_coeffs[...] = np.asarray(coeffs, dtype=np.float64)
    hamil.pauli_codes[...] = codes


def createPauliHamilFromFile(path: str) -> PauliHamil:
    """Parse the reference's Hamiltonian file format (createPauliHamilFromFile,
    QuEST.h:914): each line is ``coeff code code ... code`` with one code per
    qubit; the qubit count is inferred from the first line."""
    func = "createPauliHamilFromFile"
    try:
        f = open(path)
    except OSError:
        validation.validate_file_opened(False, path, func)
    coeffs, codes = [], []
    with f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            try:
                coeffs.append(float(parts[0]))
            except ValueError:
                validation.validate_hamil_file_coeff_parsed(False, path, func)
            row = []
            for c in parts[1:]:
                try:
                    v = float(c)
                except ValueError:
                    validation.validate_hamil_file_pauli_parsed(False, path, func)
                validation._assert(v == int(v), "Failed to parse the next "
                                   f"expected Pauli code in PauliHamil file ({path}).",
                                   func)
                validation.validate_hamil_file_pauli_code(int(v), path, func)
                row.append(int(v))
            codes.append(row)
    num_qubits = len(codes[0]) if codes else 0
    validation.validate_hamil_file_params(num_qubits, len(coeffs), path, func)
    validation._assert(all(len(c) == num_qubits for c in codes),
                       "Failed to parse the next expected Pauli code in "
                       f"PauliHamil file ({path}).", func)
    hamil = PauliHamil(num_qubits, len(coeffs), np.asarray(codes), np.asarray(coeffs))
    validation.validate_pauli_hamil(hamil, func)
    return hamil


def pauli_term_matrix(codes_row) -> np.ndarray:
    """Dense 2^N matrix of one Pauli product term; qubit 0 = least-significant
    index bit, so it is the *last* factor of the Kronecker product."""
    m = np.eye(1, dtype=np.complex128)
    for code in reversed(list(codes_row)):
        m = np.kron(m, PAULI_MATRICES[int(code)])
    return m


# ---------------------------------------------------------------------------
# DiagonalOp / SubDiagonalOp
# ---------------------------------------------------------------------------

@dataclass
class DiagonalOp:
    """Full-Hilbert 2^N diagonal operator (QuEST.h:316-332).

    The reference keeps a host copy plus a persistent GPU copy synced by
    ``syncDiagonalOp`` (QuEST_gpu_common.cu:508-640). Here ``elems`` is a
    device jax.Array (shardable exactly like a Qureg); set/sync update it
    functionally.
    """

    num_qubits: int
    elems: jnp.ndarray  # planar (2, 2^N): [0]=real plane, [1]=imag plane

    @property
    def real(self) -> np.ndarray:
        return np.asarray(self.elems[0])

    @property
    def imag(self) -> np.ndarray:
        return np.asarray(self.elems[1])


@dataclass
class SubDiagonalOp:
    """Diagonal operator on a subset of <=N qubits (QuEST.h:340-351); small and
    replicated (never sharded)."""

    num_qubits: int
    elems: np.ndarray

    @property
    def num_elems(self) -> int:
        return 2 ** self.num_qubits


def createSubDiagonalOp(num_qubits: int) -> SubDiagonalOp:
    """Allocate a diagonal operator over a qubit subset (QuEST.h:185)."""
    validation.validate_num_qubits(num_qubits, "createSubDiagonalOp")
    return SubDiagonalOp(num_qubits, np.zeros(2 ** num_qubits, dtype=np.complex128))


def destroySubDiagonalOp(op: SubDiagonalOp) -> None:
    """No-op; kept for API parity."""
