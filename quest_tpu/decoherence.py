"""Decoherence channels on density matrices (reference QuEST.h:3976-4219,
5412-5630; kernels in ops.density).

Every channel is either a broadcasted diagonal factor (dephasing) or one dense
superoperator application on qubits (T, T+N) -- see ops/density.py for why
this single mechanism replaces the reference's bespoke MPI protocols.

The built-in channels' Kraus operators live in ONE canonical table,
``quest_tpu/channels.py`` (the ops.density builders delegate to it
bit-identically), shared with the trajectory route: ``trajectories.unravel``
rewrites every CPTP mix* site recorded on a density tape into a stochastic
pure-state Kraus selection (docs/trajectories.md). The NonTP variants and
``mixDensityMatrix`` have no trajectory unraveling and stay density-only.
"""

from __future__ import annotations

import numpy as np

from . import validation as V
from .ops import density as DN, init as I
from .registers import Qureg

__all__ = [
    "mixDephasing", "mixTwoQubitDephasing", "mixDepolarising", "mixDamping",
    "mixTwoQubitDepolarising", "mixPauli", "mixDensityMatrix", "mixKrausMap",
    "mixTwoQubitKrausMap", "mixMultiQubitKrausMap", "mixNonTPKrausMap",
    "mixNonTPTwoQubitKrausMap", "mixNonTPMultiQubitKrausMap",
]


def _record(qureg, text):
    if qureg.qasm_log is not None:
        qureg.qasm_log.record_comment(text)


def mixDephasing(qureg: Qureg, target: int, prob: float) -> None:
    """rho -> (1-p) rho + p Z rho Z (QuEST.h:3976)."""
    func = "mixDephasing"
    V.validate_density_matr(qureg, func)
    V.validate_target(qureg, target, func)
    V.validate_one_qubit_dephase_prob(prob, func)
    qureg.put(DN.apply_dephasing(qureg.amps, prob, n=qureg.num_qubits_represented,
                                 target=target))
    _record(qureg, f"mixDephasing({prob:g}) on q[{target}]")


def mixTwoQubitDephasing(qureg: Qureg, q1: int, q2: int, prob: float) -> None:
    """(QuEST.h:4008)."""
    func = "mixTwoQubitDephasing"
    V.validate_density_matr(qureg, func)
    V.validate_unique_targets(qureg, q1, q2, func)
    V.validate_two_qubit_dephase_prob(prob, func)
    qureg.put(DN.apply_two_qubit_dephasing(qureg.amps, prob,
                                           n=qureg.num_qubits_represented, q1=q1, q2=q2))
    _record(qureg, f"mixTwoQubitDephasing({prob:g}) on q[{q1}],q[{q2}]")


def mixDepolarising(qureg: Qureg, target: int, prob: float) -> None:
    """rho -> (1-p) rho + p/3 (X rho X + Y rho Y + Z rho Z) (QuEST.h:4051)."""
    func = "mixDepolarising"
    V.validate_density_matr(qureg, func)
    V.validate_target(qureg, target, func)
    V.validate_one_qubit_depol_prob(prob, func)
    superop = DN.kraus_superoperator(DN.depolarising_kraus(prob))
    qureg.put(DN.apply_channel(qureg.amps, superop, n=qureg.num_qubits_represented,
                               targets=(target,)))
    _record(qureg, f"mixDepolarising({prob:g}) on q[{target}]")


def mixDamping(qureg: Qureg, target: int, prob: float) -> None:
    """Amplitude damping toward |0> (QuEST.h:4089)."""
    func = "mixDamping"
    V.validate_density_matr(qureg, func)
    V.validate_target(qureg, target, func)
    V.validate_one_qubit_damping_prob(prob, func)
    superop = DN.kraus_superoperator(DN.damping_kraus(prob))
    qureg.put(DN.apply_channel(qureg.amps, superop, n=qureg.num_qubits_represented,
                               targets=(target,)))
    _record(qureg, f"mixDamping({prob:g}) on q[{target}]")


def mixTwoQubitDepolarising(qureg: Qureg, q1: int, q2: int, prob: float) -> None:
    """(QuEST.h:4156; 3-exchange MPI protocol QuEST_cpu_distributed.c:778-868,
    here a single 16x16 superoperator)."""
    func = "mixTwoQubitDepolarising"
    V.validate_density_matr(qureg, func)
    V.validate_unique_targets(qureg, q1, q2, func)
    V.validate_two_qubit_depol_prob(prob, func)
    superop = DN.two_qubit_depolarising_superop(prob)
    qureg.put(DN.apply_channel(qureg.amps, superop, n=qureg.num_qubits_represented,
                               targets=(q1, q2)))
    _record(qureg, f"mixTwoQubitDepolarising({prob:g}) on q[{q1}],q[{q2}]")


def mixPauli(qureg: Qureg, target: int, px: float, py: float, pz: float) -> None:
    """General Pauli channel (QuEST.h:4197; 4-op Kraus, QuEST_common.c:740-760)."""
    func = "mixPauli"
    V.validate_density_matr(qureg, func)
    V.validate_target(qureg, target, func)
    V.validate_pauli_probs(px, py, pz, func)
    superop = DN.kraus_superoperator(DN.pauli_kraus(px, py, pz))
    qureg.put(DN.apply_channel(qureg.amps, superop, n=qureg.num_qubits_represented,
                               targets=(target,)))
    _record(qureg, f"mixPauli({px:g},{py:g},{pz:g}) on q[{target}]")


def mixDensityMatrix(combine: Qureg, prob: float, other: Qureg) -> None:
    """combine = (1-p) combine + p other (QuEST.h:4219)."""
    func = "mixDensityMatrix"
    V.validate_density_matr(combine, func)
    V.validate_density_matr(other, func)
    V.validate_matching_qureg_dims(combine, other, func)
    V.validate_probability(prob, 1.0, func)
    dt = combine.dtype
    import jax.numpy as jnp

    def planar(v):
        return jnp.asarray([v, 0.0], dtype=dt)

    combine.put(I.weighted_sum(planar(1 - prob), combine.amps,
                               planar(prob), other.amps,
                               planar(0.0), combine.amps))
    _record(combine, f"mixDensityMatrix({prob:g})")


def _mix_kraus(qureg, targets, ops, func, check_cptp):
    V.validate_density_matr(qureg, func)
    V.validate_multi_targets(qureg, targets, func)
    V.validate_kraus_ops(ops, len(targets), qureg.eps, func, check_cptp=check_cptp)
    superop = DN.kraus_superoperator(ops)
    qureg.put(DN.apply_channel(qureg.amps, superop, n=qureg.num_qubits_represented,
                               targets=tuple(targets)))
    _record(qureg, f"{func} on qubits {list(targets)}")


def mixKrausMap(qureg: Qureg, target: int, ops) -> None:
    """1-qubit Kraus map of up to 4 operators (QuEST.h:5412)."""
    _mix_kraus(qureg, (target,), ops, "mixKrausMap", True)


def mixTwoQubitKrausMap(qureg: Qureg, q1: int, q2: int, ops) -> None:
    """(QuEST.h:5453); matrix bit order: q1 is the least-significant bit."""
    _mix_kraus(qureg, (q1, q2), ops, "mixTwoQubitKrausMap", True)


def mixMultiQubitKrausMap(qureg: Qureg, targets, ops) -> None:
    """(QuEST.h:5505)."""
    _mix_kraus(qureg, tuple(targets), ops, "mixMultiQubitKrausMap", True)


def mixNonTPKrausMap(qureg: Qureg, target: int, ops) -> None:
    """Non-trace-preserving variant (QuEST.h:5540)."""
    _mix_kraus(qureg, (target,), ops, "mixNonTPKrausMap", False)


def mixNonTPTwoQubitKrausMap(qureg: Qureg, q1: int, q2: int, ops) -> None:
    """Two-qubit Kraus map WITHOUT completeness validation (QuEST.h:270)."""
    _mix_kraus(qureg, (q1, q2), ops, "mixNonTPTwoQubitKrausMap", False)


def mixNonTPMultiQubitKrausMap(qureg: Qureg, targets, ops) -> None:
    """Kraus map on many targets WITHOUT completeness validation (QuEST.h:271)."""
    _mix_kraus(qureg, tuple(targets), ops, "mixNonTPMultiQubitKrausMap", False)
