"""Plan/executable cache: structure fingerprints + a bounded telemetered LRU.

The reference compiles nothing, so it has no compile-cost cliff to
amortise; this build's ``Circuit`` executables are whole XLA programs whose
trace/fuse/Mosaic-compile cost at scale dwarfs a single execution. Three
layers keep that cost off the serving hot path:

1. :func:`structure_fingerprint` -- a content hash of a tape's STRUCTURE
   (gate names, targets/controls, value-slot kinds, baked operand bytes --
   never the lifted values), so "same ansatz, different angles" keys to the
   same executable.
2. :class:`LRUCache` -- a bounded, thread-safe, in-memory LRU all compiled
   replays route through (the per-``Circuit`` caches of earlier rounds grew
   without limit per (mode, mesh) key), with uniform
   ``plan_cache_{hit,miss,evict}_total{cache=...}`` counters and a
   ``plan_cache_size`` gauge.
3. :func:`enable_persistent_cache` -- wiring for JAX's persistent
   compilation cache (``QUEST_COMPILE_CACHE`` env or explicit path), so the
   cold-start Mosaic/XLA compile survives process restarts; an evicted or
   restarted executable re-traces but re-loads its binaries from disk.

Capacity defaults to ``QUEST_PLAN_CACHE_SIZE`` (128). Cache keys hold no
device buffers -- entries are host callables closing over jitted functions,
so eviction frees the jit cache via the executable's refcount.
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict

import numpy as np

from .. import telemetry
from ..resilience import sync as _sync

__all__ = ["LRUCache", "executables", "structure_fingerprint",
           "enable_persistent_cache"]


class LRUCache:
    """Bounded thread-safe LRU with flight-recorder counters.

    ``get_or_create(key, factory)`` is the one entry point the executable
    paths use: a hit refreshes recency and counts
    ``plan_cache_hit_total{cache=name}``; a miss runs ``factory()`` under
    the lock (factories here build cheap host wrappers -- compilation
    happens lazily at first call), stores, counts a miss, and evicts
    least-recently-used entries past ``capacity`` (counted per eviction).
    """

    def __init__(self, capacity: int = 128, name: str = "exec"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.name = name
        # re-entrant: a factory may itself route nested executables through
        # the same cache (compiled_blocks builds its per-block replays)
        self._lock = _sync.RLock("engine.cache")
        self._od: OrderedDict = OrderedDict()

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._od

    def peek(self, key, default=None):
        """Non-mutating probe: no recency refresh, no hit/miss counters,
        no eviction-order side effects. The pool's ahead-of-demand
        precompiler (round 18) uses this to classify a fingerprint as
        already-warm without promoting it over entries live traffic is
        actually using."""
        with self._lock:
            return self._od.get(key, default)

    def get(self, key, default=None):
        """Telemetered lookup (hit/miss counted, recency refreshed)."""
        with self._lock:
            if key in self._od:
                self._od.move_to_end(key)
                telemetry.inc("plan_cache_hit_total", cache=self.name)
                return self._od[key]
        telemetry.inc("plan_cache_miss_total", cache=self.name)
        return default

    def put(self, key, value) -> None:
        with self._lock:
            self._od[key] = value
            self._od.move_to_end(key)
            self._evict_locked()
        telemetry.set_gauge("plan_cache_size", len(self), cache=self.name)

    def get_or_create(self, key, factory):
        with self._lock:
            if key in self._od:
                self._od.move_to_end(key)
                telemetry.inc("plan_cache_hit_total", cache=self.name)
                return self._od[key]
            telemetry.inc("plan_cache_miss_total", cache=self.name)
            value = factory()
            self._od[key] = value
            self._evict_locked()
        telemetry.set_gauge("plan_cache_size", len(self), cache=self.name)
        return value

    def _evict_locked(self) -> None:
        while len(self._od) > self.capacity:
            self._od.popitem(last=False)
            telemetry.inc("plan_cache_evict_total", cache=self.name)

    def clear(self) -> None:
        with self._lock:
            self._od.clear()
        telemetry.set_gauge("plan_cache_size", 0, cache=self.name)

    def keys(self) -> list:
        with self._lock:
            return list(self._od)


#: process-global executable cache every compiled Circuit replay routes
#: through (Circuit.compiled / compiled_blocks / parameterized and the
#: Engine's batch executables); bounded so a long-lived server submitting
#: many circuit structures cannot grow it without limit
_EXECUTABLES = LRUCache(
    int(os.environ.get("QUEST_PLAN_CACHE_SIZE", "128")), name="executable")


def executables() -> LRUCache:
    """The process-global compiled-replay LRU."""
    return _EXECUTABLES


# ---------------------------------------------------------------------------
# structure fingerprint
# ---------------------------------------------------------------------------

def _canon(x):
    """Canonical hashable form of one tape operand: value slots collapse to
    their kind, baked operands hash by content, unknown objects by identity
    (unique -- never wrongly shared)."""
    import dataclasses

    from .params import Param, _SlotRef

    if isinstance(x, _SlotRef):
        return ("slot",)
    if isinstance(x, Param):  # un-lifted tape: still a value slot
        return ("slot",)
    if x is None or isinstance(x, (str, bytes)):
        return x
    if isinstance(x, bool) or isinstance(x, (int, np.integer)):
        return ("i", int(x))
    if isinstance(x, (float, np.floating)):
        return ("f", repr(float(x)))
    if isinstance(x, (complex, np.complexfloating)):
        return ("c", repr(complex(x)))
    if isinstance(x, np.ndarray):
        a = np.ascontiguousarray(x)
        return ("a", a.shape, a.dtype.str,
                hashlib.sha1(a.tobytes()).hexdigest())
    if type(x).__name__ == "HashableMatrix":  # pallas op payloads
        return ("hm",) + _canon(np.asarray(x.arr))[1:]
    if isinstance(x, (tuple, list)):
        return ("t", tuple(_canon(e) for e in x))
    if callable(x):
        return ("fn", getattr(x, "__module__", ""),
                getattr(x, "__qualname__", repr(x)))
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        return ("dc", type(x).__name__,
                tuple(_canon(getattr(x, f.name))
                      for f in dataclasses.fields(x)))
    # opaque object: identity-keyed so distinct operands never collide (the
    # same tape re-fingerprinting stays stable; sharing is simply forgone)
    return ("obj", type(x).__name__, id(x))


def structure_fingerprint(tape, num_qubits: int, is_density: bool,
                          extra=()) -> str:
    """Content hash of a tape's structure. Lifted value slots (angles,
    Complex scalars -- see :mod:`.params`) contribute only their existence,
    so two tapes differing in those values collide (by design: they share
    one executable); anything else differing -- gate names, targets,
    controls, baked matrices, channel probabilities -- changes the hash."""
    from .params import lift_tape

    lifted = lift_tape(tuple(tape))
    tokens = [("hdr", int(num_qubits), bool(is_density), _canon(tuple(extra)))]
    for fn, args, kwargs in lifted.entries:
        tokens.append((_canon(fn), _canon(args),
                       tuple(sorted((k, _canon(v))
                             for k, v in kwargs.items()))))
    return hashlib.sha256(repr(tokens).encode()).hexdigest()


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------

def enable_persistent_cache(path: str | None = None,
                            min_compile_secs: float = 0.5) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (default: the
    ``QUEST_COMPILE_CACHE`` env var; no-op returning None when neither is
    set). Compiled XLA/Mosaic binaries then survive process restarts: a
    cold Engine still traces, but re-loads its executables from disk
    instead of recompiling -- the cross-process leg of the plan/executable
    cache (the in-memory LRU covers the in-process leg)."""
    import jax

    path = path or os.environ.get("QUEST_COMPILE_CACHE")
    if not path:
        return None
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    telemetry.event("engine.persistent_cache", path=path)
    return path
