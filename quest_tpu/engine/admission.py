"""Per-tenant admission control for the replica pool.

The reference has no notion of tenancy: one caller owns the whole
simulator. A pool serving many users (ROADMAP item 1) must decide, per
request, whether to accept work BEFORE it consumes a replica's queue --
otherwise one chatty tenant starves everyone behind the shared batchers.
This module is that front door, layered on the existing backpressure
vocabulary so callers need no new error handling:

- :class:`TokenBucket` -- the classic rate limiter (``rate`` tokens/sec,
  ``burst`` capacity, refill on read) with a twist that makes priority
  non-starvation STRUCTURAL rather than probabilistic: the bottom
  ``reserve_frac`` of the bucket is reserved for ``high``-priority
  requests. A ``normal`` take must leave the reserve intact, so no volume
  of normal traffic can drain the bucket below what the next high request
  needs -- high requests are never starved by construction (the property
  tests/test_pool.py proves by exhausting a bucket with normal traffic
  and then admitting a high request).
- :class:`AdmissionController` -- one bucket per tenant (created lazily
  from a default QPS or an explicit per-tenant ``quotas`` map), the
  ``admission_{admitted,rejected,queued}_total{tenant,priority}``
  counters, and the typed rejection:
  :class:`~quest_tpu.resilience.QuESTBackpressureError` with
  ``reason="quota"`` (also counted under the engine's existing
  ``engine_backpressure_total{reason=quota}`` series so fleet dashboards
  aggregate one backpressure family).

The default quota comes from ``QUEST_TENANT_QPS`` (integer requests/sec
per tenant; 0 or unset = unlimited), parsed through
:func:`~quest_tpu.analysis.diagnostics.parse_env_int` with the QT307
warn-once diagnostic on malformed values. Time is injectable (``clock``)
so quota tests run on a fake clock instead of sleeping.
"""

from __future__ import annotations

import time

from .. import telemetry
from ..resilience import sync as _sync
from ..resilience.errors import QuESTBackpressureError

__all__ = ["PRIORITIES", "TokenBucket", "AdmissionController"]

#: admission priority classes, most urgent first
PRIORITIES = ("high", "normal")

#: QT307 warn-once tracking for QUEST_TENANT_QPS (one entry per distinct
#: malformed raw value -- the knob warns per process, not per submit)
_QPS_WARNED: set = set()


def _env_tenant_qps() -> int:
    from ..analysis.diagnostics import parse_env_int
    return parse_env_int("QUEST_TENANT_QPS", 0, minimum=0, code="QT307",
                         warned=_QPS_WARNED, noun="tenant QPS quota")


class TokenBucket:
    """Thread-safe token bucket with a high-priority reserve band.

    ``rate`` tokens accrue per second up to ``burst`` capacity (default:
    ``max(rate, 1)``). :meth:`take` refills from the injectable ``clock``
    and then admits ``n`` tokens' worth of work: ``high`` priority needs
    ``n`` tokens available; ``normal`` priority must ALSO leave
    ``reserve_frac * burst`` tokens behind for future high requests.
    The bucket starts full.
    """

    def __init__(self, rate: float, burst: float | None = None, *,
                 reserve_frac: float = 0.25, clock=time.monotonic):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        if not 0.0 <= reserve_frac < 1.0:
            raise ValueError(
                f"reserve_frac must be in [0, 1), got {reserve_frac}")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(rate, 1.0)
        if self.burst < 1.0:
            raise ValueError(f"burst must be >= 1, got {self.burst}")
        #: tokens a ``normal`` take must leave behind (the high reserve)
        self.reserve = reserve_frac * self.burst
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = _sync.Lock("admission.bucket")

    def _refill_locked(self) -> None:
        now = self._clock()
        if now > self._last:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
        self._last = now

    def tokens(self) -> float:
        """Current token count (refilled first; introspection/tests)."""
        with self._lock:
            self._refill_locked()
            return self._tokens

    def take(self, n: int = 1, *, priority: str = "normal") -> bool:
        """Admit ``n`` requests' worth of tokens, or return False without
        taking anything (all-or-nothing, like Engine.submit_many)."""
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        with self._lock:
            self._refill_locked()
            floor = 0.0 if priority == "high" else self.reserve
            if self._tokens - n < floor - 1e-9:
                return False
            self._tokens -= n
            return True


class AdmissionController:
    """Per-tenant quota enforcement in front of an :class:`EnginePool`.

    ``default_qps`` (None = read ``QUEST_TENANT_QPS``; 0 = unlimited)
    seeds a lazily-created :class:`TokenBucket` per tenant; ``quotas``
    maps specific tenants to their own QPS (0 disables the quota for
    that tenant). :meth:`admit` either counts the admission or raises
    the typed quota rejection -- it never blocks.
    """

    def __init__(self, default_qps: int | None = None, *,
                 burst: float | None = None, quotas: dict | None = None,
                 reserve_frac: float = 0.25, clock=time.monotonic):
        if default_qps is None:
            default_qps = _env_tenant_qps()
        if default_qps < 0:
            raise ValueError(
                f"default_qps must be >= 0, got {default_qps}")
        self.default_qps = int(default_qps)
        self.burst = burst
        self.reserve_frac = float(reserve_frac)
        self.quotas = dict(quotas or {})
        self._clock = clock
        self._buckets: dict[str, TokenBucket | None] = {}
        self._lock = _sync.Lock("admission.controller")

    def bucket(self, tenant: str) -> TokenBucket | None:
        """The tenant's bucket (created on first use); None = unlimited."""
        with self._lock:
            if tenant not in self._buckets:
                qps = self.quotas.get(tenant, self.default_qps)
                self._buckets[tenant] = None if not qps else TokenBucket(
                    qps, self.burst, reserve_frac=self.reserve_frac,
                    clock=self._clock)
            return self._buckets[tenant]

    def admit(self, tenant: str, priority: str = "normal",
              n: int = 1) -> None:
        """Admit ``n`` requests for ``tenant`` or raise
        :class:`QuESTBackpressureError` with ``reason="quota"``."""
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        b = self.bucket(tenant)
        if b is not None and not b.take(n, priority=priority):
            telemetry.inc("admission_rejected_total", n, tenant=tenant,
                          priority=priority)
            # the engine-level series too, so one dashboard family shows
            # every shed request regardless of which layer shed it
            telemetry.inc("engine_backpressure_total", reason="quota")
            if telemetry.trace_on():
                # a quota shed shows as an instant on any trace the
                # calling thread is already working for (nested serving)
                telemetry.trace_event_current(
                    "admission.reject", tenant=tenant, priority=priority,
                    n=n)
            raise QuESTBackpressureError(
                f"tenant {tenant!r} is over its admission quota "
                f"({b.rate:g} req/s, burst {b.burst:g}): rejecting {n} "
                f"{priority}-priority request(s)", "EnginePool.submit",
                reason="quota")
        telemetry.inc("admission_admitted_total", n, tenant=tenant,
                      priority=priority)

    def note_queued(self, tenant: str, priority: str, n: int = 1) -> None:
        """Count requests the pool parked (admitted, but no replica could
        take them yet -- e.g. mid-failover)."""
        telemetry.inc("admission_queued_total", n, tenant=tenant,
                      priority=priority)
