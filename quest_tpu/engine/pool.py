"""Replica-pool serving: health-aware routing, quarantine drain/failover,
hedged dispatch, and warm replacement spawning.

One :class:`~quest_tpu.engine.engine.Engine` serves one circuit structure
from one batcher thread; the fleet shape ROADMAP item 1 asks for is many
replicas serving heterogeneous multi-tenant traffic. :class:`EnginePool`
is that front-end. It owns N replicas (each a lazily-populated map of
structure fingerprint -> ``Engine``) and routes every submit by three
signals, in order:

1. **health** -- the replica's worst engine state plus a pool-level
   override (``healthy`` routes before ``degraded``; ``quarantined``
   never routes),
2. **structure affinity** -- same-fingerprint requests prefer a replica
   that already holds that executable, so heterogeneous traffic does not
   serialize behind one batcher (and a cold replica is not warmed by
   accident on the hot path),
3. **load** -- least outstanding requests breaks ties.

Robustness behaviors (ISSUE 13):

- **Failover + quarantine drain**: when a replica quarantines (sentinel
  breach, hang, or an injected ``pool.replica`` fault), the pool pulls it
  from rotation, closes its engines with ``drain=False`` -- every queued
  future resolves with a typed
  :class:`~quest_tpu.resilience.QuESTCancelledError` -- and the done
  callbacks re-dispatch those requests to healthy peers. No caller future
  is ever dropped, and the recovered results are bit-identical: the same
  fingerprint fetches the same executable, and the PR 4 vmap contract
  makes every batch lane identical. Counted
  ``pool_failovers_total{reason}``. A replacement replica is then spawned
  in the background and **warmed from the fingerprint manifest**
  (:meth:`EnginePool.warm_from_manifest`; with ``QUEST_COMPILE_CACHE``
  set the compile itself reloads from disk) BEFORE it joins rotation --
  its first real request performs zero retraces
  (``engine_trace_total{kind=param_replay}`` stays flat).
- **Admission control**: every submit passes the per-tenant token-bucket
  front door first (:mod:`.admission` -- ``QuESTBackpressureError`` with
  ``reason="quota"``, high-priority reserve band, the
  ``admission_*_total`` counters). Admitted requests that momentarily
  have NO routable replica (e.g. mid-failover) park in priority-ordered
  pending queues (high drains first) instead of being rejected.
- **Ahead-of-demand compilation** (round 18): the pool counts requests
  per structure fingerprint; :meth:`EnginePool.precompile` ranks the
  manifest by that frequency and warms the most popular executables OFF
  the request path (``engine_precompile_total{outcome=warmed|cached|
  error}`` -- the already-warm probe is a non-mutating LRU ``peek``, so
  ranking never perturbs eviction order). ``precompile_ms`` > 0 runs it
  periodically on a background ``quest-pool-precompile`` thread -- the
  JAX persistent-compilation-cache discipline (PAPERS.md) applied to the
  in-memory plan cache: never compile on the request path.
- **Hedged dispatch** (``hedge_ms`` > 0): a request outstanding on a
  ``degraded`` replica past the hedge deadline is re-issued to a healthy
  peer through :func:`~quest_tpu.resilience.retry.call_with_retry`
  (site ``pool.hedge``, retryable on backpressure); first completion
  wins, the loser's future is cancelled (the engines' own
  ``fut.done()`` guards make the late result a no-op). Both outcomes are
  bit-identical by the same executable-identity argument, so hedging
  never changes answers -- only tail latency.
  ``pool_hedges_total{outcome=issued|won_primary|won_hedge}``.

Env knobs (all through
:func:`~quest_tpu.analysis.diagnostics.parse_env_int`, malformed values
warn once with QT307): ``QUEST_POOL_REPLICAS`` (default 2),
``QUEST_HEDGE_MS`` (default 0 = hedging off), and ``QUEST_TENANT_QPS``
(read by :mod:`.admission`).

Telemetry: ``pool_requests_total{tenant,priority}``,
``pool_routes_total{outcome=affinity|healthy|degraded|parked}``,
``pool_failovers_total{reason}``, ``pool_quarantines_total{reason}``,
``pool_replacements_total{reason}``, ``pool_hedges_total{outcome}``, and
the ``pool_replicas`` rotation gauge, on top of everything the member
engines already emit.

Locking: the pool condition variable orders BEFORE any engine lock --
pool code may read engine health under the pool lock, but never holds an
engine lock while taking the pool lock (engine done callbacks run with
no engine lock held; ``Engine.close`` resolves cancelled futures after
releasing its lock for exactly this reason). Both locks live on the
instrumented sync layer (:mod:`quest_tpu.resilience.sync`: ``pool.cv``
orders before ``engine.cv``), so with ``QUEST_CONCHECK=1`` the ordering
contract is *verified* -- an inversion shows up as a QT601 cycle in the
lock-order graph, and a future resolved under either lock as QT602
(docs/analysis.md, the round-15 concurrency verifier).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

from .. import telemetry
from ..resilience import faultinject as _faults
from ..resilience import retry as _retry
from ..resilience import sync as _sync
from ..resilience.errors import (QuESTBackpressureError, QuESTCancelledError,
                                 QuESTHangError, QuESTIntegrityError,
                                 QuESTRetryError)
from .admission import PRIORITIES, AdmissionController
from .engine import Engine

__all__ = ["EnginePool"]

_RANK = {"healthy": 0, "degraded": 1, "quarantined": 2}
_STATES = ("healthy", "degraded", "quarantined")

#: replica-failure exception -> ``pool_failovers_total{reason}`` label;
#: anything NOT here (timeouts, poisoned requests, value errors) is a
#: REQUEST failure and propagates to the caller instead of failing over
_FAILOVER_REASONS = (
    (QuESTCancelledError, "drain"),
    (QuESTHangError, "hang"),
    (QuESTIntegrityError, "integrity"),
    (QuESTBackpressureError, "backpressure"),
)

#: QT307 warn-once tracking, one set per knob so the same malformed raw
#: value still warns on each distinct knob
_REPLICAS_WARNED: set = set()
_HEDGE_WARNED: set = set()


def _env_replicas() -> int:
    from ..analysis.diagnostics import parse_env_int
    return parse_env_int("QUEST_POOL_REPLICAS", 2, minimum=1, code="QT307",
                         warned=_REPLICAS_WARNED, noun="replica count")


def _env_hedge_ms() -> int:
    from ..analysis.diagnostics import parse_env_int
    return parse_env_int("QUEST_HEDGE_MS", 0, minimum=0, code="QT307",
                         warned=_HEDGE_WARNED, noun="hedge deadline (ms)")


def _failover_reason(exc) -> str | None:
    for cls, reason in _FAILOVER_REASONS:
        if isinstance(exc, cls):
            return reason
    return None


class _PoolRequest:
    """One pool-level request: the caller's future plus everything needed
    to re-dispatch it (circuit, params, tenant) and the bookkeeping the
    failover/hedge machinery reads (attempt count, replicas already
    failed on, in-flight engine futures)."""

    __slots__ = ("circuit", "fingerprint", "params", "tenant", "priority",
                 "fut", "deadline", "t0", "attempts", "failed", "inner",
                 "hedged", "dispatched_at", "last_exc", "settled",
                 "trace", "last_span", "mark")

    def __init__(self, circuit, fingerprint, params, tenant, priority,
                 deadline):
        self.circuit = circuit
        self.fingerprint = fingerprint
        self.params = params
        self.tenant = tenant
        self.priority = priority
        self.fut: Future = Future()
        self.deadline = deadline
        self.t0 = time.monotonic()
        self.attempts = 0
        self.failed: set = set()          # replica ids this request failed on
        self.inner: list = []    # (replica, engine_future, is_hedge, span)
        self.hedged = False
        self.dispatched_at: float | None = None
        self.last_exc = None
        self.settled = False
        self.trace = None                 # pool-minted TraceContext root
        self.last_span = None             # most recent attempt/hedge span
        self.mark = 0.0  # perf_counter of the last phase-attributed point

    def remaining(self) -> float | None:
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.monotonic())


class _Replica:
    """One pool member: a map of fingerprint -> Engine, a pool-level state
    override (quarantine sticks even after its engines are closed), and
    the outstanding-request set routing and hedging read."""

    __slots__ = ("id", "engines", "state", "in_rotation", "outstanding",
                 "build_lock")

    def __init__(self, rid: int):
        self.id = rid
        self.engines: dict = {}
        self.state = "healthy"
        self.in_rotation = False
        self.outstanding: set = set()
        self.build_lock = _sync.Lock("pool.build")

    def health(self) -> str:
        """Worst of the pool-level state and every member engine's
        health (the routing signal)."""
        h = _RANK[self.state]
        for eng in self.engines.values():
            h = max(h, _RANK[eng.health()])
        return _STATES[h]


class EnginePool:
    """Health-aware replica pool over :class:`Engine` (module docstring).

    ``env`` and the engine knobs (``max_batch``/``max_delay_ms``/
    ``queue_max``/``precision_code``/``donate``) are shared by every
    engine the pool builds. ``replicas`` defaults to
    ``QUEST_POOL_REPLICAS`` (2), ``hedge_ms`` to ``QUEST_HEDGE_MS``
    (0 = off); ``admission`` accepts a pre-built
    :class:`~quest_tpu.engine.admission.AdmissionController` (otherwise
    one is created from ``tenant_qps`` / ``QUEST_TENANT_QPS``).
    ``spawn_replacements=False`` disables automatic replacement of
    quarantined replicas (tests that count replicas exactly use it).
    """

    def __init__(self, env=None, *, replicas: int | None = None,
                 max_batch: int = 8, max_delay_ms: float = 2.0,
                 queue_max: int | None = None, hedge_ms: float | None = None,
                 tenant_qps: int | None = None, admission=None,
                 precision_code: int | None = None, donate: bool = True,
                 spawn_replacements: bool = True,
                 precompile_ms: float = 0.0, finalize=None):
        if replicas is None:
            replicas = _env_replicas()
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if hedge_ms is None:
            hedge_ms = _env_hedge_ms()
        if hedge_ms < 0:
            raise ValueError(f"hedge_ms must be >= 0, got {hedge_ms}")
        if precompile_ms < 0:
            raise ValueError(
                f"precompile_ms must be >= 0, got {precompile_ms}")
        self._env = env
        # finalize (round 19): forwarded to every engine the pool builds --
        # futures resolve to finalize(final_amps) (e.g. on-device shot
        # tables) instead of amplitude arrays
        self._engine_kw = dict(max_batch=max_batch,
                               max_delay_ms=max_delay_ms,
                               queue_max=queue_max,
                               precision_code=precision_code, donate=donate,
                               finalize=finalize)
        self.hedge_s = float(hedge_ms) / 1e3
        self.admission = (admission if admission is not None
                          else AdmissionController(tenant_qps))
        self._spawn_replacements = bool(spawn_replacements)
        self._cv = _sync.Condition("pool.cv")
        self._replicas: list[_Replica] = []
        self._manifest: dict = {}         # fingerprint -> circuit
        # round 20: per-fingerprint finalize overrides -- gradient traffic
        # rides the ordinary routing/failover machinery under a derived
        # "grad:<ham>:<fp>" fingerprint whose engines are built with the
        # adjoint grad_reduce finalize instead of the pool-wide one
        self._finalize_for: dict = {}
        self._freq: dict = {}             # fingerprint -> request count
        self._pending = {p: deque() for p in PRIORITIES}
        self._next_rid = 0
        self._closed = False
        self._max_attempts = max(3, int(replicas) + 2)
        self._workers: list[threading.Thread] = []
        for _ in range(int(replicas)):
            rep = _Replica(self._next_rid)
            self._next_rid += 1
            rep.in_rotation = True
            self._replicas.append(rep)
        telemetry.set_gauge("pool_replicas", int(replicas))
        self._hedge_thread = None
        if self.hedge_s > 0:
            self._hedge_thread = threading.Thread(
                target=self._hedge_loop, name="quest-pool-hedge",
                daemon=True)
            self._hedge_thread.start()
        self.precompile_s = float(precompile_ms) / 1e3
        self._precompile_thread = None
        if self.precompile_s > 0:
            self._precompile_thread = threading.Thread(
                target=self._precompile_loop, name="quest-pool-precompile",
                daemon=True)
            self._precompile_thread.start()
        telemetry.event("pool.start", replicas=int(replicas),
                        hedge_ms=float(hedge_ms),
                        precompile_ms=float(precompile_ms))

    # -- submission ---------------------------------------------------------

    def submit(self, circuit, params: dict | None = None, *,
               tenant: str = "default", priority: str = "normal",
               timeout: float | None = None) -> Future:
        """Admit + route one request; returns a Future resolving to the
        final planar amplitude array no matter which replica (or how many
        failovers) served it."""
        return self.submit_many(circuit, [params], tenant=tenant,
                                priority=priority, timeout=timeout)[0]

    def submit_many(self, circuit, params_list, *, tenant: str = "default",
                    priority: str = "normal",
                    timeout: float | None = None,
                    _fingerprint: str | None = None) -> list:
        """Admit ``len(params_list)`` requests atomically (the quota sees
        one take), then route each independently. ``_fingerprint``
        (internal) overrides the routing key -- submit_grad derives one
        per (structure, observable) so gradient engines never collide
        with plain replay engines of the same ansatz."""
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        if not params_list:
            return []
        if timeout is not None and timeout < 0:
            raise ValueError(f"timeout must be >= 0, got {timeout}")
        with self._cv:
            if self._closed:
                raise RuntimeError("EnginePool is closed")
        # tracing (round 17): one boolean read when off; the pool mints
        # the request's root trace (backdated to admission entry) and its
        # settle owns finishing it -- the engines the attempts land on
        # adopt the attempt span and only close their own children
        tracing = telemetry.trace_on()
        t_adm = time.perf_counter() if tracing else 0.0
        try:
            self.admission.admit(tenant, priority, len(params_list))
        except QuESTBackpressureError as e:
            if tracing:
                # errored requests are ALWAYS captured, and an admission
                # shed errors before any request object exists: mint a
                # one-span error trace for the batch
                ctx = telemetry.start_trace(
                    "request", t0=t_adm, kind="pool", tenant=tenant,
                    priority=priority)
                if ctx is not None:
                    ctx.record_span("pool.admission", t_adm,
                                    time.perf_counter() - t_adm,
                                    status="error")
                    telemetry.finish_trace(ctx, error=type(e).__name__)
            raise
        t_admitted = time.perf_counter() if tracing else 0.0
        telemetry.inc("pool_requests_total", len(params_list),
                      tenant=tenant, priority=priority)
        fp = _fingerprint if _fingerprint is not None \
            else circuit.fingerprint()
        with self._cv:
            self._manifest.setdefault(fp, circuit)
            # per-structure frequency telemetry: the precompiler's ranking
            # signal (round 18)
            self._freq[fp] = self._freq.get(fp, 0) + len(params_list)
        deadline = None if timeout is None else time.monotonic() + timeout
        futs = []
        for params in params_list:
            req = _PoolRequest(circuit, fp, params, tenant, priority,
                               deadline)
            if tracing:
                req.trace = telemetry.start_trace(
                    "request", t0=t_adm, kind="pool", tenant=tenant,
                    priority=priority)
                req.mark = t_adm
                if req.trace is not None:
                    req.trace.record_span("pool.admission", t_adm,
                                          t_admitted - t_adm)
            futs.append(req.fut)
            self._route(req)
        return futs

    def run(self, circuit, params: dict | None = None, **kw):
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(circuit, params, **kw).result()

    # -- gradients (round 20) -----------------------------------------------

    def submit_grad(self, circuit, params: dict | None = None, *,
                    hamiltonian, tenant: str = "default",
                    priority: str = "normal",
                    timeout: float | None = None) -> Future:
        """Route one variational optimizer step fleet-wide: a Future
        resolving to ``(value, grads)`` from the adjoint gradient engine
        for ``circuit`` against ``hamiltonian`` (a PauliHamil or
        ``(pauli_codes, term_coeffs)``)."""
        return self.submit_grad_many(circuit, [params],
                                     hamiltonian=hamiltonian, tenant=tenant,
                                     priority=priority, timeout=timeout)[0]

    def submit_grad_many(self, circuit, params_list, *, hamiltonian,
                         tenant: str = "default", priority: str = "normal",
                         timeout: float | None = None) -> list:
        """Batch form of :meth:`submit_grad`: gradient requests share the
        ordinary admission/affinity/failover machinery under a derived
        fingerprint, coalescing into the replica's vmapped
        ``route=grad_request`` program."""
        import hashlib

        from ..gradients import grad_reduce
        from ..precision import real_dtype

        red = grad_reduce(
            circuit, hamiltonian,
            dtype=real_dtype(self._engine_kw.get("precision_code")))
        ham_key = hashlib.sha1(
            repr(red.hamiltonian).encode()).hexdigest()[:12]
        gfp = f"grad:{ham_key}:{circuit.fingerprint()}"
        with self._cv:
            self._finalize_for[gfp] = red
        telemetry.inc("grad_requests_total", len(params_list))
        telemetry.inc("grad_slots_total",
                      float(red.num_slots * len(params_list)))
        inner = self.submit_many(circuit, params_list, tenant=tenant,
                                 priority=priority, timeout=timeout,
                                 _fingerprint=gfp)
        outs = []
        for f in inner:
            fut: Future = Future()

            def _chain(src, _fut=fut):
                exc = src.exception()
                if exc is not None:
                    _sync.resolve_future(_fut, exception=exc,
                                         site="pool.submit_grad")
                else:
                    out = src.result()
                    _sync.resolve_future(
                        _fut, result=(out["value"], out["grads"]),
                        site="pool.submit_grad")

            f.add_done_callback(_chain)
            outs.append(fut)
        return outs

    # -- routing ------------------------------------------------------------

    def _select_locked(self, fingerprint, exclude=frozenset(),
                       allow_degraded: bool = True):
        """Routing policy (pool lock held): healthiest state first, then
        structure affinity, then least-loaded; quarantined never routes."""
        best = best_key = None
        for rep in self._replicas:
            if not rep.in_rotation or rep.id in exclude:
                continue
            h = rep.health()
            if h == "quarantined" or (h == "degraded"
                                      and not allow_degraded):
                continue
            # structure-count before id: a cold fingerprint lands on the
            # replica serving the fewest structures, so heterogeneous
            # traffic spreads instead of serializing behind one batcher
            key = (_RANK[h], 0 if fingerprint in rep.engines else 1,
                   len(rep.outstanding), len(rep.engines), rep.id)
            if best_key is None or key < best_key:
                best, best_key = rep, key
        return best

    def _route(self, req: _PoolRequest) -> None:
        parked = cancel = False
        rep = None
        with self._cv:
            if self._closed:
                cancel = True
            else:
                rep = self._select_locked(req.fingerprint,
                                          exclude=req.failed)
                if rep is None and req.failed:
                    # every non-failed replica is unroutable; a replica
                    # this request once failed on may have healed -- a
                    # stale exclusion must not park the request forever
                    rep = self._select_locked(req.fingerprint)
                if rep is None:
                    telemetry.inc("pool_routes_total", outcome="parked")
                    self._pending[req.priority].append(req)
                    parked = True
                else:
                    telemetry.inc(
                        "pool_routes_total",
                        outcome=("affinity"
                                 if req.fingerprint in rep.engines
                                 else rep.health()))
        if cancel:
            self._settle(req, exc=QuESTCancelledError(
                "request dropped: EnginePool is closed",
                "EnginePool.submit"))
            return
        if parked:
            if req.trace is not None:
                req.trace.event("parked", priority=req.priority)
            self.admission.note_queued(req.tenant, req.priority)
            return
        self._dispatch_attempt(req, rep)

    def _attempt_span(self, req: _PoolRequest, rep: _Replica, name: str,
                      link_kind: str):
        """Open one attempt span (time since the last attributed point
        lands in ``queue_wait``) and link it to the previous attempt --
        the failover/hedge causality edge the waterfall renders."""
        now = time.perf_counter()
        if now > req.mark:
            req.trace.phase("queue_wait", req.mark, now - req.mark)
        req.mark = now
        sp = req.trace.child(name, replica=rep.id, attempt=req.attempts)
        if req.last_span is not None:
            sp.link(req.last_span, kind=link_kind)
        req.last_span = sp
        return sp

    def _attempt_failed(self, req: _PoolRequest, sp) -> None:
        """Close a failed attempt span; the re-route that follows charges
        its latency to ``queue_wait`` from here."""
        if sp is not None:
            sp.end(status="error")
            req.mark = time.perf_counter()

    def _dispatch_attempt(self, req: _PoolRequest, rep: _Replica) -> None:
        req.attempts += 1
        if req.attempts > self._max_attempts:
            self._settle(req, exc=req.last_exc or QuESTRetryError(
                f"request failed over {req.attempts - 1} time(s) without "
                f"a replica completing it", "EnginePool.submit"))
            return
        sp = None if req.trace is None else \
            self._attempt_span(req, rep, "pool.attempt", "failover")
        if _faults.enabled():
            # the injectable replica-death point: one visit per routed
            # dispatch attempt, so a plan's nth visit replays identically
            kind = _faults.fire("pool.replica")
            if kind is not None:
                req.failed.add(rep.id)
                req.last_exc = QuESTCancelledError(
                    f"injected {kind} fault at site 'pool.replica' "
                    f"(replica {rep.id})", "EnginePool._dispatch")
                self._attempt_failed(req, sp)
                self._quarantine(rep, reason=kind)
                telemetry.inc("pool_failovers_total", reason=kind)
                self._route(req)
                return
        eng = None
        try:
            eng = self._engine_for(rep, req.fingerprint, req.circuit)
            if req.trace is not None:
                # engine resolution (a miss builds + compiles) is the
                # pool-side cache_lookup phase
                now = time.perf_counter()
                req.trace.phase("cache_lookup", req.mark, now - req.mark)
                req.mark = now
            f = self._adopted_submit(req, sp, eng)
            if req.trace is not None:
                # the submit hop (param bind + engine-lock wait, which
                # can block behind the batcher) is queueing too; the few
                # microseconds of overlap with the engine-side
                # queue_wait window are inside the 10% tiling tolerance
                now = time.perf_counter()
                req.trace.phase("queue_wait", req.mark, now - req.mark)
                req.mark = now
        except QuESTBackpressureError as e:
            req.failed.add(rep.id)
            req.last_exc = e
            self._attempt_failed(req, sp)
            if eng is not None and eng.health() == "quarantined":
                self._quarantine(rep, reason="quarantined")
            telemetry.inc("pool_failovers_total", reason="backpressure")
            self._route(req)
            return
        except RuntimeError as e:
            if eng is not None and not eng.is_open():
                # the quarantine drain closed this engine between routing
                # and submit (the interleaving explorer's
                # pool_failover_race window): the drain's zero-lost-futures
                # contract covers it -- fail over, don't settle
                req.failed.add(rep.id)
                req.last_exc = QuESTCancelledError(
                    f"replica {rep.id} closed during dispatch",
                    "EnginePool._dispatch")
                self._attempt_failed(req, sp)
                telemetry.inc("pool_failovers_total", reason="closed")
                self._route(req)
                return
            self._attempt_failed(req, sp)
            self._settle(req, exc=e)
            return
        except BaseException as e:
            self._attempt_failed(req, sp)
            self._settle(req, exc=e)
            return
        with self._cv:
            req.dispatched_at = time.monotonic()
            req.inner.append((rep, f, False, sp))
            rep.outstanding.add(req)
        f.add_done_callback(
            lambda fut, req=req, rep=rep: self._on_done(req, rep, fut,
                                                        hedge=False))

    def _adopted_submit(self, req: _PoolRequest, sp, eng):
        """``Engine.submit`` with this request's attempt span bound to the
        submitting thread, so the engine adopts it as the parent of its
        ``engine.request`` child (ONE waterfall across the hop). The
        previous binding is restored: a failover re-dispatch runs on an
        engine batcher thread that is still working for its own batch."""
        if req.trace is None:
            if not telemetry.trace_on():
                return eng.submit(req.params, timeout=req.remaining())
            # rate-sampled out: shield the engine from adopting whatever
            # trace the dispatching thread happens to be bound to
            prev = telemetry.current_traces()
            telemetry.set_current_trace(None)
            try:
                return eng.submit(req.params, timeout=req.remaining())
            finally:
                telemetry.set_current_trace(prev or None)
        prev = telemetry.current_traces()
        telemetry.set_current_trace(sp)
        try:
            return eng.submit(req.params, timeout=req.remaining())
        finally:
            telemetry.set_current_trace(prev or None)

    def _settle(self, req: _PoolRequest, result=None, exc=None) -> bool:
        """Resolve the caller's future exactly once (concurrent engine
        completions race through here; the first wins)."""
        with self._cv:
            if req.settled:
                return False
            req.settled = True
            self._cv.notify_all()
        if req.trace is not None:
            # the pool minted this root, so the pool finishes it -- BEFORE
            # resolving, so a woken caller observes a complete trace. The
            # window since the last attributed point (the engine handoff
            # in _on_done) is the pool-side resolve; a request that never
            # reached an engine only ever waited
            now = time.perf_counter()
            if now > req.mark:
                req.trace.phase(
                    "resolve" if req.dispatched_at is not None
                    else "queue_wait", req.mark, now - req.mark)
                req.mark = now
            telemetry.finish_trace(
                req.trace,
                error=None if exc is None else type(exc).__name__)
        # resolution happens OUTSIDE the pool lock (the settled flag above
        # is the once-guard); resolve_future re-verifies that under
        # QUEST_CONCHECK=1 (QT602 on any instrumented lock still held)
        _sync.resolve_future(req.fut, result=result, exception=exc,
                             site="pool.settle")
        telemetry.observe("pool_request_latency_seconds",
                          time.monotonic() - req.t0)
        return True

    def _on_done(self, req: _PoolRequest, rep: _Replica, fut,
                 *, hedge: bool) -> None:
        if req.trace is not None:
            # engine -> pool handoff: phase attribution resumes here
            req.mark = time.perf_counter()
        with self._cv:
            mine = next((p[3] for p in req.inner if p[1] is fut), None)
            req.inner = [p for p in req.inner if p[1] is not fut]
            if not any(p[0] is rep for p in req.inner):
                rep.outstanding.discard(req)
            siblings = list(req.inner)
            settled = req.settled
            self._cv.notify_all()
        if fut.cancelled():
            if mine is not None:
                mine.end(status="cancelled")
            return  # a hedge loser we cancelled while still queued
        exc = fut.exception()
        if settled:
            # hedge loser (or late failover echo): drop silently, but the
            # waterfall marks the losing span cancelled
            if mine is not None:
                mine.end(status="cancelled")
            return
        if exc is None:
            if mine is not None:
                mine.end()
            if self._settle(req, result=fut.result()):
                if req.hedged:
                    telemetry.inc("pool_hedges_total",
                                  outcome=("won_hedge" if hedge
                                           else "won_primary"))
                for _rep2, f2, _h, sp2 in siblings:
                    f2.cancel()  # engines guard fut.done(): safe either way
                    if sp2 is not None:
                        sp2.end(status="cancelled")
            self._drain_pending()
            return
        if mine is not None:
            mine.end(status="error")
            req.last_span = mine  # the failover link target
        # a replica-level failure quarantines the replica...
        if isinstance(exc, QuESTHangError):
            self._quarantine(rep, reason="hang")
        elif isinstance(exc, QuESTIntegrityError):
            with self._cv:
                state = rep.health()
            if state == "quarantined":
                self._quarantine(rep, reason="integrity")
        if siblings:
            return  # another attempt is still in flight; let it decide
        reason = _failover_reason(exc)
        if reason is None:
            # request-level failure (timeout, poison, user error): the
            # caller gets the typed error, no failover
            self._settle(req, exc=exc)
            return
        req.failed.add(rep.id)
        req.last_exc = exc
        telemetry.inc("pool_failovers_total", reason=reason)
        telemetry.event("pool.failover", replica=rep.id, reason=reason,
                        attempts=req.attempts)
        self._route(req)
        self._drain_pending()

    def _drain_pending(self) -> None:
        """Dispatch parked requests that became routable (high first)."""
        while True:
            req = rep = None
            with self._cv:
                if self._closed:
                    return
                for prio in PRIORITIES:
                    dq = self._pending[prio]
                    if dq:
                        cand = self._select_locked(dq[0].fingerprint,
                                                   exclude=dq[0].failed) \
                            or self._select_locked(dq[0].fingerprint)
                        if cand is not None:
                            req, rep = dq.popleft(), cand
                            break
                if req is None:
                    return
            self._dispatch_attempt(req, rep)

    # -- engines ------------------------------------------------------------

    def _engine_for(self, rep: _Replica, fingerprint, circuit=None):
        with self._cv:
            eng = rep.engines.get(fingerprint)
            if circuit is None:
                circuit = self._manifest.get(fingerprint)
        if eng is not None:
            return eng
        if circuit is None:
            raise KeyError(f"no circuit recorded for fingerprint "
                           f"{fingerprint[:12]}...")
        with rep.build_lock:
            with self._cv:
                eng = rep.engines.get(fingerprint)
                override = self._finalize_for.get(fingerprint)
            if eng is not None:
                return eng
            kw = self._engine_kw
            if override is not None:
                kw = {**kw, "finalize": override}
            elif isinstance(fingerprint, str) and \
                    fingerprint.startswith("grad:"):
                # a grad manifest row without its registered observable
                # (e.g. replayed into a fresh pool) must fail loud -- a
                # plain engine under this key would serve amps where the
                # caller expects (value, grads)
                raise KeyError(
                    f"gradient fingerprint {fingerprint[:24]}... has no "
                    "registered observable; route it through submit_grad")
            eng = Engine(circuit, self._env, **kw)
            with self._cv:
                rep.engines[fingerprint] = eng
            return eng

    # -- quarantine / failover / replacement --------------------------------

    def _quarantine(self, rep: _Replica, *, reason: str) -> None:
        with self._cv:
            if rep.state == "quarantined":
                return
            rep.state = "quarantined"
            rep.in_rotation = False
            engines = list(rep.engines.values())
            spawn = self._spawn_replacements and not self._closed
            self._cv.notify_all()
        telemetry.inc("pool_quarantines_total", reason=reason)
        telemetry.set_gauge("pool_replicas", self._rotation_count())
        telemetry.event("pool.quarantine", replica=rep.id, reason=reason)
        # drain on a helper thread: _quarantine may be running ON one of
        # this replica's batcher threads (hang/integrity done callbacks),
        # and Engine.close joins the batcher
        drainer = threading.Thread(
            target=self._drain_replica, args=(engines,),
            name=f"quest-pool-drain-{rep.id}", daemon=True)
        drainer.start()
        with self._cv:
            self._workers.append(drainer)
        if spawn:
            spawner = threading.Thread(
                target=self._spawn_replacement, args=(reason,),
                name="quest-pool-respawn", daemon=True)
            spawner.start()
            with self._cv:
                self._workers.append(spawner)

    def _drain_replica(self, engines) -> None:
        """Close a quarantined replica's engines without draining: every
        queued future resolves QuESTCancelledError, whose done callbacks
        fail the requests over to healthy peers (zero dropped futures);
        in-flight batches complete and still serve their waiters."""
        for eng in engines:
            try:
                eng.close(drain=False)
            except Exception:  # pragma: no cover - close must not cascade
                pass

    def _spawn_replacement(self, reason: str) -> None:
        try:
            with self._cv:
                if self._closed:
                    return
                rep = _Replica(self._next_rid)
                self._next_rid += 1
                manifest = dict(self._manifest)
            for fp, circ in manifest.items():
                self._engine_for(rep, fp, circ).warmup()
        except Exception as e:  # pragma: no cover - respawn best-effort
            telemetry.event("pool.respawn_failed", error=type(e).__name__)
            return
        stillborn = None
        with self._cv:
            if self._closed:
                stillborn = list(rep.engines.values())
            else:
                rep.in_rotation = True
                self._replicas.append(rep)
                self._cv.notify_all()
        if stillborn is not None:
            self._drain_replica(stillborn)
            return
        telemetry.inc("pool_replacements_total", reason=reason)
        telemetry.set_gauge("pool_replicas", self._rotation_count())
        telemetry.event("pool.replacement", replica=rep.id,
                        warmed=len(manifest))
        self._drain_pending()

    def warm_from_manifest(self, manifest=None, replica=None) -> list:
        """Pre-build and :meth:`Engine.warmup` the executables for every
        fingerprint in ``manifest`` (default: every structure this pool
        has served; alternatively a ``{fingerprint: circuit}`` map or an
        iterable of circuits) on ``replica`` (an id, or None = every
        in-rotation replica). With ``QUEST_COMPILE_CACHE`` set the warmup
        compile reloads from disk, so even a fresh process serves its
        first real request with zero retraces. Returns the warmed
        fingerprints."""
        if manifest is None:
            with self._cv:
                manifest = dict(self._manifest)
        elif not isinstance(manifest, dict):
            manifest = {c.fingerprint(): c for c in manifest}
        with self._cv:
            for fp, circ in manifest.items():
                self._manifest.setdefault(fp, circ)
            if replica is None:
                reps = [r for r in self._replicas if r.in_rotation]
            elif isinstance(replica, _Replica):
                reps = [replica]
            else:
                reps = [r for r in self._replicas if r.id == replica]
                if not reps:
                    raise ValueError(f"no replica with id {replica!r}")
        for rep in reps:
            for fp, circ in manifest.items():
                self._engine_for(rep, fp, circ).warmup()
        return sorted(manifest)

    @property
    def manifest(self) -> dict:
        """Fingerprint -> circuit map of every structure served so far."""
        with self._cv:
            return dict(self._manifest)

    @property
    def frequencies(self) -> dict:
        """Fingerprint -> request count: the manifest frequency telemetry
        the ahead-of-demand precompiler ranks by."""
        with self._cv:
            return dict(self._freq)

    # -- ahead-of-demand compilation (round 18) ------------------------------

    def precompile(self, limit: int | None = None, replica=None) -> list:
        """Warm the plan cache OFF the request path: rank every structure
        fingerprint this pool has served by request frequency (descending,
        fingerprint-lexicographic tiebreak) and ensure the hottest
        ``limit`` of them (None = all) hold warm executables on
        ``replica`` (an id, or None = every in-rotation replica).

        Per (fingerprint, replica) outcome, counted
        ``engine_precompile_total{outcome}``:

        - ``cached`` -- the replica's engine exists and the process-global
          LRU still holds its batch executable (probed with the
          NON-MUTATING :meth:`~quest_tpu.engine.cache.LRUCache.peek`, so
          ranking never promotes a precompiled entry over one live
          traffic is using);
        - ``warmed`` -- a cold engine was built (or an evicted executable
          re-warmed) via :meth:`Engine.warmup`;
        - ``error`` -- the warm attempt failed; request traffic is
          unaffected (the hot path compiles lazily as before).

        Returns the fingerprints warm on every targeted replica, in rank
        order."""
        from . import cache as _ec
        with self._cv:
            ranked = sorted(self._freq,
                            key=lambda fp: (-self._freq[fp], fp))
            manifest = {fp: self._manifest[fp] for fp in ranked
                        if fp in self._manifest}
            if replica is None:
                reps = [r for r in self._replicas if r.in_rotation]
            else:
                reps = [r for r in self._replicas if r.id == replica]
                if not reps:
                    raise ValueError(f"no replica with id {replica!r}")
        if limit is not None:
            manifest = dict(list(manifest.items())[:max(0, limit)])
        done = []
        for fp, circ in manifest.items():
            ok = True
            for rep in reps:
                with self._cv:
                    eng = rep.engines.get(fp)
                try:
                    if eng is not None and eng._open:
                        key = ("param_vmap", eng.fingerprint,
                               eng.max_batch, eng.dtype.str, eng._donate,
                               eng._finalize)
                        if eng._mode() != "vmap" or \
                                _ec.executables().peek(key) is not None:
                            telemetry.inc("engine_precompile_total",
                                          outcome="cached")
                            continue
                        eng.warmup()
                    else:
                        self._engine_for(rep, fp, circ).warmup()
                    telemetry.inc("engine_precompile_total",
                                  outcome="warmed")
                except Exception as e:
                    ok = False
                    telemetry.inc("engine_precompile_total",
                                  outcome="error")
                    telemetry.event("pool.precompile_failed",
                                    fingerprint=fp[:12],
                                    error=type(e).__name__)
            if ok:
                done.append(fp)
        if done:
            telemetry.event("pool.precompile", warmed=len(done),
                            replicas=len(reps))
        return done

    def _precompile_loop(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                self._cv.wait(self.precompile_s)
                if self._closed:
                    return
            try:
                self.precompile()
            except Exception as e:  # pragma: no cover - warm best-effort
                telemetry.event("pool.precompile_failed",
                                fingerprint="", error=type(e).__name__)

    # -- hedging ------------------------------------------------------------

    def _hedge_loop(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                now = time.monotonic()
                cands = []
                for rep in self._replicas:
                    if not rep.in_rotation or rep.health() != "degraded":
                        continue
                    for req in list(rep.outstanding):
                        if (req.settled or req.hedged
                                or req.dispatched_at is None
                                or now - req.dispatched_at < self.hedge_s):
                            continue
                        peer = self._select_locked(
                            req.fingerprint,
                            exclude={rep.id} | req.failed,
                            allow_degraded=False)
                        if peer is not None:
                            req.hedged = True
                            cands.append((req, peer))
            for req, peer in cands:
                self._issue_hedge(req, peer)
            with self._cv:
                if self._closed:
                    return
                self._cv.wait(max(self.hedge_s / 2.0, 0.001))

    def _issue_hedge(self, req: _PoolRequest, peer: _Replica) -> None:
        telemetry.inc("pool_hedges_total", outcome="issued")
        telemetry.event("pool.hedge", replica=peer.id,
                        attempts=req.attempts)
        sp = None
        if req.trace is not None:
            # the hedged duplicate links to the outstanding primary
            # attempt; note the duplicate does NOT take over last_span or
            # the phase mark -- the primary still owns the request unless
            # the hedge wins, and _on_done marks the loser cancelled
            sp = req.trace.child("pool.hedge", replica=peer.id,
                                 attempt=req.attempts)
            if req.last_span is not None:
                sp.link(req.last_span, kind="hedge")

        def attempt():
            return self._engine_for(peer, req.fingerprint,
                                    req.circuit).submit(
                req.params, timeout=req.remaining())

        try:
            if sp is not None or telemetry.trace_on():
                prev = telemetry.current_traces()
                telemetry.set_current_trace(sp)
                try:
                    f = _retry.call_with_retry(
                        attempt, site="pool.hedge",
                        retryable=(QuESTBackpressureError,))
                finally:
                    telemetry.set_current_trace(prev or None)
            else:
                f = _retry.call_with_retry(
                    attempt, site="pool.hedge",
                    retryable=(QuESTBackpressureError,))
        except Exception:
            if sp is not None:
                sp.end(status="error")
            with self._cv:
                req.hedged = False  # primary still owns it; may re-hedge
            return
        with self._cv:
            req.inner.append((peer, f, True, sp))
            peer.outstanding.add(req)
        f.add_done_callback(
            lambda fut, req=req, rep=peer: self._on_done(req, rep, fut,
                                                         hedge=True))

    # -- introspection / lifecycle ------------------------------------------

    def _rotation_count(self) -> int:
        with self._cv:
            return sum(1 for r in self._replicas if r.in_rotation)

    def health(self) -> dict:
        """Replica id -> health state, quarantined ex-members included."""
        with self._cv:
            return {rep.id: rep.health() for rep in self._replicas}

    def rotation(self) -> list:
        """Ids of the replicas currently accepting traffic."""
        with self._cv:
            return [rep.id for rep in self._replicas if rep.in_rotation]

    def await_rotation(self, k: int, timeout: float | None = None) -> int:
        """Block until at least ``k`` replicas are in rotation (e.g. a
        replacement finished warming); raises TimeoutError otherwise."""
        with self._cv:
            ok = self._cv.wait_for(
                lambda: self._closed or sum(
                    1 for r in self._replicas if r.in_rotation) >= k,
                timeout)
            count = sum(1 for r in self._replicas if r.in_rotation)
        if not ok or count < k:
            raise TimeoutError(
                f"pool rotation did not reach {k} (have {count})")
        return count

    def revive(self, replica_id: int) -> str:
        """Operator acknowledgement after a quarantine: return the
        replica to rotation. Engines its drain closed are discarded (they
        rebuild lazily, warm via the executable LRU); surviving engines
        are :meth:`Engine.revive`-d. Returns the replica's new health."""
        with self._cv:
            reps = [r for r in self._replicas if r.id == replica_id]
            if not reps:
                raise ValueError(f"no replica with id {replica_id!r}")
            rep = reps[0]
            rep.state = "healthy"
            for fp in [fp for fp, e in rep.engines.items()
                       if not e._open]:
                del rep.engines[fp]
            engines = list(rep.engines.values())
        for eng in engines:
            eng.revive()
        with self._cv:
            rep.in_rotation = True
            self._cv.notify_all()
        telemetry.set_gauge("pool_replicas", self._rotation_count())
        telemetry.event("pool.revive", replica=rep.id)
        self._drain_pending()
        with self._cv:
            return rep.health()

    def close(self, drain: bool = True) -> None:
        """Close every engine on every replica (``drain`` as in
        :meth:`Engine.close`); parked pending requests resolve with a
        typed QuESTCancelledError. Every accepted future resolves."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            parked = [r for p in PRIORITIES for r in self._pending[p]]
            for p in PRIORITIES:
                self._pending[p].clear()
            reps = list(self._replicas)
            workers = list(self._workers)
            self._cv.notify_all()
        for req in parked:
            self._settle(req, exc=QuESTCancelledError(
                "request dropped by EnginePool.close before dispatch",
                "EnginePool.close"))
        for t in workers:
            _sync.join_thread(t)
        for rep in reps:
            for eng in list(rep.engines.values()):
                try:
                    eng.close(drain=drain)
                except Exception:  # pragma: no cover
                    pass
        if self._hedge_thread is not None and self._hedge_thread.is_alive():
            _sync.join_thread(self._hedge_thread)
        if self._precompile_thread is not None \
                and self._precompile_thread.is_alive():
            _sync.join_thread(self._precompile_thread)
        telemetry.set_gauge("pool_replicas", 0)
        telemetry.event("pool.close", drained=drain)

    def __enter__(self) -> "EnginePool":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close(drain=exc_type is None)
        return False
