"""Micro-batched ensemble execution of parameterized circuits.

The reference serves exactly one caller: every gate is an eager kernel
launch against one register. The serving shape this module targets is the
opposite -- many requests that are *variants of one circuit structure*
(a VQE/QAOA parameter sweep, or many users submitting the same ansatz with
their own angles) arriving concurrently. Three mechanisms make that cheap:

- **One executable, many parameter vectors**: the engine replays its
  circuit through the parameterized executable
  (:meth:`quest_tpu.circuits.Circuit.parameterized`), so a warm submit
  triggers zero retraces -- values are runtime arguments.
- **Micro-batching**: ``submit(params)`` returns a
  :class:`concurrent.futures.Future` immediately; a background batcher
  coalesces pending requests up to ``max_batch`` within a ``max_delay_ms``
  window and dispatches them together. Unsharded registers run every
  dispatch as the ONE fixed-shape ``vmap``-over-params program (``B``
  states evolve in one fused XLA program -- the ensemble analogue of
  cuQuantum's batched ``custatevecApplyMatrix``), short batches padded to
  ``max_batch``: one executable ever compiles, and a request computes the
  same bits whether or not it was coalesced (batch lanes are independent
  and identical). Sharded registers replay sequentially with donated
  buffers inside the one dispatch instead (a (B, 2, N) batch axis would
  fight the amplitude sharding for the mesh).
- **Executable reuse across structures**: executables are fetched from the
  process-global LRU (:mod:`quest_tpu.engine.cache`) per dispatch, keyed by
  the circuit's structure fingerprint -- a second Engine over a
  structure-equal circuit compiles nothing (``plan_cache_hit_total``).

Telemetry (docs/observability.md): ``engine_requests_total``,
``engine_batches_total{mode=vmap|sequential}``, ``engine_batch_size`` and
``engine_request_latency_seconds`` histograms, ``engine_queue_depth``
gauge, ``engine_trace_total{kind=param_replay}`` (one increment per jit
trace of the replay -- the retrace detector tests assert on).

Failure semantics (ISSUE 7 -- request-level, like Orca-style serving):

- **Deadlines**: ``submit(params, timeout=)`` sets a wall-clock deadline;
  requests still queued past it resolve with
  :class:`~quest_tpu.resilience.QuESTTimeoutError` instead of dispatching
  (``engine_request_timeouts_total``).
- **Backpressure**: the queue is bounded (``queue_max`` ctor arg /
  ``QUEST_ENGINE_QUEUE_MAX`` env); a full queue raises
  :class:`~quest_tpu.resilience.QuESTBackpressureError` at submit
  (``engine_backpressure_total``) rather than growing unboundedly.
- **Poisoned-batch bisection**: when a batched dispatch fails, the
  batcher bisects the batch through the SAME padded executable
  (``engine_bisections_total``) -- healthy requests complete with
  bit-identical results (vmap lanes are independent), and each poisoned
  request gets its own exception. The ``engine.request`` fault-injection
  site (quest_tpu.resilience.faultinject) pins injected poison to a
  request at submit time, which is how the isolation tests drive this.
- **Typed cancellation**: ``close(drain=False)`` resolves still-queued
  futures with :class:`~quest_tpu.resilience.QuESTCancelledError` --
  a waiter blocked on ``result()`` always wakes with a typed error.

Health states (ISSUE 8 -- engine-level, fed by the integrity machinery):

- :meth:`health` is ``healthy`` | ``degraded`` | ``quarantined``.
  A sentinel breach on a dispatch result (``QUEST_SENTINEL`` armed,
  :mod:`quest_tpu.resilience.sentinel` -- the corrupt result is NEVER
  served; its future resolves with
  :class:`~quest_tpu.resilience.QuESTIntegrityError`) marks the engine
  ``degraded``; a second breach, or a watchdog deadline expiry
  (``QUEST_WATCHDOG_MS`` around the whole dispatch, typed
  :class:`~quest_tpu.resilience.QuESTHangError`), marks it
  ``quarantined``.
- A quarantined engine rejects submits through the existing
  backpressure path (``QuESTBackpressureError``,
  ``engine_backpressure_total{reason=quarantined}``) until the operator
  calls :meth:`revive` -- in-flight and already-queued work still
  completes, so quarantine sheds load without dropping accepted futures.
- Three consecutive clean dispatches heal ``degraded`` back to
  ``healthy``; transitions count
  ``engine_health_transitions_total{from,to}``.

Async dispatch pipeline (round 18 -- the host-side twin of PR 8's
prologue/steady-state/epilogue collective pipeline):

- **Host/device overlap**: with ``async_depth >= 1`` (ctor arg /
  ``QUEST_ASYNC_DEPTH``, default 2, QT310 warn-once) the batcher never
  blocks between the queue and the device -- it issues the traced vmap
  program for batch k, parks the in-flight result in a bounded
  **completion ring**, and immediately returns to coalescing batch k+1
  while k executes. Ring entries retire (device sync + per-lane future
  resolution) when the ring is full, when the queue idles, and at
  close; a retire-time device error/hang/breach is attributed to the
  RING ENTRY's requests, never to the batch being issued
  (``engine_async_retires_total{outcome}``, ``engine_async_inflight``).
  ``async_depth=0`` restores strictly synchronous dispatch -- the A/B
  baseline; both routes run the identical padded executable, so async
  and sync results are bit-identical by construction.
- **Serial issue on timeshared backends**: XLA:CPU executes
  concurrently enqueued programs by timesharing the same host cores
  (no private execution stream), so running two batch programs ahead
  of each other costs ~20% per batch -- more than the host time it
  hides. On CPU, ring admission therefore device-syncs the in-flight
  head before the next issue and -- when a spare host core exists --
  defers its RESOLUTION until just after it: assembly and coalescing
  overlap device execution on the way in, lane extraction and future
  resolution on the way out, and the device never timeshares two
  batches. On a single-core host there is nothing to overlap (the
  "overlapped" host thread is starved by the execution thread), so
  the head resolves before the issue. Admission and settling run
  outside the dispatch watchdog; each blocking sync is bounded by its
  own ``engine.retire`` deadline and charged to the entry it retires.
- **Continuous batching** (Orca, PAPERS.md): while a batch is in
  flight, the device -- not the ``max_delay_ms`` timer -- paces the
  window: a late submit joins the NEXT vmap window instead of waiting
  out a full coalescing tick (the padded fixed-shape program makes the
  join point well-defined).

Lifecycle: construct, optionally :meth:`warmup`, ``submit``/``run``, then
:meth:`close` -- which drains the queue AND the completion ring (every
accepted future resolves) and joins the batcher thread. The engine is
also a context manager.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from .. import telemetry
from ..resilience import faultinject as _faults
from ..resilience import sentinel as _sentinel
from ..resilience import sync as _sync
from ..resilience import watchdog as _watchdog
from ..resilience.errors import (PoisonedRequestFault, QuESTBackpressureError,
                                 QuESTCancelledError, QuESTHangError,
                                 QuESTIntegrityError, QuESTTimeoutError)
from . import cache as _cache
from .params import _SEED, bind

__all__ = ["Engine", "HEALTH_STATES"]

#: engine health states, healthiest first
HEALTH_STATES = ("healthy", "degraded", "quarantined")

#: consecutive clean dispatches that heal ``degraded`` -> ``healthy``
_HEAL_STREAK = 3


class _Request:
    """One queued parameter set: bound values, the caller's future, the
    enqueue timestamp, an optional wall-clock deadline, the injected
    poison kind pinned at submit time (None on healthy requests), and the
    request's trace context (None whenever tracing is off)."""

    __slots__ = ("values", "fut", "t0", "deadline", "poison", "trace")

    def __init__(self, values: tuple, fut: Future, t0: float,
                 deadline: float | None, poison: str | None,
                 trace=None):
        self.values = values
        self.fut = fut
        self.t0 = t0
        self.deadline = deadline
        self.poison = poison
        self.trace = trace


_ASYNC_ENV = "QUEST_ASYNC_DEPTH"
_ASYNC_ENV_WARNED: set = set()


def async_depth_default() -> int:
    """``QUEST_ASYNC_DEPTH`` (default 2): completion-ring depth of the
    async dispatch pipeline -- how many issued batches may be in flight on
    the device while the host coalesces the next. ``0`` means synchronous
    dispatch (the batcher drains each batch before issuing another -- the
    A/B baseline the bench compares against). Malformed or negative values
    fall back through :func:`parse_env_int` with a QT310 warn-once."""
    from ..analysis.diagnostics import parse_env_int
    return parse_env_int(_ASYNC_ENV, 2, minimum=0, code="QT310",
                         warned=_ASYNC_ENV_WARNED,
                         noun="async completion-ring depth")


def _env_queue_max() -> int:
    """``QUEST_ENGINE_QUEUE_MAX`` (0/unset = unbounded); malformed values
    fall back to unbounded with a QT303 diagnostic."""
    raw = os.environ.get("QUEST_ENGINE_QUEUE_MAX", "").strip()
    if not raw:
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        from ..analysis.diagnostics import emit_findings, make_finding
        emit_findings([make_finding(
            "QT303", f"QUEST_ENGINE_QUEUE_MAX={raw!r} is not numeric; "
            "using the default", "engine.Engine")])
        return 0


class Engine:
    """Serving runtime for one circuit structure (see module docstring).

    ``circuit`` may be a raw or fused :class:`~quest_tpu.circuits.Circuit`
    recorded with :class:`~quest_tpu.engine.params.Param` placeholders (and
    any constant angles, which are lifted to runtime values too -- see
    :func:`~quest_tpu.engine.params.lift_tape`). ``env`` supplies the
    device mesh; with a multi-device env the initial state shards over it
    and batches replay sequentially. ``initial`` is ``"zero"`` (|0...0>),
    ``"plus"``, or a concrete planar (2, 2^nsv) array.
    """

    def __init__(self, circuit, env=None, *, precision_code: int | None = None,
                 max_batch: int = 8, max_delay_ms: float = 2.0,
                 initial="zero", donate: bool = True,
                 queue_max: int | None = None,
                 async_depth: int | None = None,
                 finalize=None, hamiltonian=None):
        import jax
        import jax.numpy as jnp

        from ..ops import init as ops_init
        from ..precision import real_dtype

        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        if queue_max is None:
            queue_max = _env_queue_max()
        if queue_max < 0:
            raise ValueError(f"queue_max must be >= 0, got {queue_max}")
        if async_depth is None:
            async_depth = async_depth_default()
        if async_depth < 0:
            raise ValueError(f"async_depth must be >= 0, got {async_depth}")
        #: completion-ring depth; 0 = synchronous dispatch (A/B baseline)
        self.async_depth = int(async_depth)
        #: pending-queue bound; 0 = unbounded (the pre-ISSUE-7 behavior)
        self.queue_max = int(queue_max)
        self.circuit = circuit
        self.env = env
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1e3
        self._donate = bool(donate)
        # round 19: optional traceable terminal stage composed INSIDE the
        # dispatched program (e.g. a sampling.sample_reduce shot table) --
        # futures then resolve to finalize(final_amps), never the 2^N
        # amplitudes, and the amps-shaped sentinel / corrupt-injection
        # gates are bypassed (the result is not a state). Must be a
        # stable (cached) callable: it keys the executable LRU.
        self._finalize = finalize
        # a values-aware finalize (the adjoint gradient reduce) carries its
        # own dispatch route label -- grad_request traffic stays countable
        # apart from plain engine_param/engine_vmap dispatches
        self._route = getattr(finalize, "dispatch_route", None)
        # round 20: the observable whose gradient submit_grad serves; the
        # companion gradient engine (same ansatz, grad_reduce finalize)
        # builds lazily on first use
        self._hamiltonian = hamiltonian
        self._precision_code = precision_code
        self._grad_companion: "Engine" | None = None
        self.dtype = real_dtype(precision_code)
        nsv = (2 if circuit.is_density_matrix else 1) * circuit.num_qubits
        self.num_amps = 1 << nsv
        self._sharding = (env.sharding(self.num_amps)
                          if env is not None else None)
        self._mesh = env.mesh if self._sharding is not None else None
        #: True when batches replay sequentially over the sharded register
        self.sharded = self._mesh is not None and self._mesh.size > 1

        if isinstance(initial, str):
            if initial == "zero":
                amps = ops_init.init_classical(self.num_amps, self.dtype, 0)
            elif initial == "plus":
                re = jnp.full((self.num_amps,),
                              1.0 / math.sqrt(self.num_amps), self.dtype)
                amps = jnp.stack([re, jnp.zeros_like(re)])
            else:
                raise ValueError(
                    f"initial must be 'zero', 'plus' or an array, "
                    f"got {initial!r}")
        else:
            amps = jnp.asarray(initial, dtype=self.dtype)
            if amps.shape != (2, self.num_amps):
                raise ValueError(
                    f"initial amps shape {amps.shape} != (2, {self.num_amps})")
        if self._sharding is not None:
            amps = jax.device_put(amps, self._sharding)
        #: planar initial-state template; each request donates a fresh copy
        self.initial_amps = amps

        self._lifted = circuit.lifted()
        self.fingerprint = circuit.fingerprint()
        self._cv = _sync.Condition("engine.cv")
        self._q: deque = deque()
        # completion ring (round 18): in-flight issued batches awaiting
        # their device sync. BATCHER-THREAD-ONLY -- submit/close never
        # touch it, so it needs no lock; the loop drains it before exit.
        # Entries are [out, batch, tick, dev_t0, t_ready]: t_ready flips
        # from None when the serial-issue admission proved the device
        # done (the entry is then "synced" and its resolution is
        # deliberately deferred past the next issue).
        self._ring: deque = deque()
        self._serial: bool | None = None  # resolved lazily by _issue_serial
        self._cores: int | None = None  # resolved lazily by _spare_core
        self._open = True
        self._health = "healthy"
        self._breaches = 0        # sentinel breaches since last full heal
        self._clean_streak = 0    # consecutive clean dispatches
        self._dispatches = 0      # dispatch ordinal = the sentinel tick
        self._t_first: float | None = None  # batcher pop instant (tracing)
        self._thread = threading.Thread(target=self._loop,
                                        name="quest-engine", daemon=True)
        self._thread.start()
        # seed-kind slots mark a trajectory-noise structure: each vmap lane
        # of a batch then carries an independent PRNG stream
        # (quest_tpu/trajectories), surfaced here for the flight recorder
        self.seed_slots = sum(1 for s in self._lifted.slots
                              if s.kind == _SEED)
        telemetry.event("engine.start", fingerprint=self.fingerprint[:12],
                        nsv=nsv, max_batch=self.max_batch,
                        sharded=self.sharded, async_depth=self.async_depth,
                        params=len(self._lifted.param_names),
                        seed_slots=self.seed_slots)

    # -- submission ---------------------------------------------------------

    @property
    def param_names(self) -> tuple:
        """Ordered Param names every submit must bind."""
        return self._lifted.param_names

    def submit(self, params: dict | None = None,
               timeout: float | None = None) -> Future:
        """Queue one parameter set; returns a Future resolving to the final
        planar (2, 2^nsv) amplitude array (a batch slice when coalesced).
        ``timeout`` (seconds) sets a deadline: a request still queued when
        it expires resolves with QuESTTimeoutError instead of running."""
        return self.submit_many([params], timeout=timeout)[0]

    def submit_many(self, params_list, timeout: float | None = None) -> list:
        """Queue several parameter sets ATOMICALLY (single lock hold), so an
        idle engine coalesces them into one dispatch -- the deterministic
        enqueue the bench and dryrun batching assertions rely on. Raises
        QuESTBackpressureError (accepting NONE of them) when the bounded
        queue cannot take the whole list."""
        if not params_list:
            return []
        if timeout is not None and timeout < 0:
            raise ValueError(f"timeout must be >= 0, got {timeout}")
        if not self._open:
            raise RuntimeError("Engine is closed")
        values_list = [bind(self._lifted, p) for p in params_list]
        futs = []
        with self._cv:
            if not self._open:
                raise RuntimeError("Engine is closed")
            if self._health == "quarantined":
                # quarantine sheds load through the EXISTING backpressure
                # contract: callers already handle QuESTBackpressureError
                telemetry.inc("engine_backpressure_total",
                              reason="quarantined")
                raise QuESTBackpressureError(
                    f"engine is quarantined ({self._breaches} integrity "
                    f"breach(es) recorded): rejecting "
                    f"{len(values_list)} request(s); investigate, then "
                    f"revive()", "Engine.submit")
            if self.queue_max and \
                    len(self._q) + len(values_list) > self.queue_max:
                telemetry.inc("engine_backpressure_total")
                raise QuESTBackpressureError(
                    f"engine queue full ({len(self._q)} pending, "
                    f"queue_max={self.queue_max}): rejecting "
                    f"{len(values_list)} request(s)", "Engine.submit")
            now = time.perf_counter()
            deadline = None if timeout is None else now + timeout
            # tracing (round 17): one boolean read when off. A pool-side
            # attempt span bound to this thread is adopted as the parent
            # (the request stays ONE waterfall across the hop); otherwise
            # the engine mints the root and owns finishing it.
            tracing = telemetry.trace_on()
            adopt = telemetry.current_trace() if tracing else None
            for values in values_list:
                fut = Future()
                # injected poison pins to the REQUEST here, at submit time,
                # so the nth-visit counting stays deterministic no matter
                # how the batcher later coalesces or bisects
                poison = _faults.fire("engine.request") \
                    if _faults.enabled() else None
                if not tracing:
                    ctx = None
                elif adopt is not None and len(values_list) == 1:
                    ctx = adopt.child("engine.request",
                                      engine=self.fingerprint[:8])
                else:
                    ctx = telemetry.start_trace(
                        "request", t0=now, kind="engine",
                        engine=self.fingerprint[:8])
                self._q.append(
                    _Request(values, fut, now, deadline, poison, ctx))
                futs.append(fut)
            telemetry.inc("engine_requests_total", len(futs))
            telemetry.set_gauge("engine_queue_depth", len(self._q))
            self._cv.notify_all()
        return futs

    def run(self, params: dict | None = None):
        """Synchronous convenience: ``submit(params).result()``."""
        return self.submit(params).result()

    # -- health -------------------------------------------------------------

    def health(self) -> str:
        """Current health state: ``healthy`` | ``degraded`` |
        ``quarantined`` (see module docstring)."""
        with self._cv:
            return self._health

    def is_open(self) -> bool:
        """True until :meth:`close` begins; a closed engine rejects every
        submit with ``RuntimeError``. The pool's dispatch path reads this
        to distinguish a drain-closed replica (fail over) from a genuine
        request error (settle)."""
        with self._cv:
            return self._open

    def revive(self) -> str:
        """Operator acknowledgement after a quarantine: transition
        ``quarantined`` -> ``degraded`` (submits are accepted again, and
        ``healthy`` returns after :data:`_HEAL_STREAK` clean dispatches).
        No-op in any other state. Returns the new state."""
        with self._cv:
            if self._health == "quarantined":
                self._transition("degraded", reason="revive")
                self._clean_streak = 0
            return self._health

    def _transition(self, to: str, *, reason: str) -> None:
        # callers hold self._cv
        if to == self._health:
            return
        telemetry.inc("engine_health_transitions_total",
                      **{"from": self._health, "to": to})
        telemetry.event("engine.health", previous=self._health, state=to,
                        reason=reason)
        self._health = to

    def _note_breach(self, *, hang: bool) -> None:
        with self._cv:
            self._clean_streak = 0
            if hang:
                # a wedged dispatch is not self-healable: straight to
                # quarantined, the operator must look at the mesh
                self._transition("quarantined", reason="hang")
                return
            self._breaches += 1
            self._transition(
                "quarantined" if self._breaches >= 2 else "degraded",
                reason="sentinel_breach")

    def _note_clean(self) -> None:
        with self._cv:
            if self._health != "degraded":
                return
            self._clean_streak += 1
            if self._clean_streak >= _HEAL_STREAK:
                self._breaches = 0
                self._transition("healthy", reason="clean_streak")

    def warmup(self, params: dict | None = None) -> "Engine":
        """Trace + compile both dispatch shapes (single and full batch) so
        every subsequent submit performs zero retraces. Named Params warm
        up at 0.0 unless ``params`` is given."""
        p = params if params is not None else {n: 0.0
                                              for n in self.param_names}
        self.run(p)
        if self.max_batch > 1:
            for f in self.submit_many([p] * self.max_batch):
                f.result()
        return self

    # -- gradients (round 20) -----------------------------------------------

    def grad_engine(self) -> "Engine":
        """The companion gradient engine: same ansatz, same batching knobs,
        finalized by the adjoint gradient reduce (quest_tpu/gradients), so
        T optimizer chains coalesce into ONE vmapped forward+backward
        program dispatched as ``route=grad_request``. Built lazily on
        first use; requires ``hamiltonian=`` at construction."""
        from ..validation import QuESTError

        with self._cv:
            if self._grad_companion is not None:
                return self._grad_companion
            if self._hamiltonian is None:
                raise QuESTError(
                    "Engine.submit_grad needs the observable: construct "
                    "the Engine with hamiltonian=(pauli_codes, term_coeffs) "
                    "or a PauliHamil", "Engine.submit_grad")
        from ..gradients import grad_reduce

        red = grad_reduce(self.circuit, self._hamiltonian, dtype=self.dtype)
        eng = Engine(self.circuit, self.env,
                     precision_code=self._precision_code,
                     max_batch=self.max_batch,
                     max_delay_ms=self.max_delay_s * 1e3,
                     initial=self.initial_amps,
                     donate=self._donate,
                     queue_max=self.queue_max,
                     async_depth=self.async_depth,
                     finalize=red)
        with self._cv:
            if self._grad_companion is None:
                self._grad_companion = eng
                eng = None
        if eng is not None:  # lost the build race
            eng.close(drain=False)
        return self._grad_companion

    def submit_grad(self, params: dict | None = None,
                    timeout: float | None = None) -> Future:
        """Queue one optimizer step: a Future resolving to ``(value,
        grads)`` -- E = ⟨ψ(θ)|H|ψ(θ)⟩ and the full adjoint gradient as a
        Param-name -> derivative dict (shared-Param slots already summed
        by the chain rule). Warm steps perform zero retraces and ONE
        device dispatch per coalesced batch."""
        eng = self.grad_engine()
        telemetry.inc("grad_requests_total")
        telemetry.inc("grad_slots_total",
                      float(eng._finalize.num_slots))
        inner = eng.submit(params, timeout=timeout)
        fut: Future = Future()

        def _chain(f, _fut=fut):
            exc = f.exception()
            if exc is not None:
                _sync.resolve_future(_fut, exception=exc,
                                     site="engine.submit_grad")
            else:
                out = f.result()
                _sync.resolve_future(_fut,
                                     result=(out["value"], out["grads"]),
                                     site="engine.submit_grad")

        inner.add_done_callback(_chain)
        return fut

    def warmup_grad(self, params: dict | None = None) -> "Engine":
        """Compile both gradient dispatch shapes ahead of traffic (the
        gradient analogue of :meth:`warmup`)."""
        self.grad_engine().warmup(params)
        return self

    # -- lifecycle ----------------------------------------------------------

    def close(self, drain: bool = True) -> None:
        """Stop accepting work and join the batcher. ``drain=True``
        (default) dispatches everything still queued first; ``drain=False``
        resolves pending futures with a typed QuESTCancelledError instead
        (in-flight work still completes). Every accepted future resolves
        either way -- a waiter blocked on ``result()`` always wakes.

        A QUARANTINED engine never drains: work accepted before the
        quarantine would otherwise sit behind a batcher the operator has
        been told to investigate (and, after a hang, one that may be
        wedged), so ``drain=True`` downgrades to the typed cancellation
        path -- queued futures resolve promptly with QuESTCancelledError
        and only in-flight work is waited on."""
        dropped: list = []
        with self._cv:
            if drain and self._health == "quarantined":
                drain = False
            if not drain:
                while self._q:
                    dropped.append(self._q.popleft())
            self._open = False
            self._cv.notify_all()
        # resolve OUTSIDE the lock: done callbacks (the pool's failover
        # re-dispatch) may take other locks, and holding self._cv across
        # arbitrary callbacks invites lock-order inversions
        for req in dropped:
            # a typed resolution, not Future.cancel(): cancel() is a
            # no-op on futures a waiter already holds in RUNNING
            # transitions elsewhere, and CancelledError carries no
            # context -- this names the drop
            exc = QuESTCancelledError(
                "request dropped by Engine.close before dispatch",
                "Engine.close")
            self._trace_error(req, exc)
            _sync.resolve_future(req.fut, exception=exc, site="engine.close")
        if self._thread.is_alive() and \
                self._thread is not threading.current_thread():
            _sync.join_thread(self._thread)
        comp = self._grad_companion
        if comp is not None:
            comp.close(drain=drain)
        telemetry.set_gauge("engine_queue_depth", 0)
        telemetry.event("engine.close", drained=drain)

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close(drain=exc_type is None)
        return False

    # -- executables --------------------------------------------------------

    def _exec1(self):
        """The single-request parameterized executable, re-fetched from the
        global LRU per dispatch (warm dispatches therefore count
        ``plan_cache_hit_total`` -- the acceptance signal that nothing
        recompiled)."""
        from .. import fusion

        with fusion.pallas_mesh(self._mesh):
            return self.circuit.parameterized(donate=self._donate,
                                              reduce=self._finalize)

    def _execB(self):
        """The vmap-over-params batch executable (unsharded registers):
        ONE fused program evolving ``max_batch`` states, batches padded to
        that size so the shape -- and hence the compiled program -- is
        constant. An armed ``finalize`` composes inside the vmapped body,
        so the program returns ``max_batch`` finalized results (e.g. shot
        tables) and the 2^N lanes never leave the device."""
        import jax

        from .. import fusion
        from ..parallel import scheduler as _dist

        key = ("param_vmap", self.fingerprint, self.max_batch, self.dtype.str,
               self._donate, self._finalize)
        circuit, donate = self.circuit, self._donate
        finalize = self._finalize

        def build():
            inner = circuit._replay_fn(circuit.lifted())
            if finalize is not None and getattr(finalize, "wants_values",
                                                False):
                # values-aware finalize (adjoint gradient): the backward
                # sweep re-assembles daggered gates from each lane's own
                # traced slot values
                body = lambda amps, values: finalize(inner(amps, values),  # noqa: E731
                                                     values)
            elif finalize is not None:
                body = lambda amps, values: finalize(inner(amps, values))  # noqa: E731
            else:
                body = inner
            if (finalize is not None
                    and getattr(finalize, "wants_values", False)
                    and jax.default_backend() == "cpu"):
                # the adjoint forward+backward body vmaps badly on
                # XLA:CPU (measured 20q batch-8: ~20x the compile and
                # ~5x the run time of the lanes executed back-to-back);
                # lax.map traces the body ONCE and runs the lanes as a
                # scan -- still one fixed-shape program, one dispatch
                batched = lambda amps_b, values_b: jax.lax.map(  # noqa: E731
                    lambda av: body(av[0], av[1]), (amps_b, values_b))
            else:
                batched = jax.vmap(body, in_axes=(0, 0))
            jitted = jax.jit(batched,
                             donate_argnums=(0,) if donate else ())

            def fn(amps_b, values_b, _inner=jitted):
                with _dist.explicit_mesh(None), fusion.pallas_mesh(None):
                    return _inner(amps_b, values_b)

            return fn

        return _cache.executables().get_or_create(key, build)

    # -- batcher ------------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and self._open and not self._ring:
                    self._cv.wait()
                if not self._q:
                    if not self._ring:
                        return  # closed and fully drained (queue AND ring)
                    batch = None  # idle (or closing) with work in flight
                else:
                    batch = [self._q.popleft()]
                    deadline = time.perf_counter() + self.max_delay_s
                    while len(batch) < self.max_batch:
                        if self._q:
                            batch.append(self._q.popleft())
                            continue
                        if not self._open:
                            break
                        remaining = deadline - time.perf_counter()
                        # continuous batching (round 18): with a batch in
                        # flight the device, not the timer, paces the
                        # window -- issue what we have and let a late
                        # submit join the NEXT vmap window
                        if remaining <= 0 or self._ring:
                            break
                        self._cv.wait(remaining)
                    telemetry.set_gauge("engine_queue_depth", len(self._q))
            if batch is None:
                # queue idle but batches in flight: retire the oldest ring
                # entry (its futures resolve) before sleeping -- the ring
                # never outlives the loop and never waits on new traffic
                self._retire_oldest()
                continue
            live = self._expire(batch)
            if live:
                # t_first (the pop instant) is recovered from the already
                # taken deadline reading: queue_wait/coalesce attribution
                # costs the untraced path zero extra clock reads. Handed
                # over on the instance so _dispatch keeps its one-argument
                # seam (tests wrap it with lambda b: ...).
                self._t_first = deadline - self.max_delay_s
                self._dispatch(live)

    def _expire(self, batch: list) -> list:
        """Resolve requests whose deadline passed while queued with
        QuESTTimeoutError; return the still-live remainder."""
        now = time.perf_counter()
        live = []
        for req in batch:
            if req.deadline is not None and now >= req.deadline:
                telemetry.inc("engine_request_timeouts_total")
                exc = QuESTTimeoutError(
                    f"request deadline expired after "
                    f"{now - req.t0:.3f}s in queue "
                    f"(timeout={req.deadline - req.t0:.3f}s)",
                    "Engine.submit")
                self._trace_error(req, exc)
                _sync.resolve_future(req.fut, exception=exc,
                                     site="engine.expire")
            else:
                live.append(req)
        return live

    def _mode(self) -> str:
        # unsharded engines with batching enabled ALWAYS run the one
        # fixed-shape padded vmap program, even for a lone request: every
        # request then executes in an identical batch lane of the identical
        # executable, so coalesced and uncoalesced traffic is bit-identical
        # BY CONSTRUCTION (XLA's batched and unbatched contractions do not
        # share accumulation order, so a separate B=1 program would drift
        # ~1 ulp per gate) -- and exactly one executable ever compiles.
        # max_batch=1 opts out for latency-only deployments.
        return ("vmap" if (not self.sharded and self.max_batch > 1
                           and self._lifted.slots) else "sequential")

    def _dispatch(self, batch: list) -> None:
        t_first = self._t_first
        mode = self._mode()
        self._dispatches += 1
        telemetry.inc("engine_batches_total", mode=mode)
        telemetry.observe("engine_batch_size", len(batch))
        # tracing (round 17): attribute queue_wait (enqueue -> batcher
        # pop) and coalesce (pop -> window close) per request, then bind
        # the batch's contexts to this thread so retry/guard/bisect hops
        # inside the dispatch can link to them. The binding MUST clear
        # after the futures resolve (QT703) -- the finally below.
        traced = [r.trace for r in batch if r.trace is not None]
        if traced:
            t_close = time.perf_counter()
            for req in batch:
                tr = req.trace
                if tr is None:
                    continue
                pivot = req.t0 if t_first is None else max(req.t0, t_first)
                tr.phase("queue_wait", req.t0, max(0.0, pivot - req.t0))
                tr.phase("coalesce", pivot, max(0.0, t_close - pivot))
            telemetry.set_current_trace(traced)
        # the injectable hang/transient point: one visit per dispatch; with
        # QUEST_WATCHDOG_MS armed the WHOLE dispatch (tracing included --
        # it begins and ends on the watchdog's worker thread, so jax's
        # thread-local trace state never splits) is deadline-bounded
        kind = _faults.fire("engine.dispatch") if _faults.enabled() else None
        ringable = (mode == "vmap" and self.async_depth > 0
                    and bool(self._lifted.slots))
        deferred = False
        try:
            with telemetry.span("engine.dispatch", mode=mode,
                                batch=len(batch)):
                if kind == "transient":
                    # an injected issue-time transient fails THIS batch
                    # before it reaches the device (or the completion
                    # ring): the bisection ladder below re-dispatches it,
                    # so healthy requests still complete and attribution
                    # never leaks onto a different in-flight batch
                    from ..resilience.errors import TransientFault
                    raise TransientFault("engine.dispatch", kind)
                if ringable:
                    # ring admission runs OUTSIDE the dispatch watchdog:
                    # each retire is its own deadline-bounded blocking
                    # boundary (guard.device_sync), so a retire-time hang
                    # is charged to the RETIRED entry -- wrapping it in
                    # this batch's dispatch deadline would misattribute
                    # the wedge to the batch being issued. The wait for
                    # ring capacity is this batch's queue_wait.
                    t_adm = time.perf_counter() if traced else 0.0
                    self._ring_admit()
                    if traced:
                        t_adm1 = time.perf_counter()
                        for req in batch:
                            if req.trace is not None and t_adm1 > t_adm:
                                req.trace.phase("queue_wait", t_adm,
                                                t_adm1 - t_adm)
                deferred = _watchdog.watched(
                    lambda: self._dispatch_one(batch, mode, defer=True),
                    site="engine.dispatch", hang=(kind == "hang"))
        except QuESTHangError as e:
            # no bisection: a wedged dispatch would wedge each half too;
            # fail the batch typed and quarantine the engine
            self._note_breach(hang=True)
            for req in batch:
                self._trace_error(req, e)
                _sync.resolve_future(req.fut, exception=e,
                                     site="engine.dispatch")
        except QuESTIntegrityError as e:
            # a corrupt result was caught BEFORE any future resolved with
            # it: fail the remainder typed, degrade (quarantine on repeat)
            self._note_breach(hang=False)
            for req in batch:
                self._trace_error(req, e)
                _sync.resolve_future(req.fut, exception=e,
                                     site="engine.dispatch")
        except Exception:
            # a failed batch bisects through the same executable: healthy
            # requests complete bit-identically, poisoned ones carry their
            # own exception -- one bad parameter set never fails neighbors
            self._bisect(batch, mode)
        except BaseException as e:  # interpreter teardown must not hang waiters
            for req in batch:
                self._trace_error(req, e)
                _sync.resolve_future(req.fut, exception=e,
                                     site="engine.dispatch")
        else:
            # a deferred batch is merely ISSUED: health credit and latency
            # observation move to its ring retire, where the device sync
            # actually proves the dispatch clean
            if not deferred:
                self._note_clean()
        finally:
            if traced:
                telemetry.clear_current_trace()
        if deferred:
            # entries the admission proved complete resolve only NOW,
            # after the issue: their lane extraction, sentinel gate and
            # future resolution overlap the batch just put on the device
            # instead of holding it idle
            self._ring_settle()
            return
        now = time.perf_counter()
        for req in batch:
            telemetry.observe("engine_request_latency_seconds", now - req.t0)

    def _dispatch_one(self, batch: list, mode: str,
                      defer: bool = False) -> bool:
        """Run one batch on its route. Returns True when the batch was
        ISSUED onto the completion ring (async vmap path -- its futures
        resolve at retire), False when it was fully dispatched and
        resolved synchronously. ``defer=False`` (the bisection ladder's
        calls) forces the synchronous route: a re-dispatched half must
        resolve before the ladder recurses, never re-enter the ring."""
        # device dispatch is a blocking boundary: flight-record QT602 if
        # any instrumented lock is still held on the dispatching thread
        _sync.guard_blocking("engine.dispatch")
        if mode == "vmap":
            return self._dispatch_vmap(batch, defer=defer)
        self._dispatch_sequential(batch)
        return False

    def _bisect(self, batch: list, mode: str, _prev: dict | None = None) -> None:
        telemetry.inc("engine_bisections_total")
        if len(batch) == 1:
            req = batch[0]
            try:
                self._dispatch_one(batch, mode)
            except BaseException as e:
                if req.poison is not None:
                    telemetry.inc("engine_poisoned_requests_total")
                self._trace_error(req, e)
                _sync.resolve_future(req.fut, exception=e,
                                     site="engine.bisect")
            return
        mid = len(batch) // 2
        for half in (batch[:mid], batch[mid:]):
            # each bisection level gets one span per traced request,
            # linked to the request's previous (failed) level so the
            # waterfall shows the isolation search (round 17)
            spans: dict = {}
            for r in half:
                if r.trace is not None:
                    sp = r.trace.child("engine.bisect", size=len(half))
                    prev = None if _prev is None else _prev.get(id(r))
                    sp.link(prev if prev is not None else r.trace,
                            kind="bisect")
                    spans[id(r)] = sp
            try:
                self._dispatch_one(half, mode)
            except BaseException:
                for sp in spans.values():
                    sp.end(status="error")
                self._bisect(half, mode, _prev=spans)
            else:
                for sp in spans.values():
                    sp.end()

    def _sentinel_gate(self, amps, tick: int | None = None) -> None:
        """Check one dispatch result against the armed sentinel policy
        (no-op boolean when ``QUEST_SENTINEL`` is off); raises
        QuESTIntegrityError rather than letting a corrupt state reach its
        future. The ``state.corrupt`` injection visit happens here too, so
        SDC tests corrupt real results, not synthetic arrays. A ring
        retire passes the ISSUING dispatch's ordinal as ``tick`` so the
        sentinel tick tracks the batch being checked, not whatever the
        host has issued since."""
        if self._finalize is not None:
            # finalized results (shot tables, expectations) are not
            # amps-shaped states -- the integrity sentinels don't apply
            return amps
        if not _sentinel.enabled():
            return amps
        findings = _sentinel.check_amps(
            amps, density=self.circuit.is_density_matrix,
            n=self.circuit.num_qubits,
            mesh=self._mesh if self.sharded else None,
            tick=self._dispatches if tick is None else tick,
            where="engine.dispatch")
        if findings:
            raise QuESTIntegrityError(
                "dispatch result breached the integrity sentinels: "
                + "; ".join(f.code for f in findings),
                "Engine._dispatch", findings=findings)
        return amps

    def _maybe_corrupt(self, amps):
        if self._finalize is not None:
            # the corrupt injector flips amplitude words; a finalized
            # result is an arbitrary pytree -- skip (chaos scenarios
            # exercise the amps-returning routes)
            return amps
        if not _faults.enabled():
            return amps
        from ..resilience import guard as _guard
        return _guard.corrupt_amps(amps)

    def _lane(self, out, i: int):
        """Lane ``i`` of a vmap batch result: a plain slice for the
        amps-returning path, a tree_map'd slice when ``finalize`` made the
        result an arbitrary pytree (e.g. ``{"shots": ..., "expec": ...}``)."""
        if self._finalize is None:
            return out[i]
        import jax
        return jax.tree_util.tree_map(lambda a: a[i], out)

    def _trace_done(self, req, rt0: float, rt1: float) -> None:
        """Record the resolve phase; finish engine-owned traces (adopted
        pool children only close their span -- the pool's settle owns
        finishing the root)."""
        tr = req.trace
        if tr is None:
            return
        tr.phase("resolve", rt0, rt1 - rt0)
        if tr.owns_root:
            telemetry.finish_trace(tr)
        else:
            tr.end()

    def _trace_error(self, req, exc) -> None:
        """Mark a request's trace failed: errored traces are ALWAYS
        retained (the QUEST_TRACE=errors contract), so every resolve-with-
        exception site pairs with this."""
        tr = req.trace
        if tr is None:
            return
        if tr.owns_root:
            telemetry.finish_trace(tr, error=type(exc).__name__)
        else:
            tr.event("error", type=type(exc).__name__)
            tr.end(status="error")

    def _traced_replay(self, req, x, t_start):
        """One per-request replay with compile/dispatch/device phase
        attribution: the retrace-counter delta decides whether the call
        paid a compile, and an explicit block_until_ready (the device
        phase) separates dispatch from device drain. The launch phase
        starts at the caller-supplied ``t_start`` and the device-sync
        timestamp is returned so consecutive phase windows tile exactly
        (bookkeeping such as the counter reads lands inside a phase, not
        between two). Tracing-armed requests only -- the untraced path
        never blocks."""
        import jax

        before = telemetry.counter_value("engine_trace_total",
                                         kind="param_replay")
        res = self._maybe_corrupt(
            x.with_values(self.initial_amps + 0, req.values))
        t_d = time.perf_counter()
        jax.block_until_ready(res)
        t_e = time.perf_counter()
        retraced = telemetry.counter_value(
            "engine_trace_total", kind="param_replay") > before
        req.trace.phase("compile" if retraced else "dispatch",
                        t_start, t_d - t_start)
        req.trace.phase("device", t_d, t_e - t_d)
        return res, t_e

    def _dispatch_sequential(self, batch: list) -> None:
        tracing = any(req.trace is not None for req in batch)
        t_a = time.perf_counter() if tracing else 0.0
        x = self._exec1()
        if tracing:
            t_b = time.perf_counter()
            for req in batch:
                if req.trace is not None:
                    req.trace.phase("cache_lookup", t_a, t_b - t_a)
        for req in batch:
            if req.poison is not None:
                raise PoisonedRequestFault("engine.request", req.poison)
            # one param-replay program launch per request (host-side
            # count: inside the program it would count traces)
            telemetry.inc("device_dispatch_total",
                          route=self._route or "engine_param")
            if req.trace is None:
                res = self._maybe_corrupt(
                    x.with_values(self.initial_amps + 0, req.values))
                self._sentinel_gate(res)
                _sync.resolve_future(req.fut, result=res,
                                     site="engine.dispatch")
                continue
            # sequential replays are serial: time spent on earlier batch
            # mates is this request's in-batch queueing
            t_i = time.perf_counter()
            if t_i > t_b:
                req.trace.phase("queue_wait", t_b, t_i - t_b)
            res, t_e = self._traced_replay(req, x, t_i)
            self._sentinel_gate(res)
            # trace bookkeeping BEFORE the resolution: a woken waiter
            # must observe its trace already finished (the pool's settle
            # callback runs inside resolve_future and copies the phase
            # vector when it closes the root)
            self._trace_done(req, t_e, time.perf_counter())
            _sync.resolve_future(req.fut, result=res,
                                 site="engine.dispatch")

    def _dispatch_vmap(self, batch: list, defer: bool = False) -> bool:
        import jax.numpy as jnp

        for req in batch:
            # an injected poisoned request fails the whole batched program
            # (the real-world analogue: one NaN-producing parameter set or
            # device-rejected lane) -- _bisect isolates it
            if req.poison is not None:
                raise PoisonedRequestFault("engine.request", req.poison)
        traced = [req for req in batch if req.trace is not None]
        if not self._lifted.slots:
            # value-free structure: every request computes the same state
            telemetry.inc("device_dispatch_total",
                          route=self._route or "engine_param")
            t_a = time.perf_counter() if traced else 0.0
            x = self._exec1()
            if traced:
                import jax

                t_b = time.perf_counter()
                before = telemetry.counter_value("engine_trace_total",
                                                 kind="param_replay")
                out = self._maybe_corrupt(
                    x.with_values(self.initial_amps + 0, ()))
                t_c = time.perf_counter()
                jax.block_until_ready(out)
                t_d = time.perf_counter()
                retraced = telemetry.counter_value(
                    "engine_trace_total", kind="param_replay") > before
                for req in traced:
                    tr = req.trace
                    tr.phase("cache_lookup", t_a, t_b - t_a)
                    tr.phase("compile" if retraced else "dispatch",
                             t_b, t_c - t_b)
                    tr.phase("device", t_c, t_d - t_c)
            else:
                out = self._maybe_corrupt(
                    x.with_values(self.initial_amps + 0, ()))
            self._sentinel_gate(out)
            rt = time.perf_counter() if traced else 0.0
            for req in batch:
                if req.trace is not None:
                    self._trace_done(req, rt, time.perf_counter())
                _sync.resolve_future(req.fut, result=out,
                                     site="engine.dispatch")
            return False
        # async pipeline: ring admission (eager retires, the in-flight
        # bound, the serial-issue gate) already ran in _dispatch, outside
        # the dispatch watchdog -- this method only assembles and issues
        defer = defer and self.async_depth > 0
        # host-side batch assembly (pad to the fixed vmap shape): on the
        # traced path this lands in the dispatch phase. The per-slot
        # stacks are NUMPY, not jnp -- each jnp.stack is its own device
        # computation, the PJRT CPU client bounds in-flight computations
        # (32), and a slot-rich ansatz issuing one stack per slot behind
        # an in-flight batch blows that bound: the "async" issue then
        # silently blocks for a full device execution. Host stacking
        # enters the program as plain transfers (bitwise the same lanes)
        # and keeps the whole batch at ~two enqueued computations.
        t_asm = time.perf_counter() if traced else 0.0
        pad = self.max_batch - len(batch)
        vals = [req.values for req in batch] + [batch[-1].values] * pad
        stacked = tuple(np.stack([np.asarray(v[k]) for v in vals])
                        for k in range(len(self._lifted.slots)))
        amps_b = jnp.repeat(self.initial_amps[None], self.max_batch, axis=0)
        t_a = time.perf_counter() if traced else 0.0
        fnB = self._execB()
        if traced:
            import jax

            t_b = time.perf_counter()
            before = telemetry.counter_value("engine_trace_total",
                                             kind="param_replay")
        # the whole coalesced batch is ONE vmap program launch
        telemetry.inc("device_dispatch_total",
                      route=self._route or "engine_vmap")
        out = fnB(amps_b, stacked)
        if defer:
            # ASYNC ISSUE: park the in-flight result on the completion
            # ring and return to coalescing -- the device executes batch k
            # while the host assembles batch k+1. Futures resolve at
            # retire; so do health credit and latency observation.
            t_c = time.perf_counter() if traced else 0.0
            dev_t0 = 0.0
            if traced:
                retraced = telemetry.counter_value(
                    "engine_trace_total", kind="param_replay") > before
                # jit COMPILE is synchronous at the call site, so a
                # retraced launch begins device work only at t_c; a warm
                # launch overlaps device execution with the launch-call
                # window [t_b, t_c] -- the dispatch and device phases
                # then legitimately overlap there, and the QT704 union
                # rule counts the shared window once
                dev_t0 = t_c if retraced else t_b
                for req in traced:
                    tr = req.trace
                    tr.phase("cache_lookup", t_a, t_b - t_a)
                    tr.phase("dispatch", t_asm, t_a - t_asm)
                    tr.phase("compile" if retraced else "dispatch",
                             t_b, t_c - t_b)
            self._ring.append([out, batch, self._dispatches, dev_t0, None])
            telemetry.set_gauge("engine_async_inflight", len(self._ring))
            return True
        if traced:
            t_c = time.perf_counter()
            jax.block_until_ready(out)
            t_d = time.perf_counter()
            retraced = telemetry.counter_value(
                "engine_trace_total", kind="param_replay") > before
            for req in traced:
                tr = req.trace
                tr.phase("cache_lookup", t_a, t_b - t_a)
                tr.phase("dispatch", t_asm, t_a - t_asm)
                tr.phase("compile" if retraced else "dispatch",
                         t_b, t_c - t_b)
                tr.phase("device", t_c, t_d - t_c)
        elif self.async_depth == 0:
            # TRUE synchronous baseline: async_depth=0 drains each batch
            # before resolving it -- the batcher never runs ahead of the
            # device, the A/B floor the serve bench compares the
            # completion ring against
            import jax
            jax.block_until_ready(out)
        # each request's resolve phase runs from the device sync to ITS
        # resolution: lane extraction (a compiled slice on the first
        # run), the sentinel gate, and the wait behind earlier lanes.
        # The windows deliberately overlap -- phases tile each request's
        # own end-to-end latency, they are not a global partition.
        for i, req in enumerate(batch):
            lane = self._maybe_corrupt(self._lane(out, i))
            self._sentinel_gate(lane)
            if req.trace is not None:
                self._trace_done(req, t_d, time.perf_counter())
            _sync.resolve_future(req.fut, result=lane,
                                 site="engine.dispatch")
        return False

    def _fail_batch(self, batch: list, exc, *, site: str) -> None:
        """Resolve every still-pending future in ``batch`` with ``exc``
        (already-resolved lanes -- e.g. the ones a retire served before a
        later lane breached -- are left alone)."""
        for req in batch:
            if req.fut.done():
                continue
            self._trace_error(req, exc)
            _sync.resolve_future(req.fut, exception=exc, site=site)

    def _ring_head_ready(self) -> bool:
        """Non-blocking poll: has the device finished the OLDEST in-flight
        batch? Drives eager retirement -- the batcher resolves completed
        work between issues instead of parking it until the ring's
        backpressure bound forces a (then-instant) sync. A buffer without
        a readiness probe counts as ready: retiring it blocks no longer
        than the probe-less sync path always did."""
        out = self._ring[0][0]
        probe = getattr(out, "is_ready", None)
        if probe is None:
            return True
        try:
            return bool(probe())
        except Exception:  # pragma: no cover - deleted/donated buffer
            return True

    def _issue_serial(self) -> bool:
        """Whether issue must wait for the in-flight batch's device sync.

        XLA:CPU has no private execution stream: two concurrently
        enqueued batch programs EXECUTE concurrently, timesharing the
        same host cores (measured ~20% per-batch throughput penalty with
        two large batches in flight), so running ahead of the device
        costs more than the host time it hides. On CPU the pipeline
        therefore still overlaps assembly, coalescing and resolution
        with device execution but never two batch programs with each
        other. Stream-ordered backends (TPU/GPU) queue enqueued work in
        hardware order -- there ``async_depth`` alone governs."""
        s = self._serial
        if s is None:
            import jax

            s = self._serial = jax.default_backend() == "cpu"
        return s

    def _spare_core(self) -> bool:
        """Whether a host core is free while the device executes -- the
        precondition for deferring resolution past the next issue. On a
        single-core host the batcher thread and the XLA execution
        thread timeshare one core, so "overlapped" host work is merely
        starved work; there the pipeline resolves before issuing."""
        c = self._cores
        if c is None:
            c = self._cores = os.cpu_count() or 1
        return c > 1

    def _ring_admit(self) -> None:
        """Make room on the completion ring before an issue. Eagerly
        retires whatever the device already finished (non-blocking
        probe), enforces the ``async_depth`` in-flight bound, and -- on
        serial-issue backends -- device-syncs the head: the proof of
        completion must precede the next issue. With a spare host core
        the head stays UNresolved so its resolution work overlaps the
        next issue (see :meth:`_ring_settle`); on a single-core host it
        resolves right here (see :meth:`_spare_core`).
        Batcher-thread-only; runs outside the dispatch watchdog, each
        blocking sync bounded by its own ``engine.retire`` deadline."""
        while self._ring and self._ring_head_ready():
            self._retire_oldest()
        while len(self._ring) >= self.async_depth:
            self._retire_oldest()
        if self._issue_serial():
            # device still busy (the eager loop above would have caught
            # an idle one): wait for it bounded. With a spare core the
            # entry is synced but NOT resolved -- resolution after the
            # next issue keeps the device fed, the host work runs on
            # another core. On a single-core host that deferral inverts:
            # the settling thread is starved by the very execution it
            # "overlaps" (measured: future resolution drifting ~0.5s into
            # a 2.3s batch at 20q), so resolve-before-issue -- the
            # latency-optimal order when host and device share the core.
            defer_resolve = self._spare_core()
            while self._ring and self._ring[0][4] is None:
                self._retire_oldest(sync_only=defer_resolve)

    def _ring_settle(self) -> None:
        """Resolve ring entries whose device work admission already
        proved complete -- called right AFTER an issue, so lane
        extraction, the sentinel gate and future resolution run while
        the just-issued batch executes."""
        while self._ring and self._ring[0][4] is not None:
            self._retire_oldest()

    def _drop_entry(self, entry) -> None:
        """Remove a failed entry from the ring if it is still the head
        (resolve-stage failures already popped it)."""
        if self._ring and self._ring[0] is entry:
            self._ring.popleft()
            telemetry.set_gauge("engine_async_inflight", len(self._ring))

    def _retire_oldest(self, *, sync_only: bool = False) -> bool:
        """Retire the OLDEST completion-ring entry: device-sync its
        in-flight batch and resolve its futures, lane by lane, through
        the same corrupt/sentinel/trace gates as a synchronous dispatch.
        ``sync_only=True`` is the serial-issue admission step: it
        device-syncs the head IN PLACE (same bounded wait, failures
        attributed identically) but leaves it on the ring unresolved,
        for a post-issue :meth:`_ring_settle`. Never raises -- every
        failure mode resolves the ENTRY's futures typed (hang ->
        quarantine, sentinel breach -> degrade/quarantine, anything
        else -> the synchronous bisection ladder re-dispatches), so a
        retire-time fault is attributed to the batch that actually
        failed, never to whatever the host happens to be issuing (the
        no-cross-batch-misattribution contract the chaos
        ``async_dispatch_fault`` scenario proves). Returns False when the
        ring is empty. Batcher-thread-only, like the ring itself."""
        if not self._ring:
            return False
        import jax

        from ..resilience import guard as _guard
        entry = self._ring[0]
        out, batch, tick, dev_t0, t_ready = entry
        traced = [r.trace for r in batch if r.trace is not None]
        if traced:
            telemetry.set_current_trace(traced)
        # the sync is a blocking boundary exactly like the dispatch is
        _sync.guard_blocking("engine.retire")
        outcome = "ok"
        retired = True
        try:
            with telemetry.span("engine.retire", batch=len(batch),
                                inflight=len(self._ring) - 1,
                                stage="resolve" if t_ready else "sync"):
                if t_ready is None:
                    _guard.device_sync(lambda: jax.block_until_ready(out))
                    t_ready = entry[4] = time.perf_counter()
                    for req in batch:
                        if req.trace is not None and dev_t0:
                            req.trace.phase("device", dev_t0,
                                            t_ready - dev_t0)
                if sync_only:
                    # proven complete, left on the ring: the entry's
                    # resolution is deferred past the next issue
                    retired = False
                    return True
                self._ring.popleft()
                telemetry.set_gauge("engine_async_inflight", len(self._ring))
                for i, req in enumerate(batch):
                    lane = self._maybe_corrupt(self._lane(out, i))
                    self._sentinel_gate(lane, tick=tick)
                    if req.trace is not None:
                        self._trace_done(req, t_ready, time.perf_counter())
                    _sync.resolve_future(req.fut, result=lane,
                                         site="engine.retire")
        except QuESTHangError as e:
            # the device wedged AFTER issue: same quarantine as a
            # synchronous hang, charged to this entry's requests
            outcome = "hang"
            self._drop_entry(entry)
            self._note_breach(hang=True)
            self._fail_batch(batch, e, site="engine.retire")
        except QuESTIntegrityError as e:
            outcome = "integrity"
            self._drop_entry(entry)
            self._note_breach(hang=False)
            self._fail_batch(batch, e, site="engine.retire")
        except Exception:
            # a device-side error surfacing at the sync: re-dispatch the
            # entry's unresolved requests through the SYNCHRONOUS
            # bisection ladder (defer=False), so healthy lanes complete
            # bit-identically and poisoned ones fail typed
            outcome = "error"
            self._drop_entry(entry)
            pending = [r for r in batch if not r.fut.done()]
            if pending:
                self._bisect(pending, "vmap")
        except BaseException as e:  # teardown must not hang waiters
            outcome = "error"
            self._drop_entry(entry)
            self._fail_batch(batch, e, site="engine.retire")
        else:
            if retired:
                self._note_clean()
        finally:
            if retired:
                telemetry.inc("engine_async_retires_total", outcome=outcome)
            if traced:
                telemetry.clear_current_trace()
        if retired:
            now = time.perf_counter()
            for req in batch:
                telemetry.observe("engine_request_latency_seconds",
                                  now - req.t0)
        return True
