"""Serving engine: parameterized replay, plan/executable cache, and
micro-batched ensemble execution.

The reference simulator compiles nothing and serves one caller; this
package is the serving layer the compiled-``Circuit`` execution model
needs to handle sweep/ensemble traffic (ROADMAP north star):

- :mod:`.params` -- :class:`Param` placeholders (alias ``P``) making gate
  angles/Complex scalars *runtime arguments* of one compiled replay, plus
  the constant-lifting canonicalisation behind structure fingerprints.
- :mod:`.cache` -- the structure fingerprint, the bounded telemetered LRU
  every compiled replay routes through, and JAX persistent-compilation-
  cache wiring (``QUEST_COMPILE_CACHE``) so cold starts survive restarts.
- :mod:`.engine` -- :class:`Engine`: ``submit(params) -> Future`` with a
  micro-batcher coalescing requests into one ``vmap``-over-params program
  (unsharded) or a donated-buffer sequential replay (sharded).
- :mod:`.pool` -- :class:`EnginePool`: N replicas behind health-aware,
  structure-affine routing, with quarantine failover (zero dropped
  futures, bit-identical recovery), hedged dispatch, and warm replacement
  spawning from a fingerprint manifest.
- :mod:`.admission` -- per-tenant token-bucket quotas with a
  high-priority reserve band in front of the pool
  (``QuESTBackpressureError`` with ``reason="quota"``).

Quickstart::

    from quest_tpu.circuits import Circuit
    from quest_tpu.engine import Engine, P

    c = Circuit(20)
    for q in range(20):
        c.rotateZ(q, P(f"theta{q}"))
    ...
    with Engine(c, env, max_batch=8) as eng:
        futs = eng.submit_many([{f"theta{q}": v for q, v in enumerate(vec)}
                                for vec in sweep])
        states = [f.result() for f in futs]

See docs/serving.md for lifecycle, batching knobs and cache sizing.
"""

import os as _os

from .admission import (  # noqa: F401
    PRIORITIES, AdmissionController, TokenBucket,
)
from .cache import (  # noqa: F401
    LRUCache, enable_persistent_cache, executables, structure_fingerprint,
)
from .engine import Engine  # noqa: F401
from .params import (  # noqa: F401
    LiftedTape, P, Param, ParamExecutable, Slot, bind, lift_tape,
)
from .pool import EnginePool  # noqa: F401

__all__ = [
    "Param", "P", "ParamExecutable", "LiftedTape", "Slot", "lift_tape",
    "bind", "LRUCache", "executables", "structure_fingerprint",
    "enable_persistent_cache", "Engine", "EnginePool",
    "AdmissionController", "TokenBucket", "PRIORITIES",
]

# opt-in cross-restart compile cache: wire it up as early as possible so
# the first Engine/Circuit compile of the process already persists
if _os.environ.get("QUEST_COMPILE_CACHE"):  # pragma: no cover - env wiring
    enable_persistent_cache()
