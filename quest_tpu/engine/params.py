"""Parameterized tapes: runtime gate angles instead of trace-time constants.

The reference (and the eager API here) receives every rotation angle as a
host float and bakes the resulting 2x2 matrix into the kernel launch; a
``Circuit`` tape goes further and bakes it into the jitted XLA program, so a
parameter sweep (VQE/QAOA, or many users submitting variants of one ansatz)
re-runs the whole trace/fuse/compile pipeline per parameter set -- at 34q
the compile dwarfs the execution it guards.

This module makes values *runtime arguments* of one compiled replay:

- :class:`Param` (alias ``P``) is a named placeholder recordable anywhere a
  gate angle or ``Complex`` scalar goes on a tape:
  ``circ.rotateZ(0, P("theta"))``.
- :func:`lift_tape` canonicalises a recorded tape into a :class:`LiftedTape`
  whose *value slots* cover every ``Param`` AND every plain float/complex
  constant sitting at a liftable position (the ``_LIFTABLE`` registry below:
  the angle/Complex-scalar arguments of the rotation and phase family).
  Constants elsewhere (unitary matrices, channel probabilities, qubit
  indices) stay baked structure.
- :func:`materialize_entry` substitutes the slot values back at replay time,
  inside the jit trace, so gate matrices are assembled from *traced* scalars
  (``matrices.py`` carries the traced assembly branches) and one executable
  replays for arbitrary value vectors -- including through a fused Pallas
  plan, where parameterized entries ride as apply-time-assembled barriers
  between the static kernel runs (plan structure never depends on values).

Two tapes that differ only in lifted values produce the SAME
:func:`quest_tpu.engine.cache.structure_fingerprint`, which is what lets the
executable cache serve "same ansatz, different angles" traffic with zero
recompiles (docs/serving.md).

Besides the ``'real'``/``'complex'`` angle slots there is a third kind,
``'seed'``: an integer PRNG-seed slot (uint32 on device) carried by
trajectory-noise entries (quest_tpu/trajectories). Seed positions lift
*plain ints* too -- a seed is always a runtime value, never structure -- so
T trajectories of one noisy circuit share a single compiled replay and
differ only in their stacked seed lanes (docs/trajectories.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = ["Param", "P", "LiftedTape", "Slot", "ParamExecutable",
           "lift_tape", "lift_slot_census", "bind", "materialize_entry",
           "materialize_tape", "has_params", "is_value"]


class Param:
    """Named placeholder for a runtime gate parameter.

    Record it anywhere a gate angle / ``Complex`` scalar goes::

        from quest_tpu.engine import P
        circ.rotateZ(0, P("theta"))

    The value is supplied per execution (``Engine.submit({"theta": 0.3})``
    or ``Circuit.parameterized()(amps, {"theta": 0.3})``); the compiled
    executable is value-independent. The same name may appear in several
    slots -- every occurrence receives the one bound value.
    """

    __slots__ = ("name",)

    def __init__(self, name: str):
        if not isinstance(name, str) or not name:
            raise ValueError("Param name must be a non-empty string")
        self.name = name

    def __repr__(self):
        return f"P({self.name!r})"

    def __eq__(self, other):
        return isinstance(other, Param) and other.name == self.name

    def __hash__(self):
        return hash(("quest_tpu.Param", self.name))


#: short alias matching the ISSUE's recording idiom: ``rotateZ(q, P("t"))``
P = Param


#: tape-arg positions (qureg excluded) and kwarg names whose values are
#: liftable runtime scalars, per API function: the angle / Complex-scalar
#: arguments of the rotation, phase and compact-unitary family. Everything
#: else a tape entry carries (targets, controls, unitary matrices, channel
#: probabilities -- whose superoperators are assembled host-side) is
#: structure and stays baked.
_REAL, _CPLX, _SEED = "real", "complex", "seed"
_LIFTABLE = {
    # trajectory noise: the per-trajectory PRNG seed is a runtime uint32
    # slot -- T trajectories replay one compiled program with T seed
    # streams stacked by the engine's vmap batcher (quest_tpu/trajectories)
    "applyTrajectoryKraus": {2: _SEED, "seed": _SEED},
    # mid-circuit measurement (round 19, sampling.measure): the draw seed
    # is the same runtime uint32 slot kind -- S sampled requests replay
    # one compiled program with S seed streams
    "applyMidMeasurement": {1: _SEED, "seed": _SEED},
    "phaseShift": {1: _REAL, "angle": _REAL},
    "controlledPhaseShift": {2: _REAL, "angle": _REAL},
    "multiControlledPhaseShift": {1: _REAL, "angle": _REAL},
    "rotateX": {1: _REAL, "angle": _REAL},
    "rotateY": {1: _REAL, "angle": _REAL},
    "rotateZ": {1: _REAL, "angle": _REAL},
    "rotateAroundAxis": {1: _REAL, "angle": _REAL},
    "controlledRotateX": {2: _REAL, "angle": _REAL},
    "controlledRotateY": {2: _REAL, "angle": _REAL},
    "controlledRotateZ": {2: _REAL, "angle": _REAL},
    "controlledRotateAroundAxis": {2: _REAL, "angle": _REAL},
    "multiRotateZ": {1: _REAL, "angle": _REAL},
    "multiControlledMultiRotateZ": {2: _REAL, "angle": _REAL},
    "multiRotatePauli": {2: _REAL, "angle": _REAL},
    "multiControlledMultiRotatePauli": {3: _REAL, "angle": _REAL},
    "compactUnitary": {1: _CPLX, 2: _CPLX, "alpha": _CPLX, "beta": _CPLX},
    "controlledCompactUnitary": {2: _CPLX, 3: _CPLX,
                                 "alpha": _CPLX, "beta": _CPLX},
}


def is_value(x) -> bool:
    """True for the scalar types the lifter treats as runtime values when
    they sit at a liftable position: Params, floats and complex numbers
    (ints and bools are always structure -- they index qubits)."""
    if isinstance(x, Param):
        return True
    if isinstance(x, bool) or isinstance(x, (int, np.integer)):
        return False
    return isinstance(x, (float, complex, np.floating, np.complexfloating))


def _is_seed_value(x) -> bool:
    """Lifting rule for ``'seed'``-kind positions: unlike angle positions
    (where ints are structure), a plain integer at a seed position IS the
    runtime value -- it lifts to an anonymous uint32 slot so plan structure
    never depends on the seed."""
    if isinstance(x, Param):
        return True
    return (isinstance(x, (int, np.integer))
            and not isinstance(x, bool))


def has_params(args, kwargs=None) -> bool:
    """True when a tape entry's arguments carry a :class:`Param` anywhere
    (one level into tuples/lists) -- the fusion planner's pre-check: such
    entries are apply-time-assembled barriers, never spy-captured."""
    items = list(args) + list((kwargs or {}).values())
    for x in items:
        if isinstance(x, Param):
            return True
        if isinstance(x, (tuple, list)) and any(
                isinstance(e, Param) for e in x):
            return True
    return False


@dataclass(frozen=True)
class Slot:
    """One runtime value slot of a lifted tape. ``name`` is None for an
    anonymous slot (a lifted constant, replayed with ``default`` unless the
    caller rebinds the whole vector); named slots come from :class:`Param`
    placeholders and MUST be bound at execution."""
    index: int
    kind: str                      # 'real' | 'complex' | 'seed'
    name: Optional[str] = None
    default: Optional[complex] = None


class _SlotRef:
    """Placeholder living in a lifted entry's argument template."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __repr__(self):
        return f"<slot {self.index}>"


@dataclass(frozen=True)
class LiftedTape:
    """A tape with its runtime values factored out: ``entries`` are
    ``(fn, args, kwargs)`` templates holding :class:`_SlotRef` markers,
    ``slots`` describes each value position in template order."""
    entries: tuple
    slots: tuple

    @property
    def param_names(self) -> tuple:
        """Ordered unique Param names (first-appearance order)."""
        seen = []
        for s in self.slots:
            if s.name is not None and s.name not in seen:
                seen.append(s.name)
        return tuple(seen)


def lift_tape(tape) -> LiftedTape:
    """Factor a recorded tape's runtime values into slots (see module
    docstring for the lifting rule). A :class:`Param` at a position the
    registry doesn't cover is an error -- there is no traced assembly route
    for it (e.g. a channel probability, whose superoperator is built
    host-side)."""
    from ..validation import QuESTError

    entries = []
    slots: list[Slot] = []

    def lift_value(v, kind):
        if isinstance(v, Param):
            slots.append(Slot(len(slots), kind, name=v.name))
        else:
            slots.append(Slot(len(slots), kind, default=v))
        return _SlotRef(len(slots) - 1)

    def liftable(v, kind):
        if kind is None:
            return False
        if kind == _SEED:
            return _is_seed_value(v)
        return is_value(v)

    for fn, args, kwargs in tape:
        spec = _LIFTABLE.get(getattr(fn, "__name__", ""), {})
        new_args = []
        for i, v in enumerate(args):
            kind = spec.get(i)
            if liftable(v, kind):
                new_args.append(lift_value(v, kind))
            elif isinstance(v, Param) or (
                    isinstance(v, (tuple, list))
                    and any(isinstance(e, Param) for e in v)):
                raise QuESTError(
                    f"Param is not supported at argument {i} of "
                    f"'{getattr(fn, '__name__', fn)}' -- only gate angles "
                    "and Complex scalars of the rotation/phase family can "
                    "be runtime parameters")
            else:
                new_args.append(v)
        new_kwargs = {}
        for k, v in kwargs.items():
            kind = spec.get(k)
            if liftable(v, kind):
                new_kwargs[k] = lift_value(v, kind)
            elif isinstance(v, Param):
                raise QuESTError(
                    f"Param is not supported for keyword '{k}' of "
                    f"'{getattr(fn, '__name__', fn)}'")
            else:
                new_kwargs[k] = v
        entries.append((fn, tuple(new_args), new_kwargs))
    return LiftedTape(tuple(entries), tuple(slots))


def lift_slot_census(tape) -> tuple[int, int]:
    """``(anonymous, named)`` slot counts of ``lift_tape(tape)``: how many
    liftable positions carry constants vs ``Param`` placeholders. Anonymous
    slots are the executable-cache hazard -- structure-equal circuits that
    differ only in those constants cannot share a compiled program
    (engine/cache.structure_fingerprint bakes them) -- and the count is
    what the tape linter reports as QT003 (quest_tpu/analysis)."""
    slots = lift_tape(tuple(tape)).slots
    anon = sum(1 for s in slots if s.name is None)
    return anon, len(slots) - anon


def bind(lifted: LiftedTape, params=None, device: bool = True) -> tuple:
    """Resolve a lifted tape's slots to a values tuple -- the ``values``
    argument of the parameterized replay.

    ``params`` maps Param names to numbers (missing names raise); anonymous
    slots replay their recorded defaults. With ``device=True`` (the
    executable hot path) scalars are coerced to NUMPY 0-d arrays at the
    process float/complex width (f64/c128 under jax x64, else f32/c64) so
    the jit signature is stable across calls. Numpy, not jnp, on purpose:
    ``jnp.asarray(v, dtype=...)`` enqueues a convert_element_type
    COMPUTATION per scalar, and the PJRT CPU client bounds in-flight
    computations -- a slot-rich circuit binding behind an in-flight batch
    would block the SUBMITTER for a full device execution (the async
    dispatch pipeline then starves at one arrival per batch). A numpy
    scalar enters the program as a plain transfer at call time, has the
    identical abstract value (no retrace), and binds in microseconds no
    matter what the device is running. ``device=False`` returns plain
    Python scalars (a tape materialized with them replays through the
    constant/numpy assembly path -- the bit-identity baseline the tests
    compare against)."""
    import jax.numpy as jnp

    from ..validation import QuESTError

    params = params or {}
    rdt = jnp.result_type(float)
    cdt = jnp.result_type(complex)
    out = []
    for s in lifted.slots:
        if s.name is not None:
            if s.name not in params:
                missing = sorted({t.name for t in lifted.slots
                                  if t.name is not None
                                  and t.name not in params})
                raise QuESTError(
                    f"missing values for Params {missing}; got "
                    f"{sorted(params)}")
            v = params[s.name]
        else:
            v = s.default
        if s.kind == _SEED:
            # seeds are integer PRNG material: uint32 on the hot path (a
            # stable jit signature the vmap batcher can stack per lane), a
            # plain int on the host/constant path. int() first so the
            # engine's warmup binding (0.0 for every name) coerces cleanly.
            out.append(np.asarray(int(v), dtype=np.uint32) if device
                       else int(v))
        elif device:
            out.append(np.asarray(v, dtype=cdt if s.kind == _CPLX else rdt))
        else:
            out.append(complex(v) if s.kind == _CPLX else float(v))
    return tuple(out)


class ParamExecutable:
    """A compiled parameterized replay bound to one circuit's slot layout.

    The underlying ``fn(amps, values)`` may be SHARED across structure-equal
    circuits (it comes out of the executable LRU keyed by the structure
    fingerprint); this wrapper carries the owning circuit's
    :class:`LiftedTape` so named Params bind and anonymous slots default to
    that circuit's own recorded constants.
    """

    def __init__(self, fn, lifted: LiftedTape, fingerprint: str):
        self._fn = fn
        self.lifted = lifted
        self.fingerprint = fingerprint

    @property
    def param_names(self) -> tuple:
        return self.lifted.param_names

    def bind(self, params=None) -> tuple:
        """Resolve ``params`` (Param name -> number) to the values tuple."""
        return bind(self.lifted, params)

    def __call__(self, amps, params=None):
        """Replay onto ``amps`` (donated) with the given Param values."""
        return self._fn(amps, self.bind(params))

    def with_values(self, amps, values):
        """Replay with an already-bound values tuple (the Engine hot path)."""
        return self._fn(amps, values)


def materialize_entry(entry, values):
    """Substitute a lifted entry's slot markers with the bound (possibly
    traced) scalars: ``(fn, args, kwargs)`` ready to replay."""
    fn, args, kwargs = entry
    args = tuple(values[a.index] if isinstance(a, _SlotRef) else a
                 for a in args)
    if kwargs:
        kwargs = {k: values[v.index] if isinstance(v, _SlotRef) else v
                  for k, v in kwargs.items()}
    return fn, args, kwargs


def materialize_tape(lifted: LiftedTape, values) -> list:
    """The lifted tape with every slot substituted -- host scalars (from
    ``bind(..., device=False)``) give back a plain constant tape."""
    return [materialize_entry(e, values) for e in lifted.entries]
