"""JAX version compatibility seam.

The engine targets the current jax API (``jax.shard_map`` with the
``check_vma`` flag, ``pltpu.CompilerParams``); CI and some build hosts pin
older releases where those names live elsewhere (``jax.experimental
.shard_map.shard_map`` with ``check_rep``, ``pltpu.TPUCompilerParams``).
Every internal module imports the handful of drifting names from here so a
version bump is a one-file change.
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as pltpu

try:  # jax >= 0.6: top-level export, replication checker flag is check_vma
    from jax import shard_map as _shard_map
    _VMA_KW = "check_vma"
except ImportError:  # older jax: experimental module, flag is check_rep
    from jax.experimental.shard_map import shard_map as _shard_map
    _VMA_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None):
    """``jax.shard_map`` with the replication-checker keyword translated to
    whatever this jax release calls it."""
    kwargs = {}
    if check_vma is not None:
        kwargs[_VMA_KW] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


#: Mosaic compiler-params dataclass (renamed TPUCompilerParams ->
#: CompilerParams upstream)
CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams


def abstract_mesh(shape: tuple, axis_names: tuple):
    """``jax.sharding.AbstractMesh`` across the ctor-signature change
    (new: (shape, axis_names); old: one (name, size) shape_tuple)."""
    from jax.sharding import AbstractMesh

    try:
        return AbstractMesh(tuple(shape), tuple(axis_names))
    except (TypeError, ValueError):
        return AbstractMesh(tuple(zip(axis_names, shape)))
