"""Scalar reductions: inner products, norms, purity, fidelity, distances.

Reference kernels: statevec_calcInnerProductLocal + MPI_Allreduce
(``QuEST_cpu_distributed.c:35-51``), calcTotalProb with Kahan summation
(``:90-119``), densmatr purity/fidelity/HS-distance/inner-product loops
(``QuEST_cpu.c:878-1130``). Each is a fused elementwise + ``jnp.sum`` here;
on sharded inputs XLA emits local reduce + psum (the Allreduce analogue).

States are planar (2, 2^n) float arrays; results are real scalars or (re, im)
pairs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _acc(x):
    return x.astype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)


def _pairwise_sum(flat):
    """Pairwise (cascade) summation: rounding error grows O(log N) instead
    of naive accumulation's O(N). ADJACENT pairing (2i, 2i+1) keeps every
    add shard-local on block-sharded inputs -- a front/back-half split would
    cross shard boundaries and turn each reduction into O(N) collective
    traffic. Total cost is ~2x one bandwidth pass, like a plain sum."""
    m = flat.shape[0]
    while m > 1 and m % 2 == 0:
        flat = flat.reshape(-1, 2).sum(axis=-1)
        m //= 2
    return jnp.sum(flat)


def _pairwise_sum_rows(x):
    """Rowwise pairwise (cascade) summation over the LAST axis of a 2-D
    array: the marginal-group analogue of :func:`_pairwise_sum`, same
    O(log N) error growth and same adjacent (2i, 2i+1) pairing so every
    add stays shard-local on block-sharded rows."""
    m = x.shape[-1]
    while m > 1 and m % 2 == 0:
        x = x.reshape(x.shape[0], -1, 2).sum(axis=-1)
        m //= 2
    return jnp.sum(x, axis=-1)


def _csum(x):
    """Compensated reduction of ``x`` (any shape).

    The reference protects its f32/f64 norm and trace accumulations with
    Kahan summation precisely because low precision drifts over 2^N terms
    (statevec_calcTotalProb, QuEST_cpu_distributed.c:62-119). Here: with
    x64 enabled, accumulate in f64 (error ~1e-16, strictly better than f32
    Kahan); with x64 off (the on-TPU f32 configuration), pairwise-sum --
    measured 2^24-amp calcTotalProb error ~1e-7 vs ~1e-5 for the naive
    jnp.sum this replaces."""
    if jax.config.jax_enable_x64:
        return jnp.sum(x.astype(jnp.float64))
    return _pairwise_sum(x.reshape(-1))


def csum_rows(x):
    """Compensated ROWWISE reduction of a 2-D array over its last axis --
    the marginal-group accumulation of ``ops.measure._group_outcome_probs``
    (round 19: the bare ``.sum(axis=1)`` it replaces drifted ~1e-5 at 20q+
    f32 marginals while the total-probability path already cascaded).
    Same policy as :func:`_csum`: f64 accumulate when x64 is on, adjacent-
    pair cascade otherwise."""
    if jax.config.jax_enable_x64:
        return jnp.sum(x.astype(jnp.float64), axis=-1)
    return _pairwise_sum_rows(x)


@jax.jit
def inner_product(bra, ket):
    """<bra|ket> with bra conjugated (statevec_calcInnerProduct); returns
    a (re, im) pair."""
    re = _csum(bra[0] * ket[0] + bra[1] * ket[1])
    im = _csum(bra[0] * ket[1] - bra[1] * ket[0])
    return re, im


@jax.jit
def total_prob_statevec(amps):
    """sum |amp|^2 (statevec_calcTotalProb, Kahan in the reference)."""
    return _csum(amps[0] * amps[0] + amps[1] * amps[1])


@partial(jax.jit, static_argnames=("n",))
def total_prob_density(amps, *, n: int):
    """Re(trace(rho)) (densmatr_calcTotalProb)."""
    dim = 1 << n
    return _csum(jnp.diagonal(amps.reshape(2, dim, dim)[0]))


@jax.jit
def purity_density(amps):
    """Tr(rho^2) = sum |rho_ij|^2 for Hermitian rho (densmatr_calcPurityLocal,
    QuEST_cpu.c:878)."""
    return _csum(amps[0] * amps[0] + amps[1] * amps[1])


@jax.jit
def density_inner_product(a, b):
    """Re(Tr(a^dagger b)) = sum Re(conj(a_i) b_i)
    (densmatr_calcInnerProductLocal, QuEST_cpu.c:975-1003)."""
    return _csum(a[0] * b[0] + a[1] * b[1])


@jax.jit
def hilbert_schmidt_distance(a, b):
    """sqrt(sum |a_ij - b_ij|^2) (densmatr_calcHilbertSchmidtDistance)."""
    d = a - b
    return jnp.sqrt(_csum(d[0] * d[0] + d[1] * d[1]))


@partial(jax.jit, static_argnames=("n",))
def density_fidelity(rho_amps, pure_amps, *, n: int):
    """<psi| rho |psi> real part (densmatr_calcFidelityLocal, QuEST_cpu.c:1007).

    rho flat layout is [col, row] so as a matrix mat[c, r] = rho(r, c);
    <psi|rho|psi> = sum_r conj(psi_r) (mat^T psi)_r.
    """
    dim = 1 << n
    m = rho_amps.reshape(2, dim, dim)
    mr, mi = m[0].T, m[1].T
    pr, pi = pure_amps[0], pure_amps[1]
    mm = partial(jnp.matmul, precision=jax.lax.Precision.HIGHEST)
    vr = mm(mr, pr) - mm(mi, pi)
    vi = mm(mr, pi) + mm(mi, pr)
    return _csum(pr * vr + pi * vi)


@jax.jit
def expec_diag_op_statevec(amps, elems):
    """sum |amp_i|^2 d_i, complex (re, im) (statevec_calcExpecDiagonalOp,
    QuEST_cpu_distributed.c:1612-1647)."""
    p = _acc(amps[0] * amps[0] + amps[1] * amps[1])
    return _csum(p * _acc(elems[0])), _csum(p * _acc(elems[1]))


@partial(jax.jit, static_argnames=("n",))
def expec_diag_op_density(amps, elems, *, n: int):
    """Tr(rho D) = sum_r rho[r,r] d_r, complex (densmatr_calcExpecDiagonalOp)."""
    dim = 1 << n
    t = amps.reshape(2, dim, dim)
    dr, di = _acc(jnp.diagonal(t[0])), _acc(jnp.diagonal(t[1]))
    er, ei = _acc(elems[0]), _acc(elems[1])
    return _csum(dr * er - di * ei), _csum(dr * ei + di * er)
