"""General gate application: the single kernel family every unitary reduces to.

The reference funnels all dense gates into
``statevec_multiControlledMultiQubitUnitary`` (gather 2^t amps / dense matvec /
scatter per task, ``QuEST_cpu.c:1840-1952``; per-gate MPI choreography
``QuEST_cpu_distributed.c:1526-1568``). The TPU-native formulation: view the
planar (2, 2^n) state as a grouped tensor (:mod:`.layout`), transpose the
touched 2-sized axes to the front, and hit them with 4 small real matmuls
(complex matmul over the planes) -- XLA tiles them onto the MXU and, when the
array is sharded over the top qubits, inserts the all-to-all /
collective-permute traffic that the reference hand-writes.

Matrix index convention matches the reference (multiQubitUnitary doc): the
row index r of the 2^t x 2^t matrix is ``sum_k bit(targets[k]) << k`` --
targets[0] is the least-significant bit of the matrix index. Matrices arrive
planar: shape (2, 2^t, 2^t).

All functions are pure and jitted with static qubit tuples: one XLA program
per (n, targets, controls) signature, reused across angles/matrices.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .layout import grouped_axes, inverse_permutation


def _plan(n, targets, controls):
    """Common transpose plan: (shape, perm, inv_perm) with the leading planar
    axis pinned at 0, controls then targets(MSB-first) next."""
    shape, axis_of = grouped_axes(n, tuple(targets) + tuple(controls))
    ctrl_axes = [axis_of[c] + 1 for c in controls]
    targ_axes = [axis_of[q] + 1 for q in reversed(targets)]
    rest = [a for a in range(1, len(shape) + 1) if a not in ctrl_axes and a not in targ_axes]
    perm = tuple([0] + ctrl_axes + targ_axes + rest)
    return (2,) + shape, perm, inverse_permutation(perm)


@partial(jax.jit, static_argnames=("n", "targets", "controls", "control_states", "conj"),
         donate_argnums=(0,))
def apply_matrix(amps, matrix, *, n: int, targets: tuple[int, ...],
                 controls: tuple[int, ...] = (), control_states: tuple[int, ...] = (),
                 conj: bool = False):
    """amps' = (ctrl-gated) M applied to ``targets`` of the n-qubit state.

    ``matrix`` is planar (2, 2^t, 2^t) and may be non-unitary (the apply*
    operator family reuses this). ``control_states`` optionally gives the
    required value of each control (default all-1, as
    multiStateControlledUnitary, QuEST.h:4448). ``conj=True`` applies the
    elementwise conjugate (density-matrix shadow op, QuEST.c:184-193).
    """
    t = len(targets)
    dim = 1 << t
    states = control_states if control_states else (1,) * len(controls)
    shape, perm, inv = _plan(n, targets, controls)
    tensor = amps.reshape(shape).transpose(perm)

    mr, mi = matrix[0], matrix[1]
    if conj:
        mi = -mi

    # full-f32 matmuls: XLA:TPU's default precision drops matmul inputs to
    # bf16, which is catastrophic for amplitude evolution (observed 3e-3 norm
    # drift in an 8-amp state). HIGHEST keeps the MXU in full precision.
    mm = partial(jnp.matmul, precision=jax.lax.Precision.HIGHEST)

    def matvec(sub):
        # sub: (2, 2, 2, ..., rest) with t leading 2-axes after the plane
        flat = sub.reshape(2, dim, -1)
        rr = mm(mr, flat[0]) - mm(mi, flat[1])
        ii = mm(mr, flat[1]) + mm(mi, flat[0])
        return jnp.stack([rr, ii]).reshape(sub.shape)

    if controls:
        idx = (slice(None),) + tuple(states)
        sub = tensor[idx]
        tensor = tensor.at[idx].set(matvec(sub))
    else:
        tensor = matvec(tensor)

    return tensor.transpose(inv).reshape(2, -1)


@partial(jax.jit, static_argnames=("n", "targets", "controls", "control_states"),
         donate_argnums=(0,))
def apply_x_class(amps, *, n: int, targets: tuple[int, ...],
                  controls: tuple[int, ...] = (), control_states: tuple[int, ...] = ()):
    """Multi-controlled multi-qubit NOT: pure axis reversal, no matmul.

    The reference's pauliX/controlledNot/multiControlledMultiQubitNot kernels
    (``QuEST_cpu.c``, dispatch ``QuEST_cpu_distributed.c:1109-1152``) are
    amplitude permutations; here each X flips one 2-sized axis, which XLA
    compiles to a strided copy (or a collective permute when the axis is
    sharded).
    """
    states = control_states if control_states else (1,) * len(controls)
    shape, perm, inv = _plan(n, targets, controls)
    tensor = amps.reshape(shape).transpose(perm)
    nc = len(controls)
    flip_axes = list(range(1 + nc, 1 + nc + len(targets)))

    if controls:
        idx = (slice(None),) + tuple(states)
        sub = tensor[idx]
        sub = jnp.flip(sub, axis=[a - nc for a in flip_axes])
        tensor = tensor.at[idx].set(sub)
    else:
        tensor = jnp.flip(tensor, axis=flip_axes)

    return tensor.transpose(inv).reshape(2, -1)


@partial(jax.jit, static_argnames=("n", "qb1", "qb2", "controls"), donate_argnums=(0,))
def apply_swap(amps, *, n: int, qb1: int, qb2: int, controls: tuple[int, ...] = ()):
    """SWAP as an axis transposition (reference: statevec_swapQubitAmps,
    ``QuEST_cpu.c:3850-3931``; distributed odd-parity pair exchange
    ``QuEST_cpu_distributed.c:1424-1459``). On a sharded axis this *is* the
    all-to-all the reference hand-codes -- and it is also the primitive the
    distributed scheduler uses to localise far targets."""
    shape, perm, inv = _plan(n, (qb1, qb2), controls)
    tensor = amps.reshape(shape).transpose(perm)
    nc = len(controls)
    a1, a2 = 1 + nc, 2 + nc  # the two target axes after the plan's transpose

    if controls:
        idx = (slice(None),) + (1,) * nc
        sub = tensor[idx]
        sp = list(range(sub.ndim))
        sp[a1 - nc], sp[a2 - nc] = sp[a2 - nc], sp[a1 - nc]
        tensor = tensor.at[idx].set(sub.transpose(sp))
    else:
        sp = list(range(tensor.ndim))
        sp[a1], sp[a2] = sp[a2], sp[a1]
        tensor = tensor.transpose(sp)

    return tensor.transpose(inv).reshape(2, -1)
