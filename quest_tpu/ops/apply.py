"""General gate application: the single kernel family every unitary reduces to.

The reference funnels all dense gates into
``statevec_multiControlledMultiQubitUnitary`` (gather 2^t amps / dense matvec /
scatter per task, ``QuEST_cpu.c:1840-1952``; per-gate MPI choreography
``QuEST_cpu_distributed.c:1526-1568``). The TPU-native formulation: view the
planar (2, 2^n) state as a grouped tensor (:mod:`.layout`), transpose the
touched 2-sized axes to the front, and hit them with 4 small real matmuls
(complex matmul over the planes) -- XLA tiles them onto the MXU and, when the
array is sharded over the top qubits, inserts the all-to-all /
collective-permute traffic that the reference hand-writes.

Matrix index convention matches the reference (multiQubitUnitary doc): the
row index r of the 2^t x 2^t matrix is ``sum_k bit(targets[k]) << k`` --
targets[0] is the least-significant bit of the matrix index. Matrices arrive
planar: shape (2, 2^t, 2^t).

All functions are pure and jitted with static qubit tuples: one XLA program
per (n, targets, controls) signature, reused across angles/matrices.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .layout import grouped_axes, inverse_permutation


def _plan(n, targets, controls):
    """Common transpose plan: (shape, perm, inv_perm) with the leading planar
    axis pinned at 0, controls then targets(MSB-first) next."""
    shape, axis_of = grouped_axes(n, tuple(targets) + tuple(controls))
    ctrl_axes = [axis_of[c] + 1 for c in controls]
    targ_axes = [axis_of[q] + 1 for q in reversed(targets)]
    rest = [a for a in range(1, len(shape) + 1) if a not in ctrl_axes and a not in targ_axes]
    perm = tuple([0] + ctrl_axes + targ_axes + rest)
    return (2,) + shape, perm, inverse_permutation(perm)


#: windows whose low edge is below this get kron-expanded down to qubit 0 so
#: the GEMM's K dimension is at least 2^_MIN_MINOR (=the 128-lane width);
#: keeps every buffer's trailing dim >= 128 and avoids TPU tile padding.
_MIN_MINOR = 7


def _mxu_precision(dtype):
    """Always HIGHEST: XLA:TPU's default silently drops matmul inputs to
    bf16 -- catastrophic for amplitude evolution (observed 3e-3 norm drift in
    an 8-amp state). HIGH (3-pass bf16) was measured no faster here and
    drifted a 26q depth-8 circuit's norm to 0.9964 (vs 1.000002 at HIGHEST);
    the dtype hook stays so a future backend can relax it deliberately."""
    del dtype
    return jax.lax.Precision.HIGHEST


def _window_of(targets):
    """(lo, hi) if ``targets`` is exactly the ascending run lo..hi, else None."""
    t = len(targets)
    lo = targets[0]
    if targets == tuple(range(lo, lo + t)):
        return lo, lo + t - 1
    return None


def _apply_matrix_window(amps, mr, mi, n, lo, hi):
    """Layout-clean dense apply for a contiguous target window [lo, hi].

    The general grouped-transpose path materialises high-rank tensors whose
    trailing dims are 2-sized; the TPU's (8, 128) tile padding then inflates
    them up to 64x (observed: a 512 MB state demanding a 32 GB allocation).
    A contiguous window never needs a transpose:

    - lo >= _MIN_MINOR: view (2, A, 2^t, 2^lo) and contract the 2^t axis
      with M -- trailing dim 2^lo >= 128, no padding, MXU GEMM.
    - lo < _MIN_MINOR: expand M to G = I (x) M (x) I over the low
      w = max(hi+1, _MIN_MINOR) qubits and right-multiply the (2, R, 2^w)
      view -- K in [128, 2048], the MXU sweet spot.
    """
    mm = partial(jnp.einsum, precision=_mxu_precision(amps.dtype))

    def cplx_block(gr, gi):
        # the complex product as ONE real contraction: out[p] = sum_q G4[p,q] x[q]
        # with G4 = [[gr, -gi], [gi, gr]] -- reads the state once instead of
        # four times (one dot_general, planes contracted alongside K).
        return jnp.stack([jnp.stack([gr, -gi]), jnp.stack([gi, gr])])

    if lo >= _MIN_MINOR:
        dim = 1 << (hi - lo + 1)
        x = amps.reshape(2, -1, dim, 1 << lo)
        g4 = cplx_block(mr, mi)
        out = mm("pqij,qajb->paib", g4, x)
        return out.reshape(2, -1)

    w = min(max(hi + 1, _MIN_MINOR), n)
    eye_hi = jnp.eye(1 << (w - 1 - hi), dtype=mr.dtype)
    eye_lo = jnp.eye(1 << lo, dtype=mr.dtype)
    gr = jnp.kron(eye_hi, jnp.kron(mr, eye_lo))
    gi = jnp.kron(eye_hi, jnp.kron(mi, eye_lo))
    g4 = cplx_block(gr, gi)
    x = amps.reshape(2, -1, 1 << w)
    out = mm("pqij,qaj->pai", g4, x)
    return out.reshape(2, -1)


@partial(jax.jit, static_argnames=("n", "targets", "controls", "control_states", "conj"),
         donate_argnums=(0,))
def apply_matrix(amps, matrix, *, n: int, targets: tuple[int, ...],
                 controls: tuple[int, ...] = (), control_states: tuple[int, ...] = (),
                 conj: bool = False):
    """amps' = (ctrl-gated) M applied to ``targets`` of the n-qubit state.

    ``matrix`` is planar (2, 2^t, 2^t) and may be non-unitary (the apply*
    operator family reuses this). ``control_states`` optionally gives the
    required value of each control (default all-1, as
    multiStateControlledUnitary, QuEST.h:4448). ``conj=True`` applies the
    elementwise conjugate (density-matrix shadow op, QuEST.c:184-193).
    """
    t = len(targets)
    dim = 1 << t
    states = control_states if control_states else (1,) * len(controls)

    mr, mi = matrix[0], matrix[1]
    if conj:
        mi = -mi

    if not controls:
        win = _window_of(targets)
        if win is not None:
            return _apply_matrix_window(amps, mr, mi, n, *win)

    shape, perm, inv = _plan(n, targets, controls)
    tensor = amps.reshape(shape).transpose(perm)

    # see _mxu_precision: never let XLA silently drop matmul inputs to bf16
    mm = partial(jnp.matmul, precision=_mxu_precision(amps.dtype))

    def matvec(sub):
        # sub: (2, 2, 2, ..., rest) with t leading 2-axes after the plane
        flat = sub.reshape(2, dim, -1)
        rr = mm(mr, flat[0]) - mm(mi, flat[1])
        ii = mm(mr, flat[1]) + mm(mi, flat[0])
        return jnp.stack([rr, ii]).reshape(sub.shape)

    if controls:
        idx = (slice(None),) + tuple(states)
        sub = tensor[idx]
        tensor = tensor.at[idx].set(matvec(sub))
    else:
        tensor = matvec(tensor)

    return tensor.transpose(inv).reshape(2, -1)


#: X/swap supports spanning at most this many contiguous qubits are applied
#: as a host-built permutation matrix through the window GEMM (layout-clean);
#: wider spans fall back to the grouped view, whose tile padding makes it
#: unusable on large states but fine on small ones.
_PERM_WINDOW_MAX = 8


def _window_perm_matrix(span_lo, span_hi, flips, cbits, states, np_dtype):
    """Permutation matrix over the window [span_lo, span_hi]: XOR ``flips``
    where every control bit matches its required state; identity elsewhere.
    All-static, built host-side at trace time."""
    import numpy as np
    k = span_hi - span_lo + 1
    dim = 1 << k
    mr = np.zeros((dim, dim), dtype=np_dtype)
    fl = 0
    for q in flips:
        fl |= 1 << (q - span_lo)
    for s in range(dim):
        ok = all(((s >> (c - span_lo)) & 1) == st for c, st in zip(cbits, states))
        mr[s ^ fl if ok else s, s] = 1
    return mr


@partial(jax.jit, static_argnames=("n", "targets", "controls", "control_states"),
         donate_argnums=(0,))
def apply_x_class(amps, *, n: int, targets: tuple[int, ...],
                  controls: tuple[int, ...] = (), control_states: tuple[int, ...] = ()):
    """Multi-controlled multi-qubit NOT: an amplitude permutation.

    The reference's pauliX/controlledNot/multiControlledMultiQubitNot kernels
    (``QuEST_cpu.c``, dispatch ``QuEST_cpu_distributed.c:1109-1152``) are
    strided-copy loops. Here, compact supports become a control-folded
    permutation matrix through the layout-clean window GEMM; wide supports
    take the grouped flip (fine at small n, sharded axes become collective
    permutes).
    """
    states = control_states if control_states else (1,) * len(controls)
    support = tuple(targets) + tuple(controls)
    lo, hi = min(support), max(support)
    if hi - lo + 1 <= _PERM_WINDOW_MAX:
        import numpy as np
        mr = _window_perm_matrix(lo, hi, targets, controls, states,
                                 np.dtype(amps.dtype))
        m = jnp.stack([jnp.asarray(mr), jnp.zeros_like(jnp.asarray(mr))])
        return _apply_matrix_window(amps, m[0], m[1], n, lo, hi)
    shape, perm, inv = _plan(n, targets, controls)
    tensor = amps.reshape(shape).transpose(perm)
    nc = len(controls)
    flip_axes = list(range(1 + nc, 1 + nc + len(targets)))

    if controls:
        idx = (slice(None),) + tuple(states)
        sub = tensor[idx]
        sub = jnp.flip(sub, axis=[a - nc for a in flip_axes])
        tensor = tensor.at[idx].set(sub)
    else:
        tensor = jnp.flip(tensor, axis=flip_axes)

    return tensor.transpose(inv).reshape(2, -1)


@partial(jax.jit, static_argnames=("n", "qb1", "qb2", "controls"), donate_argnums=(0,))
def apply_swap(amps, *, n: int, qb1: int, qb2: int, controls: tuple[int, ...] = ()):
    """SWAP as an axis transposition (reference: statevec_swapQubitAmps,
    ``QuEST_cpu.c:3850-3931``; distributed odd-parity pair exchange
    ``QuEST_cpu_distributed.c:1424-1459``). On a sharded axis this *is* the
    all-to-all the reference hand-codes -- and it is also the primitive the
    distributed scheduler uses to localise far targets."""
    support = (qb1, qb2) + tuple(controls)
    lo, hi = min(support), max(support)
    if hi - lo + 1 <= _PERM_WINDOW_MAX:
        import numpy as np
        k = hi - lo + 1
        dim = 1 << k
        mr = np.zeros((dim, dim), dtype=np.dtype(amps.dtype))
        b1, b2 = qb1 - lo, qb2 - lo
        for s in range(dim):
            ok = all(((s >> (c - lo)) & 1) == 1 for c in controls)
            if ok:
                v1, v2 = (s >> b1) & 1, (s >> b2) & 1
                s2 = s & ~(1 << b1) & ~(1 << b2) | (v2 << b1) | (v1 << b2)
            else:
                s2 = s
            mr[s2, s] = 1
        m = jnp.asarray(mr)
        return _apply_matrix_window(amps, m, jnp.zeros_like(m), n, lo, hi)

    shape, perm, inv = _plan(n, (qb1, qb2), controls)
    tensor = amps.reshape(shape).transpose(perm)
    nc = len(controls)
    a1, a2 = 1 + nc, 2 + nc  # the two target axes after the plan's transpose

    if controls:
        idx = (slice(None),) + (1,) * nc
        sub = tensor[idx]
        sp = list(range(sub.ndim))
        sp[a1 - nc], sp[a2 - nc] = sp[a2 - nc], sp[a1 - nc]
        tensor = tensor.at[idx].set(sub.transpose(sp))
    else:
        sp = list(range(tensor.ndim))
        sp[a1], sp[a2] = sp[a2], sp[a1]
        tensor = tensor.transpose(sp)

    return tensor.transpose(inv).reshape(2, -1)
