"""Amplitude-index layout algebra.

Convention (identical to the reference): qubit q is bit q of the flat
amplitude index -- qubit 0 is the least-significant bit
(``QuEST_cpu_internal.h:26-53`` extractBit/flipBit do exactly this).

A state over n qubits is a flat array of 2^n amplitudes. Reshaping it to
``(2,)*n`` would make qubit q axis ``n-1-q``, but rank-n tensors are hostile
to the TPU compiler for large n. Instead we *group*: for an operation touching
qubits Q = {q1 > q2 > ... > qk}, reshape to rank <= 2k+1 where each touched
qubit is its own 2-sized axis and the untouched index segments between them
stay fused:

    shape = (2^(n-1-q1), 2, 2^(q1-1-q2), 2, ..., 2, 2^qk)

This is the moral equivalent of the reference's block/stride loops
(e.g. statevec_compactUnitaryLocal's sizeBlock/sizeHalfBlock arithmetic,
``QuEST_cpu.c:1682-1739``) but leaves the actual tiling to XLA.
"""

from __future__ import annotations

from typing import Sequence


def grouped_shape(n: int, qubits_desc: Sequence[int]) -> tuple[int, ...]:
    """Shape with one 2-sized axis per qubit in ``qubits_desc`` (strictly
    descending) and fused segments elsewhere. Product is always 2^n."""
    dims = []
    prev = n
    for q in qubits_desc:
        dims.append(1 << (prev - 1 - q))
        dims.append(2)
        prev = q
    dims.append(1 << prev)
    return tuple(dims)


def grouped_axes(n: int, qubits: Sequence[int]) -> tuple[tuple[int, ...], dict[int, int]]:
    """(shape, {qubit: axis}) for the grouped view over ``qubits`` (any order)."""
    qs = sorted(set(qubits), reverse=True)
    shape = grouped_shape(n, qs)
    axis_of = {q: 2 * i + 1 for i, q in enumerate(qs)}
    return shape, axis_of


def inverse_permutation(perm: Sequence[int]) -> tuple[int, ...]:
    inv = [0] * len(perm)
    for i, p in enumerate(perm):
        inv[p] = i
    return tuple(inv)
