"""State initialisation kernels (reference: ``QuEST_cpu.c:1416-1680`` init
family and the density inits in ``QuEST_cpu.c:60-135``).

All states are planar float arrays of shape (2, 2^n) -- see ops.cplx. Each
function returns a fresh array; callers shard it afterwards (or jit these
under an output sharding so the fill happens shard-locally, which is how the
reference's per-chunk loops behave).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def init_blank(num_amps: int, dtype):
    """All-zero (unnormalised) state -- initBlankState."""
    return jnp.zeros((2, num_amps), dtype=dtype)


@partial(jax.jit, static_argnames=("num_amps", "dtype", "index"))
def init_classical(num_amps: int, dtype, index):
    """|index> one-hot -- initClassicalState / initZeroState (index=0),
    reference kernel statevec_initClassicalState (QuEST_cpu.c:1566+)."""
    return jnp.zeros((2, num_amps), dtype=dtype).at[0, index].set(1)


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def init_plus(num_amps: int, dtype):
    """Uniform superposition -- initPlusState (QuEST_cpu.c:1543+)."""
    re = jnp.full((1, num_amps), 1.0 / math.sqrt(num_amps), dtype=dtype)
    return jnp.concatenate([re, jnp.zeros((1, num_amps), dtype=dtype)])


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def init_debug(num_amps: int, dtype):
    """amp_i = (2i + (2i+1) j)/10 -- initDebugState, the test fixture
    (statevec_initDebugState, QuEST_cpu.c:1649-1680)."""
    i = jax.lax.iota(dtype, num_amps)
    return jnp.stack([(2 * i) / 10, (2 * i + 1) / 10])


@partial(jax.jit, static_argnames=("n",))
def density_from_pure(pure_amps, *, n: int):
    """rho = |psi><psi| flattened with row bits low (initPureState; reference
    densmatr_initPureState via pairState broadcast, QuEST_cpu_distributed.c:387-429).
    Flat index = col * 2^n + row, element = psi_row * conj(psi_col)."""
    pr, pi = pure_amps[0], pure_amps[1]
    # out[c, r] = psi_r * conj(psi_c); broadcasting keeps full precision
    # (jnp.outer lowers to a matmul, which TPU would run in bf16)
    re = pr[:, None] * pr[None, :] + pi[:, None] * pi[None, :]
    im = pr[:, None] * pi[None, :] - pi[:, None] * pr[None, :]
    return jnp.stack([re, im]).reshape(2, -1)


@partial(jax.jit, static_argnames=("num_amps", "dtype", "index"))
def density_init_classical(num_amps: int, dtype, index):
    """rho = |s><s|: single 1 at diagonal flat index s*(2^n+1)."""
    dim = int(math.isqrt(num_amps))
    return jnp.zeros((2, num_amps), dtype=dtype).at[0, index * (dim + 1)].set(1)


@partial(jax.jit, static_argnames=("num_amps", "dtype"))
def density_init_plus(num_amps: int, dtype):
    """rho = |+><+| on n qubits: every element 1/2^n."""
    dim = int(math.isqrt(num_amps))
    re = jnp.full((1, num_amps), 1.0 / dim, dtype=dtype)
    return jnp.concatenate([re, jnp.zeros((1, num_amps), dtype=dtype)])


@jax.jit
def weighted_sum(f1, amps1, f2, amps2, fo, amps_out):
    """out = f1*q1 + f2*q2 + fo*out with planar complex factors f = (re, im)
    shape-(2,) arrays -- setWeightedQureg (QuEST_cpu.c:3933)."""
    def term(f, a):
        re = f[0] * a[0] - f[1] * a[1]
        im = f[0] * a[1] + f[1] * a[0]
        return jnp.stack([re, im])
    return term(f1, amps1) + term(f2, amps2) + term(fo, amps_out)
