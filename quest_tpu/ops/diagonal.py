"""Diagonal / phase-only kernels: no data movement, pure broadcasted multiply.

The reference implements these as mask-parity loops (phaseShiftByTerm
``QuEST_cpu.c:3113``, multiRotateZ ``QuEST_cpu.c:3235-3285``). On TPU a phase
gate never needs a transpose: build planar factor tensors that broadcast
against the grouped view (1-sized everywhere except the touched 2-sized axes)
and complex-multiply the planes -- XLA fuses the whole thing into one VPU pass
over HBM, and it works unchanged on sharded arrays (factors are replicated
scalars).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .layout import grouped_axes


def _axis_vec(values, axis: int, rank: int, dtype):
    """A length-2 vector placed on one broadcast axis (axes count the grouped
    view only; the planar axis is prepended by callers via [None])."""
    shape = [1] * rank
    shape[axis] = 2
    return jnp.asarray(values, dtype=dtype).reshape(shape)


def _control_selector(axis_of, controls, rank, dtype):
    """Tensor that is 1 where all controls are 1, else 0 (broadcastable)."""
    sel = None
    for c in controls:
        v = _axis_vec([0.0, 1.0], axis_of[c], rank, dtype)
        sel = v if sel is None else sel * v
    return sel


def _mul_factor(amps, shape, fr, fi):
    """amps (2, 2^n) times planar factor (fr, fi) broadcast over ``shape``."""
    t = amps.reshape((2,) + shape)
    re = t[0] * fr - t[1] * fi
    im = t[0] * fi + t[1] * fr
    return jnp.stack([re, im]).reshape(2, -1)


@partial(jax.jit, static_argnames=("n", "targets", "controls", "conj"), donate_argnums=(0,))
def apply_diagonal(amps, diag, *, n: int, targets: tuple[int, ...],
                   controls: tuple[int, ...] = (), conj: bool = False):
    """Multiply by a planar (2, 2^t) diagonal on ``targets`` (controls gate it
    to the all-1 subspace). Index convention matches apply_matrix: targets[0]
    is the least-significant bit of the diagonal's index.

    Covers phaseShift/sGate/tGate/rotateZ/controlledPhaseFlip/diagonalUnitary/
    applySubDiagonalOp (reference kernels ``QuEST_cpu.c:1339-1386,3113-3233``).
    """
    t = len(targets)
    shape, axis_of = grouped_axes(n, tuple(targets) + tuple(controls))
    rank = len(shape)

    # place the diagonal's bits onto their grouped axes:
    # d has shape (2, 2^t) with bit k of the index belonging to targets[k]
    d = diag.astype(amps.dtype).reshape((2,) + (2,) * t)  # planar, [b_{t-1},...,b_0]
    order = [axis_of[q] for q in reversed(targets)]
    perm = sorted(range(t), key=lambda i: order[i])
    bshape = [1] * rank
    for q in targets:
        bshape[axis_of[q]] = 2
    d = d.transpose([0] + [1 + p for p in perm]).reshape([2] + bshape)
    fr, fi = d[0], d[1]
    if conj:
        fi = -fi

    if controls:
        sel = _control_selector(axis_of, controls, rank, amps.dtype)
        fr = 1 + sel * (fr - 1)
        fi = sel * fi

    return _mul_factor(amps, shape, fr, fi)


@partial(jax.jit, static_argnames=("n", "qubits", "controls", "conj"), donate_argnums=(0,))
def apply_parity_phase(amps, theta, *, n: int, qubits: tuple[int, ...],
                       controls: tuple[int, ...] = (), conj: bool = False):
    """exp(-i theta/2 * Z x Z x ... x Z) on ``qubits`` -- multiRotateZ and its
    controlled variant (reference mask-parity kernel ``QuEST_cpu.c:3235-3285``).

    Avoids materialising the 2^t diagonal: (-1)^parity is a separable product
    of per-axis [1,-1] vectors, so the factor is
    cos(theta/2) - i sin(theta/2) * prod_q (-1)^{bit_q}, fully fused by XLA.
    ``conj`` negates theta (density shadow op).
    """
    shape, axis_of = grouped_axes(n, tuple(qubits) + tuple(controls))
    rank = len(shape)
    rdtype = amps.dtype

    sign = None
    for q in qubits:
        v = _axis_vec([1.0, -1.0], axis_of[q], rank, rdtype)
        sign = v if sign is None else sign * v

    theta = jnp.asarray(theta, dtype=rdtype)
    if conj:
        theta = -theta
    fr = jnp.cos(theta / 2) * jnp.ones_like(sign)
    fi = -jnp.sin(theta / 2) * sign

    if controls:
        sel = _control_selector(axis_of, controls, rank, rdtype)
        fr = 1 + sel * (fr - 1)
        fi = sel * fi

    return _mul_factor(amps, shape, fr, fi)


@partial(jax.jit, static_argnames=("conj",), donate_argnums=(0,))
def apply_full_diagonal(amps, elems, *, conj: bool = False):
    """Elementwise multiply by a full planar 2^n diagonal operator
    (applyDiagonalOp; reference kernel ``QuEST_cpu.c:3975-4030``). ``elems``
    (2, 2^n) is sharded like ``amps`` so the multiply is purely local."""
    er, ei = elems[0].astype(amps.dtype), elems[1].astype(amps.dtype)
    if conj:
        ei = -ei
    re = amps[0] * er - amps[1] * ei
    im = amps[0] * ei + amps[1] * er
    return jnp.stack([re, im])


@partial(jax.jit, static_argnames=("n",), donate_argnums=(0,))
def apply_full_diagonal_to_density(amps, elems, *, n: int):
    """applyDiagonalOp on a density matrix: rho -> D rho (left-multiply only,
    per the reference's densmatr_applyDiagonalOp). Row bits are the low n bits
    of the 2n-qubit flattening, so broadcast D along the column axis."""
    dim = 1 << n
    t = amps.reshape(2, dim, dim)  # [plane, col, row]
    er, ei = elems[0].astype(amps.dtype)[None, :], elems[1].astype(amps.dtype)[None, :]
    re = t[0] * er - t[1] * ei
    im = t[0] * ei + t[1] * er
    return jnp.stack([re, im]).reshape(2, -1)
