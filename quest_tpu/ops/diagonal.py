"""Diagonal / phase-only kernels: no data movement, one fused pass.

The reference implements these as mask-parity loops (phaseShiftByTerm
``QuEST_cpu.c:3113``, multiRotateZ ``QuEST_cpu.c:3235-3285``). On TPU a phase
gate never reshapes or moves the state: the per-amplitude factor is computed
from flat-index bits (iota + shifts) and either gathered from the 2^t-entry
diagonal table or, for parity phases, derived from an XOR chain -- XLA fuses
the whole thing into one VPU pass over HBM, and it works unchanged on sharded
arrays (the iota is global under GSPMD).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _flat_bits(num_flat: int, qubit: int):
    """Elementwise bit-q of the flat amplitude index, shape (1, num_flat).

    Built from a >=2-D iota (TPU requires it); stays fused into the consuming
    multiply -- no reshape of the state, no materialised index array."""
    i = jax.lax.broadcasted_iota(jnp.int32, (1, num_flat), 1)
    return (i >> qubit) & 1


def _ctrl_ok(num_flat: int, controls):
    sel = None
    for c in controls:
        b = _flat_bits(num_flat, c)
        sel = b if sel is None else sel & b
    return sel


def _apply_diagonal_flat(amps, diag, targets, controls, conj):
    """Layout-clean diagonal: phase factors computed elementwise over the
    *flat* (2, 2^n) state from index bits.

    The grouped-broadcast formulation reshapes the state to rank 2t+2 with
    2-sized trailing axes; on TPU such views materialise with (8, 128) tile
    padding -- observed 64x inflation (512 MB state -> 34 GB allocation) for
    a 5-target diagonal at 26 qubits. Here the state is never reshaped: the
    2^t-entry table is gathered by an index assembled from flat-index bits
    (the same formulation as the explicit distributed backend,
    parallel/exchange.py dist_apply_diag_phase), one pass at any width,
    sharding-transparent (iota is global)."""
    num = amps.shape[-1]
    rdtype = amps.dtype
    d = diag.astype(rdtype)
    dr, di = d[0], d[1]
    if conj:
        di = -di

    sel = jnp.zeros((1, num), jnp.int32)
    for k, q in enumerate(targets):
        sel = sel | (_flat_bits(num, q) << k)
    fr = jnp.take(dr, sel[0])
    fi = jnp.take(di, sel[0])

    if controls:
        ok = _ctrl_ok(num, controls)[0].astype(rdtype)
        fr = 1 + ok * (fr - 1)
        fi = ok * fi

    re = amps[0] * fr - amps[1] * fi
    im = amps[0] * fi + amps[1] * fr
    return jnp.stack([re, im])


@partial(jax.jit, static_argnames=("n", "targets", "controls", "conj"), donate_argnums=(0,))
def apply_diagonal(amps, diag, *, n: int, targets: tuple[int, ...],
                   controls: tuple[int, ...] = (), conj: bool = False):
    """Multiply by a planar (2, 2^t) diagonal on ``targets`` (controls gate it
    to the all-1 subspace). Index convention matches apply_matrix: targets[0]
    is the least-significant bit of the diagonal's index.

    Covers phaseShift/sGate/tGate/rotateZ/controlledPhaseFlip/diagonalUnitary/
    applySubDiagonalOp (reference kernels ``QuEST_cpu.c:1339-1386,3113-3233``).
    """
    del n
    return _apply_diagonal_flat(amps, diag, targets, controls, conj)


@partial(jax.jit, static_argnames=("n", "qubits", "controls", "conj"), donate_argnums=(0,))
def apply_parity_phase(amps, theta, *, n: int, qubits: tuple[int, ...],
                       controls: tuple[int, ...] = (), conj: bool = False):
    """exp(-i theta/2 * Z x Z x ... x Z) on ``qubits`` -- multiRotateZ and its
    controlled variant (reference mask-parity kernel ``QuEST_cpu.c:3235-3285``).

    Computed elementwise over the flat state (no reshape, see
    :func:`_apply_diagonal_flat` for why): the factor is
    cos(theta/2) - i sin(theta/2) * (-1)^{parity of the target bits},
    with the parity an XOR chain over index bits gathering from a 2-entry
    phase table (the same formulation as :func:`_apply_diagonal_flat`) --
    one fused VPU pass, sharding-transparent. The table gather, rather
    than a multiply by the +-1 sign, keeps the kernel BIT-STABLE between
    a constant-folded theta and a runtime-parameter theta (the serving
    engine's parameterized replay): the sign-multiply form left the
    trailing complex multiply eligible for FMA contraction in one
    compilation but not the other, a 1-ulp divergence per parity gate.
    ``conj`` negates theta (density shadow op).
    """
    num = amps.shape[-1]
    rdtype = amps.dtype

    par = None
    for q in qubits:
        b = _flat_bits(num, q)
        par = b if par is None else par ^ b

    theta = jnp.asarray(theta, dtype=rdtype)
    if conj:
        theta = -theta
    c, s = jnp.cos(theta / 2), jnp.sin(theta / 2)
    fr = jnp.take(jnp.stack([c, c]), par[0])
    fi = jnp.take(jnp.stack([-s, s]), par[0])

    if controls:
        ok = _ctrl_ok(num, controls)[0].astype(rdtype)
        fr = 1 + ok * (fr - 1)
        fi = ok * fi

    re = amps[0] * fr - amps[1] * fi
    im = amps[0] * fi + amps[1] * fr
    return jnp.stack([re, im])


@partial(jax.jit, static_argnames=("conj",), donate_argnums=(0,))
def apply_full_diagonal(amps, elems, *, conj: bool = False):
    """Elementwise multiply by a full planar 2^n diagonal operator
    (applyDiagonalOp; reference kernel ``QuEST_cpu.c:3975-4030``). ``elems``
    (2, 2^n) is sharded like ``amps`` so the multiply is purely local."""
    er, ei = elems[0].astype(amps.dtype), elems[1].astype(amps.dtype)
    if conj:
        ei = -ei
    re = amps[0] * er - amps[1] * ei
    im = amps[0] * ei + amps[1] * er
    return jnp.stack([re, im])


@partial(jax.jit, static_argnames=("n",), donate_argnums=(0,))
def apply_full_diagonal_to_density(amps, elems, *, n: int):
    """applyDiagonalOp on a density matrix: rho -> D rho (left-multiply only,
    per the reference's densmatr_applyDiagonalOp). Row bits are the low n bits
    of the 2n-qubit flattening, so broadcast D along the column axis."""
    dim = 1 << n
    t = amps.reshape(2, dim, dim)  # [plane, col, row]
    er, ei = elems[0].astype(amps.dtype)[None, :], elems[1].astype(amps.dtype)[None, :]
    re = t[0] * er - t[1] * ei
    im = t[0] * ei + t[1] * er
    return jnp.stack([re, im]).reshape(2, -1)
