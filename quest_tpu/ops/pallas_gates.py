"""Pallas TPU kernel: a fused run of gates in ONE pass over HBM.

The hot loop of a state-vector simulator is "stream 2^n amplitudes through
an update rule". XLA's GEMM formulation (ops.apply) pays one full HBM
round-trip per fused block; this kernel applies an arbitrarily long run of
single-qubit matrices, controlled gates, and parity phases in a single
read+write of the state: each grid program pulls a (2, S, 128) planar tile
into VMEM, applies every gate of the run in-register, and writes the tile
back. The reference's analogous hot loops are one kernel launch per gate
(statevec_compactUnitaryLocal, QuEST_cpu.c:1682-1739; CUDA variant
QuEST_gpu.cu:492-554) -- fusing the run is pure TPU-side gain, the same
bandwidth argument as the dense-fusion layer (quest_tpu/fusion.py) taken to
its limit for the 1-qubit-dominated parts of a circuit.

Geometry: the flat amplitude index is split (grid, sublane, lane) =
(i >> (7+log2 S), (i >> 7) & (S-1), i & 127). A gate on qubit q pairs
amplitude i with i ^ 2^q:

- q < 7 (lane bits): partner = two pltpu.rolls along the lane axis,
  selected per element by bit q of the lane index -- a VPU permute.
- 7 <= q < 7+log2 S (sublane bits): same along the sublane axis.
- q >= 7+log2 S (grid bits): only *diagonal* roles are supported (control
  qubits, parity-phase members): their bit is a per-program scalar from
  pl.program_id. Gate TARGETS on grid bits need cross-tile data and are
  the caller's job to route elsewhere (ops.apply window GEMMs).

Ops format (all matrix data static at trace time, baked into the kernel):

    ("matrix", q, controls, states, M)   M: 2x2 complex ndarray; q local,
                                         OR any qubit if M is diagonal
                                         (grid-bit diagonals need only a
                                         per-program scalar select)
    ("parity", qubits, controls, theta)  exp(-i theta/2 Z...Z), any qubits
    ("swap", q1, q2, controls, states)   SWAP(q1, q2); both targets local
    ("diagw", targets, controls, D)      D: (2^t,) complex diagonal over
                                         ``targets`` (any qubits; grid
                                         members enter the table index as
                                         per-program scalars)
    ("lane_u", W)                        W: (3, 128, 128) real stack
                                         (Ur^T, Ui^T, Ur^T+Ui^T) -- a
                                         folded run of lane-qubit gates as
                                         THREE Karatsuba MXU dots
    ("window", lo, span, W)              W: (2*2^span)^2 real block matrix
                                         [[Ur,-Ui],[Ui,Ur]] -- a folded run
                                         of gates confined to the sublane
                                         window [lo, lo+span), applied as
                                         per-slab W @ y MXU dots

Before the kernel is built, _fold_zone_ops contracts gates into dense
per-zone unitaries: the tile's qubits split into the lane zone [0, 7) and
successive 5-qubit sublane zones, and each zone accumulates the (not
necessarily consecutive) gates fully contained in it -- open zones commute
because they touch disjoint qubits -- until a cross-zone op forces a
flush. Folded zones run on the MXU instead of per-gate butterfly rolls
(VPU): the same dense-fusion economics as quest_tpu/fusion.py, one level
down.
"""

from __future__ import annotations

import math
import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import _compat
from .. import telemetry

LANE_BITS = 7          # minor dim fixed at 128 lanes
_LANES = 1 << LANE_BITS
#: (2, 4096, 128) f32 tile = 4 MiB. Round-4 re-sweep of the manual-DMA
#: kernel's chunk size at 2^26 amps (tools/kernelprobe, min-of-3): the
#: per-PASS floor is per-chunk-overhead-bound at the old S=2048 default
#: (256 chunks, 11.2 ms) and drops to ~7.7 ms at S=4096; S=8192 is flat
#: within noise (7.5) but its 32 MiB of double-buffers plus op
#: temporaries overflow the 100 MiB Mosaic VMEM stack on op-heavy runs
#: (measured OOM at 24 mixed ops). S=4096 also raises local_qubits by
#: one over round 3 -- more in-tile targets per fused run.
_DEF_SUBLANES = 1 << 12

#: default in-flight DMA ring depth for the manual chunk pipeline
#: (_make_dma_kernel). 2 = the classic double buffer; 3 adds one spare
#: slot so a chunk whose bf16x3 zone dots finish before its store drains
#: does not stall the sweep on the store-wait (the round-5 verdict's
#: per-pass-stall finding). 3 is the widest depth whose ring buffers
#: (2 * ring * 4 MiB at the S=4096 f32 tile) stay within _RING_VMEM_BUDGET
#: alongside the op temporaries of the bench's longest fused runs -- the
#: operating point committed from the tools/kernelprobe --ring sweep
#: (re-sweep it on-chip when S or the op mix changes; BASELINE.md table).
_DEF_RING_DEPTH = 3

#: env override for the ring depth: sweepable without code edits
#: (acceptance: ISSUE 2 tentpole). The fused_local_run ``ring_depth``
#: argument -- the plan-level knob -- outranks it.
_RING_ENV = "QUEST_PALLAS_RING"

#: VMEM the ring's in+out tile buffers may claim. The Mosaic scoped-VMEM
#: limit is raised to 100 MiB for these kernels; holding the ring to
#: slightly under half keeps room for the per-op temporaries that made
#: S=8192 double-buffers OOM at 24 mixed ops (round-4 probe). Depths that
#: exceed it derate one slot at a time rather than failing to compile.
_RING_VMEM_BUDGET = 48 * 1024 * 1024


#: raw QUEST_PALLAS_RING values already diagnosed (QT205 warns once per
#: distinct value, not once per kernel launch)
_RING_ENV_WARNED: set = set()


def ring_depth_default() -> int:
    """The process-wide DMA ring depth: QUEST_PALLAS_RING if set (min 2),
    else _DEF_RING_DEPTH. Malformed or sub-minimum values are coerced as
    before, but leave a QT205 diagnostic (warn-once telemetry record
    stating the clamped value) instead of being swallowed silently --
    the shared env-int parser (analysis.diagnostics.parse_env_int, also
    behind QUEST_COMM_PIPELINE's QT206)."""
    # deliberate late import: diagnostics depends only on telemetry, so
    # this cannot cycle back into the ops layer
    from ..analysis.diagnostics import parse_env_int

    return parse_env_int(_RING_ENV, _DEF_RING_DEPTH, minimum=2,
                         code="QT205", noun="ring depth",
                         below="is below the 2-slot ring minimum",
                         warned=_RING_ENV_WARNED)


def effective_ring_depth(ring_depth: int, nchunks: int, slot_bytes: int,
                         budget: int = _RING_VMEM_BUDGET) -> int:
    """The ring depth a grid kernel actually runs: the requested depth
    clamped to [2, nchunks], then derated one slot at a time while the
    in+out ring buffers (2 * ring * slot_bytes) overflow ``budget``.
    The ONE clamp shared by the kernel caller (_fused_local_run) and the
    static ring checker (analysis.ringcheck), so the checker verifies
    the operating point the kernel really uses."""
    ring = max(2, min(int(ring_depth), int(nchunks)))
    while ring > 2 and 2 * ring * slot_bytes > budget:
        ring -= 1
    return ring


#: matmul precision for the in-kernel zone dots (lane_u / window). Mosaic
#: lowers only DEFAULT and HIGHEST (Precision.HIGH raises
#: NotImplementedError, probed round 3); HIGHEST keeps the 26q depth-8
#: norm drift at ~1.4e-5 after 7 circuits vs DEFAULT's ~8e-5 per circuit
#: (BASELINE.md precision table). f32 tiles take the manual bf16x3 route
#: below instead; this setting remains for the f64-interpreter path.
_DOT_PRECISION = jax.lax.Precision.HIGHEST


def _split_bf16(w: np.ndarray):
    """Host-side hi/lo bf16 decomposition of an f32 operand matrix:
    w ~= hi + lo with hi = bf16(w) and lo = bf16(w - hi). Stacked on a new
    leading axis so the pair ships as ONE kernel operand."""
    import ml_dtypes

    hi = w.astype(ml_dtypes.bfloat16)
    lo = (w - hi.astype(np.float32)).astype(ml_dtypes.bfloat16)
    return np.stack([hi, lo])


def _dot_bf16x3(x, w_pair, dtype):
    """x @ W at ~f32 accuracy from THREE DEFAULT-precision bf16 MXU passes.

    Mosaic's HIGHEST lowers an f32 dot to SIX bf16 passes (full 3x3 hi/lo
    cross terms); the manual split keeps the three leading terms
    (hi*hi + hi*lo + lo*hi), whose dropped lo*lo term is O(2^-16) relative
    -- measured norm drift ~1e-6/circuit on the 26q depth-8 bench vs
    HIGHEST's 1.4e-5/7-circuits budget (BASELINE.md precision table).
    Halves the MXU time of every zone dot: the lane dots are the
    serialized compute that bounds the 26q bench (round-3 floor
    analysis). ``w_pair`` = (2, ...) stacked bf16 hi/lo from _split_bf16."""
    xh = x.astype(jnp.bfloat16)
    xl = (x - xh.astype(dtype)).astype(jnp.bfloat16)
    wh, wl = w_pair[0], w_pair[1]
    acc = jnp.dot(xh, wh, preferred_element_type=dtype)
    acc += jnp.dot(xh, wl, preferred_element_type=dtype)
    acc += jnp.dot(xl, wh, preferred_element_type=dtype)
    return acc


def _dot_bf16x3_rev(w_pair, y, dtype):
    """W @ y variant of _dot_bf16x3 (static matrix on the LEFT)."""
    yh = y.astype(jnp.bfloat16)
    yl = (y - yh.astype(dtype)).astype(jnp.bfloat16)
    wh, wl = w_pair[0], w_pair[1]
    acc = jnp.dot(wh, yh, preferred_element_type=dtype)
    acc += jnp.dot(wl, yh, preferred_element_type=dtype)
    acc += jnp.dot(wh, yl, preferred_element_type=dtype)
    return acc


def local_qubits(n: int, sublanes: int = _DEF_SUBLANES) -> int:
    """Number of low qubits a tile holds entirely (targets must be below)."""
    rows = 1 << max(n - LANE_BITS, 0)
    s = min(sublanes, rows)
    return min(n, LANE_BITS + int(math.log2(s)) if s > 1 else LANE_BITS)


def _bit_mask(q: int, shape):
    """Bit q of the in-tile flat index as a (S, 128) {0,1} i32 array."""
    if q < LANE_BITS:
        lane = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        return (lane >> q) & 1
    sub = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    return (sub >> (q - LANE_BITS)) & 1


def _grid_bit(q: int, tile_bits: int):
    """Bit q of the flat index when q is a grid bit: per-program scalar."""
    return (pl.program_id(0) >> (q - tile_bits)) & 1


def _partner(arr, q: int):
    """arr[i ^ 2^q] within the tile.

    Lane bits (q < 7) use two circular rolls + per-bit select (intra-lane
    shuffles, ~free). Sublane bits use a reshape/slice half-exchange
    instead: splitting the sublane axis at the target bit and swapping the
    halves is a pure sub-array copy -- measured ~0.05-0.2 ms per gate at
    2^26 amps vs ~8 ms for the same butterfly as sublane pltpu.rolls
    (Mosaic lowers cross-sublane rolls to very slow shuffle sequences;
    round-3 microbench, the single biggest kernel cost discovered)."""
    if q < LANE_BITS:
        # np.int32 shifts: under jax x64 a python int would trace as i64,
        # which Mosaic's tpu.dynamic_rotate rejects (round-5 df path find)
        m = np.int32(1 << q)
        size = np.int32(arr.shape[1])
        up = pltpu.roll(arr, size - m, 1)  # up[i] = arr[i + m] (shift >= 0)
        dn = pltpu.roll(arr, m, 1)         # dn[i] = arr[i - m]
        bit = _bit_mask(q, arr.shape)
        return jnp.where(bit == 0, up, dn)
    m = 1 << (q - LANE_BITS)
    s, lanes = arr.shape
    v = arr.reshape(s // (2 * m), 2, m, lanes)
    return jnp.stack([v[:, 1], v[:, 0]], axis=1).reshape(s, lanes)


def _ctrl_scalar_and_mask(controls, states, tile_bits, shape, gbit):
    """(per-program scalar {0,1} or None, elementwise {0,1} mask or None)
    for a control set; ``gbit(q)`` resolves bits above the tile (grid bits
    from pl.program_id, shard bits from the SMEM shard-index scalar)."""
    states = states if states else (1,) * len(controls)
    mask = None
    scalar = None
    # np.int32 literals: under jax x64 (PRECISION=2 df kernels) python
    # ints would make these i64 vectors, which Mosaic cannot lower
    one, zero = np.int32(1), np.int32(0)
    for c, st in zip(controls, states):
        if c >= tile_bits:
            b = gbit(c)
            ok = jnp.where(b == st, one, zero)
            scalar = ok if scalar is None else scalar * ok
        else:
            b = _bit_mask(c, shape)
            ok = jnp.where(b == st, one, zero)
            mask = ok if mask is None else mask * ok
    return scalar, mask


#: width (in qubits) of each sublane fold zone; D = 2^5 gives 64x64 real
#: block matrices -- small enough to replicate per program, big enough that
#: a zone absorbs most of a layer's sublane gates
_ZONE_SPAN = 5


def _op_event(op):
    """Kernel op tuple -> GateEvent (for host-side dense folding)."""
    from ..fusion import GateEvent

    if op[0] == "matrix":
        return GateEvent("matrix", (op[1],), tuple(op[2]), tuple(op[3]),
                         matrix=np.asarray(op[4].arr if hasattr(op[4], "arr")
                                           else op[4]))
    if op[0] == "swap":
        return GateEvent("swap", (op[1], op[2]), tuple(op[3]), tuple(op[4]))
    if op[0] == "diagw":
        return GateEvent("diag", tuple(op[1]), tuple(op[2]),
                         diag=np.asarray(op[3].arr if hasattr(op[3], "arr")
                                         else op[3]).reshape(-1))
    return GateEvent("parity", tuple(op[1]), tuple(op[2]), theta=float(op[3]))


def op_dense_targets(op) -> tuple:
    """Qubits on which ``op`` needs a DENSE (partner-exchanging) action --
    the ones that must sit below the tile/shard limit. Diagonal roles
    (controls, parity members, diagw/grid-diagonal targets) are excluded:
    they resolve per-program/per-shard. The ONE authoritative extraction
    for the legality checks in fused_local_run and
    fusion._run_pallas_sharded."""
    if op[0] == "matrix":
        m = op[4].arr if hasattr(op[4], "arr") else op[4]
        if complex(m[0][1]) == 0 and complex(m[1][0]) == 0:
            return ()
        return (op[1],)
    if op[0] in ("swap", "kraus1"):
        return (op[1], op[2])
    if op[0] == "kraus2":
        return tuple(op[1:5])
    if op[0] == "krausn":
        return (*op[1], *op[2])
    return ()  # parity / diagw / lane_u / window: no dense roles above tile


def _op_support(op):
    if op[0] == "matrix":
        return {op[1], *op[2]}
    if op[0] in ("swap", "kraus1"):
        return {op[1], op[2], *(op[3] if op[0] == "swap" else ())}
    if op[0] == "kraus2":
        return {op[1], op[2], op[3], op[4]}
    if op[0] in ("diagw", "parity", "krausn"):
        return {*op[1], *op[2]}
    return set(range(LANE_BITS))  # lane_u acts on the lane zone


def _op_is_diag(op):
    if op[0] in ("diagw", "parity"):
        return True
    if op[0] == "matrix":
        m = op[4].arr if hasattr(op[4], "arr") else op[4]
        return complex(m[0][1]) == 0 and complex(m[1][0]) == 0
    return False


#: estimated per-op kernel cost in ms at 2^26 amps f32 (round-4
#: kernelprobe slopes at the S=8192 default, min-of-3 methodology). Only
#: the RATIOS matter: the fold decision compares accumulated butterfly
#: cost against the zone's dense-dot cost on the same scale. The round-3
#: model had these backwards (lane butterflies cheap, dots expensive);
#: with bf16x3 dots and the 8192-row tile, a lane butterfly (two
#: cross-lane rolls + selects over the whole tile) costs MORE than the
#: whole folded lane dot, so the lane zone folds from the first dense
#: gate, while sublane slice-butterflies stay cheaper than the per-slab
#: window dots until a zone accumulates several of them.
_FOLD_LANE_DOT_MS = 0.47    # lane_u: 3 Karatsuba bf16x3 dot triples
_FOLD_WINDOW_DOT_MS = 0.87  # sublane window: per-slab (2D,2D) dots


def _op_cost_ms(op) -> float:
    """Estimated in-kernel cost of one un-folded op (see table above):
    diagonals are ~free; sublane slice butterflies are cheap (the low-m
    ones especially); lane butterflies pay cross-lane rolls over the
    whole tile."""
    if _op_is_diag(op):
        return 0.01
    def tcost(q):
        if q < LANE_BITS:
            return 0.76
        m = q - LANE_BITS
        return 0.07 if m < 3 else 0.25
    if op[0] == "matrix":
        return tcost(op[1])
    if op[0] == "swap":
        return tcost(op[1]) + tcost(op[2])
    # kraus ops never reach this model: zone_of() bars them from accumulators
    return 0.02


def _fold_zone_ops(ops, tile_bits: int) -> tuple:
    """Contract runs of zone-local ops into dense per-zone matrices.

    The tile's qubits split into the lane zone [0, 7) and successive
    _ZONE_SPAN-wide sublane zones [7, 12), [12, 17)... Ops fully contained
    in one zone accumulate into that zone's dense unitary; because distinct
    zones touch disjoint qubits, the open accumulators commute with each
    other, so each can keep absorbing gates until an op that OVERLAPS its
    zone (a cross-zone butterfly, parity, or grid-bit-controlled gate)
    forces a flush. Emission:

      lane zone   -> ("lane_u", W3)  three Karatsuba dots on the lane axis
      sublane zone-> ("window", lo, span, W_2Dx2D)  per-A W @ y dots (MXU)

    This is the dense-fusion economics of quest_tpu/fusion.py applied
    inside the kernel, with a COST MODEL deciding each flush: a zone folds
    only when the estimated cost of its accumulated butterflies
    (_op_cost_ms) exceeds the zone's dense-dot cost. Under the round-4
    measurements (bf16x3 dots, S=8192 tiles) lane butterflies cost more
    than the whole folded lane dot -- the lane zone folds from the first
    dense gate -- while sublane slice-butterflies stay cheaper than the
    window dots until a zone accumulates several of them."""
    from ..fusion import event_matrix

    zones = [(0, LANE_BITS)]
    lo = LANE_BITS
    while lo < tile_bits:
        zones.append((lo, min(lo + _ZONE_SPAN, tile_bits)))
        lo += _ZONE_SPAN

    out = []
    accum = {z: [] for z in zones}   # zone -> [op]

    def zone_of(op):
        if op[0] in ("kraus1", "kraus2", "krausn"):
            return None  # non-unitary: must never enter a zone's dense fold
        s = _op_support(op)
        for z in zones:
            if all(z[0] <= q < z[1] for q in s):
                return z
        return None

    def flush(z):
        run = accum[z]
        if not run:
            return
        dot_ms = _FOLD_LANE_DOT_MS if z[0] == 0 else _FOLD_WINDOW_DOT_MS
        if sum(_op_cost_ms(o) for o in run) <= dot_ms:
            out.extend(run)
            run.clear()
            return
        qubits = tuple(range(z[0], z[1]))
        U = np.eye(1 << len(qubits), dtype=complex)
        for op in run:
            U = event_matrix(_op_event(op), qubits) @ U
        ur, ui = U.real, U.imag
        if z[0] == 0:
            # Karatsuba 3-multiplication complex product: ship
            # (Ur^T, Ui^T, Ur^T + Ui^T) and compute out_r = P1 - P2,
            # out_i = P3 - P1 - P2 from three 128x128 dots -- 25% fewer
            # MXU passes than the single 256x256 block dot (the lane dots
            # are the serialized compute that bounds the 26q bench)
            W = np.stack([ur.T, ui.T, ur.T + ui.T])
            out.append(("lane_u", HashableMatrix(W)))
        else:
            W = np.block([[ur, -ui], [ui, ur]])
            out.append(("window", z[0], z[1] - z[0], HashableMatrix(W)))
        run.clear()

    for op in ops:
        z = zone_of(op)
        if z is not None:
            accum[z].append(op)
            continue
        s = _op_support(op)
        for z2 in zones:
            if any(z2[0] <= q < z2[1] for q in s):
                flush(z2)
        out.append(op)
    for z in zones:
        flush(z)
    return tuple(out)


def _keep_factor(controls, states, tile_bits, shape, dtype, gbit):
    """{0,1} dtype factor that is 1 exactly where the control pattern is
    satisfied (combining grid-bit scalars and in-tile masks), or None."""
    scalar, mask = _ctrl_scalar_and_mask(controls, states, tile_bits, shape, gbit)
    if scalar is not None and mask is not None:
        return (scalar * mask).astype(dtype)
    if scalar is not None:
        return (scalar * jnp.ones(shape, jnp.int32)).astype(dtype)
    if mask is not None:
        return mask.astype(dtype)
    return None


def _ops_body(ops, xr, xi, *, tile_bits, dtype, gbit, get_w):
    """Apply a fused op run to one in-register tile (xr, xi): the shared
    compute core of both kernel styles (the BlockSpec-pipelined grid
    kernel and the manual-DMA chunk loop). ``gbit(q)`` resolves index
    bits above the tile; ``get_w(i)`` fetches the i-th dense block
    matrix from VMEM."""
    one = np.array(1, dtype)

    def mat2(xr, xi, q, M):
        """Uncontrolled 2x2 on in-tile qubit q (the core of the 'matrix'
        op, reused per-term by the kraus ops); returns new (xr, xi)."""
        shape = xr.shape
        m00, m01, m10, m11 = (complex(M[0, 0]), complex(M[0, 1]),
                              complex(M[1, 0]), complex(M[1, 1]))
        bit = _bit_mask(q, shape)
        if m01 == 0 and m10 == 0:
            dr = jnp.where(bit == 0, dtype.type(m00.real), dtype.type(m11.real))
            di = jnp.where(bit == 0, dtype.type(m00.imag), dtype.type(m11.imag))
            return (dr * xr - di * xi, dr * xi + di * xr)
        pr = _partner(xr, q)
        pi = _partner(xi, q)
        csr = jnp.where(bit == 0, dtype.type(m00.real), dtype.type(m11.real))
        cpr = jnp.where(bit == 0, dtype.type(m01.real), dtype.type(m10.real))
        if (m00.imag == 0 and m01.imag == 0 and
                m10.imag == 0 and m11.imag == 0):
            return (csr * xr + cpr * pr, csr * xi + cpr * pi)
        csi = jnp.where(bit == 0, dtype.type(m00.imag), dtype.type(m11.imag))
        cpi = jnp.where(bit == 0, dtype.type(m01.imag), dtype.type(m10.imag))
        return (csr * xr - csi * xi + cpr * pr - cpi * pi,
                csr * xi + csi * xr + cpr * pi + cpi * pr)

    def matn(xr, xi, qs, M):
        """Uncontrolled 2^t x 2^t on in-tile qubits ``qs`` (qs[j] is bit j
        of the matrix index). Row r = the element's own target bits;
        out[i] = sum_delta M[r, r^delta] * amp[i ^ delta] -- one partner
        set per delta (built incrementally, one butterfly per new bit),
        coefficients selected per element by r. Generalises the reference's
        multiQubitUnitary local kernel (QuEST_cpu.c:1846-1912) to any
        in-tile target set; used per-term by the kraus channel ops."""
        t = len(qs)
        shape = xr.shape
        r = None
        for j, q in enumerate(qs):
            term = _bit_mask(q, shape) << j
            r = term if r is None else r + term
        ps = {0: (xr, xi)}
        for delta in range(1, 1 << t):
            low = delta & -delta
            j = low.bit_length() - 1
            pr, pi = ps[delta ^ low]
            ps[delta] = (_partner(pr, qs[j]), _partner(pi, qs[j]))
        acc_r = acc_i = None
        for delta in range(1 << t):
            cvals = [complex(M[row, row ^ delta]) for row in range(1 << t)]
            if all(v == 0 for v in cvals):
                continue
            cr = jnp.full(shape, dtype.type(cvals[0].real))
            ci = jnp.full(shape, dtype.type(cvals[0].imag))
            for row in range(1, 1 << t):
                hit = r == row
                cr = jnp.where(hit, dtype.type(cvals[row].real), cr)
                ci = jnp.where(hit, dtype.type(cvals[row].imag), ci)
            sr, si = ps[delta]
            tr = cr * sr - ci * si
            ti = cr * si + ci * sr
            acc_r = tr if acc_r is None else acc_r + tr
            acc_i = ti if acc_i is None else acc_i + ti
        zero = jnp.zeros(shape, dtype)
        return (zero if acc_r is None else acc_r,
                zero if acc_i is None else acc_i)

    def mat4(xr, xi, q1, q2, M):
        return matn(xr, xi, (q1, q2), M)

    shape = xr.shape
    for op in ops:
        if op[0] == "lane_u":
            W3 = get_w(op[1])              # (3, 128, 128): Ur^T, Ui^T, sum
            if W3.dtype == jnp.bfloat16:   # (2, 3, 128, 128) hi/lo pair
                p1 = _dot_bf16x3(xr, W3[:, 0], dtype)
                p2 = _dot_bf16x3(xi, W3[:, 1], dtype)
                p3 = _dot_bf16x3(xr + xi, W3[:, 2], dtype)
            else:
                p1 = jnp.dot(xr, W3[0], preferred_element_type=xr.dtype,
                             precision=_DOT_PRECISION)
                p2 = jnp.dot(xi, W3[1], preferred_element_type=xi.dtype,
                             precision=_DOT_PRECISION)
                p3 = jnp.dot(xr + xi, W3[2], preferred_element_type=xr.dtype,
                             precision=_DOT_PRECISION)
            xr = p1 - p2
            xi = p3 - p1 - p2

        elif op[0] == "window":
            # dense folded unitary on sublane window [lo, lo+span):
            # view the tile as (A, D, B*128) and hit each A-slab with
            # one (2D, 2D) @ (2D, B*128) MXU dot (W = [[Ur,-Ui],[Ui,Ur]])
            _, wi, lo, span = op
            W = get_w(wi)
            d = 1 << span
            blk = (1 << (lo - LANE_BITS)) * _LANES
            a_cnt = (shape[0] * shape[1]) // (d * blk)
            xr4 = xr.reshape(a_cnt, d, blk)
            xi4 = xi.reshape(a_cnt, d, blk)
            outs_r, outs_i = [], []
            for a in range(a_cnt):
                y = jnp.concatenate([xr4[a], xi4[a]], axis=0)
                if W.dtype == jnp.bfloat16:  # (2, 2D, 2D) hi/lo pair
                    o = _dot_bf16x3_rev(W, y, dtype)
                else:
                    o = jnp.dot(W, y, preferred_element_type=y.dtype,
                                precision=_DOT_PRECISION)
                outs_r.append(o[:d])
                outs_i.append(o[d:])
            xr = jnp.concatenate(outs_r, axis=0).reshape(shape)
            xi = jnp.concatenate(outs_i, axis=0).reshape(shape)

        elif op[0] == "matrix":
            _, q, controls, states, M = op
            m00, m01, m10, m11 = (complex(M[0, 0]), complex(M[0, 1]),
                                  complex(M[1, 0]), complex(M[1, 1]))

            if m01 == 0 and m10 == 0:
                # diagonal 2x2: no partner exchange at all; the target
                # may even be a grid bit (per-program scalar select)
                bit = gbit(q) if q >= tile_bits else _bit_mask(q, shape)
                dr = jnp.where(bit == 0, dtype.type(m00.real), dtype.type(m11.real))
                di = jnp.where(bit == 0, dtype.type(m00.imag), dtype.type(m11.imag))
                keep = _keep_factor(controls, states, tile_bits, shape, dtype, gbit)
                if keep is not None:
                    dr = one + keep * (dr - one)
                    di = keep * di
                xr, xi = (dr * xr - di * xi, dr * xi + di * xr)
                continue
            bit = _bit_mask(q, shape)

            pr = _partner(xr, q)
            pi = _partner(xi, q)

            if (m00.imag == 0 and m01.imag == 0 and
                    m10.imag == 0 and m11.imag == 0):
                # real matrix (H, X, Ry...): half the arithmetic
                csr = jnp.where(bit == 0, dtype.type(m00.real), dtype.type(m11.real))
                cpr = jnp.where(bit == 0, dtype.type(m01.real), dtype.type(m10.real))
                keep = _keep_factor(controls, states, tile_bits, shape, dtype, gbit)
                if keep is not None:
                    csr = one + keep * (csr - one)
                    cpr = keep * cpr
                xr, xi = (csr * xr + cpr * pr, csr * xi + cpr * pi)
                continue
            # coefficient planes: self = m00/m11, pair = m01/m10 by bit q
            csr = jnp.where(bit == 0, dtype.type(m00.real), dtype.type(m11.real))
            csi = jnp.where(bit == 0, dtype.type(m00.imag), dtype.type(m11.imag))
            cpr = jnp.where(bit == 0, dtype.type(m01.real), dtype.type(m10.real))
            cpi = jnp.where(bit == 0, dtype.type(m01.imag), dtype.type(m10.imag))
            # fold controls into the coefficients (identity where the
            # control pattern misses) -- cheaper than output blending
            keep = _keep_factor(controls, states, tile_bits, shape, dtype, gbit)
            if keep is not None:
                csr = one + keep * (csr - one)
                csi = keep * csi
                cpr = keep * cpr
                cpi = keep * cpi
            xr, xi = (csr * xr - csi * xi + cpr * pr - cpi * pi,
                      csr * xi + csi * xr + cpr * pi + cpi * pr)

        elif op[0] == "parity":
            _, qubits, controls, theta = op
            sign_scalar = jnp.array(1, jnp.int32)
            par = None
            for q in qubits:
                if q >= tile_bits:
                    gb = gbit(q)
                    sign_scalar = sign_scalar * (1 - 2 * gb)
                else:
                    b = _bit_mask(q, shape)
                    par = b if par is None else par ^ b
            sign = sign_scalar.astype(dtype)
            if par is not None:
                sign = sign * (1 - 2 * par).astype(dtype)
            c = dtype.type(math.cos(theta / 2))
            s = dtype.type(math.sin(theta / 2))
            fr = c * jnp.ones_like(sign)
            fi = -s * sign
            keep = _keep_factor(controls, (), tile_bits, shape, dtype, gbit)
            if keep is not None:
                fr = one + keep * (fr - one)
                fi = keep * fi
            xr, xi = (xr * fr - xi * fi, xr * fi + xi * fr)

        elif op[0] == "swap":
            _, q1, q2, controls, states = op
            # amps where bits q1,q2 differ exchange with partner(^q1^q2)
            p2r = _partner(_partner(xr, q1), q2)
            p2i = _partner(_partner(xi, q1), q2)
            differ = (_bit_mask(q1, shape) ^ _bit_mask(q2, shape)).astype(dtype)
            keep = _keep_factor(controls, states, tile_bits, shape, dtype, gbit)
            sel = differ if keep is None else differ * keep
            xr = xr + sel * (p2r - xr)
            xi = xi + sel * (p2i - xi)

        elif op[0] in ("kraus1", "kraus2", "krausn"):
            # a whole 1-, 2- or t-target channel in ONE pass: for each
            # Kraus term apply K on the row qubit(s) and conj(K) on the
            # column qubit(s) to a COPY of the registers, accumulate
            # sign-weighted -- rho' = sum_k s_k K_k rho K_k^dagger with
            # zero extra HBM traffic. The reference pays a dedicated
            # kernel launch per channel (QuEST_gpu.cu:2423-2600) and,
            # distributed, the 3-exchange two-qubit depolarising
            # protocol (QuEST_cpu_distributed.c:778-868); round 2 paid
            # ~2 passes per term. The >=3-target form routes every
            # backend through one mechanism, like the reference's
            # superoperator treatment (QuEST_common.c:581-638).
            if op[0] == "kraus1":
                _, t, c, terms = op
                apply_k = lambda r, i, K: mat2(*mat2(r, i, t, K),
                                               c, np.conj(K))
            elif op[0] == "kraus2":
                _, t1, t2, c1, c2, terms = op
                apply_k = lambda r, i, K: mat4(*mat4(r, i, t1, t2, K),
                                               c1, c2, np.conj(K))
            else:
                _, rows_q, cols_q, terms = op
                apply_k = lambda r, i, K: matn(*matn(r, i, rows_q, K),
                                               cols_q, np.conj(K))
            acc_r = acc_i = None
            for sign, K in terms:
                K = np.asarray(K.arr if hasattr(K, "arr") else K)
                yr, yi = apply_k(xr, xi, K)
                if sign != 1.0:
                    yr = dtype.type(sign) * yr
                    yi = dtype.type(sign) * yi
                acc_r = yr if acc_r is None else acc_r + yr
                acc_i = yi if acc_i is None else acc_i + yi
            xr, xi = acc_r, acc_i

        elif op[0] == "diagw":
            _, targets, controls, D = op
            d = np.asarray(D.arr if hasattr(D, "arr") else D).reshape(-1)
            # table index: in-tile target bits come from iota masks,
            # grid-bit targets from per-program scalars (broadcasts)
            idx = None
            for j, q in enumerate(targets):
                b = gbit(q) if q >= tile_bits else _bit_mask(q, shape)
                term = b << j
                idx = term if idx is None else idx + term
            fr = jnp.full(shape, dtype.type(d[0].real))
            fi = jnp.full(shape, dtype.type(d[0].imag))
            for k in range(1, d.size):
                hit = idx == k
                fr = jnp.where(hit, dtype.type(d[k].real), fr)
                fi = jnp.where(hit, dtype.type(d[k].imag), fi)
            keep = _keep_factor(controls, (), tile_bits, shape, dtype, gbit)
            if keep is not None:
                fr = one + keep * (fr - one)
                fi = keep * fi
            xr, xi = (xr * fr - xi * fi, xr * fi + xi * fr)

        else:  # pragma: no cover
            raise ValueError(f"unknown pallas op {op[0]!r}")

    return xr, xi


def _make_kernel(ops, s_bits, tile_bits, dtype, local_n=None,
                 load_swap=None, store_swap=None, df=False, df_acc=False):
    """BlockSpec-pipelined grid kernel over (x_ref, hi_ref, *w_refs,
    o_ref); ops of kind 'lane_u'/'window' carry an index into w_refs
    (their block matrices arrive as operands -- Pallas kernels may not
    capture array constants).

    ``hi_ref`` is an SMEM scalar holding the shard index when the kernel
    runs per-device inside shard_map (``local_n`` = the shard's qubit
    count): qubit roles at q >= local_n resolve against it, so controls,
    parity members and diagonal targets on SHARDED qubits work in-kernel
    with zero communication -- the Pallas analogue of the scheduler's
    rank-bit controls (parallel/exchange.py).

    ``load_swap``/``store_swap`` = (dk, s_low) fold a frame-swap transpose
    (swap_bit_blocks of the top-k sublane block with a k-bit grid block)
    into this pass: the input block arrives frame-permuted (gathered by the
    BlockSpec from dk strided row-chunks), and/or the output block scatters
    back the same way. The relabeling then costs zero extra HBM passes --
    the pass count of a two-frame circuit drops by ~2x (round-3 attack on
    the reference hot loop QuEST_cpu.c:1682-1739; see fusion._FramePlanner).
    """

    P = 4 if df else 2

    def kernel(x_ref, hi_ref, *refs):
        w_refs = refs[:-1]
        o_ref = refs[-1]
        if load_swap is not None:
            # (P, 1, dk, 1, 1, s_low, 128) block: axis 2 is the (old)
            # grid-bit block, already sitting where the new frame's high
            # sublane bits belong -- collapsing (dk, s_low) into the sublane
            # axis IS the bit-block swap, and is layout-free when s_low
            # fills >= 1 sublane tile (the callers guarantee s_low >= 8)
            dk, s_low = load_swap
            planes = [x_ref[i, 0, :, 0, 0].reshape(dk * s_low, _LANES)
                      for i in range(P)]
        else:
            planes = [x_ref[i] for i in range(P)]

        def gbit(q):
            if local_n is not None and q >= local_n:
                return (hi_ref[0] >> (q - local_n)) & 1
            return _grid_bit(q, tile_bits)

        if df:
            from .pallas_df import _ops_body_df
            (rh, rl), (ih, il) = _ops_body_df(
                ops, (planes[0], planes[2]), (planes[1], planes[3]),
                tile_bits=tile_bits, gbit=gbit, accurate_add=df_acc)
            planes = [rh, ih, rl, il]
        else:
            xr, xi = _ops_body(ops, planes[0], planes[1],
                               tile_bits=tile_bits, dtype=dtype, gbit=gbit,
                               get_w=lambda i: w_refs[i][:])
            planes = [xr, xi]

        if store_swap is not None:
            dk, s_low = store_swap
            for i in range(P):
                o_ref[i, 0, :, 0, 0] = planes[i].reshape(dk, s_low, _LANES)
        else:
            for i in range(P):
                o_ref[i] = planes[i]

    return kernel


def _make_dma_kernel(ops, s: int, tile_bits: int, dtype,
                     nchunks: int, load_swap, store_swap, df=False,
                     ring: int = 2, local_n=None, df_acc=False):
    """Manual ring-buffered-DMA kernel: ONE pallas program owns the whole
    pass, looping over the 2^grid chunks with explicit async copies through
    an N-slot in-flight ring (``ring`` load buffers + ``ring`` store
    buffers) -- up to ring-1 chunk loads stay in flight ahead of the chunk
    being computed, and a store only blocks when its slot comes around
    again ``ring`` chunks later. Measured vs the BlockSpec grid pipeline at
    2^26 amps: full-state copy 3.9 vs 6.3 ms (the BlockSpec pipeline
    leaves ~40% of HBM bandwidth on the table; round-3 probe), which is
    most of the 26q bench's per-pass floor. Depth > 2 exists to hide the
    round-5 finding that the two-slot ring serialises on its own
    store-wait whenever a chunk's compute (the bf16x3 zone dots) runs
    shorter than its store drains: with N slots the dots of chunks
    c..c+N-2 overlap the still-draining stores of chunks c-N..c-1 instead
    of stalling the sweep. Depth is a tunable (``ring_depth`` on
    fused_local_run / QUEST_PALLAS_RING); VMEM cost is linear in depth
    (2 * ring tile buffers), so the caller derates depth on op-heavy runs.

    ``load_swap``/``store_swap`` = (dk, s_low, gm_sz) fold the frame-swap
    relabeling into the chunk DMAs: the operand arrives as the 7-D
    bit-block-swap view (_swap_view) and each chunk load/store is one
    strided descriptor gathering/scattering the dk sub-blocks.

    ``hi_ref`` is the SMEM shard-index scalar (as _make_kernel's): when
    ``local_n`` is set the kernel runs per-device inside shard_map and
    qubit roles at q >= local_n resolve against it -- the df per-shard
    route takes THIS kernel because Mosaic fails to legalize the 4-plane
    block under a BlockSpec grid (round-5 find; the round-7 extension of
    that single-tile workaround to the sharded grid: the chunk loop is one
    gridless program whatever the chunk count)."""

    P = 4 if df else 2
    ring = max(2, min(int(ring), nchunks))

    def kernel(x_hbm, hi_ref, *refs):
        w_refs = refs[:-1]
        o_hbm = refs[-1]

        def body(ins, outs, rsem, wsem):
            def chunk_coords(geo, c):
                # decompose the chunk index against THIS DMA's swap
                # geometry (load and store may use different k / hi);
                # static (python int) chunk indices compute on the host,
                # traced ones via lax with np.int32 divisors (Mosaic's
                # memref_slice rejects i64 operands)
                dk, _, gm_sz = geo
                if isinstance(c, (int, np.integer)):
                    gm = np.int32(c % gm_sz)
                    rest = c // gm_sz
                    return (np.int32(rest // dk), gm, np.int32(rest % dk))
                # np.int32 divisors: bare python ints materialise as i64
                # constants under jax x64 and Mosaic's convert-lowering
                # recurses narrowing them; the counter itself is always
                # i32 (the while_loop carry below)
                dk, gm_sz = np.int32(dk), np.int32(gm_sz)
                gm = c % gm_sz
                rest = c // gm_sz
                return (rest // dk, gm, rest % dk)

            def _i32(v):
                # static python indices canonicalise to i64 under jax
                # x64, which Mosaic's memref_slice rejects
                return np.int32(v) if isinstance(v, (int, np.integer)) \
                    else v

            def load_dma(slot, c):
                slot, c = _i32(slot), _i32(c)
                if load_swap is None:
                    return pltpu.make_async_copy(
                        x_hbm.at[:, c], ins.at[slot], rsem.at[slot])
                hi2, gm, dnew = chunk_coords(load_swap, c)
                return pltpu.make_async_copy(
                    x_hbm.at[:, hi2, :, gm, dnew], ins.at[slot],
                    rsem.at[slot])

            def store_dma(slot, c):
                slot, c = _i32(slot), _i32(c)
                if store_swap is None:
                    return pltpu.make_async_copy(
                        outs.at[slot], o_hbm.at[:, c], wsem.at[slot])
                hi2, gm, dnew = chunk_coords(store_swap, c)
                return pltpu.make_async_copy(
                    outs.at[slot], o_hbm.at[:, hi2, :, gm, dnew],
                    wsem.at[slot])

            # prologue: fill all but one ring slot, so the steady-state
            # loop always has ring-1 loads in flight ahead of the compute
            for j in range(min(ring - 1, nchunks)):
                load_dma(j, j).start()

            def gbit_for(c):
                def gbit(q):
                    if local_n is not None and q >= local_n:
                        return (hi_ref[0] >> (q - local_n)) & 1
                    return (c >> (q - tile_bits)) & 1
                return gbit

            def load_planes(slot):
                if load_swap is not None:
                    dk, s_low, _ = load_swap
                    return [ins[slot, i].reshape(dk * s_low, _LANES)
                            for i in range(P)]
                return [ins[slot, i] for i in range(P)]

            def compute(planes, gbit):
                if df:
                    from .pallas_df import _ops_body_df
                    (rh, rl), (ih, il) = _ops_body_df(
                        ops, (planes[0], planes[2]),
                        (planes[1], planes[3]),
                        tile_bits=tile_bits, gbit=gbit, accurate_add=df_acc)
                    return [rh, ih, rl, il]
                xr, xi = _ops_body(ops, planes[0], planes[1],
                                   tile_bits=tile_bits,
                                   dtype=dtype, gbit=gbit,
                                   get_w=lambda i: w_refs[i][:])
                return [xr, xi]

            def store_planes(slot, planes):
                if store_swap is not None:
                    dk, s_low, _ = store_swap
                    for i in range(P):
                        outs[slot, i] = planes[i].reshape(dk, s_low, _LANES)
                else:
                    for i in range(P):
                        outs[slot, i] = planes[i]

            def loop(c, carry):
                # np.int32 literals: a bare python int materialises as an
                # i64 constant under jax x64, and Mosaic's convert-lowering
                # recurses infinitely narrowing it (round-5 find)
                ring_i = np.int32(ring)
                slot = c % ring_i
                ahead = c + np.int32(ring - 1)
                nxt = ahead % ring_i

                @pl.when(ahead < nchunks)
                def _():
                    # slot (c-1) % ring was freed when chunk c-1's compute
                    # consumed it last iteration; refill it ring-1 ahead
                    load_dma(nxt, ahead).start()

                load_dma(slot, c).wait()
                planes = compute(load_planes(slot), gbit_for(c))

                @pl.when(c >= ring_i)
                def _():
                    # the store that used this slot ring chunks ago must
                    # drain before the slot's output buffer is overwritten
                    store_dma(slot, c - ring_i).wait()

                store_planes(slot, planes)
                store_dma(slot, c).start()
                return carry

            # while_loop with an EXPLICIT i32 carry, not fori_loop: under
            # jax x64 (the df kernels) fori's counter canonicalises to
            # i64, and Mosaic's convert-lowering recurses infinitely
            # trying to narrow it (round-5 find); a strongly-typed i32
            # carry never needs converting
            def w_cond(c):
                return c < np.int32(nchunks)

            def w_body(c):
                loop(c, 0)
                return c + np.int32(1)

            jax.lax.while_loop(w_cond, w_body, jnp.asarray(0, jnp.int32))
            for c in range(max(0, nchunks - ring), nchunks):
                store_dma(c % ring, c).wait()

        if load_swap is not None:
            dk, s_low, _ = load_swap
            in_shape = (P, dk, s_low, _LANES)
        else:
            in_shape = (P, s, _LANES)
        if store_swap is not None:
            dk, s_low, _ = store_swap
            out_shape = (P, dk, s_low, _LANES)
        else:
            out_shape = (P, s, _LANES)
        pl.run_scoped(
            body,
            ins=pltpu.VMEM((ring,) + in_shape, dtype),
            outs=pltpu.VMEM((ring,) + out_shape, dtype),
            rsem=pltpu.SemaphoreType.DMA((ring,)),
            wsem=pltpu.SemaphoreType.DMA((ring,)),
        )

    return kernel


def fused_local_run(amps, *, n: int, ops: tuple, sublanes: int = _DEF_SUBLANES,
                    interpret: bool | None = None, shard_index=None,
                    load_swap_k: int = 0, store_swap_k: int = 0,
                    load_swap_hi: int | None = None,
                    store_swap_hi: int | None = None,
                    ring_depth: int | None = None):
    """Apply ``ops`` (see module doc) to the planar (2, 2^n) state in one
    fused Pallas pass. Every matrix target must satisfy
    ``q < local_qubits(n, sublanes)``; parity members and controls may be
    any qubit. ``ops`` is hashable (tuples + HashableMatrix wrappers).
    On non-TPU backends the kernel runs in the Pallas interpreter (CI).

    ``shard_index`` (traced i32 scalar, e.g. ``jax.lax.axis_index`` inside
    shard_map) enables per-shard execution: ``amps`` is then one device's
    shard with ``n`` LOCAL qubits, and op roles on qubits >= n (sharded
    qubits of the global register) resolve against the shard index.

    ``load_swap_k`` = k > 0 folds ``swap_bit_blocks(lo1=tb-k, lo2, k)``
    (tb = the tile-bit count of this call's geometry; lo2 =
    ``load_swap_hi`` or tb) into the input DMA: the state arrives in the
    OTHER frame and is relabeled during load, so ``ops`` must already be
    in this run's frame. ``store_swap_k``/``store_swap_hi`` fold the same
    relabeling into the output DMA (the result lands in the other frame).
    Either costs zero extra HBM passes. A non-default ``*_hi`` relocates
    an ARBITRARY grid-bit block into the top sublane slots -- the free
    generalisation of the reference's swap-to-local relocation
    (QuEST_cpu_distributed.c:1526-1568). Composes with ``shard_index``
    when the swapped block is SHARD-LOCAL (``hi + k <= n`` in the shard's
    coordinates; swaps reaching sharded bits are collectives and stay the
    caller's job -- fusion runs them as explicit transposes).

    ``ring_depth`` sets the manual DMA pipeline's in-flight slot count
    (None = the QUEST_PALLAS_RING env override, else _DEF_RING_DEPTH;
    min 2); the chosen depth is clamped to the chunk count and derated to
    fit _RING_VMEM_BUDGET, and the per-shard/BlockSpec grid paths ignore
    it (the BlockSpec pipeline owns its own buffering)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if amps.shape[-1] < _LANES:
        raise ValueError(
            f"state has {amps.shape[-1]} amplitudes < one {_LANES}-lane tile; "
            f"registers below {LANE_BITS + 1} qubits take the ordinary path")
    # Folded frame swaps compose with shard_index when the swapped grid
    # block is SHARD-LOCAL (hi + k <= n in the shard's own coordinates) --
    # _fused_local_run's geometry check rejects anything reaching past the
    # shard (round 7; the round-4..6 builds raised unconditionally here).
    # double-float layout (4 planes = re/im x hi/lo, ops/pallas_df): pure
    # VPU arithmetic, so zone folding (MXU dots) is skipped. It runs
    # per-shard too (round 7, ISSUE 3): grid bits resolve from the chunk
    # counter, sharded bits from the SMEM shard-index scalar.
    df = amps.shape[0] == 4

    lq = local_qubits(n, sublanes)
    for o in ops:
        bad = [q for q in op_dense_targets(o) if q >= lq]
        if bad:
            raise ValueError(
                f"{o[0]} dense target(s) {bad} >= local_qubits({n}, "
                f"{sublanes}) = {lq}; route wide targets via ops.apply")
    if shard_index is None:
        shard_index = jnp.zeros((1,), jnp.int32)
        local_n = None
    else:
        shard_index = jnp.asarray(shard_index, jnp.int32).reshape(1)
        local_n = n
    ops_l = tuple(ops) if df else _fold_zone_ops(ops, lq)
    ring = (max(2, int(ring_depth)) if ring_depth is not None
            else ring_depth_default())
    from .pallas_df import accurate_add_enabled
    df_acc = bool(df and accurate_add_enabled())

    def call():
        return _fused_local_run(
            amps, shard_index, n=n, ops=ops_l, sublanes=sublanes,
            interpret=bool(interpret), local_n=local_n,
            load_swap_k=int(load_swap_k), store_swap_k=int(store_swap_k),
            load_swap_hi=load_swap_hi, store_swap_hi=store_swap_hi,
            ring_depth=ring, df_acc=df_acc)

    if not telemetry.enabled():
        return call()
    kind = "df" if df else str(np.dtype(amps.dtype))
    telemetry.inc("pallas_pass_total", kind="fused_run", dtype=kind)
    # the requested operating point (pre clamp/derate -- the knob value)
    telemetry.set_gauge("pallas_ring_depth", ring)
    # one read + one write of every plane is the pass's HBM traffic floor
    telemetry.inc("pallas_bytes_moved_total",
                  2 * amps.size * np.dtype(amps.dtype).itemsize,
                  kind="fused_run")
    sig = (n, ops_l, sublanes, int(load_swap_k), int(store_swap_k),
           load_swap_hi, store_swap_hi, local_n, str(amps.dtype),
           amps.shape, bool(interpret), ring, df_acc)
    if sig in _SEEN_KERNEL_SIGS:
        return call()
    # first dispatch of a new kernel signature: wall time here is Mosaic
    # trace+compile (eager call) or just tracing (inside an outer jit);
    # either way it is the host-side cost a new signature charges
    _SEEN_KERNEL_SIGS.add(sig)
    t0 = time.perf_counter()
    out = call()
    dt = time.perf_counter() - t0
    telemetry.observe("mosaic_compile_seconds", dt, kind=kind)
    telemetry.event("pallas.compile", kind=kind, n=n, ops=len(ops_l),
                    sublanes=min(sublanes, max(amps.shape[-1] >> LANE_BITS,
                                               1)),
                    load_swap_k=int(load_swap_k),
                    store_swap_k=int(store_swap_k), ring=ring,
                    seconds=round(dt, 4))
    return out


#: kernel signatures already dispatched once (compile timing recorded)
_SEEN_KERNEL_SIGS: set = set()


def _swap_view(x, rows: int, s: int, lo2_rel: int, k: int):
    """(P, rows, 128) -> the 7-D bit-block-swap view
    (P, high, dg, gmid, ds, s_low, 128): ``dg`` is the k-bit grid block at
    row bits [lo2_rel, lo2_rel+k), ``ds`` the top-k sublane block at
    [s_bits-k, s_bits), ``gmid`` the grid bits between them. Exchanging dg
    and ds relabels amplitudes exactly like swap_bit_blocks(tb-k, lo2, k)
    -- lo2 may be ANY grid-bit offset, not just the tile boundary. P = 2
    planar planes (re, im), or 4 in the double-float layout."""
    s_bits = s.bit_length() - 1
    dk = 1 << k
    gmid = 1 << (lo2_rel - s_bits)
    high = rows // (dk * gmid * (s >> k) * dk)
    return x.reshape(x.shape[0], high, dk, gmid, dk, s >> k, _LANES)


def _swap_spec(s: int, lo2_rel: int, k: int, planes: int = 2):
    """BlockSpec gathering/scattering one swap-permuted tile per program:
    for new grid index i, all dk positions of the old grid block, at the
    old-sublane-block position encoded in i's [lo2_rel - s_bits) bits --
    dk strided (s_low, 128) row-chunks whose concatenation IS the tile in
    the new frame."""
    s_bits = s.bit_length() - 1
    dk = 1 << k
    gm_sz = 1 << (lo2_rel - s_bits)

    def imap(i):
        gm = i % gm_sz
        rest = i // gm_sz
        return (0, rest // dk, 0, gm, rest % dk, 0, 0)

    return pl.BlockSpec((planes, 1, dk, 1, 1, s >> k, _LANES), imap,
                        memory_space=pltpu.VMEM)


@partial(jax.jit, static_argnames=("n", "ops", "sublanes", "interpret",
                                  "local_n", "load_swap_k", "store_swap_k",
                                  "load_swap_hi", "store_swap_hi",
                                  "ring_depth", "df_acc"),
         donate_argnums=(0,))
def _fused_local_run(amps, shard_index, *, n: int, ops: tuple, sublanes: int,
                     interpret: bool, local_n: int | None,
                     load_swap_k: int = 0, store_swap_k: int = 0,
                     load_swap_hi: int | None = None,
                     store_swap_hi: int | None = None,
                     ring_depth: int = _DEF_RING_DEPTH,
                     df_acc: bool = False):
    num = amps.shape[-1]
    P = amps.shape[0]          # 2 planar planes, or 4 in df layout
    df = P == 4
    rows = max(num >> LANE_BITS, 1)
    s = min(sublanes, rows)
    s_bits = int(math.log2(s)) if s > 1 else 0
    tile_bits = LANE_BITS + s_bits
    grid = rows // s
    for k, hi in ((load_swap_k, load_swap_hi), (store_swap_k, store_swap_hi)):
        if k:
            hi = tile_bits if hi is None else hi
            if k > s_bits or hi < tile_bits or hi + k > n:
                raise ValueError(
                    f"bit-block swap (k={k}, hi={hi}) exceeds the call "
                    f"geometry (tile_bits={tile_bits}, n={n})")

    # lane_u block matrices become pallas operands (replicated per program);
    # their op entries carry the operand index instead of the matrix
    ws = []
    ops_r = []
    # f32 tiles ship the zone matrices as bf16 hi/lo pairs (the bf16x3
    # three-DEFAULT-pass dot, half of HIGHEST's six); f64 keeps full-width
    # operands for the interpreter/engine path
    bf16x3 = np.dtype(amps.dtype) == np.dtype("float32")

    def ship(w):
        w = np.asarray(w, dtype=np.float32 if bf16x3 else amps.dtype)
        return jnp.asarray(_split_bf16(w) if bf16x3 else w)

    for o in ops:
        if o[0] == "lane_u":
            ops_r.append(("lane_u", len(ws)))
            ws.append(ship(o[1].arr.real))
        elif o[0] == "window":
            ops_r.append(("window", len(ws), o[1], o[2]))
            ws.append(ship(o[3].arr.real))
        elif o[0] == "matrix":
            ops_r.append((o[0], o[1], o[2], o[3],
                          np.asarray(o[4].arr if hasattr(o[4], "arr") else o[4])))
        elif o[0] == "diagw":
            ops_r.append((o[0], o[1], o[2],
                          np.asarray(o[3].arr if hasattr(o[3], "arr") else o[3])))
        else:
            ops_r.append(o)
    x = amps.reshape(P, rows, _LANES)
    lo2_load = (load_swap_hi if load_swap_hi is not None else tile_bits)
    lo2_store = (store_swap_hi if store_swap_hi is not None else tile_bits)

    if grid > 1 and (local_n is None or df):
        # manual double-buffered-DMA kernel (see _make_dma_kernel): one
        # program, explicit chunk pipeline -- ~40% more HBM bandwidth than
        # the BlockSpec grid pipeline on this geometry. Runs under the
        # interpreter too, so CI covers the production path; the per-shard
        # (shard_map) f32 path keeps the grid kernel, while per-shard DF
        # runs take this kernel too: Mosaic cannot legalize the 4-plane
        # block under a BlockSpec grid (round-5 find), and the one-program
        # chunk loop sidesteps the grid entirely (round 7).
        def swap_geo(k, lo2):
            if not k:
                return None
            return (1 << k, s >> k, 1 << (lo2 - LANE_BITS - s_bits))

        lsw = swap_geo(load_swap_k, lo2_load)
        ssw = swap_geo(store_swap_k, lo2_store)
        x_in = (_swap_view(x, rows, s, lo2_load - LANE_BITS, load_swap_k)
                if load_swap_k else x.reshape(P, grid, s, _LANES))
        if store_swap_k:
            oshape = _swap_view(x, rows, s, lo2_store - LANE_BITS,
                                store_swap_k).shape
        else:
            oshape = (P, grid, s, _LANES)
        # ring depth: clamp to the chunk count, then derate until the ring
        # buffers (in + out) fit the VMEM budget -- depth must never turn a
        # compiling kernel into a Mosaic OOM
        slot_bytes = P * s * _LANES * np.dtype(amps.dtype).itemsize
        ring = effective_ring_depth(ring_depth, grid, slot_bytes)
        kernel = _make_dma_kernel(tuple(ops_r), s, tile_bits,
                                  np.dtype(amps.dtype), grid, lsw, ssw,
                                  df=df, ring=ring, local_n=local_n,
                                  df_acc=df_acc)
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(oshape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pltpu.SMEM)] +
                     [pl.BlockSpec(memory_space=pltpu.VMEM) for _ in ws],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            compiler_params=_compat.CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024),
            interpret=interpret,
        )(x_in, shard_index, *ws)
        return out.reshape(P, -1)

    kernel = _make_kernel(
        tuple(ops_r), s_bits, tile_bits, np.dtype(amps.dtype),
        local_n=local_n, df=df, df_acc=df_acc,
        load_swap=(1 << load_swap_k, s >> load_swap_k) if load_swap_k else None,
        store_swap=(1 << store_swap_k, s >> store_swap_k) if store_swap_k else None)

    if df and grid == 1:
        # single-tile df call: Mosaic fails to legalize the 4-plane block
        # under a grid (func.return legalization, round-5 find); gridless
        # whole-array VMEM refs compile fine (frame swaps never reach
        # here: a one-tile register has no grid bits to exchange)
        assert not (load_swap_k or store_swap_k)
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM)] +
                     [pl.BlockSpec(memory_space=pltpu.VMEM) for _ in ws],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            compiler_params=_compat.CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024),
            interpret=interpret,
        )(x, shard_index, *ws)
        return out.reshape(P, -1)

    plain = pl.BlockSpec((P, s, _LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM)
    if load_swap_k:
        x_in = _swap_view(x, rows, s, lo2_load - LANE_BITS, load_swap_k)
        in_spec0 = _swap_spec(s, lo2_load - LANE_BITS, load_swap_k, planes=P)
    else:
        x_in = x
        in_spec0 = plain
    if store_swap_k:
        out_shape = jax.ShapeDtypeStruct(
            _swap_view(x, rows, s, lo2_store - LANE_BITS,
                       store_swap_k).shape, x.dtype)
        out_spec = _swap_spec(s, lo2_store - LANE_BITS, store_swap_k,
                              planes=P)
    else:
        out_shape = jax.ShapeDtypeStruct(x.shape, x.dtype)
        out_spec = plain
    out = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(grid,),
        in_specs=[in_spec0,
                  pl.BlockSpec(memory_space=pltpu.SMEM)] +
                 [pl.BlockSpec(w.shape, lambda i, _nd=w.ndim: (0,) * _nd,
                               memory_space=pltpu.VMEM) for w in ws],
        out_specs=out_spec,
        # long fused runs accumulate per-gate temporaries past the default
        # 16 MiB scoped-VMEM budget; the physical VMEM is far larger
        compiler_params=_compat.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(x_in, shard_index, *ws)
    return out.reshape(P, -1)


#: largest contiguous-window span window_dot accepts (2D sublane rows = 128)
_WINDOW_DOT_MAX_SPAN = 6


def window_dot_supported(n: int, lo: int, hi: int) -> bool:
    """True if window_dot can apply a dense [lo, hi] window: the low bits
    below the window must fill at least one 128-lane tile, and 2*2^span
    sublane rows must stay MXU-friendly."""
    return lo >= LANE_BITS and (hi - lo) < _WINDOW_DOT_MAX_SPAN


def window_dot(amps, matrix, *, n: int, lo: int, hi: int, conj: bool = False,
               interpret: bool | None = None):
    """Dense unitary on the contiguous window [lo, hi] as a Pallas MXU dot.

    View the flat state as (2, A, D, B) with D = 2^span and B = 2^lo >= 128;
    each grid program owns one (a, 128-lane slice of B) column and applies
    W4 = [[Ur, -Ui], [Ui, Ur]] by a single (2D, 2D) @ (2D, 128) matmul --
    no kron expansion (the einsum window path pays up to 4x FLOPs getting
    K to 128) and no output transpose. Measured ~3x faster per block than
    the XLA HIGHEST einsum at 2^26 amplitudes.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    telemetry.inc("pallas_pass_total", kind="window_dot")
    return _window_dot(amps, matrix, n=n, lo=lo, hi=hi, conj=conj,
                       interpret=bool(interpret))


def _make_window_dot_kernel(ac: int, d: int):
    def kernel(x_ref, w_ref, o_ref):
        w = w_ref[:]
        for a in range(ac):  # static unroll; ac is small by construction
            y = jnp.concatenate([x_ref[0, a], x_ref[1, a]], axis=0)  # (2D, Bc)
            out = jnp.dot(w, y, preferred_element_type=y.dtype,
                          precision=_DOT_PRECISION)
            o_ref[0, a] = out[:d]
            o_ref[1, a] = out[d:]
    return kernel


@partial(jax.jit, static_argnames=("n", "lo", "hi", "conj", "interpret"),
         donate_argnums=(0,))
def _window_dot(amps, matrix, *, n: int, lo: int, hi: int, conj: bool,
                interpret: bool):
    num = amps.shape[-1]
    span = hi - lo + 1
    d = 1 << span
    b = 1 << lo
    a = num // (d * b)
    mr, mi = matrix[0].astype(amps.dtype), matrix[1].astype(amps.dtype)
    if conj:
        mi = -mi
    w4 = jnp.concatenate([jnp.concatenate([mr, -mi], axis=1),
                          jnp.concatenate([mi, mr], axis=1)], axis=0)

    # block geometry: keep each DMA block ~1 MiB. Prefer wide contiguous
    # B-chunks (one big MXU dot, no transposes); when B itself is small,
    # stack Ac major rows per program and loop statically in-kernel.
    bc = min(b, 1 << 10)
    ac = max(1, min(a, (1 << 17) // (d * bc)))
    while a % ac:
        ac //= 2
    x = amps.reshape(2, a, d, b)
    grid = (a // ac, b // bc)
    out = pl.pallas_call(
        _make_window_dot_kernel(ac, d),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((2, ac, d, bc), lambda i, j: (0, i, 0, j),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((2 * d, 2 * d), lambda i, j: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((2, ac, d, bc), lambda i, j: (0, i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x, w4)
    return out.reshape(2, -1)


@partial(jax.jit, static_argnames=("n", "lo1", "lo2", "k"), donate_argnums=(0,))
def swap_bit_blocks(amps, *, n: int, lo1: int, lo2: int, k: int):
    """Exchange the k-bit index blocks [lo1, lo1+k) and [lo2, lo2+k)
    (lo1 + k <= lo2) of the planar (2, 2^n) state: a pure qubit relabeling
    executed as one XLA transpose. Measured at the elementwise floor
    (2.8 ms at 2^26 f32, tools/microbench) -- switching the two-frame
    execution scheme's frame costs one bandwidth pass.

    This is the single-chip analogue of the reference's swap-to-local
    relocation (QuEST_cpu_distributed.c:1526-1568): instead of moving one
    distributed qubit at a time through pair exchanges, the whole grid-bit
    block swaps with an equal sublane block so gates on high qubits become
    tile-local for the fused Pallas kernel.

    Plane-agnostic: the leading axis may be the planar pair (2, 2^n) or
    the 4-plane double-float layout (4, 2^n) -- the relabeling is pure
    index algebra on the amplitude axis."""
    assert lo1 + k <= lo2 and lo2 + k <= n
    P = amps.shape[0]
    d = 1 << k
    low = 1 << lo1
    mid = 1 << (lo2 - lo1 - k)
    x = amps.reshape(P, -1, d, mid, d, low)
    return x.transpose(0, 1, 4, 3, 2, 5).reshape(P, -1)


class HashableMatrix:
    """Immutable ndarray wrapper usable inside the static ``ops`` tuple."""

    def __init__(self, arr):
        self.arr = np.asarray(arr, dtype=complex)
        self.arr.setflags(write=False)
        self._key = self.arr.tobytes()

    def __getitem__(self, idx):
        return self.arr[idx]

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, HashableMatrix) and self._key == other._key
