"""Planar complex arithmetic.

The TPU has no native complex dtype (this backend rejects complex64 outright),
so the state is stored planar: one float array of shape (2, ...) holding
(real, imag) -- the same SoA layout as the reference's ComplexArray
(QuEST.h:94-98). Complex algebra is spelled out over the two planes; XLA fuses
the elementwise forms and maps the matmul forms onto real MXU ops (which beats
emulated complex even where complex is available).

Host <-> device conversion happens only at the API boundary (gate matrices in,
amplitudes out).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def from_complex(arr, dtype) -> jnp.ndarray:
    """Complex array -> planar (2, *shape) device array. Host numpy input
    converts at trace time (the constant-matrix path); a jax array/tracer
    input -- a gate matrix assembled from runtime parameters inside the
    trace (quest_tpu.engine.params) -- splits into planes symbolically."""
    import jax

    if isinstance(arr, jax.Array):
        a = jnp.asarray(arr)
        if jnp.iscomplexobj(a):
            return jnp.stack([jnp.real(a), jnp.imag(a)]).astype(dtype)
        return jnp.stack([a, jnp.zeros_like(a)]).astype(dtype)
    a = np.asarray(arr)
    return jnp.asarray(np.stack([a.real, a.imag]), dtype=dtype)


def to_complex(x) -> np.ndarray:
    """planar device array -> numpy complex host array."""
    h = np.asarray(x)
    return h[0] + 1j * h[1]


def cmul(ar, ai, br, bi):
    """(ar+i ai)(br+i bi) -> (re, im)."""
    return ar * br - ai * bi, ar * bi + ai * br


def cmatmul(mr, mi, vr, vi):
    """Complex matmul via 4 real matmuls: (mr+i mi)(vr+i vi)."""
    return mr @ vr - mi @ vi, mr @ vi + mi @ vr


def abs2(x):
    """|x|^2 plane-wise: x is (2, ...)."""
    return x[0] * x[0] + x[1] * x[1]
