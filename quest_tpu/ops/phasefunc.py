"""The phase-function kernel family (reference: the largest single kernel
group, ``QuEST_cpu.c:4196-4541``: applyPhaseFunc / MultiVar / Named /
ParamNamed, each with overrides and two's-complement encoding).

TPU-native design: instead of a scalar loop computing each amplitude's
sub-register values from its global index, view the flat 2^n array as a 2-D
``(2^h, 2^l)`` matrix (h = high bits, l = low bits). Every sub-register value
is a *separable* sum of per-qubit bit contributions, so it splits into a
2^h-vector plus a 2^l-vector, and the phase tensor is built by broadcasting
rank-1 vectors -- the whole operation compiles to ONE fused VPU pass over HBM
with no index materialisation and no high-rank tensors, at any qubit count.
The reference's conj flag (for the density shadow op) negates the phase.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

from ..datatypes import phaseFunc

#: sentinel divergence parameters match the reference kernel defaults
REAL_EPS_F32 = 1e-5
REAL_EPS_F64 = 1e-13


def _split(n: int) -> tuple[int, int]:
    l = n // 2
    return n - l, l


def _reg_ind_vectors(n: int, reg_qubits, encoding: int, rdtype):
    """(hi_vec, lo_vec) whose broadcast sum is the register's encoded value at
    every amplitude index. reg_qubits[0] is the least-significant bit; under
    TWOS_COMPLEMENT the last qubit contributes -2^(m-1) (QuEST_cpu.c:4236-4243)."""
    h, l = _split(n)
    hi = jnp.arange(1 << h, dtype=jnp.int32)
    lo = jnp.arange(1 << l, dtype=jnp.int32)
    hi_v = jnp.zeros(1 << h, dtype=rdtype)
    lo_v = jnp.zeros(1 << l, dtype=rdtype)
    m = len(reg_qubits)
    for j, q in enumerate(reg_qubits):
        weight = float(1 << j)
        if encoding == 1 and j == m - 1:
            weight = -float(1 << (m - 1))
        if q < l:
            bit = (lo >> q) & 1
            lo_v = lo_v + bit.astype(rdtype) * weight
        else:
            bit = (hi >> (q - l)) & 1
            hi_v = hi_v + bit.astype(rdtype) * weight
    return hi_v, lo_v


def _phase_to_factor(amps, phase2d, n):
    """amps (2, 2^n) planar times e^{i phase} over the (2^h, 2^l) split view."""
    h, l = _split(n)
    fr = jnp.cos(phase2d).astype(amps.dtype)
    fi = jnp.sin(phase2d).astype(amps.dtype)
    t = amps.reshape(2, 1 << h, 1 << l)
    re = t[0] * fr - t[1] * fi
    im = t[0] * fi + t[1] * fr
    return jnp.stack([re, im]).reshape(2, -1)


def _apply_overrides(phase, reg_inds, override_inds, override_phases, rdtype):
    """First-match-wins override semantics (QuEST_cpu.c:4245-4254): iterate in
    reverse so earlier entries overwrite later ones."""
    num_regs = len(reg_inds)
    for i in reversed(range(len(override_phases))):
        match = None
        for r in range(num_regs):
            hi_v, lo_v = reg_inds[r]
            ind = hi_v[:, None] + lo_v[None, :]
            cond = ind == override_inds[i * num_regs + r].astype(rdtype)
            match = cond if match is None else (match & cond)
        phase = jnp.where(match, override_phases[i].astype(rdtype), phase)
    return phase


@partial(jax.jit, static_argnames=("n", "reg_sizes", "qubits", "encoding",
                                   "exponents", "num_terms_per_reg", "num_overrides", "conj"))
def apply_poly_phase(amps, coeffs, override_inds, override_phases, *,
                     n: int, reg_sizes: tuple[int, ...], qubits: tuple[int, ...],
                     encoding: int, exponents: tuple[float, ...],
                     num_terms_per_reg: tuple[int, ...],
                     num_overrides: int, conj: bool):
    """applyPhaseFunc / applyMultiVarPhaseFunc (+Overrides): phase(i) =
    sum_r sum_t coeff[r,t] * ind_r(i)^exp[r,t] (QuEST_cpu.c:4196-4372).

    qubits is the flat concatenation of all registers' qubits (reg_sizes gives
    the partition); exponents static (usually few distinct), coeffs traced.
    """
    rdtype = amps.dtype
    h, l = _split(n)

    # per-register index vectors
    reg_inds = []
    off = 0
    for m in reg_sizes:
        reg_inds.append(_reg_ind_vectors(n, qubits[off:off + m], encoding, rdtype))
        off += m

    phase = jnp.zeros((1 << h, 1 << l), dtype=rdtype)
    flat = 0
    for r, m in enumerate(reg_sizes):
        hi_v, lo_v = reg_inds[r]
        ind = hi_v[:, None] + lo_v[None, :]
        for _ in range(num_terms_per_reg[r]):
            e = exponents[flat]
            c = coeffs[flat].astype(rdtype)
            if e == 0.0:
                term = c * jnp.ones_like(ind)
            elif float(e).is_integer() and 0 < e <= 8:
                p = ind
                for _k in range(int(e) - 1):
                    p = p * ind
                term = c * p
            else:
                term = c * jnp.power(ind, jnp.asarray(e, dtype=rdtype))
            phase = phase + term
            flat += 1

    if num_overrides:
        phase = _apply_overrides(phase, reg_inds, override_inds, override_phases, rdtype)
    if conj:
        phase = -phase
    return _phase_to_factor(amps, phase, n)


@partial(jax.jit, static_argnames=("n", "reg_sizes", "qubits", "encoding",
                                   "func_name", "num_params", "num_overrides", "conj"))
def apply_named_phase(amps, params, override_inds, override_phases, *,
                      n: int, reg_sizes: tuple[int, ...], qubits: tuple[int, ...],
                      encoding: int, func_name: int, num_params: int,
                      num_overrides: int, conj: bool):
    """applyNamedPhaseFunc / applyParamNamedPhaseFunc (+Overrides)
    (QuEST_cpu.c:4374-4541). Semantics mirrored exactly, including divergence
    parameters and the shifted/weighted variants."""
    rdtype = amps.dtype
    eps = REAL_EPS_F64 if rdtype == jnp.dtype(jnp.float64) else REAL_EPS_F32
    h, l = _split(n)
    fn = phaseFunc(func_name)

    reg_inds = []
    off = 0
    for m in reg_sizes:
        reg_inds.append(_reg_ind_vectors(n, qubits[off:off + m], encoding, rdtype))
        off += m
    num_regs = len(reg_sizes)

    def ind(r):
        hi_v, lo_v = reg_inds[r]
        return hi_v[:, None] + lo_v[None, :]

    def param(i):
        return params[i].astype(rdtype)

    P = phaseFunc
    if fn in (P.NORM, P.INVERSE_NORM, P.SCALED_NORM, P.SCALED_INVERSE_NORM,
              P.SCALED_INVERSE_SHIFTED_NORM):
        norm2 = jnp.zeros((1 << h, 1 << l), dtype=rdtype)
        for r in range(num_regs):
            x = ind(r)
            if fn == P.SCALED_INVERSE_SHIFTED_NORM:
                x = x - param(2 + r)
            norm2 = norm2 + x * x
        norm = jnp.sqrt(norm2)
        if fn == P.NORM:
            phase = norm
        elif fn == P.INVERSE_NORM:
            phase = jnp.where(norm == 0, param(0), 1 / jnp.where(norm == 0, 1, norm))
        elif fn == P.SCALED_NORM:
            phase = param(0) * norm
        else:  # SCALED_INVERSE_NORM, SCALED_INVERSE_SHIFTED_NORM
            phase = jnp.where(norm <= eps, param(1),
                              param(0) / jnp.where(norm <= eps, 1, norm))
    elif fn in (P.PRODUCT, P.INVERSE_PRODUCT, P.SCALED_PRODUCT, P.SCALED_INVERSE_PRODUCT):
        prod = jnp.ones((1 << h, 1 << l), dtype=rdtype)
        for r in range(num_regs):
            prod = prod * ind(r)
        if fn == P.PRODUCT:
            phase = prod
        elif fn == P.INVERSE_PRODUCT:
            phase = jnp.where(prod == 0, param(0), 1 / jnp.where(prod == 0, 1, prod))
        elif fn == P.SCALED_PRODUCT:
            phase = param(0) * prod
        else:
            phase = jnp.where(prod == 0, param(1),
                              param(0) / jnp.where(prod == 0, 1, prod))
    else:  # distance family; registers paired (r, r+1)
        dist2 = jnp.zeros((1 << h, 1 << l), dtype=rdtype)
        for r in range(0, num_regs, 2):
            if fn == P.SCALED_INVERSE_SHIFTED_DISTANCE:
                d = ind(r) - ind(r + 1) - param(2 + r // 2)
            elif fn == P.SCALED_INVERSE_SHIFTED_WEIGHTED_DISTANCE:
                d = ind(r) - ind(r + 1) - param(2 + r + 1)
                dist2 = dist2 + param(2 + r) * d * d
                continue
            else:
                d = ind(r + 1) - ind(r)
            dist2 = dist2 + d * d
        dist2 = jnp.maximum(dist2, 0)  # reference clamps negative (weighted case)
        dist = jnp.sqrt(dist2)
        if fn == P.DISTANCE:
            phase = dist
        elif fn == P.INVERSE_DISTANCE:
            phase = jnp.where(dist == 0, param(0), 1 / jnp.where(dist == 0, 1, dist))
        elif fn == P.SCALED_DISTANCE:
            phase = param(0) * dist
        else:  # SCALED_INVERSE_(SHIFTED_(WEIGHTED_))DISTANCE
            phase = jnp.where(dist <= eps, param(1),
                              param(0) / jnp.where(dist <= eps, 1, dist))

    if num_overrides:
        phase = _apply_overrides(phase, reg_inds, override_inds, override_phases, rdtype)
    if conj:
        phase = -phase
    return _phase_to_factor(amps, phase, n)
