"""Double-float (two-f32) kernel arithmetic: the PRECISION=2 fast path.

The reference's default build is double precision (QuEST_precision.h:52-64)
and all its published numbers are f64. The TPU has no f64 ALU: XLA emulates
doubles in software (measured ~170x slower than f32 on the engine path) and
Mosaic has no f64 lowering at all, so round 4 ran PRECISION=2 entirely on
the slow engine path (VERDICT r4 missing #2).

This module stores each f64 real plane as an UNEVALUATED SUM of two f32
planes (hi + lo, |lo| <= ulp(hi)/2 -- the classic double-float / "double-
double one level down" representation) and applies gate ops with
error-free-transform arithmetic:

- ``two_sum``/``quick_two_sum`` (Knuth/Dekker) for additions,
- Dekker-split ``two_prod`` for products (no FMA primitive is exposed;
  the 2^12+1 split factor makes both halves exact in f32),
- gate-matrix constants pre-split on the host at full f64 precision.

Result: ~48-bit effective mantissa -- TYPICAL/OBSERVED unit error ~2^-47
per op vs f64's 2^-53 (tools/df_verify on-chip: max amplitude error
6.6e-16 at 10q). This is not a uniform worst-case bound: ``df_add`` is the
"sloppy" double-double addition (one TwoSum on the hi components, the lo
components folded in before a single FastTwoSum), and under NEAR-
CANCELLATION of the hi components its RELATIVE error is unbounded by
2^-47 -- the classic Dekker caveat; the accurate variant (a second TwoSum
for the lo sum) would restore a uniform bound at ~1.4x the add cost.
Gate applications are unitary mixes whose coefficients are bounded by 1,
so the measured workloads sit at the typical figure, but consumers needing
a guaranteed worst case should treat the claim as empirical. Executed as
pure f32 VPU work inside the same fused single-HBM-pass kernels as the
f32 path (ops/pallas_gates). This is the precision analogue
of the bf16x3 trick already used for the f32 zone dots: synthesise the wide
type from the narrow one the hardware is fast at.

Zone folding (lane_u / window MXU dots) is disabled in df mode: the MXU
accumulates in f32, far below df precision; every dense gate stays a VPU
butterfly. Layout: the state ships as (4, 2^n) f32 planes
[re_hi, im_hi, re_lo, im_lo]; ``df_split``/``df_join`` convert to/from the
API-visible (2, 2^n) f64 planar state (both conversions are exact).
"""

from __future__ import annotations

import math
import os

import jax.numpy as jnp
import numpy as np

#: Dekker split constant for f32 (24-bit mantissa): 2^12 + 1
_SPLIT = np.float32(4097.0)

#: number of f32 planes in the df state layout [re_hi, im_hi, re_lo, im_lo]
DF_PLANES = 4

#: env switch for the df ROUTE off-TPU (see :func:`df_wanted`)
_DF_ENV = "QUEST_PALLAS_DF"

#: env switch for the accurate (double-TwoSum) df addition
_ACC_ENV = "QUEST_DF_ACCURATE_ADD"


def df_wanted() -> bool:
    """True when f64 registers should plan/execute on the double-float
    fast path: always on the TPU backend (Mosaic has no f64 lowering, so
    df IS the fast path there), opt-in elsewhere via ``QUEST_PALLAS_DF=1``
    -- the switch the CPU-mesh parity suite and the driver dryrun flip so
    the sharded df route executes in CI exactly as it does on-chip.
    Off-TPU default stays the native-f64 interpreter/engine routing."""
    import jax

    if jax.default_backend() == "tpu":
        return True
    return os.environ.get(_DF_ENV, "").strip() == "1"


def accurate_add_enabled() -> bool:
    """True when ``QUEST_DF_ACCURATE_ADD=1``: df additions use the
    accurate double-TwoSum variant (uniform ~2^-47 relative bound, ~1.4x
    the add cost) instead of the sloppy one-TwoSum form whose relative
    error is unbounded under near-cancellation of the hi components (the
    Dekker caveat flagged in ADVICE round 5; the reference guards its own
    accumulations with Kahan summation, QuEST_cpu_distributed.c:62-78).
    The flag enters every df kernel signature, so flipping it retraces
    rather than replaying a stale cached kernel."""
    return os.environ.get(_ACC_ENV, "").strip() == "1"

#: longest op run per df kernel: Mosaic compile time is superlinear in op
#: count and each df op lowers to ~15x the f32 arithmetic (a 27-op df
#: kernel took >9 min to compile on the v5e; 8-op kernels compile in
#: ~1 min). fusion._apply_pallas_run splits longer runs into chained
#: kernels over the (4, N) planes.
DF_MAX_OPS = 8

#: df kernel tile rows: the 2^20 sweep on the v5e measured 1.82 ms/pass at
#: S=1024 vs 2.86 at the f32 default S=4096 (4-op kernel; the ~15x-wider
#: df op bodies spill vector registers at the big tile). Planning and
#: execution of f64 pallas circuits both use this (circuits.fused,
#: fusion._apply_pallas_run).
DF_SUBLANES = 1 << 10


# ---------------------------------------------------------------------------
# error-free transforms (array-valued, f32)
# ---------------------------------------------------------------------------

def _two_sum(a, b):
    """s + e == a + b exactly (Knuth TwoSum, no magnitude assumption)."""
    s = a + b
    bb = s - a
    return s, (a - (s - bb)) + (b - bb)


def _quick2(a, b):
    """s + e == a + b exactly, REQUIRES |a| >= |b| (Dekker FastTwoSum)."""
    s = a + b
    return s, b - (s - a)


def _two_prod(a, b):
    """p + e == a * b exactly (Dekker split product)."""
    p = a * b
    ah = _SPLIT * a
    ah = ah - (ah - a)
    al = a - ah
    bh = _SPLIT * b
    bh = bh - (bh - b)
    bl = b - bh
    return p, ((ah * bh - p) + ah * bl + al * bh) + al * bl


# ---------------------------------------------------------------------------
# double-float arithmetic on (hi, lo) pairs
# ---------------------------------------------------------------------------

def df_add(x, y):
    s, e = _two_sum(x[0], y[0])
    return _quick2(s, e + (x[1] + y[1]))


def df_add_accurate(x, y):
    """Accurate double-double addition (a second TwoSum for the lo sum):
    uniform ~2^-47 relative bound even when the hi components nearly
    cancel -- the case where :func:`df_add`'s single rounding of
    ``x.lo + y.lo`` dominates the (small) result. ~1.4x the cost; opt in
    via ``QUEST_DF_ACCURATE_ADD=1`` (:func:`accurate_add_enabled`)."""
    s, e = _two_sum(x[0], y[0])
    t, f = _two_sum(x[1], y[1])
    e = e + t
    s, e = _quick2(s, e)
    e = e + f
    return _quick2(s, e)


def df_sub(x, y):
    return df_add(x, (-y[0], -y[1]))


def df_sub_accurate(x, y):
    return df_add_accurate(x, (-y[0], -y[1]))


def df_mul(x, y):
    p, e = _two_prod(x[0], y[0])
    return _quick2(p, e + (x[0] * y[1] + x[1] * y[0]))


def df_neg(x):
    return (-x[0], -x[1])


def _fsplit(v) -> tuple[np.float32, np.float32]:
    """Host-side exact split of a python/f64 float into (hi, lo) f32."""
    hi = np.float32(v)
    return hi, np.float32(np.float64(v) - np.float64(hi))


def _const_pair(v, shape):
    """Broadcast a host float into a df pair of full planes."""
    hi, lo = _fsplit(v)
    return (jnp.full(shape, hi), jnp.full(shape, lo))


def _sel_pair(pred, a, b):
    """Elementwise df select: where(pred, a, b) on both halves (exact)."""
    return (jnp.where(pred, a[0], b[0]), jnp.where(pred, a[1], b[1]))


def _sel_consts(pred, va, vb, shape):
    """df plane pair holding va where pred else vb (host constants).
    Both ``where`` branches are scalars, as in the f32 kernel body --
    Mosaic SIGABRTs on mixed scalar/array branches (round-5 find)."""
    ah, al = _fsplit(va)
    bh, bl = _fsplit(vb)
    hi = jnp.where(pred, ah, bh)
    lo = jnp.where(pred, al, bl)
    return (jnp.broadcast_to(hi, shape), jnp.broadcast_to(lo, shape))


# ---------------------------------------------------------------------------
# state conversion (exact both ways)
# ---------------------------------------------------------------------------

def df_split(amps64):
    """(2, N) f64 planar state -> (4, N) f32 [re_hi, im_hi, re_lo, im_lo]."""
    hi = amps64.astype(jnp.float32)
    lo = (amps64 - hi.astype(jnp.float64)).astype(jnp.float32)
    return jnp.concatenate([hi, lo], axis=0)


def df_join(planes):
    """(4, N) f32 df planes -> (2, N) f64 planar state."""
    return planes[:2].astype(jnp.float64) + planes[2:].astype(jnp.float64)


# ---------------------------------------------------------------------------
# reductions over the df layout
# ---------------------------------------------------------------------------

def df_total_prob(planes, accurate: bool | None = None):
    """sum |amp|^2 over a (4, N) df state, accumulated IN df arithmetic:
    per-amplitude squares via exact Dekker products, then an adjacent-pair
    cascade of df additions (shard-local on block-sharded inputs, like
    ops.reduce._pairwise_sum). This is the df mirror of the reference's
    Kahan-protected statevec_calcTotalProb (QuEST_cpu_distributed.c:62-119)
    -- the norm/trace reduction the accurate-add option exists for:
    ``accurate=None`` follows ``QUEST_DF_ACCURATE_ADD``
    (:func:`accurate_add_enabled`), and the near-cancellation-free bound of
    the accurate add keeps the accumulated norm within ~2^-47 of the numpy
    f64 oracle (tested in tests/test_sharded_df.py). Returns a scalar
    (f64 when jax x64 is on, else the joined f32 sum)."""
    add = df_add_accurate if (accurate if accurate is not None
                              else accurate_add_enabled()) else df_add
    re = (planes[0], planes[2])
    im = (planes[1], planes[3])
    acc = add(df_mul(re, re), df_mul(im, im))  # per-amplitude |amp|^2
    hi, lo = acc
    while hi.shape[-1] > 1:
        if hi.shape[-1] % 2:
            break
        h2 = hi.reshape(-1, 2)
        l2 = lo.reshape(-1, 2)
        hi, lo = add((h2[:, 0], l2[:, 0]), (h2[:, 1], l2[:, 1]))
    import jax

    if jax.config.jax_enable_x64:
        return jnp.sum(hi.astype(jnp.float64)) + jnp.sum(lo.astype(jnp.float64))
    return jnp.sum(hi) + jnp.sum(lo)


# ---------------------------------------------------------------------------
# the df ops body (mirrors pallas_gates._ops_body per op kind)
# ---------------------------------------------------------------------------

def _ops_body_df(ops, xr, xi, *, tile_bits, gbit, accurate_add=False):
    """Apply a fused op run to one in-register df tile. ``xr``/``xi`` are
    (hi, lo) pairs of f32 arrays; returns new pairs. Mirrors
    pallas_gates._ops_body over the VPU op kinds; 'lane_u'/'window' MXU
    folds must not reach here (df plans never fold zones).
    ``accurate_add`` swaps every df addition for the double-TwoSum variant
    (QUEST_DF_ACCURATE_ADD; see :func:`df_add_accurate`) -- the flag is
    part of the kernel signature so the jit caches never mix the two.

    Selection discipline: every conditional is an EXACT arithmetic select
    ``m*a + (1-m)*b`` with ``m`` an f32 plane of exact {0,1} values (one
    term is exactly zero, so no rounding occurs) -- the same mask/astype
    vocabulary as the proven f32 kernel body. Boolean ``where`` with
    broadcast-constant branches SIGABRTs Mosaic (round-5 find)."""
    from .pallas_gates import _bit_mask, _keep_factor, _partner

    # local rebinding: every df_add/df_sub below resolves to the selected
    # variant (df_mul's internal sums are FastTwoSum, not df_add -- only
    # the explicit additions differ between the two modes)
    df_add = df_add_accurate if accurate_add else globals()["df_add"]
    df_sub = df_sub_accurate if accurate_add else globals()["df_sub"]

    f32 = jnp.dtype("float32")
    shape = xr[0].shape

    def keep_plane(controls, states):
        """f32 {0,1} plane: 1 where the op applies (or None)."""
        return _keep_factor(controls, states, tile_bits, shape, f32, gbit)

    def partner(p, q):
        return (_partner(p[0], q), _partner(p[1], q))

    def msel(m, a, b):
        """Exact df select: a where m==1 else b (m an f32 {0,1} plane)."""
        km = 1.0 - m
        return (m * a[0] + km * b[0], m * a[1] + km * b[1])

    def bitsel(bit, v0, v1):
        """df plane pair: host constant v0 where bit==0 else v1. ``bit``
        is an int {0,1} mask plane; products by exact {0,1} masks and
        sums with an exactly-zero term are error-free."""
        b = bit.astype(f32)
        nb = 1.0 - b
        h0, l0 = _fsplit(v0)
        h1, l1 = _fsplit(v1)
        return (nb * h0 + b * h1, nb * l0 + b * l1)

    def const_pair(v):
        h, lo = _fsplit(v)
        return (jnp.full(shape, h), jnp.full(shape, lo))

    def keep_fold(keep, c, ident):
        """c where keep==1 else the identity constant (0.0 or 1.0)."""
        if keep is None:
            return c
        km = 1.0 - keep
        if ident == 0.0:
            return (keep * c[0], keep * c[1])
        h, lo = _fsplit(ident)
        return (keep * c[0] + km * h, keep * c[1] + km * lo)

    def mat2(xr, xi, q, M, keep=None):
        m00, m01, m10, m11 = (complex(M[0, 0]), complex(M[0, 1]),
                              complex(M[1, 0]), complex(M[1, 1]))
        bit = _bit_mask(q, shape)
        if m01 == 0 and m10 == 0:
            dr = keep_fold(keep, bitsel(bit, m00.real, m11.real), 1.0)
            di = keep_fold(keep, bitsel(bit, m00.imag, m11.imag), 0.0)
            return (df_sub(df_mul(dr, xr), df_mul(di, xi)),
                    df_add(df_mul(dr, xi), df_mul(di, xr)))
        pr, pi = partner(xr, q), partner(xi, q)
        csr = keep_fold(keep, bitsel(bit, m00.real, m11.real), 1.0)
        cpr = keep_fold(keep, bitsel(bit, m01.real, m10.real), 0.0)
        if (m00.imag == 0 and m01.imag == 0 and
                m10.imag == 0 and m11.imag == 0):
            return (df_add(df_mul(csr, xr), df_mul(cpr, pr)),
                    df_add(df_mul(csr, xi), df_mul(cpr, pi)))
        csi = keep_fold(keep, bitsel(bit, m00.imag, m11.imag), 0.0)
        cpi = keep_fold(keep, bitsel(bit, m01.imag, m10.imag), 0.0)
        rr = df_add(df_sub(df_mul(csr, xr), df_mul(csi, xi)),
                    df_sub(df_mul(cpr, pr), df_mul(cpi, pi)))
        ri = df_add(df_add(df_mul(csr, xi), df_mul(csi, xr)),
                    df_add(df_mul(cpr, pi), df_mul(cpi, pr)))
        return rr, ri

    def matn(xr, xi, qs, M):
        """General 2^t x 2^t on in-tile qubits (df analogue of
        pallas_gates matn; used per Kraus term)."""
        t = len(qs)
        r = None
        for j, q in enumerate(qs):
            term = _bit_mask(q, shape) << j
            r = term if r is None else r + term
        ps = {0: (xr, xi)}
        for delta in range(1, 1 << t):
            low = delta & -delta
            j = low.bit_length() - 1
            pr, pi = ps[delta ^ low]
            ps[delta] = (partner(pr, qs[j]), partner(pi, qs[j]))
        acc_r = acc_i = None
        for delta in range(1 << t):
            cvals = [complex(M[row, row ^ delta]) for row in range(1 << t)]
            if all(v == 0 for v in cvals):
                continue
            # per-row coefficient plane: sum of disjoint {0,1} masks times
            # host-split constants (exact)
            cr_h = cr_l = ci_h = ci_l = None
            for row in range(1 << t):
                v = cvals[row]
                if v == 0:
                    continue
                m = (r == row).astype(f32)
                rh, rl = _fsplit(v.real)
                ih, il = _fsplit(v.imag)
                cr_h = m * rh if cr_h is None else cr_h + m * rh
                cr_l = m * rl if cr_l is None else cr_l + m * rl
                ci_h = m * ih if ci_h is None else ci_h + m * ih
                ci_l = m * il if ci_l is None else ci_l + m * il
            zero = jnp.zeros(shape, f32)
            cr = (zero if cr_h is None else cr_h,
                  zero if cr_l is None else cr_l)
            ci = (zero if ci_h is None else ci_h,
                  zero if ci_l is None else ci_l)
            sr, si = ps[delta]
            tr = df_sub(df_mul(cr, sr), df_mul(ci, si))
            ti = df_add(df_mul(cr, si), df_mul(ci, sr))
            acc_r = tr if acc_r is None else df_add(acc_r, tr)
            acc_i = ti if acc_i is None else df_add(acc_i, ti)
        zero = (jnp.zeros(shape, f32), jnp.zeros(shape, f32))
        return (zero if acc_r is None else acc_r,
                zero if acc_i is None else acc_i)

    for op in ops:
        if op[0] == "matrix":
            _, q, controls, states, M = op
            M = np.asarray(M.arr if hasattr(M, "arr") else M)
            keep = keep_plane(controls, states)
            m01, m10 = complex(M[0, 1]), complex(M[1, 0])
            if m01 == 0 and m10 == 0 and q >= tile_bits:
                # diagonal on a grid bit: per-program scalar select
                gb = jnp.broadcast_to(gbit(q), shape).astype(f32)
                m00, m11 = complex(M[0, 0]), complex(M[1, 1])
                ngb = 1.0 - gb

                def gsel(v0, v1):
                    h0, l0 = _fsplit(v0)
                    h1, l1 = _fsplit(v1)
                    return (ngb * h0 + gb * h1, ngb * l0 + gb * l1)

                dr = keep_fold(keep, gsel(m00.real, m11.real), 1.0)
                di = keep_fold(keep, gsel(m00.imag, m11.imag), 0.0)
                xr, xi = (df_sub(df_mul(dr, xr), df_mul(di, xi)),
                          df_add(df_mul(dr, xi), df_mul(di, xr)))
            else:
                xr, xi = mat2(xr, xi, q, M, keep)

        elif op[0] == "parity":
            _, qubits, controls, theta = op
            sign_scalar = jnp.array(1, jnp.int32)
            par = None
            for q in qubits:
                if q >= tile_bits:
                    sign_scalar = sign_scalar * (1 - 2 * gbit(q))
                else:
                    b = _bit_mask(q, shape)
                    par = b if par is None else b ^ par
            sign = jnp.broadcast_to(sign_scalar, shape)
            if par is not None:
                sign = sign * (1 - 2 * par)
            signf = sign.astype(f32)          # exact +-1 plane
            ch, cl = _fsplit(math.cos(theta / 2))
            sh, sl = _fsplit(math.sin(theta / 2))
            fr = (jnp.full(shape, ch), jnp.full(shape, cl))
            fi = (-sh * signf, -sl * signf)   # exact sign application
            keep = keep_plane(controls, ())
            fr = keep_fold(keep, fr, 1.0)
            fi = keep_fold(keep, fi, 0.0)
            xr, xi = (df_sub(df_mul(xr, fr), df_mul(xi, fi)),
                      df_add(df_mul(xr, fi), df_mul(xi, fr)))

        elif op[0] == "swap":
            _, q1, q2, controls, states = op
            p2r = partner(partner(xr, q1), q2)
            p2i = partner(partner(xi, q1), q2)
            differ = (_bit_mask(q1, shape) ^ _bit_mask(q2, shape)).astype(f32)
            keep = keep_plane(controls, states)
            sel = differ if keep is None else differ * keep
            xr = msel(sel, p2r, xr)
            xi = msel(sel, p2i, xi)

        elif op[0] in ("kraus1", "kraus2", "krausn"):
            if op[0] == "kraus1":
                _, t, c, terms = op
                rows_q, cols_q = (t,), (c,)
            elif op[0] == "kraus2":
                _, t1, t2, c1, c2, terms = op
                rows_q, cols_q = (t1, t2), (c1, c2)
            else:
                _, rows_q, cols_q, terms = op
            acc_r = acc_i = None
            for sign, K in terms:
                K = np.asarray(K.arr if hasattr(K, "arr") else K)
                yr, yi = matn(xr, xi, rows_q, K)
                yr, yi = matn(yr, yi, cols_q, np.conj(K))
                if sign != 1.0:
                    sp = const_pair(float(sign))
                    yr, yi = df_mul(sp, yr), df_mul(sp, yi)
                acc_r = yr if acc_r is None else df_add(acc_r, yr)
                acc_i = yi if acc_i is None else df_add(acc_i, yi)
            xr, xi = acc_r, acc_i

        elif op[0] == "diagw":
            _, targets, controls, D = op
            d = np.asarray(D.arr if hasattr(D, "arr") else D).reshape(-1)
            idx = None
            for j, q in enumerate(targets):
                b = gbit(q) if q >= tile_bits else _bit_mask(q, shape)
                term = b << j
                idx = term if idx is None else idx + term
            idx = jnp.broadcast_to(idx, shape)
            fr_h = fr_l = fi_h = fi_l = None
            for k in range(d.size):
                v = complex(d[k])
                m = (idx == k).astype(f32)
                rh, rl = _fsplit(v.real)
                ih, il = _fsplit(v.imag)
                fr_h = m * rh if fr_h is None else fr_h + m * rh
                fr_l = m * rl if fr_l is None else fr_l + m * rl
                fi_h = m * ih if fi_h is None else fi_h + m * ih
                fi_l = m * il if fi_l is None else fi_l + m * il
            fr, fi = (fr_h, fr_l), (fi_h, fi_l)
            keep = keep_plane(controls, ())
            fr = keep_fold(keep, fr, 1.0)
            fi = keep_fold(keep, fi, 0.0)
            xr, xi = (df_sub(df_mul(xr, fr), df_mul(xi, fi)),
                      df_add(df_mul(xr, fi), df_mul(xi, fr)))

        else:  # pragma: no cover - the planner never folds zones for df
            raise ValueError(f"op {op[0]!r} has no double-float kernel form")

    return xr, xi
