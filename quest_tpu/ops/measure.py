"""Probability and collapse kernels.

Reference: statevec_findProbabilityOfZeroLocal (``QuEST_cpu.c:3385``),
calcProbOfAllOutcomesLocal (``:3477``), collapse/renormalise (``:3695-3848``),
with MPI_Allreduce completing each reduction
(``QuEST_cpu_distributed.c:1324-1368``). Here every reduction is one
``jnp.sum`` -- on a sharded array XLA lowers it to a local reduce + psum over
the ICI mesh, exactly the Allreduce the reference hand-codes.

States are planar (2, 2^n) float arrays. Accumulation is float64 when x64 is
enabled (tests/CPU) else float32; the reference's Kahan summation
(QuEST_cpu_distributed.c:62-119) addresses the same drift.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .layout import grouped_axes
from .reduce import csum_rows


def _acc_dtype():
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _density_diag(amps, n: int):
    """Planar diagonal (2, 2^n) of a flattened density matrix."""
    dim = 1 << n
    t = amps.reshape(2, dim, dim)
    return jnp.stack([jnp.diagonal(t[0]), jnp.diagonal(t[1])])


@partial(jax.jit, static_argnames=("n", "target", "outcome"))
def prob_of_outcome(amps, *, n: int, target: int, outcome: int):
    """P(measuring ``outcome`` on ``target``) of a state-vector."""
    shape, axis_of = grouped_axes(n, (target,))
    tensor = amps.reshape((2,) + shape)
    sub = jax.lax.index_in_dim(tensor, outcome, axis=axis_of[target] + 1, keepdims=False)
    p = (sub[0] * sub[0] + sub[1] * sub[1]).astype(_acc_dtype())
    return jnp.sum(p)


def _group_outcome_probs(p, n, targets):
    """Reorder a real 2^n tensor so target bits (targets[0]=LSB) lead, then
    sum the rest; returns (2^t,). The per-group accumulation is the
    compensated rowwise cascade (ops.reduce.csum_rows): a bare
    ``.sum(axis=1)`` drifts ~1e-5 against the f64 oracle at 20q f32
    marginals, well past the sampler's CDF resolution."""
    t = len(targets)
    shape, axis_of = grouped_axes(n, targets)
    p = p.reshape(shape)
    targ_axes = [axis_of[q] for q in reversed(targets)]  # MSB first
    rest = [ax for ax in range(len(shape)) if ax not in targ_axes]
    p = p.transpose(tuple(targ_axes + rest))
    return csum_rows(p.reshape((1 << t, -1)))


@partial(jax.jit, static_argnames=("n", "targets"))
def prob_of_all_outcomes(amps, *, n: int, targets: tuple[int, ...]):
    """2^t vector of outcome probabilities; outcome index o has targets[0] as
    its least-significant bit (calcProbOfAllOutcomes, QuEST.h:3633)."""
    p = (amps[0] * amps[0] + amps[1] * amps[1]).astype(_acc_dtype())
    return _group_outcome_probs(p, n, targets)


def _project_mask(n, target, outcome, dtype):
    shape, axis_of = grouped_axes(n, (target,))
    keep = [0.0, 0.0]
    keep[outcome] = 1.0
    m = [1] * len(shape)
    m[axis_of[target]] = 2
    return jnp.asarray(keep, dtype=dtype).reshape(m), shape


@partial(jax.jit, static_argnames=("n", "target", "outcome"), donate_argnums=(0,))
def collapse_statevec(amps, prob, *, n: int, target: int, outcome: int):
    """Project ``target`` to ``outcome`` and renormalise by 1/sqrt(prob)
    (statevec_collapseToKnownProbOutcome, QuEST_cpu.c:3695-3775)."""
    mask, shape = _project_mask(n, target, outcome, amps.dtype)
    scale = (1.0 / jnp.sqrt(prob)).astype(amps.dtype)
    return (amps.reshape((2,) + shape) * mask[None] * scale).reshape(2, -1)


@partial(jax.jit, static_argnames=("n", "target", "outcome"), donate_argnums=(0,))
def project_statevec(amps, *, n: int, target: int, outcome: int):
    """Unnormalised projection (applyProjector, QuEST.h:7421)."""
    mask, shape = _project_mask(n, target, outcome, amps.dtype)
    return (amps.reshape((2,) + shape) * mask[None]).reshape(2, -1)


# ---------------------------------------------------------------------------
# density-matrix variants (row bits = low n, col bits = high n of the 2n-qubit
# flattening; see registers.Qureg)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n", "target", "outcome"))
def density_prob_of_outcome(amps, *, n: int, target: int, outcome: int):
    """Tr(rho P_outcome): sum diagonal elements whose bit ``target`` equals
    ``outcome`` (densmatr_calcProbOfOutcome)."""
    diag_re = _density_diag(amps, n)[0].astype(_acc_dtype())
    shape, axis_of = grouped_axes(n, (target,))
    d = diag_re.reshape(shape)
    sub = jax.lax.index_in_dim(d, outcome, axis=axis_of[target], keepdims=False)
    return jnp.sum(sub)


@partial(jax.jit, static_argnames=("n", "targets"))
def density_prob_of_all_outcomes(amps, *, n: int, targets: tuple[int, ...]):
    diag_re = _density_diag(amps, n)[0].astype(_acc_dtype())
    return _group_outcome_probs(diag_re, n, targets)


@partial(jax.jit, static_argnames=("n", "target", "outcome", "renorm"), donate_argnums=(0,))
def density_collapse(amps, prob, *, n: int, target: int, outcome: int, renorm: bool = True):
    """Zero every element where row-bit or col-bit of ``target`` differs from
    ``outcome``; scale by 1/prob (densmatr_collapseToKnownProbOutcome,
    QuEST_cpu.c:3777-3848)."""
    shape, axis_of = grouped_axes(2 * n, (target, target + n))
    rank = len(shape)
    keep = [0.0, 0.0]
    keep[outcome] = 1.0
    mask = None
    for q in (target, target + n):
        s = [1] * rank
        s[axis_of[q]] = 2
        v = jnp.asarray(keep, dtype=amps.dtype).reshape(s)
        mask = v if mask is None else mask * v

    out = amps.reshape((2,) + shape) * mask[None]
    if renorm:
        out = out * (1.0 / prob).astype(amps.dtype)
    return out.reshape(2, -1)
