"""Density-matrix decoherence kernels.

Design (mirrors the reference's Choi trick, generalised): a density matrix on
n qubits is stored as a 2n-qubit state-vector with row bits low and column
bits high (QuEST.c:8-10). Any Kraus channel on targets T becomes *one* dense
matrix -- the superoperator sum_k conj(K_k) (x) K_k -- applied to qubits
(T, T+n) with the ordinary gate engine (:func:`..ops.apply.apply_matrix`).
The reference does the same (Kraus -> superoperator -> 2t-qubit "unitary",
QuEST_common.c:581-638) but then needs bespoke MPI half-chunk exchanges for
the non-local cases (QuEST_cpu_distributed.c:569-868); here XLA's partitioner
handles that automatically.

Purely-diagonal channels (dephasing) skip the matmul entirely and use the
broadcasted-factor path, like the reference's dedicated dephase kernels
(QuEST_cpu.c:60-135).
"""

from __future__ import annotations

import numpy as np

from . import apply, cplx, diagonal


def kraus_superoperator(kraus_ops) -> np.ndarray:
    """sum_k conj(K_k) (x) K_k, ordered for application on targets
    (T..., T+n...): row bits (K's action) are the low half of the matrix index,
    column bits (conj(K)'s action) the high half.

    Matches the reference's populateKrausSuperOperator (QuEST_common.c:581-638).
    """
    ops = [np.asarray(k, dtype=np.complex128) for k in kraus_ops]
    dim = ops[0].shape[0]
    s = np.zeros((dim * dim, dim * dim), dtype=np.complex128)
    for k in ops:
        s += np.kron(np.conj(k), k)
    return s


def apply_channel(amps, superop, *, n: int, targets: tuple[int, ...]):
    """Apply a (numpy complex) superoperator to density targets: qubits
    (T..., T+n...) of the flattened 2n-qubit state."""
    ext_targets = tuple(targets) + tuple(q + n for q in targets)
    so = cplx.from_complex(superop, amps.dtype)
    return apply.apply_matrix(amps, so, n=2 * n, targets=ext_targets)


def dephase_factors_1q(prob: float) -> np.ndarray:
    """Diagonal of the 1-qubit dephasing superoperator on (q, q+n):
    off-diagonal (row bit != col bit) scaled by 1-2p
    (densmatr_mixDephasing via densmatr_oneQubitDegradeOffDiagonal,
    QuEST_cpu.c:60-105)."""
    f = 1 - 2 * prob
    return np.array([1, f, f, 1], dtype=np.complex128)


def dephase_factors_2q(prob: float) -> np.ndarray:
    """Diagonal on (q1, q2, q1+n, q2+n): rho -> (1-p)rho + p/3 (Z1 r Z1 +
    Z2 r Z2 + Z1Z2 r Z1Z2); element factor (1-p) + p/3 (s1 + s2 + s1 s2) with
    s_i = sign agreement of row/col bit i (densmatr_mixTwoQubitDephasing,
    QuEST_cpu.c:84-135). Index bits: (b_{q2+n} b_{q1+n} b_{q2} b_{q1})."""
    d = np.empty(16, dtype=np.complex128)
    p = prob
    for idx in range(16):
        r1, r2, c1, c2 = (idx >> 0) & 1, (idx >> 1) & 1, (idx >> 2) & 1, (idx >> 3) & 1
        s1 = 1 if r1 == c1 else -1
        s2 = 1 if r2 == c2 else -1
        d[idx] = (1 - p) + p / 3 * (s1 + s2 + s1 * s2)
    return d


def apply_dephasing(amps, prob, *, n: int, target: int):
    d = cplx.from_complex(dephase_factors_1q(prob), amps.dtype)
    return diagonal.apply_diagonal(amps, d, n=2 * n, targets=(target, target + n))


def apply_two_qubit_dephasing(amps, prob, *, n: int, q1: int, q2: int):
    d = cplx.from_complex(dephase_factors_2q(prob), amps.dtype)
    return diagonal.apply_diagonal(amps, d, n=2 * n, targets=(q1, q2, q1 + n, q2 + n))


def depolarising_kraus(prob: float):
    """(1-p) rho + p/3 (X r X + Y r Y + Z r Z) (mixDepolarising, QuEST.h:4051)."""
    from ..datatypes import PAULI_MATRICES
    return [
        np.sqrt(1 - prob) * PAULI_MATRICES[0],
        np.sqrt(prob / 3) * PAULI_MATRICES[1],
        np.sqrt(prob / 3) * PAULI_MATRICES[2],
        np.sqrt(prob / 3) * PAULI_MATRICES[3],
    ]


def two_qubit_depolarising_superop(prob: float) -> np.ndarray:
    """rho -> (1-p) rho + p/15 sum_{(A,B) != (I,I)} (A x B) rho (A x B)
    (mixTwoQubitDepolarising, QuEST.h:4156)."""
    from ..datatypes import PAULI_MATRICES
    ops = []
    for a in range(4):
        for b in range(4):
            m = np.kron(PAULI_MATRICES[b], PAULI_MATRICES[a])  # qubit1 low bit
            if a == 0 and b == 0:
                ops.append(np.sqrt(1 - prob) * m)
            else:
                ops.append(np.sqrt(prob / 15) * m)
    return kraus_superoperator(ops)


def damping_kraus(prob: float):
    """Amplitude damping (mixDamping, QuEST.h:4089)."""
    k0 = np.array([[1, 0], [0, np.sqrt(1 - prob)]], dtype=np.complex128)
    k1 = np.array([[0, np.sqrt(prob)], [0, 0]], dtype=np.complex128)
    return [k0, k1]


def pauli_kraus(px: float, py: float, pz: float):
    """mixPauli as a 4-operator Kraus map (QuEST_common.c:740-760)."""
    from ..datatypes import PAULI_MATRICES
    return [
        np.sqrt(1 - px - py - pz) * PAULI_MATRICES[0],
        np.sqrt(px) * PAULI_MATRICES[1],
        np.sqrt(py) * PAULI_MATRICES[2],
        np.sqrt(pz) * PAULI_MATRICES[3],
    ]
