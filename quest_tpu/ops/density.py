"""Density-matrix decoherence kernels.

Design (mirrors the reference's Choi trick, generalised): a density matrix on
n qubits is stored as a 2n-qubit state-vector with row bits low and column
bits high (QuEST.c:8-10). Any Kraus channel on targets T becomes *one* dense
matrix -- the superoperator sum_k conj(K_k) (x) K_k -- applied to qubits
(T, T+n) with the ordinary gate engine (:func:`..ops.apply.apply_matrix`).
The reference does the same (Kraus -> superoperator -> 2t-qubit "unitary",
QuEST_common.c:581-638) but then needs bespoke MPI half-chunk exchanges for
the non-local cases (QuEST_cpu_distributed.c:569-868); here XLA's partitioner
handles that automatically.

Purely-diagonal channels (dephasing) skip the matmul entirely and use the
broadcasted-factor path, like the reference's dedicated dephase kernels
(QuEST_cpu.c:60-135).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import apply, cplx, diagonal


def kraus_superoperator(kraus_ops) -> np.ndarray:
    """sum_k conj(K_k) (x) K_k, ordered for application on targets
    (T..., T+n...): row bits (K's action) are the low half of the matrix index,
    column bits (conj(K)'s action) the high half.

    Matches the reference's populateKrausSuperOperator (QuEST_common.c:581-638).
    """
    ops = [np.asarray(k, dtype=np.complex128) for k in kraus_ops]
    dim = ops[0].shape[0]
    s = np.zeros((dim * dim, dim * dim), dtype=np.complex128)
    for k in ops:
        s += np.kron(np.conj(k), k)
    return s


#: up to this many flattened qubits the one-pass superoperator apply is used;
#: beyond it, the scattered (q, q+n) target pair would take the grouped-
#: transpose path whose tile padding explodes at scale (see ops.apply), so
#: the channel is applied as a sum of per-Kraus-term window passes instead.
_SUPEROP_MAX_QUBITS = 22


def choi_kraus(superop) -> list[tuple[float, np.ndarray]]:
    """Decompose a superoperator (ordered as :func:`kraus_superoperator`,
    sum_k conj(K) (x) K) into weighted Kraus terms [(sign, K_i), ...] via
    the eigendecomposition of its Choi matrix. Signs carry non-CP maps
    (mixNonTP* family); CP maps yield all +1."""
    d2 = superop.shape[0]
    d = int(np.sqrt(d2))
    s = np.asarray(superop, dtype=np.complex128).reshape(d, d, d, d)
    # S[(c',r'),(c,r)] -> M[(r',r),(c',c)] = sum_k vec(K_k) vec(K_k)^dagger
    m = s.transpose(1, 3, 0, 2).reshape(d2, d2)
    vals, vecs = np.linalg.eigh((m + m.conj().T) / 2)
    out = []
    for lam, v in zip(vals, vecs.T):
        if abs(lam) < 1e-12:
            continue
        out.append((float(np.sign(lam)), np.sqrt(abs(lam)) * v.reshape(d, d)))
    return out


def apply_channel(amps, superop, *, n: int, targets: tuple[int, ...]):
    """Apply a (numpy complex) superoperator to density targets: qubits
    (T..., T+n...) of the flattened 2n-qubit state.

    Large registers use the Kraus-sum formulation: rho' = sum_i s_i K_i rho
    K_i^dagger, each term two layout-clean single-group passes (row bits,
    then conjugated column bits) -- the TPU equivalent of the reference's
    pair-exchange channel protocol (QuEST_cpu_distributed.c:724-868).

    Under an explicit_mesh context, every dense application routes through
    the distributed scheduler, so channels on sharded qubits take the same
    relocation-planner path as gates (the analogue of the reference's
    half-chunk depolarising/damping exchanges,
    QuEST_cpu_distributed.c:535-868) and show up in the plan stats."""
    from ..parallel import scheduler as _dist

    sched = _dist.active()
    if sched is not None:
        sched.stats["channel_superops"] += 1
    if 2 * n <= _SUPEROP_MAX_QUBITS:
        ext_targets = tuple(targets) + tuple(q + n for q in targets)
        so = cplx.from_complex(superop, amps.dtype)
        if sched is not None:
            return sched.apply_matrix(amps, so, n=2 * n, targets=ext_targets)
        return apply.apply_matrix(amps, so, n=2 * n, targets=ext_targets)

    terms = choi_kraus(superop)
    if sched is not None:
        shifted = tuple(q + n for q in targets)
        out = None
        for sign, k in terms:
            km = jnp.asarray(np.stack([k.real, k.imag]), dtype=amps.dtype)
            t = sched.apply_matrix(amps + 0, km, n=2 * n, targets=tuple(targets))
            t = sched.apply_matrix(t, km, n=2 * n, targets=shifted, conj=True)
            out = _acc_kraus_term(out, sign, t)
        return out
    if len(targets) == 1 and jax.default_backend() == "tpu":
        # non-TPU backends stay on the XLA engine path: fused_local_run
        # would fall into the Pallas interpreter there, which is orders of
        # magnitude slower than _apply_kraus_sum at these sizes
        new = _kraus_sum_pallas(amps, terms, n, targets[0])
        if new is not None:
            return new
    signs = tuple(s for s, _ in terms)
    ks = np.stack([np.stack([k.real, k.imag]) for _, k in terms])
    return _apply_kraus_sum(amps, jnp.asarray(ks, dtype=amps.dtype),
                            n=n, targets=tuple(targets), signs=signs)


def _kraus_sum_pallas(amps, terms, n, t, lq=None):
    """Single-target Kraus sum as ONE fused Pallas pass: the whole channel
    (every term's K on the row qubit + conj(K) on the column qubit, with
    the signed accumulation) runs in-register per tile via the 'kraus1'
    kernel op -- one HBM read+write total. Returns None when the path
    doesn't apply (multi-device, row qubit above the tile, sub-tile state).

    The column qubit t+n usually sits above the tile (the density state
    has 2n qubits); its relocation to the top in-tile slot is then FOLDED
    into the pass's load/store DMA (fused_local_run's load_swap_hi) --
    the free generalisation of the reference's half-chunk density
    exchanges (QuEST_cpu_distributed.c:535-868), which pay dedicated
    pack/exchange/unpack passes. Round 2 paid ~2 passes per Kraus term
    plus 2 relocation transposes; this is one pass, always. ``lq``
    overrides the tile limit for tests."""
    import jax

    from .. import fusion as _fusion
    from . import pallas_gates as PG

    nsv = 2 * n
    if amps.shape[-1] < 2 * PG._LANES:
        return None
    if not _fusion._mosaic_supports(amps.dtype):
        return None  # f64 on TPU: no Mosaic lowering (engine path)
    sharding = getattr(amps, "sharding", None)
    if sharding is not None and len(sharding.device_set) > 1:
        return None  # pallas_call would gather the shards
    if (isinstance(amps, jax.core.Tracer)
            and _fusion.active_pallas_mesh() is not None):
        return None  # traced replay of a register known to be sharded
    if lq is None:
        lq = PG.local_qubits(nsv)
    c = t + n
    hi = None
    if c >= lq:
        # fold the 1-bit relocation [lq-1, lq) <-> [c, c+1) into the DMA;
        # it would displace a row qubit sitting at lq-1 (impossible for
        # single-chip sizes, but guard anyway)
        if t >= lq - 1:
            return None
        hi = c
        c = lq - 1
    if t >= lq:
        return None  # row qubit itself above the tile: engine path
    terms_h = tuple((float(s), PG.HashableMatrix(k)) for s, k in terms)
    return _kraus_sum_pallas_run(amps + 0, n=n, t=t, c=c, hi=hi,
                                 terms=terms_h,
                                 sublanes=1 << (lq - PG.LANE_BITS))


def _acc_kraus_term(out, sign, term):
    """out + sign * term (None-seeded), the shared Kraus accumulator."""
    term = sign * term if sign != 1.0 else term
    return term if out is None else out + term


@partial(jax.jit, static_argnames=("n", "t", "c", "hi", "terms", "sublanes"),
         donate_argnums=(0,))
def _kraus_sum_pallas_run(amps, *, n, t, c, hi, terms, sublanes):
    """The whole fused-Kraus channel as one kernel pass (see
    _kraus_sum_pallas); ``hi`` is the grid-bit column position relocated
    into the top tile slot by the folded load/store swaps. ``sublanes``
    pins the tile geometry to the ``lq`` the caller planned against."""
    from . import pallas_gates as PG

    k = 0 if hi is None else 1
    return PG.fused_local_run(
        amps, n=2 * n, ops=(("kraus1", t, c, terms),), sublanes=sublanes,
        load_swap_k=k, load_swap_hi=hi,
        store_swap_k=k, store_swap_hi=hi)


@partial(jax.jit, static_argnames=("n", "targets", "signs"), donate_argnums=(0,))
def _apply_kraus_sum(amps, ks, *, n: int, targets: tuple[int, ...],
                     signs: tuple[float, ...]):
    shifted = tuple(q + n for q in targets)
    out = None
    for i, sign in enumerate(signs):
        t = apply.apply_matrix(amps + 0, ks[i], n=2 * n, targets=targets)
        t = apply.apply_matrix(t, ks[i], n=2 * n, targets=shifted, conj=True)
        out = _acc_kraus_term(out, sign, t)
    return out


def dephase_factors_1q(prob: float) -> np.ndarray:
    """Diagonal of the 1-qubit dephasing superoperator on (q, q+n):
    off-diagonal (row bit != col bit) scaled by 1-2p
    (densmatr_mixDephasing via densmatr_oneQubitDegradeOffDiagonal,
    QuEST_cpu.c:60-105)."""
    f = 1 - 2 * prob
    return np.array([1, f, f, 1], dtype=np.complex128)


def dephase_factors_2q(prob: float) -> np.ndarray:
    """Diagonal on (q1, q2, q1+n, q2+n): rho -> (1-p)rho + p/3 (Z1 r Z1 +
    Z2 r Z2 + Z1Z2 r Z1Z2); element factor (1-p) + p/3 (s1 + s2 + s1 s2) with
    s_i = sign agreement of row/col bit i (densmatr_mixTwoQubitDephasing,
    QuEST_cpu.c:84-135). Index bits: (b_{q2+n} b_{q1+n} b_{q2} b_{q1})."""
    d = np.empty(16, dtype=np.complex128)
    p = prob
    for idx in range(16):
        r1, r2, c1, c2 = (idx >> 0) & 1, (idx >> 1) & 1, (idx >> 2) & 1, (idx >> 3) & 1
        s1 = 1 if r1 == c1 else -1
        s2 = 1 if r2 == c2 else -1
        d[idx] = (1 - p) + p / 3 * (s1 + s2 + s1 * s2)
    return d


def _diag_dispatch(amps, d, *, n, targets):
    """Dephasing diagonals via the explicit scheduler when one is active
    (comm-free by construction, counted in its plan stats)."""
    from ..parallel import scheduler as _dist

    sched = _dist.active()
    if sched is not None:
        return sched.apply_diagonal(amps, d, n=n, targets=targets)
    return diagonal.apply_diagonal(amps, d, n=n, targets=targets)


def apply_dephasing(amps, prob, *, n: int, target: int):
    d = cplx.from_complex(dephase_factors_1q(prob), amps.dtype)
    return _diag_dispatch(amps, d, n=2 * n, targets=(target, target + n))


def apply_two_qubit_dephasing(amps, prob, *, n: int, q1: int, q2: int):
    d = cplx.from_complex(dephase_factors_2q(prob), amps.dtype)
    return _diag_dispatch(amps, d, n=2 * n, targets=(q1, q2, q1 + n, q2 + n))


def depolarising_kraus(prob: float):
    """(1-p) rho + p/3 (X r X + Y r Y + Z r Z) (mixDepolarising, QuEST.h:4051).
    Operators come from the canonical channel table (quest_tpu.channels),
    shared with the trajectory sampler."""
    from ..channels import depolarising_kraus as _k
    return _k(prob)


def two_qubit_depolarising_superop(prob: float) -> np.ndarray:
    """rho -> (1-p) rho + p/15 sum_{(A,B) != (I,I)} (A x B) rho (A x B)
    (mixTwoQubitDepolarising, QuEST.h:4156). Built from the canonical
    16-operator Kraus list (quest_tpu.channels.two_qubit_depolarising_kraus)."""
    from ..channels import two_qubit_depolarising_kraus as _k
    return kraus_superoperator(_k(prob))


def damping_kraus(prob: float):
    """Amplitude damping (mixDamping, QuEST.h:4089); canonical operators
    from quest_tpu.channels."""
    from ..channels import damping_kraus as _k
    return _k(prob)


def pauli_kraus(px: float, py: float, pz: float):
    """mixPauli as a 4-operator Kraus map (QuEST_common.c:740-760); canonical
    operators from quest_tpu.channels."""
    from ..channels import pauli_kraus as _k
    return _k(px, py, pz)
