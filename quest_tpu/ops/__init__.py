"""Functional, jit-compiled kernels over raw amplitude arrays.

This package is the TPU-native analogue of the reference's L0/L1 kernel layers
(``QuEST/src/CPU/QuEST_cpu.c``, ``QuEST/src/GPU/QuEST_gpu.cu``): every function
is pure (amps in, amps out), shape-static, and safe to compose under ``jax.jit``
and to run on sharded arrays (XLA's SPMD partitioner inserts the collectives
the reference hand-codes with MPI).

The index algebra that the reference implements with bit twiddling
(``QuEST_cpu_internal.h:26-53``) is expressed here as *reshapes*: qubit q of an
amplitude index is an axis of a grouped tensor view (see :mod:`.layout`), so
gates become transposes + small matmuls and phase ops become broadcasted
elementwise multiplies -- both of which XLA maps natively onto the TPU's
MXU/VPU without materialising index arrays.
"""

from . import apply, density, diagonal, init, layout, measure, reduce  # noqa: F401
