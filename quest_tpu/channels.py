"""Canonical table of the built-in decoherence channels.

One home for the Kraus operators that both noise routes share:

- the **density route** (`decoherence.py` mix* -> `ops/density.py`) builds
  superoperators ``sum_k conj(K) (x) K`` from these exact operator lists
  (or, for the purely-diagonal dephasing family, the equivalent
  broadcasted-factor diagonals -- the reference's dedicated dephase
  kernels, QuEST_cpu.c:60-135);
- the **trajectory route** (`quest_tpu/trajectories/`) unravels the same
  lists into per-trajectory stochastic Kraus selections over pure states
  (the qsim Monte-Carlo-wavefunction technique, arXiv:2111.02396).

Keeping a single table guarantees the two routes sample the *same* channel:
the ensemble-mean-vs-oracle tests (tests/test_trajectories.py) are only
meaningful because both sides read these operators, and the density path is
regression-tested bit-identical against the pre-extraction literals
(tests/test_channels.py).

Each entry is a :class:`ChannelSpec`; ``kraus_ops(name, *probs)`` is the
lookup used by both consumers. Operator conventions: 2^t x 2^t complex128
numpy arrays, ``targets[0]`` = least-significant bit of the matrix index
(the `ops/apply.apply_matrix` convention), CPTP by construction
(``sum_k K_k^dagger K_k = I``) for every in-range probability.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np

from .datatypes import PAULI_MATRICES

__all__ = [
    "ChannelSpec", "CHANNELS", "MIX_CHANNELS", "kraus_ops",
    "dephasing_kraus", "two_qubit_dephasing_kraus",
    "depolarising_kraus", "two_qubit_depolarising_kraus",
    "damping_kraus", "pauli_kraus",
]


def dephasing_kraus(prob: float):
    """mixDephasing as a 2-operator Kraus map: rho -> (1-p) rho + p Z r Z
    (QuEST.h:4011). The density route applies it as the equivalent
    off-diagonal factor diagonal (ops/density.dephase_factors_1q)."""
    return [
        np.sqrt(1 - prob) * PAULI_MATRICES[0],
        np.sqrt(prob) * PAULI_MATRICES[3],
    ]


def two_qubit_dephasing_kraus(prob: float):
    """mixTwoQubitDephasing: rho -> (1-p) rho + p/3 (Z1 r Z1 + Z2 r Z2 +
    Z1Z2 r Z1Z2) (QuEST.h:4031; density diagonal: dephase_factors_2q).
    qubit1 is the low matrix bit, matching the superoperator target order."""
    i2, z = PAULI_MATRICES[0], PAULI_MATRICES[3]
    return [
        np.sqrt(1 - prob) * np.kron(i2, i2),
        np.sqrt(prob / 3) * np.kron(i2, z),      # Z on qubit1 (low bit)
        np.sqrt(prob / 3) * np.kron(z, i2),      # Z on qubit2
        np.sqrt(prob / 3) * np.kron(z, z),
    ]


def depolarising_kraus(prob: float):
    """(1-p) rho + p/3 (X r X + Y r Y + Z r Z) (mixDepolarising, QuEST.h:4051)."""
    return [
        np.sqrt(1 - prob) * PAULI_MATRICES[0],
        np.sqrt(prob / 3) * PAULI_MATRICES[1],
        np.sqrt(prob / 3) * PAULI_MATRICES[2],
        np.sqrt(prob / 3) * PAULI_MATRICES[3],
    ]


def two_qubit_depolarising_kraus(prob: float):
    """rho -> (1-p) rho + p/15 sum_{(A,B) != (I,I)} (A x B) rho (A x B)
    (mixTwoQubitDepolarising, QuEST.h:4156). qubit1 is the low matrix bit."""
    ops = []
    for a in range(4):
        for b in range(4):
            m = np.kron(PAULI_MATRICES[b], PAULI_MATRICES[a])  # qubit1 low bit
            if a == 0 and b == 0:
                ops.append(np.sqrt(1 - prob) * m)
            else:
                ops.append(np.sqrt(prob / 15) * m)
    return ops


def damping_kraus(prob: float):
    """Amplitude damping (mixDamping, QuEST.h:4089)."""
    k0 = np.array([[1, 0], [0, np.sqrt(1 - prob)]], dtype=np.complex128)
    k1 = np.array([[0, np.sqrt(prob)], [0, 0]], dtype=np.complex128)
    return [k0, k1]


def pauli_kraus(px: float, py: float, pz: float):
    """mixPauli as a 4-operator Kraus map (QuEST_common.c:740-760)."""
    return [
        np.sqrt(1 - px - py - pz) * PAULI_MATRICES[0],
        np.sqrt(px) * PAULI_MATRICES[1],
        np.sqrt(py) * PAULI_MATRICES[2],
        np.sqrt(pz) * PAULI_MATRICES[3],
    ]


@dataclass(frozen=True)
class ChannelSpec:
    """One built-in channel: ``kraus(*probs)`` returns its operator list.

    ``num_targets`` is the channel arity (1 or 2 qubits), ``num_probs`` the
    probability-argument count, and ``diagonal`` marks the dephasing family
    whose density-route application skips the superoperator matmul for the
    broadcasted-factor diagonal (the trajectory route always consumes the
    Kraus form)."""
    name: str
    num_targets: int
    num_probs: int
    kraus: Callable[..., list]
    diagonal: bool = False


#: the canonical table, keyed by channel name.
CHANNELS = {
    "dephasing": ChannelSpec("dephasing", 1, 1, dephasing_kraus,
                             diagonal=True),
    "two_qubit_dephasing": ChannelSpec("two_qubit_dephasing", 2, 1,
                                       two_qubit_dephasing_kraus,
                                       diagonal=True),
    "depolarising": ChannelSpec("depolarising", 1, 1, depolarising_kraus),
    "two_qubit_depolarising": ChannelSpec("two_qubit_depolarising", 2, 1,
                                          two_qubit_depolarising_kraus),
    "damping": ChannelSpec("damping", 1, 1, damping_kraus),
    "pauli": ChannelSpec("pauli", 1, 3, pauli_kraus),
}

#: decoherence.py API name -> table key (what `trajectories.unravel` uses to
#: recognise recorded mix* entries).
MIX_CHANNELS = {
    "mixDephasing": "dephasing",
    "mixTwoQubitDephasing": "two_qubit_dephasing",
    "mixDepolarising": "depolarising",
    "mixTwoQubitDepolarising": "two_qubit_depolarising",
    "mixDamping": "damping",
    "mixPauli": "pauli",
}


def kraus_ops(name: str, *probs) -> list:
    """The canonical Kraus operators of built-in channel ``name`` at the
    given probability argument(s) -- the single source both the density
    superoperator builders and the trajectory sampler read."""
    spec = CHANNELS[name]
    if len(probs) != spec.num_probs:
        raise ValueError(
            f"channel '{name}' takes {spec.num_probs} probability "
            f"argument(s), got {len(probs)}")
    return spec.kraus(*probs)
