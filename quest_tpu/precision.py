"""Precision system for quest_tpu.

The reference selects float/double/long-double at compile time
(``QuEST/include/QuEST_precision.h:40-96``) and derives ``REAL_EPS`` from it.
Here precision is a *runtime* choice carried per-Qureg (the dtype of its
amplitude array), with a process-wide default selectable via the
``QUEST_PRECISION`` environment variable (1 = single, 2 = double), mirroring
the reference's ``-DPRECISION`` CMake cache variable.

Quad precision (PRECISION=4) is impossible on TPU and is not supported; the
validation layer rejects it explicitly.

TPU notes: complex64 (f32 pairs) is the performance dtype; complex128 requires
``jax_enable_x64`` and is primarily for correctness CI on the CPU backend.
bfloat16 state storage is an extension beyond reference parity (not a default).
"""

from __future__ import annotations

import os

import jax.numpy as jnp

#: map of QuEST PRECISION codes -> (real dtype, complex dtype, REAL_EPS)
#: eps values mirror QuEST_precision.h:48,63 (1e-5 single, 1e-13 double).
_PRECISIONS = {
    1: ("float32", "complex64", 1e-5),
    2: ("float64", "complex128", 1e-13),
}


def default_precision() -> int:
    """Process-wide default precision code (1 or 2), from $QUEST_PRECISION."""
    code = int(os.environ.get("QUEST_PRECISION", "1"))
    if code not in _PRECISIONS:
        raise ValueError(f"QUEST_PRECISION must be 1 or 2, got {code}")
    return code


def real_dtype(precision: int | None = None):
    code = default_precision() if precision is None else precision
    return jnp.dtype(_PRECISIONS[code][0])


def complex_dtype(precision: int | None = None):
    code = default_precision() if precision is None else precision
    return jnp.dtype(_PRECISIONS[code][1])


def real_eps(precision: int | None = None) -> float:
    """Validation tolerance, as REAL_EPS in QuEST_precision.h:48,63."""
    code = default_precision() if precision is None else precision
    return _PRECISIONS[code][2]


def eps_for_dtype(dtype) -> float:
    """REAL_EPS for a given amplitude dtype."""
    d = jnp.dtype(dtype)
    if d in (jnp.dtype("complex64"), jnp.dtype("float32")):
        return 1e-5
    return 1e-13


def precision_for_dtype(dtype) -> int:
    d = jnp.dtype(dtype)
    if d in (jnp.dtype("complex64"), jnp.dtype("float32")):
        return 1
    return 2
