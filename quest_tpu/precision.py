"""Precision system for quest_tpu.

The reference selects float/double/long-double at compile time
(``QuEST/include/QuEST_precision.h:40-96``) and derives ``REAL_EPS`` from it.
Here precision is a *runtime* choice carried per-Qureg (the dtype of its
amplitude array), with a process-wide default selectable via the
``QUEST_PRECISION`` environment variable (1 = single, 2 = double), mirroring
the reference's ``-DPRECISION`` CMake cache variable.

Quad precision (PRECISION=4) is impossible on TPU and is not supported; the
validation layer rejects it explicitly.

TPU notes (the QUEST_PRECISION=2 policy, probed round 3 on a v5e chip):

- Requesting double precision auto-enables jax's x64 mode (:func:`_ensure_x64`)
  -- without it jnp silently truncates f64 arrays to f32, violating the
  reference's PRECISION=2 contract (QuEST_precision.h:52-64).
- f64 **is supported on the TPU backend**: XLA emulates it in software. The
  Pallas/Mosaic kernels have no f64 lowering (MXU dots are bf16/f32 hardware),
  so f64 registers on TPU transparently take the XLA engine paths
  (fusion._mosaic_supports); measured ~866 gates/s at 20 qubits vs ~30-50k
  in f32 -- "supported but slow", still ~2x the reference CPU anchor, with
  true double accuracy (22q fused-circuit norm error ~3e-14).
- f32 (QUEST_PRECISION=1, the default) is the performance dtype; REAL_EPS
  tolerances scale accordingly (1e-5 vs 1e-13, mirroring the reference).

bfloat16 state storage is an extension beyond reference parity (not a default).
"""

from __future__ import annotations

import os

import jax.numpy as jnp

#: map of QuEST PRECISION codes -> (real dtype, complex dtype, REAL_EPS)
#: eps values mirror QuEST_precision.h:48,63 (1e-5 single, 1e-13 double).
_PRECISIONS = {
    1: ("float32", "complex64", 1e-5),
    2: ("float64", "complex128", 1e-13),
}


def default_precision() -> int:
    """Process-wide default precision code (1 or 2), from $QUEST_PRECISION."""
    code = int(os.environ.get("QUEST_PRECISION", "1"))
    if code not in _PRECISIONS:
        raise ValueError(f"QUEST_PRECISION must be 1 or 2, got {code}")
    return code


def _ensure_x64(code: int, explicit: bool) -> None:
    """Double precision requires jax's x64 mode; without it jnp silently
    truncates requested f64 arrays to f32 -- a register created under
    QUEST_PRECISION=2 would quietly lose half its mantissa (the reference's
    PRECISION=2 is a hard contract, QuEST_precision.h:52-64).

    Policy: when the PROCESS default is double (QUEST_PRECISION=2) the
    flag auto-enables on first use -- the whole session is f64 and the
    global flip is the declared intent. An EXPLICIT per-register
    ``precision_code=2`` in an otherwise-f32 process raises instead:
    flipping jax_enable_x64 mid-run would silently change dtype promotion
    (and TPU kernel selection) for every concurrent f32 register."""
    if code != 2:
        return
    import jax

    if jax.config.jax_enable_x64:
        return
    if explicit and default_precision() != 2:
        from .validation import QuESTError

        raise QuESTError(
            "precision_code=2 requires jax x64 mode. Set QUEST_PRECISION=2 "
            "(process-wide double precision) or enable jax_enable_x64 before "
            "creating f64 registers; enabling it implicitly here would "
            "change dtype semantics for every existing f32 register.")
    jax.config.update("jax_enable_x64", True)


def real_dtype(precision: int | None = None):
    explicit = precision is not None
    code = default_precision() if precision is None else precision
    _ensure_x64(code, explicit)
    return jnp.dtype(_PRECISIONS[code][0])


def complex_dtype(precision: int | None = None):
    explicit = precision is not None
    code = default_precision() if precision is None else precision
    _ensure_x64(code, explicit)
    return jnp.dtype(_PRECISIONS[code][1])


def real_eps(precision: int | None = None) -> float:
    """Validation tolerance, as REAL_EPS in QuEST_precision.h:48,63."""
    code = default_precision() if precision is None else precision
    return _PRECISIONS[code][2]


def eps_for_dtype(dtype) -> float:
    """REAL_EPS for a given amplitude dtype."""
    d = jnp.dtype(dtype)
    if d in (jnp.dtype("complex64"), jnp.dtype("float32")):
        return 1e-5
    return 1e-13


def precision_for_dtype(dtype) -> int:
    d = jnp.dtype(dtype)
    if d in (jnp.dtype("complex64"), jnp.dtype("float32")):
        return 1
    return 2
