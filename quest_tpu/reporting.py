"""Reporting / debug output (reference: reportState and friends,
QuEST.h:1538-1579, QuEST_common.c:219-242) and the QASM recording API
(QuEST.h:3906-3965)."""

from __future__ import annotations

import numpy as np

from . import validation
from .registers import Qureg, get_np

__all__ = [
    "reportState", "reportStateToScreen", "reportQuregParams", "reportPauliHamil",
    "startRecordingQASM", "stopRecordingQASM", "clearRecordedQASM",
    "printRecordedQASM", "writeRecordedQASMToFile",
]


def reportState(qureg: Qureg) -> None:
    """Dump amplitudes to ``state_rank_0.csv`` (reportState writes one file
    per rank in the reference, QuEST_common.c:219-231; the single-controller
    TPU runtime writes one)."""
    amps = get_np(qureg)
    with open("state_rank_0.csv", "w") as f:
        f.write("real, imag\n")
        for a in amps:
            f.write(f"{a.real:.12f}, {a.imag:.12f}\n")


def reportStateToScreen(qureg: Qureg, env=None, report_rank: int = 0) -> None:
    """Print every amplitude to stdout, rank-prefixed (QuEST.h:317)."""
    amps = get_np(qureg)
    print("Reporting state from rank 0 of 1")
    for a in amps:
        print(f"{a.real:.14f}, {a.imag:.14f}")


def reportQuregParams(qureg: Qureg) -> None:
    """(reportQuregParams, QuEST_common.c:233-242)."""
    print("QUBITS:")
    print(f"Number of qubits is {qureg.num_qubits_represented}.")
    print(f"Number of amps is {qureg.num_amps_total}.")
    print(f"Number of amps per device is "
          f"{qureg.num_amps_total // max(1, qureg.env.num_ranks)}.")


def reportPauliHamil(hamil) -> None:
    """Print coeff + codes lines, matching the input file format
    (reportPauliHamil)."""
    for t in range(hamil.num_sum_terms):
        codes = " ".join(str(int(c)) for c in hamil.pauli_codes[t])
        print(f"{hamil.term_coeffs[t]:g}\t{codes}")


def startRecordingQASM(qureg: Qureg) -> None:
    """Begin recording subsequent gates as QASM (QuEST.h:319)."""
    qureg.qasm_log.start()


def stopRecordingQASM(qureg: Qureg) -> None:
    """Pause QASM recording; the buffer is kept (QuEST.h:320)."""
    qureg.qasm_log.stop()


def clearRecordedQASM(qureg: Qureg) -> None:
    """Discard the QASM recorded so far (QuEST.h:321)."""
    qureg.qasm_log.clear()


def printRecordedQASM(qureg: Qureg) -> None:
    """Print the recorded QASM to stdout (QuEST.h:322)."""
    print(qureg.qasm_log.printed(), end="")


def writeRecordedQASMToFile(qureg: Qureg, filename: str) -> None:
    """Flush the recorded QASM to ``filename``; an unopenable path raises
    through the validation layer (validateFileOpened, QuEST_qasm.c:855)."""
    try:
        qureg.qasm_log.write_to_file(filename)
    except OSError:
        validation.validate_file_opened(False, filename,
                                        "writeRecordedQASMToFile")
