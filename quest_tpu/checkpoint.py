"""Checkpoint / resume: durable snapshots of registers and RNG state.

The reference has no built-in checkpointing (SURVEY.md section 5); its
primitives for rolling your own are ``reportState`` (CSV dump of the local
chunk, QuEST_common.c:219-231) and ``initStateFromAmps``/``setAmps``
(QuEST.c:157-162). This module provides both:

- :func:`saveQureg` / :func:`loadQureg` -- binary snapshots (npz + JSON
  metadata) that round-trip the full register, including density matrices,
  precision, and the environment's PRNG stream position, and re-place the
  amplitudes with the environment's sharding on load (the orbax-style
  sharded-checkpoint superset SURVEY.md calls for; orbax itself is
  overkill for a single logical array per register).
- :func:`writeStateToCSV` -- the reference's ``reportState`` file format
  (one "re, im" row per amplitude, state_rank_0.csv) for interop.

Loads validate shape/type metadata before touching the register, so a
corrupt or mismatched snapshot raises QuESTError and leaves state intact.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from .environment import QuESTEnv
from .registers import Qureg, createQureg, createDensityQureg
from .validation import QuESTError

__all__ = ["saveQureg", "loadQureg", "writeStateToCSV", "saveSeeds", "loadSeeds"]

_META_NAME = "qureg.json"
_AMPS_NAME = "amps.npz"


def saveQureg(qureg: Qureg, directory: str) -> None:
    """Snapshot ``qureg`` (amplitudes + structure + env RNG position) into
    ``directory`` (created if needed). A partial save is never loadable:
    any existing metadata is invalidated first, the amplitude payload is
    written via rename, and fresh metadata is written (also via rename)
    only after the payload is on disk."""
    amps = qureg.amps
    if not amps.is_fully_addressable:
        # multi-host (jax.distributed) global array: gather every shard to
        # every process first -- np.asarray on a non-addressable array
        # raises. The gather is a collective, so EVERY process must reach
        # it before any rank-dependent branch; afterwards only process 0
        # touches the filesystem, so pod-wide saves into one shared
        # directory don't race on the unlink/rename.
        from jax.experimental import multihost_utils

        host = np.asarray(multihost_utils.process_allgather(
            amps, tiled=True))
        if jax.process_index() != 0:
            return
    else:
        host = np.asarray(amps)  # device -> host, any single-host sharding
    os.makedirs(directory, exist_ok=True)
    meta_path = os.path.join(directory, _META_NAME)
    if os.path.exists(meta_path):
        os.unlink(meta_path)  # a crash mid-overwrite must not look loadable
    amps_tmp = os.path.join(directory, _AMPS_NAME + ".tmp")
    with open(amps_tmp, "wb") as f:
        np.savez_compressed(f, amps=host)
    os.replace(amps_tmp, os.path.join(directory, _AMPS_NAME))
    meta = {
        "format": 1,
        "num_qubits_represented": qureg.num_qubits_represented,
        "is_density_matrix": qureg.is_density_matrix,
        "dtype": str(np.dtype(qureg.dtype)),
        "num_amps_total": qureg.num_amps_total,
        "seeds": list(qureg.env.seeds) if qureg.env is not None else [],
        "rng_state": _rng_state_json(qureg.env),
    }
    tmp = os.path.join(directory, _META_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(directory, _META_NAME))


def loadQureg(directory: str, env: QuESTEnv) -> Qureg:
    """Recreate a register from :func:`saveQureg` output, sharded per
    ``env`` (the snapshot's own sharding is irrelevant -- layout is an
    execution property, not a state property). Restores ``env``'s RNG
    stream so measurement sequences resume deterministically."""
    meta_path = os.path.join(directory, _META_NAME)
    if not os.path.exists(meta_path):
        raise QuESTError(f"no checkpoint at {directory!r}")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise QuESTError(f"unreadable checkpoint metadata: {e}") from e
    if meta.get("format") != 1:
        raise QuESTError(f"unsupported checkpoint format {meta.get('format')!r}")

    try:
        with np.load(os.path.join(directory, _AMPS_NAME)) as z:
            host = z["amps"]
    except Exception as e:
        raise QuESTError(f"unreadable checkpoint payload: {e}") from e
    expect = (2, meta["num_amps_total"])
    if host.shape != expect:
        raise QuESTError(
            f"checkpoint amplitude shape {host.shape} != metadata {expect}")

    n = meta["num_qubits_represented"]
    make = createDensityQureg if meta["is_density_matrix"] else createQureg
    qureg = make(n, env)
    sharding = env.sharding(meta["num_amps_total"])
    arr = jax.device_put(host.astype(meta["dtype"]), sharding)
    qureg.put(arr)

    # only restore the seed/RNG pair when the snapshot actually carries one
    # (a register saved with env=None must not clobber the live env's seeds
    # while leaving its RNG stream untouched)
    if meta.get("rng_state") is not None:
        env.seeds = list(meta.get("seeds", []))
        _restore_rng(env, meta["rng_state"])
    return qureg


def writeStateToCSV(qureg: Qureg, filename: str | None = None) -> str:
    """The reference's reportState format (QuEST_common.c:219-231): a
    ``state_rank_0.csv`` with header and one "re, im" row per amplitude."""
    filename = filename or "state_rank_0.csv"
    host = np.asarray(qureg.amps)
    with open(filename, "w") as f:
        f.write("real, imag\n")
        for k in range(host.shape[1]):
            f.write(f"{host[0, k]}, {host[1, k]}\n")
    return filename


def saveSeeds(env: QuESTEnv, path: str) -> None:
    with open(path, "w") as f:
        json.dump({"seeds": list(env.seeds), "rng_state": _rng_state_json(env)}, f)


def loadSeeds(env: QuESTEnv, path: str) -> None:
    with open(path) as f:
        data = json.load(f)
    env.seeds = list(data.get("seeds", []))
    _restore_rng(env, data.get("rng_state"))


def _rng_state_json(env: QuESTEnv | None):
    if env is None or env.rng is None:
        return None
    name, keys, pos, has_gauss, cached = env.rng.get_state()
    return {"name": name, "keys": np.asarray(keys).tolist(), "pos": int(pos),
            "has_gauss": int(has_gauss), "cached": float(cached)}


def _restore_rng(env: QuESTEnv, state) -> None:
    if state is None or env.rng is None:
        return
    env.rng.set_state((state["name"],
                       np.asarray(state["keys"], dtype=np.uint32),
                       int(state["pos"]), int(state["has_gauss"]),
                       float(state["cached"])))
